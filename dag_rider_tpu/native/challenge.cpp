// Batched Ed25519 challenge-scalar computation — native host component.
//
// The verify host path computes k = SHA-512(R || A || M) mod L per vertex
// (RFC 8032 §5.1.7 step 2); at the 50k sigs/s north star this per-row work
// is the last Python loop in TPUVerifier._prepare. This library does the
// whole batch in one C call: a self-contained FIPS 180-4 SHA-512 (spec
// constants, no OpenSSL dependency) and a byte-Horner mod-L reduction.
//
// Exposed via ctypes (dag_rider_tpu/utils/native.py); built on demand with
// `g++ -O2 -shared -fPIC`. Pure-Python hashlib remains the fallback and
// the differential-testing oracle (tests/test_native.py).

#include <cstdint>
#include <cstring>
#include <cstddef>
#include <vector>

#include <dlfcn.h>

namespace {

// OpenSSL's one-shot SHA512 (stable libcrypto ABI), resolved at runtime —
// the image ships libcrypto.so.3 but no dev headers/symlink. When absent
// the self-contained FIPS 180-4 implementation below is used instead;
// both produce identical digests (differentially tested against hashlib).
typedef unsigned char* (*sha512_fn)(const unsigned char*, size_t,
                                    unsigned char*);

sha512_fn resolve_openssl_sha512() {
  static sha512_fn cached = nullptr;
  static bool tried = false;
  if (!tried) {
    tried = true;
    // RTLD_LOCAL: we only dlsym from our own handle; exporting OpenSSL
    // symbols globally could interpose on a different libcrypto already
    // loaded by Python's _ssl/cryptography modules.
    void* h = dlopen("libcrypto.so.3", RTLD_NOW | RTLD_LOCAL);
    if (!h) h = dlopen("libcrypto.so.1.1", RTLD_NOW | RTLD_LOCAL);
    if (h) cached = (sha512_fn)dlsym(h, "SHA512");
  }
  return cached;
}

// ----------------------------------------------------------------------
// SHA-512 (FIPS 180-4). Straightforward scalar implementation.
// ----------------------------------------------------------------------

const uint64_t K[80] = {
    0x428a2f98d728ae22ULL, 0x7137449123ef65cdULL, 0xb5c0fbcfec4d3b2fULL,
    0xe9b5dba58189dbbcULL, 0x3956c25bf348b538ULL, 0x59f111f1b605d019ULL,
    0x923f82a4af194f9bULL, 0xab1c5ed5da6d8118ULL, 0xd807aa98a3030242ULL,
    0x12835b0145706fbeULL, 0x243185be4ee4b28cULL, 0x550c7dc3d5ffb4e2ULL,
    0x72be5d74f27b896fULL, 0x80deb1fe3b1696b1ULL, 0x9bdc06a725c71235ULL,
    0xc19bf174cf692694ULL, 0xe49b69c19ef14ad2ULL, 0xefbe4786384f25e3ULL,
    0x0fc19dc68b8cd5b5ULL, 0x240ca1cc77ac9c65ULL, 0x2de92c6f592b0275ULL,
    0x4a7484aa6ea6e483ULL, 0x5cb0a9dcbd41fbd4ULL, 0x76f988da831153b5ULL,
    0x983e5152ee66dfabULL, 0xa831c66d2db43210ULL, 0xb00327c898fb213fULL,
    0xbf597fc7beef0ee4ULL, 0xc6e00bf33da88fc2ULL, 0xd5a79147930aa725ULL,
    0x06ca6351e003826fULL, 0x142929670a0e6e70ULL, 0x27b70a8546d22ffcULL,
    0x2e1b21385c26c926ULL, 0x4d2c6dfc5ac42aedULL, 0x53380d139d95b3dfULL,
    0x650a73548baf63deULL, 0x766a0abb3c77b2a8ULL, 0x81c2c92e47edaee6ULL,
    0x92722c851482353bULL, 0xa2bfe8a14cf10364ULL, 0xa81a664bbc423001ULL,
    0xc24b8b70d0f89791ULL, 0xc76c51a30654be30ULL, 0xd192e819d6ef5218ULL,
    0xd69906245565a910ULL, 0xf40e35855771202aULL, 0x106aa07032bbd1b8ULL,
    0x19a4c116b8d2d0c8ULL, 0x1e376c085141ab53ULL, 0x2748774cdf8eeb99ULL,
    0x34b0bcb5e19b48a8ULL, 0x391c0cb3c5c95a63ULL, 0x4ed8aa4ae3418acbULL,
    0x5b9cca4f7763e373ULL, 0x682e6ff3d6b2b8a3ULL, 0x748f82ee5defb2fcULL,
    0x78a5636f43172f60ULL, 0x84c87814a1f0ab72ULL, 0x8cc702081a6439ecULL,
    0x90befffa23631e28ULL, 0xa4506cebde82bde9ULL, 0xbef9a3f7b2c67915ULL,
    0xc67178f2e372532bULL, 0xca273eceea26619cULL, 0xd186b8c721c0c207ULL,
    0xeada7dd6cde0eb1eULL, 0xf57d4f7fee6ed178ULL, 0x06f067aa72176fbaULL,
    0x0a637dc5a2c898a6ULL, 0x113f9804bef90daeULL, 0x1b710b35131c471bULL,
    0x28db77f523047d84ULL, 0x32caab7b40c72493ULL, 0x3c9ebe0a15c9bebcULL,
    0x431d67c49c100d4cULL, 0x4cc5d4becb3e42b6ULL, 0x597f299cfc657e2aULL,
    0x5fcb6fab3ad6faecULL, 0x6c44198c4a475817ULL};

inline uint64_t rotr(uint64_t x, int n) { return (x >> n) | (x << (64 - n)); }

struct Sha512 {
  uint64_t h[8];
  uint8_t buf[128];
  size_t buflen;
  uint64_t total;

  Sha512() { reset(); }

  void reset() {
    static const uint64_t init[8] = {
        0x6a09e667f3bcc908ULL, 0xbb67ae8584caa73bULL, 0x3c6ef372fe94f82bULL,
        0xa54ff53a5f1d36f1ULL, 0x510e527fade682d1ULL, 0x9b05688c2b3e6c1fULL,
        0x1f83d9abfb41bd6bULL, 0x5be0cd19137e2179ULL};
    std::memcpy(h, init, sizeof(h));
    buflen = 0;
    total = 0;
  }

  void compress(const uint8_t* p) {
    uint64_t w[80];
    for (int i = 0; i < 16; i++) {
      w[i] = 0;
      for (int j = 0; j < 8; j++) w[i] = (w[i] << 8) | p[8 * i + j];
    }
    for (int i = 16; i < 80; i++) {
      uint64_t s0 = rotr(w[i - 15], 1) ^ rotr(w[i - 15], 8) ^ (w[i - 15] >> 7);
      uint64_t s1 = rotr(w[i - 2], 19) ^ rotr(w[i - 2], 61) ^ (w[i - 2] >> 6);
      w[i] = w[i - 16] + s0 + w[i - 7] + s1;
    }
    uint64_t a = h[0], b = h[1], c = h[2], d = h[3], e = h[4], f = h[5],
             g = h[6], hh = h[7];
    for (int i = 0; i < 80; i++) {
      uint64_t S1 = rotr(e, 14) ^ rotr(e, 18) ^ rotr(e, 41);
      uint64_t ch = (e & f) ^ (~e & g);
      uint64_t t1 = hh + S1 + ch + K[i] + w[i];
      uint64_t S0 = rotr(a, 28) ^ rotr(a, 34) ^ rotr(a, 39);
      uint64_t maj = (a & b) ^ (a & c) ^ (b & c);
      uint64_t t2 = S0 + maj;
      hh = g; g = f; f = e; e = d + t1;
      d = c; c = b; b = a; a = t1 + t2;
    }
    h[0] += a; h[1] += b; h[2] += c; h[3] += d;
    h[4] += e; h[5] += f; h[6] += g; h[7] += hh;
  }

  void update(const uint8_t* p, size_t len) {
    total += len;
    if (buflen) {
      size_t take = 128 - buflen;
      if (take > len) take = len;
      std::memcpy(buf + buflen, p, take);
      buflen += take;
      p += take;
      len -= take;
      if (buflen == 128) {
        compress(buf);
        buflen = 0;
      }
    }
    while (len >= 128) {
      compress(p);
      p += 128;
      len -= 128;
    }
    if (len) {
      std::memcpy(buf, p, len);
      buflen = len;
    }
  }

  void final(uint8_t out[64]) {
    uint64_t bits = total * 8;
    uint8_t pad = 0x80;
    update(&pad, 1);
    uint8_t zero = 0;
    while (buflen != 112) update(&zero, 1);
    uint8_t lenb[16] = {0};
    for (int i = 0; i < 8; i++) lenb[15 - i] = (uint8_t)(bits >> (8 * i));
    update(lenb, 16);
    for (int i = 0; i < 8; i++)
      for (int j = 0; j < 8; j++) out[8 * i + j] = (uint8_t)(h[i] >> (56 - 8 * j));
  }
};

// ----------------------------------------------------------------------
// Reduction mod L, L = 2^252 + c, c = 27742317777372353535851937790883648493
// (~2^124.7). Horner over the digest's 64-bit limbs; each step reduces
// t = acc * 2^64 + d (< 2^64 * L < 2^317) via the quotient estimate
// q = floor(t / 2^252) >= floor(t / L), exact to within 2 because
// c / 2^252 < 2^-127: after s = t - q*L, at most two add-backs of L.
// ----------------------------------------------------------------------

typedef unsigned __int128 u128;

// L in little-endian 64-bit limbs (4 limbs; bit 252 set in limb 3).
const uint64_t L_LIMBS[4] = {0x5812631a5cf5d3edULL, 0x14def9dea2f79cd6ULL,
                             0ULL, 0x1000000000000000ULL};

// acc: 5 limbs, invariant acc < L after each step (top limb scratch).
inline void reduce_step(uint64_t acc[5], uint64_t d) {
  // t = acc * 2^64 + d  (shift limbs up; acc < L keeps t < 2^64 * L)
  uint64_t t[5] = {d, acc[0], acc[1], acc[2], acc[3]};
  // q = t >> 252  (<= 2^65 - 1: needs 65 bits -> q_hi in {0, 1})
  uint64_t q_lo = (t[3] >> 60) | (t[4] << 4);
  uint64_t q_hi = t[4] >> 60;
  // t -= q * L   (q * L = q_lo * L + q_hi * (L << 64))
  u128 borrow = 0;
  u128 carry = 0;
  uint64_t prod[5];
  for (int i = 0; i < 4; i++) {
    u128 p = (u128)q_lo * L_LIMBS[i] + carry;
    prod[i] = (uint64_t)p;
    carry = p >> 64;
  }
  prod[4] = (uint64_t)carry;
  if (q_hi) {  // add L << 64 (q_hi is 0 or 1)
    u128 c2 = 0;
    for (int i = 1; i < 5; i++) {
      u128 s = (u128)prod[i] + L_LIMBS[i - 1] + c2;
      prod[i] = (uint64_t)s;
      c2 = s >> 64;
    }
  }
  for (int i = 0; i < 5; i++) {
    u128 diff = (u128)t[i] - prod[i] - borrow;
    t[i] = (uint64_t)diff;
    borrow = (diff >> 64) ? 1 : 0;  // two's-complement borrow out
  }
  // q may overshoot by <= 2: add L back while negative (borrow set)
  while (borrow) {
    u128 c2 = 0;
    for (int i = 0; i < 5; i++) {
      u128 s = (u128)t[i] + (i < 4 ? L_LIMBS[i] : 0) + c2;
      t[i] = (uint64_t)s;
      c2 = s >> 64;
    }
    borrow = c2 ? 0 : 1;  // still negative iff no carry out of bit 320
  }
  // one final conditional subtract: t may equal/exceed L but < 2L
  bool ge = t[4] != 0;
  if (!ge) {
    ge = true;
    for (int i = 3; i >= 0; i--) {
      if (t[i] != L_LIMBS[i]) {
        ge = t[i] > L_LIMBS[i];
        break;
      }
    }
  }
  if (ge) {
    u128 b2 = 0;
    for (int i = 0; i < 5; i++) {
      u128 diff = (u128)t[i] - (i < 4 ? L_LIMBS[i] : 0) - b2;
      t[i] = (uint64_t)diff;
      b2 = (diff >> 64) ? 1 : 0;
    }
  }
  for (int i = 0; i < 5; i++) acc[i] = t[i];
}

void reduce_digest_mod_l(const uint8_t digest_le[64], uint8_t out_le[32]) {
  uint64_t acc[5] = {0, 0, 0, 0, 0};
  for (int i = 7; i >= 0; i--) {
    uint64_t d = 0;
    for (int j = 7; j >= 0; j--) d = (d << 8) | digest_le[8 * i + j];
    reduce_step(acc, d);
  }
  for (int i = 0; i < 32; i++) out_le[i] = (uint8_t)(acc[i / 8] >> (8 * (i % 8)));
}

}  // namespace

extern "C" {

// rs/pks: [n][32]; msgs: concatenated message bytes with [n+1] offsets;
// out: [n][32] little-endian challenge scalars k = H(R||A||M) mod L.
void dagrider_challenge_batch(const uint8_t* rs, const uint8_t* pks,
                              const uint8_t* msgs, const uint64_t* msg_off,
                              uint64_t n, uint8_t* out) {
  sha512_fn ossl = resolve_openssl_sha512();
  uint8_t digest[64];
  if (ossl) {
    std::vector<uint8_t> buf;
    for (uint64_t i = 0; i < n; i++) {
      size_t mlen = msg_off[i + 1] - msg_off[i];
      buf.resize(64 + mlen);
      std::memcpy(buf.data(), rs + 32 * i, 32);
      std::memcpy(buf.data() + 32, pks + 32 * i, 32);
      std::memcpy(buf.data() + 64, msgs + msg_off[i], mlen);
      ossl(buf.data(), buf.size(), digest);
      reduce_digest_mod_l(digest, out + 32 * i);
    }
    return;
  }
  Sha512 sha;
  for (uint64_t i = 0; i < n; i++) {
    sha.reset();
    sha.update(rs + 32 * i, 32);
    sha.update(pks + 32 * i, 32);
    sha.update(msgs + msg_off[i], msg_off[i + 1] - msg_off[i]);
    sha.final(digest);
    reduce_digest_mod_l(digest, out + 32 * i);
  }
}

}  // extern "C"
