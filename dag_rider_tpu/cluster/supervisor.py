"""Cluster liveness supervisor: boot, kill -9, restart, gather.

The supervisor owns the OS processes. It boots one
:mod:`dag_rider_tpu.cluster.runner` per committee member, waits for the
per-node ready markers, then executes a **fault plan** — a list of
``{"t": seconds_from_start, "action": "kill" | "restart" | "term",
"node": i}`` events on the wall clock. ``kill`` is a genuine SIGKILL
(no handler runs, no flush happens: exactly the failure the WAL +
atomic-checkpoint machinery exists for); ``restart`` re-spawns the same
config, so the runner restores from its checkpoint, re-injects its WAL,
and rejoins via snapshot sync when the cluster has pruned past it.

Before a restart the supervisor writes the node's **delivered hint** —
the union of transaction payloads any CURRENT delivery log shows
committed — closing the torn-tail window where the dead node's own log
lost its final lines to the SIGKILL.

On any invariant violation the harness gathers each node's flight-
recorder dumps (the distributed black box): one causal chain spanning
processes, joined on content-derived trace ids.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time
from typing import Callable, Dict, List, Optional

from dag_rider_tpu.cluster.directory import ClusterSpec
from dag_rider_tpu.cluster.runner import read_delivered_txs


def seeded_kill_plan(
    seed: int,
    n: int,
    *,
    kill_at_s: float = 3.0,
    restart_after_s: float = 2.0,
    victims: int = 1,
) -> List[dict]:
    """A deterministic kill-and-rejoin plan: ``victims`` distinct nodes
    (chosen by seed, never node 0 so the client's primary target
    survives) each SIGKILLed at a seeded jitter around ``kill_at_s``
    and restarted ``restart_after_s`` later."""
    import random

    rng = random.Random(seed)
    order = list(range(1, n))
    rng.shuffle(order)
    plan = []
    for k, node in enumerate(order[: max(1, victims)]):
        t_kill = kill_at_s + k * 0.5 + rng.uniform(0.0, 0.5)
        plan.append({"t": round(t_kill, 3), "action": "kill", "node": node})
        plan.append(
            {
                "t": round(t_kill + restart_after_s, 3),
                "action": "restart",
                "node": node,
            }
        )
    return sorted(plan, key=lambda e: e["t"])


class ClusterSupervisor:
    """Spawns and terminates the per-node runner processes."""

    def __init__(
        self,
        spec: ClusterSpec,
        *,
        clock: Callable[[], float] = time.time,
        env: Optional[Dict[str, str]] = None,
        trace: bool = True,
    ) -> None:
        self.spec = spec
        self.clock = clock
        self.procs: Dict[int, subprocess.Popen] = {}
        self.kill_counts: Dict[int, int] = {}
        self.restart_counts: Dict[int, int] = {}
        self._outs: List = []
        base_env = dict(os.environ)
        # consensus workloads here are tiny; keep JAX off accelerators
        # and the runners' import time deterministic
        base_env.setdefault("JAX_PLATFORMS", "cpu")
        if trace:
            base_env["DAGRIDER_TRACE"] = "1"
        if env:
            base_env.update(env)
        self._env = base_env

    # -- lifecycle -----------------------------------------------------

    def start(self, index: int) -> None:
        nf = self.spec.nodes[index]
        env = dict(self._env)
        # per-node flight dir: the distributed black box gathers into
        # one place per process, not one shared trampled directory
        env["DAGRIDER_FLIGHT_DIR"] = nf.flight_dir
        out = open(nf.stdout, "a")
        err = open(nf.stderr, "a")
        self._outs += [out, err]
        self.procs[index] = subprocess.Popen(
            [
                sys.executable,
                "-m",
                "dag_rider_tpu.cluster.runner",
                "--config",
                nf.config,
            ],
            stdout=out,
            stderr=err,
            env=env,
        )

    def start_all(self) -> None:
        for i in range(self.spec.n):
            self.start(i)

    def wait_ready(self, timeout_s: float = 15.0) -> List[int]:
        """Block until every LIVE node's ready marker exists; returns
        the indices that failed to come up in time (empty = all good)."""
        deadline = self.clock() + timeout_s
        pending = set(self.procs)
        while pending and self.clock() < deadline:
            for i in sorted(pending):
                proc = self.procs[i]
                if proc.poll() is not None:
                    # died during boot: surface immediately
                    pending.discard(i)
                    continue
                if os.path.exists(self.spec.nodes[i].ready_marker):
                    pending.discard(i)
            if pending:
                time.sleep(0.05)
        dead = [
            i
            for i, p in self.procs.items()
            if p.poll() is not None
            or not os.path.exists(self.spec.nodes[i].ready_marker)
        ]
        return sorted(set(dead) | pending)

    def kill(self, index: int) -> None:
        """SIGKILL — the violent path. No handler, no flush, no
        checkpoint: whatever was not already on disk is gone."""
        proc = self.procs.get(index)
        if proc is None or proc.poll() is not None:
            return
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=10)
        self.kill_counts[index] = self.kill_counts.get(index, 0) + 1

    def write_delivered_hint(self, index: int) -> int:
        """Union every current delivery log's committed payloads into
        the node's hint file (read by the runner before re-injecting its
        WAL). Returns the hint size."""
        union = set()
        for i, nf in enumerate(self.spec.nodes):
            if i == index:
                continue
            union |= read_delivered_txs(nf.delivery_log)
        nf = self.spec.nodes[index]
        tmp = nf.delivered_hint + ".tmp"
        with open(tmp, "w") as fh:
            for tx in sorted(union):
                fh.write(tx.hex() + "\n")
        os.replace(tmp, nf.delivered_hint)
        return len(union)

    def restart(self, index: int) -> None:
        """Respawn a killed node from its on-disk state: checkpoint
        restore + WAL re-injection + (if pruned past) snapshot rejoin.
        The stale ready marker is cleared first so wait_ready() tracks
        THIS incarnation."""
        marker = self.spec.nodes[index].ready_marker
        try:
            os.remove(marker)
        except OSError:
            pass
        self.write_delivered_hint(index)
        self.start(index)
        self.restart_counts[index] = self.restart_counts.get(index, 0) + 1

    def run_plan(
        self, plan: List[dict], t0: Optional[float] = None
    ) -> List[dict]:
        """Execute fault events relative to ``t0`` (default: now).
        Returns the executed events with actual wall stamps attached."""
        start = self.clock() if t0 is None else t0
        executed = []
        for ev in sorted(plan, key=lambda e: e["t"]):
            delay = start + float(ev["t"]) - self.clock()
            if delay > 0:
                time.sleep(delay)
            node = int(ev["node"])
            if ev["action"] == "kill":
                self.kill(node)
            elif ev["action"] == "restart":
                self.restart(node)
            elif ev["action"] == "term":
                proc = self.procs.get(node)
                if proc is not None and proc.poll() is None:
                    proc.send_signal(signal.SIGTERM)
            else:
                raise ValueError(f"unknown fault action {ev['action']!r}")
            executed.append({**ev, "at": self.clock() - start})
        return executed

    def stop_all(self, timeout_s: float = 20.0) -> List[int]:
        """Graceful SIGTERM sweep (runners drain, checkpoint, and write
        final.json), SIGKILL stragglers. Returns indices that had to be
        SIGKILLed (their final.json is missing/stale — the audit treats
        them as crashed)."""
        for proc in self.procs.values():
            if proc.poll() is None:
                proc.send_signal(signal.SIGTERM)
        deadline = self.clock() + timeout_s
        forced = []
        for i, proc in sorted(self.procs.items()):
            left = deadline - self.clock()
            try:
                proc.wait(timeout=max(0.1, left))
            except subprocess.TimeoutExpired:
                proc.send_signal(signal.SIGKILL)
                proc.wait(timeout=10)
                forced.append(i)
        for fh in self._outs:
            try:
                fh.close()
            except OSError:
                pass
        self._outs = []
        return forced

    # -- post-mortem ---------------------------------------------------

    def gather_flight_dumps(self) -> Dict[int, List[str]]:
        """The distributed black box: every node's flight-recorder dump
        files (empty lists everywhere = clean run, the bench gate)."""
        dumps: Dict[int, List[str]] = {}
        for i, nf in enumerate(self.spec.nodes):
            try:
                files = sorted(
                    os.path.join(nf.flight_dir, f)
                    for f in os.listdir(nf.flight_dir)
                )
            except OSError:
                files = []
            dumps[i] = files
        return dumps

    def exit_codes(self) -> Dict[int, Optional[int]]:
        return {i: p.poll() for i, p in sorted(self.procs.items())}
