"""Over-the-wire load client: seeded traffic through the Submit door.

The in-process chaos suite drives :class:`~dag_rider_tpu.mempool.Mempool`
objects directly; here the same seeded
:class:`~dag_rider_tpu.mempool.loadgen.LoadGenerator` schedule crosses a
real socket — one JSON-framed unary RPC per transaction against
``/dagrider.Transport/Submit`` on the arrival's home node (client c →
node c mod n, mirroring the in-process driver's assignment).

A transaction counts as **accepted** only when some node's admission
verdict says so (``accepted`` — or ``deduped``, which means an earlier
ack already covered the identical bytes). Every accepted transaction is
appended to ``accepted.jsonl`` with its submit wall stamp — the audit's
zero-loss ledger and the join key (the payload bytes themselves) for
wire-level submit→deliver latency percentiles.

Failure handling is what a real client does: on an RPC error (the target
is dead, or mid kill -9) retry ONCE against the next node. If that also
fails, the transaction was never acknowledged, so the zero-loss audit
does not count it — exactly the at-least-once-ack contract.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Callable, List, Optional

import grpc

from dag_rider_tpu.cluster.directory import ClusterSpec
from dag_rider_tpu.mempool.loadgen import LoadGenerator

_SUBMIT_METHOD = "/dagrider.Transport/Submit"
_identity = lambda b: b  # noqa: E731 — bytes in, bytes out


class SubmitClient:
    """Thin per-cluster Submit stub pool with retry-next-node."""

    def __init__(
        self,
        spec: ClusterSpec,
        *,
        rpc_timeout_s: float = 2.0,
    ) -> None:
        self.spec = spec
        self.rpc_timeout_s = rpc_timeout_s
        self._channels: List[Optional[grpc.Channel]] = [None] * spec.n
        self._stubs: List[Optional[Callable]] = [None] * spec.n
        self._lock = threading.Lock()
        self.ok = 0
        self.errors = 0
        self.rejected = 0

    def _stub(self, node: int) -> Callable:
        with self._lock:
            stub = self._stubs[node]
            if stub is None:
                chan = grpc.insecure_channel(self.spec.addresses[node])
                self._channels[node] = chan
                stub = chan.unary_unary(
                    _SUBMIT_METHOD,
                    request_serializer=_identity,
                    response_deserializer=_identity,
                )
                self._stubs[node] = stub
            return stub

    def _drop_stub(self, node: int) -> None:
        with self._lock:
            chan = self._channels[node]
            self._channels[node] = None
            self._stubs[node] = None
        if chan is not None:
            chan.close()

    def submit(self, node: int, client: str, tx: bytes) -> Optional[dict]:
        """One transaction to ``node``, retrying once on the next node.
        Returns the admission verdict dict, or None when no node
        answered (the transaction is NOT acknowledged)."""
        body = json.dumps({"client": client, "txs": [tx.hex()]}).encode()
        for hop in range(2):
            target = (node + hop) % self.spec.n
            try:
                raw = self._stub(target)(body, timeout=self.rpc_timeout_s)
                if raw:
                    verdict = json.loads(raw)
                    verdict["node"] = target
                    return verdict
                # empty reply: door closed (shutdown) — treat as error
            except (grpc.RpcError, ValueError):
                pass
            # channel may be wedged on a dead incarnation; re-dial next use
            self._drop_stub(target)
        self.errors += 1
        return None

    def close(self) -> None:
        with self._lock:
            chans = [c for c in self._channels if c is not None]
            self._channels = [None] * self.spec.n
            self._stubs = [None] * self.spec.n
        for c in chans:
            c.close()


def drive_load(
    spec: ClusterSpec,
    *,
    duration_s: float,
    rate: float = 400.0,
    clients: int = 8,
    tx_bytes: int = 32,
    seed: int = 7,
    profile: str = "poisson",
    clock: Callable[[], float] = time.time,
    rpc_timeout_s: float = 2.0,
) -> dict:
    """Run the seeded open-loop schedule against the live cluster on the
    wall clock, recording every acknowledged transaction.

    Appends one JSON line per accepted transaction to
    ``spec.accepted_log``: ``{"tx": hex, "ts": submit stamp, "node": i,
    "client": c}``. Line-buffered like the node WALs, so the ledger
    survives a harness crash too. Returns the offered/accepted summary.
    """
    gen = LoadGenerator(
        clients=clients,
        rate=rate,
        tx_bytes=tx_bytes,
        seed=seed,
        profile=profile,
    )
    cli = SubmitClient(spec, rpc_timeout_s=rpc_timeout_s)
    accepted = 0
    deduped = 0
    shed = 0
    start = clock()
    with open(spec.accepted_log, "a", buffering=1) as ledger:
        while True:
            t = clock() - start
            if t >= duration_s:
                break
            for _, c, tx in gen.events_until(t):
                verdict = cli.submit(c % spec.n, f"c{c}", tx)
                if verdict is None:
                    continue
                if verdict.get("accepted") or verdict.get("deduped"):
                    stamp = clock()
                    if verdict.get("accepted"):
                        accepted += 1
                    else:
                        deduped += 1
                    ledger.write(
                        json.dumps(
                            {
                                "tx": tx.hex(),
                                "ts": stamp,
                                "node": verdict["node"],
                                "client": f"c{c}",
                            }
                        )
                        + "\n"
                    )
                else:
                    shed += int(verdict.get("shed", 0)) or 1
                    cli.rejected += 1
            # open loop: sleep to the next arrival, not on the system
            time.sleep(0.002)
    cli.close()
    return {
        "offered": gen.emitted,
        "accepted": accepted,
        "deduped": deduped,
        "shed": shed,
        "rpc_errors": cli.errors,
        "duration_s": duration_s,
    }


def read_accepted(path: str) -> List[dict]:
    """The accepted-transaction ledger (torn final line skipped)."""
    out: List[dict] = []
    try:
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                    if "tx" in rec:
                        out.append(rec)
                except ValueError:
                    continue
    except OSError:
        pass
    return out
