"""Post-hoc cluster audit: the paper's invariants over per-node logs.

The in-process suite asserts invariants against live Python objects; a
multi-process run only leaves files behind. This module reconstructs the
same evidence from the on-disk logs — per-node ``delivery.jsonl`` commit
records, the client's ``accepted.jsonl`` ledger, and each node's
``final.json`` retained-state report — and feeds it to the exact same
checkers in :mod:`dag_rider_tpu.consensus.invariants`:

- **agreement** + **commit uniqueness** over (round, source, digest)
  records parsed from every node's delivery log (kill -9 victims
  included: their log is a valid, possibly torn, prefix);
- **zero loss**: accepted ⊆ delivered ∪ retained, where retained is the
  union of clean-shutdown ``final.json`` retained sets;
- **bounded liveness** over the final decided waves;
- **wire latency**: submit→first-delivery percentiles joined on the
  transaction bytes (the client stamps submits, every deliverer stamps
  commits, and the payload itself is the join key — the same
  content-derived identity the trace ids use).

All checks are collected, not fail-fast: one report lists every broken
property, because a torn log that breaks agreement usually breaks
zero-loss too and the overlap is the diagnostic signal.
"""

from __future__ import annotations

import json
import os
from typing import Dict, Iterable, List, Optional, Tuple

from dag_rider_tpu.cluster.client import read_accepted
from dag_rider_tpu.cluster.directory import ClusterSpec
from dag_rider_tpu.consensus import invariants
from dag_rider_tpu.utils.metrics import Histogram


def read_delivery_log(path: str) -> List[dict]:
    """Per-node commit records (JSONL; torn final line skipped)."""
    out: List[dict] = []
    try:
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                    if "d" in rec and "r" in rec and "s" in rec:
                        out.append(rec)
                except ValueError:
                    continue  # torn tail
    except OSError:
        pass
    return out


def read_final(path: str) -> Optional[dict]:
    try:
        with open(path) as fh:
            return json.load(fh)
    except (OSError, ValueError):
        return None


def _sync_jumped(nf) -> bool:
    """True when the node's event log shows it rebuilt state mid-run —
    a checkpoint restore or a snapshot state transfer. Either skips
    already-committed (or pruned-past) history without replaying the
    on_deliver stream, leaving the same legitimate recovery gap in the
    delivery log as a supervised kill -9 + rejoin; a node that lagged
    hard enough to state-transfer in an otherwise clean run must be
    audited by embedding, not strict prefix agreement."""
    try:
        with open(nf.events_log) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue  # torn tail
                if rec.get("event") in ("restored", "state_transferred"):
                    return True
    except OSError:
        pass
    return False


def _records(log: List[dict]) -> List[invariants.Record]:
    return [
        (int(rec["r"]), int(rec["s"]), bytes.fromhex(rec["d"]))
        for rec in log
    ]


def flight_dumps(spec: ClusterSpec) -> Dict[int, List[str]]:
    """Flight-recorder dump files per node (non-empty = something
    tripped a trigger watch on that node)."""
    out: Dict[int, List[str]] = {}
    for i, nf in enumerate(spec.nodes):
        try:
            out[i] = sorted(os.listdir(nf.flight_dir))
        except OSError:
            out[i] = []
    return out


def audit_cluster(
    spec: ClusterSpec,
    *,
    restarted: Iterable[int] = (),
    byzantine: Iterable[int] = (),
    min_decided_wave: int = 1,
    require_finals: bool = True,
) -> dict:
    """Full post-hoc audit of a finished (or crashed) cluster run.

    ``restarted`` names the views that were killed and rejoined: their
    logs carry a legitimate recovery gap, so they are checked with
    :func:`~dag_rider_tpu.consensus.invariants.check_rejoin_embedding`
    against the canonical survivor order instead of strict prefix
    agreement. ``byzantine`` views are excluded from honest-order and
    liveness checks entirely (their logs still feed commit-uniqueness —
    an adversary must not get a conflicting digest committed anywhere).

    Returns a report dict; ``report["ok"]`` is True iff every property
    held. ``report["violations"]`` lists each failure as
    ``{"check": name, "detail": str}``.
    """
    restarted = set(restarted)
    byzantine = set(byzantine)
    # Auto-detect rejoiners the caller did not name: any node whose own
    # event log records a checkpoint restore or snapshot state transfer
    # carries a recovery gap, supervised restart or not.
    for i, nf in enumerate(spec.nodes):
        if i not in restarted and _sync_jumped(nf):
            restarted.add(i)
    violations: List[dict] = []

    def _run(name: str, fn, *a, **kw):
        try:
            fn(*a, **kw)
        except invariants.InvariantViolation as e:
            violations.append({"check": name, "detail": str(e)})

    # -- per-node commit logs -----------------------------------------
    dlogs = [read_delivery_log(nf.delivery_log) for nf in spec.nodes]
    logs = {i: _records(log) for i, log in enumerate(dlogs)}
    honest = [i for i in logs if i not in byzantine]
    steady = [i for i in honest if i not in restarted]
    _run(
        "agreement",
        invariants.check_agreement,
        {i: logs[i] for i in steady},
    )
    # canonical order = the most advanced steady honest log (fall back
    # to the longest honest log if every honest node was restarted)
    canon_pool = steady or honest
    canonical = max(
        (logs[i] for i in canon_pool), key=len, default=[]
    )
    for i in sorted(restarted & set(honest)):
        _run(
            f"rejoin_embedding_p{i}",
            invariants.check_rejoin_embedding,
            canonical,
            logs[i],
            view=i,
        )
    _run("commit_uniqueness", invariants.check_commit_uniqueness, logs)

    # -- zero loss of accepted transactions ---------------------------
    # Zero loss is a promise an HONEST node's ack makes; an ack from a
    # Byzantine node guarantees nothing (it may never propose the
    # transaction at all), so the ledger is filtered by accepting node.
    accepted_recs = [
        rec
        for rec in read_accepted(spec.accepted_log)
        if rec.get("node") not in byzantine
    ]
    accepted = [bytes.fromhex(rec["tx"]) for rec in accepted_recs]
    delivered_by_view = [
        [
            bytes.fromhex(hx)
            for rec in dlogs[i]
            for hx in rec.get("tx", ())
        ]
        for i in honest
    ]
    finals = [read_final(nf.final_report) for nf in spec.nodes]
    missing_finals = [i for i, f in enumerate(finals) if f is None]
    if require_finals and missing_finals:
        violations.append(
            {
                "check": "final_reports",
                "detail": f"missing final.json for nodes {missing_finals} "
                "(crashed during shutdown?)",
            }
        )
    retained: set = set()
    for i in honest:
        f = finals[i]
        if f:
            retained.update(bytes.fromhex(hx) for hx in f.get("retained", ()))
    tx_audit = invariants.transaction_audit(
        accepted, delivered_by_view, retained
    )
    _run("zero_loss", invariants.check_zero_loss, tx_audit)

    # -- liveness ------------------------------------------------------
    decided = {
        i: int(f.get("decided_wave", 0) or 0)
        for i, f in enumerate(finals)
        if f is not None and i not in byzantine
    }
    if decided:
        _run(
            "liveness",
            invariants.check_liveness,
            decided,
            min_max=min_decided_wave,
        )
    else:
        violations.append(
            {"check": "liveness", "detail": "no final reports at all"}
        )

    # -- flight recorder (distributed black box) ----------------------
    dumps = flight_dumps(spec)
    dirty = {
        i: fs for i, fs in dumps.items() if fs and i not in byzantine
    }
    if dirty:
        violations.append(
            {
                "check": "flight_dumps",
                "detail": f"flight recorder dumped on nodes {sorted(dirty)}: "
                f"{dirty}",
            }
        )

    # -- wire latency: submit stamp -> first delivery stamp -----------
    first_seen: Dict[bytes, float] = {}
    for log in dlogs:
        for rec in log:
            ts = rec.get("ts")
            if ts is None:
                continue
            for hx in rec.get("tx", ()):
                tx = bytes.fromhex(hx)
                if tx not in first_seen or ts < first_seen[tx]:
                    first_seen[tx] = ts
    lat = Histogram()
    for rec in accepted_recs:
        tx = bytes.fromhex(rec["tx"])
        seen = first_seen.get(tx)
        if seen is not None and seen >= rec["ts"]:
            lat.observe(seen - rec["ts"])

    report = {
        "ok": not violations,
        "violations": violations,
        "nodes": spec.n,
        "rejoined": sorted(restarted),
        "accepted_tx": tx_audit["accepted"],
        "delivered_tx": tx_audit["delivered"],
        "in_flight_tx": tx_audit["in_flight"],
        "lost_tx": tx_audit["lost"],
        "duplicate_tx": tx_audit["duplicates"],
        "decided_waves": decided,
        "log_lengths": {i: len(r) for i, r in logs.items()},
        "missing_finals": missing_finals,
        "flight_dump_files": sum(len(v) for v in dumps.values()),
    }
    if len(lat):
        report["submit_deliver_p50_ms"] = round(1e3 * lat.percentile(50), 3)
        report["submit_deliver_p99_ms"] = round(1e3 * lat.percentile(99), 3)
        report["latency_samples"] = len(lat)
    return report


def commit_prefix_digest(spec: ClusterSpec) -> Dict[int, Tuple[int, str]]:
    """Per-node (length, sha256 hex) of its full commit record sequence —
    the byte-identical-prefix evidence quoted in bench reports."""
    import hashlib

    out: Dict[int, Tuple[int, str]] = {}
    for i, nf in enumerate(spec.nodes):
        h = hashlib.sha256()
        recs = _records(read_delivery_log(nf.delivery_log))
        for r, s, d in recs:
            h.update(f"{r}:{s}:".encode() + d)
        out[i] = (len(recs), h.hexdigest())
    return out
