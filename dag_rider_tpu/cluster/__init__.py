"""Multi-process cluster harness (ISSUE 19).

Every prior round hardened the engine inside ONE simulator process; this
package runs the full stack as N separate OS processes over real sockets
and proves it survives violent failure:

- :mod:`directory` — workspace layout, address allocation (UDS or TCP),
  committee key dealing, per-node runner config files;
- :mod:`runner` — the per-node OS-process entrypoint
  (``python -m dag_rider_tpu.cluster.runner --config node0.json``): one
  :class:`dag_rider_tpu.node.Node` with a durable submit WAL, a
  line-buffered delivery log, the client Submit front door, and clean
  SIGTERM shutdown with a final state report;
- :mod:`supervisor` — boots the processes, injects process-level faults
  (kill -9 at seeded times, restart-from-checkpoint), and gathers logs,
  final reports, and flight-recorder dumps;
- :mod:`client` — the over-the-wire load generator: seeded open-loop
  traffic through the gRPC Submit door, recording per-transaction
  accepted stamps for the zero-loss audit;
- :mod:`audit` — post-hoc invariant checking over the per-node logs
  (commit-order agreement, uniqueness, zero loss of accepted
  transactions, liveness) via :mod:`dag_rider_tpu.consensus.invariants`.

The crash-durability contract: a transaction is only acknowledged to a
client after it is (a) admitted by the node's mempool AND (b) appended to
that node's line-buffered submit WAL — data a kill -9 cannot un-write.
On restart the runner re-injects WAL transactions not already covered by
its delivery log, its restored checkpoint state, or the supervisor's
cluster-wide delivered hint, so every acknowledged transaction is either
already committed or back in flight. The audit then proves the stronger
end-to-end property: accepted ⊆ delivered ∪ retained across the cluster.
"""

from dag_rider_tpu.cluster.directory import ClusterSpec, build_cluster

__all__ = ["ClusterSpec", "build_cluster"]
