"""Per-node OS-process entrypoint — ``python -m dag_rider_tpu.cluster.runner``.

One :class:`dag_rider_tpu.node.Node` wrapped in the harness durability
seams the kill -9 chaos suite audits against:

- **Submit WAL**: a transaction is acknowledged to the client only after
  the node's mempool accepted it AND its hex landed in a line-buffered
  append-only WAL. ``write(2)`` data survives SIGKILL (the kernel owns
  it once the syscall returns), so every acknowledged transaction is
  recoverable even when the process dies between checkpoints.
- **Delivery log**: every a_delivered vertex appends one JSON line
  (round, source, digest, payload hexes, wall stamp) — the audit's
  commit-order record AND the latency join point for wire-level
  submit→deliver percentiles.
- **Re-injection**: on restart the WAL is replayed minus what the
  delivery log, the restored checkpoint state (mempool pending, staged
  blocks, DAG payloads), and the supervisor's cluster-delivered hint
  already cover — zero loss without duplicate delivery.
- **Clean stop**: SIGTERM drains, checkpoints, and writes ``final.json``
  (metrics snapshot + retained transaction set) for the audit's
  accepted ⊆ delivered ∪ retained accounting.

Trace ids cross the process boundary for free: the round-16 trace key is
content-derived (``obs.tx_key`` = crc32 of the transaction bytes), so
the identical payload bytes produce the identical id at the client, the
accepting node, and every delivering node — the wire format IS the
propagation. Runners started with DAGRIDER_TRACE=1 each keep a flight
recorder whose dumps the supervisor gathers into one distributed black
box on any invariant violation.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import threading
import time
from typing import Callable, Set

from dag_rider_tpu.core.types import Block
from dag_rider_tpu.node import Node
from dag_rider_tpu.utils.slog import EventLog


def read_wal(path: str) -> list:
    """Acknowledged transactions from a submit WAL, oldest first.

    Tolerates a torn final line (kill -9 mid-append): a line that does
    not decode as hex is skipped — by construction it can only be the
    last one, and a torn line was never fsync'd into an acknowledgement.
    """
    txs = []
    try:
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    txs.append(bytes.fromhex(line))
                except ValueError:
                    continue  # torn tail
    except OSError:
        return []
    return txs


def read_delivered_txs(path: str) -> Set[bytes]:
    """Transaction payloads already committed per a delivery log
    (JSONL; torn final line skipped)."""
    out: Set[bytes] = set()
    try:
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                    for hx in rec.get("tx", ()):
                        out.add(bytes.fromhex(hx))
                except (ValueError, TypeError):
                    continue  # torn tail
    except OSError:
        pass
    return out


def read_hint(path: str) -> Set[bytes]:
    """The supervisor's cluster-delivered hint (hex lines): payloads some
    OTHER node already committed while we were dead. Closes the torn-tail
    duplicate window — our own delivery log may be missing its final
    entries, but a survivor's is not."""
    out: Set[bytes] = set()
    try:
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if line:
                    try:
                        out.add(bytes.fromhex(line))
                    except ValueError:
                        continue
    except OSError:
        pass
    return out


def retained_txs(node: Node) -> Set[bytes]:
    """Every accepted-but-not-yet-committed payload the node currently
    holds: mempool pending, staged proposal blocks, and live DAG vertex
    payloads (covers batched-and-proposed but undelivered)."""
    out: Set[bytes] = set()
    if node.mempool is not None:
        for entry in node.mempool.pool.pending():
            out.add(entry.tx)
    for block in node.process.blocks_to_propose:
        out.update(block.transactions)
    for v in node.process.dag.vertices.values():
        if v.block is not None:
            out.update(v.block.transactions)
    return out


class NodeRunner:
    """The harness wrapper around one Node: WAL, delivery log, Submit
    front door, re-injection, and shutdown reporting."""

    def __init__(
        self,
        cfg: dict,
        *,
        clock: Callable[[], float] = time.time,
    ) -> None:
        self.cfg = cfg
        self.files = cfg["files"]
        self.clock = clock
        self.index = int(cfg["node"]["index"])
        self._stop = threading.Event()
        self._reinject_due = threading.Event()
        self._wal_lock = threading.Lock()
        self._dlog_lock = threading.Lock()

        # Line-buffered text appends: each write() reaches the kernel at
        # the newline, which is exactly the durability SIGKILL respects.
        self._wal = open(self.files["submits_wal"], "a", buffering=1)
        self._dlog = open(self.files["delivery_log"], "a", buffering=1)
        self._events = open(self.files["events_log"], "a", buffering=1)

        log = EventLog(
            self._event_sink, clock=clock, node=self.index
        )
        self.node = Node(cfg["node"], log=log)

        # Delivery-log wrap: Process calls its on_deliver attribute per
        # committed vertex (pump thread); chain ours after the Node's
        # own bookkeeping so mempool latency books stay intact.
        inner = self.node.process.on_deliver
        self.node.process.on_deliver = (
            lambda v: (inner(v), self._log_delivery(v))
        )

        # Crash recovery: anything acknowledged before the previous
        # incarnation died must be back in flight unless some log shows
        # it already committed (or the restored state still holds it).
        self._reinject()

        # Client front door LAST: no submissions race the re-injection.
        self.node.net.set_submit_sink(self._on_submit)

    # -- sinks ---------------------------------------------------------

    def _event_sink(self, rec: dict) -> None:
        try:
            self._events.write(json.dumps(rec, default=repr) + "\n")
        except ValueError:
            pass  # closed during shutdown race
        # A rejoining node that restored an old checkpoint proposes at
        # rounds the cluster may have pruned past; the snapshot jump (or
        # an attested-floor prune) then discards those vertices — and
        # the acknowledged payloads they carried, which are now in no
        # mempool, no staging list, and no live vertex. Re-run WAL
        # re-injection whenever state is discarded so they re-enter the
        # pipeline. Deferred to the run loop: this sink fires on the
        # pump thread, which owns the very state _reinject scans.
        if rec.get("event") in ("state_transferred", "pruned"):
            self._reinject_due.set()

    def _log_delivery(self, vertex) -> None:
        txs = (
            [tx.hex() for tx in vertex.block.transactions]
            if vertex.block is not None
            else []
        )
        rec = {
            "ts": self.clock(),
            "r": vertex.id.round,
            "s": vertex.id.source,
            "d": vertex.digest().hex(),
            "tx": txs,
        }
        with self._dlog_lock:
            try:
                self._dlog.write(json.dumps(rec) + "\n")
            except ValueError:
                pass

    # -- submit front door --------------------------------------------

    def _on_submit(self, request: bytes) -> bytes:
        """gRPC Submit sink: {"client": c, "txs": [hex...]} in, the
        admission verdict out. WAL-before-ack: accepted transactions
        are appended (and kernel-owned) before the response leaves."""
        req = json.loads(request)
        txs = tuple(bytes.fromhex(t) for t in req["txs"])
        res = self.node.submit(
            Block(txs), client=str(req.get("client", "wire"))
        )
        if res is None:  # no mempool: legacy queue accepted everything
            accepted = len(txs)
            deduped = shed = 0
            state = "accept"
        else:
            accepted, deduped, shed, state = res
        if accepted or deduped:
            # Per-call granularity: the client submits one transaction
            # per RPC, so accepted>0 means THE transaction is in. (A
            # dedup hit means a prior ack already covered these bytes.)
            if accepted:
                with self._wal_lock:
                    for tx in txs:
                        self._wal.write(tx.hex() + "\n")
        return json.dumps(
            {
                "accepted": accepted,
                "deduped": deduped,
                "shed": shed,
                "state": state,
            }
        ).encode()

    # -- crash recovery -----------------------------------------------

    def _reinject(self) -> None:
        wal = read_wal(self.files["submits_wal"])
        if not wal:
            return
        covered = read_delivered_txs(self.files["delivery_log"])
        covered |= read_hint(self.files["delivered_hint"])
        try:
            covered |= retained_txs(self.node)
        except RuntimeError:
            # live-state scan raced the pump (dict mutated during
            # iteration); retry on the next run-loop tick
            self._reinject_due.set()
            return
        pending = [tx for tx in wal if tx not in covered]
        if not pending:
            return
        self.node.submit(Block(tuple(pending)), client="__wal__")
        self.node.process.metrics.inc("cluster_reinjects", len(pending))
        self.node.log.event(
            "cluster_reinject",
            count=len(pending),
            wal=len(wal),
            covered=len(covered & set(wal)),
        )

    # -- lifecycle -----------------------------------------------------

    def run(self, duration: float = 0.0) -> int:
        self.node.start()
        # Ready marker AFTER start: the gRPC server is bound during Node
        # construction, the pump is live now — the supervisor's boot
        # barrier waits on this file.
        with open(self.files["ready_marker"], "w") as fh:
            fh.write(str(os.getpid()))
        deadline = self.clock() + duration if duration > 0 else None
        while not self._stop.is_set():
            if deadline is not None and self.clock() >= deadline:
                break
            if self._reinject_due.is_set():
                self._reinject_due.clear()
                self._reinject()
            self._stop.wait(0.05)
        self.shutdown()
        return 0

    def request_stop(self) -> None:
        self._stop.set()

    def shutdown(self) -> None:
        self.node.net.set_submit_sink(None)  # refuse new client traffic
        self.node.stop()  # final drain + checkpoint (incl. mempool)
        retained = retained_txs(self.node)
        # WAL orphans count as retained: a state-transfer jump right
        # before SIGTERM may have discarded acknowledged payloads the
        # run loop never got to re-inject. They are durable on disk and
        # re-enter the pipeline on the next boot, so the audit's
        # accepted ⊆ delivered ∪ retained accounting must see them.
        covered = read_delivered_txs(self.files["delivery_log"])
        covered |= read_hint(self.files["delivered_hint"])
        covered |= retained
        retained |= {
            tx
            for tx in read_wal(self.files["submits_wal"])
            if tx not in covered
        }
        final = {
            "index": self.index,
            "round": self.node.process.round,
            "decided_wave": self.node.process.decided_wave,
            "delivered": len(self.node.delivered),
            "retained": sorted(tx.hex() for tx in retained),
            "metrics": self.node.process.metrics.snapshot(),
        }
        tmp = self.files["final_report"] + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(final, fh)
        os.replace(tmp, self.files["final_report"])
        for fh in (self._wal, self._dlog, self._events):
            try:
                fh.close()
            except OSError:
                pass



def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="dag_rider_tpu.cluster.runner")
    ap.add_argument("--config", required=True)
    ap.add_argument(
        "--duration", type=float, default=0.0, help="0 = until signaled"
    )
    args = ap.parse_args(argv)
    with open(args.config) as fh:
        cfg = json.load(fh)
    runner = NodeRunner(cfg)

    def _on_term(_sig, _frame):
        runner.request_stop()

    signal.signal(signal.SIGTERM, _on_term)
    signal.signal(signal.SIGINT, _on_term)
    return runner.run(args.duration)


if __name__ == "__main__":
    sys.exit(main())
