"""Cluster workspace layout + peer directory.

One workspace directory per cluster run:

    <root>/
      cluster.json          — the ClusterSpec (addresses, file map)
      keys.json             — dealer committee key material (seeded)
      sock/node<i>.sock     — UDS endpoints (transport="uds")
      node<i>/
        config.json         — runner config (node cfg + harness files)
        ckpt/               — periodic checkpoints
        flight/             — flight-recorder dumps (distributed black box)
        submits.wal         — acknowledged-transaction WAL (hex lines)
        delivery.jsonl      — committed-vertex log (one JSON line each)
        events.jsonl        — structured event log (slog records)
        final.json          — clean-shutdown state report
        ready               — liveness marker (written when serving)
        stdout.log / stderr.log

Addresses are allocated up front — UDS paths under the workspace, or
TCP ports reserved by binding ``127.0.0.1:0`` and recording what the OS
handed out — so every node's config can name every peer before any
process boots (static peer directory; discovery is the file, matching
the dealer-style key distribution).
"""

from __future__ import annotations

import json
import os
import socket
from dataclasses import dataclass, field
from typing import Dict, List, Optional

#: mempool TTL for cluster runs: the default 60 s is tuned for a live
#: simulator; across a kill -9 + restart-from-checkpoint window an
#: accepted-but-expired transaction would audit as LOST, so cluster
#: pools hold entries long past any plausible recovery time.
CLUSTER_MEMPOOL_TTL_S = 600.0


@dataclass
class NodeFiles:
    """Per-node harness file map (all paths absolute)."""

    workdir: str
    config: str
    checkpoint_dir: str
    flight_dir: str
    submits_wal: str
    delivery_log: str
    events_log: str
    final_report: str
    ready_marker: str
    stdout: str
    stderr: str
    delivered_hint: str

    @classmethod
    def for_node(cls, root: str, index: int) -> "NodeFiles":
        wd = os.path.join(root, f"node{index}")
        return cls(
            workdir=wd,
            config=os.path.join(wd, "config.json"),
            checkpoint_dir=os.path.join(wd, "ckpt"),
            flight_dir=os.path.join(wd, "flight"),
            submits_wal=os.path.join(wd, "submits.wal"),
            delivery_log=os.path.join(wd, "delivery.jsonl"),
            events_log=os.path.join(wd, "events.jsonl"),
            final_report=os.path.join(wd, "final.json"),
            ready_marker=os.path.join(wd, "ready"),
            stdout=os.path.join(wd, "stdout.log"),
            stderr=os.path.join(wd, "stderr.log"),
            delivered_hint=os.path.join(wd, "delivered.hint"),
        )


@dataclass
class ClusterSpec:
    """Everything the supervisor, client, and audit need to find a
    running (or finished) cluster on disk."""

    root: str
    n: int
    transport: str  # "uds" | "tcp"
    addresses: List[str]
    seed: int
    nodes: List[NodeFiles] = field(default_factory=list)
    accepted_log: str = ""

    def to_json(self) -> dict:
        return {
            "root": self.root,
            "n": self.n,
            "transport": self.transport,
            "addresses": list(self.addresses),
            "seed": self.seed,
            "accepted_log": self.accepted_log,
            "nodes": [vars(nf) for nf in self.nodes],
        }

    @classmethod
    def from_json(cls, blob: dict) -> "ClusterSpec":
        spec = cls(
            root=blob["root"],
            n=int(blob["n"]),
            transport=blob["transport"],
            addresses=list(blob["addresses"]),
            seed=int(blob["seed"]),
            accepted_log=blob.get("accepted_log", ""),
        )
        spec.nodes = [NodeFiles(**nf) for nf in blob["nodes"]]
        return spec

    @classmethod
    def load(cls, root: str) -> "ClusterSpec":
        with open(os.path.join(root, "cluster.json")) as fh:
            return cls.from_json(json.load(fh))

    def save(self) -> None:
        with open(os.path.join(self.root, "cluster.json"), "w") as fh:
            json.dump(self.to_json(), fh, indent=1)


def allocate_addresses(root: str, n: int, transport: str) -> List[str]:
    """Pre-allocate n peer addresses.

    ``uds``: paths under <root>/sock — collision-free by construction
    and immune to port exhaustion on busy CI hosts. The gRPC address
    form is ``unix:<path>``.
    ``tcp``: reserve ephemeral ports by binding :0 and recording the
    OS's choice. The sockets are closed before the nodes boot — a small
    reuse race, acceptable for a harness (UDS is the CI default).
    """
    if transport == "uds":
        sock_dir = os.path.join(root, "sock")
        os.makedirs(sock_dir, exist_ok=True)
        return [
            f"unix:{os.path.join(sock_dir, f'node{i}.sock')}"
            for i in range(n)
        ]
    if transport != "tcp":
        raise ValueError(f"transport must be 'uds' or 'tcp', got {transport!r}")
    socks, addrs = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        addrs.append(f"127.0.0.1:{s.getsockname()[1]}")
    for s in socks:
        s.close()
    return addrs


def _derive_auth_master(seed: int) -> str:
    import hashlib

    return hashlib.sha256(f"dagrider-cluster-{seed}|auth".encode()).hexdigest()


def build_cluster(
    root: str,
    n: int,
    *,
    transport: str = "uds",
    seed: int = 0,
    coin: str = "round_robin",
    cert: str = "off",
    rbc: bool = True,
    gc_depth: int = 16,
    checkpoint_every_s: float = 0.5,
    adversaries: Optional[Dict[int, dict]] = None,
    wan: Optional[dict] = None,
    node_overrides: Optional[dict] = None,
) -> ClusterSpec:
    """Lay out a cluster workspace: keys, addresses, per-node configs.

    ``adversaries`` maps node index -> {"kind": ..., "seed": ...} for
    Byzantine-over-sockets scenarios; ``wan`` is a WanFault config dict
    applied to EVERY node's transport (delay/drop at the real gRPC send
    seam). ``node_overrides`` merges extra keys into every node config
    (e.g. {"cert": "agg"} or mempool tuning).
    """
    if n < 4:
        raise ValueError(f"cluster needs n >= 4 (3f+1, f >= 1), got {n}")
    os.makedirs(root, exist_ok=True)
    addrs = allocate_addresses(root, n, transport)

    from dag_rider_tpu.node import _dump_secret_file, generate_keys

    keys_path = os.path.join(root, "keys.json")
    threshold = (n - 1) // 3 + 1  # f+1 coin shares reconstruct
    _dump_secret_file(
        keys_path,
        generate_keys(n, threshold, seed=f"dagrider-cluster-{seed}"),
    )

    spec = ClusterSpec(
        root=os.path.abspath(root),
        n=n,
        transport=transport,
        addresses=addrs,
        seed=seed,
        accepted_log=os.path.join(os.path.abspath(root), "accepted.jsonl"),
    )
    auth_master = _derive_auth_master(seed)
    for i in range(n):
        nf = NodeFiles.for_node(spec.root, i)
        os.makedirs(nf.workdir, exist_ok=True)
        os.makedirs(nf.checkpoint_dir, exist_ok=True)
        os.makedirs(nf.flight_dir, exist_ok=True)
        node_cfg = {
            "index": i,
            "n": n,
            "listen": addrs[i],
            "peers": {str(j): addrs[j] for j in range(n) if j != i},
            "keys": keys_path,
            "rbc": rbc,
            # cpu: real Ed25519 on every vertex without the device
            # verifier's AOT-compile boot cost — cluster rungs measure
            # process/socket behavior, not kernel throughput
            "verifier": "cpu",
            "coin": coin,
            "cert": cert,
            "gc_depth": gc_depth,
            "checkpoint_dir": nf.checkpoint_dir,
            "checkpoint_every_s": checkpoint_every_s,
            "mempool": {"ttl_s": CLUSTER_MEMPOOL_TTL_S},
            "auto_propose": False,
            "auth_master": auth_master,
            "snapshot_min_interval_s": 0.2,
        }
        if wan:
            node_cfg["wan"] = dict(wan)
        if adversaries and i in adversaries:
            node_cfg["adversary"] = dict(adversaries[i])
        if node_overrides:
            node_cfg.update(node_overrides)
        runner_cfg = {
            "node": node_cfg,
            "files": vars(nf),
        }
        with open(nf.config, "w") as fh:
            json.dump(runner_cfg, fh, indent=1)
        spec.nodes.append(nf)
    spec.save()
    return spec
