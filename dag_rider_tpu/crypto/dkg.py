"""Joint-Feldman distributed key generation for the threshold-BLS coin.

Replaces :meth:`ThresholdKeys.generate`'s trusted dealer — the exact gap
its docstring names ("a real deployment runs a DKG so nobody ever holds
the group secret", crypto/threshold.py) and the PKI the reference's TODO
asks for ("PKI and a threshold signature scheme with a threshold of
(f+1)-of-n", process/process.go:388). The output is drop-in
:class:`~dag_rider_tpu.crypto.threshold.ThresholdKeys` material: Shamir
x-coordinates are ``index + 1`` and the group public key lives in G2,
matching ``threshold.aggregate`` / ``batch_verify_shares`` unchanged.

Protocol (t-of-n, classic joint-Feldman):

1. **Deal.** Every participant d samples a degree-(t-1) polynomial
   ``f_d`` over Z_r, broadcasts Feldman commitments
   ``C_{d,k} = g2^{a_{d,k}}`` and sends each participant j the share
   ``s_{d,j} = f_d(j+1)`` over a *private* channel (here: XOR-pad +
   HMAC under a pairwise key from ECDH over the committee's Ed25519
   identities — :func:`channel_key` — so the consensus transport's
   plaintext gRPC never sees a share).
2. **Verify / complain.** j checks every received share against the
   dealer's commitments: ``g2^{s_{d,j}} == sum_k (j+1)^k * C_{d,k}``
   (evaluated in the exponent). Failures produce a public complaint.
3. **Reveal / disqualify.** A complained-against dealer must reveal the
   complained share publicly; everyone re-checks it against the
   commitments. Invalid or missing reveals disqualify the dealer.
   (Revealing a genuinely valid share only de-privatizes that one
   share — the standard Feldman trade for a one-round complaint fix.)
4. **Finalize.** With Q the qualified dealer set:
   ``share_sk_j = sum_{d in Q} s_{d,j}``,
   ``share_pk_i = prod_{d in Q} eval_d(i+1)``,
   ``group_pk = prod_{d in Q} C_{d,0}``. The group secret
   ``sum_{d in Q} a_{d,0}`` is never materialized anywhere.

Security model notes: Feldman commitments leak ``g2^{a_{d,0}}`` (fine
for BLS — the group pk is public anyway); bias via adaptive
disqualification (Gennaro et al.) is out of scope for a coin whose only
requirement is unpredictability-before-f+1-shares, which survives any
qualified set containing one honest dealer.
"""

from __future__ import annotations

import hashlib
import hmac
import os
import struct
from typing import Dict, List, Optional, Sequence, Set, Tuple

from dag_rider_tpu.crypto import bls12381 as bls
from dag_rider_tpu.crypto import ed25519 as ed
from dag_rider_tpu.crypto.threshold import ThresholdKeys

_SCALAR_BYTES = 32
_G2_BYTES = 4 * 48  # bls.g2_serialize: x.c1||x.c0||y.c1||y.c0, 48B BE each
_CHAN_DOMAIN = b"dagrider-dkg-chan-v1|"
_PAD_DOMAIN = b"dagrider-dkg-pad-v1|"
_TAG_DOMAIN = b"dagrider-dkg-tag-v1|"
TAG_BYTES = 32


# ---------------------------------------------------------------------------
# G2 wire format (commitments): the key-file format bls12381 already
# defines (g2_serialize — uncompressed, range/curve/subgroup-validated on
# read via the unreduced [r]P == O ladder), with two DKG-specific policy
# differences at the boundary: junk returns None instead of raising
# (Byzantine input is an expected verdict, not an exception), and the
# identity encoding is refused (an identity commitment is either a
# zero-polynomial dealer — a useless no-op contribution — or malformed).
# ---------------------------------------------------------------------------


def g2_encode(p) -> bytes:
    if p is None:
        raise ValueError("cannot encode the identity commitment")
    return bls.g2_serialize(p)


def g2_decode(data: bytes):
    """None on anything malformed: wrong length, out-of-range
    coordinates, off the twist, outside the r-order subgroup (an
    adversarial small-subgroup commitment would corrupt everyone's
    derived share_pks undetectably), or the identity encoding."""
    try:
        p = bls.g2_deserialize(data)
    except ValueError:
        return None
    return p  # g2_deserialize returns None only for the identity


# ---------------------------------------------------------------------------
# Private pairwise channels from the committee's Ed25519 identities
# ---------------------------------------------------------------------------


def channel_key(my_seed: bytes, peer_pk: bytes) -> Optional[bytes]:
    """Symmetric pairwise key: SHA-512(DH point)[:32] with
    DH = [a_i]A_j = [a_i a_j]B over edwards25519 (clamped scalars are
    multiples of 8, so small-subgroup components vanish). k_ij == k_ji
    because scalar multiplication commutes through the shared base."""
    a, _, _ = ed.expand_seed(my_seed)
    pt = ed.point_decompress(peer_pk)
    if pt is None or not ed.on_curve(pt):
        return None
    shared = ed.scalar_mult(a, pt)
    return hashlib.sha512(
        _CHAN_DOMAIN + ed.point_compress(shared)
    ).digest()[:32]


def _share_nonce(dealer: int, recipient: int) -> bytes:
    return dealer.to_bytes(4, "little") + recipient.to_bytes(4, "little")


def encrypt_share(key: bytes, dealer: int, recipient: int, s: int) -> bytes:
    """One-shot XOR-pad + MAC. The (dealer, recipient) pair encrypts
    exactly one scalar per DKG run, so the deterministic nonce never
    repeats under a key; the MAC binds the direction."""
    nonce = _share_nonce(dealer, recipient)
    pad = hashlib.sha512(_PAD_DOMAIN + key + nonce).digest()[:_SCALAR_BYTES]
    ct = bytes(
        a ^ b for a, b in zip(s.to_bytes(_SCALAR_BYTES, "little"), pad)
    )
    tag = hmac.new(key, _TAG_DOMAIN + nonce + ct, hashlib.sha256).digest()
    return ct + tag


def decrypt_share(
    key: bytes, dealer: int, recipient: int, blob: bytes
) -> Optional[int]:
    if len(blob) != _SCALAR_BYTES + TAG_BYTES:
        return None
    ct, tag = blob[:_SCALAR_BYTES], blob[_SCALAR_BYTES:]
    nonce = _share_nonce(dealer, recipient)
    want = hmac.new(key, _TAG_DOMAIN + nonce + ct, hashlib.sha256).digest()
    if not hmac.compare_digest(want, tag):
        return None
    pad = hashlib.sha512(_PAD_DOMAIN + key + nonce).digest()[:_SCALAR_BYTES]
    return int.from_bytes(bytes(a ^ b for a, b in zip(ct, pad)), "little")


# ---------------------------------------------------------------------------
# The per-participant state machine (transport-agnostic)
# ---------------------------------------------------------------------------


def _eval_commitments(commits: Sequence, x: int):
    """prod_k [x^k] C_k — the dealer's polynomial at x, in the exponent."""
    xs = 1
    scalars = []
    for _ in commits:
        scalars.append(xs)
        xs = xs * x % bls.R
    return bls.g2_msm(scalars, list(commits))


class DkgSession:
    """One participant's joint-Feldman run.

    Drive it: broadcast :meth:`commitment_blob`, send each j its
    :meth:`share_blob_for`; feed peers' traffic to :meth:`on_commitments`
    / :meth:`on_share`; after the dealing round, broadcast
    :meth:`complaints`; answer complaints against yourself with
    :meth:`reveal_blob`; feed reveals to :meth:`on_reveal`; then
    :meth:`finalize`. Message authenticity (who sent what) is the
    transport's job — gRPC deployments wrap frames in FrameAuth exactly
    like consensus traffic; share *confidentiality* is handled here.
    """

    def __init__(
        self,
        index: int,
        n: int,
        threshold: int,
        identity_seed: bytes,
        identity_pks: Sequence[bytes],
        *,
        rng: Optional[bytes] = None,
    ):
        if not 1 <= threshold <= n:
            raise ValueError("need 1 <= threshold <= n")
        if len(identity_pks) != n:
            raise ValueError("need one identity pk per participant")
        self.index = index
        self.n = n
        self.t = threshold
        self._seed = identity_seed
        self._ids = list(identity_pks)
        # Polynomial coefficients: rng is for tests only; deployments use
        # os.urandom. (The coefficients themselves necessarily live for
        # the whole session — reveal_blob re-evaluates the polynomial —
        # so there is no secret-scrubbing story here beyond process
        # lifetime.)
        material = rng if rng is not None else os.urandom(64)
        self._coeffs = [
            int.from_bytes(
                hashlib.sha512(
                    b"dkg-coeff|" + material + k.to_bytes(4, "little")
                ).digest(),
                "little",
            )
            % bls.R
            for k in range(threshold)
        ]
        self.commits = [bls.pk_of(a) for a in self._coeffs]
        #: dealer -> validated commitment vector
        self.peer_commits: Dict[int, List] = {self.index: self.commits}
        #: dealer -> decrypted share for me
        self.shares: Dict[int, int] = {
            self.index: self._poly_at(self.index + 1)
        }
        #: dealers I complained about (bad/missing/undecryptable share)
        self._my_complaints: Set[int] = set()
        #: (dealer, complainer) pairs still awaiting a valid reveal
        self._open_complaints: Set[Tuple[int, int]] = set()
        #: frames that outraced their dealer's commitments, stashed
        #: judgement-free until on_commitments replays them
        self._pending_shares: Dict[int, int] = {}
        self._pending_reveals: Dict[Tuple[int, int], bytes] = {}
        self.disqualified: Set[int] = set()

    # -- dealing ----------------------------------------------------------

    def _poly_at(self, x: int) -> int:
        acc = 0
        for c in reversed(self._coeffs):
            acc = (acc * x + c) % bls.R
        return acc

    def commitment_blob(self) -> bytes:
        return b"".join(g2_encode(c) for c in self.commits)

    def share_blob_for(self, j: int) -> Optional[bytes]:
        """Encrypted share for participant j (None if j's identity key is
        malformed — j will complain and this dealer must reveal)."""
        if j == self.index:
            return None
        key = channel_key(self._seed, self._ids[j])
        if key is None:
            return None
        return encrypt_share(key, self.index, j, self._poly_at(j + 1))

    # -- receiving --------------------------------------------------------

    def on_commitments(self, dealer: int, blob: bytes) -> bool:
        """Validate + store dealer's commitment vector. Malformed vectors
        disqualify immediately (commitments are broadcast, so everyone
        reaches the same verdict). Shares/reveals that arrived BEFORE the
        commitments (separate frames race over a real network) were
        stashed judgement-free and are re-judged now."""
        if dealer == self.index or dealer in self.peer_commits:
            return dealer in self.peer_commits
        if len(blob) != self.t * _G2_BYTES:
            self.disqualified.add(dealer)
            return False
        commits = []
        for k in range(self.t):
            p = g2_decode(blob[k * _G2_BYTES : (k + 1) * _G2_BYTES])
            if p is None:
                self.disqualified.add(dealer)
                return False
            commits.append(p)
        self.peer_commits[dealer] = commits
        s = self._pending_shares.pop(dealer, None)
        if s is not None and dealer not in self.shares:
            if self._share_ok(dealer, self.index + 1, s):
                self.shares[dealer] = s
                self._my_complaints.discard(dealer)
            else:
                self._my_complaints.add(dealer)
        for (d, complainer), blob_r in list(self._pending_reveals.items()):
            if d == dealer:
                del self._pending_reveals[(d, complainer)]
                self.on_reveal(d, complainer, blob_r)
        return True

    def _share_ok(self, dealer: int, x: int, s: int) -> bool:
        commits = self.peer_commits.get(dealer)
        if commits is None:
            return False
        return bls.pk_of(s) == _eval_commitments(commits, x)

    def on_share(self, dealer: int, blob: bytes) -> bool:
        """Decrypt + verify my share from dealer against its commitments.

        A share whose dealer's commitments have not arrived yet cannot
        be judged: it is stashed (no complaint, no verdict) and re-judged
        when the commitments land — the two frames race over a real
        network, and misjudging the ordering as dealer fault would force
        a needless public reveal (or, pre-round-5-fix, a divergent
        disqualification)."""
        if dealer == self.index or dealer in self.shares:
            return dealer in self.shares
        key = channel_key(self._seed, self._ids[dealer])
        s = (
            decrypt_share(key, dealer, self.index, blob)
            if key is not None
            else None
        )
        if s is None:
            self._my_complaints.add(dealer)
            return False
        if dealer not in self.peer_commits:
            self._pending_shares[dealer] = s
            return False
        if not self._share_ok(dealer, self.index + 1, s):
            self._my_complaints.add(dealer)
            return False
        self.shares[dealer] = s
        # A share can verify on a retransmit after an earlier failure
        # (e.g. commitments arrived late): clear the provisional
        # complaint, or the dealer would be forced into a needless
        # public reveal of this node's share.
        self._my_complaints.discard(dealer)
        return True

    # -- complaints -------------------------------------------------------

    def complaints(self) -> List[int]:
        """Dealers to publicly complain about: bad shares seen so far plus
        dealers whose share (or commitments) never arrived. Call once the
        dealing round is over (driver-level timeout)."""
        missing = {
            d
            for d in range(self.n)
            if d != self.index
            and (d not in self.shares or d not in self.peer_commits)
        }
        self._my_complaints |= missing
        return sorted(self._my_complaints)

    def on_complaint(self, complainer: int, dealer: int) -> None:
        if complainer == dealer or not 0 <= dealer < self.n:
            return
        if dealer in self.disqualified:
            return
        self._open_complaints.add((dealer, complainer))

    def reveal_blob(self, complainer: int) -> bytes:
        """Public reveal of complainer's share (this dealer answering a
        complaint against itself)."""
        return self._poly_at(complainer + 1).to_bytes(
            _SCALAR_BYTES, "little"
        )

    def on_reveal(self, dealer: int, complainer: int, blob: bytes) -> None:
        """A revealed share settles the complaint: valid -> complaint
        cleared (and the complainer adopts it as its share if it was the
        one complaining); invalid -> dealer disqualified. A reveal that
        outraces the dealer's commitments is stashed judgement-free and
        replayed by on_commitments."""
        if (dealer, complainer) not in self._open_complaints:
            return
        if len(blob) != _SCALAR_BYTES:
            self.disqualified.add(dealer)
            return
        if dealer not in self.peer_commits:
            self._pending_reveals[(dealer, complainer)] = bytes(blob)
            return
        s = int.from_bytes(blob, "little")
        if self._share_ok(dealer, complainer + 1, s):
            self._open_complaints.discard((dealer, complainer))
            if complainer == self.index and dealer not in self.shares:
                self.shares[dealer] = s
                self._my_complaints.discard(dealer)
        else:
            self.disqualified.add(dealer)

    # -- output -----------------------------------------------------------

    def finalize(self) -> "DkgResult":
        """Close the run: unanswered complaints disqualify, and the
        qualified dealers' contributions combine into ThresholdKeys-shaped
        output (share_sks holds only MY share; the rest are None)."""
        for dealer, _ in list(self._open_complaints):
            self.disqualified.add(dealer)
        qualified = sorted(
            d
            for d in self.peer_commits
            if d not in self.disqualified
            and (d == self.index or d in self.shares)
        )
        if len(qualified) < self.t:
            raise RuntimeError(
                f"DKG failed: only {len(qualified)} qualified dealers "
                f"(< threshold {self.t})"
            )
        share_sk = sum(self.shares[d] for d in qualified) % bls.R
        # Commitments are homomorphic in the coefficients: summing the
        # qualified vectors coefficient-wise once, then evaluating the
        # combined polynomial per participant, replaces n*|Q| t-term
        # MSMs with |Q|*t adds + n MSMs (identical output, ~|Q|x less
        # work at committee scale).
        combined = [None] * self.t
        for d in qualified:
            for k, c in enumerate(self.peer_commits[d]):
                combined[k] = bls.g2_add(combined[k], c)
        group_pk = combined[0]
        share_pks = [
            _eval_commitments(combined, i + 1) for i in range(self.n)
        ]
        return DkgResult(
            index=self.index,
            threshold=self.t,
            qualified=tuple(qualified),
            share_sk=share_sk,
            share_pks=tuple(share_pks),
            group_pk=group_pk,
        )


class DkgResult:
    """One participant's DKG output, adaptable to ThresholdKeys."""

    def __init__(self, index, threshold, qualified, share_sk, share_pks, group_pk):
        self.index = index
        self.threshold = threshold
        self.qualified = qualified
        self.share_sk = share_sk
        self.share_pks = share_pks
        self.group_pk = group_pk

    def to_keys(self) -> ThresholdKeys:
        """ThresholdKeys view for the existing coin machinery: share_sks
        carries only this participant's secret (None elsewhere) — exactly
        the dealerless property."""
        sks: List[Optional[int]] = [None] * len(self.share_pks)
        sks[self.index] = self.share_sk
        return ThresholdKeys(
            self.threshold, self.group_pk, self.share_pks, sks
        )


# ---------------------------------------------------------------------------
# In-process driver (the message flow, honestly executed — the gRPC
# runner in node.py routes these same blobs over the network)
# ---------------------------------------------------------------------------


def run_dkg(
    n: int,
    threshold: int,
    identity_seeds: Sequence[bytes],
    *,
    byzantine: Optional[Dict[int, str]] = None,
) -> List[DkgResult]:
    """Full joint-Feldman round among n in-process participants.

    ``byzantine`` maps dealer index -> fault: "bad_share" (corrupt every
    outgoing share; the reveal is also bad, so disqualification follows),
    "silent" (deal nothing). Returns each honest participant's result;
    Byzantine participants get no result (None placeholders are skipped).
    """
    byzantine = byzantine or {}
    pks = [ed.generate_keypair(s)[1] for s in identity_seeds]
    sessions = [
        DkgSession(i, n, threshold, identity_seeds[i], pks)
        for i in range(n)
    ]
    # deal: broadcast commitments, direct-send shares
    for d, sess in enumerate(sessions):
        fault = byzantine.get(d)
        if fault == "silent":
            continue
        cblob = sess.commitment_blob()
        for j, other in enumerate(sessions):
            if j == d:
                continue
            other.on_commitments(d, cblob)
        for j, other in enumerate(sessions):
            if j == d:
                continue
            blob = sess.share_blob_for(j)
            if fault == "bad_share":
                blob = bytes(len(blob))  # MAC fails -> undecryptable
            other.on_share(d, blob)
    # complain: broadcast
    all_complaints = {i: sess.complaints() for i, sess in enumerate(sessions)}
    for complainer, dealers in all_complaints.items():
        for dealer in dealers:
            for sess in sessions:
                sess.on_complaint(complainer, dealer)
    # reveal: each complained-against dealer answers publicly
    for complainer, dealers in all_complaints.items():
        for dealer in dealers:
            fault = byzantine.get(dealer)
            if fault == "silent":
                continue  # no reveal -> finalize() disqualifies
            blob = sessions[dealer].reveal_blob(complainer)
            if fault == "bad_share":
                blob = bytes(_SCALAR_BYTES)
            for sess in sessions:
                sess.on_reveal(dealer, complainer, blob)
    return [
        sessions[i].finalize() for i in range(n) if i not in byzantine
    ]


# ---------------------------------------------------------------------------
# Epoch resharing entry (ISSUE 20)
# ---------------------------------------------------------------------------


def run_resharing(
    n: int,
    threshold: int,
    epoch_seed: bytes,
    *,
    byzantine: Optional[Dict[int, str]] = None,
) -> List[DkgResult]:
    """Joint-Feldman resharing for an epoch boundary: every input —
    identity seeds AND each dealer's polynomial material — is derived
    from ``epoch_seed``, so any two processes that committed the same
    reconfiguration transcript run byte-identical protocol flows and
    finalize the same group key. This is what lets the in-process epoch
    manager rotate keys without a wire round-trip: the "randomness" is
    the committed transcript digest, which the adversary cannot bias
    after the fact any more than it can bias the ordered log itself
    (the networked deployment path swaps in per-node ``os.urandom``
    material over :func:`run_dkg_networked` unchanged).
    """
    byzantine = byzantine or {}
    identity_seeds = [
        hashlib.sha512(
            b"dkg-reshare-id|" + epoch_seed + i.to_bytes(4, "little")
        ).digest()[:32]
        for i in range(n)
    ]
    pks = [ed.generate_keypair(s)[1] for s in identity_seeds]
    sessions = [
        DkgSession(
            i,
            n,
            threshold,
            identity_seeds[i],
            pks,
            rng=hashlib.sha512(
                b"dkg-reshare-coeff|" + epoch_seed + i.to_bytes(4, "little")
            ).digest(),
        )
        for i in range(n)
    ]
    for d, sess in enumerate(sessions):
        fault = byzantine.get(d)
        if fault == "silent":
            continue
        cblob = sess.commitment_blob()
        for j, other in enumerate(sessions):
            if j == d:
                continue
            other.on_commitments(d, cblob)
        for j, other in enumerate(sessions):
            if j == d:
                continue
            blob = sess.share_blob_for(j)
            if fault == "bad_share":
                blob = bytes(len(blob))
            other.on_share(d, blob)
    all_complaints = {i: sess.complaints() for i, sess in enumerate(sessions)}
    for complainer, dealers in all_complaints.items():
        for dealer in dealers:
            for sess in sessions:
                sess.on_complaint(complainer, dealer)
    for complainer, dealers in all_complaints.items():
        for dealer in dealers:
            fault = byzantine.get(dealer)
            if fault == "silent":
                continue
            blob = sessions[dealer].reveal_blob(complainer)
            if fault == "bad_share":
                blob = bytes(_SCALAR_BYTES)
            for sess in sessions:
                sess.on_reveal(dealer, complainer, blob)
    return [
        sessions[i].finalize() for i in range(n) if i not in byzantine
    ]


# ---------------------------------------------------------------------------
# Networked runner (gRPC BlobBus — the deployment path; VERDICT r4 #9)
# ---------------------------------------------------------------------------


def run_dkg_networked(
    bus,
    n: int,
    threshold: int,
    identity_seed: bytes,
    identity_pks: Sequence[bytes],
    *,
    phase_timeout_s: float = 15.0,
    poll_s: float = 0.05,
) -> "DkgResult":
    """One participant's joint-Feldman run over a
    :class:`~dag_rider_tpu.transport.blobbus.BlobBus` (or anything with
    its send/broadcast/recv surface).

    Four timed phases — deal, complain, reveal, confirm — each barriered
    on either hearing from every peer or the phase timeout, so silent or
    partitioned dealers cost one timeout, not a deadlock, and end up
    disqualified exactly as in the in-process driver. Retransmits the
    deal once mid-phase to ride out one-shot send failures (the bus has
    no retry of its own).

    The CONFIRM phase makes key divergence a detected abort, not a
    silent fork: timeout-based views can legitimately differ (a dealer
    that crashed after reaching half the committee is qualified on one
    side, complained-about on the other), so every participant
    broadcasts a digest of its (qualified, group_pk, share_pks) and
    requires every peer's digest to match — any mismatch or missing
    confirmation raises, and the operators rerun the ceremony. A
    Byzantine participant can therefore abort the run (deny the
    ceremony) but never split it into two working committees with
    different group keys."""
    import time as _t

    me = bus.index
    sess = DkgSession(me, n, threshold, identity_seed, identity_pks)
    others = [j for j in range(n) if j != me]

    def _deal() -> None:
        bus.broadcast("dkg_commit", sess.commitment_blob())
        for j in others:
            blob = sess.share_blob_for(j)
            if blob is not None:
                bus.send(j, "dkg_share", blob)

    complaints_from: Dict[int, List[int]] = {}
    confirms: Dict[int, bytes] = {}

    def _pump() -> None:
        for sender, kind, payload in bus.recv():
            if not 0 <= sender < n or sender == me:
                continue
            if kind == "dkg_commit":
                sess.on_commitments(sender, payload)
            elif kind == "dkg_share":
                sess.on_share(sender, payload)
            elif kind == "dkg_confirm":
                confirms.setdefault(sender, payload)
            elif kind == "dkg_complaint":
                dealers = [
                    d
                    for d in payload
                    if d < n  # one byte per dealer index (n <= 255 here)
                ]
                complaints_from[sender] = dealers
                for d in dealers:
                    sess.on_complaint(sender, d)
            elif kind == "dkg_reveal":
                if len(payload) >= 4:
                    (complainer,) = struct.unpack_from("<I", payload)
                    sess.on_reveal(sender, complainer, payload[4:])

    def _phase(done, timeout: float, *, mid=None) -> None:
        deadline = _t.monotonic() + timeout
        fired_mid = False
        while _t.monotonic() < deadline:
            _pump()
            if done():
                return
            if mid is not None and not fired_mid and (
                deadline - _t.monotonic() < timeout / 2
            ):
                fired_mid = True
                mid()
            bus.wait(poll_s)
        _pump()

    if n > 255:
        raise ValueError("networked DKG complaint frame packs byte indices")
    # phase 1: deal, and hear everyone's deal
    _deal()
    _phase(
        lambda: all(
            d in sess.peer_commits and d in sess.shares for d in others
        ),
        phase_timeout_s,
        mid=_deal,  # one retransmit halfway through the window
    )
    # phase 2: broadcast complaints (always — peers barrier on hearing
    # from everyone), hear everyone's. The broadcast must ALSO be fed to
    # our own session: _pump filters sender == me, and on_reveal only
    # accepts reveals for complaints registered via on_complaint — a
    # complainer that skipped self-registration would reject the very
    # reveal it waited for (round-5 review: one false complaint aborted
    # every networked ceremony while the in-process driver — which
    # delivers to all sessions including the sender's — passed).
    my_complaints = sess.complaints()
    for d in my_complaints:
        sess.on_complaint(me, d)
    bus.broadcast("dkg_complaint", bytes(my_complaints))
    _phase(
        lambda: all(j in complaints_from for j in others),
        phase_timeout_s,
    )

    # phase 3: answer complaints against me; wait until every open
    # complaint against OTHER dealers is settled (valid reveal clears
    # the entry; invalid reveal marks the dealer disqualified) or the
    # window closes. Driven off the session's own _open_complaints —
    # the authoritative set — not a complaints_from snapshot, which a
    # duplicate/forged complaint frame can overwrite racily.
    def _reveal(complainer: int) -> None:
        # the complaint may be about MISSING commitments (the complainer
        # started late and lost the deal broadcast): re-broadcast them
        # first so the reveal that follows can actually be judged
        bus.broadcast("dkg_commit", sess.commitment_blob())
        blob = sess.reveal_blob(complainer)
        bus.broadcast(
            "dkg_reveal", struct.pack("<I", complainer) + blob
        )
        # self-feed, or our own open (me, complainer) entry would
        # never clear and finalize() would self-disqualify us
        sess.on_reveal(me, complainer, blob)

    for dealer, complainer in list(sess._open_complaints):
        if dealer == me:
            _reveal(complainer)

    def _reveals_settled() -> bool:
        return all(
            d in sess.disqualified
            for d, _ in sess._open_complaints
            if d != me
        )

    _phase(_reveals_settled, phase_timeout_s)
    # answer complaints that arrived during phase 3 before closing (a
    # residual race here means divergent views — caught by CONFIRM)
    for dealer, complainer in list(sess._open_complaints):
        if dealer == me:
            _reveal(complainer)
    result = sess.finalize()
    # phase 4: confirm — everyone must have derived the same key set
    digest = hashlib.sha256(
        b"dkg-confirm|"
        + bytes(result.qualified)
        # g2_serialize, not g2_encode: the digest must never raise, and
        # it encodes a (negligible-probability) identity as zeros
        + bls.g2_serialize(result.group_pk)
        + b"".join(bls.g2_serialize(pk) for pk in result.share_pks)
    ).digest()
    bus.broadcast("dkg_confirm", digest)
    _phase(lambda: all(j in confirms for j in others), phase_timeout_s)
    bad = [
        j
        for j in others
        if confirms.get(j) != digest
    ]
    if bad:
        raise RuntimeError(
            "DKG confirmation failed: participants "
            f"{bad} missing or diverged — rerun the ceremony"
        )
    return result
