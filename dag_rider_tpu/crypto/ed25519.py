"""Ed25519 (RFC 8032) — host reference implementation.

The reference repository has **no cryptography at all** (SURVEY.md D10: no
signatures, no authentication, ``go.mod`` has no crypto deps). This module
supplies the per-vertex signing scheme the north star requires
(BASELINE.json: "per-vertex reliable-broadcast verify ... vmap'd Ed25519"),
implemented from the RFC 8032 specification in pure Python:

- the *correctness oracle* for the TPU verifier (byte-identical accept
  masks are asserted between this and the JAX/Pallas path), and
- the CPU Verifier backend (configs #1-2 of the benchmark ladder).

Big-int field arithmetic uses Python ints (CPython's native bignums); the
TPU path re-implements the field in int32 limbs (ops/field.py).
"""

from __future__ import annotations

import hashlib
import secrets
from typing import List, Optional, Sequence, Tuple

# --- field / curve parameters (RFC 8032 §5.1) ------------------------------

P = 2**255 - 19
L = 2**252 + 27742317777372353535851937790883648493  # group order
D = (-121665 * pow(121666, P - 2, P)) % P  # edwards d
SQRT_M1 = pow(2, (P - 1) // 4, P)  # sqrt(-1)

# Base point: y = 4/5 (mod p), x recovered with even parity.
_BY = (4 * pow(5, P - 2, P)) % P


def _inv(x: int) -> int:
    return pow(x, P - 2, P)


def _recover_x(y: int, sign: int) -> Optional[int]:
    """x from y via x^2 = (y^2 - 1) / (d y^2 + 1)  (RFC 8032 §5.1.3)."""
    if y >= P:
        return None
    x2 = (y * y - 1) * _inv(D * y * y + 1) % P
    if x2 == 0:
        return None if sign else 0
    x = pow(x2, (P + 3) // 8, P)
    if (x * x - x2) % P != 0:
        x = x * SQRT_M1 % P
    if (x * x - x2) % P != 0:
        return None
    if x & 1 != sign:
        x = P - x
    return x


_BX = _recover_x(_BY, 0)
assert _BX == 15112221349535400772501151409588531511454012693041857206046113283949847762202

# Points are extended homogeneous coordinates (X, Y, Z, T), x=X/Z, y=Y/Z,
# T = XY/Z.
Point = Tuple[int, int, int, int]
B: Point = (_BX, _BY, 1, _BX * _BY % P)
IDENTITY: Point = (0, 1, 1, 0)


def point_add(p1: Point, p2: Point) -> Point:
    """Unified addition (RFC 8032 §5.1.4) — complete on the curve."""
    X1, Y1, Z1, T1 = p1
    X2, Y2, Z2, T2 = p2
    A = (Y1 - X1) * (Y2 - X2) % P
    Bv = (Y1 + X1) * (Y2 + X2) % P
    C = 2 * T1 * T2 * D % P
    Dv = 2 * Z1 * Z2 % P
    E = Bv - A
    F = Dv - C
    G = Dv + C
    H = Bv + A
    return (E * F % P, G * H % P, F * G % P, E * H % P)


def point_double(p1: Point) -> Point:
    X1, Y1, Z1, _ = p1
    A = X1 * X1 % P
    Bv = Y1 * Y1 % P
    C = 2 * Z1 * Z1 % P
    H = A + Bv
    E = H - (X1 + Y1) * (X1 + Y1)
    G = A - Bv
    F = C + G
    return (E * F % P, G * H % P, F * G % P, E * H % P)


def point_neg(p1: Point) -> Point:
    X, Y, Z, T = p1
    return (P - X if X else 0, Y, Z, P - T if T else 0)


def scalar_mult(s: int, p1: Point) -> Point:
    """Double-and-add (host oracle; the TPU path uses fixed windows)."""
    q = IDENTITY
    while s > 0:
        if s & 1:
            q = point_add(q, p1)
        p1 = point_double(p1)
        s >>= 1
    return q


# Fixed-base comb for B — the host-side twin of the TPU verifier's comb
# tables (ops/comb.py): [s]B = sum_w [digit_w(s) * 16^w]B is 63 additions
# with ZERO doublings from a precomputed [64][16] table. Built lazily
# (~25 ms once); signing was ~8.5 ms/op on the 380-op double-and-add
# ladder and every sign/keygen multiplies the FIXED base, so this is the
# hot path of bench batch building and per-proposal signing.
_B_COMB: Optional[List[List[Point]]] = None


def _b_comb() -> List[List[Point]]:
    global _B_COMB
    if _B_COMB is None:
        table: List[List[Point]] = []
        g = B
        for _ in range(64):
            row = [IDENTITY]
            for _ in range(15):
                row.append(point_add(row[-1], g))
            table.append(row)
            for _ in range(4):
                g = point_double(g)
        _B_COMB = table
    return _B_COMB


def scalar_mult_base(s: int) -> Point:
    """[s]B via the fixed-base comb (bit-identical to scalar_mult(s, B):
    the same group element by associativity; tests assert equality).
    The 64-window table covers s < 2^256 — every RFC 8032 scalar (clamped
    secrets and values reduced mod L); larger inputs fall back to the
    ladder rather than walking off the table."""
    if s >= 1 << 256:
        return scalar_mult(s, B)
    table = _b_comb()
    q = IDENTITY
    w = 0
    while s > 0:
        d = s & 0xF
        if d:
            q = point_add(q, table[w][d])
        s >>= 4
        w += 1
    return q


def point_equal(p1: Point, p2: Point) -> bool:
    X1, Y1, Z1, _ = p1
    X2, Y2, Z2, _ = p2
    return (X1 * Z2 - X2 * Z1) % P == 0 and (Y1 * Z2 - Y2 * Z1) % P == 0


def point_compress(p1: Point) -> bytes:
    X, Y, Z, _ = p1
    zi = _inv(Z)
    x = X * zi % P
    y = Y * zi % P
    return int.to_bytes(y | ((x & 1) << 255), 32, "little")


def point_decompress(data: bytes) -> Optional[Point]:
    if len(data) != 32:
        return None
    enc = int.from_bytes(data, "little")
    y = enc & ((1 << 255) - 1)
    sign = enc >> 255
    x = _recover_x(y, sign)
    if x is None:
        return None
    return (x, y, 1, x * y % P)


def on_curve(p1: Point) -> bool:
    X, Y, Z, T = p1
    if Z % P == 0 or (X * Y - Z * T) % P != 0:
        return False
    # -x^2 + y^2 = z^2 + d t^2 (projective twisted Edwards a=-1)
    return (-X * X + Y * Y - Z * Z - D * T * T) % P == 0


# --- keys / sign / verify (RFC 8032 §5.1.5-5.1.7) --------------------------


def _sha512(*parts: bytes) -> bytes:
    h = hashlib.sha512()
    for part in parts:
        h.update(part)
    return h.digest()


def _clamp(h: bytes) -> int:
    a = int.from_bytes(h[:32], "little")
    a &= (1 << 254) - 8
    a |= 1 << 254
    return a


def generate_keypair(seed: Optional[bytes] = None) -> Tuple[bytes, bytes]:
    """Returns (private_seed32, public_key32)."""
    if seed is None:
        seed = secrets.token_bytes(32)
    if len(seed) != 32:
        raise ValueError("seed must be 32 bytes")
    a = _clamp(_sha512(seed))
    A = scalar_mult_base(a)
    return seed, point_compress(A)


def expand_seed(seed: bytes) -> Tuple[int, bytes, bytes]:
    """One-time key expansion: (scalar a, prefix, A_enc). Callers that sign
    repeatedly (VertexSigner) cache this — re-deriving A costs a full
    scalar multiplication per signature otherwise."""
    h = _sha512(seed)
    a = _clamp(h)
    prefix = h[32:]
    A_enc = point_compress(scalar_mult_base(a))
    return a, prefix, A_enc


def sign_expanded(a: int, prefix: bytes, A_enc: bytes, message: bytes) -> bytes:
    r = int.from_bytes(_sha512(prefix, message), "little") % L
    R_enc = point_compress(scalar_mult_base(r))
    k = int.from_bytes(_sha512(R_enc, A_enc, message), "little") % L
    s = (r + k * a) % L
    return R_enc + int.to_bytes(s, 32, "little")


def sign(seed: bytes, message: bytes) -> bytes:
    return sign_expanded(*expand_seed(seed), message)


def verify(public_key: bytes, message: bytes, signature: bytes) -> bool:
    """Unbatched verification: [S]B == R + [k]A (non-cofactored)."""
    if len(signature) != 64 or len(public_key) != 32:
        return False
    A = point_decompress(public_key)
    R = point_decompress(signature[:32])
    if A is None or R is None:
        return False
    s = int.from_bytes(signature[32:], "little")
    if s >= L:  # malleability check (RFC 8032 §5.1.7)
        return False
    k = int.from_bytes(_sha512(signature[:32], public_key, message), "little") % L
    sB = scalar_mult_base(s)
    kA = scalar_mult(k, A)
    return point_equal(sB, point_add(R, kA))


def verify_batch(
    items: Sequence[Tuple[bytes, bytes, bytes]]
) -> List[bool]:
    """Per-item verification of (public_key, message, signature) triples.

    Intentionally independent per item (no random linear combination): the
    output is the per-vertex accept *mask* consensus consumes, and it must
    be byte-identical to the TPU verifier's mask — an RLC batch check only
    yields a single aggregate bit.
    """
    return [verify(pk, m, sig) for (pk, m, sig) in items]


def verify_precomputed(
    public_key: bytes, k: int, signature: bytes
) -> bool:
    """Verification with the SHA-512 challenge scalar k already computed.

    This is the exact host-side work split the TPU verifier uses: hashing
    (k) and decoding on host, group arithmetic on device. Used by
    differential tests to isolate the group-op path.
    """
    A = point_decompress(public_key)
    R = point_decompress(signature[:32])
    if A is None or R is None:
        return False
    s = int.from_bytes(signature[32:], "little")
    if s >= L:
        return False
    sB = scalar_mult_base(s)
    kA = scalar_mult(k % L, A)
    return point_equal(sB, point_add(R, kA))
