"""BLS12-381 — host reference implementation (fields, curves, pairing).

The reference's common coin is a hardcoded stub returning 1
(``process/process.go:390-392``); its TODO names the real design: "PKI and
a threshold signature scheme with a threshold of (f+1)-of-n"
(``process.go:388``). This module supplies the pairing-friendly curve that
scheme runs on (BASELINE.json: "256-node BLS12-381 aggregate sigs +
threshold-BLS common coin").

Pure Python ints (CPython bignums), written for auditability over speed:

- Fp / Fp2 / Fp6 / Fp12 tower (u^2 = -1, v^3 = u + 1, w^2 = v);
- E(Fp): y^2 = x^3 + 4 (G1) and the M-twist E'(Fp2):
  y^2 = x^3 + 4(u+1) (G2), Jacobian-free affine arithmetic;
- the ate pairing via a generic Miller loop over E(Fp12) (G2 points are
  untwisted through (x, y) -> (x w^-2, y w^-3)) and full final
  exponentiation — slower than a dedicated tower pipeline but easily
  checked against bilinearity tests;
- minimal-signature-size BLS: signatures in G1 (48 bytes), public keys in
  G2; hash-to-G1 by try-and-increment (internal protocol — we control
  both ends, no interop constraint with the hash-to-curve draft).

The TPU side accelerates the G1 MSM used by threshold-share aggregation
(ops/bls_msm.py); the pairing checks stay host-side, exactly as ordering
decisions do (SURVEY.md §7 hard part (b)).
"""

from __future__ import annotations

import collections
import hashlib
from typing import List, Optional, Sequence, Tuple

# --- base field / curve parameters (standard BLS12-381 constants) ----------

P = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB
R = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001
X_PARAM = -0xD201000000010000  # the BLS parameter (negative)

G1_GEN = (
    0x17F1D3A73197D7942695638C4FA9AC0FC3688C4F9774B905A14E3A3F171BAC586C55E83FF97A1AEFFB3AF00ADB22C6BB,
    0x08B3F481E3AAA0F1A09E30ED741D8AE4FCF5E095D5D00AF600DB18CB2C04B3EDD03CC744A2888AE40CAA232946C5E7E1,
)
G2_GEN = (
    (
        0x024AA2B2F08F0A91260805272DC51051C6E47AD4FA403B02B4510B647AE3D1770BAC0326A805BBEFD48056C8C121BDB8,
        0x13E02B6052719F607DACD3A088274F65596BD0D09920B61AB5DA61BBDC7F5049334CF11213945D57E5AC7D055D042B7E,
    ),
    (
        0x0CE5D527727D6E118CC9CDC6DA2E351AADFD9BAA8CBDD3A76D429A695160D12C923AC9CC3BACA289E193548608B82801,
        0x0606C4A02EA734CC32ACD2B02BC28B99CB3E287E85A763AF267492AB572E99AB3F370D275CEC1DA1AAA9075FF05F79BE,
    ),
)


def _inv_p(x: int) -> int:
    return pow(x, P - 2, P)


# --- Fp2 = Fp[u] / (u^2 + 1) ----------------------------------------------
# elements are (a, b) = a + b u


def fp2_add(x, y):
    return ((x[0] + y[0]) % P, (x[1] + y[1]) % P)


def fp2_sub(x, y):
    return ((x[0] - y[0]) % P, (x[1] - y[1]) % P)


def fp2_neg(x):
    return (-x[0] % P, -x[1] % P)


def fp2_mul(x, y):
    a, b = x
    c, d = y
    return ((a * c - b * d) % P, (a * d + b * c) % P)


def fp2_sqr(x):
    a, b = x
    return ((a + b) * (a - b) % P, 2 * a * b % P)


def fp2_scalar(x, k: int):
    return (x[0] * k % P, x[1] * k % P)


def fp2_inv(x):
    a, b = x
    norm = (a * a + b * b) % P
    ni = _inv_p(norm)
    return (a * ni % P, -b * ni % P)


FP2_ZERO = (0, 0)
FP2_ONE = (1, 0)


# --- Fp6 = Fp2[v] / (v^3 - (u+1)) -----------------------------------------
# elements are (c0, c1, c2) with ci in Fp2; XI = u + 1

XI = (1, 1)


def fp6_add(x, y):
    return tuple(fp2_add(a, b) for a, b in zip(x, y))


def fp6_sub(x, y):
    return tuple(fp2_sub(a, b) for a, b in zip(x, y))


def fp6_neg(x):
    return tuple(fp2_neg(a) for a in x)


def fp6_mul(x, y):
    a0, a1, a2 = x
    b0, b1, b2 = y
    t0 = fp2_mul(a0, b0)
    t1 = fp2_mul(a1, b1)
    t2 = fp2_mul(a2, b2)
    c0 = fp2_add(
        t0,
        fp2_mul(
            XI,
            fp2_sub(
                fp2_mul(fp2_add(a1, a2), fp2_add(b1, b2)), fp2_add(t1, t2)
            ),
        ),
    )
    c1 = fp2_add(
        fp2_sub(fp2_mul(fp2_add(a0, a1), fp2_add(b0, b1)), fp2_add(t0, t1)),
        fp2_mul(XI, t2),
    )
    c2 = fp2_add(
        fp2_sub(fp2_mul(fp2_add(a0, a2), fp2_add(b0, b2)), fp2_add(t0, t2)),
        t1,
    )
    return (c0, c1, c2)


def fp6_scalar_fp2(x, s):
    return tuple(fp2_mul(a, s) for a in x)


def fp6_mul_by_v(x):
    """v * (c0 + c1 v + c2 v^2) = XI c2 + c0 v + c1 v^2."""
    return (fp2_mul(XI, x[2]), x[0], x[1])


def fp6_inv(x):
    a0, a1, a2 = x
    t0 = fp2_sub(fp2_sqr(a0), fp2_mul(XI, fp2_mul(a1, a2)))
    t1 = fp2_sub(fp2_mul(XI, fp2_sqr(a2)), fp2_mul(a0, a1))
    t2 = fp2_sub(fp2_sqr(a1), fp2_mul(a0, a2))
    denom = fp2_add(
        fp2_mul(a0, t0),
        fp2_mul(XI, fp2_add(fp2_mul(a2, t1), fp2_mul(a1, t2))),
    )
    di = fp2_inv(denom)
    return (fp2_mul(t0, di), fp2_mul(t1, di), fp2_mul(t2, di))


FP6_ZERO = (FP2_ZERO, FP2_ZERO, FP2_ZERO)
FP6_ONE = (FP2_ONE, FP2_ZERO, FP2_ZERO)


# --- Fp12 = Fp6[w] / (w^2 - v) --------------------------------------------
# elements are (c0, c1) with ci in Fp6


def fp12_add(x, y):
    return (fp6_add(x[0], y[0]), fp6_add(x[1], y[1]))


def fp12_sub(x, y):
    return (fp6_sub(x[0], y[0]), fp6_sub(x[1], y[1]))


def fp12_mul(x, y):
    a0, a1 = x
    b0, b1 = y
    t0 = fp6_mul(a0, b0)
    t1 = fp6_mul(a1, b1)
    c0 = fp6_add(t0, fp6_mul_by_v(t1))
    c1 = fp6_sub(
        fp6_mul(fp6_add(a0, a1), fp6_add(b0, b1)), fp6_add(t0, t1)
    )
    return (c0, c1)


def fp12_sqr(x):
    return fp12_mul(x, x)


def fp12_inv(x):
    a0, a1 = x
    denom = fp6_sub(fp6_mul(a0, a0), fp6_mul_by_v(fp6_mul(a1, a1)))
    di = fp6_inv(denom)
    return (fp6_mul(a0, di), fp6_neg(fp6_mul(a1, di)))


def fp12_conj(x):
    """Conjugation c0 - c1 w == x^(p^6) — the cheap inverse for elements
    in the cyclotomic subgroup (|x| = 1 after the easy exponentiation)."""
    return (x[0], fp6_neg(x[1]))


def fp12_pow(x, e: int):
    if e < 0:
        x = fp12_inv(x)
        e = -e
    acc = FP12_ONE
    while e:
        if e & 1:
            acc = fp12_mul(acc, x)
        x = fp12_sqr(x)
        e >>= 1
    return acc


FP12_ZERO = (FP6_ZERO, FP6_ZERO)
FP12_ONE = (FP6_ONE, FP6_ZERO)

# w and its inverse powers, used by the untwist map.
W = (FP6_ZERO, FP6_ONE)  # w
W2 = (  # w^2 = v
    (FP2_ZERO, FP2_ONE, FP2_ZERO),
    FP6_ZERO,
)
W2_INV = fp12_inv(W2)
W3_INV = fp12_inv(fp12_mul(W2, W))


def fp12_from_fp2(x) -> tuple:
    return (((x[0], x[1]), FP2_ZERO, FP2_ZERO), FP6_ZERO)


# --- affine curve arithmetic over a generic field --------------------------
# Points are None (infinity) or (x, y) with coordinates in the field; the
# field is abstracted by the ops tuple (add, sub, mul, inv, neg, scalar).


class _Ops:
    __slots__ = ("add", "sub", "mul", "inv", "neg", "small")

    def __init__(self, add, sub, mul, inv, neg, small):
        self.add, self.sub, self.mul, self.inv, self.neg, self.small = (
            add,
            sub,
            mul,
            inv,
            neg,
            small,
        )


_FP_OPS = _Ops(
    lambda a, b: (a + b) % P,
    lambda a, b: (a - b) % P,
    lambda a, b: a * b % P,
    _inv_p,
    lambda a: -a % P,
    lambda a, k: a * k % P,
)
_FP2_OPS = _Ops(fp2_add, fp2_sub, fp2_mul, fp2_inv, fp2_neg, fp2_scalar)


def _ec_add(ops: _Ops, p1, p2):
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2:
        if y1 == ops.neg(y2) and y1 != y2:
            return None
        if y1 == y2:
            return _ec_double(ops, p1)
        return None
    lam = ops.mul(ops.sub(y2, y1), ops.inv(ops.sub(x2, x1)))
    x3 = ops.sub(ops.sub(ops.mul(lam, lam), x1), x2)
    y3 = ops.sub(ops.mul(lam, ops.sub(x1, x3)), y1)
    return (x3, y3)


def _ec_double(ops: _Ops, p1):
    if p1 is None:
        return None
    x1, y1 = p1
    three_x2 = ops.small(ops.mul(x1, x1), 3)
    lam = ops.mul(three_x2, ops.inv(ops.small(y1, 2)))
    x3 = ops.sub(ops.mul(lam, lam), ops.small(x1, 2))
    y3 = ops.sub(ops.mul(lam, ops.sub(x1, x3)), y1)
    return (x3, y3)


def _ec_mul(ops: _Ops, k: int, p1):
    """[k]P via the Jacobian ladder (one inversion total — the affine
    double-and-add paid a ~0.2 ms modular inverse per step, which made
    255-bit muls ~0.14 s each and committee-scale keygen/signing minutes
    of host time)."""
    if k % R == 0 or p1 is None:
        return None
    if ops is _FP_OPS:
        zero, one = 0, 1
    else:
        zero, one = FP2_ZERO, FP2_ONE
    return _ec_msm(ops, zero, one, [k], [p1])


# public G1/G2 ops

# Fixed-base comb for the generators (host twin of the Ed25519 signing
# comb, crypto/ed25519.scalar_mult_base): [k]GEN decomposes into 4-bit
# digits over precomputed [16^w]GEN powers, and the Straus MSM then costs
# 4 doublings + ~2 mixed additions per nonzero digit instead of the
# 255-doubling ladder. Committee keygen is n fixed-base G2 muls (~9 ms
# each on the ladder — 2.4 s of the sim256 box at n=256); the tables
# build lazily (~50 ms per curve, affine doubles).
_GEN_POWS: dict = {}


def _gen_pows(curve: str):
    if curve not in _GEN_POWS:
        ops, gen = (
            (_FP_OPS, G1_GEN) if curve == "g1" else (_FP2_OPS, G2_GEN)
        )
        pows, g = [], gen
        for w in range(64):
            pows.append(g)
            if w < 63:  # the last entry needs no further doublings
                for _ in range(4):
                    g = _ec_double(ops, g)
        _GEN_POWS[curve] = pows
    return _GEN_POWS[curve]


def _gen_mul(curve: str, k: int):
    ops, zero, one = (
        (_FP_OPS, 0, 1) if curve == "g1" else (_FP2_OPS, FP2_ZERO, FP2_ONE)
    )
    k %= R
    digits = [(k >> (4 * w)) & 0xF for w in range(64)]
    return _ec_msm(ops, zero, one, digits, _gen_pows(curve))


def g1_add(p1, p2):
    return _ec_add(_FP_OPS, p1, p2)


def g1_double(p1):
    return _ec_double(_FP_OPS, p1)


def g1_mul(k: int, p1=G1_GEN):
    if p1 is G1_GEN:
        return _gen_mul("g1", k)
    return _ec_mul(_FP_OPS, k, p1)


def g1_neg(p1):
    return None if p1 is None else (p1[0], -p1[1] % P)


def g2_add(p1, p2):
    return _ec_add(_FP2_OPS, p1, p2)


def g2_mul(k: int, p1=G2_GEN):
    if p1 is G2_GEN:
        return _gen_mul("g2", k)
    return _ec_mul(_FP2_OPS, k, p1)


def g2_neg(p1):
    return None if p1 is None else (p1[0], fp2_neg(p1[1]))


# --- Jacobian multi-scalar multiplication ---------------------------------
#
# Host-side MSM over either curve. The affine _ec_add pays one field
# inversion (a ~0.2 ms pow) per addition; Jacobian coordinates defer the
# single inversion to the very end, which is what makes the coin's batched
# share verification (threshold.batch_verify_shares) and the host
# aggregate() fallback tractable at committee scale (round-2 VERDICT
# weak #4). Formulas: EFD dbl-2009-l / madd-2007-bl (a = 0 curves; both
# E(Fp) and the twist E'(Fp2) have a = 0). Identity is Z == 0.


def _jac_double(ops: _Ops, p):
    X1, Y1, Z1 = p
    A = ops.mul(X1, X1)
    B = ops.mul(Y1, Y1)
    C = ops.mul(B, B)
    t = ops.add(X1, B)
    D = ops.small(ops.sub(ops.sub(ops.mul(t, t), A), C), 2)
    E = ops.small(A, 3)
    X3 = ops.sub(ops.mul(E, E), ops.small(D, 2))
    Y3 = ops.sub(ops.mul(E, ops.sub(D, X3)), ops.small(C, 8))
    Z3 = ops.small(ops.mul(Y1, Z1), 2)
    return (X3, Y3, Z3)


def _jac_madd(ops: _Ops, p, q, zero):
    """Mixed addition: Jacobian p + affine q. Neither may be the identity
    (the caller tracks an identity accumulator as None). Returns None for
    p == -q."""
    X1, Y1, Z1 = p
    x2, y2 = q
    Z1Z1 = ops.mul(Z1, Z1)
    U2 = ops.mul(x2, Z1Z1)
    S2 = ops.mul(ops.mul(y2, Z1), Z1Z1)
    H = ops.sub(U2, X1)
    r = ops.small(ops.sub(S2, Y1), 2)
    if H == zero:
        if ops.sub(S2, Y1) == zero:
            return _jac_double(ops, p)
        return None  # p == -q: identity (caller substitutes)
    HH = ops.mul(H, H)
    I = ops.small(HH, 4)
    J = ops.mul(H, I)
    V = ops.mul(X1, I)
    X3 = ops.sub(ops.sub(ops.mul(r, r), J), ops.small(V, 2))
    Y3 = ops.sub(ops.mul(r, ops.sub(V, X3)), ops.small(ops.mul(Y1, J), 2))
    t = ops.add(Z1, H)
    Z3 = ops.sub(ops.sub(ops.mul(t, t), Z1Z1), HH)
    return (X3, Y3, Z3)


def _ec_msm(ops: _Ops, zero, one, scalars, points):
    """sum_i [k_i] P_i — Straus shared-doubling over Jacobian coords.

    Points are affine tuples or None (identity). One inversion total, at
    the final Jacobian->affine conversion. Cost: max_bits doublings +
    (popcount of all scalars) mixed additions.
    """
    pairs = [
        (k % R, p)
        for k, p in zip(scalars, points)
        if p is not None and k % R != 0
    ]
    if not pairs:
        return None
    nbits = max(k.bit_length() for k, _ in pairs)
    acc = None  # Jacobian identity
    for bit in range(nbits - 1, -1, -1):
        if acc is not None:
            acc = _jac_double(ops, acc)
        for k, p in pairs:
            if (k >> bit) & 1:
                if acc is None:
                    acc = (p[0], p[1], one)
                else:
                    acc = _jac_madd(ops, acc, p, zero)
    return _jac_to_affine(ops, acc, zero)


def _jac_to_affine(ops: _Ops, acc, zero):
    if acc is None or acc[2] == zero:
        return None
    zi = ops.inv(acc[2])
    zi2 = ops.mul(zi, zi)
    return (ops.mul(acc[0], zi2), ops.mul(ops.mul(acc[1], zi2), zi))


def g1_msm(scalars: Sequence[int], points) :
    """Host G1 MSM (Jacobian Straus) — fallback when no device MSM is
    plugged in; also the fast path for small (RLC) coefficients."""
    return _ec_msm(_FP_OPS, 0, 1, scalars, points)


def g2_msm(scalars: Sequence[int], points):
    """Host G2 MSM (Jacobian Straus over Fp2)."""
    return _ec_msm(_FP2_OPS, FP2_ZERO, FP2_ONE, scalars, points)


def g1_on_curve(p1) -> bool:
    if p1 is None:
        return True
    x, y = p1
    return (y * y - x * x * x - 4) % P == 0


def g2_on_curve(p1) -> bool:
    if p1 is None:
        return True
    x, y = p1
    rhs = fp2_add(fp2_mul(fp2_mul(x, x), x), fp2_scalar(XI, 4))
    return fp2_sub(fp2_mul(y, y), rhs) == (0, 0)


# --- pairing ---------------------------------------------------------------


def _untwist(q):
    """E'(Fp2) -> E(Fp12): (x, y) -> (x w^-2, y w^-3)."""
    if q is None:
        return None
    x, y = q
    return (
        fp12_mul(fp12_from_fp2(x), W2_INV),
        fp12_mul(fp12_from_fp2(y), W3_INV),
    )


def _line(ops: _Ops, t, s, p):
    """Evaluate the line through t and s (or the tangent at t when t == s)
    at the G1 point p (embedded in Fp12)."""
    xp, yp = p
    xt, yt = t
    if t == s:
        num = ops.small(ops.mul(xt, xt), 3)
        den = ops.small(yt, 2)
    else:
        xs, ys = s
        if xt == xs:
            # vertical line x - xt
            return ops.sub(xp, xt)
        num = ops.sub(ys, yt)
        den = ops.sub(xs, xt)
    lam = ops.mul(num, ops.inv(den))
    return ops.sub(ops.sub(yp, yt), ops.mul(lam, ops.sub(xp, xt)))


def miller_loop(q, p) -> tuple:
    """f_{|x|, Q}(P) over E(Fp12), generic double-and-add Miller loop."""
    if p is None or q is None:
        return FP12_ONE
    ops = _Ops(
        fp12_add,
        fp12_sub,
        fp12_mul,
        fp12_inv,
        lambda v: fp12_sub(FP12_ZERO, v),
        lambda v, k: fp12_mul(v, fp12_from_small(k)),
    )
    qe = _untwist(q)
    pe = (fp12_from_fp(p[0]), fp12_from_fp(p[1]))
    t = qe
    f = FP12_ONE
    n = abs(X_PARAM)
    for bit in bin(n)[3:]:
        f = fp12_mul(fp12_sqr(f), _line(ops, t, t, pe))
        t = _ec_double(ops, t)
        if bit == "1":
            f = fp12_mul(f, _line(ops, t, qe, pe))
            t = _ec_add(ops, t, qe)
    if X_PARAM < 0:
        f = fp12_conj(f)  # f^(p^6) == f^-1 up to the final exponentiation
    return f


def fp12_from_fp(a: int) -> tuple:
    return (((a % P, 0), FP2_ZERO, FP2_ZERO), FP6_ZERO)


def fp12_from_small(k: int) -> tuple:
    return fp12_from_fp(k)


# Frobenius: u^2 = -1 so conj is the Fp2 Frobenius; v^p = gamma1 * v and
# w^p = gamma_w * w with the constants below (p == 1 mod 6).
_GAMMA1 = None
_GAMMAW = None


def _frob_consts():
    global _GAMMA1, _GAMMAW
    if _GAMMA1 is None:
        # XI^((p-1)/3) and XI^((p-1)/6) in Fp2
        def fp2_pow(x, e):
            acc = FP2_ONE
            while e:
                if e & 1:
                    acc = fp2_mul(acc, x)
                x = fp2_sqr(x)
                e >>= 1
            return acc

        _GAMMA1 = fp2_pow(XI, (P - 1) // 3)
        _GAMMAW = fp2_pow(XI, (P - 1) // 6)
    return _GAMMA1, _GAMMAW


def fp2_conj(x):
    return (x[0], -x[1] % P)


def fp12_frobenius(x):
    """x^p via coefficient-wise conjugation and the twist constants."""
    g1c, gw = _frob_consts()
    g2c = fp2_sqr(g1c)
    (a0, a1, a2), (b0, b1, b2) = x
    c0 = (fp2_conj(a0), fp2_mul(fp2_conj(a1), g1c), fp2_mul(fp2_conj(a2), g2c))
    d0 = fp2_mul(fp2_conj(b0), gw)
    d1 = fp2_mul(fp2_mul(fp2_conj(b1), g1c), gw)
    d2 = fp2_mul(fp2_mul(fp2_conj(b2), g2c), gw)
    return (c0, (d0, d1, d2))


_HARD_EXP = (P**4 - P**2 + 1) // R


def final_exponentiation(f) -> tuple:
    """f^((p^12-1)/r): easy part via conjugation + Frobenius, hard part by
    direct exponentiation with (p^4 - p^2 + 1)/r."""
    # easy: f^((p^6 - 1)(p^2 + 1))
    f = fp12_mul(fp12_conj(f), fp12_inv(f))       # f^(p^6 - 1)
    f = fp12_mul(fp12_frobenius(fp12_frobenius(f)), f)  # * f^(p^2)
    return fp12_pow(f, _HARD_EXP)


def pairing(p, q) -> tuple:
    """e(P, Q) for P in G1, Q in G2 — ate Miller loop + final exp."""
    return final_exponentiation(miller_loop(q, p))


def pairing_product(pairs: Sequence[Tuple[object, object]]) -> tuple:
    """prod e(Pi, Qi) as a GT element (shared final exponentiation).

    The GT *value* (not just the ==1 bit) is what the coin's batched
    share verification uses to localize a single bad share: the defect
    ratios of two coefficient vectors pin down the bad index
    (threshold.batch_verify_shares)."""
    f = FP12_ONE
    for p, q in pairs:
        f = fp12_mul(f, miller_loop(q, p))
    return final_exponentiation(f)


def pairing_check(pairs: Sequence[Tuple[object, object]]) -> bool:
    """prod e(Pi, Qi) == 1 — the multi-pairing product check. The final
    exponentiation is shared across the product (the big win of batching
    pairing checks)."""
    return pairing_product(pairs) == FP12_ONE


# --- precomputed multi-pairing (ISSUE 9 certificate fast path) -------------
#
# The generic miller_loop pays one fp12 inversion PER STEP in _line — the
# dominant cost of a host pairing. For the certificate path the G2 side is
# always a long-lived public key (or -G2_GEN), so the line coefficients of
# the fixed double-and-add schedule over |x| can be computed once per key
# and replayed: evaluation per pair per step is then one fp12-by-Fp scalar
# multiply (12 base-field mults) plus adds — no inversions. All pairs share
# one accumulator (one fp12_sqr per bit regardless of pair count) and one
# final exponentiation, so the marginal cost of an extra pair is ~20x below
# a fresh miller_loop. Verdicts are bit-identical to pairing_check (the
# algebra is the same product, reassociated) — tests pin this.

#: the fixed Miller schedule: bits of |x| below the leading one
_X_BITS = bin(abs(X_PARAM))[3:]

#: q -> line coefficients, one entry per consumed schedule slot
_G2_PRECOMP: dict = {}
_G2_PRECOMP_MAX = 1024


def _miller_ops() -> _Ops:
    return _Ops(
        fp12_add,
        fp12_sub,
        fp12_mul,
        fp12_inv,
        lambda v: fp12_sub(FP12_ZERO, v),
        lambda v, k: fp12_mul(v, fp12_from_small(k)),
    )


def _line_coeffs(ops: _Ops, t, s):
    """(lam, lam*xt - yt) of the line through t and s — everything the
    per-point evaluation needs; (None, xt) for a vertical line."""
    xt, yt = t
    if t == s:
        num = ops.small(ops.mul(xt, xt), 3)
        den = ops.small(yt, 2)
    else:
        xs, ys = s
        if xt == xs:
            return (None, xt)
        num = ops.sub(ys, yt)
        den = ops.sub(xs, xt)
    lam = ops.mul(num, ops.inv(den))
    return (lam, ops.sub(ops.mul(lam, xt), yt))


def g2_precompute(q) -> list:
    """Line coefficients of the full Miller schedule for G2 point ``q``,
    cached by point. One-time cost ~ one miller_loop; afterwards every
    pairing against ``q`` evaluates inversion-free."""
    hit = _G2_PRECOMP.get(q)
    if hit is not None:
        return hit
    ops = _miller_ops()
    qe = _untwist(q)
    t = qe
    coeffs = []
    for bit in _X_BITS:
        coeffs.append(_line_coeffs(ops, t, t))
        t = _ec_double(ops, t)
        if bit == "1":
            coeffs.append(_line_coeffs(ops, t, qe))
            t = _ec_add(ops, t, qe)
    if len(_G2_PRECOMP) >= _G2_PRECOMP_MAX:
        _G2_PRECOMP.clear()
    _G2_PRECOMP[q] = coeffs
    return coeffs


def _fp12_scale_fp(x, s: int):
    """x * s for an Fp scalar s — 12 base-field mults, no tower mults."""
    (a0, a1, a2), (b0, b1, b2) = x
    return (
        (
            (a0[0] * s % P, a0[1] * s % P),
            (a1[0] * s % P, a1[1] * s % P),
            (a2[0] * s % P, a2[1] * s % P),
        ),
        (
            (b0[0] * s % P, b0[1] * s % P),
            (b1[0] * s % P, b1[1] * s % P),
            (b2[0] * s % P, b2[1] * s % P),
        ),
    )


def _line_eval(lam, c, xp: int, yp: int):
    """The precomputed line at affine G1 point (xp, yp):
    yp + (lam*xt - yt) - lam*xp, or xp - xt for a vertical line."""
    if lam is None:
        # c is xt: ell = emb(xp) - xt
        (a0, a1, a2), b = fp12_sub(FP12_ZERO, c)
        return (((((a0[0] + xp) % P), a0[1]), a1, a2), b)
    (a0, a1, a2), b = fp12_sub(c, _fp12_scale_fp(lam, xp))
    return (((((a0[0] + yp) % P), a0[1]), a1, a2), b)


def multi_pairing_check(pairs: Sequence[Tuple[object, object]]) -> bool:
    """pairing_check via per-G2-key precomputed lines, a shared
    accumulator (one squaring per schedule bit for the whole product)
    and one shared final exponentiation. Bit-identical verdicts to
    :func:`pairing_check`; ~20x cheaper per marginal pair on host."""
    evs = []
    for p, q in pairs:
        if p is None or q is None:
            continue  # identity factor contributes 1, as in miller_loop
        evs.append((p[0] % P, p[1] % P, g2_precompute(q)))
    if not evs:
        return True
    f = FP12_ONE
    idx = 0
    for bit in _X_BITS:
        f = fp12_sqr(f)
        for xp, yp, coeffs in evs:
            lam, c = coeffs[idx]
            f = fp12_mul(f, _line_eval(lam, c, xp, yp))
        idx += 1
        if bit == "1":
            for xp, yp, coeffs in evs:
                lam, c = coeffs[idx]
                f = fp12_mul(f, _line_eval(lam, c, xp, yp))
            idx += 1
    if X_PARAM < 0:
        f = fp12_conj(f)
    return final_exponentiation(f) == FP12_ONE


def g1_sum(points) -> object:
    """Affine sum of G1 points (None = identity) — the host fallback for
    certificate signature aggregation (an all-ones MSM)."""
    acc = None
    for p in points:
        acc = g1_add(acc, p)
    return acc


# --- serialization (internal format: affine, uncompressed-ish) -------------


def g1_compress(p1) -> bytes:
    """48-byte x with 2 flag bits (internal format, zcash-style layout)."""
    if p1 is None:
        return bytes([0xC0] + [0] * 47)
    x, y = p1
    flag = 0x80 | (0x20 if y > (P - 1) // 2 else 0)
    data = bytearray(x.to_bytes(48, "big"))
    data[0] |= flag
    return bytes(data)


def g1_decompress(data: bytes):
    """Inverse of g1_compress. Returns None on malformed input (callers
    treat None as a rejected share)."""
    if len(data) != 48 or not data[0] & 0x80:
        return None
    if data[0] & 0x40:
        # compressed infinity: never a valid signature (sk == 0), reject
        return None
    big_y = bool(data[0] & 0x20)
    x = int.from_bytes(bytes([data[0] & 0x1F]) + data[1:], "big")
    if x >= P:
        return None
    y2 = (x * x * x + 4) % P
    y = pow(y2, (P + 1) // 4, P)  # p == 3 (mod 4)
    if y * y % P != y2:
        return None
    if (y > (P - 1) // 2) != big_y:
        y = P - y
    return (x, y)


#: G1 cofactor-clearing multiplier h1 = (x_param - 1)^2 // 3
_H1_COFACTOR = (X_PARAM - 1) ** 2 // 3

# hash_to_g1 memo — a hand-rolled LRU (was functools.lru_cache) so the
# batched signer can consult it without recomputing and so cache behavior
# is observable: hit/miss totals surface in the metrics snapshot
# (hash_g1_cache_hits / hash_g1_cache_misses, ISSUE 12 satellite).
_H2G1_CACHE: "collections.OrderedDict[tuple, tuple]" = collections.OrderedDict()
_H2G1_CACHE_MAX = 256
_H2G1_STATS = {"hits": 0, "misses": 0}


def hash_g1_cache_stats() -> dict:
    """Process-global hash_to_g1 cache counters (cumulative)."""
    return dict(_H2G1_STATS)


def hash_g1_cache_clear() -> None:
    _H2G1_CACHE.clear()
    _H2G1_STATS["hits"] = 0
    _H2G1_STATS["misses"] = 0


def _h2g1_lookup(msg: bytes, domain: bytes):
    hit = _H2G1_CACHE.get((msg, domain))
    if hit is not None:
        _H2G1_CACHE.move_to_end((msg, domain))
        _H2G1_STATS["hits"] += 1
        return hit
    _H2G1_STATS["misses"] += 1
    return None


def _h2g1_store(msg: bytes, domain: bytes, pt: tuple) -> None:
    if len(_H2G1_CACHE) >= _H2G1_CACHE_MAX:
        _H2G1_CACHE.popitem(last=False)
    _H2G1_CACHE[(msg, domain)] = pt


def _hash_candidate_x(msg: bytes, domain: bytes, ctr: int) -> int:
    """The try-and-increment field candidate H(domain || ctr || msg) mod p
    — the per-row host half of the split map (SHA stays on host, the
    square-root/ladder half batches on a backend)."""
    h = hashlib.sha512(domain + ctr.to_bytes(4, "little") + msg).digest()
    return int.from_bytes(h, "big") % P


def hash_to_g1(msg: bytes, domain: bytes = b"dagrider-coin-v1") -> tuple:
    """Try-and-increment hash onto the r-torsion of E(Fp).

    Internal-protocol map (deterministic, constant participants): take
    x = H(domain || ctr || msg) mod p until x^3 + 4 is square, pick the
    smaller root for determinism, then clear the cofactor by multiplying
    with h1 = (x-1)^2 / 3 ... here simply multiply by the G1 cofactor.

    LRU-cached: a pure ~2.3 ms map, and every share signer / verifier of
    a wave hashes the SAME wave tag (n redundant computations per wave
    in a committee; bounded cache — tags are per-wave, 256 covers any
    live window many times over).
    """
    hit = _h2g1_lookup(msg, domain)
    if hit is not None:
        return hit
    ctr = 0
    while True:
        x = _hash_candidate_x(msg, domain, ctr)
        y2 = (x * x * x + 4) % P
        y = pow(y2, (P + 1) // 4, P)
        if y * y % P == y2:
            y = min(y, P - y)
            pt = (x, y)
            cleared = _ec_mul_raw(_FP_OPS, _H1_COFACTOR, pt)
            if cleared is not None:
                _h2g1_store(msg, domain, cleared)
                return cleared
        ctr += 1


def _ec_mul_raw(ops: _Ops, k: int, p1):
    """Scalar mult WITHOUT reducing k mod R (cofactor clearing operates on
    points outside the r-torsion, where mod-R reduction is invalid).
    Jacobian ladder — one inversion total, like :func:`_ec_mul`; the
    Jacobian formulas hold for any point on the curve, independent of its
    order."""
    if k < 0:
        k = -k
        p1 = (p1[0], ops.neg(p1[1]))
    if k == 0 or p1 is None:
        return None
    if ops is _FP_OPS:
        zero, one = 0, 1
    else:
        zero, one = FP2_ZERO, FP2_ONE
    acc = None
    for bit in range(k.bit_length() - 1, -1, -1):
        if acc is not None:
            acc = _jac_double(ops, acc)
            if acc is not None and acc[2] == zero:
                # Doubling a point of even order lands on the Jacobian
                # identity (Z == 0); collapse it to the None convention
                # before a mixed addition could read the garbage X/Y.
                # (Both BLS12-381 cofactors are odd, so this is a
                # safety rail for arbitrary-point callers, not a path
                # current inputs reach.)
                acc = None
        if (k >> bit) & 1:
            if acc is None:
                acc = (p1[0], p1[1], one)
            else:
                acc = _jac_madd(ops, acc, p1, zero)
    return _jac_to_affine(ops, acc, zero)


def g2_serialize(p1) -> bytes:
    """192-byte uncompressed affine G2 point (internal key-file format:
    x.c1 || x.c0 || y.c1 || y.c0, big-endian 48-byte Fp each; identity is
    all-zero). Uncompressed by choice — decompression would need an Fp2
    square root, and public keys live in our own key files, not on the
    wire."""
    if p1 is None:
        return bytes(192)
    (x0, x1), (y0, y1) = p1
    return (
        x1.to_bytes(48, "big")
        + x0.to_bytes(48, "big")
        + y1.to_bytes(48, "big")
        + y0.to_bytes(48, "big")
    )


def g2_deserialize(data: bytes):
    """Inverse of g2_serialize; validates field range and curve membership.
    Returns None for the identity encoding; raises ValueError on junk."""
    if len(data) != 192:
        raise ValueError("G2 point must be 192 bytes")
    if data == bytes(192):
        return None
    vals = [int.from_bytes(data[i * 48 : (i + 1) * 48], "big") for i in range(4)]
    if any(v >= P for v in vals):
        raise ValueError("G2 coordinate out of field range")
    x1, x0, y1, y0 = vals
    pt = ((x0, x1), (y0, y1))
    if not g2_on_curve(pt):
        raise ValueError("point not on the G2 curve")
    # r-order subgroup check: the twist's cofactor is huge, and a
    # non-subgroup "public key" would silently corrupt pairing-based
    # share verification (small-subgroup structure) instead of failing
    # loudly here. [r]P must be the identity. (_ec_mul reduces mod r, so
    # the raw ladder is required.)
    if _ec_mul_raw(_FP2_OPS, R, pt) is not None:
        raise ValueError("G2 point not in the r-order subgroup")
    return pt


# --- BLS signatures (minimal-signature-size: sig in G1, pk in G2) ----------


def sign(sk: int, msg: bytes) -> bytes:
    """sigma = sk * H(msg) in G1, compressed to 48 bytes."""
    return g1_compress(g1_mul(sk, hash_to_g1(msg)))


def _sign_many_via(
    pow_p_batch,
    ladder_batch,
    sks: Sequence[int],
    msgs: Sequence[bytes],
    domain: bytes,
) -> List[bytes]:
    """Round-batched signing over two backend primitives.

    The merged-scalar trick: the oracle computes [sk % R]([h1]candidate)
    in two stages (cofactor clearing inside hash_to_g1, then the signing
    mul); one ladder over the merged scalar (sk % R) * h1 gives the same
    group element in a single pass — [ab]Q == [a]([b]Q) in any abelian
    group, and both ladders are exact mod-p arithmetic. [h1]candidate is
    the identity iff the merged result is (sk % R is nonzero and [h1]Q
    has order r or 1), which is exactly the case where the oracle retries
    the next hash candidate — those rows (and any backend-flagged rows)
    fall back to the sequential host `sign`, keeping byte-identity on
    every input.
    """
    out: List[Optional[bytes]] = [None] * len(msgs)
    scalars: List[int] = []
    points: List[Tuple[int, int]] = []
    idxs: List[int] = []
    pend: List[list] = []  # [out_index, sk_mod_r, msg, ctr]
    for i, (sk, msg) in enumerate(zip(sks, msgs)):
        skr = sk % R
        if skr == 0:
            out[i] = g1_compress(None)
            continue
        hit = _h2g1_lookup(msg, domain)
        if hit is not None:
            scalars.append(skr)
            points.append(hit)
            idxs.append(i)
        else:
            pend.append([i, skr, msg, 0])
    # try-and-increment with the square-root power map batched: every
    # unresolved row advances its counter in lockstep (~2 rounds expected;
    # each candidate is square with probability 1/2)
    while pend:
        xs = [_hash_candidate_x(m, domain, ctr) for (_, _, m, ctr) in pend]
        y2s = [(x * x * x + 4) % P for x in xs]
        ys = pow_p_batch(y2s, (P + 1) // 4)
        nxt = []
        for row, x, y2, y in zip(pend, xs, y2s, ys):
            if y * y % P == y2:
                y = min(y, P - y)
                scalars.append(row[1] * _H1_COFACTOR)
                points.append((x, y))
                idxs.append(row[0])
            else:
                row[3] += 1
                nxt.append(row)
        pend = nxt
    if scalars:
        results, fallback = ladder_batch(scalars, points)
    else:
        results, fallback = [], []
    for i, res, fb in zip(idxs, results, fallback):
        if fb or res is None:
            out[i] = g1_compress(g1_mul(sks[i], hash_to_g1(msgs[i], domain)))
        else:
            out[i] = g1_compress(res)
    return out  # type: ignore[return-value]


def sign_many(
    sks: Sequence[int],
    msgs: Sequence[bytes],
    domain: bytes = b"dagrider-coin-v1",
    backend: Optional[str] = None,
) -> List[bytes]:
    """Batched `sign` — byte-for-byte [sign(sk, m) for sk, m in zip(...)].

    Backend (explicit arg beats the DAGRIDER_CERT_SIGN knob):

    - ``host``: the sequential oracle (default);
    - ``native``: cffi C Montgomery kernels (ops/native381.py) — the
      single-core fast lane (falls back to host when no toolchain);
    - ``device``: the field381 limb-kernel lane (ops/bls_g1.py) — the
      real-chip story, bit-identical everywhere.
    """
    sks = list(sks)
    msgs = list(msgs)
    if len(sks) != len(msgs):
        raise ValueError("sign_many: sks and msgs length mismatch")
    if backend is None:
        from dag_rider_tpu import config

        backend = config.env_choice("DAGRIDER_CERT_SIGN")
    if backend == "native" and msgs:
        from dag_rider_tpu.ops import native381

        if native381.available():
            return _sign_many_via(
                native381.pow_p_batch,
                native381.g1_ladder_batch,
                sks,
                msgs,
                domain,
            )
        backend = "host"
    if backend == "device" and msgs:
        from dag_rider_tpu.ops import bls_g1

        return _sign_many_via(
            bls_g1.pow_p_batch, bls_g1.g1_ladder_batch, sks, msgs, domain
        )
    return [
        g1_compress(g1_mul(sk, hash_to_g1(m, domain)))
        for sk, m in zip(sks, msgs)
    ]


def pk_of(sk: int):
    return g2_mul(sk, G2_GEN)


def verify(pk_g2, msg: bytes, sig: bytes) -> bool:
    """e(sigma, g2) == e(H(msg), pk)  <=>  e(sigma, -g2) e(H(m), pk) == 1."""
    s = g1_decompress(sig)
    if s is None:
        return False
    return pairing_check(
        [(s, g2_neg(G2_GEN)), (hash_to_g1(msg), pk_g2)]
    )
