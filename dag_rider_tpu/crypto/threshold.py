"""(f+1)-of-n threshold-BLS — the real common coin.

Exactly the design the reference's TODO names ("PKI and a threshold
signature scheme with a threshold of (f+1)-of-n",
``process/process.go:388``), built on :mod:`dag_rider_tpu.crypto.bls12381`:

- a trusted dealer (or DKG, out of scope) Shamir-shares a group secret
  over Z_r; process i holds share sk_i = poly(i+1);
- for wave w, each process signs the wave tag with its share and
  piggybacks the 48-byte share signature on its round(w,4) vertex;
- any f+1 valid shares Lagrange-interpolate (in the exponent — a G1
  multi-scalar multiplication, the TPU-acceleration target of
  BASELINE.json config #5) to the unique group signature sigma_w;
- leader(w) = H(sigma_w) mod n. Agreement: sigma_w is unique regardless
  of which f+1 shares combined. Unpredictability: fewer than f+1 shares
  reveal nothing (Shamir). Fairness: H(sigma_w) is uniform.
"""

from __future__ import annotations

import hashlib
from typing import Dict, List, Optional, Sequence, Tuple

from dag_rider_tpu.crypto import bls12381 as bls

_COIN_DOMAIN = b"dagrider-threshold-coin-v1|"


def wave_tag(wave: int) -> bytes:
    return _COIN_DOMAIN + wave.to_bytes(8, "little")


class ThresholdKeys:
    """Dealer-generated key material for one committee.

    share_sks[i] is private to process i; group_pk and share_pks are the
    public PKI every process (and any external verifier) holds.
    """

    def __init__(
        self,
        threshold: int,
        group_pk,
        share_pks: Sequence,
        share_sks: Sequence[int],
    ):
        self.threshold = threshold
        self.group_pk = group_pk
        self.share_pks = tuple(share_pks)
        self.share_sks = tuple(share_sks)

    @staticmethod
    def generate(
        n: int, threshold: int, seed: bytes = b"dagrider-coin-dealer"
    ) -> "ThresholdKeys":
        """Deterministic dealer (seeded — tests / simulations only; a real
        deployment runs a DKG so nobody ever holds the group secret)."""
        if not 1 <= threshold <= n:
            raise ValueError("need 1 <= threshold <= n")
        coeffs = []
        for j in range(threshold):
            h = hashlib.sha512(seed + b"|coeff|" + str(j).encode()).digest()
            coeffs.append(int.from_bytes(h, "little") % bls.R)
        def poly(x: int) -> int:
            acc = 0
            for c in reversed(coeffs):
                acc = (acc * x + c) % bls.R
            return acc

        share_sks = [poly(i + 1) for i in range(n)]
        share_pks = [bls.pk_of(sk) for sk in share_sks]
        return ThresholdKeys(
            threshold, bls.pk_of(coeffs[0]), share_pks, share_sks
        )


def sign_share(share_sk: int, wave: int) -> bytes:
    """Process-local share signature for wave w (48 bytes)."""
    return bls.sign(share_sk, wave_tag(wave))


def verify_share(share_pk, wave: int, share: bytes) -> bool:
    """Pairing check of one share against that process's share pk."""
    return bls.verify(share_pk, wave_tag(wave), share)


def lagrange_at_zero(indices: Sequence[int]) -> List[int]:
    """Coefficients lambda_i for interpolation at x=0 over Z_r; indices
    are the Shamir x-coordinates (process index + 1)."""
    lams = []
    for i in indices:
        num, den = 1, 1
        for j in indices:
            if j == i:
                continue
            num = num * j % bls.R
            den = den * (j - i) % bls.R
        lams.append(num * pow(den, bls.R - 2, bls.R) % bls.R)
    return lams


def aggregate(
    shares: Dict[int, bytes], threshold: int, *, msm=None
) -> Optional[bytes]:
    """Combine >= threshold shares {source -> 48B sig} into the group
    signature. Returns None if fewer than threshold decode.

    The combination sigma = sum_i lambda_i * sigma_i is a G1 MSM; `msm`
    may override the backend (host double-and-add by default, the TPU
    kernel via ops.bls_msm when supplied).
    """
    decoded: List[Tuple[int, tuple]] = []
    for src in sorted(shares):
        pt = bls.g1_decompress(shares[src])
        if pt is not None:
            decoded.append((src, pt))
        if len(decoded) == threshold:
            break
    if len(decoded) < threshold:
        return None
    xs = [src + 1 for src, _ in decoded]
    lams = lagrange_at_zero(xs)
    points = [pt for _, pt in decoded]
    if msm is not None:
        sigma = msm(lams, points)
    else:
        sigma = bls.g1_msm(lams, points)
    return bls.g1_compress(sigma)


_RLC_DOMAIN = b"dagrider-coin-rlc-v1|"
_RLC_BITS = 64  # soundness 2^-64 per adversarial attempt


def _rlc_coeffs(wave: int, items: Sequence[Tuple[int, bytes]]) -> List[int]:
    """Fiat-Shamir 64-bit batch coefficients, bound to the whole share set
    (so no share's coefficient is predictable before all shares are fixed
    — an adversary cannot craft cancelling errors)."""
    h = hashlib.sha512()
    h.update(_RLC_DOMAIN)
    h.update(wave.to_bytes(8, "little"))
    for src, sh in items:
        h.update(src.to_bytes(4, "little"))
        h.update(sh)
    root = h.digest()
    out = []
    for src, _ in items:
        d = hashlib.sha512(root + src.to_bytes(4, "little")).digest()
        out.append(int.from_bytes(d[: _RLC_BITS // 8], "little") | 1)
    return out


def batch_verify_shares(
    share_pks: Sequence,
    wave: int,
    shares: Dict[int, bytes],
    *,
    msm=None,
) -> Dict[int, bytes]:
    """The subset of ``shares`` that individually verify — at batched cost.

    Replaces one pairing *per share* (seconds each in the host tower;
    minutes at committee scale — round-2 VERDICT weak #4) with:

    1. one random-linear-combination check over the whole set
       (2 Miller loops + two small-scalar MSMs): all-honest sets pass
       with exactly one pairing-product evaluation;
    2. on failure, single-bad-share localization from the failed check's
       own GT defect plus one x-weighted defect: with errors
       e_i = sigma_i - [sk_i]H, the RLC product gives
       v_c = e(-sum c_i e_i, g2) and x-scaled coefficients give
       v2 = e(-sum c_i x_i e_i, g2); one bad index j makes
       v2 == v_c^(x_j), found by an incremental GT power scan (Fp12
       muls, microseconds) — only one extra pairing product total;
    3. bisection over RLC checks for the multi-bad case, O(bad * log n)
       pairing products.

    Soundness: the RLC coefficients are 64-bit Fiat-Shamir outputs bound
    to the full share set, so a set with any invalid share passes with
    probability <= 2^-63 (coefficients are forced odd).
    """
    h_pt = bls.hash_to_g1(wave_tag(wave))
    neg_g2 = bls.g2_neg(bls.G2_GEN)
    decoded: List[Tuple[int, tuple]] = []
    for src in sorted(shares):
        pt = bls.g1_decompress(shares[src])
        if pt is not None:
            decoded.append((src, pt))
    if not decoded:
        return {}

    def rlc_product(
        subset: List[Tuple[int, tuple]], weights: Optional[List[int]] = None
    ) -> tuple:
        """GT defect of the subset under (optionally x-scaled) Fiat-Shamir
        coefficients: FP12_ONE iff every share in the subset verifies."""
        cs = _rlc_coeffs(wave, [(s, shares[s]) for s, _ in subset])
        if weights is not None:
            cs = [c * w for c, w in zip(cs, weights)]
        pts = [pt for _, pt in subset]
        sig_comb = msm(cs, pts) if msm is not None else bls.g1_msm(cs, pts)
        pk_comb = bls.g2_msm(cs, [share_pks[s] for s, _ in subset])
        return bls.pairing_product([(sig_comb, neg_g2), (h_pt, pk_comb)])

    def rlc_holds(subset: List[Tuple[int, tuple]]) -> bool:
        return rlc_product(subset) == bls.FP12_ONE

    v_c = rlc_product(decoded)
    if v_c == bls.FP12_ONE:
        return {s: shares[s] for s, _ in decoded}

    # One-bad-share localization from the defect we already have: with
    # errors e_i = sigma_i - [sk_i]H, v_c = e(-sum c_i e_i, g2); weighting
    # the same coefficients by x_i = src_i + 1 gives
    # v2 = e(-sum c_i x_i e_i, g2). A single bad index j makes
    # v2 == v_c^(x_j) — found by an incremental GT power scan.
    xs = [s + 1 for s, _ in decoded]
    v2 = rlc_product(decoded, weights=xs)
    by_x = {x: s for x, (s, _) in zip(xs, decoded)}
    power = v_c  # v_c^x at loop head
    bad_src = None
    for x in range(1, max(xs) + 1):
        if x in by_x and power == v2:
            bad_src = by_x[x]
            break
        power = bls.fp12_mul(power, v_c)
    if bad_src is not None:
        rest = [(s, pt) for s, pt in decoded if s != bad_src]
        if not rest:
            return {}
        if rlc_holds(rest):
            return {s: shares[s] for s, _ in rest}

    # Multiple bad shares: bisect. Precondition of _failed: the subset's
    # RLC check is already known False (the full set failed above), so
    # split immediately instead of re-paying that pairing product.
    def filt_failed(subset: List[Tuple[int, tuple]]) -> List[Tuple[int, tuple]]:
        if len(subset) == 1:
            return []
        mid = len(subset) // 2
        out: List[Tuple[int, tuple]] = []
        for part in (subset[:mid], subset[mid:]):
            if rlc_holds(part):
                out.extend(part)
            else:
                out.extend(filt_failed(part))
        return out

    return {s: shares[s] for s, _ in filt_failed(decoded)}


def verify_group(group_pk, wave: int, sigma: bytes) -> bool:
    return bls.verify(group_pk, wave_tag(wave), sigma)


def leader_from_sigma(sigma: bytes, n: int) -> int:
    """H(sigma) mod n — uniform because sigma is a uniform group element
    determined before any adversary sees f+1 shares."""
    return int.from_bytes(hashlib.sha512(sigma).digest(), "little") % n
