"""(f+1)-of-n threshold-BLS — the real common coin.

Exactly the design the reference's TODO names ("PKI and a threshold
signature scheme with a threshold of (f+1)-of-n",
``process/process.go:388``), built on :mod:`dag_rider_tpu.crypto.bls12381`:

- a trusted dealer (or DKG, out of scope) Shamir-shares a group secret
  over Z_r; process i holds share sk_i = poly(i+1);
- for wave w, each process signs the wave tag with its share and
  piggybacks the 48-byte share signature on its round(w,4) vertex;
- any f+1 valid shares Lagrange-interpolate (in the exponent — a G1
  multi-scalar multiplication, the TPU-acceleration target of
  BASELINE.json config #5) to the unique group signature sigma_w;
- leader(w) = H(sigma_w) mod n. Agreement: sigma_w is unique regardless
  of which f+1 shares combined. Unpredictability: fewer than f+1 shares
  reveal nothing (Shamir). Fairness: H(sigma_w) is uniform.
"""

from __future__ import annotations

import hashlib
from typing import Dict, List, Optional, Sequence, Tuple

from dag_rider_tpu.crypto import bls12381 as bls

_COIN_DOMAIN = b"dagrider-threshold-coin-v1|"


def wave_tag(wave: int) -> bytes:
    return _COIN_DOMAIN + wave.to_bytes(8, "little")


class ThresholdKeys:
    """Dealer-generated key material for one committee.

    share_sks[i] is private to process i; group_pk and share_pks are the
    public PKI every process (and any external verifier) holds.
    """

    def __init__(
        self,
        threshold: int,
        group_pk,
        share_pks: Sequence,
        share_sks: Sequence[int],
    ):
        self.threshold = threshold
        self.group_pk = group_pk
        self.share_pks = tuple(share_pks)
        self.share_sks = tuple(share_sks)

    @staticmethod
    def generate(
        n: int, threshold: int, seed: bytes = b"dagrider-coin-dealer"
    ) -> "ThresholdKeys":
        """Deterministic dealer (seeded — tests / simulations only; a real
        deployment runs a DKG so nobody ever holds the group secret)."""
        if not 1 <= threshold <= n:
            raise ValueError("need 1 <= threshold <= n")
        coeffs = []
        for j in range(threshold):
            h = hashlib.sha512(seed + b"|coeff|" + str(j).encode()).digest()
            coeffs.append(int.from_bytes(h, "little") % bls.R)
        def poly(x: int) -> int:
            acc = 0
            for c in reversed(coeffs):
                acc = (acc * x + c) % bls.R
            return acc

        share_sks = [poly(i + 1) for i in range(n)]
        share_pks = [bls.pk_of(sk) for sk in share_sks]
        return ThresholdKeys(
            threshold, bls.pk_of(coeffs[0]), share_pks, share_sks
        )


def sign_share(share_sk: int, wave: int) -> bytes:
    """Process-local share signature for wave w (48 bytes)."""
    return bls.sign(share_sk, wave_tag(wave))


def verify_share(share_pk, wave: int, share: bytes) -> bool:
    """Pairing check of one share against that process's share pk."""
    return bls.verify(share_pk, wave_tag(wave), share)


def lagrange_at_zero(indices: Sequence[int]) -> List[int]:
    """Coefficients lambda_i for interpolation at x=0 over Z_r; indices
    are the Shamir x-coordinates (process index + 1)."""
    lams = []
    for i in indices:
        num, den = 1, 1
        for j in indices:
            if j == i:
                continue
            num = num * j % bls.R
            den = den * (j - i) % bls.R
        lams.append(num * pow(den, bls.R - 2, bls.R) % bls.R)
    return lams


def aggregate(
    shares: Dict[int, bytes], threshold: int, *, msm=None
) -> Optional[bytes]:
    """Combine >= threshold shares {source -> 48B sig} into the group
    signature. Returns None if fewer than threshold decode.

    The combination sigma = sum_i lambda_i * sigma_i is a G1 MSM; `msm`
    may override the backend (host double-and-add by default, the TPU
    kernel via ops.bls_msm when supplied).
    """
    decoded: List[Tuple[int, tuple]] = []
    for src in sorted(shares):
        pt = bls.g1_decompress(shares[src])
        if pt is not None:
            decoded.append((src, pt))
        if len(decoded) == threshold:
            break
    if len(decoded) < threshold:
        return None
    xs = [src + 1 for src, _ in decoded]
    lams = lagrange_at_zero(xs)
    points = [pt for _, pt in decoded]
    if msm is not None:
        sigma = msm(lams, points)
    else:
        sigma = None
        for lam, pt in zip(lams, points):
            sigma = bls.g1_add(sigma, bls.g1_mul(lam, pt))
    return bls.g1_compress(sigma)


def verify_group(group_pk, wave: int, sigma: bytes) -> bool:
    return bls.verify(group_pk, wave_tag(wave), sigma)


def leader_from_sigma(sigma: bytes, n: int) -> int:
    """H(sigma) mod n — uniform because sigma is a uniform group element
    determined before any adversary sees f+1 shares."""
    return int.from_bytes(hashlib.sha512(sigma).digest(), "little") % n
