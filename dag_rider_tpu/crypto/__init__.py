from dag_rider_tpu.crypto import ed25519

__all__ = ["ed25519"]
