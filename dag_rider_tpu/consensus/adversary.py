"""Seeded Byzantine adversary strategies driving a faulty Process.

The transport-level mutator in transport/faults.py corrupts honest
traffic in flight; this module is the stronger model — the *sender
itself* is Byzantine. A :class:`ByzantineProcess` runs the full honest
state machine (its own DAG only ever holds gate-valid vertices: inserting
a forged out-of-range edge into the dense mirrors would corrupt the
adversary, not test its peers) but hands every proposal to a seeded
:class:`ByzantineBehavior` at the ``_broadcast_vertex`` dissemination
seam, where the wire output is mutated, withheld, or split.

Strategies (per ISSUE/ROADMAP open item 5):

- :class:`EquivocateBehavior` — conflicting, validly re-signed payloads
  for the same (round, source) slot; ``split=True`` sends disjoint
  variants to disjoint halves of the cluster (the divergence-inducing
  shape Bracha RBC exists to close — safe only under ``rbc=True``).
- :class:`WithholdBehavior` — selective per-destination withholding of
  own proposals (crash-ish at the edge, but asymmetric: some peers see
  the vertex, some must recover it via anti-entropy).
- :class:`InvalidEdgesBehavior` — validly signed vertices whose
  strong/weak edges violate the admission gate (out-of-range sources,
  wrong target rounds, sub-quorum parents) — exercising the
  ``edges_valid`` clamp in consensus/process.py.
- :class:`GarbageCoinBehavior` — sustained threshold-coin pollution:
  every wave-boundary proposal carries a well-formed-but-worthless BLS
  share (a real G1 point that is NOT a signature under the adversary's
  share key — random bytes would fail point decompression and be
  skipped for free), so the coin's batched bad-share filter
  (consensus/coin.py) must recover wave after wave, not once.

All randomness is seeded per behavior instance — scenarios replay
byte-for-byte.
"""

from __future__ import annotations

import dataclasses
import random
from typing import Callable, Optional

from dag_rider_tpu.consensus.process import Process
from dag_rider_tpu.core.types import Block, BroadcastMessage, Vertex, VertexID

#: strategy names accepted by :func:`make_behavior` (and the scenario
#: runner's --adversary flag)
ADVERSARIES = (
    "equivocate",
    "equivocate_split",
    "withhold",
    "invalid_edges",
    "garbage_coin",
    "lane_withhold",
    "lane_garbage_ack",
    "stale_epoch",
)


def _resolve_enqueue(transport) -> Optional[Callable]:
    """Find a per-destination send seam by unwrapping ``.inner`` chains
    until something exposes ``enqueue(dest, msg)`` (InMemoryTransport
    does; FaultyTransport/RbcTransport wrap it). Per-destination sends
    still traverse the wrapper's delivery-time fault/RBC machinery —
    handlers registered with the inner broker ARE the wrapped ones.
    Returns None when the stack has no such seam (point-to-point sends
    degrade to broadcast-or-withhold)."""
    seen: set = set()
    tp = transport
    while tp is not None and id(tp) not in seen:
        seen.add(id(tp))
        fn = getattr(tp, "enqueue", None)
        if callable(fn):
            return fn
        tp = getattr(tp, "inner", None)
    return None


class ByzantineBehavior:
    """Base strategy: honest dissemination (broadcast verbatim).
    Subclasses override :meth:`disseminate`; ``stats`` counts what the
    adversary actually did, so scenario reports can assert the attack
    genuinely ran (no vacuous survivals)."""

    name = "honest"

    def __init__(self, seed: int = 0) -> None:
        self.rng = random.Random(seed)
        self.stats = {"mutated": 0, "withheld": 0, "extra_sent": 0}

    def bind(self, proc: Process) -> None:
        """Hook run once after the host process is fully constructed —
        strategies that corrupt state *creation* (not just the wire)
        install themselves here. Default: nothing."""

    def bind_lanes(self, proc: Process) -> None:
        """Hook run when a dissemination-lane coordinator is attached
        (ISSUE 17) — lanes are wired post-construction, after
        :meth:`bind` has already run, so lane strategies install here.
        Default: nothing (a lanes-off run leaves lane adversaries
        honest, and their stats prove vacuity)."""

    def disseminate(self, proc: Process, v: Vertex) -> None:
        proc.transport.broadcast(self._msg(v))

    @staticmethod
    def _msg(v: Vertex) -> BroadcastMessage:
        return BroadcastMessage(vertex=v, round=v.round, sender=v.id.source)

    def _resign(self, proc: Process, v: Vertex) -> Vertex:
        """Valid signature over forged content — the adversary owns its
        key, so honest nodes must reject on *semantic* gates (edges,
        RBC consistency), not signature checks."""
        if proc.signer is not None:
            v = proc.signer.sign_vertex(v)
        return v

    def _variant(self, proc: Process, v: Vertex, tag: str) -> Vertex:
        """Same (round, source) slot, different payload, validly
        re-signed. dataclasses.replace drops the memoized digest/gate,
        so honest nodes evaluate the forgery on its own content."""
        self.stats["mutated"] += 1
        forged = dataclasses.replace(
            v,
            block=Block((f"equiv-{tag}-r{v.round}".encode(),)),
            signature=None,
        )
        return self._resign(proc, forged)


class EquivocateBehavior(ByzantineBehavior):
    """Equivocation at the source. ``split=False``: both variants are
    broadcast to everyone — a FIFO broker admits the first and every
    honest node flags the second (``equivocations_detected``), so this
    drives *detection*. ``split=True``: disjoint halves of the cluster
    receive different variants — without an RBC stage the halves admit
    conflicting payloads and agreement genuinely breaks (the planted
    violation the invariant mutation tests rely on); under ``rbc=True``
    neither variant reaches an echo quorum and safety holds."""

    name = "equivocate"

    def __init__(self, seed: int = 0, *, split: bool = False) -> None:
        super().__init__(seed)
        self.split = split
        if split:
            self.name = "equivocate_split"

    def disseminate(self, proc: Process, v: Vertex) -> None:
        alt = self._variant(proc, v, "b")
        if self.split:
            enqueue = _resolve_enqueue(proc.transport)
            if enqueue is not None:
                dests = [i for i in range(proc.cfg.n) if i != proc.index]
                self.rng.shuffle(dests)
                half = len(dests) // 2
                for d in dests[:half]:
                    enqueue(d, self._msg(v))
                for d in dests[half:]:
                    enqueue(d, self._msg(alt))
                self.stats["extra_sent"] += 1
                return
        proc.transport.broadcast(self._msg(v))
        proc.transport.broadcast(self._msg(alt))
        self.stats["extra_sent"] += 1


class WithholdBehavior(ByzantineBehavior):
    """Selective per-destination withholding: each proposal picks a
    seeded victim subset that never receives it. Victims see the slot
    referenced by later honest vertices and must recover it through the
    anti-entropy sync path (or advance without it — an f-bounded source
    owes nobody liveness of its own slots)."""

    name = "withhold"

    def disseminate(self, proc: Process, v: Vertex) -> None:
        dests = [i for i in range(proc.cfg.n) if i != proc.index]
        enqueue = _resolve_enqueue(proc.transport)
        if enqueue is None:
            # no point-to-point seam: degrade to all-or-nothing
            if self.rng.random() < 0.5:
                self.stats["withheld"] += len(dests)
                return
            proc.transport.broadcast(self._msg(v))
            return
        k = self.rng.randrange(1, max(2, len(dests)))
        victims = set(self.rng.sample(dests, k))
        msg = self._msg(v)
        for d in dests:
            if d in victims:
                self.stats["withheld"] += 1
            else:
                enqueue(d, msg)


class InvalidEdgesBehavior(ByzantineBehavior):
    """Validly signed vertices with forged edges, cycling through the
    admission-gate violation classes: a strong edge with an
    out-of-range source (>= n — sources are packed unsigned, so the
    clamp, not wraparound, must catch it), strong edges targeting the
    wrong round, fewer than quorum distinct strong parents, and weak
    edges outside [1, round-2]. Honest nodes must reject at
    ``edges_valid`` (``msgs_rejected_edges``) and stay safe and live."""

    name = "invalid_edges"
    MODES = ("oob_source", "stale_round", "thin_quorum", "weak_oob")

    def disseminate(self, proc: Process, v: Vertex) -> None:
        mode = self.MODES[self.rng.randrange(len(self.MODES))]
        proc.transport.broadcast(self._msg(self._forge(proc, v, mode)))

    def _forge(self, proc: Process, v: Vertex, mode: str) -> Vertex:
        strong, weak = v.strong_edges, v.weak_edges
        vr = v.id.round
        if mode == "stale_round" and vr < 2:
            mode = "oob_source"  # round -1 targets can't even be encoded
        if mode == "oob_source":
            strong = strong + (VertexID(vr - 1, proc.cfg.n + 7),)
        elif mode == "stale_round":
            strong = tuple(VertexID(vr - 2, e.source) for e in strong)
        elif mode == "thin_quorum":
            strong = strong[: max(1, proc.cfg.quorum - 1)]
        else:  # weak_oob: weak round vr-1 violates wr <= vr-2 (and >= 1)
            weak = weak + (VertexID(max(1, vr - 1), 0),)
        self.stats["mutated"] += 1
        forged = dataclasses.replace(
            v, strong_edges=strong, weak_edges=weak, signature=None
        )
        return self._resign(proc, forged)


class GarbageCoinBehavior(ByzantineBehavior):
    """Sustained threshold-coin pollution, applied at share *creation*
    (:meth:`bind` wraps ``coin.my_share``): every wave-boundary proposal
    carries a seeded garbage share that is a genuine G1 point — it
    decodes, enters honest share books, and lands in the first
    combination attempt (``aggregate`` walks shares sorted by source, so
    run this adversary at a LOW index) — but is no signature under any
    share key. The coin's first aggregate fails each wave and the
    batched filter must discard the junk and recombine
    (ThresholdCoin.filtered counts the recoveries). Purely random bytes
    would be useless here: they fail point decompression and aggregate
    skips them without ever engaging the filter.

    Poisoning my_share (rather than rewriting the wire) also keeps the
    vertex signature honest over the garbage — exactly the adversary
    model: a validly signed vertex whose *coin contribution* is junk.
    Share-less coins (round_robin, fixed) return None and are left
    alone."""

    name = "garbage_coin"

    def bind(self, proc: Process) -> None:
        coin = proc.coin
        orig = coin.my_share

        def poisoned(wave: int):
            if orig(wave) is None:
                return None
            self.stats["mutated"] += 1
            return self._garbage_share(wave)

        coin.my_share = poisoned  # instance attribute shadows the method

    def _garbage_share(self, wave: int) -> bytes:
        from dag_rider_tpu.crypto import bls12381 as bls

        pt = bls.hash_to_g1(
            b"dagrider-garbage-share|"
            + wave.to_bytes(8, "little")
            + self.rng.randbytes(8)
        )
        return bls.g1_compress(pt)


class LaneWithholdBehavior(ByzantineBehavior):
    """Payload withholding at the lane layer (ISSUE 17): vertices and
    lane *refs* flow honestly, but each lane batch is withheld from a
    seeded victim subset. A victim admits and orders the carrier vertex
    normally (ordering is payload-blind — that's the point of lanes)
    and only discovers the hole at delivery resolution, where
    fetch-on-miss must recover the bytes from a certified holder. If
    the victim set is large enough to starve the 2f+1 ack quorum, the
    producer's own materialize degrades the block to the inline oracle
    instead — zero loss either way."""

    name = "lane_withhold"

    def bind_lanes(self, proc: Process) -> None:
        coord = proc.lanes
        if coord is None:
            return
        endpoint = coord.endpoint
        dests = [i for i in range(proc.cfg.n) if i != proc.index]

        def withholding(digest: bytes, payload: bytes) -> int:
            k = self.rng.randrange(1, max(2, len(dests)))
            victims = set(self.rng.sample(dests, k))
            sent = 0
            for d in dests:
                if d in victims:
                    self.stats["withheld"] += 1
                else:
                    endpoint.send(d, "batch", (digest, payload))
                    sent += 1
            return sent

        coord._broadcast_batch = withholding  # instance attr shadows


class LaneGarbageAckBehavior(ByzantineBehavior):
    """Garbage availability acks (ISSUE 17): this process receives lane
    batches honestly (it must — an f-bounded adversary can't fake what
    it serves on fetch) but answers every one with a corrupted ack —
    wrong digest echo plus junk signature bytes. Producers key ack
    collection by echoed digest and structurally filter shares, so the
    garbage never enters a certificate; at n = 3f+1 the remaining
    self + (n-1-f) honest acks are exactly the 2f+1 quorum, so honest
    producers still certify every batch."""

    name = "lane_garbage_ack"

    def bind_lanes(self, proc: Process) -> None:
        coord = proc.lanes
        if coord is None:
            return

        def garbled(digest: bytes):
            self.stats["mutated"] += 1
            bad_digest = bytes(b ^ 0xFF for b in digest)
            return bad_digest, self.rng.randbytes(48)

        coord._make_ack = garbled  # instance attr shadows the method


class StaleEpochBehavior(ByzantineBehavior):
    """Pre-rotation replay (ISSUE 20): every proposal is disseminated
    honestly, tagged with the sender's current epoch, and recorded;
    once the host crosses an epoch boundary the recorded pre-boundary
    traffic is re-broadcast verbatim — old epoch tag and all. Honest
    receivers must drop each replay at the wire stale gate
    (``epoch_stale_rejected``) before spending signature or RBC work on
    it; a replayed coin share from the pre-rotation key set must never
    enter a post-rotation share book. With the epoch path off the
    behavior degrades to honest and its stats prove vacuity."""

    name = "stale_epoch"
    KEEP = 32  # recorded messages retained
    REPLAY = 4  # stale replays injected per fresh proposal

    def __init__(self, seed: int = 0) -> None:
        super().__init__(seed)
        self._recorded: list = []  # (epoch_at_send, BroadcastMessage)

    def disseminate(self, proc: Process, v: Vertex) -> None:
        mgr = proc.epoch_mgr
        cur = mgr.epoch if mgr is not None else 0
        msg = BroadcastMessage(
            vertex=v, round=v.round, sender=v.id.source, epoch=cur
        )
        proc.transport.broadcast(msg)
        if mgr is None:
            return
        stale = [m for e, m in self._recorded if e < cur]
        self.rng.shuffle(stale)
        for m in stale[: self.REPLAY]:
            proc.transport.broadcast(m)
            self.stats["extra_sent"] += 1
        self._recorded.append((cur, msg))
        if len(self._recorded) > self.KEEP:
            self._recorded.pop(0)


def make_behavior(kind: str, seed: int = 0) -> ByzantineBehavior:
    """Factory over :data:`ADVERSARIES` (scenario runner / bench rung)."""
    if kind == "equivocate":
        return EquivocateBehavior(seed)
    if kind == "equivocate_split":
        return EquivocateBehavior(seed, split=True)
    if kind == "withhold":
        return WithholdBehavior(seed)
    if kind == "invalid_edges":
        return InvalidEdgesBehavior(seed)
    if kind == "garbage_coin":
        return GarbageCoinBehavior(seed)
    if kind == "lane_withhold":
        return LaneWithholdBehavior(seed)
    if kind == "lane_garbage_ack":
        return LaneGarbageAckBehavior(seed)
    if kind == "stale_epoch":
        return StaleEpochBehavior(seed)
    raise ValueError(f"unknown adversary {kind!r} (choose from {ADVERSARIES})")


class ByzantineProcess(Process):
    """A Process whose wire output is driven by a ByzantineBehavior.

    Local state stays honest — the vertex inserted into this process's
    own DAG is the unforged original, and mutation happens only at the
    ``_broadcast_vertex`` seam. That is deliberate: the adversary's
    *peers* are under test, and a forged out-of-range edge inside the
    adversary's own dense mirrors would crash the adversary instead of
    probing the honest admission gates."""

    def __init__(
        self,
        cfg,
        index: int,
        transport,
        *,
        behavior: Optional[ByzantineBehavior] = None,
        **kwargs,
    ) -> None:
        # set before super().__init__: start() may propose immediately
        self.behavior = behavior if behavior is not None else ByzantineBehavior()
        super().__init__(cfg, index, transport, **kwargs)
        # bind AFTER construction (needs self.coin etc.); the first
        # wave-boundary proposal is rounds away, so nothing is missed
        self.behavior.bind(self)

    def _broadcast_vertex(self, v: Vertex) -> None:
        self.behavior.disseminate(self, v)

    def attach_lanes(self, coordinator) -> None:
        # lanes are wired after __init__ (simulator post-construction
        # pass), so lane behaviors get their own bind point here
        super().attach_lanes(coordinator)
        self.behavior.bind_lanes(self)
