"""Host-side DAG state with dense numpy mirrors.

The authoritative per-process DAG. Replaces the reference's
``dag [][]vertex`` array-of-rounds plus linear scans
(``process/process.go:76, 374-384``) with:

- a ``(round, source) -> Vertex`` map for payload access, and
- dense boolean mirrors ``exists[R, n]`` / ``strong[R, n, n]`` — the exact
  tensors the device kernels (:mod:`dag_rider_tpu.ops.dag_kernels`) consume,
  so shipping a round/wave to the TPU is a zero-copy slice, and the dense
  encoding doubles as the checkpoint format (SURVEY.md §5: the reference has
  no serialization at all).

Weak edges are kept sparse host-side (they are rare and round-skipping);
ordering/reachability queries use vectorized frontier propagation over the
dense mirrors + sparse weak lists — O(rounds * n) bitmap work per query
instead of the reference's per-edge full-DAG scans.

Memory bounding (round-4): the reference grows its DAG forever
(``process.go:72-85``) and so did rounds 1-3 here. :meth:`prune_below`
retires everything under a caller-chosen floor — dense rows shift down so
row index = ``round - base_round``, vertices/weak entries are dropped, and
the window's capacity is reused instead of doubling forever. All public
methods keep speaking ABSOLUTE round numbers; with ``base_round == 0``
(pruning disabled, the default) every code path is bit-identical to the
unbounded behavior. The *policy* for choosing the floor (the deterministic
GC/ordering-exclusion rule that makes pruning safe across processes) lives
in the Process.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Tuple

import numpy as np

from dag_rider_tpu.config import Config
from dag_rider_tpu.core.types import Vertex, VertexID


class DagState:
    """One process's view of the DAG (rounds x sources)."""

    def __init__(self, cfg: Config):
        self.cfg = cfg
        self.n = cfg.n
        self._capacity = max(cfg.max_rounds, 8)
        #: absolute round of dense row 0; rounds below are retired.
        self.base_round = 0
        self.exists = np.zeros((self._capacity, self.n), dtype=bool)
        self.strong = np.zeros((self._capacity, self.n, self.n), dtype=bool)
        #: dense mirror of ``weak``'s key set: has_weak[row, src] is True
        #: iff weak[(base+row, src)] exists. Weak edges are rare, and the
        #: closure sweeps were paying a dict probe per ACTIVE source per
        #: round (~1M probes per n=256 bench window) to discover that.
        self.has_weak = np.zeros((self._capacity, self.n), dtype=bool)
        # weak[(r, i)] -> tuple of (r2, j) targets, r2 < r-1 (absolute).
        self.weak: Dict[Tuple[int, int], Tuple[Tuple[int, int], ...]] = {}
        self.vertices: Dict[VertexID, Vertex] = {}
        #: per-round {source: Vertex} mirror of `vertices` (fast
        #: round_size / vertices_in_round without dense-row scans)
        self._round_vertices: Dict[int, Dict[int, Vertex]] = {}
        self.max_round = 0
        #: lowest round inserted since the owner last consumed this marker
        #: (consumer: Process._weak_edges_for's truncated sweep — 0 means
        #: "sweep everything", the cold-start/restore-safe default).
        self.insert_min_round = 0
        #: vertices dropped by prune_below (metrics/tests)
        self.pruned_count = 0

    def reset(self) -> None:
        """Empty every mirror (used by checkpoint restore before
        re-inserting in round order — keeps the mirrors' consistency
        logic in one place instead of field-poking from callers)."""
        self.vertices.clear()
        self._round_vertices.clear()
        self.exists[:] = False
        self.strong[:] = False
        self.has_weak[:] = False
        self.weak.clear()
        self.base_round = 0
        self.max_round = 0
        self.insert_min_round = 0
        self.pruned_count = 0

    # -- growth / retirement ----------------------------------------------

    def _ensure_capacity(self, rnd: int) -> None:
        row = rnd - self.base_round
        if row < self._capacity:
            return
        new_cap = self._capacity
        while new_cap <= row:
            new_cap *= 2
        exists = np.zeros((new_cap, self.n), dtype=bool)
        strong = np.zeros((new_cap, self.n, self.n), dtype=bool)
        has_weak = np.zeros((new_cap, self.n), dtype=bool)
        exists[: self._capacity] = self.exists
        strong[: self._capacity] = self.strong
        has_weak[: self._capacity] = self.has_weak
        self.exists, self.strong, self.has_weak = exists, strong, has_weak
        self._capacity = new_cap

    def prune_below(self, floor: int) -> int:
        """Retire every vertex with ``round < floor``; returns the count.

        Dense rows shift down in place (capacity is *reused*, so a pruned
        long-running DAG stops growing), vertex payloads and weak entries
        below the floor are dropped, and ``base_round`` becomes ``floor``.
        Callers own the safety argument — the Process only passes floors
        under its deterministic ordering-exclusion horizon (cfg.gc_depth),
        below which no delivery can ever happen at any correct process.
        """
        if floor <= self.base_round:
            return 0
        floor = min(floor, self.max_round + 1)
        shift = floor - self.base_round
        live = self._capacity - shift
        if live > 0:
            # .copy(): numpy overlapping slice assignment is not defined
            self.exists[:live] = self.exists[shift:].copy()
            self.strong[:live] = self.strong[shift:].copy()
            self.has_weak[:live] = self.has_weak[shift:].copy()
        self.exists[max(live, 0) :] = False
        self.strong[max(live, 0) :] = False
        self.has_weak[max(live, 0) :] = False
        removed = 0
        for r in [r for r in self._round_vertices if r < floor]:
            for v in self._round_vertices.pop(r).values():
                del self.vertices[v.id]
                removed += 1
        for key in [k for k in self.weak if k[0] < floor]:
            del self.weak[key]
        self.base_round = floor
        if self.max_round < floor:
            self.max_round = floor
        if self.insert_min_round < floor:
            self.insert_min_round = floor
        self.pruned_count += removed
        return removed

    # -- mutation ----------------------------------------------------------

    def insert(self, v: Vertex) -> None:
        """Add a vertex whose predecessors are already present.

        Admission policy (who may call this, and when) lives in the Process;
        this container only maintains the mirrors.
        """
        vid = v.id
        r, s = vid.round, vid.source
        if r < self.base_round:
            raise ValueError(f"vertex {vid} is below the pruned floor")
        self._ensure_capacity(r)
        if vid in self.vertices:
            raise ValueError(f"vertex {vid} already present")
        sr, ss, wr, ws = v.edge_arrays()
        # The admission gate (Process.on_message) already proved the edge
        # rounds for vertices that passed it — its memo on the vertex
        # skips the redundant re-scan on this hot path.
        g = v.__dict__.get("_gate")
        if (g is None or g[1]) and sr.size and (sr != r - 1).any():
            raise ValueError(
                f"strong edges from {vid} must target round {r - 1}"
            )
        self.vertices[vid] = v
        rv = self._round_vertices.get(r)
        if rv is None:
            rv = self._round_vertices[r] = {}
        rv[s] = v
        row = r - self.base_round
        self.exists[row, s] = True
        # one fancy-index write instead of ~2f+1 numpy scalar stores
        self.strong[row, s, ss] = True
        if wr.size:
            self.weak[(r, s)] = tuple(zip(wr.tolist(), ws.tolist()))
            self.has_weak[row, s] = True
        if r > self.max_round:
            self.max_round = r
        if r < self.insert_min_round:
            self.insert_min_round = r

    def insert_many(
        self,
        vs: List[Vertex],
        trusted: bool = False,
        prepped: Optional[tuple] = None,
    ) -> None:
        """Batch :meth:`insert` for vertices of ONE round.

        The vectorized drain admits whole per-round groups at once; this
        pays the dense-mirror bookkeeping (capacity check, row lookup,
        fancy-index writes) once per *group* instead of once per vertex,
        and the dict mirrors land as C-level bulk ``update`` calls — the
        interpreted per-vertex stores were ~40% of this method in the
        n=256 profile. By default it validates the whole batch before
        mutating anything, so a bad vertex leaves the mirrors untouched.
        ``trusted=True`` skips that pass: the vector drain calls it only
        with vertices it just proved (one round group, presence-filtered
        against the mirrors, edge gate memoized by edges_valid).

        ``prepped = (srcs, flats)`` threads batch geometry the drain
        already computed: the per-vertex source list and the per-vertex
        FLAT strong-row indices (``source * n + strong_cols``, memoized
        cluster-wide on each shared vertex object), under the
        caller-proved guarantee that NO vertex in ``vs`` carries weak
        edges. The strong mirror then lands as one 1-D scatter into the
        round's row block — no per-vertex edge walk, no ``np.repeat``.
        """
        if not vs:
            return
        r = vs[0].id.round
        if r < self.base_round:
            raise ValueError(f"vertex {vs[0].id} is below the pruned floor")
        if not trusted:
            seen = set()
            for v in vs:
                vid = v.id
                if vid.round != r:
                    raise ValueError(
                        f"insert_many needs one round, got {vid.round} != {r}"
                    )
                if vid in self.vertices or vid in seen:
                    raise ValueError(f"vertex {vid} already present")
                seen.add(vid)
                sr, _, _, _ = v.edge_arrays()
                g = v.__dict__.get("_gate")
                if (g is None or g[1]) and sr.size and (sr != r - 1).any():
                    raise ValueError(
                        f"strong edges from {vid} must target round {r - 1}"
                    )
        self._ensure_capacity(r)
        row = r - self.base_round
        rv = self._round_vertices.get(r)
        if rv is None:
            rv = self._round_vertices[r] = {}
        self.vertices.update((v.id, v) for v in vs)
        if prepped is not None:
            srcs, flats = prepped
            rv.update(zip(srcs, vs))
            self.exists[row, srcs] = True
            flat = np.concatenate(flats) if len(flats) > 1 else flats[0]
            if flat.size:
                # one 1-D scatter into the round's (n, n) row block.
                # strong is always a base C-contiguous allocation (see
                # _ensure_capacity / prune_below, which copy in place),
                # so the reshape is a writable view, never a copy.
                self.strong[row].reshape(-1)[flat] = True
        else:
            srcs = [v.id.source for v in vs]
            rv.update(zip(srcs, vs))
            arrs = [
                v.__dict__.get("_edge_arrays") or v.edge_arrays()
                for v in vs
            ]
            lens = np.fromiter(
                (a[1].size for a in arrs), dtype=np.intp, count=len(vs)
            )
            cols = [a[1] for a in arrs]
            cat = np.concatenate(cols) if len(cols) > 1 else cols[0]
            weak = self.weak
            for s, a in zip(srcs, arrs):
                wr = a[2]
                if wr.size:
                    weak[(r, s)] = tuple(zip(wr.tolist(), a[3].tolist()))
                    self.has_weak[row, s] = True
            self.exists[row, srcs] = True
            if cat.size:
                self.strong[row, np.repeat(srcs, lens), cat] = True
        if r > self.max_round:
            self.max_round = r
        if r < self.insert_min_round:
            self.insert_min_round = r

    # -- queries -----------------------------------------------------------

    def present(self, vid: VertexID) -> bool:
        """Membership — the reference's ``present`` full-DAG scan
        (``process/process.go:373-384``), here O(1).

        Dict lookup, not the dense mirror: ``exists`` is only ever set by
        :meth:`insert`, which also fills ``vertices``, so the two agree —
        and a numpy scalar index costs ~8x a (hash-cached) dict probe,
        on the hottest call in the 64-node consensus profile."""
        return vid in self.vertices

    def get(self, vid: VertexID) -> Optional[Vertex]:
        return self.vertices.get(vid)

    def round_size(self, rnd: int) -> int:
        return len(self._round_vertices.get(rnd, ()))

    def quorum_frontier(self, quorum: int) -> int:
        """Highest round whose vertex count reaches ``quorum`` (0 when
        only genesis does). Round fills are monotone downward —
        admission requires >= quorum strong edges into every prior
        round — so a backward scan from ``max_round`` stops at the
        first hit. The pipelined wave pass uses this to bound which
        wave instances can possibly have quorum votes yet."""
        for r in range(self.max_round, 0, -1):
            if self.round_size(r) >= quorum:
                return r
        return 0

    def vertices_in_round(self, rnd: int) -> List[Vertex]:
        """Vertices of one round in ascending-source order (the
        deterministic order proposals and total-order delivery rely on).
        Served from the per-round dict mirror — the dense-row scan built
        a VertexID per occupied slot on one of the hottest query paths."""
        d = self._round_vertices.get(rnd)
        if not d:
            return []
        return [d[s] for s in sorted(d)]

    def closure(
        self, seeds: Iterable[VertexID], strong_only: bool = False
    ) -> np.ndarray:
        """Causal history of a seed set as a bool bitmap whose row index
        is ``round - base_round`` (absolute round with pruning off).

        Vectorized frontier propagation round-by-round (the host twin of
        :func:`dag_rider_tpu.ops.dag_kernels.closure_from`); weak edges are
        applied from the sparse map. Replaces the reference's per-target BFS
        ``path`` (``process/process.go:89-148``). Propagation stops at the
        pruned floor: retired rounds report nothing.
        """
        base = self.base_round
        R = self.max_round + 1 - base
        reached = np.zeros((R, self.n), dtype=bool)
        top = -1
        for s in seeds:
            if not self.present(s):
                raise KeyError(f"seed {s} not in DAG")
            reached[s.round - base, s.source] = True
            top = max(top, s.round)
        for r in range(top, max(base, 0), -1):
            row = reached[r - base]
            if not row.any():
                continue
            # strong: one vector-matrix product per round.
            reached[r - base - 1] |= row @ self.strong[r - base]
            if not strong_only:
                # has_weak prefilter: only sources that actually carry
                # weak edges get the dict probe (weak edges are rare)
                for i in np.flatnonzero(row & self.has_weak[r - base]):
                    for (r2, j) in self.weak.get((r, i), ()):
                        if r2 >= base:
                            reached[r2 - base, j] = True
        return reached

    def closure_stopped(
        self, seed: VertexID, stop_mask: np.ndarray
    ) -> np.ndarray:
        """Causal history of ``seed``, pruning propagation at vertices
        where ``stop_mask`` is True. Rows of both bitmaps are indexed by
        ``round - base_round`` (the caller's delivered mask is kept
        base-aligned by Process.maybe_prune).

        Sound ONLY for a causally-closed stop set (callers pass the
        delivered bitmap, and delivery is whole-history-at-a-time):
        anything reachable solely through a stopped vertex is itself in
        the stop set, so pruning there loses no *unstopped* vertex.
        Steady-state wave commits touch only the few undelivered rounds
        at the top instead of rescanning the full DAG depth, and the
        early-exit fires once no unstopped vertex remains at or below
        the sweep round.
        """
        base = self.base_round
        R = seed.round + 1 - base
        reached = np.zeros((R, self.n), dtype=bool)
        reached[seed.round - base, seed.source] = True
        for r in range(seed.round, max(base, 0), -1):
            row = r - base
            act = reached[row] & ~stop_mask[row]
            if act.any():
                reached[row - 1] |= act @ self.strong[row]
                # has_weak prefilter — see closure()
                for i in np.flatnonzero(act & self.has_weak[row]):
                    for (r2, j) in self.weak.get((r, i), ()):
                        if r2 >= base:
                            reached[r2 - base, j] = True
            elif not (reached[:row] & ~stop_mask[:row]).any():
                break
        return reached

    def path(
        self, frm: VertexID, to: VertexID, strong_only: bool = False
    ) -> bool:
        """Is there a (strong-)path from ``frm`` down to ``to``?

        Mirrors the reference API ``path(from, to, strongPath)``
        (``process/process.go:89``): edges point from higher rounds to lower,
        so a path exists iff ``to`` is in ``frm``'s causal history.
        """
        if not self.present(frm) or not self.present(to):
            return False
        if frm == to:
            return True
        if to.round >= frm.round:
            return False
        reached = self.closure([frm], strong_only=strong_only)
        return bool(reached[to.round - self.base_round, to.source])

    # -- dense views for device kernels ------------------------------------

    def strong_stack(self, hi: int, lo: int) -> np.ndarray:
        """strong adjacency chain for rounds (lo, hi], top round first —
        the input format of :func:`ops.dag_kernels.reach_chain`."""
        if not 0 <= lo < hi:
            raise ValueError(f"need 0 <= lo < hi, got lo={lo}, hi={hi}")
        if lo < self.base_round:
            raise ValueError(
                f"rounds <= {self.base_round} are pruned; asked for lo={lo}"
            )
        base = self.base_round
        return self.strong[hi - base : lo - base : -1]

    def dense_snapshot(self, rounds: Optional[int] = None):
        """(exists, strong) trimmed to ``rounds`` rows (rows start at
        ``base_round``) — checkpoint payload and device-dispatch input."""
        R = (self.max_round + 1 - self.base_round) if rounds is None else rounds
        return self.exists[:R].copy(), self.strong[:R].copy()
