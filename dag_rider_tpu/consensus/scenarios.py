"""Byzantine-adversary x WAN scenario runner with checked invariants.

One entry point (:func:`run_scenario`) wires the three robustness layers
built for round 11 into a single reproducible experiment:

- a seeded :class:`~dag_rider_tpu.consensus.adversary.ByzantineBehavior`
  driving up to f :class:`ByzantineProcess` instances (always the LOWEST
  indices — the threshold coin's ``aggregate`` walks shares sorted by
  source, so a garbage share from a low index deterministically lands in
  the first combination attempt instead of hiding behind honest shares),
- a :class:`~dag_rider_tpu.transport.faults.WanTopology` on the fault
  transport: per-link RTT/jitter/drop matrices, geo regions, and
  partitions that heal on schedule (held, never lost),
- every invariant from :mod:`dag_rider_tpu.consensus.invariants`,
  asserted BOTH online (an :class:`InvariantMonitor` raises at the exact
  delivery that breaks safety) and post-hoc over the full honest logs.

A scenario that returns at all has passed agreement, commit-uniqueness,
zero-loss, and bounded-liveness; the report carries the detection and
containment counters (equivocations detected, forged edges rejected,
garbage coin shares filtered, sync serves) so callers can additionally
assert the attack genuinely ran — see tests/test_adversary.py and the
``ladder.byzantine`` bench rung.

CLI (the tier1-byz CI lane):

    python -m dag_rider_tpu.consensus.scenarios --matrix --n 4
    python -m dag_rider_tpu.consensus.scenarios --adversary equivocate \\
        --wan regions --n 7 --cycles 200
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys
from typing import List, Optional, Tuple

from dag_rider_tpu.config import Config
from dag_rider_tpu.consensus import invariants as inv
from dag_rider_tpu.consensus.adversary import (
    ADVERSARIES,
    ByzantineProcess,
    make_behavior,
)
from dag_rider_tpu.consensus.simulator import Simulation
from dag_rider_tpu.core.types import Block
from dag_rider_tpu.transport.faults import (
    FaultPlan,
    FaultyTransport,
    LinkPlan,
    Partition,
    WanTopology,
)

#: WAN profiles understood by :func:`build_topology`
WAN_PROFILES = ("lan", "wan", "regions", "partition")


@dataclasses.dataclass
class Scenario:
    """One adversary x topology experiment. ``cycles`` x ``dt`` is the
    virtual duration; None picks a profile-appropriate default."""

    name: str = ""
    n: int = 4
    adversary: Optional[str] = None  # one of ADVERSARIES, or None=clean
    wan: str = "lan"  # one of WAN_PROFILES
    #: Byzantine node count; None = cfg.f when an adversary is set.
    #: Always clamped to cfg.f — the suite tests f-bounded adversaries.
    byzantine: Optional[int] = None
    seed: int = 0
    cycles: Optional[int] = None
    dt: float = 0.01
    #: Bracha RBC stage. None resolves to True exactly where safety
    #: needs it: split equivocation (disjoint variants to disjoint
    #: halves), and any equivocation under jittery links (per-link
    #: jitter can reorder the two variants per destination, so
    #: first-VAL-wins no longer agrees across honest nodes).
    rbc: Optional[bool] = None
    #: "round_robin" (default) or "threshold_bls"; None resolves to
    #: threshold for the garbage_coin adversary (its target) and
    #: round_robin everywhere else.
    coin: Optional[str] = None
    #: liveness floors handed to check_liveness after the drain
    min_waves: int = 2
    min_each: int = 1
    blocks_per_process: int = 3
    #: dissemination lanes (ISSUE 17). None resolves to forced-on for the
    #: lane_* adversaries (their attack surface IS the lane layer) and
    #: otherwise defers to the DAGRIDER_LANES env default — under which
    #: the stock 32-byte scenario blocks sit below the lane batch floor
    #: and ship inline, so the legacy matrix is byte-identical either way.
    lanes: Optional[bool] = None
    #: epoch reconfiguration (ISSUE 20). None resolves to forced-on for
    #: the stale_epoch adversary (its attack surface IS the wire stale
    #: gate) and off everywhere else — epoch scenarios inject one
    #: ``rotate`` control op at the start so a boundary genuinely
    #: crosses mid-run. Coin stays round_robin here: the matrix's
    #: shared-book threshold factory cannot rotate per-process keys.
    epoch: Optional[bool] = None
    epoch_waves: int = 4

    def __post_init__(self) -> None:
        if self.adversary is not None and self.adversary not in ADVERSARIES:
            raise ValueError(
                f"unknown adversary {self.adversary!r} "
                f"(choose from {ADVERSARIES})"
            )
        if self.wan not in WAN_PROFILES:
            raise ValueError(
                f"unknown WAN profile {self.wan!r} (choose from {WAN_PROFILES})"
            )
        if not self.name:
            self.name = f"{self.adversary or 'clean'}/{self.wan}"

    def resolved_cycles(self) -> int:
        if self.cycles is not None:
            return self.cycles
        if self.coin_kind() == "threshold_bls":
            # threshold aggregation is host-tower pairing math (~0.3s+
            # per wave); keep the wave count small
            return 10
        return 48 if self.wan == "lan" else 160

    def coin_kind(self) -> str:
        if self.coin is not None:
            return self.coin
        return (
            "threshold_bls"
            if self.adversary == "garbage_coin"
            else "round_robin"
        )

    def resolved_lanes(self) -> bool:
        if self.lanes is not None:
            return self.lanes
        return self.adversary in ("lane_withhold", "lane_garbage_ack")

    def resolved_epoch(self) -> bool:
        if self.epoch is not None:
            return self.epoch
        return self.adversary == "stale_epoch"

    def resolved_rbc(self) -> bool:
        if self.rbc is not None:
            return self.rbc
        if self.adversary == "equivocate_split":
            return True
        return self.adversary == "equivocate" and self.wan != "lan"


def build_topology(
    sc: Scenario, duration: float
) -> Optional[WanTopology]:
    """Scenario WAN profile -> topology (None = direct LAN delivery).

    - ``wan``: uniform moderate-latency links with light loss/duplication
      — the sync/anti-entropy stress shape.
    - ``regions``: geo-replicated clusters (cheap intra, 40ms inter).
    - ``partition``: regions plus one cut that severs the LAST f nodes
      (the honest tail — Byzantine nodes sit at the low indices) from
      25% to 60% of the run, healing with all held traffic released.
      n - f >= 2f+1 nodes stay connected, so the majority side keeps
      committing while the minority is dark.
    """
    if sc.wan == "lan":
        return None
    if sc.wan == "wan":
        return WanTopology(
            default=LinkPlan(
                rtt_s=0.02, jitter_s=0.004, drop=0.005, duplicate=0.01
            )
        )
    cfg_f = (sc.n - 1) // 3
    partitions: Tuple[Partition, ...] = ()
    if sc.wan == "partition":
        m = max(1, cfg_f)
        partitions = (
            Partition(
                start_s=0.25 * duration,
                heal_s=0.60 * duration,
                groups=(
                    tuple(range(sc.n - m)),
                    tuple(range(sc.n - m, sc.n)),
                ),
            ),
        )
    return WanTopology.regions(
        sc.n, k=min(4, sc.n), partitions=partitions
    )


def _coin_factory(kind: str, n: int, f: int):
    """round_robin -> None (the Config default); threshold_bls -> real
    (f+1)-of-n BLS coins sharing one set of share/sigma books (the bench
    idiom): share SIGNING stays per-process and real, but each wave's
    aggregation + bad-share recovery runs once for the cluster instead
    of once per process — pure-Python pairings are too slow to repeat
    n times per wave in a scenario sweep."""
    if kind != "threshold_bls":
        return None
    from dag_rider_tpu.consensus.coin import ThresholdCoin
    from dag_rider_tpu.crypto import threshold as th

    keys = th.ThresholdKeys.generate(n, f + 1)
    oracle = ThresholdCoin(keys, 0, n)

    def factory(i: int):
        c = ThresholdCoin(keys, i, n)
        c._shares = oracle._shares
        c._sigma = oracle._sigma
        c._tried_at = oracle._tried_at
        c.prune_below = lambda wave: None  # shared books: nobody prunes
        return c

    return factory


def run_scenario(sc: Scenario) -> dict:
    """Run one scenario end to end and audit every invariant.

    Raises :class:`~dag_rider_tpu.consensus.invariants.InvariantViolation`
    (online, at the offending delivery, or in the post-run audit) if the
    honest cluster ever breaks agreement, commits an equivocation, loses
    an accepted transaction, or stalls below the liveness floor. Returns
    the report dict on success."""
    cfg = Config(
        n=sc.n,
        propose_empty=True,
        # None defers to the DAGRIDER_LANES env default (tier1-lanes CI
        # runs the whole legacy matrix with lanes on; 32-byte blocks
        # stay inline there by the batch-size floor)
        lanes=True if sc.resolved_lanes() else None,
        epoch=True if sc.resolved_epoch() else False,
        epoch_waves=sc.epoch_waves,
        # virtual-time lockstep: wall-clock flood control off
        sync_request_cooldown_s=0.0,
        sync_serve_cooldown_s=0.0,
    )
    nbyz = 0
    if sc.adversary is not None:
        nbyz = cfg.f if sc.byzantine is None else sc.byzantine
        nbyz = max(0, min(nbyz, cfg.f))
    byz = tuple(range(nbyz))  # low indices: see module docstring
    behaviors = {
        i: make_behavior(sc.adversary, seed=sc.seed + 1000 + i)
        for i in byz
    }

    cycles = sc.resolved_cycles()
    topo = build_topology(sc, duration=cycles * sc.dt)
    tp = FaultyTransport(FaultPlan(seed=sc.seed), topology=topo)

    def process_factory(pcfg, i, ptp, **kwargs):
        if i in behaviors:
            return ByzantineProcess(
                pcfg, i, ptp, behavior=behaviors[i], **kwargs
            )
        from dag_rider_tpu.consensus.process import Process

        return Process(pcfg, i, ptp, **kwargs)

    sim = Simulation(
        cfg,
        transport=tp,
        coin_factory=_coin_factory(sc.coin_kind(), cfg.n, cfg.f),
        rbc=sc.resolved_rbc(),
        process_factory=process_factory,
    )
    monitor = sim.attach_invariant_monitor(exclude=byz)

    honest = [i for i in range(cfg.n) if i not in set(byz)]
    accepted: set = set()
    # Lane scenarios pad past the batch floor so every block actually
    # takes the lane path; everything else keeps the 32-byte legacy shape.
    pad = 2 * cfg.lane_batch_bytes if sc.resolved_lanes() else 32
    for i in honest:
        for k in range(sc.blocks_per_process):
            tx = f"s{sc.seed}-p{i}-b{k}".encode().ljust(pad, b".")
            accepted.add(tx)
            sim.processes[i].submit(Block((tx,)))
    if sc.resolved_epoch():
        # one committed rotate op -> a deterministic boundary crosses
        # mid-run; the op itself is an accepted tx, so zero-loss also
        # proves control traffic survives the adversary
        from dag_rider_tpu.core.codec import encode_epoch_op
        from dag_rider_tpu.core.types import EpochOp

        op = encode_epoch_op(EpochOp("rotate", 0, sc.seed, b""))
        accepted.add(op)
        sim.processes[honest[0]].submit(Block((op,)))
    if sc.resolved_lanes():
        # Byzantine lane workers only misbehave on their OWN publishes
        # (withhold their own batches / garble their acks), so feed them
        # blocks too. Excluded from `accepted`: zero-loss is an
        # honest-input property; recovery of Byzantine payloads is what
        # fetch-on-miss at honest delivery proves.
        for i in byz:
            for k in range(sc.blocks_per_process):
                tx = f"s{sc.seed}-byz{i}-b{k}".encode().ljust(pad, b"!")
                sim.processes[i].submit(Block((tx,)))

    # Per-cycle pump budget: ~a round's worth of deliveries. Bracha
    # multiplies every VAL by ~2n (echo + ready fan-outs), so RBC runs
    # need 2n x the budget — starving them turns latency into a sync
    # churn spiral (serves re-enter RBC and eat the whole budget).
    chunk = 2 * cfg.n * cfg.n * (2 * cfg.n if sc.resolved_rbc() else 1)
    for _ in range(cycles):
        if sim.run(max_messages=chunk) == 0:
            # Idle tick: the pump steps each process exactly ONCE when
            # the queue is empty, but an idle cluster is exactly where
            # sync patience must accrue (withholding wedges, post-
            # partition catch-up). One step per cycle makes recovery
            # glacial at n=32 — grant a burst of silent steps so a
            # patience window fits inside a couple of cycles.
            for _ in range(cfg.sync_patience or 4):
                sim.run(max_messages=chunk)
        tp.advance(sc.dt)
    # drain: release everything in flight (partition holds included) and
    # give laggards pump budget to catch up past the liveness floor
    for _ in range(6):
        tp.flush_delayed()
        sim.run(max_messages=2 * chunk)

    # Post-hoc audits raise InvariantViolation directly (no delivery
    # callback to hook); route them through the event stream so the
    # flight recorder — when tracing is on — dumps its last-N ring and
    # metrics snapshots before the exception propagates. The ONLINE
    # monitor needs no such wrapper: it emits invariant_violation at the
    # offending delivery, which the flight sink auto-dumps on.
    try:
        logs = {i: inv.delivery_records(sim.deliveries[i]) for i in honest}
        inv.check_agreement(logs)
        inv.check_commit_uniqueness(logs)

        retained: set = set()
        for i in honest:
            p = sim.processes[i]
            for b in p.blocks_to_propose:
                retained.update(b.transactions)
            for v in p.dag.vertices.values():
                b = v.block
                if p.lanes is not None:
                    # undelivered carrier vertices retain their payload
                    # through the lane store; a local miss (withheld
                    # batch not yet fetched) falls back to the carrier
                    # ref — some other honest holder retains the bytes
                    b = p.lanes.peek_block(b) or b
                retained.update(b.transactions)
        audit = inv.transaction_audit(
            accepted,
            (
                (tx for v in sim.deliveries[i] for tx in v.block.transactions)
                for i in honest
            ),
            retained,
        )
        inv.check_zero_loss(audit)

        decided = {i: sim.processes[i].decided_wave for i in honest}
        inv.check_liveness(
            decided, min_max=sc.min_waves, min_each=sc.min_each
        )
    except inv.InvariantViolation as e:
        if sim.log.enabled:
            sim.log.event(
                "invariant_violation",
                view="posthoc",
                kind="audit",
                detail=str(e)[:500],
            )
        raise

    def _counter(name: str) -> int:
        return sum(
            sim.processes[i].metrics.counters.get(name, 0) for i in honest
        )

    behavior_stats = {"mutated": 0, "withheld": 0, "extra_sent": 0}
    for b in behaviors.values():
        for k, v in b.stats.items():
            behavior_stats[k] = behavior_stats.get(k, 0) + v
    flight_dumps = (
        [str(p) for p in sim.flight.dumps] if sim.flight is not None else []
    )
    return {
        "name": sc.name,
        "n": cfg.n,
        "f": cfg.f,
        "byzantine": list(byz),
        "adversary": sc.adversary,
        "wan": sc.wan,
        "rbc": sc.resolved_rbc(),
        "coin": sc.coin_kind(),
        "seed": sc.seed,
        "cycles": cycles,
        "rounds": max(sim.processes[i].round for i in honest),
        "decided_waves": {
            "min": min(decided.values()),
            "max": max(decided.values()),
        },
        "delivered": {
            "min": min(len(logs[i]) for i in honest),
            "max": max(len(logs[i]) for i in honest),
        },
        "audit": audit,
        # detection / containment counters — callers assert on these to
        # prove the attack was not vacuous
        "equivocations_detected": _counter("equivocations_detected"),
        "edge_rejects": _counter("msgs_rejected_edges"),
        "sync_requested": _counter("sync_requested"),
        "sync_served": _counter("sync_served"),
        "coin_filtered": sum(
            getattr(sim.processes[i].coin, "filtered", 0)
            for i in range(cfg.n)
        ),
        "lanes": bool(cfg.lanes),
        "epoch": bool(cfg.epoch),
        "epoch_boundaries": _counter("epoch_boundaries"),
        "epoch_min": (
            min(
                sim.processes[i].metrics.counters.get("epoch_current", 0)
                for i in honest
            )
            if cfg.epoch
            else 0
        ),
        "epoch_stale_rejected": _counter("epoch_stale_rejected"),
        "lane_batches_certified": _counter("lane_batches_certified"),
        "lane_fetch_misses": _counter("lane_fetch_misses"),
        "lane_publish_degraded": _counter("lane_publish_degraded"),
        "lane_acks_rejected": _counter("lane_acks_rejected"),
        "behavior": behavior_stats,
        "transport": dict(tp.stats),
        "monitor": monitor.stats(),
        "flight_dumps": flight_dumps,
        "invariants": {
            "agreement": True,
            "commit_uniqueness": True,
            "zero_loss": True,
            "liveness": True,
        },
    }


def default_matrix(
    n: int = 4, seed: int = 0, cycles: Optional[int] = None
) -> List[Scenario]:
    """The CI sweep: every adversary class on LAN, a clean WAN + a clean
    partition-then-heal run, and equivocation under geo regions (where
    jitter forces the RBC stage to earn its keep)."""
    mk = lambda **kw: Scenario(n=n, seed=seed, cycles=cycles, **kw)  # noqa: E731
    return [
        mk(),
        mk(wan="partition"),
        mk(adversary="equivocate"),
        mk(adversary="equivocate_split"),
        mk(adversary="withhold"),
        mk(adversary="invalid_edges"),
        mk(adversary="garbage_coin"),
        mk(adversary="lane_withhold"),
        mk(adversary="lane_garbage_ack"),
        mk(adversary="equivocate", wan="regions"),
        mk(adversary="stale_epoch"),
        # straggler-join: the honest tail is dark while the boundary
        # commits; on heal it must sync across the epoch (the sync /
        # sync_nack exemption from the stale gate is what lets a
        # behind-the-epoch node discover it is behind at all)
        mk(name="epoch_straggler", epoch=True, wan="partition"),
    ]


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="Byzantine x WAN scenario runner (checked invariants)"
    )
    ap.add_argument("--n", type=int, default=4)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--cycles", type=int, default=None)
    ap.add_argument(
        "--adversary", choices=ADVERSARIES, default=None
    )
    ap.add_argument("--wan", choices=WAN_PROFILES, default="lan")
    ap.add_argument(
        "--epoch",
        action="store_true",
        help="force epoch reconfiguration on (a rotate op is injected)",
    )
    ap.add_argument(
        "--matrix",
        action="store_true",
        help="run the default scenario sweep instead of one scenario",
    )
    args = ap.parse_args(argv)

    if args.matrix:
        scenarios = default_matrix(
            n=args.n, seed=args.seed, cycles=args.cycles
        )
    else:
        scenarios = [
            Scenario(
                n=args.n,
                seed=args.seed,
                cycles=args.cycles,
                adversary=args.adversary,
                wan=args.wan,
                epoch=True if args.epoch else None,
            )
        ]
    reports = []
    for sc in scenarios:
        print(f"# {sc.name} ...", file=sys.stderr, flush=True)
        reports.append(run_scenario(sc))
    print(json.dumps(reports, indent=2))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
