"""Reusable DAG-Rider protocol invariants (safety + liveness checkers).

Following "Reusable Formal Verification of DAG-based Consensus Protocols"
(arXiv:2407.02167), the paper's correctness properties are encoded ONCE and
asserted under every scenario — clean runs, chaos runs, and the Byzantine
adversary suite (consensus/adversary.py + consensus/scenarios.py) all go
through the same four checkers:

- **agreement** (:func:`check_agreement`): honest commit logs are
  prefix-consistent — compared at *digest* level, so an admitted
  equivocation cannot masquerade as agreement.
- **total order / no-equivocation-commit**
  (:func:`check_commit_uniqueness`): at most one payload per
  (round, source) slot is ever a_delivered, anywhere, and no honest view
  delivers a slot twice.
- **validity / zero loss** (:func:`transaction_audit` +
  :func:`check_zero_loss`): every accepted client transaction is
  delivered or still retained (queued/staged/in-DAG) — never silently
  dropped.
- **bounded liveness** (:func:`check_liveness`): waves keep committing
  while <= f nodes misbehave.

Each property is usable two ways: as a *post-hoc auditor* over recorded
delivery logs (the functions below; ``Simulation.check_agreement``
delegates here) and as an *online assertion hook*
(:class:`InvariantMonitor`, attached to a live ``Simulation`` via
``Simulation.attach_invariant_monitor``) that raises at the exact
delivery that violates safety instead of after the run.

All violations raise :class:`InvariantViolation`, an ``AssertionError``
subclass — existing tests that ``pytest.raises(AssertionError)`` on the
old one-off checks keep passing unchanged.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from dag_rider_tpu.utils.slog import NOOP, EventLog

#: one delivery record: (round, source, payload digest)
Record = Tuple[int, int, bytes]


class InvariantViolation(AssertionError):
    """A checked protocol property does not hold."""


def delivery_records(deliveries: Iterable) -> List[Record]:
    """Project a_delivered vertices onto comparable (round, source,
    digest) records — identity AND content, so equivocations differ."""
    return [(v.id.round, v.id.source, v.digest()) for v in deliveries]


def check_agreement(logs: Dict[int, Sequence[Record]]) -> None:
    """Agreement: every pair of honest logs is prefix-consistent (one may
    lag the other, but the common prefix must match record-for-record).
    All pairs are compared — a lagging view must not mask divergence
    between two others. ``logs`` maps process index -> delivery records;
    the caller chooses the honest subset."""
    idxs = sorted(logs)
    for ai, i in enumerate(idxs):
        for j in idxs[ai + 1 :]:
            a, b = logs[i], logs[j]
            k = min(len(a), len(b))
            if a[:k] != b[:k]:
                diverge = next(x for x in range(k) if a[x] != b[x])
                raise InvariantViolation(
                    f"order divergence between p{i} and p{j} at "
                    f"position {diverge}: {a[diverge]} vs {b[diverge]}"
                )


def check_commit_uniqueness(logs: Dict[int, Sequence[Record]]) -> None:
    """Total order / no-equivocation-commit: across ALL views, at most
    one digest is ever delivered for a (round, source) slot, and within
    one view no slot is delivered twice. Stronger than prefix agreement
    alone: two views that deliver conflicting payloads for a slot at
    *different* log positions pass the pairwise prefix check until both
    logs grow long enough — this check catches the conflict as soon as
    both deliveries exist."""
    committed: Dict[Tuple[int, int], Tuple[int, bytes]] = {}
    for i in sorted(logs):
        seen_slots: set = set()
        for r, s, d in logs[i]:
            slot = (r, s)
            if slot in seen_slots:
                raise InvariantViolation(
                    f"p{i} delivered slot (round={r}, source={s}) twice"
                )
            seen_slots.add(slot)
            prev = committed.get(slot)
            if prev is None:
                committed[slot] = (i, d)
            elif prev[1] != d:
                raise InvariantViolation(
                    f"equivocation committed: slot (round={r}, source={s}) "
                    f"delivered as {prev[1]!r} at p{prev[0]} but {d!r} at p{i}"
                )


def check_rejoin_embedding(
    canonical: Sequence[Record],
    log: Sequence[Record],
    *,
    view: Optional[int] = None,
) -> None:
    """Commit-order agreement for a crash-recovered view.

    A node that died (kill -9), restored from checkpoint, and rejoined
    via snapshot sync does NOT re-deliver: its on-disk log is the
    pre-crash prefix followed by the post-rejoin segment, with a
    legitimate gap covering what the cluster committed while it was dead
    plus what the snapshot import skipped. Prefix comparison is the
    wrong invariant there; the right one is an **order-preserving
    embedding**: every slot the rejoiner delivered that a survivor also
    delivered must carry the same digest AND appear in the same relative
    order. (Slots beyond the canonical view's tail — shutdown skew — are
    exempt here; :func:`check_commit_uniqueness` still cross-checks
    their digests.)"""
    pos: Dict[Tuple[int, int], Tuple[int, bytes]] = {
        (r, s): (k, d) for k, (r, s, d) in enumerate(canonical)
    }
    who = "view" if view is None else f"p{view}"
    last = -1
    for k, (r, s, d) in enumerate(log):
        hit = pos.get((r, s))
        if hit is None:
            continue
        cpos, cd = hit
        if cd != d:
            raise InvariantViolation(
                f"rejoin divergence: {who} delivered slot (round={r}, "
                f"source={s}) as {d!r}, canonical has {cd!r}"
            )
        if cpos <= last:
            raise InvariantViolation(
                f"rejoin order violation: {who} log position {k} maps to "
                f"canonical position {cpos}, not after {last} — the "
                f"recovered segment reorders committed slots"
            )
        last = cpos


def transaction_audit(
    accepted: Iterable[bytes],
    delivered_by_view: Iterable[Iterable[bytes]],
    retained: Iterable[bytes] = (),
) -> dict:
    """Validity / zero-loss books: every accepted transaction must be
    delivered in some honest view or still retained (pending in a pool,
    queued for proposal, or sitting in a DAG vertex) — ``lost`` > 0 is
    a safety bug. ``duplicates`` is the max per-view count of
    transactions delivered more than once (total-order dedup failure).
    Pure accounting — :func:`check_zero_loss` raises on the result."""
    accepted_set = set(accepted)
    delivered: set = set()
    dup_max = 0
    for view in delivered_by_view:
        seen: Dict[bytes, int] = {}
        for tx in view:
            if tx in accepted_set:
                seen[tx] = seen.get(tx, 0) + 1
        delivered.update(seen)
        dup_max = max(dup_max, sum(1 for c in seen.values() if c > 1))
    retained_set = set(retained) & accepted_set
    lost = accepted_set - delivered - retained_set
    return {
        "accepted": len(accepted_set),
        "delivered": len(delivered),
        "in_flight": len(retained_set - delivered),
        "lost": len(lost),
        "duplicates": dup_max,
    }


def check_zero_loss(audit: dict) -> None:
    """Raise unless the :func:`transaction_audit` books balance."""
    if audit.get("lost", 0) > 0:
        raise InvariantViolation(f"accepted transactions lost: {audit}")
    if audit.get("duplicates", 0) > 0:
        raise InvariantViolation(f"duplicate deliveries: {audit}")


def check_liveness(
    decided_waves: Dict[int, int],
    *,
    min_max: int = 1,
    min_each: int = 0,
) -> None:
    """Bounded liveness with <= f misbehaving nodes: the honest cluster
    kept committing waves (``min_max`` for the most advanced honest
    view) and — after partitions heal and held traffic drains — no
    honest view is stuck before ``min_each``."""
    if not decided_waves:
        raise InvariantViolation("liveness check over zero honest views")
    top = max(decided_waves.values())
    if top < min_max:
        raise InvariantViolation(
            f"liveness: max honest decided wave {top} < required {min_max} "
            f"({decided_waves})"
        )
    for i, w in sorted(decided_waves.items()):
        if w < min_each:
            raise InvariantViolation(
                f"liveness: p{i} decided wave {w} < required {min_each} "
                f"({decided_waves})"
            )


class InvariantMonitor:
    """Online safety assertions over a live cluster's a_deliver stream.

    Wrap each honest process's delivery callback (``Simulation.
    attach_invariant_monitor`` does the plumbing) and every delivery is
    checked *as it happens* against:

    - prefix agreement with the canonical log (the union order built
      from the first view to deliver each position),
    - slot uniqueness within the view (no double delivery),
    - no-equivocation-commit across views (one digest per slot, ever).

    Violations raise :class:`InvariantViolation` from inside the
    delivery callback — the pump surfaces it at the exact message that
    broke safety, with the offending vertex in hand, instead of a
    post-mortem diff over full logs."""

    def __init__(
        self,
        n: int,
        exclude: Iterable[int] = (),
        log: EventLog = NOOP,
    ) -> None:
        self.n = n
        self.exclude = frozenset(exclude)
        #: obs seam: an "invariant_violation" event fires just before
        #: each raise — the flight recorder's trigger watch sees it and
        #: dumps the post-mortem even though the exception unwinds past
        #: any in-band handler
        self.log = log
        #: canonical record sequence: position k holds the first record
        #: any honest view delivered at log position k
        self._canon: List[Record] = []
        #: per-view next log position
        self._cursor: Dict[int, int] = {}
        #: slot -> (first view, digest)
        self._committed: Dict[Tuple[int, int], Tuple[int, bytes]] = {}
        self._seen_slots: Dict[int, set] = {}
        self.observed = 0

    def observe(self, view: int, vertex) -> None:
        """One a_delivery at ``view``. Raises on any safety violation."""
        if view in self.exclude:
            return
        rec: Record = (vertex.id.round, vertex.id.source, vertex.digest())
        slot = rec[:2]
        slots = self._seen_slots.setdefault(view, set())
        if slot in slots:
            raise self._violation(
                view,
                "double_delivery",
                f"p{view} delivered slot (round={rec[0]}, "
                f"source={rec[1]}) twice",
            )
        slots.add(slot)
        prev = self._committed.get(slot)
        if prev is None:
            self._committed[slot] = (view, rec[2])
        elif prev[1] != rec[2]:
            raise self._violation(
                view,
                "equivocation_commit",
                f"equivocation committed: slot (round={rec[0]}, "
                f"source={rec[1]}) delivered as {prev[1]!r} at "
                f"p{prev[0]} but {rec[2]!r} at p{view}",
            )
        pos = self._cursor.get(view, 0)
        if pos < len(self._canon):
            if self._canon[pos] != rec:
                raise self._violation(
                    view,
                    "order_divergence",
                    f"order divergence at p{view} position {pos}: "
                    f"{self._canon[pos]} vs {rec}",
                )
        else:
            self._canon.append(rec)
        self._cursor[view] = pos + 1
        self.observed += 1

    def _violation(
        self, view: int, kind: str, detail: str
    ) -> "InvariantViolation":
        self.log.event(
            "invariant_violation", view=view, kind=kind, detail=detail
        )
        return InvariantViolation(detail)

    def wrap(self, view: int, callback: Optional[callable]):
        """Compose the monitor in front of an existing a_deliver
        callback for ``view``."""

        def _deliver(v, _cb=callback, _i=view):
            self.observe(_i, v)
            if _cb is not None:
                _cb(v)

        return _deliver

    def stats(self) -> dict:
        return {
            "observed": self.observed,
            "canonical_len": len(self._canon),
            "slots_committed": len(self._committed),
        }
