"""Deterministic N-process cluster simulation.

The reference's multi-node story is "N Process instances sharing one
in-memory Transport" but no test ever exercises it (SURVEY.md §4). This
harness makes that story real and *deterministic*: processes are synchronous
state machines, the broker delivers FIFO, and a seeded scheduler can
interleave deliveries to explore asynchrony.
"""

from __future__ import annotations

import random
import time
from typing import Callable, List, Optional

from dag_rider_tpu import obs
from dag_rider_tpu.config import Config
from dag_rider_tpu.consensus.coin import CommonCoin
from dag_rider_tpu.consensus.process import Process
from dag_rider_tpu.core.types import Block, Vertex
from dag_rider_tpu.transport.base import Transport
from dag_rider_tpu.transport.memory import InMemoryTransport
from dag_rider_tpu.utils.metrics import Timer
from dag_rider_tpu.utils.slog import NOOP


class Simulation:
    """Build-and-run helper for an n-node in-process cluster."""

    def __init__(
        self,
        cfg: Config,
        *,
        transport: Optional[Transport] = None,
        coin_factory: Optional[Callable[[int], CommonCoin]] = None,
        verifier: Optional[str] = None,
        verifier_factory: Optional[Callable[[int], object]] = None,
        signer_factory: Optional[Callable[[int], object]] = None,
        cert: Optional[bool] = None,
        cert_msm: Optional[str] = None,
        cert_pair: Optional[str] = None,
        rbc: bool = False,
        process_factory: Optional[Callable[..., Process]] = None,
        log=None,
    ) -> None:
        self.cfg = cfg
        # Aggregated round certificates (ISSUE 9): defaults from the
        # config knob (DAGRIDER_CERT=agg); needs the named-verifier
        # registry to carry BLS keys, so cert mode requires verifier=.
        import dataclasses as _dc

        use_cert = cert if cert is not None else cfg.cert == "agg"
        if use_cert and cfg.cert != "agg":
            # the explicit ctor flag wins over the knob: processes gate
            # the fast path on cfg.cert, so the override must land there
            cfg = _dc.replace(cfg, cert="agg")
            self.cfg = cfg
        if use_cert and verifier is None and cert is None:
            # knob-driven cert (DAGRIDER_CERT=agg / Config(cert="agg"))
            # on a keyless sim: there is no named-verifier registry to
            # carry BLS keys, so fall back to the reference per-vertex
            # path instead of failing — the env knob must not break
            # suites whose sims never touch signatures (same availability
            # -over-fast-path rule as Byzantine-aggregator degradation).
            # An explicit cert=True ctor request still errors below.
            use_cert = False
            cfg = _dc.replace(cfg, cert="off")
            self.cfg = cfg
        cert_signers: Optional[list] = None
        self.cert_verifier = None
        if verifier is not None:
            if verifier_factory is not None:
                raise ValueError(
                    "pass verifier= or verifier_factory=, not both"
                )
            (
                verifier_factory,
                signer_factory,
                cert_signers,
                self.cert_verifier,
            ) = self._named_verifier(
                verifier,
                signer_factory,
                with_cert=use_cert,
                cert_msm=cert_msm,
                cert_pair=cert_pair,
            )
        elif use_cert:
            raise ValueError(
                'cert mode needs a named verifier (verifier="cpu"/"device"/'
                '"sharded") so the shared registry carries BLS keys'
            )
        self.transport = transport if transport is not None else InMemoryTransport()
        # Causal tracing (ISSUE 13, DAGRIDER_TRACE): when the caller
        # brought no log and the knob is on, install the obs bundle —
        # ring recorder + flight-recorder trigger watch tee'd into one
        # EventLog handed to every process. An explicit log= always
        # wins (tests capture events their own way).
        self.tracing = None
        self.recorder = None
        self.flight = None
        if log is None and obs.trace_enabled():
            self.tracing = obs.build_tracing()
            self.recorder = self.tracing.recorder
            self.flight = self.tracing.flight
            log = self.tracing.log
        self.log = log if log is not None else NOOP
        self.deliveries: List[List[Vertex]] = [[] for _ in range(cfg.n)]
        #: depth-K dispatch window over the shared verifier, built lazily
        #: by run() and kept across run() calls so the window/overlap
        #: stats accumulate for the bench's breakdown
        self._verify_pipe = None
        #: dedup identical signatures across sibling batches before the
        #: shared device dispatch (see run()); off = every copy is
        #: dispatched, the pre-round-5 behavior (kept for A/B tests)
        self.dedup = True
        self.processes: List[Process] = []
        # Per-index process constructor seam: the Byzantine scenario suite
        # (consensus/adversary.py) substitutes ByzantineProcess for the
        # faulty indices; same signature as Process.
        mk = process_factory if process_factory is not None else Process
        for i in range(cfg.n):
            sink = self.deliveries[i]
            tp: Transport = self.transport
            if rbc:
                # Bracha amplification stage per process: equivocating
                # senders cannot get divergent payloads admitted at honest
                # nodes (transport/rbc.py).
                from dag_rider_tpu.transport.rbc import RbcTransport

                tp = RbcTransport(self.transport, i, cfg.n, cfg.f)
            self.processes.append(
                mk(
                    cfg,
                    i,
                    tp,
                    coin=coin_factory(i) if coin_factory else None,
                    verifier=verifier_factory(i) if verifier_factory else None,
                    signer=signer_factory(i) if signer_factory else None,
                    cert_signer=cert_signers[i] if cert_signers else None,
                    cert_verifier=self.cert_verifier,
                    on_deliver=sink.append,
                    log=log if log is not None else NOOP,
                )
            )
        self._rbc = rbc
        # Eager optimistic delivery (ISSUE 16): each process's
        # speculative stream lands in its own sink, mirroring
        # self.deliveries — wired post-construction so the
        # process_factory seam (ByzantineProcess and friends) keeps the
        # plain Process signature. The finality suite asserts each sink
        # is a prefix-complete copy of the canonical one.
        self.eager_deliveries: List[List[Vertex]] = [
            [] for _ in range(cfg.n)
        ]
        if cfg.eager_deliver:
            for p, esink in zip(self.processes, self.eager_deliveries):
                if getattr(p, "on_deliver_early", None) is None:
                    p.on_deliver_early = esink.append
        # Dissemination lanes (ISSUE 17): one in-memory lane bus for the
        # cluster, a coordinator per process — wired post-construction
        # like the eager sinks (attach_lanes is the seam ByzantineProcess
        # overrides to bind lane behaviors). Keyed deployments reuse the
        # cert share machinery for signed availability acks; keyless
        # sims run unsigned.
        self.lane_bus = None
        if cfg.lanes:
            from dag_rider_tpu.lanes import LaneCoordinator
            from dag_rider_tpu.transport.lanebus import LaneBus

            self.lane_bus = LaneBus(cfg.n, workers=cfg.lane_workers)
            for i, p in enumerate(self.processes):
                p.attach_lanes(
                    LaneCoordinator(
                        cfg,
                        i,
                        self.lane_bus.endpoint(i),
                        cert_signer=cert_signers[i] if cert_signers else None,
                        cert_verifier=self.cert_verifier,
                        metrics=p.metrics,
                        log=p.log,
                    )
                )
        if self.flight is not None:
            # a dump captures every process's full counter state
            for p in self.processes:
                self.flight.add_metrics_source(
                    str(p.index), p.metrics.snapshot
                )
        # Grouped-pump registration (ISSUE 8): vector-path processes
        # accept whole VAL runs through on_messages — one handler call
        # per destination per run instead of one per message. Not under
        # RBC (the broker-level handlers there belong to the Bracha
        # stage, which must see every message singly) and only on
        # brokers that support it (InMemoryTransport natively; a
        # delay-free FaultyTransport forwards through its batch wrapper;
        # anything else keeps the per-message path).
        sub_many = getattr(self.transport, "subscribe_many", None)
        if not rbc and callable(sub_many):
            for p in self.processes:
                if getattr(p, "_vector", False):
                    # on_val_batch, not on_messages: pump_grouped only
                    # hands out pure VAL runs, so the kind re-scan is
                    # skipped (on_messages stays the network entry)
                    sub_many(p.index, p.on_val_batch)

    def _named_verifier(
        self, kind: str, signer_factory, *, with_cert: bool = False,
        cert_msm: Optional[str] = None, cert_pair: Optional[str] = None,
    ):
        """Convenience spelling of the common cluster shapes:
        ``verifier="cpu" | "device" | "sharded"`` builds one SHARED
        verifier (the coalesced-dispatch configuration Simulation.run
        optimizes for) over a deterministic committee registry, plus the
        matching signer factory when the caller didn't bring one — so a
        CPU-oracle run and a sharded run of the same Config verify the
        exact same signatures and their commit orders are comparable
        byte for byte. "sharded" takes its mesh from DAGRIDER_MESH (or
        the virtual-device fallback — parallel/mesh.mesh_from_env)."""
        from dag_rider_tpu.verifier.base import (
            CertSigner,
            KeyRegistry,
            VertexSigner,
        )

        cert_signers = None
        cert_verifier = None
        if with_cert:
            # same seed prefix as generate(): the ed25519 keys are
            # identical, so cert-on and cert-off runs verify the exact
            # same vertex signatures
            reg, seeds, bls_sks = KeyRegistry.generate_with_cert(self.cfg.n)
            cert_signers = [CertSigner(sk) for sk in bls_sks]
            from dag_rider_tpu.verifier.cert import CertVerifier

            cert_verifier = CertVerifier(
                reg, self.cfg.quorum, msm=cert_msm, pair=cert_pair
            )
        else:
            reg, seeds = KeyRegistry.generate(self.cfg.n)
        if kind == "cpu":
            from dag_rider_tpu.verifier.cpu import CPUVerifier

            shared = CPUVerifier(reg)
        elif kind == "device":
            from dag_rider_tpu.verifier.tpu import TPUVerifier

            shared = TPUVerifier(reg)
        elif kind == "sharded":
            from dag_rider_tpu.parallel.mesh import mesh_from_env
            from dag_rider_tpu.parallel.sharded_verifier import (
                ShardedTPUVerifier,
            )

            shared = ShardedTPUVerifier(reg, mesh_from_env())
        else:
            raise ValueError(f"unknown verifier {kind!r}")
        if signer_factory is None:
            signers = [VertexSigner(s) for s in seeds]
            signer_factory = lambda i: signers[i]  # noqa: E731
        return (lambda i: shared), signer_factory, cert_signers, cert_verifier

    @staticmethod
    def _dedup(flat):
        """Unique (digest, signature, source) entries + the inverse map
        fanning each flat index back to its unique slot. The accept bit
        is a pure function of the key, so every copy receives exactly
        the verdict it would have computed itself; equivocating or
        corrupted copies differ in digest/signature and stay separate."""
        uniq: List[Vertex] = []
        inv: List[int] = []
        seen: dict = {}
        for v in flat:
            key = (v.digest(), v.signature, v.id.source)
            j = seen.get(key)
            if j is None:
                j = seen[key] = len(uniq)
                uniq.append(v)
            inv.append(j)
        return uniq, inv

    def _pipeline_for(self, shared):
        """The depth-K window for the shared verifier (one per verifier,
        reused across run() calls). A caller that already wired a
        VerifierPipeline (node.py's device configuration) is used as-is
        — two nested windows would double-count the seam stats."""
        from dag_rider_tpu.verifier.pipeline import VerifierPipeline

        if isinstance(shared, VerifierPipeline):
            return shared
        if self._verify_pipe is None or self._verify_pipe.verifier is not shared:
            # warmup=False: the bench warms AOT shapes explicitly outside
            # its timed box; tests compile only what they exercise
            self._verify_pipe = VerifierPipeline(shared, warmup=False)
        return self._verify_pipe

    def submit_blocks(self, per_process: int, tx_bytes: int = 32) -> None:
        """Queue distinct client blocks at every process."""
        for p in self.processes:
            for k in range(per_process):
                p.submit(
                    Block((f"p{p.index}-blk{k}".encode().ljust(tx_bytes, b"."),))
                )

    def attach_mempools(self, mcfg=None, *, clock=None) -> list:
        """One Mempool front door per process (round 10): each process's
        a_deliver callback is wrapped so its mempool closes the
        submit→a_deliver latency books, and the mempool's gauges land in
        that process's metrics snapshot. Returns the mempools; drive
        load through them with mempool.loadgen.ClusterLoadDriver (or by
        hand: ``mp.submit(...)`` then feed ``mp.build_blocks()`` into
        ``processes[i].submit``)."""
        import time as _time

        from dag_rider_tpu.mempool import Mempool

        self.mempools = [
            Mempool(
                mcfg,
                clock=clock if clock is not None else _time.monotonic,
                metrics=p.metrics,
                log=p.log,
            )
            for p in self.processes
        ]
        for p, mp in zip(self.processes, self.mempools):
            prev = p.on_deliver

            def _deliver(v, prev=prev, mp=mp):
                if prev is not None:
                    prev(v)
                mp.observe_delivered(v.block)

            p.on_deliver = _deliver
        return self.mempools

    def run(self, max_messages: int = 100_000) -> int:
        """Start everyone, then pump to quiescence in *bursts*: deliver
        every queued message, then step each process once. Returns messages
        delivered. Deterministic for a given construction order.

        Burst delivery is the live-pipeline analog of the north star's
        "one DAG round per device dispatch": a process receives all its
        peers' round-r vertices in one burst, so the Verifier seam gets one
        round-sized batch instead of n-1 single-vertex dispatches.
        """
        pump = getattr(self.transport, "pump", None)
        if pump is None:
            raise TypeError("transport has no pump; drive it externally")
        # Grouped pump (ISSUE 8): byte-safe exactly when VAL delivery
        # has no transport side effects — every process on the vector
        # path (delivery only queues to the inbox; this run() defers
        # steps) and no RBC stage (there even a VAL delivery broadcasts
        # echoes at the broker layer, so cross-destination grouping
        # would reorder the queue tail).
        grouped = getattr(self.transport, "pump_grouped", None)
        if (
            callable(grouped)
            and not self._rbc
            and self.processes
            and all(getattr(p, "_vector", False) for p in self.processes)
        ):
            pump = grouped
            # Compress fan-out to one queue entry per broadcast; the
            # pump expands lazily with budget-exact sentinel splitting,
            # so boundaries match the eager queue entry-for-entry. Safe
            # here because the subscriber set was fixed at construction.
            if hasattr(self.transport, "fanout_sentinel"):
                self.transport.fanout_sentinel = True
        # Cross-process dispatch coalescing: when every process shares ONE
        # Verifier instance (the bench's device configuration), all n
        # processes' burst batches merge into a single padded device
        # dispatch per pump cycle (Verifier.verify_rounds) — n-1 fewer
        # fixed per-dispatch costs per cycle, identical accept bits.
        shared = self.processes[0].verifier if self.processes else None
        coalesce = (
            shared is not None
            and len(self.processes) > 1
            and all(p.verifier is shared for p in self.processes)
        )
        # Pipelined dispatch (round-3 VERDICT #2; depth-K window since
        # round 6): with an async-capable shared verifier, stream the
        # merged burst through a VerifierPipeline — fixed-bucket chunks
        # enter a depth-K in-flight window (chunk k+1's host prep
        # overlaps chunk k's device execution), the deferred delivery
        # walks run after the last dispatch while the tail executes (the
        # one slice of host work with no causal dependency on the
        # in-flight masks — everything else in the cycle is downstream
        # of them), and masks resolve FIFO. Every cycle drains the
        # window before masks are applied, so admission timing — and the
        # commit order downstream of it — is byte-identical to the
        # synchronous path.
        pipelined = (
            coalesce
            and callable(getattr(shared, "dispatch_batch", None))
            and callable(getattr(shared, "resolve_batch", None))
            and getattr(shared, "pipeline_enabled", True)
        )
        pipe = self._pipeline_for(shared) if pipelined else None
        for p in self.processes:
            p.defer_steps = True
            p.defer_delivery = pipelined
        delivered = 0
        pump_wall = 0.0
        try:
            for p in self.processes:
                p.start()
            while True:
                t0 = time.perf_counter()
                got = pump(max_messages - delivered)
                cycle_host = time.perf_counter() - t0
                pump_wall += cycle_host
                if coalesce:
                    batches = [p.take_verify_batch() for p in self.processes]
                    if any(batches):
                        flat = [v for b in batches for v in b]
                        # Dedup identical (digest, signature, source)
                        # entries across the n sibling batches before
                        # they reach the device: a broadcast vertex
                        # appears in up to n-1 processes' batches, so a
                        # coalesced round burst carries n*(n-1) entries
                        # but only n unique signatures — a real cluster
                        # spreads those checks over n chips, and one
                        # chip simulating all n views should pay the
                        # unique work, not the fan-out. The accept bit
                        # is a pure function of the key, so every copy
                        # gets exactly the mask bit it would have
                        # computed (equivocating or corrupted copies
                        # differ in digest/signature and stay separate
                        # entries). Per-process metrics still count
                        # APPLIED signatures; the verifier's breakdown
                        # counts what the device actually dispatched.
                        if self.dedup:
                            uniq, inv = self._dedup(flat)
                        else:
                            uniq, inv = flat, []
                        if pipelined:

                            def _overlap():
                                # deferred delivery walks, overlapped
                                # with the in-flight tail
                                for p in self.processes:
                                    p.flush_deliveries()

                            umask = pipe.run_coalesced(
                                uniq, overlap=_overlap
                            )
                            # seam wall time excludes the overlapped
                            # delivery flush (flush_deliveries already
                            # observes it into the wave-commit metric —
                            # charging it here too would double-count);
                            # the pipeline books its resolve waits into
                            # the verifier's cumulative breakdown itself.
                            # NOTE (ADVICE r5 #1): with the window open,
                            # the resolve waits the pipeline books as
                            # device time are a LOWER BOUND — device
                            # execution that completes under the flush
                            # window (or under later chunks' host prep)
                            # never blocks resolve and reads ~0 there,
                            # so verifier_breakdown's device_s
                            # understates true device occupancy on
                            # pipelined runs.
                            verify_s = pipe.last_seam_s
                        else:
                            with Timer() as t:
                                # chunked, synchronous (verify_rounds
                                # splits uniq at the fixed bucket; a
                                # pipeline_enabled=False verifier keeps
                                # its streaming window at depth 1)
                                umask = [
                                    m
                                    for ms in shared.verify_rounds([uniq])
                                    for m in ms
                                ]
                            verify_s = t.seconds
                        if self.log.enabled:
                            self.log.event(
                                "phase_verify",
                                dur_s=verify_s,
                                batch=len(flat),
                            )
                        mask = [umask[j] for j in inv] if inv else umask
                        # Attribute the merged dispatch time size-
                        # proportionally and skip empty batches — charging
                        # every process the full wall time would corrupt
                        # per-process sigs_per_sec / p50 metrics. The
                        # window gauges fan out the same way.
                        total = len(flat)
                        pos = 0
                        # latest host-prep engine gauges, fanned out to
                        # every participating process below
                        ps = (
                            shared.prep_stats()
                            if callable(getattr(shared, "prep_stats", None))
                            else None
                        )
                        # round-9 resilience gauges: from the window when
                        # pipelined, else from the shared verifier itself
                        # (a ResilientVerifier ladder takes the sync
                        # verify_rounds path — its pipelining lives inside
                        # the device tier). Fanned out when the stack IS
                        # a ladder (zeros are meaningful there) or once
                        # any fault was actually absorbed — a clean
                        # non-resilient run keeps its snapshot unchanged.
                        rs_fn = getattr(
                            pipe if pipelined else shared,
                            "resilience_stats",
                            None,
                        )
                        rs = rs_fn() if callable(rs_fn) else None
                        if rs is not None and not (
                            hasattr(shared, "tier_health")
                            or rs.get("retries")
                            or rs.get("fallbacks")
                            or rs.get("poisoned_windows")
                            or rs.get("quarantined")
                            or rs.get("sidecar_rpc_failures")
                        ):
                            rs = None
                        for p, b in zip(self.processes, batches):
                            if b:
                                share = len(b) / total
                                p.apply_verify_mask(
                                    b,
                                    mask[pos : pos + len(b)],
                                    verify_s * share,
                                )
                                if self.dedup:
                                    # per-process verify timings are
                                    # AMORTIZED under the dedup'd shared
                                    # verifier: each process is charged
                                    # its size-proportional share of one
                                    # union dispatch, so the n series do
                                    # not sum to n independent verify
                                    # costs (ADVICE r5 #2)
                                    p.metrics.mark_verify_amortized()
                                if ps is not None:
                                    p.metrics.observe_prep(
                                        ps["workers"],
                                        ps["parallel_fraction"],
                                    )
                                if rs is not None:
                                    p.metrics.observe_resilience(
                                        rs.get("retries", 0),
                                        rs.get("fallback_tier", 0),
                                        rs.get("quarantined", 0),
                                        sidecar_health=rs.get(
                                            "sidecar_health"
                                        ),
                                        rpc_failures=rs.get(
                                            "sidecar_rpc_failures", 0
                                        ),
                                    )
                                if pipelined:
                                    p.metrics.observe_verify_queue_depth(
                                        pipe.last_max_depth
                                    )
                                    p.metrics.observe_verify_overlap(
                                        pipe.last_wait_s * share,
                                        verify_s * share,
                                    )
                                if getattr(shared, "mesh_devices", 0):
                                    # mesh-sharded dispatch: how evenly
                                    # the cycle's last chunk filled the
                                    # shards (ShardedTPUVerifier gauge)
                                    p.metrics.observe_shard_imbalance(
                                        shared.last_shard_imbalance
                                    )
                                pos += len(b)
                            # empty batches advance nothing
                t0 = time.perf_counter()
                for p in self.processes:
                    p.step()
                step_wall = time.perf_counter() - t0
                pump_wall += step_wall
                cycle_host += step_wall
                if self.log.enabled:
                    # per-cycle host-pump phase span (delivery + steps)
                    self.log.event(
                        "phase_pump", dur_s=cycle_host, msgs=got
                    )
                if got == 0 or delivered + got >= max_messages:
                    delivered += got
                    break
                delivered += got
        finally:
            for p in self.processes:
                p.defer_steps = False
                if pipelined:
                    p.flush_deliveries()
                    p.defer_delivery = False
            # chaos observability: a FaultyTransport's injected-fault
            # counters land in every process's snapshot next to the
            # verifier resilience gauges
            tstats = getattr(self.transport, "stats", None)
            if isinstance(tstats, dict):
                for p in self.processes:
                    p.metrics.observe_transport_faults(tstats)
            # Host-pump accounting (ISSUE 8): CLUSTER-level delivered
            # messages and pump+step wall seconds, mirrored to every
            # process (same convention as the fault stats) — so
            # pump_msgs_per_s reads cluster throughput; the per-round
            # gauge divides by each process's own rounds_advanced.
            if delivered:
                for p in self.processes:
                    p.metrics.observe_pump(
                        delivered,
                        pump_wall,
                        "vector"
                        if getattr(p, "_vector", False)
                        else "scalar",
                    )
        return delivered

    # -- assertions for tests ---------------------------------------------

    def delivered_ids(self, i: int) -> List:
        return [v.id for v in self.deliveries[i]]

    def check_agreement(self, exclude: tuple = ()) -> None:
        """Total order safety: every pair of processes delivered consistent
        prefixes (one may lag the other). All pairs are compared — a lagging
        p0 must not mask divergence between other processes.

        Compares delivered *digests*, not just vertex ids: two processes
        that delivered the same (round, source) slots but with different
        payloads (an admitted equivocation) must fail this check (round-1
        VERDICT missing #6).

        ``exclude`` drops Byzantine indices from the comparison: the BFT
        agreement property covers HONEST processes only — an unsigned
        equivocator's own log legitimately diverges from the honest
        quorum's RBC-agreed version of its vertex (with signatures the
        mutated copies fail verification at honest nodes instead, and
        the full check passes — see test_full_stack). Default compares
        everyone, which is the right check whenever no process is
        deliberately faulty.

        Delegates to the reusable checker in consensus/invariants.py
        (raises InvariantViolation, an AssertionError subclass)."""
        from dag_rider_tpu.consensus.invariants import (
            check_agreement,
            delivery_records,
        )

        excluded = set(exclude)
        logs = {
            i: delivery_records(self.deliveries[i])
            for i in range(self.cfg.n)
            if i not in excluded
        }
        check_agreement(logs)

    def attach_invariant_monitor(self, exclude: tuple = ()):
        """Online safety assertions (consensus/invariants.py): wrap every
        non-excluded process's a_deliver callback in an InvariantMonitor
        so agreement / commit-uniqueness violations raise at the exact
        delivery that breaks them, not in a post-run audit. Attach BEFORE
        running; returns the monitor."""
        from dag_rider_tpu.consensus.invariants import InvariantMonitor

        mon = InvariantMonitor(self.cfg.n, exclude=exclude, log=self.log)
        for p in self.processes:
            if p.index in mon.exclude:
                continue
            p.on_deliver = mon.wrap(p.index, p.on_deliver)
        return mon


class RandomizedScheduler:
    """Seeded adversarial-ish scheduler: delivers queued messages in random
    order by pumping the broker after shuffling its queue. Used by
    property tests over message interleavings (SURVEY.md §5 race-detection
    build item)."""

    def __init__(self, transport: InMemoryTransport, seed: int) -> None:
        self.transport = transport
        self.rng = random.Random(seed)

    def run(self, max_messages: int = 100_000) -> int:
        delivered = 0
        while delivered < max_messages:
            items = self.transport.drain_pending()
            if not items:
                break
            self.rng.shuffle(items)
            self.transport.requeue(items)
            if not self.transport.pump_one():
                break
            delivered += 1
        return delivered
