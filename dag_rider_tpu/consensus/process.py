"""The DAG-Rider process: Algorithms 1-3 of the paper, de-bugged.

This is the host-side consensus state machine — the counterpart of the
reference's ``Process`` (``process/process.go``), implementing the *paper
semantics* the reference quotes in its comments (Alg. 2 at
``process.go:189-199, 271-275, 300-302``; Alg. 3 at ``process.go:315-325,
358-361``; Alg. 1 ordering at ``process.go:405-411``) while fixing the
reference's defects (SURVEY.md §8):

- D2: genesis round 0 is seeded with one vertex per source (a "predefined
  set"), not n copies of the caller's own id.
- D3: round advancement lives *inside* the progress loop, not after an
  infinite loop; the machine is event-driven (``on_message``/``step``), not
  a busy-spin.
- D4: state mutation is real (no value-receiver copies to lose updates).
- D5: ``order_vertices`` is actually invoked by the commit rule.
- D6: delivery is an ``a_deliver`` client callback, not a re-broadcast into
  the consensus transport.
- D7: a public :meth:`submit` API feeds ``blocks_to_propose`` (and
  ``propose_empty`` keeps liveness when clients are idle).
- D8: the delivered-set dedup actually skips delivered vertices.
- D9: the common coin is pluggable; the threshold-BLS coin replaces the
  constant stub.
- D10: vertices are signature-checked (via the batched Verifier seam) and
  message stamps are cross-checked against the signed vertex id before any
  state changes.

Concurrency model: the process is a *synchronous* state machine — all
methods run on the caller's thread and delivery order is whatever the
Transport pump chooses. This makes N-process simulations deterministic and
replayable; threading (if any) lives in the Transport, exactly where the
process/network boundary sits in the reference (``process.go:186``).
"""

from __future__ import annotations

import time as _time
from collections import deque
from typing import Callable, Deque, Dict, List, Optional, Set

import numpy as np

from dag_rider_tpu.config import Config
from dag_rider_tpu.consensus.coin import CommonCoin, FixedCoin, RoundRobinCoin
from dag_rider_tpu.consensus.dag_state import DagState
from dag_rider_tpu.core.codec import EPOCH_MAGIC, encode_epoch_op
from dag_rider_tpu.core.stack import Stack
from dag_rider_tpu.core.types import (
    Block,
    BroadcastMessage,
    EpochOp,
    RoundCertificate,
    SpanCertificate,
    Vertex,
    VertexID,
)
from dag_rider_tpu.epoch.manager import (
    EpochManager,
    EpochTransition,
    derive_epoch_keys,
)
from dag_rider_tpu.obs import block_key
from dag_rider_tpu.transport.base import Transport, resolve_unicast
from dag_rider_tpu.utils.metrics import Metrics, Timer
from dag_rider_tpu.utils.slog import NOOP, EventLog

# a_deliver callback: (vertex) — the client-facing output of Algorithm 1.
DeliverCallback = Callable[[Vertex], None]


class Process:
    """One DAG-Rider participant."""

    def __init__(
        self,
        cfg: Config,
        index: int,
        transport: Transport,
        *,
        coin: Optional[CommonCoin] = None,
        verifier=None,
        signer=None,
        cert_signer=None,
        cert_verifier=None,
        on_deliver: Optional[DeliverCallback] = None,
        on_deliver_early: Optional[DeliverCallback] = None,
        log: EventLog = NOOP,
    ) -> None:
        if not 0 <= index < cfg.n:
            raise ValueError(f"index must be in [0, {cfg.n}), got {index}")
        self.cfg = cfg
        self.index = index
        self.transport = transport
        self.coin = coin if coin is not None else self._default_coin(cfg)
        self.verifier = verifier
        self.signer = signer
        self.cert_signer = cert_signer
        self.cert_verifier = cert_verifier
        self.on_deliver = on_deliver
        #: speculative a_deliver seam (ISSUE 16): with cfg.eager_deliver
        #: a decided wave's canonical chunk is surfaced here at DECISION
        #: time, ahead of the (possibly deferred) on_deliver flush. The
        #: stream is a prefix of the final order by construction;
        #: _order_vertices reconciles and treats divergence as an
        #: invariant violation.
        self.on_deliver_early = on_deliver_early
        # Structured event log (SURVEY §5 L5; the reference has 3 zap
        # Debug sites — here every state transition emits a typed event).
        # NOOP by default: one attribute test per call site.
        self.log = log.child(process=index) if log.enabled else log

        self.dag = DagState(cfg)
        # Genesis: the predefined round-0 vertex set, one per source (D2
        # fixed — the reference stamps every genesis vertex with the
        # caller's own id, process.go:43-49).
        for i in range(cfg.n):
            self.dag.insert(Vertex(id=VertexID(0, i)))

        self.round = 0
        #: round-batched pump (cfg.pump == "vector" / DAGRIDER_PUMP):
        #: VAL admission checks run batched at the top of :meth:`step`
        #: (_process_inbox) and the buffer drains whole round groups
        #: against the dense mirrors (_drain_buffer_vector). Scalar mode
        #: is the reference oracle; byte-identical commit order is the
        #: gate (tests/test_pump_vector.py).
        self._vector = cfg.pump == "vector"
        #: deferred VAL messages awaiting _process_inbox (vector mode
        #: only; control messages are never deferred).
        self._inbox: List[BroadcastMessage] = []
        self._buffer: List[Vertex] = []
        #: vector-mode buffer storage: round -> {source: vertex} in
        #: arrival order (dicts preserve insertion order; the source key
        #: doubles as the duplicate-membership probe — within one round a
        #: (round, source) collision IS a vid collision, and an int key
        #: skips the VertexID tuple hash the PROFILE round-12 flame chart
        #: charges ~0.5s of dict.get to).
        self._buffer_rounds: Dict[int, Dict[int, Vertex]] = {}
        #: scalar-mode buffer membership mirror; vector mode keys the
        #: round groups by vid instead and leaves this set empty.
        self._buffered_ids: Set[VertexID] = set()
        #: blocked-vertex memo for _drain_buffer's short-circuit; entries
        #: live exactly as long as the vertex sits in the buffer.
        self._blocked_on: Dict[VertexID, VertexID] = {}
        self._pending_verify: List[Vertex] = []
        self._pending_verify_ids: Set[VertexID] = set()
        self._waves_tried: Set[int] = set()
        #: entries are payload Blocks — or, when dissemination lanes are
        #: attached, LanePending handles whose in-flight publish
        #: materializes into a certified carrier block at proposal time
        #: (ISSUE 17); handles expose ``transactions`` so queue readers
        #: (checkpoint, audits, depth backpressure) need not care
        self.blocks_to_propose: Deque[Block] = deque()
        #: dissemination-lane coordinator, wired post-construction via
        #: attach_lanes when cfg.lanes is on (None = inline payloads,
        #: the byte-identity oracle)
        self.lanes = None
        self.decided_wave = 0
        self._pending_waves: Set[int] = set()
        self.delivered_log: List[VertexID] = []
        #: deliveries dropped from delivered_log by GC pruning (the log
        #: keeps only the live window when cfg.gc_depth is set)
        self.delivered_trimmed = 0
        #: dense bool[capacity, n] twin of ``delivered`` — lets the
        #: ordering pass diff a closure bitmap against delivered state in
        #: one vectorized op instead of per-slot set probes (the
        #: per-commit rescan of the whole history was ~25% of the 64-node
        #: host profile). Written only by _order_vertices; checkpoint
        #: restore re-derives it via _rebuild_delivered_mask.
        self._delivered_mask = np.zeros_like(self.dag.exists)
        self._stuck_steps = 0
        #: msgs_received watermark for backlog-aware sync patience — see
        #: _maybe_request_sync (a node still being fed is throttled, not
        #: partitioned)
        self._rx_at_patience = 0
        self._sync_last_request = float("-inf")
        #: round-robin cursor over peers for pull-based sync requests;
        #: start offset by our index so n stuck nodes don't all probe
        #: peer 0 in the same window
        self._sync_peer_rr = index + 1
        self._sync_last_serve: Dict[int, float] = {}  # requester -> mono
        #: responder -> GC floor from sync_nack replies; f+1 distinct
        #: floors above our round flip state_transfer_needed (the node
        #: runtime acts on it — Process has no transport-level RPC).
        self._horizon_nacks: Dict[int, int] = {}
        self.state_transfer_needed = False
        #: round lo of our most recent sync request — nacks are judged
        #: against the *requested window*, not just our round: a node
        #: whose round is ahead of peers' floors can still be wedged
        #: re-requesting pruned straggler rounds forever.
        self._sync_last_lo: Optional[int] = None
        #: responder -> highest nacked floor (monotone for honest
        #: responders; bounded at n entries). The (f+1)-th largest value
        #: is the highest floor at least one HONEST responder attests —
        #: rounds at/below it are finalized history nobody will serve.
        self._window_nacks: Dict[int, int] = {}
        #: f+1-attested peer GC floor (monotone max). It gates ONLY the
        #: sync-request targeting (_maybe_request_sync skips blockers
        #: at/below it — the endless re-request wedge this exists for).
        #: It deliberately does NOT touch admission: f+1 floors prove
        #: one honest peer pruned that history, not that every honest
        #: peer has — a lower-floor peer may still serve it, so
        #: dropping buffered vertices here could forfeit a recovery
        #: (and fork our delivered log from peers who did deliver
        #: them). Kept-but-unrequested vertices cost bounded memory and
        #: zero traffic; if the gap ever blocks real progress the node
        #: falls behind until the floors-above-round rule flips
        #: state_transfer_needed, the designed recovery.
        self._attested_floor = 0
        #: equivocation book, round -> n-slot digest list indexed by
        #: source (satellite of ISSUE 9: the vid-keyed dict was the
        #: hottest memo in the round-12 profile — a list index replaces
        #: the tuple hash). Trimmed with the GC floor like the dag.
        self._seen_digests: Dict[int, List[Optional[bytes]]] = {}
        # -- aggregated round certificates (ISSUE 9) -------------------
        #: cert fast path is live only when the knob, a verifier, and
        #: both cert-key seams are present; otherwise every field below
        #: stays empty and the per-vertex path is untouched.
        self._cert = (
            cfg.cert == "agg"
            and verifier is not None
            and cert_signer is not None
            and cert_verifier is not None
        )
        #: round -> {source: vertex} awaiting that round's certificate
        #: (non-aggregator rounds only)
        self._cert_pool: Dict[int, Dict[int, Vertex]] = {}
        #: aggregator-side: round -> {source: (digest, cert_sig)} of
        #: directly verified vertices, consumed by _maybe_assemble_certs
        self._cert_stash: Dict[int, Dict[int, tuple]] = {}
        #: rounds settled either way (cert applied or degraded) — later
        #: copies take the normal per-vertex path
        self._cert_done: Set[int] = set()
        #: rounds whose certificate we already assembled and gossiped
        self._certs_sent: Set[int] = set()
        #: round -> steps spent waiting on its certificate; exceeding
        #: cfg.cert_patience degrades the round to per-vertex verifies
        #: (a Byzantine aggregator can cost a round its fast path, never
        #: its liveness)
        self._cert_wait: Dict[int, int] = {}
        #: certificates received but not yet applied (application runs in
        #: step(), after _process_inbox, so a cert can never outrun the
        #: VALs it covers through the deferred-inbox path)
        self._pending_certs: List[RoundCertificate] = []
        # -- cert-of-certs overlay (ISSUE 12 tentpole 3) ---------------
        #: span width k; epoch e covers rounds e*k+1 .. (e+1)*k and its
        #: designated span aggregator is process e % n. 0 = off. Spans
        #: ride ON TOP of round certificates: a receiver never waits on
        #: one (liveness stays anchored on the per-round path), it only
        #: settles still-pending covered rounds with one combined check.
        self._span = int(cfg.cert_span or 0) if self._cert else 0
        #: span-aggregator side: epoch -> {round: verified cert} banked
        #: toward that epoch's cert-of-certs
        self._span_bank: Dict[int, Dict[int, RoundCertificate]] = {}
        #: epochs whose span we already assembled and gossiped
        self._spans_sent: Set[int] = set()
        #: epochs settled locally (span applied) or abandoned (a covered
        #: round degraded / bank went stale) — later spans are ignored
        self._span_done: Set[int] = set()
        #: epoch -> ticks a partial bank has been waiting; stale epochs
        #: abandon (the overlay is best-effort, certs keep flowing)
        self._span_wait: Dict[int, int] = {}
        #: spans received but not yet applied (same deferred application
        #: discipline as _pending_certs)
        self._pending_spans: List[SpanCertificate] = []
        # -- epoch reconfiguration (ISSUE 20) --------------------------
        #: None = static membership (the oracle path). NAMING NOTE: the
        #: span-certificate books above use "epoch" for their k-round
        #: aggregation groups — unrelated. Everything reconfiguration
        #: lives behind epoch_mgr / the ``epoch_*`` method prefix.
        self.epoch_mgr = (
            EpochManager(cfg.epoch_waves) if cfg.epoch else None
        )
        #: pending epoch-boundary GC floor (applied at the next
        #: maybe_prune, never mid-ordering — see _epoch_advance)
        self._epoch_gc_floor: Optional[int] = None
        self.metrics = Metrics()
        if self._cert:
            self.metrics.counters["cert_path_enabled"] = 1
            if self._span:
                self.metrics.counters["span_path_enabled"] = 1
        if self.epoch_mgr is not None:
            # visible-at-zero gauges, same discipline as the eager path:
            # "epoch 0, nothing rejected" must be distinguishable from
            # "epoch path absent" in snapshots
            self.metrics.counters["epoch_path_enabled"] = 1
            self.metrics.counters["epoch_current"] = 0
            self.metrics.counters["epoch_stale_rejected"] = 0
            #: high-water mark of live (unpruned) vertices — the
            #: flatness witness for epoch GC (ISSUE 20 satellite 2)
            self.metrics.counters["vertices_live_max"] = 0
        #: verified span certificates kept for snapshot attestation
        #: (ISSUE 20): span-epoch -> SpanCertificate, populated on both
        #: the aggregator and receiver sides, pruned with the GC floor
        #: but only below the snapshot base (the attestation must cover
        #: the window a joiner restores).
        self._span_chain: Dict[int, SpanCertificate] = {}
        self._started = False
        # Burst delivery (the north-star batching shape): when True,
        # ``on_message`` only queues — the driver (Simulation pump / net
        # inbox drain) delivers a whole burst, then calls :meth:`step`
        # once, so ``_drain_verify`` sees round-sized batches instead of
        # one dispatch per message (round-1 VERDICT weak #2).
        self.defer_steps = False
        # Deferred a_deliver (pipeline overlap): when True, _try_wave
        # commits waves immediately (decided_wave advances, protocol
        # progress is unaffected) but queues the ordering/delivery walk
        # for :meth:`flush_deliveries` — the only host work with no
        # causal dependency on an in-flight verify dispatch, so a driver
        # can run it while the device crunches the next batch. Safe to
        # defer: an admitted leader's entire causal history is already
        # present (buffer admission gate), so the closure is identical
        # whenever it runs, and FIFO flushing preserves delivery order.
        self.defer_delivery = False
        self._deferred_orders: Deque = deque()
        # -- pipelined waves + eager delivery (ISSUE 16) ---------------
        #: cfg.wave_pipeline: every undecided wave whose commit round
        #: holds a quorum is (re)attempted each step by
        #: _try_waves_pipelined instead of once at the 4-round boundary.
        self._pipelined_waves = bool(cfg.wave_pipeline)
        #: vertices dispatched through a hold-tail verifier window whose
        #: masks have not come back yet (FIFO = dispatch = resolve order)
        self._verify_owed: Deque[Vertex] = deque()
        #: waves whose boundary-equivalent attempt (round counter at or
        #: past the commit round) has been taken — the pipelined twin of
        #: the oracle's _waves_tried one-shot bookkeeping
        self._waves_spent: Set[int] = set()
        #: wave -> (round_size(r4), round_size(r1)) at the last early
        #: attempt; votes and leader presence are pure functions of
        #: those fills, so an unchanged pair means an unchanged verdict
        self._wave_try_memo: Dict[int, tuple] = {}
        #: cfg.eager_deliver: speculative delivery log + its own dense
        #: mask (the eager twin of delivered_log/_delivered_mask) and
        #: the reconciliation cursor _order_vertices advances
        self._eager = bool(cfg.eager_deliver)
        self.eager_log: List[VertexID] = []
        self._eager_cursor = 0
        self._eager_mask = (
            np.zeros_like(self.dag.exists) if self._eager else None
        )
        if self._eager:
            # visible-at-zero gauges: "0 mismatches" must be
            # distinguishable from "eager path absent" in snapshots
            self.metrics.counters["eager_rollbacks_expected_zero"] = 0
            self.metrics.counters["eager_delivered"] = 0
            # Cert-quorum optimism needs no extra wiring here: a
            # certificate applied in _apply_certificate admits its
            # round inside the same step() loop, so the pipelined wave
            # pass decides — and the eager surface fires — the moment
            # the round-certificate quorum forms. The CertVerifier's
            # on_certified seam (verifier/cert.py) is for SINGLE-owner
            # stacks (node.py); the simulator's verifier is shared.

        transport.subscribe(index, self.on_message)

    @staticmethod
    def _default_coin(cfg: Config) -> CommonCoin:
        if cfg.coin == "fixed":
            return FixedCoin(0)
        if cfg.coin == "round_robin":
            return RoundRobinCoin(cfg.n)
        raise ValueError(
            "threshold_bls coin must be constructed explicitly with keys"
        )

    @property
    def buffer(self) -> List[Vertex]:
        """Buffered vertices awaiting predecessors.

        Scalar mode stores a flat arrival-order list; vector mode stores
        per-round groups (the drain key) and flattens on demand —
        round-ascending, arrival order within a round — for external
        readers (checkpoint save, sync targeting, tests). The setter
        accepts a flat list either way (checkpoint restore assigns one).
        """
        if self._vector:
            out: List[Vertex] = []
            for r in sorted(self._buffer_rounds):
                out.extend(self._buffer_rounds[r].values())
            return out
        return self._buffer

    @buffer.setter
    def buffer(self, vs: List[Vertex]) -> None:
        if self._vector:
            groups: Dict[int, Dict[int, Vertex]] = {}
            for v in vs:
                groups.setdefault(v.id.round, {})[v.id.source] = v
            self._buffer_rounds = groups
        else:
            self._buffer = vs

    # ------------------------------------------------------------------
    # Client API (Algorithm 1 lines 1-4)
    # ------------------------------------------------------------------

    def submit(self, block: Block) -> None:
        """Enqueue a client block for proposal — the missing writer of the
        reference's ``blocksToPropose`` (D7, ``process.go:80``) — and kick
        the state machine: with ``propose_empty=False`` a quiescent cluster
        must be able to resume on submission alone.

        With dissemination lanes attached the block's payload starts its
        lane round-trip here, so the dissemination overlaps the
        submit→propose gap; the inline enqueue is the oracle (and the
        degradation target for any block a lane cannot certify)."""
        if self.lanes is not None:
            self._submit_via_lanes(block)
        else:
            self._submit_inline(block)

    def _submit_inline(self, block: Block) -> None:
        """The oracle path: the payload block itself rides the vertex."""
        self.blocks_to_propose.append(block)
        if self._started:
            self.step()

    def _submit_via_lanes(self, block: Block) -> None:
        """Lane path (ISSUE 17): start the payload publish on the lane
        workers and queue the pending handle in the block's submission
        slot — proposal-time materialization keeps the carrier in
        exactly the round the inline block would have taken, which is
        what makes lanes-vs-inline byte-identity provable. Blocks the
        lane refuses (undersized, magic-aliasing) ship inline."""
        if any(
            tx.startswith(EPOCH_MAGIC) for tx in block.transactions
        ):
            # Epoch control transactions (ISSUE 20) must ride the vertex
            # itself: the boundary scan reads delivered blocks, and a
            # lane carrier would hide the magic behind a payload ref
            # that stragglers resolve at different times.
            self._submit_inline(block)
            return
        pending = self.lanes.begin_publish(block)
        if pending is None:
            self._submit_inline(block)
            return
        self.blocks_to_propose.append(pending)
        if self._started:
            self.step()

    def attach_lanes(self, coordinator) -> None:
        """Wire a LaneCoordinator (post-construction, like the eager
        sink): subsequent submits disseminate payloads via lanes and
        deliveries resolve carrier refs back to payload bytes."""
        self.lanes = coordinator

    def start(self) -> None:
        """Begin participating: advance from the genesis round."""
        self._started = True
        self.step()

    # ------------------------------------------------------------------
    # r_deliver path (Algorithm 2 lines 1-4)
    # ------------------------------------------------------------------

    def on_message(self, msg: BroadcastMessage) -> None:
        """Reliable-broadcast delivery of a remote vertex.

        The reference trusts message stamps outright (D10,
        ``process.go:159-162``); here the stamps must match the (signed)
        vertex identity, and the signature is checked before the vertex can
        influence any state.
        """
        self.metrics.inc("msgs_received")
        if msg.kind != "val" or msg.vertex is None:
            self._on_control(msg)
            return
        if self.epoch_mgr is not None and msg.epoch < self.epoch_mgr.epoch:
            self._epoch_reject_stale(msg)
            return
        if self._vector:
            # Defer the admission checks to step(): nothing between
            # delivery and the next step reads the state those checks
            # write (the DAG only mutates inside step, and sync serving
            # reads the DAG, not the inbox), so running them batched at
            # the step boundary is observationally identical to running
            # them here — in FIFO order either way.
            self._inbox.append(msg)
            if not self.defer_steps:
                if self._started:
                    self.step()
                else:
                    # not started: run the checks now (scalar counters
                    # and pending/buffer state stay exactly in sync)
                    # without stepping
                    self._process_inbox()
            return
        v = msg.vertex
        if (
            v.id.round != msg.round
            or v.id.source != msg.sender
            or not 0 <= v.id.source < self.cfg.n
            or v.id.round < 1
        ):
            self.metrics.inc("msgs_rejected_stamp")
            self.log.event(
                "reject_stamp", round=msg.round, sender=msg.sender
            )
            return
        if v.id.round <= self.dag.base_round:
            # At/below the GC floor: retired everywhere, unadmittable
            # here — drop BEFORE digest/verify/coin-share observation, or
            # replayed old VALs would re-feed the books the prune just
            # retired and burn verify work (round-4 review; the RBC
            # stage's floor gate covers only RBC deployments).
            self.metrics.inc("msgs_below_gc_horizon")
            return
        pooled = self._cert_pool.get(v.id.round) if self._cert else None
        if (
            self.dag.present(v.id)
            or v.id in self._buffered_ids
            or v.id in self._pending_verify_ids
            or (pooled is not None and v.id.source in pooled)
        ):
            row = self._seen_digests.get(v.id.round)
            prev = row[v.id.source] if row is not None else None
            if prev is not None and prev != v.digest():
                # same (round, source), different content — equivocation.
                self.metrics.inc("equivocations_detected")
                self.log.event(
                    "equivocation", round=v.round, source=v.source
                )
            else:
                self.metrics.inc("msgs_duplicate")
            return
        if not self.edges_valid(v):
            self.metrics.inc("msgs_rejected_edges")
            self.log.event(
                "reject_edges",
                round=v.round,
                source=v.source,
                strong=len(v.strong_edges),
                weak=len(v.weak_edges),
            )
            return
        self._note_seen(v)
        if self.verifier is not None:
            if (
                self._cert
                and v.id.round % self.cfg.n != self.index
                and v.id.round not in self._cert_done
            ):
                # await this round's certificate instead of paying a
                # per-vertex verify; patience degrades us back if the
                # aggregator never delivers
                self._cert_pool.setdefault(v.id.round, {})[v.id.source] = v
            else:
                self._pending_verify.append(v)
                self._pending_verify_ids.add(v.id)
        else:
            self._admit_to_buffer(v)
        if self._started and not self.defer_steps:
            self.step()

    def _on_control(self, msg: BroadcastMessage) -> None:
        """Non-VAL dispatch, shared by both pump paths (the caller has
        already counted msgs_received)."""
        if (
            self.epoch_mgr is not None
            and msg.epoch < self.epoch_mgr.epoch
            and (msg.kind == "cert" or msg.kind == "cert_span")
        ):
            # Signed pre-rotation consensus traffic replayed after the
            # boundary: reject at the seam (ISSUE 20). sync/sync_nack
            # stay exempt — a straggler's sync probe is how it learns it
            # is behind and enters the state-transfer path.
            self._epoch_reject_stale(msg)
            return
        if msg.kind == "sync":
            self._serve_sync(msg)
        elif msg.kind == "sync_nack":
            self._on_sync_nack(msg)
        elif msg.kind == "cert":
            self._on_certificate(msg)
        elif msg.kind == "cert_span":
            self._on_span(msg)
        else:
            # RBC control traffic (echo/ready/fetch) is consumed by the
            # transport/rbc.py stage; a Process only eats vertex payloads.
            self.metrics.inc("msgs_ignored_kind")

    def on_messages(self, batch: List[BroadcastMessage]) -> None:
        """Batch delivery entry (transport ``pump_grouped``): one call
        per destination per pump chunk instead of one handler dispatch
        per message. Scalar mode degrades to the per-message path;
        vector mode queues VALs for the batched inbox checks and runs
        ONE step for the whole batch."""
        if not batch:
            return
        if not self._vector:
            for m in batch:
                self.on_message(m)
            return
        self.metrics.inc("msgs_received", len(batch))
        inbox = self._inbox
        for m in batch:
            if m.kind != "val" or m.vertex is None:
                # mixed batch (network codec frames): fall back to the
                # per-message split so controls dispatch in position
                for m2 in batch:
                    if m2.kind == "val" and m2.vertex is not None:
                        inbox.append(m2)
                    else:
                        self._on_control(m2)
                break
        else:
            # pure VAL run — one C-level extend
            inbox.extend(batch)
        if not self.defer_steps:
            if self._started:
                self.step()
            else:
                self._process_inbox()

    def on_val_batch(self, batch: List[BroadcastMessage]) -> None:
        """Grouped-pump fast entry (vector mode): the broker guarantees
        a pure VAL run (controls are delivered singly as barriers), so
        the batch goes straight to the inbox with no per-message kind
        scan. :meth:`on_messages` stays the kind-agnostic entry for
        codec-decoded network frames."""
        self.metrics.inc("msgs_received", len(batch))
        self._inbox.extend(batch)
        if not self.defer_steps:
            if self._started:
                self.step()
            else:
                self._process_inbox()

    def _process_inbox(self) -> None:
        """Run the deferred VAL admission checks (vector mode) — the
        exact scalar on_message sequence per message, in FIFO order,
        with the per-message constants hoisted and everything the
        broadcast shares across the n-1 sibling processes memoized on
        the message/vertex objects (stamp verdict, edge gate, digest).
        The body is deliberately inline — at n=256 one round is ~65k
        copies through this loop, and every helper call or re-probed
        attribute showed up as ~0.5 us x 65k x rounds in the profile."""
        inbox, self._inbox = self._inbox, []
        n = self.cfg.n
        gate_key = (n, self.cfg.quorum)
        wave_len = self.cfg.wave_length
        dag = self.dag
        base = dag.base_round  # nothing in this loop prunes
        exists = dag.exists
        n_rows = exists.shape[0]
        groups = self._buffer_rounds
        pending = self._pending_verify_ids
        seen = self._seen_digests
        metrics_inc = self.metrics.inc
        verifier = self.verifier
        observe_share = self.coin.observe_share
        cert_on = self._cert
        cert_pool = self._cert_pool
        cert_done = self._cert_done
        my_index = self.index
        cur_epoch = (
            self.epoch_mgr.epoch if self.epoch_mgr is not None else None
        )
        last_r = -1  # round-group cache: batches arrive in same-round runs
        grp: Optional[Dict[int, Vertex]] = None
        seen_row: Optional[List[Optional[bytes]]] = None
        exists_row: Optional[list] = None
        pool_row: Optional[Dict[int, Vertex]] = None
        pool_this = False
        for msg in inbox:
            if cur_epoch is not None and msg.epoch < cur_epoch:
                self._epoch_reject_stale(msg)
                continue
            v = msg.vertex
            ok = msg.__dict__.get("_stamp_ok")
            if ok is None or ok[0] != n:
                ok = (
                    n,
                    v.id.round == msg.round
                    and v.id.source == msg.sender
                    and 0 <= v.id.source < n
                    and v.id.round >= 1,
                )
                object.__setattr__(msg, "_stamp_ok", ok)
            if not ok[1]:
                metrics_inc("msgs_rejected_stamp")
                self.log.event(
                    "reject_stamp", round=msg.round, sender=msg.sender
                )
                continue
            vid = v.id
            r = vid.round
            if r <= base:
                metrics_inc("msgs_below_gc_horizon")
                continue
            if r != last_r:
                last_r = r
                grp = groups.get(r)
                # presence snapshot: nothing in this loop inserts into
                # the dag, so one .tolist() per round-run turns the
                # per-message VertexID dict probe into a C list index
                # (PROFILE round 12: those probes were ~0.5s of the
                # remaining 2.9s at n=256)
                rr = r - base
                exists_row = exists[rr].tolist() if rr < n_rows else None
                seen_row = seen.get(r)
                pool_row = cert_pool.get(r) if cert_on else None
                pool_this = (
                    cert_on and r % n != my_index and r not in cert_done
                )
            src = vid.source
            if (
                (exists_row is not None and exists_row[src])
                or (grp is not None and src in grp)
                or (pool_row is not None and src in pool_row)
                or (pending and vid in pending)
            ):
                prev = seen_row[src] if seen_row is not None else None
                if prev is not None and prev != v.digest():
                    metrics_inc("equivocations_detected")
                    self.log.event(
                        "equivocation", round=r, source=src
                    )
                else:
                    metrics_inc("msgs_duplicate")
                continue
            g = v.__dict__.get("_gate")
            if g is not None and g[0] == gate_key:
                valid = not g[1]
            else:
                valid = self.edges_valid(v)
            if not valid:
                metrics_inc("msgs_rejected_edges")
                self.log.event(
                    "reject_edges",
                    round=r,
                    source=vid.source,
                    strong=len(v.strong_edges),
                    weak=len(v.weak_edges),
                )
                continue
            if seen_row is None:
                seen_row = seen[r] = [None] * n
            seen_row[src] = v.__dict__.get("_digest") or v.digest()
            if verifier is not None:
                if pool_this:
                    if pool_row is None:
                        pool_row = cert_pool[r] = {}
                    pool_row[src] = v
                else:
                    self._pending_verify.append(v)
                    pending.add(vid)
            else:
                if grp is None:
                    grp = groups[r] = {}
                grp[src] = v
                cs = v.coin_share
                if cs is not None and r % wave_len == 0:
                    observe_share(r // wave_len, src, cs)

    def edges_valid(self, v: Vertex) -> bool:
        """The r_deliver admission gate: >= 2f+1 distinct strong edges
        (process.go:164-168), all targeting round-1, all sources in
        [0, n) — a Byzantine vertex must not be able to index outside the
        dense mirrors (negative sources would silently alias via numpy
        wraparound), and every downstream fancy-index (dag.insert,
        _drain_buffer) relies on this gate having run. Vectorized over
        the memoized edge arrays and memoized on the vertex: the result
        is a pure function of (vertex, n, quorum), so the n-1 sibling
        processes of an in-process cluster reuse it instead of
        re-scanning ~2f+1 edges each (round-4 host profile: this gate's
        per-edge loops were ~15 us/message)."""
        vr = v.id.round
        gate_key = (self.cfg.n, self.cfg.quorum)
        cached_gate = v.__dict__.get("_gate")
        if cached_gate is not None and cached_gate[0] == gate_key:
            return not cached_gate[1]
        sr, ss, wr, ws = v.edge_arrays()
        n_cfg = self.cfg.n
        bad_edges = bool(
            len(np.unique(ss)) < self.cfg.quorum
            or (sr != vr - 1).any()
            or (ss < 0).any()
            or (ss >= n_cfg).any()
            or (wr < 1).any()
            or (wr > vr - 2).any()
            or (ws < 0).any()
            or (ws >= n_cfg).any()
        )
        object.__setattr__(v, "_gate", (gate_key, bad_edges))
        return not bad_edges

    def _admit_to_buffer(self, v: Vertex) -> None:
        if self._vector:
            self._buffer_rounds.setdefault(v.id.round, {})[v.id.source] = v
        else:
            self._buffer.append(v)
            self._buffered_ids.add(v.id)
        self._observe_coin_share(v)

    def _remove_from_buffer(self, vid: VertexID) -> None:
        """Single site for buffer-exit bookkeeping: the id set and the
        blocked-vertex memo must leave together, or a later drain pass
        resurrects a stale short-circuit for a vertex that is long gone
        (the storage list/group entry is dropped by the drain itself)."""
        self._buffered_ids.discard(vid)
        self._blocked_on.pop(vid, None)

    def _observe_coin_share(self, v: Vertex) -> None:
        if v.coin_share is not None and v.round % self.cfg.wave_length == 0:
            wave = v.round // self.cfg.wave_length
            self.coin.observe_share(wave, v.source, v.coin_share)

    def take_verify_batch(self) -> List[Vertex]:
        """Pop the pending-verify queue without verifying — the collect
        half of cross-process dispatch coalescing: a driver that owns
        several processes sharing one device Verifier gathers every
        process's batch and issues ONE merged dispatch
        (Verifier.verify_rounds), then hands each mask back through
        :meth:`apply_verify_mask`. Per-vertex accept bits are a pure
        function of (vertex bytes, registry), so coalescing cannot change
        any process's behavior."""
        batch, self._pending_verify = self._pending_verify, []
        self._pending_verify_ids.clear()
        return batch

    def apply_verify_mask(
        self, batch: List[Vertex], ok: List[bool], seconds: float
    ) -> None:
        """Admit/reject a previously collected batch (apply half of the
        coalescing protocol; also the tail of :meth:`_drain_verify`)."""
        self.metrics.observe_verify_batch(len(batch), seconds)
        cert = self._cert
        n = self.cfg.n
        for v, good in zip(batch, ok):
            if good:
                self._admit_to_buffer(v)
                if (
                    cert
                    and v.cert_sig is not None
                    and v.id.round % n == self.index
                    and v.id.round not in self._certs_sent
                ):
                    # we are this round's designated aggregator: bank the
                    # directly verified share for certificate assembly
                    self._cert_stash.setdefault(v.id.round, {})[
                        v.id.source
                    ] = (v.digest(), v.cert_sig)
            else:
                self.metrics.inc("msgs_rejected_signature")
                self.log.event(
                    "reject_signature", round=v.round, source=v.source
                )

    def _drain_verify(self) -> None:
        """Batch-verify queued vertices through the Verifier seam — one
        whole batch per dispatch (the north-star shape).

        Under cfg.wave_pipeline with a windowed verifier (node.py wires
        a VerifierPipeline directly as ``self.verifier``), the dispatch
        window spans pump cycles (ISSUE 16 tentpole 4): each pass ships
        this cycle's batch with ``hold_tail=True`` so up to depth-1
        chunks stay in flight on the device while the host runs the
        next transport pump, and applies whatever masks resolved —
        which cover the OLDEST owed vertices in FIFO dispatch order.
        :meth:`_flush_verify_owed` settles the remainder at quiescence,
        so admission is only ever deferred, never lost. The lockstep
        simulator keeps its own full-drain coalescing path
        (take_verify_batch/apply_verify_mask) — byte-identity of its
        A/B runs is argued there."""
        if not self._pending_verify:
            return
        batch = self.take_verify_batch()
        rc = getattr(self.verifier, "run_coalesced", None)
        if (
            self._pipelined_waves
            and callable(rc)
            and callable(getattr(self.verifier, "drain", None))
        ):
            with Timer() as t:
                ok = rc(batch, hold_tail=True)
            self._verify_owed.extend(batch)
            if ok:
                front = [
                    self._verify_owed.popleft() for _ in range(len(ok))
                ]
                self.apply_verify_mask(front, ok, t.seconds)
            return
        with Timer() as t:
            ok = self.verifier.verify_batch(batch)
        self.apply_verify_mask(batch, ok, t.seconds)

    def _flush_verify_owed(self) -> bool:
        """Resolve every mask still held across pump cycles by the
        hold-tail window (see :meth:`_drain_verify`) and admit/reject
        the owed vertices. Called at step() quiescence: when no other
        transition can fire, the held tail is the only possible source
        of progress left."""
        if not self._verify_owed:
            return False
        with Timer() as t:
            ok = self.verifier.drain()
        front = [self._verify_owed.popleft() for _ in range(len(ok))]
        self.apply_verify_mask(front, ok, t.seconds)
        return bool(front)

    # ------------------------------------------------------------------
    # Aggregated round certificates (ISSUE 9)
    # ------------------------------------------------------------------
    # Round r's designated aggregator is process r % n. It verifies the
    # round's vertices directly (the per-vertex oracle path), then sums
    # the quorum's BLS shares into ONE certificate and gossips it; every
    # other process parks round-r vertices in _cert_pool and admits them
    # on one aggregate check instead of n signature verifies. A bad or
    # missing certificate degrades that round back to per-vertex — the
    # resilient.py ladder shape applied to the protocol layer.

    def _note_seen(self, v: Vertex) -> None:
        """Record ``v``'s digest in the per-round equivocation book."""
        row = self._seen_digests.get(v.id.round)
        if row is None:
            row = self._seen_digests[v.id.round] = [None] * self.cfg.n
        row[v.id.source] = v.digest()

    def _on_certificate(self, msg: BroadcastMessage) -> None:
        """Queue a received round certificate; application runs in
        :meth:`step` after the deferred inbox drains, so a certificate
        can never outrun the VALs it covers."""
        cert = msg.cert
        if not self._cert or cert is None:
            self.metrics.inc("msgs_ignored_kind")
            return
        if (
            cert.round < 1
            or cert.round <= self.dag.base_round
            or cert.round in self._cert_done
        ):
            self.metrics.inc("certs_ignored")
            return
        self._pending_certs.append(cert)
        if self._started and not self.defer_steps:
            self.step()

    def _on_span(self, msg: BroadcastMessage) -> None:
        """Queue a received cert-of-certs; like round certificates,
        application is deferred to :meth:`step`. Shape gating is strict —
        a span must be exactly this deployment's epoch geometry."""
        span = msg.span
        if not self._cert or not self._span or span is None:
            self.metrics.inc("msgs_ignored_kind")
            return
        k = self._span
        if (
            span.first_round < 1
            or len(span.signers) != k
            or (span.first_round - 1) % k != 0
            or span.last_round <= self.dag.base_round
            or (span.first_round - 1) // k in self._span_done
        ):
            self.metrics.inc("spans_ignored")
            return
        self._pending_spans.append(span)
        if self._started and not self.defer_steps:
            self.step()

    def _cert_step(self) -> bool:
        """Apply queued span + round certificates and assemble ours when
        enough material is banked. Returns True when anything admitted
        vertices (buffer progress). Spans apply first so a round they
        settle skips its (now redundant) per-round check this step."""
        progress = False
        if self._pending_spans:
            spans, self._pending_spans = self._pending_spans, []
            for span in spans:
                progress |= self._apply_span(span)
        if self._pending_certs:
            certs, self._pending_certs = self._pending_certs, []
            fresh: List[RoundCertificate] = []
            seen: Set[tuple] = set()
            for c in certs:
                key = c.signing_key()
                if (
                    c.round > self.dag.base_round
                    and c.round not in self._cert_done
                    and key not in seen
                ):
                    seen.add(key)
                    fresh.append(c)
            # two or more live certificates in one step share ONE
            # combined product check (verify_many), with per-cert
            # localization when the combined check fails
            verdicts = (
                self.cert_verifier.verify_many(fresh)
                if len(fresh) >= 2
                else [None] * len(fresh)
            )
            for cert, ok in zip(fresh, verdicts):
                progress |= self._apply_certificate(cert, ok)
        if self._cert_stash:
            self._maybe_assemble_certs()
        if self._span and self._span_bank:
            self._maybe_assemble_spans()
        return progress

    def _apply_certificate(
        self, cert: RoundCertificate, valid: Optional[bool] = None
    ) -> bool:
        r = cert.round
        if r <= self.dag.base_round or r in self._cert_done:
            return False
        if valid is None:
            valid = self.cert_verifier.verify_certificate(cert)
        if not valid:
            # forged aggregate / bad bitmap / substituted digests: reject
            # and fall back to per-vertex verifies for the whole round
            self.metrics.inc("certs_rejected")
            self.log.event("cert_reject", round=r)
            self._degrade_cert_round(r)
            return False
        self.metrics.inc("certs_verified")
        self._bank_span_cert(cert)
        pool = self._cert_pool.pop(r, None) or {}
        self._cert_done.add(r)
        self._cert_wait.pop(r, None)
        covered = dict(zip(cert.signers, cert.digests))
        admitted = False
        for src, v in pool.items():
            d = covered.get(src)
            if d is not None and d == (
                v.__dict__.get("_digest") or v.digest()
            ):
                # certificate-admitted: enters the DAG through the
                # trusted buffer/insert_many path, no per-vertex verify
                self._admit_to_buffer(v)
                self.metrics.inc("sigs_saved")
                admitted = True
            else:
                # pooled copy the certificate doesn't vouch for — the
                # per-vertex oracle decides
                self._pending_verify.append(v)
                self._pending_verify_ids.add(v.id)
        return admitted

    def _degrade_cert_round(self, r: int) -> None:
        """agg -> per-vertex degradation rung: route the round's pooled
        vertices through the normal verify queue. A Byzantine aggregator
        costs a round its fast path, never its liveness."""
        pool = self._cert_pool.pop(r, None)
        self._cert_done.add(r)
        self._cert_wait.pop(r, None)
        self.metrics.inc("cert_rounds_degraded")
        self.log.event(
            "cert_degraded", round=r, pooled=len(pool) if pool else 0
        )
        if pool:
            for v in pool.values():
                self._pending_verify.append(v)
                self._pending_verify_ids.add(v.id)
        if self._span:
            # a degraded round's certificate will never be banked, so a
            # partially banked epoch covering it can never complete —
            # abandon it (the span is an overlay; nothing to degrade)
            e = (r - 1) // self._span
            if self._span_bank.pop(e, None) is not None:
                self._span_wait.pop(e, None)
                self._span_done.add(e)

    def _cert_tick(self) -> bool:
        """One patience tick for every round still waiting on its
        certificate; expired rounds degrade. Returns True when anything
        degraded (there is now per-vertex work to drain). Partial span
        banks age here too — k rounds' worth of patience, since an epoch
        legitimately spans k certificate latencies."""
        if self._span and self._span_bank:
            stale = []
            for e in self._span_bank:
                w = self._span_wait.get(e, 0) + 1
                self._span_wait[e] = w
                if w > self.cfg.cert_patience * self._span:
                    stale.append(e)
            for e in stale:
                del self._span_bank[e]
                self._span_wait.pop(e, None)
                self._span_done.add(e)
                self.metrics.inc("span_timeouts")
                self.log.event("span_timeout", epoch=e)
        if not self._cert_pool:
            return False
        patience = self.cfg.cert_patience
        timed_out = []
        for r in self._cert_pool:
            w = self._cert_wait.get(r, 0) + 1
            self._cert_wait[r] = w
            if w > patience:
                timed_out.append(r)
        for r in timed_out:
            self.metrics.inc("cert_timeouts")
            self.log.event("cert_timeout", round=r)
            self._degrade_cert_round(r)
        return bool(timed_out)

    # -- cert-of-certs (ISSUE 12 tentpole 3) ---------------------------

    def _bank_span_cert(self, cert: RoundCertificate) -> None:
        """Bank a VERIFIED (or self-assembled) round certificate toward
        its epoch's cert-of-certs — span-aggregator side only."""
        k = self._span
        if not k:
            return
        e = (cert.round - 1) // k
        if (
            e % self.cfg.n != self.index
            or e in self._spans_sent
            or e in self._span_done
        ):
            return
        self._span_bank.setdefault(e, {})[cert.round] = cert

    def _maybe_assemble_spans(self) -> None:
        """Fold a fully banked epoch into one SpanCertificate and gossip
        it. The bank is keyed by round inside the epoch's k-round window,
        so len == k means gap-free coverage."""
        k = self._span
        for e in sorted(self._span_bank):
            bank = self._span_bank[e]
            if len(bank) < k:
                continue
            del self._span_bank[e]
            self._span_wait.pop(e, None)
            if e in self._spans_sent:
                continue
            self._spans_sent.add(e)
            first = e * k + 1
            span = self.cert_verifier.make_span(
                first, [bank[r] for r in sorted(bank)]
            )
            if span is None:
                continue
            # pre-gossip self-check, knob-gated like the round-cert one
            if self.cfg.cert_selfcheck and not self.cert_verifier.verify_span(
                span
            ):
                continue
            # the aggregator banks its own span for snapshot attestation
            # (ISSUE 20) — receivers bank verified spans in _apply_span
            self._span_chain[e] = span
            self.metrics.inc("spans_assembled")
            self.log.event("span_assembled", first_round=first, rounds=k)
            self.transport.broadcast(
                BroadcastMessage(
                    vertex=None,
                    round=span.last_round,
                    sender=self.index,
                    kind="cert_span",
                    span=span,
                    epoch=self._wire_epoch,
                )
            )

    def _apply_span(self, span: SpanCertificate) -> bool:
        """Settle every covered round still awaiting its certificate with
        the span's ONE combined check. Rounds already settled (cert
        applied, degraded, or pruned) are left alone — a span never
        un-decides anything, and a receiver never waits for one."""
        k = self._span
        e = (span.first_round - 1) // k
        if span.last_round <= self.dag.base_round or e in self._span_done:
            return False
        pending = [
            r
            for r in range(span.first_round, span.last_round + 1)
            if r > self.dag.base_round and r not in self._cert_done
        ]
        if not pending:
            self.metrics.inc("spans_ignored")
            return False
        if not self.cert_verifier.verify_span(span):
            # no degradation: the per-round certificates remain the
            # covered rounds' liveness anchor, so a bad span costs
            # nothing but this check
            self.metrics.inc("spans_rejected")
            self.log.event("span_reject", first_round=span.first_round)
            return False
        self.metrics.inc("spans_verified")
        self._span_done.add(e)
        self._span_chain[e] = span
        admitted = False
        for r in pending:
            covered = dict(
                zip(
                    span.signers[r - span.first_round],
                    span.digests[r - span.first_round],
                )
            )
            pool = self._cert_pool.pop(r, None) or {}
            self._cert_done.add(r)
            self._cert_wait.pop(r, None)
            self.metrics.inc("span_rounds_settled")
            for src, v in pool.items():
                d = covered.get(src)
                if d is not None and d == (
                    v.__dict__.get("_digest") or v.digest()
                ):
                    self._admit_to_buffer(v)
                    self.metrics.inc("sigs_saved")
                    admitted = True
                else:
                    self._pending_verify.append(v)
                    self._pending_verify_ids.add(v.id)
        return admitted

    def _maybe_assemble_certs(self) -> None:
        quorum = self.cfg.quorum
        for r in sorted(self._cert_stash):
            entries = self._cert_stash[r]
            if len(entries) < quorum:
                continue
            del self._cert_stash[r]
            if r in self._certs_sent:
                continue
            self._certs_sent.add(r)
            cert = self.cert_verifier.make_certificate(
                r, [(src, d, sig) for src, (d, sig) in entries.items()]
            )
            if cert is None:
                continue
            # Self-check before gossip: the shared verifier memoizes the
            # verdict by certificate content, so in-process receivers'
            # checks are dict hits — the cluster pays each aggregate
            # pairing once (mirrors the simulator's dedup'd verify).
            # Knob-gated (DAGRIDER_CERT_SELFCHECK): off trades early
            # local-corruption detection for assembly latency; peers
            # verify independently either way, so safety is unchanged.
            if self.cfg.cert_selfcheck and not self.cert_verifier.verify_certificate(
                cert
            ):
                continue
            self._bank_span_cert(cert)
            self.metrics.inc("certs_assembled")
            self.log.event("cert_assembled", round=r, signers=len(cert.signers))
            self.transport.broadcast(
                BroadcastMessage(
                    vertex=None,
                    round=r,
                    sender=self.index,
                    kind="cert",
                    cert=cert,
                    epoch=self._wire_epoch,
                )
            )

    # ------------------------------------------------------------------
    # The progress engine (Algorithm 2 lines 5-15)
    # ------------------------------------------------------------------

    def step(self) -> None:
        """Drive the state machine until quiescent.

        The reference's main loop busy-spins and its round-advance block is
        dead code after an infinite loop (D3, ``process.go:200-245``); here
        buffer-drain, round advancement, wave commits and proposals repeat
        until no further progress is possible.
        """
        made_progress = False
        progress = True
        cert_ticked = False
        while progress:
            progress = False
            if self._inbox:
                self._process_inbox()
            if self._cert:
                if self.log.enabled:
                    t0 = _time.perf_counter()
                    progress |= self._cert_step()
                    self.log.event(
                        "phase_cert", dur_s=_time.perf_counter() - t0
                    )
                else:
                    progress |= self._cert_step()
            self._drain_verify()
            progress |= self._drain_buffer()
            progress |= self._try_advance()
            if self._pipelined_waves:
                progress |= self._try_waves_pipelined()
            progress |= self._retry_pending_waves()
            if self.epoch_mgr is not None:
                progress |= self._epoch_retry_held_waves()
                live = int(self.dag.exists.sum())
                if live > self.metrics.counters["vertices_live_max"]:
                    self.metrics.counters["vertices_live_max"] = live
            made_progress |= progress
            if not progress and self._verify_owed:
                # quiescent with masks still in the hold-tail window:
                # settle them now — the held tail is the only remaining
                # source of admissions
                progress |= self._flush_verify_owed()
            if not progress and self._cert and not cert_ticked:
                # one patience tick per step(), taken only at quiescence
                # so a timeout-degraded round drains in THIS step
                cert_ticked = True
                progress |= self._cert_tick()
        self._maybe_request_sync(made_progress)

    def _drain_buffer(self) -> bool:
        """Admit buffered vertices whose predecessors are all present
        (Alg. 2 lines 6-10, quoted at reference ``process.go:189-195``).

        A vertex from a future round stays buffered (``process.go:203-206``);
        repeated passes handle chains unlocked by an admission.
        """
        if self._vector:
            return self._drain_buffer_vector()
        admitted_any = False
        changed = True
        log_admit = self.log.wants("admit")
        present = self.dag.present
        # Short-circuit memo: the first still-missing predecessor seen for
        # each blocked vertex. While that one vertex is absent the full
        # ~2f+1-edge scan must fail too, so repeated drain passes check
        # ONE id instead of every edge (identical admission decisions —
        # the memo only skips work when the outcome is already known).
        blocked = self._blocked_on
        while changed:
            changed = False
            exists = self.dag.exists  # re-fetch: capacity growth reallocates
            base = self.dag.base_round
            keep: List[Vertex] = []
            # Pass 1: cheap filters; survivors become candidates for ONE
            # vectorized predecessor check over the whole buffer.
            cand: List[Vertex] = []
            cand_arrs = []
            for v in self._buffer:
                vid = v.id
                if vid.round > self.round:
                    keep.append(v)
                    continue
                if vid.round <= base:
                    # Below the pruned floor: its predecessors are retired
                    # and the GC ordering rule excludes it from delivery
                    # anywhere — unadmittable, drop it. (No re-pass: a
                    # drop adds nothing to the DAG, so it cannot unlock
                    # any other vertex's predecessor check.)
                    self._remove_from_buffer(vid)
                    self.metrics.inc("msgs_below_gc_horizon")
                    continue
                if present(vid):
                    # raced in via another path; drop rather than
                    # re-insert (no re-pass — see above)
                    self._remove_from_buffer(vid)
                    self.metrics.inc("msgs_duplicate")
                    continue
                bp = blocked.get(vid)
                if (
                    bp is not None
                    and bp.round > base
                    and not present(bp)
                ):
                    keep.append(v)
                    continue
                # (a memoized blocker at/below the pruned floor falls
                # through to full re-evaluation: the weak-target-below-
                # base satisfaction rule below must get its chance, or a
                # vertex blocked before a prune would wait forever on a
                # round nobody can serve anymore)
                cand.append(v)
                cand_arrs.append(v.edge_arrays())
            # Pass 2: strong-predecessor check for ALL candidates in one
            # fancy index + one segmented reduce against the dense mirror
            # (edge rounds/sources are gate-validated in [0, n) and below
            # v.round <= self.round < capacity, so the index cannot
            # alias). The per-candidate numpy-call version of this check
            # was ~half the n=256 host profile. Admissions land in pass 3
            # AFTER this snapshot; a candidate whose predecessor is
            # admitted later in the same sweep just waits for the next
            # while-pass — same fixpoint, identical admitted set.
            if cand:
                lens = np.fromiter(
                    (a[1].size for a in cand_arrs),
                    dtype=np.intp,
                    count=len(cand),
                )
                rows = (
                    np.fromiter(
                        (v.id.round for v in cand),
                        dtype=np.intp,
                        count=len(cand),
                    )
                    - 1
                    - base
                )
                ss_cat = (
                    np.concatenate([a[1] for a in cand_arrs])
                    if len(cand) > 1
                    else cand_arrs[0][1]
                )
                hits = exists[np.repeat(rows, lens), ss_cat]
                offs = np.zeros(len(cand), dtype=np.intp)
                np.cumsum(lens[:-1], out=offs[1:])
                # every vertex carries >= quorum >= 1 strong edges (the
                # admission gate proved it), so no zero-length segment
                ok = np.bitwise_and.reduceat(hits, offs)
                # Pass 3: admit / memo the first missing blocker.
                for i, v in enumerate(cand):
                    if not ok[i]:
                        seg = hits[offs[i] : offs[i] + lens[i]]
                        k = int(np.argmin(seg))
                        blocked[v.id] = VertexID(
                            v.id.round - 1, int(cand_arrs[i][1][k])
                        )
                        keep.append(v)
                        continue
                    _, _, wr, ws = cand_arrs[i]
                    if wr.size:
                        if base:
                            # weak targets under the pruned floor are in
                            # finalized history — treated satisfied (they
                            # can never be re-fetched, and ordering never
                            # descends below the GC horizon).
                            w_live = wr > base
                            wr, ws = wr[w_live], ws[w_live]
                        if wr.size:
                            # live mirror, not the pass-2 snapshot: an
                            # insert below may have grown capacity
                            w_hit = self.dag.exists[wr - base, ws]
                            if not w_hit.all():
                                k = int(np.argmin(w_hit))
                                blocked[v.id] = VertexID(
                                    int(wr[k]), int(ws[k])
                                )
                                keep.append(v)
                                continue
                    self._remove_from_buffer(v.id)
                    self.dag.insert(v)
                    self.metrics.inc("vertices_admitted")
                    if log_admit:
                        self.log.event(
                            "admit", round=v.round, source=v.source
                        )
                    changed = True
                    admitted_any = True
            self._buffer = keep
        return admitted_any

    def _drain_buffer_vector(self) -> bool:
        """Round-batched buffer drain (the vector pump).

        Edges only ever target LOWER rounds (strong: r-1, weak: < r-1 —
        gate-enforced), so there are no intra-round dependencies and ONE
        ascending sweep over the round groups reaches the same fixpoint
        as the scalar while-changed loop: by the time round r is
        checked, every admissible vertex below it has been admitted.
        Per group the strong-predecessor check is one fancy index into a
        SINGLE ``exists`` row + one segmented AND, and admissions land
        as one :meth:`DagState.insert_many` batch. Admitted sets — and
        hence everything downstream — are identical to scalar; only the
        per-vertex bookkeeping is batched.
        """
        groups = self._buffer_rounds
        if not groups:
            return False
        admitted_any = False
        dag = self.dag
        n = self.cfg.n
        vertices = dag.vertices
        metrics_inc = self.metrics.inc
        log_on = self.log.wants("admit")
        for r in sorted(groups):
            if r > self.round:
                continue  # future round: stays buffered (process.go:203)
            grp = groups.pop(r)
            base = dag.base_round
            if r <= base:
                # Below the pruned floor: unadmittable everywhere — see
                # the scalar pass-1 comment.
                metrics_inc("msgs_below_gc_horizon", len(grp))
                continue
            exists_prev = dag.exists[r - 1 - base]
            if len(grp) > 1 and exists_prev.all():
                # Steady-state shape: round r-1 fully present, so every
                # strong probe passes — ONE pass over the group fuses
                # the duplicate filter with collecting the per-vertex
                # flat strong-row indices (memoized cluster-wide on the
                # shared vertex objects), and the whole batch lands as
                # one 1-D scatter in insert_many. A weak edge (rare
                # here: weak edges only exist for sources the proposer
                # could NOT reach) bails to the general path below.
                srcs: List[int] = []
                flats: List[np.ndarray] = []
                admit: List[Vertex] = []
                sa, fa, aa = srcs.append, flats.append, admit.append
                dups = 0
                weak_seen = False
                for v in grp.values():
                    if v.id in vertices:
                        dups += 1
                        continue
                    d = v.__dict__
                    a = d.get("_edge_arrays") or v.edge_arrays()
                    if a[2].size:
                        weak_seen = True
                        break
                    s = v.id.source
                    sa(s)
                    aa(v)
                    fs = d.get("_flat_strong")
                    if fs is None or fs[0] != n:
                        fs = (n, s * n + a[1])
                        object.__setattr__(v, "_flat_strong", fs)
                    fa(fs[1])
                if not weak_seen:
                    if dups:
                        metrics_inc("msgs_duplicate", dups)
                    if admit:
                        dag.insert_many(
                            admit, trusted=True, prepped=(srcs, flats)
                        )
                        metrics_inc("vertices_admitted", len(admit))
                        if log_on:
                            for v in admit:
                                self.log.event(
                                    "admit", round=v.round, source=v.source
                                )
                        admitted_any = True
                    continue
            live = [v for v in grp.values() if v.id not in vertices]
            dups = len(grp) - len(live)
            if dups:
                metrics_inc("msgs_duplicate", dups)
            if not live:
                continue
            arrs = [
                v.__dict__.get("_edge_arrays") or v.edge_arrays()
                for v in live
            ]
            if len(live) == 1:
                ok = (True,) if exists_prev[arrs[0][1]].all() else (False,)
            elif exists_prev.all():
                # full presence but weak edges in the group: every
                # strong probe passes; the loop below gates the weak
                ok = (True,) * len(live)
            else:
                lens = np.fromiter(
                    (a[1].size for a in arrs),
                    dtype=np.intp,
                    count=len(live),
                )
                hits = exists_prev[np.concatenate([a[1] for a in arrs])]
                offs = np.zeros(len(live), dtype=np.intp)
                np.cumsum(lens[:-1], out=offs[1:])
                # >= quorum >= 1 strong edges each (gate-proved), so
                # no zero-length segment
                ok = np.bitwise_and.reduceat(hits, offs)
            admit: List[Vertex] = []
            keep: List[Vertex] = []
            for i, v in enumerate(live):
                if not ok[i]:
                    keep.append(v)
                    continue
                wr, ws = arrs[i][2], arrs[i][3]
                if wr.size:
                    if base:
                        # weak targets under the pruned floor are
                        # finalized history — treated satisfied (scalar
                        # pass-3 rule)
                        w_live = wr > base
                        wr, ws = wr[w_live], ws[w_live]
                    if wr.size and not dag.exists[wr - base, ws].all():
                        keep.append(v)
                        continue
                admit.append(v)
            if admit:
                # the drain already proved single-round grouping,
                # non-presence and the edge gate — skip re-validation
                dag.insert_many(admit, trusted=True)
                metrics_inc("vertices_admitted", len(admit))
                if log_on:
                    for v in admit:
                        self.log.event(
                            "admit", round=v.round, source=v.source
                        )
                admitted_any = True
            if keep:
                groups[r] = {v.id.source: v for v in keep}
        return admitted_any

    def _try_advance(self) -> bool:
        """Round advancement (Alg. 2 lines 11-15, quoted at
        ``process.go:196-199``): when the current round has 2f+1 vertices,
        fire the wave boundary, move to the next round, and propose."""
        advanced = False
        while self.dag.round_size(self.round) >= self.cfg.quorum:
            r = self.round
            # Wave boundary fires BEFORE the proposal gate: committing a
            # wave needs no new proposal (the paper's wave_ready is an
            # independent upon-clause), so an idle client must not stall
            # delivery of a completed wave.
            if (
                r > 0
                and r % self.cfg.wave_length == 0
                and not self._pipelined_waves
            ):
                # cfg.wave_pipeline delegates every attempt to the
                # per-step _try_waves_pipelined pass (same step, same
                # DAG state — decisions land no later, never differ)
                w = r // self.cfg.wave_length
                if w not in self._waves_tried:
                    self._waves_tried.add(w)
                    self._try_wave(w)
            if self.epoch_mgr is not None and self.epoch_mgr.hold_round(
                r + 1, self.cfg.wave_length
            ):
                # Epoch barrier (ISSUE 20): rounds past the boundary's
                # last round belong to the next epoch and must carry
                # next-epoch coin shares — a mix of pre- and
                # post-rotation shares for one wave can never aggregate,
                # which would wedge the retro leader chain. Hold here
                # until the boundary chunk delivers and the local epoch
                # crosses; every correct process converges at round 4B.
                self.metrics.inc("epoch_barrier_holds")
                break
            if not self.blocks_to_propose and not self.cfg.propose_empty:
                break  # paper: wait until a block is available
            self.round += 1
            self.metrics.inc("rounds_advanced")
            self.log.event("round_advance", round=self.round)
            v = self._create_vertex(self.round)
            if self.log.enabled and v.block.transactions:
                # causal lifecycle stamp: this block (joined by payload
                # crc in the mempool's tx_batch events) now rides the
                # (round, source) vertex the tx_deliver stamp names
                self.log.event(
                    "tx_propose",
                    block=block_key(v.block.encode()),
                    round=self.round,
                    source=self.index,
                )
            self.dag.insert(v)
            self._note_seen(v)
            if (
                self._cert
                and v.cert_sig is not None
                and self.round % self.cfg.n == self.index
            ):
                # our own proposal in a round we aggregate: bank the share
                self._cert_stash.setdefault(self.round, {})[self.index] = (
                    v.digest(),
                    v.cert_sig,
                )
            self._broadcast_vertex(v)
            self.metrics.inc("vertices_proposed")
            advanced = True
        return advanced

    def _broadcast_vertex(self, v: Vertex) -> None:
        """Dissemination seam for own proposals. The local DAG already
        holds ``v`` (state first, wire second), so an override that
        mutates, withholds, or splits what goes on the wire — the
        Byzantine strategies in consensus/adversary.py — cannot corrupt
        this process's own dense mirrors, only test its peers."""
        self.transport.broadcast(
            BroadcastMessage(
                vertex=v,
                round=v.round,
                sender=self.index,
                epoch=self._wire_epoch,
            )
        )

    def _create_vertex(self, rnd: int) -> Vertex:
        """Vertex factory (Alg. 2 lines 17-21 + 29-31, quoted at
        ``process.go:271-275`` and ``process.go:300-302``)."""
        block = (
            self.blocks_to_propose.popleft()
            if self.blocks_to_propose
            else Block()
        )
        if self.lanes is not None:
            # a LanePending handle becomes its certified carrier block
            # (or the payload itself on degrade); plain blocks pass
            # through untouched
            block = self.lanes.materialize(block)
        # u.id IS VertexID(rnd-1, u.source) — reuse instead of
        # re-constructing n ids per proposal (a top allocation site of
        # the n=256 host profile)
        strong = tuple(
            u.id for u in self.dag.vertices_in_round(rnd - 1)
        )
        weak = self._weak_edges_for(rnd, strong)
        share = None
        if rnd % self.cfg.wave_length == 0:
            wave = rnd // self.cfg.wave_length
            share = self.coin.my_share(wave)
            if share is not None:
                self.coin.observe_share(wave, self.index, share)
        v = Vertex(
            id=VertexID(rnd, self.index),
            block=block,
            strong_edges=strong,
            weak_edges=weak,
            coin_share=share,
        )
        if self._cert:
            # BLS share over the digest (which excludes both signatures),
            # attached before the ed25519 sign copies the fields forward
            object.__setattr__(
                v, "cert_sig", self.cert_signer.sign_digest(v.digest())
            )
        if self.signer is not None:
            v = self.signer.sign_vertex(v)
        # Own proposals satisfy the admission gate by construction
        # (strong = the full quorum-checked frontier, weak from the
        # sweep); pre-stamping the gate memo keeps dag.insert and sibling
        # processes off the re-validation path.
        object.__setattr__(
            v, "_gate", ((self.cfg.n, self.cfg.quorum), False)
        )
        return v

    def _weak_edges_for(
        self, rnd: int, strong: tuple
    ) -> tuple:
        """Weak edges: for every round r < rnd-1 (descending), any vertex
        not already reachable gets a weak edge (Alg. 2 lines 29-31; the
        reference's ``setWeakEdges`` runs one BFS per candidate,
        ``process.go:303-309`` — here one incremental closure bitmap)."""
        if rnd < 3:
            return ()
        dag = self.dag
        n = self.cfg.n
        # Backward sweep (round-2 VERDICT weak #5: the closure-per-
        # straggler version). Invariant: when the sweep reaches round r,
        # reached[r] is the set of round-r vertices in the causal history
        # of v via all higher rounds — valid because after processing a
        # round every existing vertex there is *covered* (reachable or
        # freshly weak-linked), so covered vertices' out-edges are exactly
        # what must propagate. Order within a round is irrelevant (edges
        # only cross rounds).
        #
        # Truncation (round 4): every vertex of round <= rnd-2 already
        # present at our previous proposal is in that proposal's causal
        # history (its strong edges took ALL of round rnd-2, and its sweep
        # weak-linked everything unreachable below), and our previous
        # vertex is itself a strong-edge target of this proposal — so only
        # rounds >= dag.insert_min_round (the lowest round inserted since
        # that sweep) can hold uncovered candidates. Paths are monotone in
        # round, so stopping the propagation at lo loses nothing above it.
        # Steady state sweeps O(1) rounds instead of O(R); cold start and
        # checkpoint restore reset the marker to 0 (full sweep).
        # The GC horizon also floors the sweep: rounds <= base_round are
        # retired and excluded from delivery everywhere, so they can
        # never need a weak edge.
        lo = max(1, dag.base_round + 1, min(dag.insert_min_round, rnd - 1))
        dag.insert_min_round = rnd
        dag_base = dag.base_round
        base = lo - 1  # lowest row the sweep can write (r == lo writes lo-1)
        reached = np.zeros((rnd - base, n), dtype=bool)  # rows base..rnd-1
        covered = np.zeros(n, dtype=bool)
        for e in strong:  # frontier round rnd-1: covered = strong targets
            covered[e.source] = True
        weak: List[VertexID] = []
        for r in range(rnd - 1, lo - 1, -1):
            if r <= rnd - 2:
                covered = reached[r - base].copy()
                for u in dag.vertices_in_round(r):
                    if not covered[u.source]:
                        weak.append(u.id)
                        covered[u.source] = True
            if r == 1:
                break  # round 0 is genesis; nothing below to propagate to
            reached[r - 1 - base] |= covered @ dag.strong[r - dag_base]
            for i in np.flatnonzero(covered):
                for (r2, j) in dag.weak.get((r, i), ()):
                    if r2 >= lo:  # below lo is never read
                        reached[r2 - base, j] = True
        return tuple(weak)

    # ------------------------------------------------------------------
    # Catch-up sync (anti-entropy) — elastic recovery, SURVEY §5.
    #
    # A process that was down (or partitioned) while the cluster advanced
    # has buffered vertices whose predecessors nobody will re-broadcast:
    # without this, it stalls forever (the reference has the same hole,
    # plus no persistence at all). Requesters ask for a bounded round
    # window once the buffer has been stuck for `sync_patience` steps;
    # responders re-broadcast their *original signed* vertices for those
    # rounds, capped per (requester, window). Served vertices flow through
    # the normal admission path — signatures, stamps and (with RBC) the
    # Bracha consistency machinery still gate them, so a Byzantine
    # "helper" cannot use sync to smuggle an equivocation.
    # ------------------------------------------------------------------

    def _maybe_request_sync(self, made_progress: bool = False) -> None:
        # Stuck = no progress while there is something to wait for: a
        # non-empty buffer (missing predecessors), or queued client blocks
        # with an incomplete current round (our — or our peers' — round-r
        # broadcasts were lost, so everyone's buffers can be EMPTY while
        # the cluster deadlocks; a quiescent cluster with no pending
        # blocks is *idle*, not stuck, and must not request forever).
        # Scalar mirrors the buffer in _buffered_ids; vector keys the
        # round-group dicts by vid instead — either emptiness check is
        # O(1), unlike the ``buffer`` property which flattens groups.
        waiting = (
            (
                bool(self._buffer_rounds)
                if self._vector
                else bool(self._buffered_ids)
            )
            or bool(self._cert_pool)  # rounds parked awaiting a cert
            or (
                bool(self.blocks_to_propose)
                and self.round >= 1
                and self.dag.round_size(self.round) < self.cfg.quorum
            )
        )
        if self.cfg.sync_patience <= 0 or made_progress or not waiting:
            # any forward progress resets patience — a node that is being
            # fed (however slowly) is not partitioned
            self._stuck_steps = 0
            return
        rx = self.metrics.counters.get("msgs_received", 0)
        if rx != self._rx_at_patience:
            # Traffic is still ARRIVING at this node: a driver pumping in
            # chunks (mempool load drivers, WAN clocks) is throttling
            # delivery below the offered load — throttled, not
            # partitioned. HOLD the counter (don't accrue, don't reset):
            # patience accrues only across steps where nothing reached us
            # at all. Without this gate every chunk-limited pump cycle
            # read as a stall, and once sync_patience elapsed all n nodes
            # broadcast requests whose vertex re-serves amplify n^2 into
            # a re-serve storm (the round-10 load drivers had to run with
            # sync_patience=0 to avoid it). Receipts — not the shared
            # broker's global queue length — are the signal a real
            # deployment would have: a partitioned node sees silence and
            # correctly keeps accruing toward a sync request.
            self._rx_at_patience = rx
            return
        self._stuck_steps += 1
        if self._stuck_steps < self.cfg.sync_patience:
            return
        now = _time.monotonic()
        if now - self._sync_last_request < self.cfg.sync_request_cooldown_s:
            return  # patience keeps accruing; request fires on cooldown
        self._stuck_steps = 0
        self._sync_last_request = now
        lo: Optional[int] = None
        # Rounds at/below our GC floor — or the f+1-attested PEER floor —
        # are unservable everywhere (peers refuse pruned windows) and
        # unadmittable here; requesting them would loop forever.
        floor = max(self.dag.base_round, self._attested_floor)
        for v in self.buffer:
            for e in (*v.strong_edges, *v.weak_edges):
                if e.round > max(0, floor) and not self.dag.present(e):
                    lo = e.round if lo is None else min(lo, e.round)
        if lo is not None:
            # Anchor at our own frontier: buffered vertices only reveal
            # the round directly below themselves, so chasing their
            # predecessors would walk the gap backward one round per
            # request. Rounds < self.round are quorum-complete locally,
            # but self.round itself may not be (lost broadcasts).
            lo = min(lo, max(1, self.round))
        elif (
            self.blocks_to_propose
            and self.round >= 1
            and self.dag.round_size(self.round) < self.cfg.quorum
        ):
            # Nothing is missing *below* the buffer, but we want to
            # advance and our current round lacks quorum (lost
            # broadcasts): ask for the current round.
            lo = self.round
        else:
            # Nothing sync can provide (e.g. idle with future-round
            # vertices buffered and no client blocks): requesting would
            # be a perpetual O(n^2) duplicate-traffic loop.
            return
        hi = lo + self.cfg.sync_window - 1
        self._sync_last_lo = lo
        self.metrics.inc("sync_requested")
        self.log.event("sync_request", lo=lo, hi=hi)
        req = BroadcastMessage(
            vertex=None,
            round=lo,
            sender=self.index,
            kind="sync",
            origin=hi,
            epoch=self._wire_epoch,
        )
        # Anti-entropy is PULL gossip: ask ONE peer per patience window,
        # rotating deterministically, instead of broadcasting the
        # request to all n-1. A broadcast request makes every peer
        # answer with the full window — n responders x window x n
        # destinations amplified one stuck round into ~n^2 duplicate
        # traffic at n=32 (the re-serve storm). Rotation reaches an
        # honest, connected peer within f+1 windows; if the stack has
        # no unicast seam (or the chosen peer is gone) the request
        # degrades to the old broadcast.
        send = resolve_unicast(self.transport)
        if send is not None:
            peer = self._sync_peer_rr % self.cfg.n
            if peer == self.index:
                peer = (peer + 1) % self.cfg.n
            self._sync_peer_rr = peer + 1
            try:
                send(peer, req)
                return
            except KeyError:
                pass  # peer not subscribed (down/late): fall back
        self.transport.broadcast(req)

    def _on_sync_nack(self, msg: BroadcastMessage) -> None:
        """A responder's "your window is below my GC floor" signal.

        Once f+1 DISTINCT responders (at least one honest) report floors
        above our round, anti-entropy can never close the gap —
        ``state_transfer_needed`` flips and the node runtime fetches a
        peer snapshot (utils.checkpoint.restore_from_snapshot). Floors at
        or below our round are stale/irrelevant for THAT signal and clear
        that responder's entry (progress may have resumed).

        Separately, floors above the *requested window* (lo) feed the
        attested-floor quorum even when our round is ahead of them: a
        node blocked on pruned straggler rounds would otherwise ignore
        every nack and re-request unservable history forever (its own
        GC floor may never advance past the blockers, e.g. with
        gc_depth=None against pruning peers)."""
        if (
            not 0 <= msg.sender < self.cfg.n
            or msg.sender == self.index
            or msg.origin != self.index
        ):
            return
        floor = msg.round
        if self._sync_last_lo is not None and floor >= self._sync_last_lo:
            prev = self._window_nacks.get(msg.sender, 0)
            if floor > prev:
                self._window_nacks[msg.sender] = floor
            if len(self._window_nacks) >= self.cfg.f + 1:
                # Highest floor that f+1 distinct responders (>= 1
                # honest) attest: the (f+1)-th largest reported value.
                # Byzantine inflation is clipped to what an honest
                # responder corroborates.
                attested = sorted(self._window_nacks.values())[
                    len(self._window_nacks) - (self.cfg.f + 1)
                ]
                if attested > self._attested_floor:
                    self._attested_floor = attested
                    self.log.event(
                        "attested_floor", floor=attested,
                        responders=len(self._window_nacks),
                    )
                    self.metrics.inc("sync_attested_floor_raises")
        if floor > self.round:
            self._horizon_nacks[msg.sender] = floor
            self.metrics.inc("sync_nacks")
            # Threshold over CURRENTLY-live floors only: entries recorded
            # while briefly behind must not linger and let a single later
            # Byzantine nack fake the f+1 quorum after we caught up.
            live = {
                k: v for k, v in self._horizon_nacks.items() if v > self.round
            }
            self._horizon_nacks = live
            if len(live) >= self.cfg.f + 1:
                if not self.state_transfer_needed:
                    self.log.event(
                        "behind_horizon", floors=sorted(live.values())
                    )
                self.state_transfer_needed = True
        else:
            self._horizon_nacks.pop(msg.sender, None)

    def _serve_sync(self, msg: BroadcastMessage) -> None:
        # Requester id is range-checked (spoofable in-protocol, but the
        # throttle table stays bounded at n entries) and self-requests are
        # ignored.
        if not 0 <= msg.sender < self.cfg.n or msg.sender == self.index:
            return
        lo = max(1, msg.round)
        hi = msg.origin if msg.origin is not None else lo
        hi = min(hi, lo + self.cfg.sync_window - 1, self.round)
        if hi < lo and lo > self.dag.base_round:
            return
        # Rate limit per requester (not per window — window rotation must
        # not multiply the budget, and a lost response must be
        # re-requestable once the cooldown passes). The below-horizon
        # nack path shares this throttle: the requester id is spoofable
        # in-protocol, and an unthrottled nack broadcast would be an n^2
        # traffic amplifier.
        now = _time.monotonic()
        if (
            now - self._sync_last_serve.get(msg.sender, float("-inf"))
            < self.cfg.sync_serve_cooldown_s
        ):
            self.metrics.inc("sync_throttled")
            return
        self._sync_last_serve[msg.sender] = now
        if lo <= self.dag.base_round:
            # Below the GC horizon: that history is retired here (and
            # excluded from delivery everywhere) — refuse cleanly rather
            # than serve a partial window the requester can't use, and
            # tell the requester WHY (sync_nack with our floor): f+1
            # such nacks are its signal that anti-entropy cannot help
            # and peer state transfer (snapshot sync) is needed.
            self.metrics.inc("sync_refused_pruned")
            self.log.event(
                "sync_refuse_pruned", lo=lo, floor=self.dag.base_round
            )
            self.transport.broadcast(
                BroadcastMessage(
                    vertex=None,
                    round=self.dag.base_round,
                    sender=self.index,
                    kind="sync_nack",
                    origin=msg.sender,
                    epoch=self._wire_epoch,
                )
            )
            return
        # Serve UNICAST to the requester when the stack has a
        # per-destination seam: a broadcast response multiplies every
        # served vertex by n-1 destinations, and with many peers
        # answering the same request the re-serve traffic amplifies
        # ~n^2 — at n=32 one patience round buried live VALs behind
        # ~300k stale duplicates and wedged the cluster. Under Bracha
        # (requires_broadcast) the seam resolves to None and responses
        # stay broadcast: peers must see repeat VALs to refresh READYs
        # or the requester can never reach delivery quorum.
        send = resolve_unicast(self.transport)
        count = 0
        for r in range(lo, hi + 1):
            for v in self.dag.vertices_in_round(r):
                out = BroadcastMessage(
                    vertex=v,
                    round=v.round,
                    sender=v.source,
                    epoch=self._wire_epoch,
                )
                if send is not None:
                    try:
                        send(msg.sender, out)
                    except KeyError:
                        # requester has no inbox on this broker (left,
                        # or never subscribed): degrade to broadcast
                        # for the rest of the window
                        send = None
                        self.transport.broadcast(out)
                else:
                    self.transport.broadcast(out)
                count += 1
        if count:
            self.metrics.inc("sync_served", count)
            self.log.event("sync_serve", lo=lo, hi=hi, vertices=count)

    # ------------------------------------------------------------------
    # Wave commit (Algorithm 3, quoted at process.go:315-325, 358-361)
    # ------------------------------------------------------------------

    def _retry_pending_waves(self) -> bool:
        fired = False
        for w in sorted(self._pending_waves):
            if self.coin.ready(w):
                self._pending_waves.discard(w)
                self._try_wave(w)
                fired = True
        return fired

    def _try_waves_pipelined(self) -> bool:
        """Attempt every live undecided wave whose commit round already
        holds a quorum (ISSUE 16 tentpole 1; cfg.wave_pipeline).

        The boundary one-shot in _try_advance serializes wave
        evaluation behind the local round counter: a wave whose votes
        land mid-step waits for the counter to cross round(w, 4), and a
        wave that fails its single boundary attempt is only ever
        committed retroactively through a later wave's chain walk. Here
        every wave from decided_wave+1 up to the DAG's quorum frontier
        is (re)attempted each pass, so a decision lands the moment its
        votes exist and undecided waves stay retryable while younger
        waves fill — overlapping wave instances instead of a lockstep
        4-round cadence.

        The committed leader sequence — and therefore the total order —
        is unchanged (the A/B invariant): chain-walk path checks run
        over the deciding leader's immutable causal past, so they are
        time-invariant, and a wave's one-shot is spent exactly at the
        first attempt with the round counter at/past its commit round —
        the same DAG state the oracle's boundary attempt sees — so no
        wave decides here that the boundary path would have skipped
        (decisions land earlier in the step, never different).
        """
        wl = self.cfg.wave_length
        frontier = self.dag.quorum_frontier(self.cfg.quorum)
        if frontier < wl:
            self.metrics.counters["waves_inflight"] = 0
            return False
        before = self.decided_wave
        w_hi = self.cfg.wave_of_round(frontier)
        for w in range(self.decided_wave + 1, w_hi + 1):
            r4 = self.cfg.wave_round(w, wl)
            if r4 > frontier:
                break
            if w <= self.decided_wave or w in self._waves_spent:
                continue
            spend = self.round >= r4
            if spend:
                # boundary-equivalent attempt: one-shot spent, exactly
                # like the oracle's _waves_tried bookkeeping
                self._waves_spent.add(w)
                self._wave_try_memo.pop(w, None)
                self._try_wave(w)
                continue
            # early retryable attempt: votes and leader presence are
            # pure functions of the r4/r1 fills (strong edges are fixed
            # at admission), so an unchanged fill pair means the last
            # verdict stands — skip the reach count
            fills = (
                self.dag.round_size(r4),
                self.dag.round_size(self.cfg.wave_round(w, 1)),
            )
            if self._wave_try_memo.get(w) == fills:
                continue
            self._wave_try_memo[w] = fills
            self._try_wave(w, quiet=True)
        if self.decided_wave > before:
            self._waves_spent = {
                w for w in self._waves_spent if w > self.decided_wave
            }
            self._wave_try_memo = {
                w: m
                for w, m in self._wave_try_memo.items()
                if w > self.decided_wave
            }
        # gauge: undecided waves whose commit round has a quorum — the
        # live overlap depth of the wave pipeline
        self.metrics.counters["waves_inflight"] = max(
            0,
            min(w_hi, self.cfg.wave_of_round(frontier))
            - self.decided_wave,
        )
        return self.decided_wave > before

    def _try_wave(self, wave: int, quiet: bool = False) -> None:
        """The commit rule (reference ``waveReady``, ``process.go:312-354``,
        with D4/D5 fixed: state persists and ordering actually runs).

        ``quiet`` marks a retryable pipelined attempt: a failed quorum
        or absent leader is expected to be re-tried as the DAG fills,
        so it must not inflate ``waves_skipped`` or spam skip events —
        the spend-time attempt (and the oracle boundary path) keeps the
        reference accounting."""
        if wave <= self.decided_wave:
            return
        if not self.coin.ready(wave):
            self._pending_waves.add(wave)
            if not quiet:
                self.log.event("wave_pending_coin", wave=wave)
            return
        leader = self._wave_leader(wave)
        if leader is None:
            if not quiet:
                self.metrics.inc("waves_skipped")
                self.log.event("wave_skip", wave=wave, reason="no_leader")
            return
        r4, r1 = self.cfg.wave_round(wave, self.cfg.wave_length), self.cfg.wave_round(wave, 1)
        votes = self._strong_reach_count(r4, r1, leader.source)
        if votes < self.cfg.quorum:
            if not quiet:
                self.metrics.inc("waves_skipped")
                self.log.event(
                    "wave_skip", wave=wave, reason="quorum", votes=votes
                )
            return
        # Retroactive leader chain (process.go:341-350): walk back through
        # undecided waves, committing every prior leader the current one
        # covers by a strong path.
        t0 = _time.perf_counter()
        leaders: Stack[Vertex] = Stack()
        leaders.push(leader)
        cur = leader
        for w in range(wave - 1, self.decided_wave, -1):
            if not self.coin.ready(w):
                if self.cfg.wave_round(w, 1) <= self.dag.base_round:
                    # The coin shares for w live below our GC window
                    # (after a prune or state transfer), so the leader
                    # is unknowable here — and every delivery this
                    # chain link could produce sits at rounds <=
                    # r1(w) <= base, all floor-excluded at this
                    # process. Skipping the link keeps the total order
                    # identical to processes that do walk it.
                    continue
                # An IN-WINDOW link whose shares are still in flight:
                # skipping would diverge the total order (other
                # processes may commit this leader), so defer the WHOLE
                # commit and let _retry_pending_waves re-enter once the
                # shares land — decided_wave is untouched, so the
                # re-entry redoes the full walk.
                self._pending_waves.add(wave)
                self.log.event(
                    "wave_pending_chain_coin", wave=wave, link=w
                )
                return
            prior = self._wave_leader(w)
            if prior is not None and (
                self._leader_path(cur.id, prior.id)
                if self._vector
                else self.dag.path(cur.id, prior.id, strong_only=True)
            ):
                leaders.push(prior)
                cur = prior
        self.decided_wave = wave
        self.metrics.inc("waves_decided")
        # interval stamp at DECIDE time — a deferred flush that runs two
        # waves' ordering walks back-to-back must not record ~0 cadence
        self.metrics.observe_wave_decided()
        self.log.event(
            "wave_decided",
            wave=wave,
            leader=leader.source,
            votes=votes,
            chain=len(leaders),
        )
        if self._eager:
            # surface the exact canonical chunks NOW, ahead of the
            # (possibly deferred) on_deliver flush — list(leaders)
            # iterates in pop order (oldest leader first) without
            # consuming the stack the flush still owns
            self._eager_surface(list(leaders), wave)
        if self.defer_delivery:
            # cur is the oldest leader in the chain — maybe_prune anchors
            # the GC floor on it until the deferred walk flushes.
            self._deferred_orders.append(
                (leaders, _time.perf_counter() - t0, cur.round)
            )
            return
        self._order_vertices(leaders)
        self.metrics.observe_wave_commit(_time.perf_counter() - t0)
        self.maybe_prune()

    def _eager_surface(self, chain: List[Vertex], wave: int) -> None:
        """Speculatively surface a decided chain's canonical chunks
        (ISSUE 16 tentpole 2; cfg.eager_deliver).

        The chunks computed here are byte-identical to what the
        canonical _order_vertices walk will deliver for the same chain:
        a leader's closure is immutable once admitted (admission
        requires full causal history), the GC exclusion bound is a pure
        function of the leader round, and the eager mask has exactly
        the prior decisions' chunks applied (decisions and flushes are
        both FIFO). So the speculative stream is a prefix of the final
        order by construction; _order_vertices reconciles and routes
        any divergence through the flight recorder."""
        mask = self._eager_mask
        if mask.shape[0] < self.dag.exists.shape[0]:
            grown = np.zeros_like(self.dag.exists)
            grown[: mask.shape[0]] = mask
            self._eager_mask = mask = grown
        base = self.dag.base_round
        gc = self.cfg.gc_depth
        cb = self.on_deliver_early
        lanes = self.lanes
        by_round = self.dag._round_vertices
        count = 0
        for leader in chain:
            reached = self.dag.closure_stopped(leader.id, mask)
            lo_round = max(1, base + 1)
            if gc is not None:
                lo_round = max(lo_round, leader.round - gc + 1)
            lo = lo_round - base
            hi = leader.round + 1 - base
            if hi <= lo:
                continue
            fresh = reached[lo:hi] & ~mask[lo:hi]
            rrs, srcs = np.nonzero(fresh)
            if not rrs.size:
                continue
            mask[lo:hi][fresh] = True
            cur = -1
            d: Dict[int, Vertex] = {}
            for rr, src in zip(rrs.tolist(), srcs.tolist()):
                if rr != cur:
                    cur = rr
                    d = by_round[rr + lo_round]
                v = d[src]
                self.eager_log.append(v.id)
                if cb is not None:
                    if lanes is not None:
                        v = lanes.resolve_vertex(v)
                    cb(v)
            count += int(rrs.size)
        if count:
            self.metrics.inc("eager_delivered", count)
            self.log.event("eager_deliver", wave=wave, count=count)

    def _reconcile_eager(self, n_before: int) -> None:
        """Match canonical deliveries just appended by _order_vertices
        against the speculative stream (prefix property). The canonical
        order always wins — the eager stream is advisory — so a
        mismatch never rolls back delivered state; it bumps the
        expected-zero counter, fires the flight-recorder trigger, and
        disables further speculation on this process."""
        fresh = self.delivered_log[n_before:]
        if not fresh:
            return
        cur = self._eager_cursor
        elog = self.eager_log
        ok = 0
        for vid in fresh:
            if cur < len(elog) and elog[cur] == vid:
                cur += 1
                ok += 1
                continue
            self.metrics.inc("eager_rollbacks_expected_zero")
            self.log.event(
                "eager_mismatch",
                cursor=cur,
                expected=str(elog[cur]) if cur < len(elog) else None,
                delivered=str(vid),
            )
            self.log.event(
                "invariant_violation",
                kind="eager_prefix",
                detail=f"speculative order diverged at cursor {cur}",
            )
            self._eager = False
            break
        self._eager_cursor = cur
        if ok:
            self.metrics.inc("eager_reconciled", ok)
            self.log.event("eager_reconciled", count=ok)

    def flush_deliveries(self) -> None:
        """Run queued ordering/delivery walks (see ``defer_delivery``).
        The wave-commit metric observes chain-walk + ordering as one
        sample, same as the inline path."""
        while self._deferred_orders:
            leaders, partial, _ = self._deferred_orders.popleft()
            with Timer() as t:
                self._order_vertices(leaders)
            self.metrics.observe_wave_commit(partial + t.seconds)
        self.maybe_prune()

    def maybe_prune(self, floor: Optional[int] = None) -> int:
        """Retire DAG/process state below the GC horizon (cfg.gc_depth).

        The floor is ``oldest_undelivered_leader_round - gc_depth``: the
        ordering rule (see _order_vertices) already guarantees no correct
        process will ever deliver below it, so dropping that state cannot
        diverge the total order. Pending deferred delivery walks anchor
        the floor at their oldest leader — pruning may never outrun a
        delivery that is merely deferred. Returns vertices removed.

        ``floor`` overrides the computed horizon (epoch-boundary GC,
        ISSUE 20): the caller — :meth:`_epoch_advance` — passes a floor
        that is a pure function of the committed boundary, so every
        correct process prunes at the same point in the total order and
        the ``base_round`` delivery exclusion stays identical
        everywhere. Deferred delivery walks still clamp it.
        """
        gc = self.cfg.gc_depth
        if floor is None and self._epoch_gc_floor is not None:
            # one-shot epoch-boundary floor armed by _epoch_advance
            floor, self._epoch_gc_floor = self._epoch_gc_floor, None
        if floor is None:
            if gc is None or self.decided_wave == 0:
                return 0
            anchor = self.cfg.wave_round(self.decided_wave, 1)
            for (_, _, oldest_round) in self._deferred_orders:
                anchor = min(anchor, oldest_round)
            floor = anchor - gc
        else:
            for (_, _, oldest_round) in self._deferred_orders:
                floor = min(floor, oldest_round - (gc or 1))
        if floor <= self.dag.base_round:
            return 0
        old_base = self.dag.base_round
        removed = self.dag.prune_below(floor)
        shift = self.dag.base_round - old_base
        # Realign the delivered bitmap with the shifted dense rows.
        dmask = self._delivered_mask
        new = np.zeros_like(self.dag.exists)
        src = dmask[shift:]
        m = min(src.shape[0], new.shape[0])
        new[:m] = src[:m]
        self._delivered_mask = new
        if self._eager_mask is not None:
            # the eager twin shifts with the same realignment, and the
            # reconciled head of the speculative log retires with the
            # canonical one (entries past the cursor are still awaiting
            # their canonical match and must survive the prune)
            enew = np.zeros_like(self.dag.exists)
            esrc = self._eager_mask[shift:]
            em = min(esrc.shape[0], enew.shape[0])
            enew[:em] = esrc[:em]
            self._eager_mask = enew
            nb = self.dag.base_round
            drop = 0
            while (
                drop < self._eager_cursor
                and drop < len(self.eager_log)
                and self.eager_log[drop].round < nb
            ):
                drop += 1
            if drop:
                self.eager_log = self.eager_log[drop:]
                self._eager_cursor -= drop
        # Bound the book-keeping that grows with history. delivered_log
        # keeps only the live window (the trimmed count is preserved for
        # checkpoints/metrics); deliveries below the horizon can never
        # recur, so dedup state for them is dead weight.
        base = self.dag.base_round
        if self.delivered_log and self.delivered_log[0].round < base:
            keep = [v for v in self.delivered_log if v.round >= base]
            self.delivered_trimmed += len(self.delivered_log) - len(keep)
            self.delivered_log = keep
        self._seen_digests = {
            r: row for r, row in self._seen_digests.items() if r >= base
        }
        if self._cert:
            # Certificate books follow the same floor. Pooled vertices at
            # or below it are retired history (unadmittable anyway).
            for r in [r for r in self._cert_pool if r <= base]:
                del self._cert_pool[r]
                self._cert_wait.pop(r, None)
            self._cert_stash = {
                r: s for r, s in self._cert_stash.items() if r > base
            }
            self._cert_done = {r for r in self._cert_done if r > base}
            self._certs_sent = {r for r in self._certs_sent if r > base}
            if self._span:
                # epoch books retire once the epoch's last round sinks
                # below the floor ((e+1)*k is epoch e's last round)
                k = self._span
                self._span_bank = {
                    e: b
                    for e, b in self._span_bank.items()
                    if (e + 1) * k > base
                }
                self._span_wait = {
                    e: w
                    for e, w in self._span_wait.items()
                    if e in self._span_bank
                }
                self._spans_sent = {
                    e for e in self._spans_sent if (e + 1) * k > base
                }
                self._span_done = {
                    e for e in self._span_done if (e + 1) * k > base
                }
                # the attestation chain keeps exactly the spans whose
                # window overlaps the restorable DAG (rounds > base) —
                # what snapshot_bytes will cover (ISSUE 20)
                self._span_chain = {
                    e: s
                    for e, s in self._span_chain.items()
                    if (e + 1) * k > base
                }
        # A reliable-broadcast stage keeps per-slot vote books — retire
        # them along the same floor (transport/rbc.py prune_below), or a
        # long-running RBC node leaks exactly the state class the DAG
        # prune just bounded.
        tp_prune = getattr(self.transport, "prune_below", None)
        if tp_prune is not None:
            tp_prune(base)
        # ... and the coin's per-wave share books (same floor, in waves)
        if base >= 1:
            self.coin.prune_below(self.cfg.wave_of_round(base))
        # Pending waves whose shares just got pruned can never become
        # ready — and their deliveries are floor-excluded here anyway;
        # without this they would be re-polled every step forever.
        self._pending_waves = {
            w
            for w in self._pending_waves
            if self.cfg.wave_round(w, 1) > base
        }
        self._waves_spent = {
            w for w in self._waves_spent if self.cfg.wave_round(w, 1) > base
        }
        self._wave_try_memo = {
            w: f
            for w, f in self._wave_try_memo.items()
            if self.cfg.wave_round(w, 1) > base
        }
        self.metrics.inc("vertices_pruned", removed)
        self.log.event("pruned", floor=base, removed=removed)
        return removed

    def _wave_leader(self, wave: int) -> Optional[Vertex]:
        """Leader lookup (reference ``getWaveVertexLeader``,
        ``process.go:356-371``): the unique vertex at round(w, 1) authored
        by the coin's choice, if present in this process's DAG."""
        src = self.coin.choose_leader(wave)
        return self.dag.get(VertexID(self.cfg.wave_round(wave, 1), src))

    def _leader_path(self, hi: VertexID, lo: VertexID) -> bool:
        """Strong-path query for the retroactive leader chain (vector
        pump): seeded vector @ matrix descent over the dense mirrors
        (:func:`ops.dag_kernels.leader_reach_np`) — O(k·n²) bit ops for
        a k-round gap instead of the scalar closure walk's per-round
        Python bookkeeping. Same boolean-semiring reachability as
        ``dag.path(strong_only=True)``; tests pin the twin against the
        jitted kernel."""
        from dag_rider_tpu.ops.dag_kernels import leader_reach_np

        dag = self.dag
        if not dag.present(hi) or not dag.present(lo):
            return False
        if hi == lo:
            return True
        if lo.round >= hi.round:
            return False
        vec = leader_reach_np(
            dag.strong_stack(hi.round, lo.round), hi.source
        )
        return bool(vec[lo.source])

    def _strong_reach_count(self, r_hi: int, r_lo: int, leader_src: int) -> int:
        """|{v in dag[r_hi] : strong path v -> leader}| — host twin of
        ops.dag_kernels.wave_commit_votes.

        Back-propagates a reach VECTOR up the wave instead of chaining
        n x n bool matmuls: only the leader's column of the full reach
        matrix is ever consumed, so each level is one masked column
        selection + row-OR (~n^2 bit ops) rather than an n^3 matmul —
        at n=256 this was ~4.5 ms per wave try, ~10% of the host loop."""
        base = self.dag.base_round
        if r_hi == r_lo:
            return int(self.dag.exists[r_hi - base, leader_src])
        # vec[i] = True iff (r, i) strong-reaches the leader at r_lo
        vec = self.dag.strong[r_lo + 1 - base][:, leader_src]
        for r in range(r_lo + 2, r_hi + 1):
            vec = self.dag.strong[r - base][:, vec].any(axis=1)
        votes = vec & self.dag.exists[r_hi - base]
        return int(votes.sum())

    # ------------------------------------------------------------------
    # Total order delivery (Algorithm 1 lines 51-57, process.go:405-411)
    # ------------------------------------------------------------------

    def _order_vertices(self, leaders: Stack[Vertex]) -> None:
        """Deterministic a_deliver of every vertex in each committed
        leader's causal history, oldest leader first (D5/D6/D8 fixed: it
        runs, it calls the client callback, and delivered vertices are
        skipped exactly once)."""
        n_before = len(self.delivered_log)
        trace = self.log.enabled
        dmask = self._delivered_mask
        if dmask.shape[0] < self.dag.exists.shape[0]:
            grown = np.zeros_like(self.dag.exists)
            grown[: dmask.shape[0]] = dmask
            self._delivered_mask = dmask = grown
        base = self.dag.base_round
        gc = self.cfg.gc_depth
        while not leaders.is_empty():
            leader = leaders.pop()
            chunk_start = len(self.delivered_log)
            # Delivered-pruned closure: identical fresh set as the full
            # closure (delivery is causally closed), but the sweep stops
            # at the already-delivered frontier instead of descending the
            # whole DAG depth on every commit.
            reached = self.dag.closure_stopped(leader.id, dmask)
            # Deterministic GC exclusion (cfg.gc_depth): vertices at
            # round <= leader.round - gc_depth are skipped by EVERY
            # process for the same committed leader (a pure function of
            # the leader round), so the total order stays identical while
            # state below the horizon becomes safely prunable. A vertex
            # excluded at its first containing leader stays excluded at
            # every later one (leader rounds only grow).
            lo_round = max(1, base + 1)
            if gc is not None:
                lo_round = max(lo_round, leader.round - gc + 1)
            # One vectorized diff against delivered state, then touch only
            # the genuinely-new slots. argwhere's row-major order IS the
            # delivery order (ascending round, then source).
            lo = lo_round - base
            hi = leader.round + 1 - base
            if hi <= lo:
                self._epoch_note_delivery(leader, chunk_start)
                continue
            fresh = reached[lo:hi] & ~dmask[lo:hi]
            if self._vector:
                # Same slots in the same order (nonzero is row-major,
                # exactly argwhere's ascending round-then-source), but
                # the mask write and the counter land once per commit
                # instead of once per slot.
                rrs, srcs = np.nonzero(fresh)
                if rrs.size:
                    dmask[lo:hi][fresh] = True
                    self.metrics.inc("vertices_delivered", int(rrs.size))
                    by_round = self.dag._round_vertices
                    log_append = self.delivered_log.append
                    cb = self.on_deliver
                    lanes = self.lanes
                    # per-round source dict fetched once per run of
                    # consecutive slots (nonzero is round-major), and
                    # the existing v.id is reused — constructing a
                    # fresh VertexID per delivered slot was a visible
                    # slice of the n=256 commit path
                    cur = -1
                    d: Dict[int, Vertex] = {}
                    for rr, src in zip(rrs.tolist(), srcs.tolist()):
                        if rr != cur:
                            cur = rr
                            d = by_round[rr + lo_round]
                        v = d[src]
                        log_append(v.id)
                        if cb is not None:
                            if lanes is not None:
                                # carrier refs surface as payload bytes
                                # (fetch-on-miss inside); the id the log
                                # keeps is unchanged
                                v = lanes.resolve_vertex(v)
                            cb(v)
                        if (
                            trace
                            and src == self.index
                            and v.block.transactions
                        ):
                            # the proposer's own delivery closes the
                            # lifecycle chain opened by tx_propose
                            self.log.event(
                                "tx_deliver",
                                round=rr + lo_round,
                                source=src,
                            )
                self._epoch_note_delivery(leader, chunk_start)
                continue
            for rr, src in np.argwhere(fresh):
                vid = VertexID(int(rr) + lo_round, int(src))
                dmask[vid.round - base, vid.source] = True
                self.delivered_log.append(vid)
                self.metrics.inc("vertices_delivered")
                if self.on_deliver is not None:
                    v = self.dag.vertices[vid]
                    if self.lanes is not None:
                        v = self.lanes.resolve_vertex(v)
                    self.on_deliver(v)
                if trace and vid.source == self.index:
                    v = self.dag.vertices[vid]
                    if v.block.transactions:
                        self.log.event(
                            "tx_deliver", round=vid.round, source=vid.source
                        )
            self._epoch_note_delivery(leader, chunk_start)
        self.log.event(
            "delivered",
            count=len(self.delivered_log) - n_before,
            total=len(self.delivered_log),
        )
        if self._eager_mask is not None and self._eager:
            self._reconcile_eager(n_before)

    @property
    def delivered(self) -> Set[VertexID]:
        """Delivered vertex ids as a set, derived on demand —
        ``delivered_log`` (order) and ``_delivered_mask`` (dense dedup)
        are the authorities; nothing on the hot path reads this."""
        return set(self.delivered_log)

    def _rebuild_delivered_mask(self) -> None:
        """Re-derive the dense delivered bitmap from ``delivered_log`` —
        for callers (checkpoint restore) that replace the log wholesale."""
        base = self.dag.base_round
        self._delivered_mask = np.zeros_like(self.dag.exists)
        for vid in self.delivered_log:
            if vid.round >= base:
                self._delivered_mask[vid.round - base, vid.source] = True
        if self._eager_mask is not None:
            # a wholesale log replacement (checkpoint restore) voids the
            # speculative stream: restart it from the canonical state so
            # nothing already delivered is ever re-surfaced
            self._eager_mask = self._delivered_mask.copy()
            self.eager_log = []
            self._eager_cursor = 0

    # ------------------------------------------------------------------
    # Epoch reconfiguration (ISSUE 20)
    # ------------------------------------------------------------------

    @property
    def _wire_epoch(self) -> int:
        """Epoch id stamped on outgoing messages. 0 (static membership
        or epoch 0) makes the codec omit the epoch section entirely, so
        pre-epoch deployments keep byte-identical wire frames."""
        mgr = self.epoch_mgr
        return mgr.epoch if mgr is not None else 0

    def _epoch_reject_stale(self, msg: BroadcastMessage) -> None:
        """Count + trace one rejected pre-rotation message (the caller
        has already matched kind and compared epochs)."""
        self.metrics.inc("epoch_stale_rejected")
        self.log.event(
            "epoch_stale",
            kind=msg.kind,
            msg_epoch=msg.epoch,
            epoch=self.epoch_mgr.epoch,
            sender=msg.sender,
        )

    def _epoch_note_delivery(
        self, leader: Vertex, chunk_start: int
    ) -> None:
        """Entry seam for the epoch ladder (analysis/ladder.py): called
        once per committed leader chunk from :meth:`_order_vertices`.
        With reconfiguration off it falls through to the static-
        membership oracle; with it on, the chunk is scanned for control
        transactions and the boundary crossing is evaluated."""
        if self.epoch_mgr is None:
            self._epoch_static()
            return
        self._epoch_scan_chunk(leader, chunk_start)

    def _epoch_static(self) -> None:
        """Static-membership oracle: membership never changes, so a
        delivered chunk carries no reconfiguration consequence. The
        explicit seam (rather than an inlined no-op) is what lets the
        ladder checker prove the degradation edge stays intact."""

    def _epoch_scan_chunk(self, leader: Vertex, chunk_start: int) -> None:
        """Scan the chunk just delivered for ``leader`` (delivery-log
        entries from ``chunk_start`` on) for epoch control transactions,
        then cross the boundary if this chunk's wave reached it. Both
        halves are pure functions of the total order, so every correct
        process schedules and crosses identically."""
        mgr = self.epoch_mgr
        wave = self.cfg.wave_of_round(leader.round)
        had_boundary = mgr.boundary_wave
        accepted = 0
        vertices = self.dag.vertices
        for vid in self.delivered_log[chunk_start:]:
            v = vertices.get(vid)
            if v is not None and v.block.transactions:
                accepted += mgr.note_block(v.block, wave)
        if accepted:
            self.metrics.inc("epoch_ctrl_txs", accepted)
            if had_boundary is None and mgr.boundary_wave is not None:
                self.log.event(
                    "epoch_scheduled",
                    boundary=mgr.boundary_wave,
                    wave=wave,
                    ops=accepted,
                )
        if mgr.should_advance(wave):
            self._epoch_advance()

    def _epoch_advance(self) -> None:
        """Cross the pending boundary: rotate the threshold-coin keys
        (mode per cfg.epoch_rotate), retire the finished epoch's wave
        books (coin share/sigma entries and wave one-shot memos at or
        below the boundary — the planted-leak test pins this), and arm
        the epoch GC floor so the settled prefix prunes into the
        span-attested snapshot window."""
        mgr = self.epoch_mgr
        t = mgr.advance()
        b = t.boundary_wave
        self.metrics.inc("epoch_boundaries")
        self.metrics.counters["epoch_current"] = mgr.epoch
        mode = self.cfg.epoch_rotate
        if mode != "none" and getattr(self.coin, "keys", None) is not None:
            keys = derive_epoch_keys(
                t, self.cfg.n, self.cfg.f + 1, mode, self.index
            )
            if keys is not None:
                self.coin.rotate(keys, t.first_wave)
                self.metrics.inc("epoch_rotations")
        # Finished-epoch cleanup (satellite 3): waves <= B are settled
        # (the crossing itself proves decided_wave >= B), so their share
        # books and one-shot/memo entries are dead weight that the
        # round-floor prune would otherwise keep alive until the GC
        # window catches up.
        self.coin.prune_below(t.first_wave)
        self._pending_waves = {w for w in self._pending_waves if w > b}
        self._waves_spent = {w for w in self._waves_spent if w > b}
        self._waves_tried = {w for w in self._waves_tried if w > b}
        self._wave_try_memo = {
            w: f for w, f in self._wave_try_memo.items() if w > b
        }
        gc = self.cfg.gc_depth
        if gc is not None:
            # Epoch GC floor: keep epoch_gc rounds (default gc_depth)
            # behind the boundary's last round, clamped so it never
            # outruns the ordering rule's exclusion window for the next
            # possible leader (round 4B+1 delivers down to 4B+2-gc).
            # Applied at the NEXT maybe_prune — never mid-ordering,
            # where _order_vertices holds dense-array aliases.
            depth = self.cfg.epoch_gc or gc
            wl = self.cfg.wave_length
            floor = min(
                self.cfg.wave_round(b, wl) - depth,
                self.cfg.wave_round(b + 1, 1) - gc,
            )
            if self._epoch_gc_floor is None or floor > self._epoch_gc_floor:
                self._epoch_gc_floor = floor
        self.log.event(
            "epoch_advanced",
            epoch=mgr.epoch,
            boundary=b,
            ops=len(t.ops),
            seed=t.seed.hex()[:16],
        )

    def _epoch_retry_held_waves(self) -> bool:
        """While the barrier holds the round counter at the boundary's
        last round, the scalar oracle's one-shot boundary attempt for
        waves <= B has already been spent — but those waves keep filling
        as straggler vertices land, and the crossing cannot happen until
        one of them decides. Re-attempt them with the same fills-changed
        memo the pipelined pass uses (which is why pipelined mode needs
        no twin of this)."""
        mgr = self.epoch_mgr
        if (
            mgr is None
            or mgr.boundary_wave is None
            or self._pipelined_waves
        ):
            return False
        before = self.decided_wave
        wl = self.cfg.wave_length
        for w in range(self.decided_wave + 1, mgr.boundary_wave + 1):
            if w <= self.decided_wave:
                continue
            fills = (
                self.dag.round_size(self.cfg.wave_round(w, wl)),
                self.dag.round_size(self.cfg.wave_round(w, 1)),
            )
            if fills[0] < self.cfg.quorum:
                continue
            if self._wave_try_memo.get(w) == fills:
                continue
            self._wave_try_memo[w] = fills
            self._try_wave(w, quiet=True)
        return self.decided_wave > before

    # -- checkpoint seam ------------------------------------------------

    def epoch_state(self) -> Optional[Dict]:
        """JSON-serializable epoch manager state for checkpoint
        manifests and snapshot heads (None = static membership)."""
        mgr = self.epoch_mgr
        if mgr is None:
            return None
        return {
            "epoch": mgr.epoch,
            "seed": mgr.seed.hex(),
            "epoch_waves": mgr.epoch_waves,
            "boundary_wave": mgr.boundary_wave,
            "pending_ops": [
                [wave, op.kind, op.target, op.nonce, op.payload.hex()]
                for wave, op in mgr.pending_ops
            ],
            "last_boundary": (
                mgr.history[-1].boundary_wave if mgr.history else 0
            ),
        }

    def restore_epoch_state(self, d: Optional[Dict]) -> None:
        """Install checkpointed epoch state (inverse of
        :meth:`epoch_state`) and re-derive the restored epoch's coin
        keys — both rotation modes chain every input from the committed
        seed, so a joiner lands on the exact key set the survivors
        rotated to at the original crossing."""
        import hashlib as _hashlib

        mgr = self.epoch_mgr
        if mgr is None or not d:
            return
        mgr.epoch = int(d.get("epoch", 0))
        seed_hex = d.get("seed")
        if seed_hex:
            mgr.seed = bytes.fromhex(seed_hex)
        bw = d.get("boundary_wave")
        mgr.boundary_wave = int(bw) if bw is not None else None
        mgr.pending_ops = []
        mgr._seen = set()
        for wave, kind, target, nonce, payload in d.get(
            "pending_ops", []
        ):
            op = EpochOp(
                kind=kind,
                target=int(target),
                nonce=int(nonce),
                payload=bytes.fromhex(payload),
            )
            mgr._seen.add(
                _hashlib.sha256(encode_epoch_op(op)).digest()
            )
            mgr.pending_ops.append((int(wave), op))
        last_b = int(d.get("last_boundary", 0))
        if (
            mgr.epoch > 0
            and self.cfg.epoch_rotate != "none"
            and getattr(self.coin, "keys", None) is not None
        ):
            t = EpochTransition(
                epoch=mgr.epoch,
                boundary_wave=last_b,
                seed=mgr.seed,
                ops=(),
            )
            keys = derive_epoch_keys(
                t,
                self.cfg.n,
                self.cfg.f + 1,
                self.cfg.epoch_rotate,
                self.index,
            )
            if keys is not None:
                self.coin.rotate(keys, t.first_wave)
        self.metrics.counters["epoch_current"] = mgr.epoch
