from dag_rider_tpu.consensus.coin import CommonCoin, FixedCoin, RoundRobinCoin
from dag_rider_tpu.consensus.dag_state import DagState
from dag_rider_tpu.consensus.process import Process
from dag_rider_tpu.consensus.simulator import RandomizedScheduler, Simulation

__all__ = [
    "CommonCoin",
    "FixedCoin",
    "RoundRobinCoin",
    "DagState",
    "Process",
    "RandomizedScheduler",
    "Simulation",
]
