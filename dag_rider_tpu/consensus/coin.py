"""Common-coin (wave leader election) implementations.

The reference's ``chooseLeader`` is a stub that always returns 1
(``process/process.go:386-392``) with a TODO naming the real design: "PKI
and a threshold signature scheme with a threshold of (f+1)-of-n"
(``process.go:388``). The coin must satisfy agreement, termination,
unpredictability and fairness (``process.go:386-387``).

Three implementations:

- :class:`FixedCoin` — the reference stub's semantics (constant leader),
  kept for differential testing against the reference's intent; predictable,
  breaks liveness against an adaptive adversary (SURVEY.md D9).
- :class:`RoundRobinCoin` — deterministic wave-indexed rotation. Fair and
  live against *static* adversaries; still predictable. Default for tests.
- ``ThresholdCoin`` (:mod:`dag_rider_tpu.crypto.threshold`) — the real
  (f+1)-of-n threshold-BLS coin; shares are piggybacked on round(w,4)
  vertices so the coin is revealed only once the wave is complete.
"""

from __future__ import annotations

import abc
from typing import Optional


class CommonCoin(abc.ABC):
    """Leader-election oracle for waves.

    ``observe_share`` feeds coin shares extracted from delivered vertices;
    ``ready`` says whether wave w's coin can be evaluated; ``choose_leader``
    returns the elected source index (must be identical at every correct
    process — the agreement property).
    """

    @abc.abstractmethod
    def ready(self, wave: int) -> bool: ...

    @abc.abstractmethod
    def choose_leader(self, wave: int) -> int: ...

    def my_share(self, wave: int) -> Optional[bytes]:
        """Share this process contributes for wave ``wave`` (piggybacked on
        its round(w,4) vertex). None for share-less coins."""
        return None

    def observe_share(self, wave: int, source: int, share: bytes) -> None:
        """Ingest another process's share. No-op for share-less coins."""


class FixedCoin(CommonCoin):
    """Constant leader — reference-stub semantics (``process.go:390-392``),
    with the constant made explicit instead of hardcoded."""

    def __init__(self, leader: int = 0):
        self._leader = leader

    def ready(self, wave: int) -> bool:
        return True

    def choose_leader(self, wave: int) -> int:
        return self._leader


class RoundRobinCoin(CommonCoin):
    """Wave-indexed rotation: leader(w) = w mod n. Deterministic and fair
    (every source leads infinitely often); not unpredictable."""

    def __init__(self, n: int):
        self.n = n

    def ready(self, wave: int) -> bool:
        return True

    def choose_leader(self, wave: int) -> int:
        return wave % self.n
