"""Common-coin (wave leader election) implementations.

The reference's ``chooseLeader`` is a stub that always returns 1
(``process/process.go:386-392``) with a TODO naming the real design: "PKI
and a threshold signature scheme with a threshold of (f+1)-of-n"
(``process.go:388``). The coin must satisfy agreement, termination,
unpredictability and fairness (``process.go:386-387``).

Three implementations:

- :class:`FixedCoin` — the reference stub's semantics (constant leader),
  kept for differential testing against the reference's intent; predictable,
  breaks liveness against an adaptive adversary (SURVEY.md D9).
- :class:`RoundRobinCoin` — deterministic wave-indexed rotation. Fair and
  live against *static* adversaries; still predictable. Default for tests.
- ``ThresholdCoin`` (:mod:`dag_rider_tpu.crypto.threshold`) — the real
  (f+1)-of-n threshold-BLS coin; shares are piggybacked on round(w,4)
  vertices so the coin is revealed only once the wave is complete.
"""

from __future__ import annotations

import abc
from typing import Optional


class CommonCoin(abc.ABC):
    """Leader-election oracle for waves.

    ``observe_share`` feeds coin shares extracted from delivered vertices;
    ``ready`` says whether wave w's coin can be evaluated; ``choose_leader``
    returns the elected source index (must be identical at every correct
    process — the agreement property).
    """

    @abc.abstractmethod
    def ready(self, wave: int) -> bool: ...

    @abc.abstractmethod
    def choose_leader(self, wave: int) -> int: ...

    def my_share(self, wave: int) -> Optional[bytes]:
        """Share this process contributes for wave ``wave`` (piggybacked on
        its round(w,4) vertex). None for share-less coins."""
        return None

    def observe_share(self, wave: int, source: int, share: bytes) -> None:
        """Ingest another process's share. No-op for share-less coins."""

    def prune_below(self, wave: int) -> None:
        """Drop per-wave state below ``wave`` (the GC floor's wave) —
        no-op for stateless coins. Called by Process.maybe_prune so the
        coin's books follow the same bounded window as the DAG and the
        RBC stage."""

    def rotate(self, keys, from_wave: int) -> None:
        """Install rotated threshold keys effective for waves >=
        ``from_wave`` (ISSUE 20 epoch boundary) — no-op for keyless
        coins, whose leader schedule is wave-indexed and survives any
        membership epoch unchanged."""


class FixedCoin(CommonCoin):
    """Constant leader — reference-stub semantics (``process.go:390-392``),
    with the constant made explicit instead of hardcoded."""

    def __init__(self, leader: int = 0):
        self._leader = leader

    def ready(self, wave: int) -> bool:
        return True

    def choose_leader(self, wave: int) -> int:
        return self._leader


class RoundRobinCoin(CommonCoin):
    """Wave-indexed rotation: leader(w) = w mod n. Deterministic and fair
    (every source leads infinitely often); not unpredictable."""

    def __init__(self, n: int):
        self.n = n

    def ready(self, wave: int) -> bool:
        return True

    def choose_leader(self, wave: int) -> int:
        return wave % self.n


class ThresholdCoin(CommonCoin):
    """(f+1)-of-n threshold-BLS coin (crypto/threshold.py) — the design
    the reference's TODO names (``process.go:388``).

    Shares arrive piggybacked on round(w,4) vertices via
    ``observe_share``; the coin becomes ready once f+1 shares combine into
    a group signature that passes the pairing check. Aggregation is lazy
    and cached; if a combination fails (a Byzantine share slipped in),
    shares are verified individually, the bad ones discarded, and the
    remainder re-combined — so one corrupt share cannot stall the coin.
    """

    def __init__(self, keys, index: int, n: int, *, msm=None):
        from dag_rider_tpu.crypto import threshold as th

        self._th = th
        self.keys = keys
        self.index = index
        self.n = n
        self._msm = msm
        #: epoch key schedule (ISSUE 20): (first_wave, keys) entries,
        #: ascending. ``keys`` above always aliases the newest entry;
        #: :meth:`_keys_for` resolves the keys a given wave signs and
        #: verifies under, so a boundary rotation never invalidates
        #: shares already piggybacked for pre-boundary waves.
        self._schedule: list = [(1, keys)]
        self._shares: dict = {}
        self._sigma: dict = {}
        self._tried_at: dict = {}
        #: shares discarded by the batched bad-share filter, cumulative —
        #: under SUSTAINED pollution (a garbage-share adversary feeding
        #: junk every wave, consensus/adversary.py) this counts the
        #: recovery work wave after wave; the single-bad-share case is
        #: just its first increment
        self.filtered = 0

    def _keys_for(self, wave: int):
        """The key set wave ``wave`` operates under: the newest schedule
        entry whose first_wave is <= wave."""
        keys = self._schedule[0][1]
        for first, k in self._schedule:
            if first > wave:
                break
            keys = k
        return keys

    def rotate(self, keys, from_wave: int) -> None:
        """Install rotated keys for waves >= ``from_wave`` and make them
        the default for share signing. Aggregation state for pending
        waves is reset — any share that arrived early for a post-boundary
        wave must be re-judged under the keys that wave now verifies
        against (stale-epoch shares fail the pairing filter and are
        discarded, not trusted)."""
        if self._schedule[-1][0] >= from_wave:
            self._schedule = [
                (f, k) for f, k in self._schedule if f < from_wave
            ]
        self._schedule.append((from_wave, keys))
        self.keys = keys
        for w in [w for w in self._sigma if w >= from_wave]:
            del self._sigma[w]
        for w in [w for w in self._tried_at if w >= from_wave]:
            del self._tried_at[w]

    def my_share(self, wave: int):
        keys = self._keys_for(wave)
        sk = keys.share_sks[self.index]
        if sk is None:
            return None
        return self._th.sign_share(sk, wave)

    def observe_share(self, wave: int, source: int, share: bytes) -> None:
        if not isinstance(share, (bytes, bytearray)) or len(share) != 48:
            return
        self._shares.setdefault(wave, {}).setdefault(source, bytes(share))

    def _try_aggregate(self, wave: int) -> None:
        if wave in self._sigma:
            return
        keys = self._keys_for(wave)
        shares = self._shares.get(wave, {})
        if len(shares) < keys.threshold:
            return
        have = frozenset(shares)
        if self._tried_at.get(wave) == have:
            return  # no new shares since the last failed attempt
        self._tried_at[wave] = have
        sigma = self._th.aggregate(shares, keys.threshold, msm=self._msm)
        if sigma is not None and self._th.verify_group(
            keys.group_pk, wave, sigma
        ):
            self._sigma[wave] = sigma
            return
        # Byzantine share in the first combination: batched filter (RLC +
        # GT-defect localization — one pairing product for the honest
        # remainder instead of one pairing per share).
        good = self._th.batch_verify_shares(
            keys.share_pks, wave, shares, msm=self._msm
        )
        self.filtered += len(shares) - len(good)
        self._shares[wave] = good
        if len(good) >= keys.threshold:
            sigma = self._th.aggregate(good, keys.threshold, msm=self._msm)
            if sigma is not None:
                self._sigma[wave] = sigma

    def prune_below(self, wave: int) -> None:
        """Retire share/sigma/attempt books for waves below ``wave``.
        Safe: the retro leader chain only walks waves above the decided
        cursor, and the GC floor sits gc_depth rounds below it."""
        for d in (self._shares, self._sigma, self._tried_at):
            for w in [w for w in d if w < wave]:
                del d[w]
        # retire key-schedule entries wholly below the floor, keeping
        # the entry in force AT the floor wave (still needed to verify
        # shares for every surviving wave)
        while len(self._schedule) > 1 and self._schedule[1][0] <= wave:
            self._schedule.pop(0)

    def ready(self, wave: int) -> bool:
        self._try_aggregate(wave)
        return wave in self._sigma

    def choose_leader(self, wave: int) -> int:
        if not self.ready(wave):
            raise RuntimeError(f"coin for wave {wave} not ready")
        return self._th.leader_from_sigma(self._sigma[wave], self.n)
