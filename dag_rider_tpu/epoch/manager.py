"""Epoch manager: deterministic boundary scheduling + key derivation.

Everything here is a pure function of the ordered log. A control
transaction committed in (the delivery chunk of) wave ``w`` schedules the
next boundary at the first multiple of ``epoch_waves`` that leaves at
least :data:`MIN_SLACK_WAVES` waves of runway — the slack guarantees
every correct process learns the boundary (by delivering the scheduling
chunk) before any round past the boundary can gather a quorum, because
:meth:`Process._try_advance` holds round advancement at the boundary's
last round until the local epoch has crossed (the barrier; see
``process.py``). Since the total order is identical at every correct
process, so are the boundary, the op batch, the epoch seed, and hence
the rotated keys.

The epoch **seed** chains: ``seed_{e+1} = H(domain | seed_e | e+1 |
boundary | ops...)``, with every committed op's canonical encoding
folded in. An adversary can pick its ops' bytes, but it cannot bias the
seed after commitment any more than it can rewrite the ordered log —
the same argument the committed-transcript coin designs make.
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import List, Optional, Set, Tuple

from dag_rider_tpu.core.codec import encode_epoch_op, epoch_op_of
from dag_rider_tpu.core.types import Block, EpochOp

#: minimum waves between a scheduling chunk's wave and the boundary it
#: schedules: the barrier needs every correct process to deliver the
#: scheduling chunk (and so learn the boundary) before the boundary's
#: last round can quorum, and one wave of slack is not enough once wave
#: evaluation pipelines — two keeps a full wave of retroactive-commit
#: runway between "the tx is visible" and "rounds stop".
MIN_SLACK_WAVES = 2

_SEED_DOMAIN = b"dagrider-epoch-seed-v1|"
_GENESIS_SEED = b"dagrider-epoch-genesis-v1"


def epoch_seed(
    prev_seed: bytes,
    epoch: int,
    boundary_wave: int,
    ops: Tuple[Tuple[int, EpochOp], ...],
) -> bytes:
    """The deterministic seed for ``epoch`` (the epoch being entered)."""
    h = hashlib.sha512()
    h.update(_SEED_DOMAIN)
    h.update(prev_seed)
    h.update(epoch.to_bytes(8, "little"))
    h.update(boundary_wave.to_bytes(8, "little"))
    for wave, op in ops:
        h.update(wave.to_bytes(8, "little"))
        h.update(encode_epoch_op(op))
    return h.digest()[:32]


@dataclasses.dataclass(frozen=True)
class EpochTransition:
    """One crossed boundary: the epoch being entered, the last wave of
    the epoch just finished, the chained seed, and the op batch that
    rode into it (in delivery order)."""

    epoch: int
    boundary_wave: int
    seed: bytes
    ops: Tuple[Tuple[int, EpochOp], ...]

    @property
    def first_wave(self) -> int:
        """First wave governed by the new epoch's keys."""
        return self.boundary_wave + 1


def derive_epoch_keys(
    transition: EpochTransition,
    n: int,
    threshold: int,
    mode: str,
    index: int,
):
    """The new :class:`~dag_rider_tpu.crypto.threshold.ThresholdKeys`
    for ``transition``, or None when ``mode`` is "none".

    "seed" runs the deterministic seeded dealer — every process derives
    the identical full key set from the committed transcript, the cheap
    path for in-process clusters and tests. "dkg" runs the full
    joint-Feldman resharing flow (:func:`dag_rider_tpu.crypto.dkg.
    run_resharing`) and returns this participant's dealerless view —
    the group pk and share pks still agree across processes because the
    resharing's inputs all chain from the same committed seed.
    """
    if mode == "none":
        return None
    if mode == "seed":
        from dag_rider_tpu.crypto.threshold import ThresholdKeys

        return ThresholdKeys.generate(n, threshold, seed=transition.seed)
    if mode == "dkg":
        from dag_rider_tpu.crypto.dkg import run_resharing

        results = run_resharing(n, threshold, transition.seed)
        for r in results:
            if r.index == index:
                return r.to_keys()
        raise RuntimeError(
            f"resharing produced no result for participant {index}"
        )
    raise ValueError(f"unknown epoch rotation mode {mode!r}")


class EpochManager:
    """Schedules boundaries and accumulates op batches from delivered
    blocks. One instance per :class:`Process`; all of its state is a
    deterministic function of the delivery stream it is fed, so two
    managers fed the same total order are bit-identical — the property
    every test in tests/test_epoch.py leans on.
    """

    def __init__(self, epoch_waves: int, *, epoch: int = 0,
                 seed: Optional[bytes] = None):
        if epoch_waves < 1:
            raise ValueError(f"epoch_waves must be >= 1, got {epoch_waves}")
        self.epoch_waves = epoch_waves
        #: current (active) epoch id — what outgoing messages are tagged
        #: with and what the stale gate compares against
        self.epoch = epoch
        #: chained seed of the ACTIVE epoch (genesis constant for epoch 0
        #: unless restored from a checkpoint)
        self.seed = seed if seed is not None else _GENESIS_SEED
        #: pending boundary wave (None = nothing scheduled)
        self.boundary_wave: Optional[int] = None
        #: committed ops awaiting the pending boundary, delivery order
        self.pending_ops: List[Tuple[int, EpochOp]] = []
        #: crossed transitions, oldest first (bounded: one per epoch)
        self.history: List[EpochTransition] = []
        #: dedup keys for ops already accepted into the current batch —
        #: client retries commit the same bytes twice; every process
        #: sees the same duplicates in the same order, so dropping
        #: repeats is deterministic
        self._seen: Set[bytes] = set()

    # -- scheduling --------------------------------------------------------

    def _schedule_from(self, wave: int) -> int:
        w = self.epoch_waves
        boundary = ((wave // w) + 1) * w
        while boundary - wave < MIN_SLACK_WAVES:
            boundary += w
        return boundary

    def observe_op(self, op: EpochOp, wave: int) -> bool:
        """Record one committed control op from wave ``wave``'s delivery
        chunk. Returns True when the op entered the batch (False for an
        in-batch duplicate)."""
        key = hashlib.sha256(encode_epoch_op(op)).digest()
        if key in self._seen:
            return False
        self._seen.add(key)
        self.pending_ops.append((wave, op))
        if self.boundary_wave is None:
            self.boundary_wave = self._schedule_from(wave)
        return True

    def note_block(self, block: Block, wave: int) -> int:
        """Scan a delivered block for control transactions; returns how
        many entered the batch. Malformed magic-prefixed transactions
        are payload bytes (codec.epoch_op_of) and every correct process
        skips them identically."""
        accepted = 0
        for tx in block.transactions:
            op = epoch_op_of(tx)
            if op is not None and self.observe_op(op, wave):
                accepted += 1
        return accepted

    # -- crossing ----------------------------------------------------------

    def should_advance(self, delivered_wave: int) -> bool:
        """True once ``delivered_wave`` has reached the pending
        boundary: the chunk for the boundary wave itself is the last
        pre-rotation delivery."""
        return (
            self.boundary_wave is not None
            and delivered_wave >= self.boundary_wave
        )

    def advance(self) -> EpochTransition:
        """Cross the pending boundary: bump the epoch, chain the seed,
        archive the transition, and reset the op batch."""
        if self.boundary_wave is None:
            raise RuntimeError("no boundary pending")
        boundary = self.boundary_wave
        ops = tuple(self.pending_ops)
        nxt = self.epoch + 1
        seed = epoch_seed(self.seed, nxt, boundary, ops)
        transition = EpochTransition(
            epoch=nxt, boundary_wave=boundary, seed=seed, ops=ops
        )
        self.epoch = nxt
        self.seed = seed
        self.boundary_wave = None
        self.pending_ops = []
        self._seen = set()
        self.history.append(transition)
        return transition

    # -- round barrier -----------------------------------------------------

    def hold_round(self, rnd: int, wave_length: int) -> bool:
        """True when creating a vertex in round ``rnd`` must wait for
        the pending boundary to be crossed first: rounds past the
        boundary's last round belong to the next epoch and must carry
        next-epoch coin shares. Rounds at or below the boundary flow
        freely — the boundary wave itself has to complete for the
        crossing to ever happen."""
        if self.boundary_wave is None:
            return False
        return rnd > self.boundary_wave * wave_length
