"""Epoch reconfiguration (ISSUE 20): validator-set changes ordered
through consensus itself.

The subsystem closes ROADMAP item 2's "run forever" gap: reconfiguration
requests (join / leave / key-rotation) ride the mempool as magic-prefixed
control transactions (:data:`dag_rider_tpu.core.codec.EPOCH_MAGIC`),
commit through the ordinary total order, and take effect at a
deterministic **epoch boundary** — a wave number every correct process
derives identically from the ordered log — where the threshold-coin keys
rotate (seeded dealer or full joint-Feldman resharing over
:mod:`dag_rider_tpu.crypto.dkg`), stale pre-rotation messages start
bouncing off the receive seam via the epoch id in the wire form, and the
settled epoch's DAG prefix feeds span-certificate-attested snapshots
(:mod:`dag_rider_tpu.utils.checkpoint`) that a joiner verifies with a
handful of pairing checks instead of replaying pruned history.
"""

from dag_rider_tpu.epoch.manager import (
    EpochManager,
    EpochTransition,
    derive_epoch_keys,
    epoch_seed,
)

__all__ = [
    "EpochManager",
    "EpochTransition",
    "derive_epoch_keys",
    "epoch_seed",
]
