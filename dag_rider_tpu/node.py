"""Runnable committee node — ``python -m dag_rider_tpu.node``.

The reference is a library with no main package (SURVEY §3); a framework
needs a deployment shape. This wires the full stack for one participant:
gRPC transport (+ optional Bracha RBC), the batched device Verifier,
Ed25519 vertex signing, the threshold-BLS (or round-robin) coin, periodic
checkpointing, and structured logs — all from one JSON config.

Subcommands:

- ``keygen --n 4 --threshold 2 --out keys.json`` — dealer-style committee
  key material: Ed25519 registry + per-node seeds, threshold-BLS shares.
  (Deterministic dealer = test/deploy convenience; a production committee
  would run a DKG so nobody ever holds the group secret.)
- ``run --config node0.json`` — start one node and pump until stopped.

Config (JSON):
{
  "index": 0, "n": 4, "listen": "127.0.0.1:7000",
  "peers": {"1": "127.0.0.1:7001", ...},
  "keys": "keys.json",            // from keygen
  "rbc": true,                     // Bracha reliable broadcast stage
  "verifier": "device",            // | "sharded" | "cpu" | "remote" | "none"
  "verify_bucket": 16384,          // optional: fixed dispatch bucket
  "verify_depth": 2,               // optional: in-flight dispatch window
  "verify_prep_workers": 4,        // optional: parallel host-prep workers
  "verify_warmup": true,           // AOT-compile the bucket at startup
  "verify_fallback": "cpu",        // optional: degradation-ladder floor
                                   // under device/sharded/remote
                                   // (default DAGRIDER_VERIFY_FALLBACK)
  "verify_retry": 1,               // optional: retries per ladder tier /
                                   // sidecar attempt resends
                                   // (default DAGRIDER_VERIFY_RETRY)
  "coin": "threshold_bls",         // | "round_robin" | "fixed"
  "coin_msm": "host",              // "device": share aggregation on the mesh
  "cert": "agg",                   // aggregated round certificates (ISSUE 9):
                                   // one BLS aggregate check admits a whole
                                   // round; default "off" (per-vertex path);
                                   // env default DAGRIDER_CERT
  "cert_msm": "host",              // | "device" | "sharded" — certificate
                                   // aggregation seam (DAGRIDER_CERT_MSM)

  "checkpoint_dir": "ckpt/node0",  // optional, periodic + on shutdown
  "checkpoint_every_s": 30,
  "submit_interval_s": 0.5,        // synthetic client load (0: none)

  "mempool": true,                 // round 10: admission + batching
                                   // front door (dag_rider_tpu/mempool).
                                   // true = env-tuned knobs
                                   // (DAGRIDER_MEMPOOL_CAP etc.), or a
                                   // dict of MempoolConfig overrides:
                                   // {"cap": 65536, "batch_bytes": 8192,
                                   //  "batch_deadline_ms": 50, ...}.
                                   // Absent/false = the legacy direct
                                   // one-block-per-submit path.
  "auto_propose": false            // explicit gate on the synthetic
                                   // n{i}-auto-{seq} generator; defaults
                                   // ON only when no mempool is attached
                                   // (load tests through the mempool
                                   // must measure injected traffic only)
}
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import threading
import time
from collections import deque
from typing import Deque, Dict, Optional

from dag_rider_tpu.config import Config
from dag_rider_tpu.consensus.coin import FixedCoin, RoundRobinCoin, ThresholdCoin
from dag_rider_tpu.consensus.process import Process
from dag_rider_tpu.core.types import Block
from dag_rider_tpu.crypto import threshold as th
from dag_rider_tpu.transport.rbc import RbcTransport
from dag_rider_tpu.utils import checkpoint
from dag_rider_tpu.utils.slog import EventLog, NOOP, stdlib_sink
from dag_rider_tpu.verifier.base import KeyRegistry, VertexSigner


# ----------------------------------------------------------------------
# keygen
# ----------------------------------------------------------------------

def generate_keys(
    n: int, threshold: int, seed: Optional[str] = None
) -> dict:
    """Committee key material as one JSON-serializable dict.

    ``seed`` pins the material deterministically — tests/fixtures only.
    Left unset (the CLI default), a fresh 256-bit secret is drawn from
    os.urandom: a guessable seed makes every identity seed publicly
    re-derivable, which in turn voids the DKG's share confidentiality
    (anyone can compute the pairwise channel keys offline)."""
    if seed is None:
        import secrets

        seed = secrets.token_hex(32)
    reg, seeds = KeyRegistry.generate(n, seed_prefix=seed.encode() + b"|ed|")
    coin_keys = th.ThresholdKeys.generate(n, threshold, seed=seed.encode())
    from dag_rider_tpu.crypto import bls12381 as bls

    # per-node BLS certificate keys (ISSUE 9 aggregated round
    # certificates) — distinct from the threshold-coin shares: cert
    # signatures are independent per node, never Shamir-combined
    import hashlib

    cert_sks = [
        int.from_bytes(
            hashlib.sha256(
                seed.encode() + b"|cert|" + str(i).encode()
            ).digest(),
            "big",
        )
        % bls.R
        for i in range(n)
    ]
    return {
        "n": n,
        "threshold": threshold,
        "ed25519_public": [pk.hex() for pk in reg.public_keys],
        "ed25519_seeds": [s.hex() for s in seeds],
        "bls_group_pk": bls.g2_serialize(coin_keys.group_pk).hex(),
        "bls_share_pks": [
            bls.g2_serialize(pk).hex() for pk in coin_keys.share_pks
        ],
        "bls_share_sks": [hex(sk) for sk in coin_keys.share_sks],
        "bls_cert_pks": [
            bls.g2_serialize(bls.pk_of(sk)).hex() for sk in cert_sks
        ],
        "bls_cert_sks": [hex(sk) for sk in cert_sks],
    }


def _dump_secret_file(path: str, blob: dict) -> None:
    """Write a key file owner-readable only (0600): these carry Ed25519
    seeds / BLS share secrets, and a world-readable default would hand
    any local user the node's DKG channel keys."""
    fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
    # open()'s mode only applies on CREATION — overwriting a
    # pre-existing world-readable file must tighten it too
    os.fchmod(fd, 0o600)
    with os.fdopen(fd, "w") as fh:
        json.dump(blob, fh, indent=1)


def load_keys(blob: dict):
    """(KeyRegistry, seeds, ThresholdKeys) from a keygen dict."""
    from dag_rider_tpu.crypto import bls12381 as bls

    reg = KeyRegistry(tuple(bytes.fromhex(pk) for pk in blob["ed25519_public"]))
    # DKG-produced files scrub other nodes' identity seeds (null)
    seeds = [
        bytes.fromhex(s) if s else None for s in blob["ed25519_seeds"]
    ]
    coin_keys = th.ThresholdKeys(
        blob["threshold"],
        bls.g2_deserialize(bytes.fromhex(blob["bls_group_pk"])),
        [bls.g2_deserialize(bytes.fromhex(p)) for p in blob["bls_share_pks"]],
        # DKG-produced files carry only this node's secret (null
        # elsewhere) — the dealerless property
        [int(sk, 16) if sk else None for sk in blob["bls_share_sks"]],
    )
    if blob.get("bls_cert_pks"):
        # certificate PKI rides the same registry (ISSUE 9); older key
        # files without it simply leave the cert path gated off
        import dataclasses

        reg = dataclasses.replace(
            reg,
            bls_public_keys=tuple(
                bls.g2_deserialize(bytes.fromhex(p))
                for p in blob["bls_cert_pks"]
            ),
        )
    return reg, seeds, coin_keys


# ----------------------------------------------------------------------
# node
# ----------------------------------------------------------------------

class Node:
    """One running participant; owns the pump thread."""

    def __init__(self, cfg: dict, *, log: Optional[EventLog] = None):
        n = int(cfg["n"])
        index = int(cfg["index"])
        gc_depth = cfg.get("gc_depth")
        self.ccfg = Config(
            n=n,
            coin=cfg.get("coin", "round_robin"),
            propose_empty=bool(cfg.get("propose_empty", True)),
            # bounded DAG memory for long-running nodes (None = grow
            # forever, reference-compatible)
            gc_depth=int(gc_depth) if gc_depth is not None else None,
            # hot-path pump flavor; None defers to DAGRIDER_PUMP / scalar
            pump=cfg.get("pump"),
            # aggregated round certificates; None defers to DAGRIDER_CERT
            cert=cfg.get("cert"),
            # certificate patience is counted in quiescent pump ticks
            # (~ms each): the in-process default of 6 is far too tight
            # for share aggregation over real sockets, so the cluster
            # harness overrides it per node
            cert_patience=int(cfg.get("cert_patience", 6)),
        )
        with open(cfg["keys"]) as fh:
            keyblob = json.load(fh)
        reg, seeds, coin_keys = load_keys(keyblob)
        if reg.n != n:
            raise ValueError(f"keys are for n={reg.n}, config says n={n}")

        # Causal tracing + flight recorder (ISSUE 13, DAGRIDER_TRACE):
        # tee the ring recorder and the flight trigger watch into
        # whatever sink the caller brought (e.g. --verbose's stdlib
        # bridge), so pump_error / verify_exhausted leave a post-mortem.
        from dag_rider_tpu import obs

        self.tracing = None
        if obs.trace_enabled():
            self.tracing = obs.build_tracing(
                base_sink=log.sink if log is not None else None,
                context={"node": index},
            )
            log = self.tracing.log
        self.log = log if log is not None else NOOP
        peers: Dict[int, str] = {int(k): v for k, v in cfg.get("peers", {}).items()}
        # Lazy: transport/net.py imports grpc at module scope, and grpcio
        # is the optional [net] extra — keygen must work without it.
        from dag_rider_tpu.transport.net import GrpcTransport, WanFault

        # WAN emulation at the real send seam (ISSUE 19): the cluster
        # harness sets {"wan": {"delay_ms": [lo, hi], "delay_rate": p,
        # "drop": p, "seed": s}} so delay/drop apply to genuine gRPC
        # sends between OS processes, not a simulator queue. Seed is
        # offset by index so peers do not fault in lockstep.
        wan = cfg.get("wan")
        send_fault = None
        if wan:
            send_fault = WanFault(
                seed=int(wan.get("seed", 0)) + index,
                delay_ms=tuple(wan.get("delay_ms", (0.0, 0.0))),
                delay_rate=float(wan.get("delay_rate", 1.0)),
                drop=float(wan.get("drop", 0.0)),
            )

        auth = None
        master_hex = cfg.get("auth_master")
        if master_hex:
            # Pairwise-MAC frame auth (transport/auth.py): the cluster
            # dealer puts one shared master secret in every node's config;
            # each node derives only its own key row. Without it the
            # Deliver endpoint accepts forged control frames (VERDICT r3
            # missing #5).
            from dag_rider_tpu.transport.auth import FrameAuth

            auth = FrameAuth.for_node(bytes.fromhex(master_hex), index, n)
        snap_fresh = cfg.get("snapshot_freshness_s", 300.0)
        self.net = GrpcTransport(
            index,
            cfg["listen"],
            peers,
            auth=auth,
            # Peer state transfer (elastic recovery past the GC horizon):
            # serve our live DAG window; it is self-certifying, see
            # utils.checkpoint.restore_from_snapshot. Attested (ISSUE
            # 20): the envelope carries our verified span chain so a
            # joiner settles the window with ~1 pairing per span; falls
            # back to the plain blob when no spans are banked.
            snapshot_provider=lambda: checkpoint.attested_snapshot_bytes(
                self.process
            ),
            # Donor-side availability knobs: per-relayer serve interval,
            # and the request-timestamp freshness window (fleets with
            # known clock skew widen it; null in the JSON config
            # disables freshness checking entirely).
            snapshot_min_interval_s=float(
                cfg.get("snapshot_min_interval_s", 1.0)
            ),
            snapshot_freshness_s=(
                None if snap_fresh is None else float(snap_fresh)
            ),
            send_fault=send_fault,
            log=self.log,
        )
        transport = self.net
        if cfg.get("rbc", True):
            transport = RbcTransport(self.net, index, n, self.ccfg.f)

        verifier = None
        kind = cfg.get("verifier", "device")
        # Round-9 resilience knobs. "verify_fallback": "cpu" ladders the
        # configured verifier onto a CPUVerifier floor (ResilientVerifier:
        # bounded per-tier retry, background health probe + promotion, a
        # batch rejected only after the whole ladder fails).
        # "verify_retry" is the per-tier retry count (and the sidecar's
        # resend count for a bare "remote"). Explicit config beats the
        # DAGRIDER_VERIFY_FALLBACK / DAGRIDER_VERIFY_RETRY env defaults.
        from dag_rider_tpu.verifier.resilient import (
            default_verify_fallback,
            default_verify_retry,
        )

        fallback = cfg.get("verify_fallback")
        fallback = (
            default_verify_fallback() if fallback is None else str(fallback)
        )
        if fallback and fallback != "cpu":
            raise ValueError(
                f'verify_fallback must be "cpu" or empty, got {fallback!r}'
            )
        retry = cfg.get("verify_retry")
        retry = default_verify_retry() if retry is None else int(retry)

        def _ladder(primary):
            from dag_rider_tpu.verifier.cpu import CPUVerifier
            from dag_rider_tpu.verifier.resilient import ResilientVerifier

            return ResilientVerifier(
                [primary, CPUVerifier(reg)], retries=retry, log=self.log
            )

        if kind in ("device", "sharded"):
            # Production entry-path parity with bench/tests: repo-local
            # XLA compile cache, then wrap the device verifier in a
            # depth-K dispatch window whose construction AOT-compiles
            # the fixed-bucket program — the first consensus round must
            # not eat a cold ~35 s XLA compile. "sharded" shares every
            # knob (verify_bucket/verify_depth/verify_warmup) and lays
            # the batch over a device mesh sized by DAGRIDER_MESH
            # (virtual-device fallback on CPU — parallel/mesh.py); its
            # bucket rounds up to a mesh multiple internally, masks stay
            # byte-identical to the single-chip program.
            from dag_rider_tpu.utils.jaxcache import enable_persistent_cache
            from dag_rider_tpu.verifier.pipeline import VerifierPipeline
            from dag_rider_tpu.verifier.tpu import TPUVerifier

            enable_persistent_cache()
            if kind == "sharded":
                from dag_rider_tpu.parallel.mesh import mesh_from_env
                from dag_rider_tpu.parallel.sharded_verifier import (
                    ShardedTPUVerifier,
                )

                base = ShardedTPUVerifier(reg, mesh_from_env())
            else:
                base = TPUVerifier(reg)
            bucket = cfg.get("verify_bucket")
            if bucket:
                base.fixed_bucket = int(bucket)
            # parallel host-prep engine (verifier/prep.py): explicit
            # config beats the DAGRIDER_PREP_WORKERS env default
            prep = cfg.get("verify_prep_workers")
            if prep:
                base.prep_workers = int(prep)
            depth = cfg.get("verify_depth")
            verifier = VerifierPipeline(
                base,
                depth=int(depth) if depth else None,
                warmup=bool(cfg.get("verify_warmup", True)),
                log=self.log,
            )
            if fallback:
                # ladder wiring also hands the pipeline's quarantined
                # chunks to the CPU floor (quarantine_verifier)
                verifier = _ladder(verifier)
        elif kind == "cpu":
            from dag_rider_tpu.verifier.cpu import CPUVerifier

            verifier = CPUVerifier(reg)
        elif kind == "remote":
            # The north star's stated deployment shape (BASELINE.json:
            # "gRPC to a JAX sidecar"): consensus host ships whole-round
            # batches to a VerifierSidecarServer at verifier_address.
            from dag_rider_tpu.verifier.sidecar import RemoteVerifier

            addr = cfg.get("verifier_address")
            if not addr:
                raise ValueError(
                    'verifier "remote" needs a "verifier_address"'
                )
            verifier = RemoteVerifier(
                addr,
                timeout=float(cfg.get("verifier_timeout_s", 30.0)),
                retries=retry,
            )
            if fallback:
                verifier = _ladder(verifier)
        elif kind != "none":
            raise ValueError(f"unknown verifier {kind!r}")

        coin = None
        if self.ccfg.coin == "threshold_bls":
            msm = None
            msm_kind = cfg.get("coin_msm", "host")
            if msm_kind == "device":
                from dag_rider_tpu.parallel.msm import ShardedMSM

                msm = ShardedMSM()
            elif msm_kind != "host":
                raise ValueError(f"unknown coin_msm {msm_kind!r}")
            coin = ThresholdCoin(coin_keys, index, n, msm=msm)
        elif self.ccfg.coin == "fixed":
            coin = FixedCoin(0)
        elif self.ccfg.coin == "round_robin":
            coin = RoundRobinCoin(n)

        cert_signer = cert_verifier = None
        if self.ccfg.cert == "agg":
            # aggregated round certificates (ISSUE 9): needs the cert PKI
            # in the key file AND a verifier (the aggregator tier still
            # verifies its own rounds per-vertex)
            if verifier is None:
                raise ValueError('cert "agg" needs a verifier (not "none")')
            if not reg.bls_public_keys:
                raise ValueError(
                    'cert "agg" needs bls_cert_pks in the key file '
                    "(re-run keygen)"
                )
            sk_hex = (keyblob.get("bls_cert_sks") or [None] * n)[index]
            if not sk_hex:
                raise ValueError(
                    'cert "agg" needs this node\'s bls_cert_sks entry'
                )
            from dag_rider_tpu.verifier.base import CertSigner
            from dag_rider_tpu.verifier.cert import CertVerifier

            cert_signer = CertSigner(int(sk_hex, 16))
            cert_verifier = CertVerifier(
                reg, self.ccfg.quorum, msm=cfg.get("cert_msm")
            )
            if hasattr(verifier, "cert_verifier"):
                # ladder deployments surface the certificate gauges in
                # the same resilience bundle (verifier/resilient.py)
                verifier.cert_verifier = cert_verifier

        self.delivered = []
        self.mempool = None

        # Byzantine-over-sockets (ISSUE 19): {"adversary": {"kind":
        # "equivocate", "seed": 7}} swaps in a ByzantineProcess whose
        # forged wire output crosses REAL process boundaries — the same
        # round-11 behaviors, now probing honest admission gates over
        # gRPC instead of a simulator queue.
        adv = cfg.get("adversary")
        behavior = None
        if adv:
            from dag_rider_tpu.consensus.adversary import make_behavior

            behavior = make_behavior(
                adv["kind"], seed=int(adv.get("seed", 0))
            )

        def _build_process() -> Process:
            if behavior is not None:
                from dag_rider_tpu.consensus.adversary import (
                    ByzantineProcess,
                )

                proc_cls = ByzantineProcess
                extra = {"behavior": behavior}
            else:
                proc_cls = Process
                extra = {}
            return proc_cls(
                self.ccfg,
                index,
                transport,
                coin=coin,
                verifier=verifier,
                signer=VertexSigner(seeds[index]),
                cert_signer=cert_signer,
                cert_verifier=cert_verifier,
                on_deliver=self._on_deliver,
                log=self.log,
                **extra,
            )

        def _attach() -> None:
            """(Re)bind everything keyed to the current Process's
            metrics object — also used by the corrupt-checkpoint
            rebuild path below, which swaps in a fresh Process."""
            mp_cfg = cfg.get("mempool")
            if mp_cfg:
                from dag_rider_tpu.config import MempoolConfig
                from dag_rider_tpu.mempool import Mempool

                self.mempool = Mempool(
                    MempoolConfig.from_dict(
                        mp_cfg if isinstance(mp_cfg, dict) else None
                    ),
                    metrics=self.process.metrics,
                    log=self.process.log,
                )
            self.net.attach_metrics(self.process.metrics)
            if self.tracing is not None:
                self.tracing.flight.add_metrics_source(
                    str(index), self.process.metrics.snapshot
                )

        self.process = _build_process()
        # Round-10 ingestion edge: "mempool": true (env-tuned) or a dict
        # of MempoolConfig overrides attaches the admission + batching
        # front door; submit() then routes through it and the pump pulls
        # built blocks. Absent/false keeps the legacy direct-block path.
        _attach()
        self.ckpt_dir = cfg.get("checkpoint_dir")
        self.ckpt_every = float(cfg.get("checkpoint_every_s", 30))
        #: per-peer state-transfer fetch deadline — short, because the
        #: fetch runs on the pump thread (one candidate per cycle)
        self.snapshot_timeout_s = float(cfg.get("snapshot_timeout_s", 5.0))
        self.submit_interval = float(cfg.get("submit_interval_s", 0))
        #: the synthetic n{i}-auto-{seq} generator gate: default ON only
        #: without a mempool (legacy behavior); with one attached, load
        #: tests must measure injected traffic only, so the generator
        #: needs an explicit opt-in
        self.auto_propose = bool(
            cfg.get("auto_propose", self.mempool is None)
        )
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._submit_lock = threading.Lock()
        self._submit_queue: Deque[Block] = deque()
        self._stopped = False

        if self.ckpt_dir and checkpoint.present(self.ckpt_dir):
            # present() (not latest_round): a torn manifest must reach
            # restore() so the corruption is COUNTED, not silently
            # mistaken for a first boot.
            try:
                checkpoint.restore(
                    self.process, self.ckpt_dir, mempool=self.mempool
                )
                self.log.event("restored", round=self.process.round)
            except checkpoint.CorruptCheckpointError as e:
                # kill -9 landed mid-save on a pre-atomic layout, or the
                # disk bit-rotted: start empty (fresh Process — restore
                # validates before mutating, but a rebuild costs nothing
                # and guarantees genesis state) and let snapshot sync
                # re-join us past whatever the cluster pruned. Accepted
                # transactions are the WAL's job, not the checkpoint's.
                unsub = getattr(transport, "unsubscribe", None)
                if unsub is not None:
                    unsub()
                self.process = _build_process()
                _attach()
                self.process.metrics.inc("checkpoint_corrupt")
                self.log.event("checkpoint_corrupt", error=str(e)[:200])

    def _on_deliver(self, vertex) -> None:
        self.delivered.append(vertex)
        if self.mempool is not None:
            # close the submit→a_deliver latency books for our payloads
            self.mempool.observe_delivered(vertex.block)

    def submit(self, block: Block, *, client: str = "client0"):
        """Client API — the mempool front door (round 10). With a
        mempool attached the block's transactions go through admission
        (accept/throttle/shed) into the pool, and the returned
        SubmitResult carries the backpressure signal: overload sheds
        and reports, it does not raise. Without one, the legacy direct
        path: the block lands whole in a handoff queue the pump thread
        drains — Process state is only ever touched from the pump
        thread (a caller-thread process.submit racing the pump's step()
        corrupted state rarely enough to be a flaky-suite heisenbug).
        Either way, after stop() nothing is drained again, so a late
        submit raises instead of silently swallowing the block
        (ADVICE r3)."""
        with self._submit_lock:
            if self._stopped:
                raise RuntimeError(
                    f"node {self.process.index} is stopped; block not accepted"
                )
            if self.mempool is None:
                self._submit_queue.append(block)
                return None
            # under the same lock as the stop check: a submit racing
            # stop() must not slip into the pool after the shutdown
            # checkpoint already persisted it
            return self.mempool.submit(block.transactions, client=client)

    def start(self) -> None:
        self.process.defer_steps = True
        self.process.start()
        self._thread = threading.Thread(target=self._pump_loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        # Refuse new submissions first: anything enqueued after the final
        # _drain_submissions below would never be drained again.
        with self._submit_lock:
            self._stopped = True
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            if self._thread.is_alive():
                # A wedged pump thread may still be mutating Process;
                # draining or checkpointing from this thread would race
                # it (and persist a mid-mutation snapshot). Leave state
                # alone and just tear the transport down.
                self.log.event("stop_pump_hung")
                self.net.close()
                return
        # The pump thread is down; flush any blocks still queued into the
        # Process (safe from this thread now) so the shutdown checkpoint
        # carries them — queued client submissions must not vanish.
        try:
            self._drain_submissions()
        except Exception as e:  # noqa: BLE001 — shutdown must proceed,
            # but never silently: the dropped block and stranded
            # remainder need a trace.
            self.log.event("stop_drain_error", error=repr(e)[:200])
        if self.mempool is not None:
            # final gauge refresh so the post-stop snapshot is current
            self.process.metrics.observe_mempool(self.mempool.stats())
        if self.ckpt_dir:
            # pending mempool transactions ride mempool.json in the same
            # checkpoint: accepted traffic survives the restart
            checkpoint.save(self.process, self.ckpt_dir, mempool=self.mempool)
        self.net.close()

    def _pump_loop(self) -> None:
        last_ckpt = last_submit = last_gauge = time.monotonic()
        seq = 0
        while not self._stop.is_set():
            try:
                self._pump_once()
                now = time.monotonic()
                if (
                    self.auto_propose
                    and self.submit_interval
                    and now - last_submit >= self.submit_interval
                ):
                    last_submit = now
                    seq += 1
                    payload = f"n{self.process.index}-auto-{seq}".encode()
                    if self.mempool is not None:
                        # explicit auto_propose with a mempool: the
                        # synthetic load takes the front door too, so it
                        # shows up in the same gauges as real traffic
                        self.mempool.submit(
                            (payload,),
                            client=f"auto{self.process.index}",
                        )
                    else:
                        self.process.submit(Block((payload,)))
                if self.mempool is not None and now - last_gauge >= 1.0:
                    # stats() is counter reads, but snapshot consumers
                    # only need ~1 Hz freshness — keep it off the hot loop
                    last_gauge = now
                    self.process.metrics.observe_mempool(
                        self.mempool.stats()
                    )
                if (
                    self.ckpt_dir
                    and self.ckpt_every > 0
                    and now - last_ckpt >= self.ckpt_every
                ):
                    last_ckpt = now
                    checkpoint.save(
                        self.process, self.ckpt_dir, mempool=self.mempool
                    )
                    self.log.event("checkpointed", round=self.process.round)
            except Exception as e:  # noqa: BLE001 — a BFT node must not
                # die silently: before this guard, any exception
                # (step, checkpoint IO, anything) killed the daemon pump
                # thread and the node kept accepting traffic it never
                # processed (observed as a stalled cluster with empty
                # diagnostics).
                self.process.metrics.inc("pump_errors")
                self.log.event("pump_error", error=repr(e)[:200])
                time.sleep(0.01)

    def _drain_submissions(self) -> None:
        """Move queued client blocks into the Process, one at a time; on
        an exception the not-yet-processed remainder goes back to the
        front of the queue (the failing block is dropped and logged —
        retrying it forever would livelock the pump). Deques at both
        ends: the old list's pop(0) drain was O(n) per block."""
        with self._submit_lock:
            pending, self._submit_queue = self._submit_queue, deque()
        while pending:
            block = pending.popleft()
            try:
                self.process.submit(block)
            except Exception:
                with self._submit_lock:
                    pending.extend(self._submit_queue)
                    self._submit_queue = pending
                raise

    def _pump_once(self) -> None:
        self._drain_submissions()
        if self.mempool is not None:
            # the pump pulls BUILT blocks (size-or-deadline batches), not
            # raw submissions — the round-10 front-door contract; staged=
            # current proposal backlog so overload stays in the pool
            # (bounded, sheddable) instead of blocks_to_propose (neither)
            for block in self.mempool.build_blocks(
                staged=len(self.process.blocks_to_propose)
            ):
                self.process.submit(block)
        if self.process.state_transfer_needed:
            self._state_transfer()
        moved = self.net.pump(256)
        self.process.step()
        if not moved:
            time.sleep(0.002)

    def _state_transfer(self) -> None:
        """f+1 peers reported GC floors above our round (sync_nack):
        anti-entropy cannot help, so fetch a peer's live window and
        replay it (utils.checkpoint.restore_from_snapshot — signatures
        verified, consensus state recomputed locally, atomic on
        failure). Runs on the pump thread, which owns all Process state
        — so at most ONE candidate is tried per pump cycle with a short
        RPC deadline (a dead peer must not stall consensus pumping for
        tens of seconds; the next cycle tries the next candidate). The
        highest-reported floor goes first (the most caught-up donor);
        when every candidate has failed, the flag clears and nacks must
        re-accrue before another attempt (no hot fetch loop against
        dead/Byzantine peers)."""
        nacks = self.process._horizon_nacks
        if not nacks:
            self.process.state_transfer_needed = False
            self.log.event("state_transfer_failed")
            return
        peer = max(nacks, key=nacks.get)
        nacks.pop(peer)  # consumed: success clears the rest, failure moves on
        blob = self.net.fetch_snapshot(
            peer, timeout_s=self.snapshot_timeout_s
        )
        if blob and checkpoint.restore_from_snapshot(
            self.process,
            blob,
            verifier=self.process.verifier,
            span_verifier=getattr(self.process, "cert_verifier", None),
        ):
            self.log.event(
                "state_transferred",
                peer=peer,
                round=self.process.round,
                base=self.process.dag.base_round,
            )
            return
        self.log.event("state_transfer_attempt_failed", peer=peer)
        if not nacks:
            self.process.state_transfer_needed = False
            self.log.event("state_transfer_failed")


# ----------------------------------------------------------------------
# CLI
# ----------------------------------------------------------------------

def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="dag_rider_tpu.node")
    sub = ap.add_subparsers(dest="cmd", required=True)
    kg = sub.add_parser("keygen", help="generate committee key material")
    kg.add_argument("--n", type=int, required=True)
    kg.add_argument("--threshold", type=int, required=True)
    kg.add_argument(
        "--seed",
        default=None,
        help="deterministic committee seed — tests only; default draws "
        "fresh randomness (a guessable seed voids DKG confidentiality)",
    )
    kg.add_argument(
        "--out",
        default=None,
        help="combined key file holding EVERY node's secrets (dealer "
        "deployments / tests). Omit it when --per-node-dir is given: "
        "for a DKG ceremony the combined file is exactly the "
        "single-holder-decrypts-everything artifact to avoid",
    )
    kg.add_argument(
        "--per-node-dir",
        default=None,
        help="also write <dir>/node<i>-identity.json per node, each "
        "holding ONLY that node's secrets (the files a DKG ceremony "
        "should start from — a combined file holding every seed lets "
        "any single holder decrypt all DKG share traffic)",
    )
    dk = sub.add_parser(
        "dkg",
        help="dealerless coin keygen: joint-Feldman DKG over gRPC "
        "(replaces keygen's BLS dealer; Ed25519 identities from --keys "
        "bootstrap the private share channels)",
    )
    dk.add_argument("--keys", required=True, help="keygen file (identities)")
    dk.add_argument("--index", type=int, required=True)
    dk.add_argument("--threshold", type=int, required=True)
    dk.add_argument("--listen", required=True)
    dk.add_argument(
        "--peers",
        required=True,
        help='comma list "0=host:port,1=host:port,..." (all n participants)',
    )
    dk.add_argument("--out", required=True, help="per-node key file")
    dk.add_argument("--timeout", type=float, default=15.0)
    rn = sub.add_parser("run", help="run one node until interrupted")
    rn.add_argument("--config", required=True)
    rn.add_argument("--duration", type=float, default=0, help="0 = forever")
    rn.add_argument("--verbose", action="store_true")
    args = ap.parse_args(argv)

    if args.cmd == "keygen":
        if not args.out and not args.per_node_dir:
            raise SystemExit("keygen needs --out and/or --per-node-dir")
        blob = generate_keys(args.n, args.threshold, args.seed)
        if args.out:
            _dump_secret_file(args.out, blob)
            print(
                f"wrote {args.out} (n={args.n}, threshold={args.threshold})"
            )
        if args.per_node_dir:
            os.makedirs(args.per_node_dir, exist_ok=True)
            for i in range(args.n):
                per = dict(blob)
                per["ed25519_seeds"] = [
                    s if j == i else None
                    for j, s in enumerate(blob["ed25519_seeds"])
                ]
                per["bls_share_sks"] = [
                    sk if j == i else None
                    for j, sk in enumerate(blob["bls_share_sks"])
                ]
                per["bls_cert_sks"] = [
                    sk if j == i else None
                    for j, sk in enumerate(blob["bls_cert_sks"])
                ]
                path = os.path.join(
                    args.per_node_dir, f"node{i}-identity.json"
                )
                _dump_secret_file(path, per)
            print(
                f"wrote {args.n} per-node identity files under "
                f"{args.per_node_dir} (each holds only its own secrets)"
            )
        return 0

    if args.cmd == "dkg":
        from dag_rider_tpu.crypto import bls12381 as bls
        from dag_rider_tpu.crypto import dkg as dkg_mod
        from dag_rider_tpu.transport.auth import FrameAuth
        from dag_rider_tpu.transport.blobbus import BlobBus

        with open(args.keys) as fh:
            keyblob = json.load(fh)
        my_seed = bytes.fromhex(keyblob["ed25519_seeds"][args.index])
        pks = [bytes.fromhex(p) for p in keyblob["ed25519_public"]]
        n = len(pks)
        peers = {}
        for part in args.peers.split(","):
            k, _, addr = part.partition("=")
            peers[int(k)] = addr
        # Frame authentication from the Ed25519 identities themselves
        # (pairwise ECDH keys — dkg.channel_key): sender indices on DKG
        # traffic must be unforgeable or one Byzantine peer could stamp
        # garbage commitments with an honest dealer's index and split
        # the committee's qualified-set verdicts. No extra dealer
        # secret involved — the identities ARE the PKI bootstrap.
        pair_keys = {
            j: dkg_mod.channel_key(my_seed, pks[j])
            for j in range(n)
            if j != args.index
        }
        if any(k is None for k in pair_keys.values()):
            raise ValueError("malformed identity public key in --keys")
        bus = BlobBus(
            args.index, args.listen, peers,
            auth=FrameAuth(args.index, pair_keys),
        )
        try:
            res = dkg_mod.run_dkg_networked(
                bus,
                n,
                args.threshold,
                my_seed,
                pks,
                phase_timeout_s=args.timeout,
            )
        finally:
            bus.close()
        # same shape as keygen, but every secret list carries ONLY this
        # node's entries — the dealerless property the DKG exists for
        # (copying all n identity seeds into each out-file would hand
        # any single file-holder every channel key and thereby the
        # group secret)
        out = dict(keyblob)
        out["ed25519_seeds"] = [
            keyblob["ed25519_seeds"][i] if i == args.index else None
            for i in range(n)
        ]
        out["threshold"] = args.threshold
        out["bls_group_pk"] = bls.g2_serialize(res.group_pk).hex()
        out["bls_share_pks"] = [
            bls.g2_serialize(pk).hex() for pk in res.share_pks
        ]
        out["bls_share_sks"] = [
            hex(res.share_sk) if i == args.index else None for i in range(n)
        ]
        if out.get("bls_cert_sks"):
            # same dealerless scrub for the certificate secrets
            out["bls_cert_sks"] = [
                sk if i == args.index else None
                for i, sk in enumerate(out["bls_cert_sks"])
            ]
        out["dkg_qualified"] = list(res.qualified)
        _dump_secret_file(args.out, out)
        print(
            f"wrote {args.out} (dkg n={n}, threshold={args.threshold}, "
            f"qualified={list(res.qualified)})"
        )
        return 0

    with open(args.config) as fh:
        cfg = json.load(fh)
    log = NOOP
    if args.verbose:
        logging.basicConfig(level=logging.DEBUG, format="%(message)s")
        log = EventLog(stdlib_sink(), node=cfg["index"])
    node = Node(cfg, log=log)
    node.start()
    try:
        if args.duration > 0:
            time.sleep(args.duration)
        else:
            while True:
                time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        node.stop()
    snap = node.process.metrics.snapshot()
    print(json.dumps({"delivered": len(node.delivered), "metrics": snap}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
