"""Fixed-key comb verification — the fast device Ed25519 path.

The committee (KeyRegistry) is fixed for the lifetime of a run, so the
variable-base scalar multiplication [k]A that dominates
:func:`dag_rider_tpu.ops.curve.verify_core` (252 doublings + 63 adds per
signature, ~2400 field muls) can be replaced by a *comb* walk over
per-key precomputed tables — 64 cached adds, zero doublings, exactly like
the existing fixed-base path for B. Per-signature cost drops from ~3200
field muls to ~1300 (measured on-chip: the dispatch is mul-throughput
bound, so wall time follows the mul count).

Tables are built ON DEVICE at verifier construction (one batched dispatch
over all n keys — ~1.3k point ops at batch n), stored in HBM
([n, 64, 16, 4, 22] int32 ≈ 92 MB at n=256), never uploaded from host.

Semantics are unchanged: the walk computes [s]B and [k]A exactly (any
A, including adversarial keys outside the prime-order subgroup — the
equation is NOT rearranged into [s]B - [k]A, which would differ for
8-torsion components), then checks [s]B == R + [k]A projectively. The
accept mask is bit-identical to both `curve.verify_core` and the CPU
oracle (tests/test_comb.py — valid, corrupted, and malleable batches).

Representation notes:

- a *packed* point is one int32 array [..., 4, 22] with rows (X, Y, Z, T)
  — every field op then moves 4 coordinates per XLA op instead of 1,
  which matters because the dispatch cost is op-count x op-size bound;
- a *cached* entry is rows (Y-X, Y+X, 2d*T, 2Z): the add-2008-hwcd-3
  addition of a cached entry is exactly 2 packed muls + cheap linear ops.

Reference seam: SURVEY.md §2a (the north-star batched Verifier);
the reference itself has no crypto (process.go carries none — D10).
"""

from __future__ import annotations


import numpy as np
import jax
import jax.numpy as jnp

from dag_rider_tpu.ops import curve, field as F

WINDOWS = 64  # 4-bit windows over 256-bit scalars
ENTRIES = 16


def pack_point(p: curve.Point) -> jax.Array:
    """(X, Y, Z, T) tuple of [..., 22] -> packed [..., 4, 22]."""
    return jnp.stack(p, axis=-2)


def unpack_point(a: jax.Array) -> curve.Point:
    return tuple(a[..., i, :] for i in range(4))


def to_cached(packed: jax.Array) -> jax.Array:
    """Packed XYZT [..., 4, 22] -> cached (Y-X, Y+X, 2dT, 2Z).

    Row-wise (one real multiply, the 2dT row) rather than a packed
    constant multiply — cheaper, and bit-identical limb representations
    to the Pallas kernel's in-VMEM transform (tests/test_pallas_group.py
    asserts raw-coordinate equality, not just mask equality)."""
    x = packed[..., 0, :]
    y = packed[..., 1, :]
    z = packed[..., 2, :]
    t = packed[..., 3, :]
    return jnp.stack(
        [F.sub(y, x), F.add(y, x), F.mul(t, jnp.asarray(F.D2)), F.add(z, z)],
        axis=-2,
    )


def padd_cached(p: jax.Array, c: jax.Array) -> jax.Array:
    """Packed point + cached entry -> packed point (complete addition).

    add-2008-hwcd-3 with the cached operand pre-transformed:
      A = (Y1-X1)*c0, B = (Y1+X1)*c1, C = T1*c2, D = Z1*c3
      E = B-A, F = D-C, G = D+C, H = B+A
      X3 = E*F, Y3 = G*H, Z3 = F*G, T3 = E*H
    Two packed muls; the stacking/linear steps are cheap elementwise ops.
    """
    x1 = p[..., 0, :]
    y1 = p[..., 1, :]
    z1 = p[..., 2, :]
    t1 = p[..., 3, :]
    lhs = jnp.stack([F.sub(y1, x1), F.add(y1, x1), t1, z1], axis=-2)
    abcd = F.mul(lhs, c)
    a = abcd[..., 0, :]
    b = abcd[..., 1, :]
    cc = abcd[..., 2, :]
    d = abcd[..., 3, :]
    e = F.sub(b, a)
    f = F.sub(d, cc)
    g = F.add(d, cc)
    h = F.add(b, a)
    efge = jnp.stack([e, g, f, e], axis=-2)
    fhgh = jnp.stack([f, h, g, h], axis=-2)
    out = F.mul(efge, fhgh)  # rows (X3, Y3, Z3, T3)
    # F.mul output row order: (E*F, G*H, F*G, E*H) == (X3, Y3, Z3, T3)
    return out


def pdouble_packed(p: jax.Array) -> jax.Array:
    """Packed doubling (dbl-2008-hwcd) — 2 packed muls + linear ops."""
    x1 = p[..., 0, :]
    y1 = p[..., 1, :]
    z1 = p[..., 2, :]
    sq_in = jnp.stack([x1, y1, z1, F.add(x1, y1)], axis=-2)
    sq = F.mul(sq_in, sq_in)  # (X^2, Y^2, Z^2, (X+Y)^2)
    a = sq[..., 0, :]
    b = sq[..., 1, :]
    c2 = F.add(sq[..., 2, :], sq[..., 2, :])
    s = sq[..., 3, :]
    h = F.add(a, b)
    e = F.sub(h, s)
    g = F.sub(a, b)
    f = F.add(c2, g)
    efge = jnp.stack([e, g, f, e], axis=-2)
    fhgh = jnp.stack([f, h, g, h], axis=-2)
    return F.mul(efge, fhgh)


# ---------------------------------------------------------------------------
# Device-side comb-table construction (batched over keys)
# ---------------------------------------------------------------------------


@jax.jit
def build_key_tables(a_x: jax.Array, a_y: jax.Array, a_t: jax.Array) -> jax.Array:
    """Packed-XYZT comb tables for every key: [n, 64, 16, 4, 22] int32.

    TABLE[key, w, d] = d * 16^w * A_key. Built in one dispatch:
    an outer scan over the 64 windows (carry: the window base 16^w * A),
    an inner scan over the 15 nonzero digits. ~64*(15+4) batched point
    ops total — about the cost of one verify dispatch, once per registry.
    """
    n = a_x.shape[0]
    one = jnp.broadcast_to(jnp.asarray(F.ONE), (n, F.LIMBS))
    base = jnp.stack([a_x, a_y, one, a_t], axis=-2)  # packed [n, 4, 22]
    ident = pack_point(curve.identity((n,)))

    def window_step(b, _):
        b_cached = to_cached(b)

        def entry_step(prev, _):
            nxt = padd_cached(prev, b_cached)
            return nxt, nxt

        _, entries = jax.lax.scan(entry_step, ident, None, length=ENTRIES - 1)
        # entries: [15, n, 4, 22]; prepend identity (d = 0)
        table_w = jnp.concatenate([ident[None], entries], axis=0)
        nb = pdouble_packed(pdouble_packed(pdouble_packed(pdouble_packed(b))))
        return nb, table_w

    _, tables = jax.lax.scan(window_step, base, None, length=WINDOWS)
    # tables: [64, 16, n, 4, 22] -> [n, 64, 16, 4, 22]
    return jnp.transpose(tables, (2, 0, 1, 3, 4))


def base_table_xyzt() -> np.ndarray:
    """Packed-XYZT comb table for the base point B: [64, 16, 4, 22]
    (host-built from curve.b_table()'s affine entries: Z == 1, T = x*y)."""
    xs, ys, ts = curve.b_table()  # [64, 16, 22] each, affine
    ones = np.broadcast_to(F.ONE, xs.shape).copy()
    return np.stack([xs, ys, ones, ts], axis=2)  # [64, 16, 4, 22]


WINDOWS8 = 32  # 8-bit windows over 256-bit scalars
ENTRIES8 = 256


# Digit -> table-position permutation for the 8-bit tables. The build
# stores each level's entries block-ordered ([all evens; all odds] of the
# previous level's order) instead of digit-ordered: an interleaving
# stack+reshape INSIDE a lax.scan body miscompiles on the TPU backend
# for n >= 64 (silently wrong values from level 2 on; the identical
# unrolled body and the CPU backend are both correct — reproduced and
# bisected in round 3, see PROFILE.md). Position order is defined by
# L_0 = [1], L_{l+1} = [2d for d in L_l] + [2d+1 for d in L_l].
def _digit_pos8() -> np.ndarray:
    order = [0, 1]
    cur = [1]
    for _ in range(7):
        cur = [2 * d for d in cur] + [2 * d + 1 for d in cur]
        order += cur
    pos = np.zeros(ENTRIES8, dtype=np.int32)
    for p, d in enumerate(order):
        pos[d] = p
    return pos


DIGIT_POS8 = _digit_pos8()


@jax.jit
def build_key_tables8(
    a_x: jax.Array, a_y: jax.Array, a_t: jax.Array
) -> jax.Array:
    """8-bit-window comb tables: [n, 32, 256, 4, 22] int32.

    TABLE[key, w, DIGIT_POS8[d]] = d * 256^w * A_key (block-ordered — see
    :data:`DIGIT_POS8`). Halves both the gather rows and the tree levels
    of the verify dispatch vs the 4-bit tables (the two dominant on-chip
    costs after the Pallas kernels — PROFILE.md), at 16x the HBM
    (1.07 GB padded at n=256; selected only for n <= 512).

    Each window's 256 entries are built in 8 doubling levels (evens are
    doubles of the previous level, odds add the base), so the whole
    build is ~32 * 16 wide batched point ops — still one dispatch.
    """
    n = a_x.shape[0]
    one = jnp.broadcast_to(jnp.asarray(F.ONE), (n, F.LIMBS))
    base0 = jnp.stack([a_x, a_y, one, a_t], axis=-2)  # [n, 4, 22]
    ident = pack_point(curve.identity((n,)))

    def window_step(b, _):
        b_cached = to_cached(b)
        levels = [ident[:, None], b[:, None]]  # positions 0 and 1
        prev = b[:, None]  # [n, 1, 4, 22]
        for _lvl in range(7):
            evens = pdouble_packed(prev)
            odds = padd_cached(evens, b_cached[:, None])
            lvl = jnp.concatenate([evens, odds], axis=1)  # block order
            levels.append(lvl)
            prev = lvl
        table_w = jnp.concatenate(levels, axis=1)  # [n, 256, 4, 22]
        nb = b
        for _ in range(8):
            nb = pdouble_packed(nb)
        return nb, table_w

    _, tables = jax.lax.scan(window_step, base0, None, length=WINDOWS8)
    # [32, n, 256, 4, 22] -> [n, 32, 256, 4, 22]
    return jnp.transpose(tables, (1, 0, 2, 3, 4))


def comb_verify_core8(
    s_bytes: jax.Array,
    k_bytes: jax.Array,
    key_idx: jax.Array,
    key_tables: jax.Array,
    b_table: jax.Array,
    a_valid: jax.Array,
    r_y: jax.Array,
    r_sign: jax.Array,
    prevalid: jax.Array,
    impl: str = "jnp",
) -> jax.Array:
    """8-bit-window twin of :func:`comb_verify_core`.

    s_bytes/k_bytes: int32[B, 32] little-endian byte digits (the raw
    scalar bytes — no nibble split); tables from
    :func:`build_key_tables8` via :func:`pad_rows`. Identical accept
    mask; only the window decomposition differs (the scalar sum is the
    same group element).
    """
    # digits -> block-ordered table positions (see DIGIT_POS8)
    pos = jnp.asarray(DIGIT_POS8)
    s_pos = jnp.take(pos, s_bytes, axis=0)
    k_pos = jnp.take(pos, k_bytes, axis=0)
    wins = jnp.arange(WINDOWS8, dtype=jnp.int32)[None, :]
    b_rows = jnp.take(b_table, wins * ENTRIES8 + s_pos, axis=0)
    a_idx = (key_idx[:, None] * WINDOWS8 + wins) * ENTRIES8 + k_pos
    a_rows = jnp.take(key_tables, a_idx, axis=0)
    stacked = jnp.stack([b_rows, a_rows], axis=1)  # [B, 2, 32, 128]
    entries = stacked[..., : 4 * F.LIMBS].reshape(
        (*stacked.shape[:-1], 4, F.LIMBS)
    )
    if impl in ("pallas", "pallas_interpret"):
        from dag_rider_tpu.ops import pallas_group

        interp = impl == "pallas_interpret"
        acc = pallas_group.tree_sum_xyzt(entries, interpret=interp)
        ok = pallas_group.finish_check(r_y, r_sign, acc, interpret=interp)
        return ok & a_valid & prevalid
    acc = tree_sum_packed(entries)
    lhs = unpack_point(acc[:, 0])
    ka = unpack_point(acc[:, 1])
    r_point, r_valid = curve.decompress(r_y, r_sign)
    rhs = curve.padd(r_point, ka)
    return curve.points_equal(lhs, rhs) & a_valid & r_valid & prevalid


ROW_PAD = 128  # gather-row width: one aligned lane tile


def pad_rows(tables: jax.Array) -> jax.Array:
    """[..., 16, 4, 22] tables -> flat [rows, 128] gather layout.

    TPU row-gathers run ~2.2x faster from 512-byte lane-aligned rows
    than from the raw 352-byte [4, 22] entries (measured on-chip,
    PROFILE.md round 3); the 40 pad lanes are sliced off after gather.
    """
    flat = tables.reshape((-1, 4 * F.LIMBS))
    return jnp.pad(flat, ((0, 0), (0, ROW_PAD - 4 * F.LIMBS)))


# ---------------------------------------------------------------------------
# The comb verify core
# ---------------------------------------------------------------------------


def tree_sum_packed(entries: jax.Array) -> jax.Array:
    """Sum a power-of-two axis of packed XYZT points (jnp fallback).

    entries: [..., M, 4, 22] XYZT, M a power of two. Each level halves
    the axis with one wide packed add (first half + to_cached(second
    half)); log2(M) levels of WIDE ops — the whole reduction is ~20 XLA
    ops regardless of M, so the VPU sees huge elementwise ops instead of
    a long dependent chain (the sequential 64-step walk was
    latency-bound — PROFILE.md round 3). The TPU fast path is
    :func:`dag_rider_tpu.ops.pallas_group.tree_sum_xyzt` (bit-identical).
    """
    acc = entries
    while acc.shape[-3] > 1:
        m = acc.shape[-3] // 2
        acc = padd_cached(
            acc[..., :m, :, :], to_cached(acc[..., m:, :, :])
        )
    return acc[..., 0, :, :]


def comb_verify_core(
    s_nibbles: jax.Array,
    k_nibbles: jax.Array,
    key_idx: jax.Array,
    key_tables: jax.Array,
    b_table: jax.Array,
    a_valid: jax.Array,
    r_y: jax.Array,
    r_sign: jax.Array,
    prevalid: jax.Array,
    impl: str = "jnp",
) -> jax.Array:
    """Batched [s]B == R + [k]A with both scalar muls as comb sums.

    s_nibbles/k_nibbles: int32[B, 64] little-endian 4-bit digits;
    key_idx: int32[B] row of each vertex's key in the registry;
    key_tables: [n, 64, 16, 4, 22] from :func:`build_key_tables`;
    b_table: [64, 16, 4, 22] from :func:`base_table_xyzt`.

    A comb scalar mul is a pure sum of per-window table entries (no
    doublings), so both sides are ONE fused gather ([B, 2, 64, 4, 22] —
    axis 1 is ([s]B, [k]A)) followed by a 6-level tree reduction of wide
    packed adds. The R decompression chain (the one unavoidable
    sequential part) runs concurrently — it has no data dependence on
    the trees until the final addition.

    impl: "jnp" (portable) or "pallas" (TPU kernels for the tree and the
    sqrt chain — bit-identical results, one HBM pass per operand).

    key_tables/b_table arrive in the padded [rows, 128] gather layout of
    :func:`pad_rows`.
    """
    wins = jnp.arange(WINDOWS, dtype=jnp.int32)[None, :]
    b_rows = jnp.take(b_table, wins * ENTRIES + s_nibbles, axis=0)
    a_idx = (key_idx[:, None] * WINDOWS + wins) * ENTRIES + k_nibbles
    a_rows = jnp.take(key_tables, a_idx, axis=0)
    stacked = jnp.stack([b_rows, a_rows], axis=1)  # [B, 2, 64, 128]
    entries = stacked[..., : 4 * F.LIMBS].reshape(
        (*stacked.shape[:-1], 4, F.LIMBS)
    )  # [B, 2, 64, 4, 22]

    if impl in ("pallas", "pallas_interpret"):
        from dag_rider_tpu.ops import pallas_group

        interp = impl == "pallas_interpret"
        acc = pallas_group.tree_sum_xyzt(entries, interpret=interp)  # [B, 2, 4, 22]
        # decompress + rhs addition + projective equality in one launch
        ok = pallas_group.finish_check(r_y, r_sign, acc, interpret=interp)
        return ok & a_valid & prevalid
    acc = tree_sum_packed(entries)
    lhs = unpack_point(acc[:, 0])  # [s]B
    ka = unpack_point(acc[:, 1])  # [k]A
    r_point, r_valid = curve.decompress(r_y, r_sign)
    rhs = curve.padd(r_point, ka)
    return curve.points_equal(lhs, rhs) & a_valid & r_valid & prevalid
