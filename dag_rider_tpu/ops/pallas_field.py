"""Pallas TPU kernel for the GF(2^255 - 19) limb multiply.

SURVEY.md §2a/§7 name "limb decomposition in Pallas" as the riskiest build
item; round-2 VERDICT next #3 asks for either a working Pallas field mul
with byte-identical results or a measured justification for pure jnp.
This module is the kernel half of that evidence: the same 22×12-bit
signed-limb schoolbook multiply as :func:`dag_rider_tpu.ops.field.mul`,
laid out the way the VPU wants it.

Why a different layout: the jnp path keeps limbs in the trailing axis
([B, 22]), so on TPU the 22-wide limb vectors occupy the 128-lane axis at
~17% utilization, and the [B, 22, 22] outer product + pad/reshape
anti-diagonal sum materializes at that poor occupancy. Here the batch
axis IS the lane axis: operands are transposed to [22, B] once outside
the kernel, every product column c_k = sum_{i+j=k} a_i * b_j is a
straight multiply-add over [1, B] lane vectors (484 MACs total, fully
unrolled — limb indices are static), and carries/folds are the exact
integer steps of ``field.mul`` applied row-wise. Results are
bit-identical to ``field.mul`` (tests/test_pallas_field.py runs the
kernel in interpret mode against the jnp oracle).

The kernel is *opt-in* evidence-gathering: nothing routes through it by
default. ``bench.py`` times it against the jnp multiply on the real chip
(phase "pallas_field_mul") so the Pallas-vs-XLA decision is made from an
on-chip number, not a guess.
"""

from __future__ import annotations

import functools
from typing import Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from dag_rider_tpu.ops import field as F

_LANES = 128  # TPU lane width; batch is padded to a multiple of this


def _mul_kernel(a_ref, b_ref, o_ref):
    """One block: a, b int32[22, T] -> o int32[22, T] (reduced limbs).

    Mirrors ``field.mul`` step for step (same masks, shifts and fold
    constants), with columns as [1, T] lane vectors instead of trailing
    limb axes. All loop bounds are static Python ints — the kernel is one
    straight-line vector program.
    """
    a = [a_ref[i : i + 1, :] for i in range(F.LIMBS)]
    b = [b_ref[i : i + 1, :] for i in range(F.LIMBS)]
    # schoolbook product columns c[k] = sum_{i+j=k} a_i b_j  (46 columns;
    # cols 44/45 only ever hold carry spill, exactly as in field._columns)
    c = []
    for k in range(2 * F.LIMBS - 1):  # 0..42
        acc = None
        for i in range(max(0, k - F.LIMBS + 1), min(F.LIMBS, k + 1)):
            t = a[i] * b[k - i]
            acc = t if acc is None else acc + t
        c.append(acc)
    zeros = jnp.zeros_like(a[0])
    c += [zeros, zeros, zeros]  # cols 43+1..45  (43 real cols: 0..42)
    # -- two parallel column-normalize steps (field.mul's pre-fold loop)
    for _ in range(2):
        carries = [ck >> F.LIMB_BITS for ck in c]
        c = [ck & F.LIMB_MASK for ck in c]
        for k in range(len(c) - 1):
            c[k + 1] = c[k + 1] + carries[k]
        # carry out of the last column is 0 by the same range analysis
    # -- fold high columns through 2^255 == 19 (weight 19 * 2^(12j + 9))
    lo = c[: F.LIMBS]
    hi = c[F.LIMBS : 2 * F.LIMBS]
    t = [h * 19 for h in hi]
    for j in range(F.LIMBS):
        lo[j] = lo[j] + ((t[j] & 0x7) << 9)
    up = [tj >> 3 for tj in t]
    for j in range(F.LIMBS - 1):
        lo[j + 1] = lo[j + 1] + up[j]
    t2 = up[F.LIMBS - 1] * 19
    lo[0] = lo[0] + ((t2 & 0x7) << 9)
    lo[1] = lo[1] + (t2 >> 3)
    lo[1] = lo[1] + c[44] * 23104
    lo[2] = lo[2] + c[45] * 23104
    # -- final three parallel carry steps (field.carry(steps=3))
    for _ in range(3):
        cs = [l >> F.LIMB_BITS for l in lo]
        lo = [l & F.LIMB_MASK for l in lo]
        lo[0] = lo[0] + cs[F.LIMBS - 1] * F.TOP_FOLD
        for j in range(F.LIMBS - 1):
            lo[j + 1] = lo[j + 1] + cs[j]
    for j in range(F.LIMBS):
        o_ref[j : j + 1, :] = lo[j]


@functools.partial(jax.jit, static_argnames=("interpret", "block"))
def _mul_limb_major(
    at: jax.Array, bt: jax.Array, *, interpret: bool = False, block: int = 512
) -> jax.Array:
    """at, bt: int32[22, B] (B a multiple of `block`) -> int32[22, B]."""
    n_blocks = at.shape[1] // block
    return pl.pallas_call(
        _mul_kernel,
        out_shape=jax.ShapeDtypeStruct(at.shape, jnp.int32),
        grid=(n_blocks,),
        in_specs=[
            pl.BlockSpec((F.LIMBS, block), lambda i: (0, i)),
            pl.BlockSpec((F.LIMBS, block), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((F.LIMBS, block), lambda i: (0, i)),
        interpret=interpret,
    )(at, bt)


def mul(a: jax.Array, b: jax.Array, *, interpret: bool = False) -> jax.Array:
    """Drop-in twin of :func:`field.mul` (int32[..., 22] -> int32[..., 22])
    backed by the Pallas kernel. Transposes to limb-major, pads the batch
    to a lane multiple, runs the kernel, transposes back."""
    batch_shape = a.shape[:-1]
    flat = int(np.prod(batch_shape)) if batch_shape else 1
    block = _LANES if flat <= _LANES else 512
    padded = -(-flat // block) * block
    at = jnp.moveaxis(a.reshape(flat, F.LIMBS), 0, 1)
    bt = jnp.moveaxis(b.reshape(flat, F.LIMBS), 0, 1)
    if padded != flat:
        pad = ((0, 0), (0, padded - flat))
        at = jnp.pad(at, pad)
        bt = jnp.pad(bt, pad)
    out = _mul_limb_major(at, bt, interpret=interpret, block=block)
    out = jnp.moveaxis(out[:, :flat], 0, 1)
    return out.reshape(*batch_shape, F.LIMBS)


# ----------------------------------------------------------------------
# On-chip microbenchmark (bench.py "pallas_field_mul" phase)
# ----------------------------------------------------------------------

def benchmark_vs_xla(
    batch: int = 8192, chain: int = 64, seed: int = 0
) -> Tuple[float, float, bool]:
    """Time a `chain`-long dependent multiply chain over an int32[batch, 22]
    operand set: (xla_ms, pallas_ms, bit_identical). A dependent chain
    (x := x * b each step) amortizes dispatch overhead and defeats fusion
    shortcuts, approximating the multiply density of the verify kernel."""
    import time

    rng = np.random.default_rng(seed)
    xs = np.stack(
        [F.to_limbs(int(v)) for v in rng.integers(1, 2**60, size=batch)]
    ).astype(np.int32)
    bs = np.stack(
        [F.to_limbs(int(v)) for v in rng.integers(1, 2**60, size=batch)]
    ).astype(np.int32)

    @jax.jit
    def chain_xla(x, b):
        def body(_, x):
            return F.mul(x, b)

        return jax.lax.fori_loop(0, chain, body, x)

    @jax.jit
    def chain_pallas(x, b):
        def body(_, x):
            return mul(x, b)

        return jax.lax.fori_loop(0, chain, body, x)

    xj, bj = jnp.asarray(xs), jnp.asarray(bs)
    r_xla = chain_xla(xj, bj).block_until_ready()  # compile + warm
    r_pal = chain_pallas(xj, bj).block_until_ready()
    same = bool((np.asarray(r_xla) == np.asarray(r_pal)).all())
    t0 = time.perf_counter()
    chain_xla(xj, bj).block_until_ready()
    xla_ms = 1e3 * (time.perf_counter() - t0)
    t0 = time.perf_counter()
    chain_pallas(xj, bj).block_until_ready()
    pallas_ms = 1e3 * (time.perf_counter() - t0)
    return xla_ms, pallas_ms, same
