"""Device multi-pairing Miller product — the `DAGRIDER_CERT_PAIR=device`
lane (ISSUE 12 tentpole 2).

The certificate aggregate check is one product check
``e(agg, -g2) * prod_i e(H(d_i), pk_i) == 1``. The host fast path
(`crypto/bls12381.multi_pairing_check`) already replays per-key
precomputed line coefficients over the fixed 63-bit Miller schedule; this
module moves the replay onto the accelerator: all pairs' line evaluations
per schedule step run lane-parallel as batched Fp12 limb arithmetic on
:mod:`dag_rider_tpu.ops.field381`, a uniform `lax.scan` walks the
schedule (add-step products are computed every step and gated by the
schedule flag — branch-free), and only the cheap-but-branchy final
exponentiation stays on host.

Bit-identity with the host oracle is structural: every limb op is exact
mod-p arithmetic, so the Miller accumulator is the same Fp12 *element*
regardless of product association, and conjugation + final
exponentiation of equal elements give equal verdicts AND equal GT
values. The only host-side escape is a vertical line in a precomputed
schedule (impossible for r-order G2 points, whose schedule never hits
the point at infinity mid-walk) — those pairs route to the host oracle.

Like the sharded MSM and the G1 signing lane, this is a where-the-work-
runs lane: on the 1-core CPU host it loses to the host replay (PROFILE
round 15 has the A/B); the lane is the committee-scale accelerator story
for the verify side.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from dag_rider_tpu.crypto import bls12381 as bls
from dag_rider_tpu.ops import field381 as f

#: schedule length (63 bits below the leading one of |x|)
_N_STEPS = len(bls._X_BITS)

P_INT = f.P_INT

#: fp12 one as packed limbs [12, LIMBS]
_ONE_PACKED = np.zeros((12, f.LIMBS), dtype=np.int32)
_ONE_PACKED[0] = f.ONE


def _fp12_flat(x) -> List[int]:
    """Host fp12 tuple -> 12 coefficient ints, (a0 a1 a2 b0 b1 b2) each
    (re, im) — the packed coefficient order used on device."""
    (a0, a1, a2), (b0, b1, b2) = x
    return [
        a0[0], a0[1], a1[0], a1[1], a2[0], a2[1],
        b0[0], b0[1], b1[0], b1[1], b2[0], b2[1],
    ]


def _fp12_unflat(c: Sequence[int]):
    return (
        ((c[0], c[1]), (c[2], c[3]), (c[4], c[5])),
        ((c[6], c[7]), (c[8], c[9]), (c[10], c[11])),
    )


# --- packed tower arithmetic (coefficient axis -2, limb axis -1) -----------


def _unpack(a):
    c = [a[..., j, :] for j in range(12)]
    return (
        ((c[0], c[1]), (c[2], c[3]), (c[4], c[5])),
        ((c[6], c[7]), (c[8], c[9]), (c[10], c[11])),
    )


def _pack(x):
    (a0, a1, a2), (b0, b1, b2) = x
    return jnp.stack(
        [
            a0[0], a0[1], a1[0], a1[1], a2[0], a2[1],
            b0[0], b0[1], b1[0], b1[1], b2[0], b2[1],
        ],
        axis=-2,
    )


def _fp2_add(x, y):
    return (f.add(x[0], y[0]), f.add(x[1], y[1]))


def _fp2_sub(x, y):
    return (f.sub(x[0], y[0]), f.sub(x[1], y[1]))


def _fp2_mul(x, y):
    a, b = x
    c, d = y
    return (
        f.sub(f.mul(a, c), f.mul(b, d)),
        f.add(f.mul(a, d), f.mul(b, c)),
    )


def _fp2_mul_xi(x):
    """x * (1 + u): (a - b) + (a + b) u."""
    a, b = x
    return (f.sub(a, b), f.add(a, b))


def _fp6_add(x, y):
    return tuple(_fp2_add(a, b) for a, b in zip(x, y))


def _fp6_sub(x, y):
    return tuple(_fp2_sub(a, b) for a, b in zip(x, y))


def _fp6_mul(x, y):
    a0, a1, a2 = x
    b0, b1, b2 = y
    t0 = _fp2_mul(a0, b0)
    t1 = _fp2_mul(a1, b1)
    t2 = _fp2_mul(a2, b2)
    c0 = _fp2_add(
        t0,
        _fp2_mul_xi(
            _fp2_sub(
                _fp2_mul(_fp2_add(a1, a2), _fp2_add(b1, b2)),
                _fp2_add(t1, t2),
            )
        ),
    )
    c1 = _fp2_add(
        _fp2_sub(
            _fp2_mul(_fp2_add(a0, a1), _fp2_add(b0, b1)), _fp2_add(t0, t1)
        ),
        _fp2_mul_xi(t2),
    )
    c2 = _fp2_add(
        _fp2_sub(
            _fp2_mul(_fp2_add(a0, a2), _fp2_add(b0, b2)), _fp2_add(t0, t2)
        ),
        t1,
    )
    return (c0, c1, c2)


def _fp6_mul_by_v(x):
    return (_fp2_mul_xi(x[2]), x[0], x[1])


def _fp12_mul_packed(xa, ya):
    x, y = _unpack(xa), _unpack(ya)
    a0, a1 = x
    b0, b1 = y
    t0 = _fp6_mul(a0, b0)
    t1 = _fp6_mul(a1, b1)
    c0 = _fp6_add(t0, _fp6_mul_by_v(t1))
    c1 = _fp6_sub(
        _fp6_mul(_fp6_add(a0, a1), _fp6_add(b0, b1)), _fp6_add(t0, t1)
    )
    return _pack((c0, c1))


@jax.jit
def _eval_lines(lam, c, xp, yp):
    """The precomputed lines at (xp, yp): (c - lam*xp) + yp at coefficient
    a0.re — the packed twin of the host `_line_eval` non-vertical arm,
    evaluated for every schedule step and pair at once.
    lam, c: [steps, n, 12, LIMBS]; xp, yp: [n, LIMBS]."""
    ell = f.sub(c, f.mul(lam, xp[None, :, None, :]))
    ell0 = f.add(ell[..., 0, :], yp[None])
    return jnp.concatenate([ell0[..., None, :], ell[..., 1:, :]], axis=-2)


# One jitted fp12 multiply reused for the whole walk: compiled once per
# operand shape ([steps, 12, L] for the cross-pair product, [12, L] for
# the accumulator) and shared across every pair count — a monolithic
# scan-the-schedule kernel was bit-identical but took minutes of XLA
# compile per pair-count; ~200 small dispatches beat that by >100x.
_mul_packed_jit = jax.jit(_fp12_mul_packed)
_canonical_jit = jax.jit(f.canonical)


# --- host-side schedule marshalling ----------------------------------------

#: q -> (dbl_lam, dbl_c, add_lam, add_c) limb arrays [steps, 12, LIMBS]
_SLOT_CACHE: dict = {}
_SLOT_CACHE_MAX = 1024

def _slot_limbs(q):
    """Per-step (doubling, addition) line-coefficient limb arrays for G2
    point q; vertical slots (never hit by r-order points) return None and
    the caller falls back to the host oracle."""
    hit = _SLOT_CACHE.get(q)
    if hit is not None:
        return hit
    coeffs = bls.g2_precompute(q)
    if any(lam is None for lam, _ in coeffs):
        return None
    dbl_lam, dbl_c, add_lam, add_c = [], [], [], []
    idx = 0
    zero12 = [0] * 12
    for bit in bls._X_BITS:
        lam, c = coeffs[idx]
        idx += 1
        dbl_lam.append(_fp12_flat(lam))
        dbl_c.append(_fp12_flat(c))
        if bit == "1":
            lam, c = coeffs[idx]
            idx += 1
            add_lam.append(_fp12_flat(lam))
            add_c.append(_fp12_flat(c))
        else:
            add_lam.append(zero12)
            add_c.append(zero12)
    out = tuple(
        f.to_limbs_bulk(
            [v for step in arr for v in step]
        ).reshape(_N_STEPS, 12, f.LIMBS)
        for arr in (dbl_lam, dbl_c, add_lam, add_c)
    )
    if len(_SLOT_CACHE) >= _SLOT_CACHE_MAX:
        _SLOT_CACHE.clear()
    _SLOT_CACHE[q] = out
    return out


def miller_product(pairs: Sequence[Tuple[object, object]]):
    """The Miller-loop product of (G1, G2) pairs as a host fp12 tuple
    (conjugated for the negative x, exactly like the host oracle) — feed
    to `bls.final_exponentiation`. None-containing pairs contribute 1."""
    evs = []
    for p, q in pairs:
        if p is None or q is None:
            continue
        slots = _slot_limbs(q)
        if slots is None:
            # vertical schedule slot: not reachable for subgroup keys;
            # route the whole product to the host oracle for exactness
            return None
        evs.append((p[0] % P_INT, p[1] % P_INT, slots))
    if not evs:
        return bls.FP12_ONE
    n = len(evs)
    xp = jnp.asarray(f.to_limbs_bulk([e[0] for e in evs]))
    yp = jnp.asarray(f.to_limbs_bulk([e[1] for e in evs]))
    stacked = [
        jnp.asarray(
            np.stack([e[2][k] for e in evs], axis=1)
        )  # [steps, n, 12, LIMBS]
        for k in range(4)
    ]
    evals_d = _eval_lines(stacked[0], stacked[1], xp, yp)
    evals_a = _eval_lines(stacked[2], stacked[3], xp, yp)
    # cross-pair product, all schedule steps at once ([steps, 12, LIMBS])
    dprod, aprod = evals_d[:, 0], evals_a[:, 0]
    for k in range(1, n):
        dprod = _mul_packed_jit(dprod, evals_d[:, k])
        aprod = _mul_packed_jit(aprod, evals_a[:, k])
    # schedule walk on the [12, LIMBS] accumulator (garbage add-step
    # products are never touched — the host loop skips them)
    acc = jnp.asarray(_ONE_PACKED)
    for s, bit in enumerate(bls._X_BITS):
        acc = _mul_packed_jit(acc, acc)
        acc = _mul_packed_jit(acc, dprod[s])
        if bit == "1":
            acc = _mul_packed_jit(acc, aprod[s])
    out = np.asarray(_canonical_jit(acc))
    fvals = [f.from_limbs(out[j]) for j in range(12)]
    res = _fp12_unflat(fvals)
    if bls.X_PARAM < 0:
        res = bls.fp12_conj(res)
    return res


def multi_pairing_check(pairs: Sequence[Tuple[object, object]]) -> bool:
    """Device twin of `bls.multi_pairing_check` — bit-identical verdicts
    (pinned on the full Byzantine certificate matrix in tests)."""
    fm = miller_product(pairs)
    if fm is None:
        return bls.multi_pairing_check(pairs)
    return bls.final_exponentiation(fm) == bls.FP12_ONE
