"""Native (cffi) batched GF(p) kernels for BLS12-381 — the host fast lane.

This single-core container cannot hit the ISSUE-12 signing gate (>= 3x
over sequential `bls.sign`) from pure Python: CPython bignum mulmod costs
~1.4 us while a 6-limb Montgomery CIOS multiply in C costs ~85 ns, and a
381-bit merged-scalar ladder is ~4.8k field muls per point. So the
`DAGRIDER_CERT_SIGN=native` lane compiles a tiny C extension at first use
(cffi API mode against the system gcc, ~0.7 s once per process) exposing
batched Montgomery field ops and a batched Jacobian double-and-add ladder,
and the Python layer only marshals 48-byte little-endian limb arrays.

Bit-identity with the host oracle is structural, not numerical: the C
ladder transcribes the exact `_jac_double` (EFD dbl-2009-l) and
`_jac_madd` (madd-2007-bl) formulas from ``crypto/bls12381.py`` including
both exceptional branches (H == 0 doubling / p == -q collapse to the
identity), over exact mod-p arithmetic — so `[k]P` here equals the
oracle's `[k]P` for every scalar and every curve point, and
`sign_many(..., backend="native")` is byte-for-byte `sign` (pinned by the
fuzz suite in tests/test_cert_phase2.py).

When cffi or a C compiler is missing the module reports unavailable and
callers fall back to the host oracle — never an import-time failure.
"""

from __future__ import annotations

import importlib.util
import tempfile
import threading
from typing import List, Optional, Sequence, Tuple

import numpy as np

P = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB

#: Montgomery radix 2^384: to_mont(x) = mont_mul(x, R2), from_mont = *1
_R_MONT = (1 << 384) % P
_R2 = pow(1 << 384, 2, P)

_CDEF = """
void mont_mul_batch(uint64_t* out, const uint64_t* a, const uint64_t* b,
                    size_t n);
void mont_pow_batch(uint64_t* out, const uint64_t* base,
                    const uint64_t* exp, int expbits, size_t n);
void g1_ladder_batch(uint64_t* X, uint64_t* Y, uint64_t* Z,
                     const uint64_t* px, const uint64_t* py,
                     const uint64_t* rone, const unsigned char* bits,
                     int nbits, size_t rows);
"""

# The mont_mul CIOS core is the prototype validated against CPython pow()
# over the full limb range; the EC layer transcribes crypto/bls12381.py's
# Jacobian formulas one line per field op.
_C_SOURCE = r"""
#include <stdint.h>
#include <stddef.h>
typedef unsigned __int128 u128;

static const uint64_t PL[6] = {
  0xb9feffffffffaaabULL, 0x1eabfffeb153ffffULL, 0x6730d2a0f6b0f624ULL,
  0x64774b84f38512bfULL, 0x4b1ba7b6434bacd7ULL, 0x1a0111ea397fe69aULL};
static const uint64_t N0INV = 0x89f3fffcfffcfffdULL; /* -P^-1 mod 2^64 */

static void mont_mul(uint64_t* t, const uint64_t* a, const uint64_t* b){
  uint64_t r[7] = {0,0,0,0,0,0,0};
  for(int i=0;i<6;i++){
    u128 c = 0;
    for(int j=0;j<6;j++){ c += (u128)a[i]*b[j] + r[j]; r[j] = (uint64_t)c; c >>= 64; }
    uint64_t hi = r[6] + (uint64_t)c;
    uint64_t m = r[0]*N0INV;
    c = (u128)m*PL[0] + r[0]; c >>= 64;
    for(int j=1;j<6;j++){ c += (u128)m*PL[j] + r[j]; r[j-1] = (uint64_t)c; c >>= 64; }
    c += hi; r[5] = (uint64_t)c; r[6] = (uint64_t)(c>>64);
  }
  uint64_t s[6]; u128 br = 0;
  for(int j=0;j<6;j++){ u128 d = (u128)r[j] - PL[j] - (uint64_t)br; s[j]=(uint64_t)d; br = (d >> 64) & 1; }
  int ge = (r[6] || !br);
  for(int j=0;j<6;j++) t[j] = ge ? s[j] : r[j];
}

static void addmod(uint64_t* t, const uint64_t* a, const uint64_t* b){
  uint64_t r[6]; u128 c = 0;
  for(int j=0;j<6;j++){ c += (u128)a[j] + b[j]; r[j]=(uint64_t)c; c >>= 64; }
  /* a,b < p < 2^381 so no carry out of limb 5 */
  uint64_t s[6]; u128 br = 0;
  for(int j=0;j<6;j++){ u128 d = (u128)r[j] - PL[j] - (uint64_t)br; s[j]=(uint64_t)d; br = (d >> 64) & 1; }
  int ge = !br;
  for(int j=0;j<6;j++) t[j] = ge ? s[j] : r[j];
}

static void submod(uint64_t* t, const uint64_t* a, const uint64_t* b){
  uint64_t r[6]; u128 br = 0;
  for(int j=0;j<6;j++){ u128 d = (u128)a[j] - b[j] - (uint64_t)br; r[j]=(uint64_t)d; br = (d >> 64) & 1; }
  if(br){ u128 c = 0; for(int j=0;j<6;j++){ c += (u128)r[j] + PL[j]; r[j]=(uint64_t)c; c >>= 64; } }
  for(int j=0;j<6;j++) t[j]=r[j];
}

static int is_zero6(const uint64_t* a){
  for(int j=0;j<6;j++) if(a[j]) return 0;
  return 1;
}
static void cpy6(uint64_t* d, const uint64_t* s){
  for(int j=0;j<6;j++) d[j]=s[j];
}

/* EFD dbl-2009-l, the oracle's _jac_double line for line */
static void jac_double(uint64_t* X, uint64_t* Y, uint64_t* Z){
  uint64_t A[6],B[6],C[6],D[6],E[6],t[6],u[6],X3[6],Y3[6],Z3[6];
  mont_mul(A,X,X); mont_mul(B,Y,Y); mont_mul(C,B,B);
  addmod(t,X,B); mont_mul(t,t,t); submod(t,t,A); submod(t,t,C); addmod(D,t,t);
  addmod(E,A,A); addmod(E,E,A);
  mont_mul(X3,E,E); addmod(u,D,D); submod(X3,X3,u);
  submod(u,D,X3); mont_mul(u,E,u);
  addmod(t,C,C); addmod(t,t,t); addmod(t,t,t); submod(Y3,u,t);
  mont_mul(t,Y,Z); addmod(Z3,t,t);
  cpy6(X,X3); cpy6(Y,Y3); cpy6(Z,Z3);
}

/* EFD madd-2007-bl, the oracle's _jac_madd including both exceptional
   branches (H==0 & S2==Y1 -> double; H==0 else -> identity). */
static void jac_madd(uint64_t* X, uint64_t* Y, uint64_t* Z,
                     const uint64_t* x2, const uint64_t* y2){
  uint64_t Z1Z1[6],U2[6],S2[6],H[6],rr[6],HH[6],I[6],J[6],V[6];
  uint64_t t[6],u[6],X3[6],Y3[6],Z3[6];
  mont_mul(Z1Z1,Z,Z);
  mont_mul(U2,x2,Z1Z1);
  mont_mul(S2,y2,Z); mont_mul(S2,S2,Z1Z1);
  submod(H,U2,X);
  submod(t,S2,Y); addmod(rr,t,t);
  if(is_zero6(H)){
    if(is_zero6(t)){ jac_double(X,Y,Z); return; }
    for(int j=0;j<6;j++) Z[j]=0;
    return;
  }
  mont_mul(HH,H,H);
  addmod(I,HH,HH); addmod(I,I,I);
  mont_mul(J,H,I);
  mont_mul(V,X,I);
  mont_mul(X3,rr,rr); submod(X3,X3,J); addmod(u,V,V); submod(X3,X3,u);
  submod(u,V,X3); mont_mul(u,rr,u);
  mont_mul(t,Y,J); addmod(t,t,t); submod(Y3,u,t);
  addmod(t,Z,H); mont_mul(t,t,t); submod(t,t,Z1Z1); submod(Z3,t,HH);
  cpy6(X,X3); cpy6(Y,Y3); cpy6(Z,Z3);
}

void mont_mul_batch(uint64_t* out, const uint64_t* a, const uint64_t* b,
                    size_t n){
  for(size_t i=0;i<n;i++) mont_mul(out+6*i, a+6*i, b+6*i);
}

void mont_pow_batch(uint64_t* out, const uint64_t* base,
                    const uint64_t* exp, int expbits, size_t n){
  for(size_t i=0;i<n;i++){
    uint64_t acc[6]; const uint64_t* b = base+6*i;
    for(int j=0;j<6;j++) acc[j]=b[j];
    for(int k=expbits-2;k>=0;k--){
      mont_mul(acc,acc,acc);
      if((exp[k/64]>>(k%64))&1) mont_mul(acc,acc,b);
    }
    for(int j=0;j<6;j++) out[6*i+j]=acc[j];
  }
}

/* Batched left-to-right double-and-add over Jacobian coords; identity is
   Z == 0 (Montgomery canonical forms make limb-zero == field-zero). The
   accumulators arrive zeroed (identity), exactly mirroring the oracle's
   acc = None start in _ec_mul_raw / _ec_msm. */
void g1_ladder_batch(uint64_t* X, uint64_t* Y, uint64_t* Z,
                     const uint64_t* px, const uint64_t* py,
                     const uint64_t* rone, const unsigned char* bits,
                     int nbits, size_t rows){
  for(size_t r=0;r<rows;r++){
    uint64_t *x=X+6*r, *y=Y+6*r, *z=Z+6*r;
    const uint64_t *bx=px+6*r, *by=py+6*r;
    const unsigned char* rb = bits + (size_t)nbits*r;
    for(int b=0;b<nbits;b++){
      if(!is_zero6(z)) jac_double(x,y,z);
      if(rb[b]){
        if(is_zero6(z)){ cpy6(x,bx); cpy6(y,by); cpy6(z,rone); }
        else jac_madd(x,y,z,bx,by);
      }
    }
  }
}
"""

_LOCK = threading.Lock()
_LIB = None  # None = untried, False = unavailable, else (ffi, lib)


def _load():
    """Compile-and-load the extension once; False when the toolchain is
    missing (no cffi / no C compiler) so callers can fall back to host."""
    global _LIB
    if _LIB is not None:
        return _LIB
    with _LOCK:
        if _LIB is not None:
            return _LIB
        try:
            import cffi

            builder = cffi.FFI()
            builder.cdef(_CDEF)
            builder.set_source("_dr_native381", _C_SOURCE)
            tmpdir = tempfile.mkdtemp(prefix="dr-native381-")
            lib_path = builder.compile(tmpdir=tmpdir, verbose=False)
            spec = importlib.util.spec_from_file_location(
                "_dr_native381", lib_path
            )
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)  # type: ignore[union-attr]
            _LIB = (mod.ffi, mod.lib)
        except Exception:
            _LIB = False
    return _LIB


def available() -> bool:
    return bool(_load())


# --- limb marshalling (48-byte little-endian <-> uint64[6]) ----------------


def _to_u64(vals: Sequence[int]) -> np.ndarray:
    out = np.empty((len(vals), 6), dtype=np.uint64)
    for i, v in enumerate(vals):
        out[i] = np.frombuffer(int(v).to_bytes(48, "little"), dtype=np.uint64)
    return out


def _from_u64(arr: np.ndarray) -> List[int]:
    return [
        int.from_bytes(arr[i].tobytes(), "little")
        for i in range(arr.shape[0])
    ]


def _ptr(ffi, arr: np.ndarray):
    return ffi.cast("uint64_t*", ffi.from_buffer(arr))


def _cptr(ffi, arr: np.ndarray):
    return ffi.cast("const uint64_t*", ffi.from_buffer(arr))


def _mul(ffi, lib, a: np.ndarray, b: np.ndarray) -> np.ndarray:
    out = np.empty_like(a)
    lib.mont_mul_batch(_ptr(ffi, out), _cptr(ffi, a), _cptr(ffi, b), a.shape[0])
    return out


def _to_mont(ffi, lib, arr: np.ndarray) -> np.ndarray:
    r2 = np.ascontiguousarray(np.broadcast_to(_to_u64([_R2])[0], arr.shape))
    return _mul(ffi, lib, arr, r2)


def _from_mont(ffi, lib, arr: np.ndarray) -> np.ndarray:
    one = np.ascontiguousarray(np.broadcast_to(_to_u64([1])[0], arr.shape))
    return _mul(ffi, lib, arr, one)


def _exp_words(exp: int) -> Tuple[np.ndarray, int]:
    nbits = exp.bit_length()
    nwords = (nbits + 63) // 64
    words = np.frombuffer(
        exp.to_bytes(nwords * 8, "little"), dtype=np.uint64
    ).copy()
    return words, nbits


# --- the two batch primitives sign_many builds on --------------------------


def pow_p_batch(values: Sequence[int], exp: int) -> List[int]:
    """[v^exp mod p for v in values] — the batched square-root / inversion
    power map. Falls back to CPython pow when the kernel is unavailable
    (identical results either way; pow is exact)."""
    if not values:
        return []
    loaded = _load()
    if not loaded:
        return [pow(v % P, exp, P) for v in values]
    ffi, lib = loaded
    base = _to_mont(ffi, lib, _to_u64([v % P for v in values]))
    out = np.empty_like(base)
    words, nbits = _exp_words(exp)
    lib.mont_pow_batch(
        _ptr(ffi, out), _cptr(ffi, base), _cptr(ffi, words), nbits, base.shape[0]
    )
    return _from_u64(_from_mont(ffi, lib, out))


def g1_ladder_batch(
    scalars: Sequence[int], points: Sequence[Tuple[int, int]]
) -> Tuple[List[Optional[Tuple[int, int]]], List[bool]]:
    """Batched [k_i]P_i over E(Fp), exact oracle semantics.

    Returns (results, fallback_mask). A result of None means the scalar
    multiple landed on the identity (the caller re-runs the host oracle,
    which retries hash candidates in that case). The fallback mask is all
    False here — the C ladder covers every exceptional branch — and goes
    all True only when the toolchain is unavailable.
    """
    n = len(scalars)
    if n == 0:
        return [], []
    loaded = _load()
    if not loaded:
        return [None] * n, [True] * n
    ffi, lib = loaded
    nbits = max(int(s).bit_length() for s in scalars)
    if nbits == 0:
        return [None] * n, [False] * n
    nbytes = (nbits + 7) // 8
    raw = np.frombuffer(
        b"".join(int(s).to_bytes(nbytes, "big") for s in scalars),
        dtype=np.uint8,
    ).reshape(n, nbytes)
    bits = np.ascontiguousarray(
        np.unpackbits(raw, axis=1)[:, nbytes * 8 - nbits :]
    )
    px = _to_mont(ffi, lib, _to_u64([p[0] for p in points]))
    py = _to_mont(ffi, lib, _to_u64([p[1] for p in points]))
    X = np.zeros((n, 6), dtype=np.uint64)
    Y = np.zeros((n, 6), dtype=np.uint64)
    Z = np.zeros((n, 6), dtype=np.uint64)
    rone = _to_u64([_R_MONT])[0].copy()
    lib.g1_ladder_batch(
        _ptr(ffi, X),
        _ptr(ffi, Y),
        _ptr(ffi, Z),
        _cptr(ffi, px),
        _cptr(ffi, py),
        _cptr(ffi, rone),
        ffi.cast("const unsigned char*", ffi.from_buffer(bits)),
        nbits,
        n,
    )
    inf = ~Z.any(axis=1)
    # one batched inversion pass: z^-1 = z^(p-2), then affine conversion
    zi = np.empty_like(Z)
    words, pbits = _exp_words(P - 2)
    lib.mont_pow_batch(
        _ptr(ffi, zi), _cptr(ffi, Z), _cptr(ffi, words), pbits, n
    )
    zi2 = _mul(ffi, lib, zi, zi)
    xa = _from_u64(_from_mont(ffi, lib, _mul(ffi, lib, X, zi2)))
    ya = _from_u64(
        _from_mont(ffi, lib, _mul(ffi, lib, _mul(ffi, lib, Y, zi2), zi))
    )
    results: List[Optional[Tuple[int, int]]] = [
        None if inf[i] else (xa[i], ya[i]) for i in range(n)
    ]
    return results, [False] * n
