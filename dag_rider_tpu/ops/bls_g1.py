"""Batched G1 hash-map powers and scalar ladders on the field381 limb
kernels — the `DAGRIDER_CERT_SIGN=device` lane (ISSUE 12 tentpole 1).

Same split as the round-3 verifier prep: SHA challenge hashing stays
per-row on the host (`crypto/bls12381._hash_candidate_x`), while the two
heavy batch primitives run as jitted lax.scan ladders over
:mod:`dag_rider_tpu.ops.field381` int32 limbs:

- :func:`pow_p_batch` — shared-exponent powering (the try-and-increment
  square root y2^((p+1)/4) and the affine-conversion inverse z^(p-2));
- :func:`g1_ladder_batch` — left-to-right Jacobian double-and-add over
  all rows at once, transcribing the host oracle's `_jac_double` /
  `_jac_madd` formulas limb-for-limb.

Exactness is the contract: every limb op is exact mod-p arithmetic, so
the ladder result equals the oracle's for every reachable input. The one
branch not worth a device implementation — a mixed addition hitting
H == 0 (the accumulator meeting ±base mid-ladder, possible only for
tiny-order non-torsion candidates) — raises a per-row fallback flag and
the caller re-signs that row on the host, preserving byte-identity.

Like the sharded MSM, this lane is about where the work runs, not local
wall-clock: on this 1-core CPU host the limb kernels lose to the cffi
native lane (see PROFILE round 15); the lane exists so committee-scale
signing has a real accelerator story next to `ops/bls_msm.py`.
"""

from __future__ import annotations

import functools
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from dag_rider_tpu.ops import field381 as f

P = f.P_INT


def _jac_double(X, Y, Z):
    """EFD dbl-2009-l, limb transcription of the oracle's _jac_double.
    All-zero (X, Y, Z) — the identity encoding — is a fixed point."""
    A = f.mul(X, X)
    B = f.mul(Y, Y)
    C = f.mul(B, B)
    t = f.add(X, B)
    D = f.mul_small(f.sub(f.sub(f.mul(t, t), A), C), 2)
    E = f.mul_small(A, 3)
    X3 = f.sub(f.mul(E, E), f.mul_small(D, 2))
    Y3 = f.sub(f.mul(E, f.sub(D, X3)), f.mul_small(C, 8))
    Z3 = f.mul_small(f.mul(Y, Z), 2)
    return X3, Y3, Z3


def _jac_madd(X, Y, Z, x2, y2):
    """EFD madd-2007-bl main branch + the H == 0 detection the step
    function turns into a fallback flag."""
    Z1Z1 = f.mul(Z, Z)
    U2 = f.mul(x2, Z1Z1)
    S2 = f.mul(f.mul(y2, Z), Z1Z1)
    H = f.sub(U2, X)
    r = f.mul_small(f.sub(S2, Y), 2)
    h_zero = f.is_zero(H)
    HH = f.mul(H, H)
    I = f.mul_small(HH, 4)
    J = f.mul(H, I)
    V = f.mul(X, I)
    X3 = f.sub(f.sub(f.mul(r, r), J), f.mul_small(V, 2))
    Y3 = f.sub(f.mul(r, f.sub(V, X3)), f.mul_small(f.mul(Y, J), 2))
    t = f.add(Z, H)
    Z3 = f.sub(f.sub(f.mul(t, t), Z1Z1), HH)
    return X3, Y3, Z3, h_zero


@functools.lru_cache(maxsize=8)
def _pow_fn(nbits: int):
    """Jitted shared-exponent power scan; exponent bits arrive as data
    (top bit excluded — the accumulator starts at the base)."""

    @jax.jit
    def run(base, bits):
        def body(acc, b):
            acc = f.mul(acc, acc)
            acc = f.select(b != 0, f.mul(acc, base), acc)
            return acc, None

        acc, _ = jax.lax.scan(body, base, bits)
        return f.canonical(acc)

    return run


@functools.lru_cache(maxsize=8)
def _ladder_fn(nbits: int):
    """Jitted batched Jacobian ladder over per-row scalar bit columns."""

    @jax.jit
    def run(px, py, bits):
        n = px.shape[0]
        one = jnp.broadcast_to(jnp.asarray(f.ONE), px.shape)

        def body(carry, b):
            X, Y, Z, inf, fb = carry
            X, Y, Z = _jac_double(X, Y, Z)
            Xm, Ym, Zm, h_zero = _jac_madd(X, Y, Z, px, py)
            bit = b != 0
            fb = fb | (bit & ~inf & h_zero)
            take_init = bit & inf
            take_madd = bit & ~inf
            X = f.select(take_init, px, f.select(take_madd, Xm, X))
            Y = f.select(take_init, py, f.select(take_madd, Ym, Y))
            Z = f.select(take_init, one, f.select(take_madd, Zm, Z))
            inf = inf & ~bit
            return (X, Y, Z, inf, fb), None

        zero = jnp.zeros_like(px)
        inf0 = jnp.ones((n,), dtype=bool)
        fb0 = jnp.zeros((n,), dtype=bool)
        (X, Y, Z, inf, fb), _ = jax.lax.scan(
            body, (zero, zero, zero, inf0, fb0), bits
        )
        # affine conversion stays on device: one batched z^(p-2) pass
        zbits = jnp.asarray(
            np.array(
                [(P - 2) >> k & 1 for k in range((P - 2).bit_length() - 2, -1, -1)],
                dtype=np.int32,
            )
        )

        def inv_body(acc, b):
            acc = f.mul(acc, acc)
            acc = f.select(b != 0, f.mul(acc, Z), acc)
            return acc, None

        zi, _ = jax.lax.scan(inv_body, Z, zbits)
        zi2 = f.mul(zi, zi)
        xa = f.canonical(f.mul(X, zi2))
        ya = f.canonical(f.mul(Y, f.mul(zi2, zi)))
        return xa, ya, inf, fb

    return run


def _bit_columns(scalars: Sequence[int]) -> Tuple[np.ndarray, int]:
    """MSB-first bit columns [nbits, n] over the max scalar width (leading
    zeros keep short rows on the identity — exact, like the oracle)."""
    nbits = max(int(s).bit_length() for s in scalars)
    nbytes = (nbits + 7) // 8
    raw = np.frombuffer(
        b"".join(int(s).to_bytes(nbytes, "big") for s in scalars),
        dtype=np.uint8,
    ).reshape(len(scalars), nbytes)
    bits = np.unpackbits(raw, axis=1)[:, nbytes * 8 - nbits :]
    return np.ascontiguousarray(bits.T).astype(np.int32), nbits


def pow_p_batch(values: Sequence[int], exp: int) -> List[int]:
    """[v^exp mod p for v in values] on the limb kernels."""
    if not values:
        return []
    if exp.bit_length() < 2:
        return [pow(v % P, exp, P) for v in values]
    base = jnp.asarray(np.stack([f.to_limbs(v % P) for v in values]))
    ebits = np.array(
        [exp >> k & 1 for k in range(exp.bit_length() - 2, -1, -1)],
        dtype=np.int32,
    )
    out = _pow_fn(exp.bit_length())(base, jnp.asarray(ebits))
    out = np.asarray(out)
    return [f.from_limbs(out[i]) for i in range(out.shape[0])]


def g1_ladder_batch(
    scalars: Sequence[int], points: Sequence[Tuple[int, int]]
) -> Tuple[List[Optional[Tuple[int, int]]], List[bool]]:
    """Batched [k_i]P_i over E(Fp); (results, fallback_mask) with None for
    identity results and flagged rows for the host to re-sign."""
    n = len(scalars)
    if n == 0:
        return [], []
    bits, nbits = _bit_columns(scalars)
    px = jnp.asarray(np.stack([f.to_limbs(p[0]) for p in points]))
    py = jnp.asarray(np.stack([f.to_limbs(p[1]) for p in points]))
    xa, ya, inf, fb = _ladder_fn(nbits)(px, py, jnp.asarray(bits))
    xa, ya = np.asarray(xa), np.asarray(ya)
    inf, fb = np.asarray(inf), np.asarray(fb)
    results: List[Optional[Tuple[int, int]]] = []
    for i in range(n):
        if inf[i] or fb[i]:
            results.append(None)
        else:
            results.append((f.from_limbs(xa[i]), f.from_limbs(ya[i])))
    return results, [bool(x) for x in fb]
