from dag_rider_tpu.ops.dag_kernels import (
    admission_mask,
    closure_from,
    closure_from_full,
    leader_reach,
    pairwise_reach,
    reach_chain,
    round_complete,
    strong_edge_quorum,
    wave_commit_votes,
)

__all__ = [
    "admission_mask",
    "closure_from",
    "closure_from_full",
    "leader_reach",
    "pairwise_reach",
    "reach_chain",
    "round_complete",
    "strong_edge_quorum",
    "wave_commit_votes",
]
