"""Pallas TPU kernel for whole BLS12-381 G1 additions — the MSM tree engine.

Same rationale as :mod:`dag_rider_tpu.ops.pallas_group` (measured on-chip,
PROFILE.md round 3): a group addition is ~12 field multiplies with
stacks/slices/carries between them, and XLA materializes the intermediate
columns of every step in HBM — the Ed25519 comb tree ran ~20x above its
compute floor until its additions became single kernel launches. The MSM
window tree (:func:`dag_rider_tpu.ops.bls_msm.window_sums`) has the same
shape; this kernel performs one complete RCB15 addition per launch with
every intermediate in VMEM.

Layout: limb-major [99, N] int32 — rows are (coordinate, limb) pairs
(3 x 33 homogeneous X, Y, Z), N the flattened batch in the 128-wide lane
axis. Tree levels pair first-half/second-half contiguous lane slices.

Bit-exactness: the limb math replicates :mod:`dag_rider_tpu.ops.field381`
step for step (same masks, carry counts, fold matrix) and the addition
replicates :func:`dag_rider_tpu.ops.bls_msm.padd` op for op, so results
are bit-identical to the jnp path (tests/test_pallas_group381.py runs
interpret mode against it).
"""

from __future__ import annotations

import functools
from typing import List, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from dag_rider_tpu.ops import field381 as F
from dag_rider_tpu.ops.pallas_group import _call_rowwise

L = F.LIMBS  # 33
COORDS = 3
ROWS = COORDS * L  # 99
_NCOLS = F._NCOLS  # 67
_FOLD = [[int(v) for v in row] for row in F.FOLD]  # [35][32]
_FOLD_TOP = [int(v) for v in F._FOLD_TOP]  # [33]


# ---------------------------------------------------------------------------
# In-kernel limb math on lists of lane-vector rows (twin of field381)
# ---------------------------------------------------------------------------


def _carry33(rows: List, steps: int = 2) -> List:
    """field381.carry on a 33-row list: parallel carry steps, the top
    (weight 2^396) carry folding back through the 2^396 mod p row."""
    for _ in range(steps):
        cs = [r >> F.LIMB_BITS for r in rows]
        rows = [r & F.LIMB_MASK for r in rows]
        top = cs[L - 1]
        for j in range(L - 1):
            rows[j + 1] = rows[j + 1] + cs[j]
        for i in range(L):
            if _FOLD_TOP[i]:
                rows[i] = rows[i] + top * _FOLD_TOP[i]
    return rows


def _add33(a: List, b: List) -> List:
    return _carry33([x + y for x, y in zip(a, b)])


def _sub33(a: List, b: List) -> List:
    return _carry33([x - y for x, y in zip(a, b)])


def _mul_small33(a: List, k: int) -> List:
    return _carry33([x * k for x in a], steps=3)


def _mul33(a: List, b: List) -> List:
    """Schoolbook 33x33 -> 67 columns, two normalize passes, fold-matrix
    reduction — the exact step sequence of field381.mul."""
    c = [None] * (2 * L - 1)  # columns 0..64
    for i in range(L):
        for j in range(L):
            t = a[i] * b[j]
            k = i + j
            c[k] = t if c[k] is None else c[k] + t
    zero = jnp.zeros_like(a[0])
    c = [zero if x is None else x for x in c] + [zero, zero]  # 67 cols
    for _ in range(2):
        carries = [x >> F.LIMB_BITS for x in c]
        c = [x & F.LIMB_MASK for x in c]
        for k in range(len(c) - 1):
            c[k + 1] = c[k + 1] + carries[k]
    lo = c[:32]
    hi = c[32:_NCOLS]  # 35 columns
    for j in range(len(hi)):
        row = _FOLD[j]
        for i in range(32):
            if row[i]:
                lo[i] = lo[i] + hi[j] * row[i]
    out = lo + [zero]  # limb 32 = 0
    return _carry33(out, steps=3)


# ---------------------------------------------------------------------------
# Complete addition (RCB15 Algorithm 7, a = 0, b3 = 12) — bls_msm.padd twin
# ---------------------------------------------------------------------------


def _padd381_core(p: List[List], q: List[List]) -> List[List]:
    X1, Y1, Z1 = p
    X2, Y2, Z2 = q
    t0 = _mul33(X1, X2)
    t1 = _mul33(Y1, Y2)
    t2 = _mul33(Z1, Z2)
    t3 = _mul33(_add33(X1, Y1), _add33(X2, Y2))
    t3 = _sub33(t3, _add33(t0, t1))
    t4 = _mul33(_add33(Y1, Z1), _add33(Y2, Z2))
    t4 = _sub33(t4, _add33(t1, t2))
    x3 = _mul33(_add33(X1, Z1), _add33(X2, Z2))
    y3 = _sub33(x3, _add33(t0, t2))
    x3 = _add33(_add33(t0, t0), t0)  # 3 X1 X2
    t2 = _mul_small33(t2, 12)  # b3 Z1 Z2
    z3 = _add33(t1, t2)
    t1 = _sub33(t1, t2)
    y3 = _mul_small33(y3, 12)  # b3 (X1 Z2 + X2 Z1)
    X3 = _sub33(_mul33(t3, t1), _mul33(t4, y3))
    Y3 = _add33(_mul33(y3, x3), _mul33(t1, z3))
    Z3 = _add33(_mul33(z3, t4), _mul33(x3, t3))
    return [X3, Y3, Z3]


def _read_point(ref) -> List[List]:
    if len(ref.shape) == 2:
        return [
            [ref[c * L + i : c * L + i + 1, :] for i in range(L)]
            for c in range(COORDS)
        ]
    return [[ref[c * L + i, 0] for i in range(L)] for c in range(COORDS)]


def _write_point(ref, coords: Sequence[List]) -> None:
    if len(ref.shape) == 2:
        for c in range(COORDS):
            for i in range(L):
                ref[c * L + i : c * L + i + 1, :] = coords[c][i]
    else:
        for c in range(COORDS):
            for i in range(L):
                ref[c * L + i, 0] = coords[c][i]


def _padd381_kernel(p_ref, q_ref, o_ref):
    _write_point(o_ref, _padd381_core(_read_point(p_ref), _read_point(q_ref)))


# ---------------------------------------------------------------------------
# Host-callable wrappers
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("interpret",))
def padd381_xx(
    p: jax.Array, q: jax.Array, *, interpret: bool = False
) -> jax.Array:
    """p, q: int32[99, N] packed XYZ -> [99, N] complete addition."""
    return _call_rowwise(_padd381_kernel, ROWS, interpret, p, q)


def tree_sum_xyz381(
    entries: jax.Array, *, interpret: bool = False
) -> jax.Array:
    """Sum M packed XYZ points per element: [..., M, 3, 33] -> [..., 3, 33].

    Transposes once to limb-major [99, M * flat], halves the lane axis
    each level with :func:`padd381_xx` (contiguous first-half/second-half
    pairing — order is free by associativity), transposes the tiny result
    back. M must be a power of two; identity (0:1:0) entries are harmless
    padding (complete formulas).
    """
    *lead, m, coords, limbs = entries.shape
    assert coords == COORDS and limbs == L and m & (m - 1) == 0
    flat = int(np.prod(lead)) if lead else 1
    x = jnp.moveaxis(entries.reshape(flat, m, COORDS, L), 0, -1)
    x = jnp.moveaxis(x, 0, -2)  # [3, 33, M, flat]
    x = x.reshape(ROWS, m * flat)
    while m > 1:
        half = m // 2 * flat
        x = padd381_xx(x[:, :half], x[:, half:], interpret=interpret)
        m //= 2
    out = x.reshape(COORDS, L, *lead) if lead else x.reshape(COORDS, L)
    return jnp.moveaxis(jnp.moveaxis(out, 1, -1), 0, -2)  # [..., 3, 33]
