"""Pallas TPU kernels for whole Edwards group ops — the comb tree's engine.

Why these exist (measured on-chip, PROFILE.md round 3): the jnp field
multiply runs its 484 MACs at near-VPU-peak *inside* one fused op, but a
group addition is ~10 multiplies with stacks/slices/carries between them,
and XLA materializes the intermediate columns between every step — the
comb tree ran ~20x above its compute floor, memory-bound on HLO temps.
Each kernel here performs one complete point addition (two full
schoolbook multiplies per coordinate set, carries, the 2^255==19 fold)
with every intermediate in VMEM/vector registers: HBM sees exactly one
read of each operand block and one write of the result.

Layout: limb-major [88, N] int32 — rows are (coordinate, limb) pairs
(4 x 22), N is the flattened batch in the 128-wide lane axis. The comb
pipeline gathers row-major table entries, transposes ONCE to limb-major,
runs the whole reduction tree in these kernels, and transposes the tiny
result back. Tree levels pair first-half/second-half (contiguous lane
slices — pairing order is free by associativity), never strided lanes.

Bit-exactness: the limb math is the same signed-12-bit schoolbook as
:mod:`dag_rider_tpu.ops.field` (same masks, shifts, fold constants, same
carry counts), so results are bit-identical to the jnp path
(tests/test_pallas_group.py runs interpret mode against the jnp oracle).
"""

from __future__ import annotations

import functools
from typing import List, Sequence

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from dag_rider_tpu.ops import field as F

L = F.LIMBS  # 22
ROWS = 4 * L  # 88


# ---------------------------------------------------------------------------
# In-kernel limb math on lists of [1, T] lane vectors
# ---------------------------------------------------------------------------


def _carry2(rows: List, steps: int = 2) -> List:
    """field.carry on a 22-row list (parallel steps, top fold)."""
    for _ in range(steps):
        cs = [r >> F.LIMB_BITS for r in rows]
        rows = [r & F.LIMB_MASK for r in rows]
        rows[0] = rows[0] + cs[L - 1] * F.TOP_FOLD
        for j in range(L - 1):
            rows[j + 1] = rows[j + 1] + cs[j]
    return rows


def _add22(a: List, b: List) -> List:
    return _carry2([x + y for x, y in zip(a, b)])


def _sub22(a: List, b: List) -> List:
    return _carry2([x - y for x, y in zip(a, b)])


def _dbl22(a: List) -> List:
    return _carry2([x + x for x in a])


def _mul22(a: List, b) -> List:
    """Schoolbook multiply of 22-row lists (b may be a list of rows or a
    22-int constant limb vector); same steps as field.mul."""
    b_const = not isinstance(b[0], jax.Array)
    c = [None] * 43
    for i in range(L):
        for j in range(L):
            if b_const:
                if b[j] == 0:
                    continue
                t = a[i] * int(b[j])
            else:
                t = a[i] * b[j]
            k = i + j
            c[k] = t if c[k] is None else c[k] + t
    zero = jnp.zeros_like(a[0])
    c = [zero if x is None else x for x in c] + [zero, zero, zero]  # 46 cols
    for _ in range(2):
        carries = [x >> F.LIMB_BITS for x in c]
        c = [x & F.LIMB_MASK for x in c]
        for k in range(len(c) - 1):
            c[k + 1] = c[k + 1] + carries[k]
    lo = c[:L]
    hi = c[L : 2 * L]
    t = [h * 19 for h in hi]
    for j in range(L):
        lo[j] = lo[j] + ((t[j] & 0x7) << 9)
    up = [tj >> 3 for tj in t]
    for j in range(L - 1):
        lo[j + 1] = lo[j + 1] + up[j]
    t2 = up[L - 1] * 19
    lo[0] = lo[0] + ((t2 & 0x7) << 9)
    lo[1] = lo[1] + (t2 >> 3)
    lo[1] = lo[1] + c[44] * 23104
    lo[2] = lo[2] + c[45] * 23104
    return _carry2(lo, steps=3)


_D2_LIMBS = [int(v) for v in F.D2]
_D_LIMBS = [int(v) for v in F.D]
_SQRT_M1_LIMBS = [int(v) for v in F.SQRT_M1]
_BIG_P = [int(v) for v in F.BIG_P]


def _seq_carry_fold_rows(rows: List) -> List:
    """In-kernel twin of field._seq_carry_fold (exact sequential pass)."""
    carry_in = jnp.zeros_like(rows[0])
    out = []
    for i in range(L):
        v = rows[i] + carry_in
        out.append(v & F.LIMB_MASK)
        carry_in = v >> F.LIMB_BITS
    out[0] = out[0] + carry_in * F.TOP_FOLD
    hi = out[L - 1] >> 3
    out[L - 1] = out[L - 1] & 0x7
    out[0] = out[0] + hi * 19
    return out


def _canon22(rows: List) -> List:
    """In-kernel twin of field.canonical — unique representative mod p."""
    rows = [r + _BIG_P[i] for i, r in enumerate(rows)]
    for _ in range(3):
        rows = _seq_carry_fold_rows(rows)
    t = list(rows)
    t[0] = t[0] + 19
    carry_in = jnp.zeros_like(t[0])
    tt = []
    for i in range(L):
        v = t[i] + carry_in
        tt.append(v & F.LIMB_MASK)
        carry_in = v >> F.LIMB_BITS
    ge_p = (tt[L - 1] >> 3) > 0
    tt[L - 1] = tt[L - 1] & 0x7
    return [jnp.where(ge_p, tt[i], rows[i]) for i in range(L)]


def _is_zero22(rows: List):
    c = _canon22(rows)
    acc = c[0] == 0
    for i in range(1, L):
        acc = acc & (c[i] == 0)
    return acc


def _eq22(a: List, b: List):
    return _is_zero22(_sub22(a, b))


def _parity22(rows: List):
    return _canon22(rows)[0] & 1


def _neg22(a: List) -> List:
    return _carry2([-x for x in a])


def _select22(cond, a: List, b: List) -> List:
    return [jnp.where(cond, x, y) for x, y in zip(a, b)]


def _read_point(ref) -> List[List]:
    """Block ref -> 4 coordinate row-lists (X, Y, Z, T).

    2D blocks ([88, T]) keep rows as [1, T]; 4D blocks ([88, 1, 8, 128])
    give each row a full (8, 128) vreg — 8x the lane-axis utilization
    (the [1, T] layout left 7 of 8 sublanes idle per op)."""
    if len(ref.shape) == 2:
        return [
            [ref[c * L + i : c * L + i + 1, :] for i in range(L)]
            for c in range(4)
        ]
    return [[ref[c * L + i, 0] for i in range(L)] for c in range(4)]


def _write_point(ref, coords: Sequence[List]) -> None:
    if len(ref.shape) == 2:
        for c in range(4):
            for i in range(L):
                ref[c * L + i : c * L + i + 1, :] = coords[c][i]
    else:
        for c in range(4):
            for i in range(L):
                ref[c * L + i, 0] = coords[c][i]


def _padd_core(p: List[List], qc: List[List]) -> List[List]:
    """add-2008-hwcd-3 with q pre-transformed to cached rows
    (Y-X, Y+X, 2dT, 2Z). Returns XYZT row-lists."""
    x1, y1, z1, t1 = p
    a = _mul22(_sub22(y1, x1), qc[0])
    b = _mul22(_add22(y1, x1), qc[1])
    cc = _mul22(t1, qc[2])
    d = _mul22(z1, qc[3])
    e = _sub22(b, a)
    f = _sub22(d, cc)
    g = _add22(d, cc)
    h = _add22(b, a)
    return [_mul22(e, f), _mul22(g, h), _mul22(f, g), _mul22(e, h)]


def _padd_xx_kernel(p_ref, q_ref, o_ref):
    """Packed XYZT + packed XYZT -> packed XYZT (complete addition)."""
    p = _read_point(p_ref)
    q = _read_point(q_ref)
    x2, y2, z2, t2 = q
    qc = [
        _sub22(y2, x2),
        _add22(y2, x2),
        _mul22(t2, _D2_LIMBS),
        _dbl22(z2),
    ]
    _write_point(o_ref, _padd_core(p, qc))


def _pow22523_rows(z: List) -> List:
    """z^(2^252 - 3) on row lists — the RFC 8032 sqrt exponent chain,
    entirely in VMEM. fori_loop keeps the Mosaic program small for the
    long square runs; tuple carries, not stacked arrays (jnp.stack of 22
    rows forced a VMEM relayout every iteration — the 250-deep chain
    spent ~5x its multiply time shuffling, measured on-chip)."""

    def nsq(x: List, n: int) -> List:
        if n <= 4:
            for _ in range(n):
                x = _mul22(x, x)
            return x

        def body(_, rows):
            return tuple(_mul22(list(rows), list(rows)))

        out = jax.lax.fori_loop(0, n, body, tuple(x))
        return list(out)

    t0 = _mul22(z, z)                       # 2
    t1 = _mul22(z, nsq(t0, 2))              # 9
    t0 = _mul22(t0, t1)                     # 11
    t0 = _mul22(t1, _mul22(t0, t0))         # 31
    t0 = _mul22(nsq(t0, 5), t0)             # 2^10 - 1
    t1 = _mul22(nsq(t0, 10), t0)            # 2^20 - 1
    t2 = _mul22(nsq(t1, 20), t1)            # 2^40 - 1
    t1 = _mul22(nsq(t2, 10), t0)            # 2^50 - 1
    t2 = _mul22(nsq(t1, 50), t1)            # 2^100 - 1
    t3 = _mul22(nsq(t2, 100), t2)           # 2^200 - 1
    t1 = _mul22(nsq(t3, 50), t1)            # 2^250 - 1
    return _mul22(nsq(t1, 2), z)            # 2^252 - 3


def _read_rows(ref, start: int, count: int) -> List:
    if len(ref.shape) == 2:
        return [ref[start + i : start + i + 1, :] for i in range(count)]
    return [ref[start + i, 0] for i in range(count)]


def _pow22523_kernel(z_ref, o_ref):
    out = _pow22523_rows(_read_rows(z_ref, 0, L))
    for i in range(L):
        if len(o_ref.shape) == 2:
            o_ref[i : i + 1, :] = out[i]
        else:
            o_ref[i, 0] = out[i]


def _finish_kernel(y_ref, sign_ref, acc_ref, o_ref):
    """Everything after the comb trees, in ONE launch: R decompression
    (incl. the sqrt chain), rhs = R + [k]A, and the projective equality
    [s]B == rhs — the equality/parity tests each need an exact canonical
    pass (22-step sequential carries), which as XLA ops were a long
    dependent chain of tiny kernels.

    y_ref: [22, T] R.y limbs; sign_ref: [1, T] sign bits;
    acc_ref: [176, T] — rows 0..87 = [s]B (XYZT), 88..175 = [k]A.
    o_ref: [1, T] int32 — 1 iff R decompressed valid AND lhs == rhs.
    Ports curve.decompress + curve.padd + curve.points_equal exactly
    (same decision tree; boolean output bit-identical by canonicality).
    """
    y = _read_rows(y_ref, 0, L)
    sign = _read_rows(sign_ref, 0, 1)[0]
    lhs = [_read_rows(acc_ref, c * L, L) for c in range(4)]
    ka = [_read_rows(acc_ref, 88 + c * L, L) for c in range(4)]

    one = [jnp.ones_like(y[0])] + [jnp.zeros_like(y[0])] * (L - 1)
    y2 = _mul22(y, y)
    u = _sub22(y2, one)
    v = _add22(_mul22(y2, _D_LIMBS), one)
    v3 = _mul22(_mul22(v, v), v)
    v7 = _mul22(_mul22(v3, v3), v)
    cand = _mul22(_mul22(u, v3), _pow22523_rows(_mul22(u, v7)))
    vxx = _mul22(v, _mul22(cand, cand))
    root1 = _eq22(vxx, u)
    root2 = _eq22(vxx, _neg22(u))
    x = _select22(root1, cand, _mul22(cand, _SQRT_M1_LIMBS))
    valid = root1 | root2
    x_zero = _is_zero22(x)
    valid = valid & ~(x_zero & (sign == 1))
    flip = _parity22(x) != sign
    x = _select22(flip, _neg22(x), x)
    r_point = [x, y, one, _mul22(x, y)]

    # rhs = R + [k]A (complete addition, ka cached on the fly)
    x2, y2k, z2, t2 = ka
    qc = [
        _sub22(y2k, x2),
        _add22(y2k, x2),
        _mul22(t2, _D2_LIMBS),
        _dbl22(z2),
    ]
    rhs = _padd_core(r_point, qc)

    # projective equality lhs == rhs
    ex = _is_zero22(
        _sub22(_mul22(lhs[0], rhs[2]), _mul22(rhs[0], lhs[2]))
    )
    ey = _is_zero22(
        _sub22(_mul22(lhs[1], rhs[2]), _mul22(rhs[1], lhs[2]))
    )
    bit = (ex & ey & valid).astype(jnp.int32)
    if len(o_ref.shape) == 2:
        o_ref[0:1, :] = bit
    else:
        o_ref[0, 0] = bit


# ---------------------------------------------------------------------------
# Host-callable wrappers
# ---------------------------------------------------------------------------


def _block(n: int) -> int:
    for b in (512, 256, 128):
        if n % b == 0:
            return b
    return n  # tiny test sizes (interpret mode)


_VREG = 8 * 128  # one (8, 128) int32 vector register's worth of lanes


def _call_rowwise(kernel, out_rows: int, interpret: bool, *args: jax.Array):
    """Run `kernel` over [rows_i, N] operands, blocked for full-vreg rows.

    Row counts may differ per operand (each arg's shape[0] is used); the
    lane count N must match. When N divides into (8, 128) vregs the
    operands are viewed as [rows, G, 8, 128] and each block is one
    vreg-shaped row set; otherwise (tiny test sizes) a flat [rows, blk]
    2D block is used.
    """
    n = args[0].shape[1]
    if n % _VREG == 0:
        g = n // _VREG
        shaped = [a.reshape(a.shape[0], g, 8, 128) for a in args]
        out = pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((out_rows, g, 8, 128), jnp.int32),
            grid=(g,),
            in_specs=[
                pl.BlockSpec((a.shape[0], 1, 8, 128), lambda i: (0, i, 0, 0))
                for a in args
            ],
            out_specs=pl.BlockSpec(
                (out_rows, 1, 8, 128), lambda i: (0, i, 0, 0)
            ),
            interpret=interpret,
        )(*shaped)
        return out.reshape(out_rows, n)
    blk = _block(n)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((out_rows, n), jnp.int32),
        grid=(n // blk,),
        in_specs=[
            pl.BlockSpec((a.shape[0], blk), lambda i: (0, i)) for a in args
        ],
        out_specs=pl.BlockSpec((out_rows, blk), lambda i: (0, i)),
        interpret=interpret,
    )(*args)


@functools.partial(jax.jit, static_argnames=("interpret",))
def padd_xx(p: jax.Array, q: jax.Array, *, interpret: bool = False) -> jax.Array:
    """p, q: int32[88, N] packed XYZT (N a multiple of 128) -> [88, N]."""
    return _call_rowwise(_padd_xx_kernel, ROWS, interpret, p, q)


@functools.partial(jax.jit, static_argnames=("interpret",))
def pow22523(z: jax.Array, *, interpret: bool = False) -> jax.Array:
    """z: int32[22, N] -> z^(2^252-3): one launch, zero HBM between muls.

    The production path runs this chain inside :func:`finish_check`'s
    kernel; this standalone entry exists for benchmarking and as the
    kernel-level unit under test."""
    return _call_rowwise(_pow22523_kernel, L, interpret, z)


@functools.partial(jax.jit, static_argnames=("interpret",))
def finish_check(
    r_y: jax.Array, r_sign: jax.Array, acc: jax.Array, *, interpret: bool = False
) -> jax.Array:
    """The post-tree tail of comb verification as ONE kernel launch.

    r_y: int32[B, 22]; r_sign: int32[B]; acc: int32[B, 2, 4, 22]
    (axis 1 = ([s]B, [k]A)). Returns bool[B]: R valid AND [s]B == R+[k]A.
    """
    b = r_y.shape[0]
    y_t = jnp.moveaxis(r_y, 0, 1)  # [22, B]
    sign_t = r_sign.reshape(1, b)
    acc_t = jnp.moveaxis(acc.reshape(b, 8, L), 0, -1).reshape(8 * L, b)
    out = _call_rowwise(_finish_kernel, 1, interpret, y_t, sign_t, acc_t)
    return out.reshape(b).astype(bool)


def tree_sum_xyzt(entries: jax.Array, *, interpret: bool = False) -> jax.Array:
    """Sum M packed XYZT points per element: [..., M, 4, 22] -> [..., 4, 22].

    Transposes once to limb-major [88, M * flat], halves the lane axis
    each level with :func:`padd_xx` (contiguous first-half/second-half
    pairing), transposes the tiny result back. M must be a power of two;
    identity entries are harmless padding (complete formulas).
    """
    *lead, m, four, limbs = entries.shape
    assert four == 4 and limbs == L and m & (m - 1) == 0
    flat = int(np.prod(lead)) if lead else 1
    # [..., M, 4, 22] -> [4, 22, M, flat] -> [88, M * flat]
    x = jnp.moveaxis(entries.reshape(flat, m, 4, L), 0, -1)  # [M, 4, 22, flat]
    x = jnp.moveaxis(x, 0, -2)  # [4, 22, M, flat]
    x = x.reshape(ROWS, m * flat)
    while m > 1:
        half = m // 2 * flat
        x = padd_xx(x[:, :half], x[:, half:], interpret=interpret)
        m //= 2
    out = x.reshape(4, L, *lead) if lead else x.reshape(4, L)
    return jnp.moveaxis(jnp.moveaxis(out, 1, -1), 0, -2)  # [..., 4, 22]
