"""Dense-tensor DAG kernels (the TPU-native graph layer).

The reference implements graph queries by pointer-chasing and linear scans:
``path()`` is a per-query BFS (``process/process.go:89-148``) and
``present()`` scans the entire DAG per predecessor
(``process/process.go:374-384``) — O(n^2 * rounds) per vertex admission.

Here the DAG is encoded as dense tensors indexed by (round, source):

- ``exists[R, n]``  : bool — vertex (r, i) is in the DAG.
- ``strong[R, n, n]``: bool — strong[r, i, j] means vertex (r, i) has a
  strong edge to vertex (r-1, j). Row r=0 is unused (genesis has no edges).
- weak edges (round-skipping, rare) are kept sparse on the host; an optional
  dense ``weak[R, n, R, n]`` form is supported for small configs/tests.

Reachability then becomes a chain of boolean matrix products — an exact MXU
fit: reach(r_hi -> r_lo) = strong[r_hi] @ strong[r_hi-1] @ ... @
strong[r_lo+1], and the wave-commit rule "2f+1 round-(w,4) vertices have a
strong path to the leader" (``process/process.go:331-339``) is one 3-matmul
chain + a popcount.

All kernels are pure jnp and jit-able; ``n`` and ``R`` are static shapes.
Matmuls are done in float32/bf16 (counts saturate via > 0) so XLA tiles them
onto the MXU; booleans only materialize at the edges.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

# ---------------------------------------------------------------------------
# Boolean semiring primitives
# ---------------------------------------------------------------------------


def _bmm(a: jax.Array, b: jax.Array) -> jax.Array:
    """Boolean matrix product: (a @ b) > 0, computed in float32 on the MXU.

    a: [..., m, k] bool, b: [..., k, p] bool -> [..., m, p] bool.
    """
    return (
        jnp.matmul(
            a.astype(jnp.float32),
            b.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        > 0.0
    )


@jax.jit
def reach_chain(strong_stack: jax.Array) -> jax.Array:
    """Multi-round strong reachability as a matmul chain.

    Args:
        strong_stack: bool[k, n, n], ordered top round first:
            strong_stack[0] maps round r_hi -> r_hi - 1,
            strong_stack[k-1] maps round r_lo + 1 -> r_lo.

    Returns:
        bool[n, n]: entry (i, j) — vertex (r_hi, i) has a strong path to
        vertex (r_lo, j). Rows of absent vertices are all-zero because their
        strong rows are all-zero.

    Replaces repeated BFS calls over consecutive rounds (reference ``path``,
    ``process/process.go:89-148``, restricted to strong edges).
    """

    def step(carry, s):
        return _bmm(carry, s), None

    init = strong_stack[0]
    if strong_stack.shape[0] == 1:
        return init
    out, _ = lax.scan(step, init, strong_stack[1:])
    return out


# ---------------------------------------------------------------------------
# Round advancement + admission (Algorithm 2)
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("quorum",))
def round_complete(exists_row: jax.Array, *, quorum: int) -> jax.Array:
    """|dag[r]| >= 2f+1 — the round-advance condition
    (reference ``process/process.go:236``)."""
    return jnp.sum(exists_row.astype(jnp.int32)) >= quorum


@jax.jit
def admission_mask(
    strong_pred: jax.Array,
    exists_prev: jax.Array,
    weak_pred: jax.Array,
    exists: jax.Array,
) -> jax.Array:
    """Which buffered vertices have *all* predecessors already in the DAG.

    This is the buffer-drain predicate of Algorithm 2 (reference
    ``process/process.go:208-228``), vectorized over a whole buffer:

    Args:
        strong_pred: bool[B, n]   — strong-edge targets in round r-1.
        exists_prev: bool[n]      — exists[r-1].
        weak_pred:   bool[B, R, n] — weak-edge targets across all rounds.
        exists:      bool[R, n]   — full presence bitmap.

    Returns:
        bool[B] — admissible[b] iff every referenced predecessor exists.
    """
    strong_ok = ~jnp.any(strong_pred & ~exists_prev[None, :], axis=-1)
    weak_ok = ~jnp.any(weak_pred & ~exists[None, :, :], axis=(-2, -1))
    return strong_ok & weak_ok


@functools.partial(jax.jit, static_argnames=("quorum",))
def strong_edge_quorum(strong_pred: jax.Array, *, quorum: int) -> jax.Array:
    """r_deliver admission gate: vertex carries >= 2f+1 strong edges
    (reference ``process/process.go:164-168``). strong_pred: bool[B, n]."""
    return jnp.sum(strong_pred.astype(jnp.int32), axis=-1) >= quorum


# ---------------------------------------------------------------------------
# Wave commit (Algorithm 3)
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("quorum",))
def wave_commit_votes(
    strong_wave: jax.Array,
    exists_r4: jax.Array,
    leader: jax.Array,
    *,
    quorum: int,
) -> tuple[jax.Array, jax.Array]:
    """The wave-commit quorum check (reference ``process/process.go:331-339``).

    Args:
        strong_wave: bool[3, n, n] — strong adjacency for rounds
            (w,4), (w,3), (w,2), i.e. strong_wave[0] maps round(w,4) ->
            round(w,3), ..., strong_wave[2] maps round(w,2) -> round(w,1).
        exists_r4: bool[n] — presence bitmap of round(w,4).
        leader: int32 scalar — source index of the wave-w leader vertex at
            round(w,1).

    Returns:
        (commit: bool scalar, votes: bool[n]) — votes[i] iff vertex
        (round(w,4), i) exists and has a strong path to the leader; commit
        iff popcount(votes) >= 2f+1.
    """
    reach = reach_chain(strong_wave)  # [n, n]: round(w,4) -> round(w,1)
    votes = reach[:, leader] & exists_r4
    commit = jnp.sum(votes.astype(jnp.int32)) >= quorum
    return commit, votes


@jax.jit
def leader_reach(strong_wave: jax.Array, hi_leader: jax.Array) -> jax.Array:
    """One step of the retroactive leader-chain descent
    (reference ``process/process.go:342-350``).

    Args:
        strong_wave: bool[k, n, n] — adjacency chain from the higher
            leader's round down to the lower leader's round + 1 (k = 4 for
            consecutive waves).
        hi_leader: int32 — source of the already-committed higher leader.

    Returns:
        bool[n] — which sources' vertices at the lower round are reachable
        from the higher leader by a strong path.
    """
    reach = reach_chain(strong_wave)
    return reach[hi_leader, :]


# ---------------------------------------------------------------------------
# Causal closure (total ordering support)
# ---------------------------------------------------------------------------


@jax.jit
def closure_from(seeds: jax.Array, strong: jax.Array) -> jax.Array:
    """Strong-edge causal history of a seed set.

    Propagates reachability downward round by round:
        reached[r-1] |= reached[r] @ strong[r]

    Args:
        seeds: bool[R, n] — starting vertices (e.g. one-hot of a leader).
        strong: bool[R, n, n].

    Returns:
        bool[R, n] — all vertices reachable from the seeds via strong paths
        (seeds included). This is the dense analog of the per-vertex BFS the
        reference runs inside ``orderVertices`` (``process/process.go:417-431``).
    """
    R = seeds.shape[0]

    def step(carry_row, xs):
        seed_row, strong_r = xs  # seed_row = seeds[r-1]; strong_r = strong[r]
        nxt = seed_row | _bmm(carry_row[None, :], strong_r)[0]
        return nxt, nxt

    init = seeds[R - 1]
    if R == 1:
        return seeds
    xs = (seeds[R - 2 :: -1], strong[: 0 : -1])
    _, rows = lax.scan(step, init, xs)
    return jnp.concatenate([rows[::-1], init[None, :]], axis=0)


@jax.jit
def closure_from_full(
    seeds: jax.Array, strong: jax.Array, weak: jax.Array
) -> jax.Array:
    """Causal history over strong *and* weak edges (dense weak form).

    weak: bool[R, n, R, n] — weak[r, i, r2, j] means (r, i) has a weak edge
    to (r2, j), r2 < r-1. Dense weak tensors are only practical for small
    configs (tests, n<=16); production ordering keeps weak edges sparse on
    the host (see consensus.dag_state), exactly as the north star keeps
    ordering host-side.

    Returns bool[R, n] as in :func:`closure_from`.
    """
    R, n = seeds.shape

    def body(r_rev, acc):
        r = R - 1 - r_rev
        row = acc[r]  # finalized: nothing above r is unprocessed
        strong_contrib = _bmm(row[None, :], strong[r])[0]
        acc = lax.cond(
            r > 0,
            lambda a: a.at[r - 1].set(a[r - 1] | strong_contrib),
            lambda a: a,
            acc,
        )
        weak_contrib = (
            jnp.tensordot(
                row.astype(jnp.float32),
                weak[r].astype(jnp.float32).reshape(n, R * n),
                axes=1,
            )
            > 0.0
        ).reshape(R, n)
        return acc | weak_contrib

    return lax.fori_loop(0, R, body, seeds)


# ---------------------------------------------------------------------------
# Host twins (numpy)
# ---------------------------------------------------------------------------
#
# The vectorized host pump (consensus/process.py, DAGRIDER_PUMP=vector)
# needs these same predicates per round, but a jitted dispatch costs
# ~50-100 us on CPU — more than the whole batched numpy op at n=256. So
# the hot path calls these numpy twins; tests/test_pump_vector.py pins
# each twin equal to its jitted sibling on random DAGs so they cannot
# drift apart. Bool @ bool numpy matmul is the established idiom here
# (consensus/process.py _weak_edges_for).


def reach_chain_np(strong_stack) -> "np.ndarray":
    """Numpy twin of :func:`reach_chain`: bool[k, n, n] top round first ->
    bool[n, n] reachability from round r_hi to round r_lo."""
    out = strong_stack[0]
    for s in strong_stack[1:]:
        out = out @ s
    return np.asarray(out, dtype=bool)


def round_complete_np(exists_row, *, quorum: int) -> bool:
    """Numpy twin of :func:`round_complete`."""
    return bool(np.count_nonzero(exists_row) >= quorum)


def admission_mask_np(strong_pred, exists_prev, weak_pred, exists):
    """Numpy twin of :func:`admission_mask` (same shapes/semantics)."""
    strong_ok = ~np.any(strong_pred & ~exists_prev[None, :], axis=-1)
    weak_ok = ~np.any(weak_pred & ~exists[None, :, :], axis=(-2, -1))
    return strong_ok & weak_ok


def strong_edge_quorum_np(strong_pred, *, quorum: int):
    """Numpy twin of :func:`strong_edge_quorum`: bool[B]."""
    return np.count_nonzero(strong_pred, axis=-1) >= quorum


def leader_reach_np(strong_stack, hi_leader: int) -> "np.ndarray":
    """Numpy twin of :func:`leader_reach` — but seeded, so the descent is
    vector @ matrix per round (O(k n^2)) instead of materializing the full
    n x n chain product (O(k n^3))."""
    vec = np.asarray(strong_stack[0][hi_leader], dtype=bool)
    for s in strong_stack[1:]:
        vec = vec @ s
    return np.asarray(vec, dtype=bool)


@jax.jit
def pairwise_reach(strong: jax.Array) -> jax.Array:
    """All-pairs strong reachability: bool[R, n, R*? ] — here returned as
    reach[R, n, n] where reach[r] maps round-r vertices to round-0... no:

    Returns reach[R, n, R, n]? That is O((Rn)^2); instead this returns the
    cumulative chain products chain[r] = strong[r] @ ... @ strong[1],
    i.e. chain[r][i, j] — (r, i) strongly reaches (0, j). Useful for genesis
    anchoring tests. chain[0] = I.
    """
    R, n, _ = strong.shape

    def step(carry, s):
        nxt = _bmm(s, carry)
        return nxt, nxt

    init = jnp.eye(n, dtype=bool)
    _, outs = lax.scan(step, init, strong[1:])
    return jnp.concatenate([init[None], outs], axis=0)
