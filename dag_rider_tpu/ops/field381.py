"""GF(p) arithmetic for BLS12-381 in int32 limbs — the 381-bit field layer.

Companion of :mod:`dag_rider_tpu.ops.field` (the 2^255-19 field under the
Ed25519 verifier) for the BLS12-381 base field under the G1 MSM kernel
(:mod:`dag_rider_tpu.ops.bls_msm` — BASELINE.json configs #4-5; the
reference's coin TODO at ``process/process.go:388`` is what this
ultimately serves).

Same design stance as ``field.py`` (SURVEY.md §7 hard part (a): no widening
64-bit multiply on the accelerator), adapted to a *generic* modulus:

- **33 little-endian limbs of 12 bits in int32** (396 bits of headroom over
  the 381-bit p). Limbs are signed; subtraction is limb-wise.
- 2^255-19 folds its top limb with a scalar (19·2^9); an arbitrary p
  cannot. Instead high product columns fold through a precomputed
  **fold matrix**: row j holds the 32 strict limbs of 2^(12(j+32)) mod p,
  so folding is one small integer matmul — still static-shape, gather-free.
- "reduced" invariant (accepted/produced by every public op): |limb| <
  2^12 + 2^7 across all 33 limbs. Schoolbook columns then stay below
  33 * (2^12.07)^2 < 2^29.3 — comfortably inside int32.
- carry propagation is parallel (all limbs at once, constant steps); the
  carry out of limb 32 (weight 2^396) folds via the matrix row for
  2^396 mod p. Exact sequential passes appear only in :func:`canonical`.

Everything is shape-polymorphic over leading batch dims and jit/vmap safe.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

LIMB_BITS = 12
LIMBS = 33  # 33 * 12 = 396 >= 381
LIMB_MASK = (1 << LIMB_BITS) - 1
P_INT = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB

_NCOLS = 67  # 65 product columns (0..64) + 2 spill columns for carries


def _strict_limbs(x: int, n: int) -> np.ndarray:
    out = np.zeros(n, dtype=np.int32)
    for i in range(n):
        out[i] = x & LIMB_MASK
        x >>= LIMB_BITS
    if x:
        raise ValueError("value does not fit")
    return out


def to_limbs(x: int) -> np.ndarray:
    """Host helper: python int in [0, 2^396) -> int32[33]."""
    if not 0 <= x < 2**396:
        raise ValueError("out of limb range")
    return _strict_limbs(x, LIMBS)


def to_limbs_bulk(vals) -> np.ndarray:
    """Host helper: sequence of ints in [0, 2^396) -> int32[n, 33].
    Vectorized via byte unpacking — the per-int :func:`to_limbs` loop is
    the marshalling bottleneck at multi-pairing sizes (257 pairs x 12
    coefficients x 68 schedule slots)."""
    n = len(vals)
    if n == 0:
        return np.zeros((0, LIMBS), dtype=np.int32)
    raw = np.frombuffer(
        b"".join(int(v).to_bytes(50, "little") for v in vals), dtype=np.uint8
    ).reshape(n, 50)
    bits = np.unpackbits(raw, axis=1, bitorder="little")[:, : LIMBS * LIMB_BITS]
    weights = (1 << np.arange(LIMB_BITS, dtype=np.int32)).astype(np.int32)
    return (
        bits.reshape(n, LIMBS, LIMB_BITS).astype(np.int32) * weights
    ).sum(axis=-1, dtype=np.int32)


def from_limbs(limbs) -> int:
    """Host helper: limb vector -> python int (signed limbs allowed)."""
    arr = np.asarray(limbs, dtype=np.int64)
    val = 0
    for i in reversed(range(arr.shape[-1])):
        val = (val << LIMB_BITS) + int(arr[..., i])
    return val


# Fold matrix: FOLD[j] = strict 32-limb decomposition of 2^(12(j+32)) mod p,
# for j = 0 .. (_NCOLS - 32 - 1). Row 1 (= 2^396 mod p) doubles as the
# top-limb fold inside the parallel carry step.
FOLD = np.stack(
    [
        _strict_limbs(pow(2, LIMB_BITS * (j + 32), P_INT), 32)
        for j in range(_NCOLS - 32)
    ]
).astype(np.int32)
_FOLD_TOP = np.zeros(LIMBS, dtype=np.int32)
_FOLD_TOP[:32] = FOLD[1]

ZERO = np.zeros(LIMBS, dtype=np.int32)
ONE = to_limbs(1)

# p * 2^15 > any reduced-magnitude value (|value| < 2^12.1 * 2^384 <
# 2^396.1 < p * 2^15 ~ 2^396.7), held as 32 strict limbs + a wide top limb.
_BIG = P_INT << 15
_BIG_P = np.zeros(LIMBS, dtype=np.int32)
for _i in range(32):
    _BIG_P[_i] = (_BIG >> (LIMB_BITS * _i)) & LIMB_MASK
_BIG_P[32] = _BIG >> (LIMB_BITS * 32)  # < 2^13

# k*p in strict limbs for the canonical conditional subtractions
_KP = {k: to_limbs(k * P_INT) for k in (1, 2, 4, 8)}


# --- carry propagation -----------------------------------------------------


def _carry_step(x: jax.Array) -> jax.Array:
    """One parallel carry step; the carry out of limb 32 (weight 2^396)
    folds back through 2^396 mod p."""
    c = x >> LIMB_BITS
    low = x & LIMB_MASK
    shifted = jnp.concatenate(
        [jnp.zeros_like(c[..., :1]), c[..., :-1]], axis=-1
    )
    return low + shifted + c[..., -1:] * jnp.asarray(_FOLD_TOP)


def carry(x: jax.Array, steps: int = 2) -> jax.Array:
    for _ in range(steps):
        x = _carry_step(x)
    return x


# --- ring ops --------------------------------------------------------------


def add(a: jax.Array, b: jax.Array) -> jax.Array:
    return carry(a + b, steps=2)


def sub(a: jax.Array, b: jax.Array) -> jax.Array:
    return carry(a - b, steps=2)


def neg(a: jax.Array) -> jax.Array:
    return carry(-a, steps=2)


def _columns(a: jax.Array, b: jax.Array) -> jax.Array:
    """Schoolbook product columns c[k] = sum_{i+j=k} a_i b_j -> [..., 67]
    via the pad/reshape anti-diagonal trick (static shapes, no gathers)."""
    outer = a[..., :, None] * b[..., None, :]  # [..., 33, 33], |.| < 2^24.2
    padded = jnp.pad(
        outer, [(0, 0)] * (outer.ndim - 2) + [(0, 0), (0, _NCOLS + 1 - LIMBS)]
    )
    flat = padded.reshape(*outer.shape[:-2], LIMBS * (_NCOLS + 1))
    flat = flat[..., : LIMBS * _NCOLS]
    return flat.reshape(*outer.shape[:-2], LIMBS, _NCOLS).sum(axis=-2)


def mul(a: jax.Array, b: jax.Array) -> jax.Array:
    """a * b (mod p), reduced. Inputs must be reduced."""
    c = _columns(a, b)  # |col| < 33 * 2^24.2 < 2^29.3
    # Normalize columns before folding (fold rows are 12-bit, so columns
    # must be ~12-bit first). Carries spill into columns 65/66, which start
    # at zero; nothing falls off the end.
    for _ in range(2):
        cc = c >> LIMB_BITS
        c = (c & LIMB_MASK) + jnp.concatenate(
            [jnp.zeros_like(cc[..., :1]), cc[..., :-1]], axis=-1
        )
    lo = c[..., :32]
    hi = c[..., 32:_NCOLS]  # 35 columns, weights 2^(12(j+32))
    # fold: lo += hi @ FOLD — 35 products of ~2^12 * 2^12 per output limb,
    # |acc| < 2^12 + 35 * 2^24.2 < 2^29.4
    folded = lo + jnp.sum(
        hi[..., :, None] * jnp.asarray(FOLD), axis=-2
    )
    out = jnp.concatenate(
        [folded, jnp.zeros_like(folded[..., :1])], axis=-1
    )  # limb 32 = 0
    return carry(out, steps=3)


def square(a: jax.Array) -> jax.Array:
    return mul(a, a)


def mul_small(a: jax.Array, k: int) -> jax.Array:
    """a * k for python int 0 <= k < 2^12."""
    return carry(a * jnp.int32(k), steps=3)


def select(cond: jax.Array, a: jax.Array, b: jax.Array) -> jax.Array:
    """cond ? a : b, limb-wise; cond is bool[...] broadcast over limbs."""
    return jnp.where(cond[..., None], a, b)


# --- canonicalization / predicates ----------------------------------------


def _seq_pass(x: jax.Array) -> jax.Array:
    """Exact sequential carry pass; limbs 0..31 end strict in [0, 2^12),
    the top limb absorbs the tail and the 2^396 overflow folds back."""
    carry_in = jnp.zeros_like(x[..., 0])
    limbs = []
    for i in range(LIMBS):
        v = x[..., i] + carry_in
        limbs.append(v & LIMB_MASK)
        carry_in = v >> LIMB_BITS
    out = jnp.stack(limbs, axis=-1)
    return out + carry_in[..., None] * jnp.asarray(_FOLD_TOP)


def _cond_sub(x: jax.Array, kp: np.ndarray) -> jax.Array:
    """x - kp if that is non-negative else x (inputs strict-limbed)."""
    d = x - jnp.asarray(kp)
    carry_in = jnp.zeros_like(d[..., 0])
    limbs = []
    for i in range(LIMBS):
        v = d[..., i] + carry_in
        limbs.append(v & LIMB_MASK)
        carry_in = v >> LIMB_BITS
    sub_ok = carry_in >= 0  # no net borrow out the top
    d_strict = jnp.stack(limbs, axis=-1)
    return select(sub_ok, d_strict, x)


def canonical(x: jax.Array) -> jax.Array:
    """Unique representative in [0, p), limbs strictly in [0, 2^12)."""
    # force positive, then normalize exactly
    x = x + jnp.asarray(_BIG_P)
    for _ in range(3):
        x = _seq_pass(x)
    # Fold the strict top limb (weight 2^384) down repeatedly. Each round
    # shrinks the above-2^384 excess by ~2^-3.5 (2^384 mod p ~ 0.85 p ~
    # 2^380.5): top < 2^12 -> 2^8.5 -> 2^5 -> 2^1.5 -> value < 1.4 * 2^384.
    for _ in range(4):
        top = x[..., 32]
        x = jnp.concatenate(
            [
                x[..., :32] + top[..., None] * jnp.asarray(FOLD[0]),
                jnp.zeros_like(x[..., 32:]),
            ],
            axis=-1,
        )
        x = _seq_pass(x)
    # now value < 1.4 * 2^384 < 15p: binary conditional subtraction
    for k in (8, 4, 2, 1):
        x = _cond_sub(x, _KP[k])
    return x


def is_zero(x: jax.Array) -> jax.Array:
    return jnp.all(canonical(x) == 0, axis=-1)


def eq(a: jax.Array, b: jax.Array) -> jax.Array:
    return is_zero(sub(a, b))
