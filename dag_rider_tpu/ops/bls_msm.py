"""BLS12-381 G1 multi-scalar multiplication on the accelerator.

The device half of threshold-share aggregation
(:func:`dag_rider_tpu.crypto.threshold.aggregate`): the combination
sigma = sum_i lambda_i * sigma_i is a G1 MSM — the TPU-acceleration target
BASELINE.json names for the n=256/1024 rungs (configs #4-5) and the
riskiest item of the build plan (SURVEY.md §7). The pairing checks stay
host-side (:mod:`dag_rider_tpu.crypto.bls12381`), exactly as ordering
decisions do.

Design, TPU-first rather than a CPU-algorithm port:

- Field: :mod:`dag_rider_tpu.ops.field381` (signed 12-bit int32 limbs,
  fold-matrix reduction — no widening multiply needed).
- Group law: the **Renes-Costello-Batina complete addition formulas**
  (eprint 2015/1060, Algorithm 7 specialized to a = 0, b3 = 3*4 = 12) in
  homogeneous projective coordinates. Complete means *no* exceptional
  cases: P == Q, P == -Q, and the identity (0:1:0) all flow through the
  same 12M straight-line program — zero data-dependent control flow, no
  device-side equality tests or inversions, which is exactly what XLA
  wants. A Jacobian ladder with branch selects would cost less raw M but
  serializes on canonical() equality checks; completeness is the right
  trade on this hardware.
- MSM shape: per-point 4-bit windowed scalar multiplication (radix-16
  table of 0..15 multiples, 63 windows for the 255-bit scalar group order,
  4 doublings + 1 table add per window) vmapped over the points, then a
  pairwise tree reduction over the point axis. Pippenger bucket
  accumulation needs data-dependent scatters — hostile to the compiler;
  batched windows + tree sum keep every step dense and fused.

Scalars are taken mod r (the G1 group order) on the host; points arrive as
host affine tuples (already decompressed/validated by
``bls12381.g1_decompress``) and return as one host affine tuple.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from dag_rider_tpu.ops import field381 as F

R_INT = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001
P_INT = F.P_INT
WINDOWS = 64  # 256-bit scalar capacity in 4-bit windows (r is 255 bits)

Point = Tuple[jax.Array, jax.Array, jax.Array]  # homogeneous (X, Y, Z)


def identity(shape=()) -> Point:
    """The group identity (0 : 1 : 0)."""
    zero = jnp.broadcast_to(jnp.asarray(F.ZERO), (*shape, F.LIMBS))
    one = jnp.broadcast_to(jnp.asarray(F.ONE), (*shape, F.LIMBS))
    return (zero, one, zero)


def padd(p: Point, q: Point) -> Point:
    """Complete addition, RCB15 Algorithm 7 (a = 0, b3 = 12): 12M + 2m."""
    X1, Y1, Z1 = p
    X2, Y2, Z2 = q
    t0 = F.mul(X1, X2)
    t1 = F.mul(Y1, Y2)
    t2 = F.mul(Z1, Z2)
    t3 = F.mul(F.add(X1, Y1), F.add(X2, Y2))
    t3 = F.sub(t3, F.add(t0, t1))
    t4 = F.mul(F.add(Y1, Z1), F.add(Y2, Z2))
    t4 = F.sub(t4, F.add(t1, t2))
    x3 = F.mul(F.add(X1, Z1), F.add(X2, Z2))
    y3 = F.sub(x3, F.add(t0, t2))
    x3 = F.add(F.add(t0, t0), t0)  # 3 X1 X2
    t2 = F.mul_small(t2, 12)  # b3 Z1 Z2
    z3 = F.add(t1, t2)
    t1 = F.sub(t1, t2)
    y3 = F.mul_small(y3, 12)  # b3 (X1 Z2 + X2 Z1)
    X3 = F.sub(F.mul(t3, t1), F.mul(t4, y3))
    Y3 = F.add(F.mul(y3, x3), F.mul(t1, z3))
    Z3 = F.add(F.mul(z3, t4), F.mul(x3, t3))
    return (X3, Y3, Z3)


def pdouble(p: Point) -> Point:
    """Doubling via the complete formula (P + P is a valid input to it)."""
    return padd(p, p)


def pselect(cond: jax.Array, p: Point, q: Point) -> Point:
    return tuple(F.select(cond, a, b) for a, b in zip(p, q))


# ---------------------------------------------------------------------------
# Windowed scalar multiplication + tree-sum MSM
# ---------------------------------------------------------------------------


def _gather_entry(table: Tuple[jax.Array, ...], idx: jax.Array) -> Point:
    """table coords [..., 16, LIMBS]; idx int32[...] in [0, 16)."""
    out = []
    for coord in table:
        g = jnp.take_along_axis(
            coord, idx[..., None, None].astype(jnp.int32), axis=-2
        )
        out.append(g[..., 0, :])
    return tuple(out)


def _identity_like(p: Point) -> Point:
    """Identity (0 : 1 : 0) with ``p``'s shape, DERIVED from ``p``
    (0*X, 0*Y + 1, 0*Z) rather than broadcast from constants, so that
    under shard_map the scan/fori carries built from it inherit the batch
    axis's "varying" type from the inputs (shard_map rejects an unvarying
    carry that becomes varying after one body application)."""
    one = jnp.broadcast_to(jnp.asarray(F.ONE), p[1].shape)
    return (
        jnp.zeros_like(p[0]),
        jnp.zeros_like(p[1]) + one,
        jnp.zeros_like(p[2]),
    )


def _point_tables(p: Point) -> Tuple[jax.Array, ...]:
    """Radix-16 multiples [0..15]P per point: coords [..., 16, LIMBS].

    Built via scan — one padd body in the HLO instead of 14 inlined ones
    (compile-time win; identical values).
    """
    ident = _identity_like(p)

    def _entry(prev, _):
        nxt = padd(prev, p)
        return nxt, nxt

    _, steps = jax.lax.scan(_entry, ident, None, length=15)
    return tuple(
        jnp.moveaxis(
            jnp.concatenate([ident[c][None], steps[c]], axis=0), 0, -2
        )
        for c in range(3)
    )


def scalar_mul(nibbles: jax.Array, p: Point) -> Point:
    """[k]P — 4-bit fixed windows, MSB first, batched over leading dims.

    nibbles: int32[..., 64], little-endian. The window walk is a fori_loop
    so the HLO stays one window long regardless of scalar size. (The MSM
    path uses :func:`window_sums` instead — this per-point ladder remains
    for single-scalar consumers and differential tests.)
    """
    table = _point_tables(p)
    ident = _identity_like(p)

    def body(i, acc):
        acc = pdouble(pdouble(pdouble(pdouble(acc))))
        idx = jnp.take(nibbles, WINDOWS - 1 - i, axis=-1)
        return padd(acc, _gather_entry(table, idx))

    return jax.lax.fori_loop(0, WINDOWS, body, ident)


def window_sums(nibbles: jax.Array, p: Point, impl: str = "jnp") -> Point:
    """Per-window partial sums S_w = sum_i [d_{i,w}] P_i, coords [64, L].

    The TPU-shaped half of the MSM (round-4; same restructuring that took
    the Ed25519 comb from a sequential walk to a wide tree — PROFILE.md):
    radix-16 tables per point, ONE take_along_axis gathering every
    window's digit entry ([T, 64, L]), then a pairwise tree reduction
    over the point axis with full batch-level ILP. Work is
    15T (tables) + 64T (tree) complete additions versus the ladder's
    320T, with no 64-step dependent accumulator chain over the batch.

    impl: "jnp" (portable tree) or "pallas"/"pallas_interpret" — the
    tree's additions as single Mosaic launches with all intermediates in
    VMEM (ops/pallas_group381.py), bit-identical.
    """
    table = _point_tables(p)  # [T, 16, L] per coord
    ent = tuple(
        jnp.take_along_axis(c, nibbles[..., None], axis=-2) for c in table
    )  # [T, 64, L]
    if impl in ("pallas", "pallas_interpret"):
        from dag_rider_tpu.ops import pallas_group381 as PG381

        stacked = jnp.stack(ent, axis=-2)  # [T, 64, 3, L]
        stacked = jnp.moveaxis(stacked, 0, 1)  # [64, T, 3, L]
        acc = PG381.tree_sum_xyz381(
            stacked, interpret=impl == "pallas_interpret"
        )  # [64, 3, L]
        return tuple(acc[:, c] for c in range(3))
    acc = tree_reduce(ent)  # [1, 64, L]
    return tuple(c[0] for c in acc)


def horner_combine(wsums: Point) -> Point:
    """sum_w 16^w S_w from [64, L] window sums — 4 doublings + 1 add per
    window on a single point (negligible next to the batch tree)."""
    ident = _identity_like(tuple(c[0] for c in wsums))

    def body(i, acc):
        acc = pdouble(pdouble(pdouble(pdouble(acc))))
        w = tuple(jnp.take(c, WINDOWS - 1 - i, axis=0) for c in wsums)
        return padd(acc, w)

    return jax.lax.fori_loop(0, WINDOWS, body, ident)


def tree_reduce(acc: Point) -> Point:
    """Pairwise-fold a [t, ...] point batch to [1, ...] — any t >= 1
    (odd counts carry their last element into the next level)."""
    t = acc[0].shape[0]
    while t > 1:
        half = t // 2
        folded = padd(
            tuple(c[:half] for c in acc),
            tuple(c[half : 2 * half] for c in acc),
        )
        if t % 2:
            folded = tuple(
                jnp.concatenate([fc, c[2 * half :]], axis=0)
                for fc, c in zip(folded, acc)
            )
        acc = folded
        t = half + t % 2
    return acc


@functools.partial(jax.jit, static_argnames=("impl",))
def msm_kernel(
    nibbles: jax.Array,
    px: jax.Array,
    py: jax.Array,
    pz: jax.Array,
    impl: str = "jnp",
) -> Point:
    """sum_i [k_i] P_i for a padded batch of T points.

    nibbles: int32[T, 64]; px/py/pz: int32[T, 33]. Pad slots use scalar 0
    (maps to the identity). Returns one projective point (X, Y, Z) [33].
    """
    wsums = window_sums(nibbles, (px, py, pz), impl=impl)  # [64, 33] each
    return horner_combine(wsums)


def msm_impl(t: int) -> str:
    """Tree-impl selection, mirroring verifier.tpu._comb_impl: Mosaic
    kernels on a real TPU backend for lane-aligned batches, portable jnp
    everywhere else. DAGRIDER_MSM_PALLAS=0 (default 1) pins jnp — the
    kernels are bit-identical, this is purely a speed selection."""
    from dag_rider_tpu import config

    if not config.env_flag("DAGRIDER_MSM_PALLAS"):
        return "jnp"
    if t >= 128 and jax.default_backend() in ("tpu", "axon"):
        return "pallas"
    return "jnp"


# ---------------------------------------------------------------------------
# Host seam: threshold.aggregate(msm=...) plug
# ---------------------------------------------------------------------------


def _nibbles(k: int) -> np.ndarray:
    out = np.zeros(WINDOWS, dtype=np.int32)
    for i in range(WINDOWS):
        out[i] = (k >> (4 * i)) & 0xF
    return out


def _pad(n: int, base: int = 4) -> int:
    """Smallest base * 2^k >= max(n, base) — the padded batch size."""
    t = base
    while t < n:
        t *= 2
    return t


def pack_inputs(
    scalars: Sequence[int], points: Sequence[tuple], t: int
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Marshal host (scalar, affine point) pairs into padded kernel inputs.

    Pad slots (and None points) become the identity (0 : 1 : 0) with
    scalar 0; scalars are reduced mod r. Shared by the single-device
    :func:`msm` and the mesh-sharded ``parallel.msm.ShardedMSM`` so the
    crypto-sensitive marshalling lives exactly once.
    """
    if len(scalars) != len(points):
        raise ValueError("scalars/points length mismatch")
    nib = np.zeros((t, WINDOWS), dtype=np.int32)
    px = np.zeros((t, F.LIMBS), dtype=np.int32)
    py = np.zeros((t, F.LIMBS), dtype=np.int32)
    pz = np.zeros((t, F.LIMBS), dtype=np.int32)
    py[:] = F.ONE
    for i, (k, pt) in enumerate(zip(scalars, points)):
        if pt is None:
            continue  # identity contributes nothing regardless of scalar
        nib[i] = _nibbles(k % R_INT)
        px[i] = F.to_limbs(pt[0])
        py[i] = F.to_limbs(pt[1])
        pz[i] = F.ONE
    return nib, px, py, pz


def unpack_point(X, Y, Z) -> Optional[tuple]:
    """Projective limb point -> host affine (x, y) tuple (None: identity)."""
    xi = F.from_limbs(np.asarray(F.canonical(X)))
    yi = F.from_limbs(np.asarray(F.canonical(Y)))
    zi = F.from_limbs(np.asarray(F.canonical(Z)))
    if zi == 0:
        return None
    z_inv = pow(zi, P_INT - 2, P_INT)
    return (xi * z_inv % P_INT, yi * z_inv % P_INT)


def msm(scalars: Sequence[int], points: Sequence[tuple]) -> Optional[tuple]:
    """Device MSM over host affine points; the ``msm=`` backend of
    :func:`dag_rider_tpu.crypto.threshold.aggregate`.

    Args:
        scalars: python ints (reduced mod r here).
        points: affine (x, y) int tuples or None (identity), as produced by
            ``bls12381.g1_decompress``.

    Returns an affine (x, y) tuple, or None for the identity.
    """
    t = _pad(len(points))
    nib, px, py, pz = pack_inputs(scalars, points, t)
    X, Y, Z = msm_kernel(
        jnp.asarray(nib),
        jnp.asarray(px),
        jnp.asarray(py),
        jnp.asarray(pz),
        impl=msm_impl(t),
    )
    return unpack_point(X, Y, Z)


def sum_points(points: Sequence[tuple]) -> Optional[tuple]:
    """Plain G1 point sum as an all-ones MSM — the device half of
    certificate signature aggregation (ISSUE 9). Same input/output
    conventions as :func:`msm`."""
    return msm([1] * len(points), points)
