"""Edwards25519 group arithmetic on the limb field — device-side Ed25519.

Implements the group layer of the north-star TPU Verifier (BASELINE.json:
"vmap'd Ed25519 ... batch-verify ... one DAG round per device dispatch"):
point add/double in extended homogeneous coordinates, RFC 8032 §5.1.3
point decompression (square root via exponentiation — no data-dependent
control flow), fixed-base scalar multiplication of B from a precomputed
radix-16 comb table, and 4-bit-windowed variable-base scalar multiplication.

Everything is pure jnp over the signed-limb field of
:mod:`dag_rider_tpu.ops.field`, shape-polymorphic over leading batch dims,
jit-safe (static shapes, `fori_loop` for the window walks). The host oracle
(:mod:`dag_rider_tpu.crypto.ed25519`, RFC 8032 in python ints) uses the
*same* formulas, which is what makes CPU and TPU accept masks
byte-identical (SURVEY.md §7 hard part (b)).

A "point" is a tuple (X, Y, Z, T) of limb arrays [..., 22]; x = X/Z,
y = Y/Z, T = XY/Z (extended homogeneous coordinates, RFC 8032 §5.1.4).
"""

from __future__ import annotations

from typing import Tuple

import numpy as np
import jax
import jax.numpy as jnp

from dag_rider_tpu.ops import field as F

Point = Tuple[jax.Array, jax.Array, jax.Array, jax.Array]

WINDOWS = 64  # 256-bit scalars, 4-bit windows


def identity(shape=(), like: jax.Array | None = None) -> Point:
    """The neutral element (0, 1, 1, 0), broadcast to leading `shape`."""
    zero = jnp.broadcast_to(jnp.asarray(F.ZERO), (*shape, F.LIMBS))
    one = jnp.broadcast_to(jnp.asarray(F.ONE), (*shape, F.LIMBS))
    return (zero, one, one, zero)


def padd(p: Point, q: Point) -> Point:
    """Unified addition (add-2008-hwcd-3 for a=-1) — complete on the curve;
    identical formulas to the host oracle's ``point_add``
    (crypto/ed25519.py), so results agree bit-for-bit after canonical()."""
    X1, Y1, Z1, T1 = p
    X2, Y2, Z2, T2 = q
    a = F.mul(F.sub(Y1, X1), F.sub(Y2, X2))
    b = F.mul(F.add(Y1, X1), F.add(Y2, X2))
    c = F.mul(F.mul(T1, T2), jnp.asarray(F.D2))
    d = F.mul_small(F.mul(Z1, Z2), 2)
    e = F.sub(b, a)
    f = F.sub(d, c)
    g = F.add(d, c)
    h = F.add(b, a)
    return (F.mul(e, f), F.mul(g, h), F.mul(f, g), F.mul(e, h))


def pdouble(p: Point) -> Point:
    """Doubling (dbl-2008-hwcd), same formulas as host ``point_double``."""
    X1, Y1, Z1, _ = p
    a = F.square(X1)
    b = F.square(Y1)
    c = F.mul_small(F.square(Z1), 2)
    h = F.add(a, b)
    e = F.sub(h, F.square(F.add(X1, Y1)))
    g = F.sub(a, b)
    f = F.add(c, g)
    return (F.mul(e, f), F.mul(g, h), F.mul(f, g), F.mul(e, h))


def pselect(cond: jax.Array, p: Point, q: Point) -> Point:
    """cond ? p : q, element-wise over the batch."""
    return tuple(F.select(cond, a, b) for a, b in zip(p, q))


def pneg(p: Point) -> Point:
    X, Y, Z, T = p
    return (F.neg(X), Y, Z, F.neg(T))


def points_equal(p: Point, q: Point) -> jax.Array:
    """Projective equality: X1 Z2 == X2 Z1 and Y1 Z2 == Y2 Z1 (mod p) —
    the device twin of host ``point_equal``."""
    X1, Y1, Z1, _ = p
    X2, Y2, Z2, _ = q
    ex = F.is_zero(F.sub(F.mul(X1, Z2), F.mul(X2, Z1)))
    ey = F.is_zero(F.sub(F.mul(Y1, Z2), F.mul(Y2, Z1)))
    return ex & ey


# ---------------------------------------------------------------------------
# Decompression (RFC 8032 §5.1.3) — branch-free
# ---------------------------------------------------------------------------


def decompress(y: jax.Array, sign: jax.Array) -> Tuple[Point, jax.Array]:
    """Recover (x, y) from the y limbs + sign bit; returns (point, valid).

    Candidate square root of u/v computed as u v^3 (u v^7)^((p-5)/8)
    (RFC 8032's inversion-free form). Mirrors the host ``_recover_x``
    decision tree exactly, branch-free:

    - no root (v x^2 != ±u)            -> invalid
    - x == 0 with sign bit set         -> invalid (the host's
      ``return None if sign else 0`` arm)
    - parity(x) != sign                -> x := p - x

    The caller is responsible for the y < p canonicity check (done on the
    host from the raw bytes, where it is one integer compare). (The TPU
    fast path runs this whole routine inside the Pallas finish kernel —
    ops/pallas_group.py _finish_kernel — this jnp version is the
    portable twin and differential oracle.)
    """
    one = jnp.broadcast_to(jnp.asarray(F.ONE), y.shape)
    y2 = F.square(y)
    u = F.sub(y2, one)                      # y^2 - 1
    v = F.add(F.mul(y2, jnp.asarray(F.D)), one)  # d y^2 + 1
    v3 = F.mul(F.square(v), v)
    v7 = F.mul(F.square(v3), v)
    cand = F.mul(F.mul(u, v3), F.pow22523(F.mul(u, v7)))
    vxx = F.mul(v, F.square(cand))
    root1 = F.eq(vxx, u)
    root2 = F.eq(vxx, F.neg(u))
    x = F.select(root1, cand, F.mul(cand, jnp.asarray(F.SQRT_M1)))
    valid = root1 | root2
    x_zero = F.is_zero(x)
    valid = valid & ~(x_zero & (sign == 1))
    flip = F.parity(x) != sign
    x = F.select(flip, F.neg(x), x)
    z = jnp.broadcast_to(jnp.asarray(F.ONE), y.shape)
    return (x, y, z, F.mul(x, y)), valid


# ---------------------------------------------------------------------------
# Scalar multiplication
# ---------------------------------------------------------------------------


def _gather_point(table: Tuple[jax.Array, ...], idx: jax.Array) -> Point:
    """table: per-coord arrays [..., 16, 22]; idx: int32[...] in [0, 16)."""
    out = []
    for coord in table:
        g = jnp.take_along_axis(
            coord, idx[..., None, None].astype(jnp.int32), axis=-2
        )
        out.append(g[..., 0, :])
    return tuple(out)


def scalar_mul_var(nibbles: jax.Array, a: Point) -> Point:
    """[k]A for per-element points A — 4-bit fixed windows, MSB first.

    nibbles: int32[..., 64], little-endian (nibbles[..., 0] = k & 0xF).
    252 doublings + 63 adds + 14 table-build adds, all batched; the window
    walk is a fori_loop so the HLO stays one window long.
    """
    # Window table 0..15: T[d] = d * A. Built with a scan (one padd body
    # in the HLO instead of 14 inlined ones — round-2 VERDICT next #1c:
    # smaller program, faster compile; same values).
    ident = identity(nibbles.shape[:-1])

    def _entry(prev, _):
        nxt = padd(prev, a)
        return nxt, nxt

    _, steps = jax.lax.scan(_entry, ident, None, length=15)
    table = tuple(
        jnp.moveaxis(
            jnp.concatenate([ident[c][None], steps[c]], axis=0), 0, -2
        )
        for c in range(4)
    )

    def body(i, acc):
        acc = pdouble(pdouble(pdouble(pdouble(acc))))
        idx = jnp.take(nibbles, WINDOWS - 1 - i, axis=-1)
        return padd(acc, _gather_point(table, idx))

    return jax.lax.fori_loop(
        0, WINDOWS, body, identity(nibbles.shape[:-1])
    )


# Fixed-base comb table for B: TABLE[i][d] = d * 2^(4i) * B, affine
# (Z == 1), as numpy limb arrays [64, 16, 22] per coordinate (X, Y, T).
_B_TABLE: Tuple[np.ndarray, np.ndarray, np.ndarray] | None = None


def _build_b_table() -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    from dag_rider_tpu.crypto import ed25519 as host

    xs = np.zeros((WINDOWS, 16, F.LIMBS), dtype=np.int32)
    ys = np.zeros((WINDOWS, 16, F.LIMBS), dtype=np.int32)
    ts = np.zeros((WINDOWS, 16, F.LIMBS), dtype=np.int32)
    base = host.B
    for i in range(WINDOWS):
        acc = host.IDENTITY
        for d in range(16):
            X, Y, Z, _ = acc
            zi = pow(Z, F.P_INT - 2, F.P_INT)
            x = X * zi % F.P_INT
            y = Y * zi % F.P_INT
            xs[i, d] = F.to_limbs(x)
            ys[i, d] = F.to_limbs(y)
            ts[i, d] = F.to_limbs(x * y % F.P_INT)
            acc = host.point_add(acc, base)
        for _ in range(4):
            base = host.point_double(base)
    return xs, ys, ts


def b_table() -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Lazy host-side comb-table build (~1.2k host point ops, one-time)."""
    global _B_TABLE
    if _B_TABLE is None:
        _B_TABLE = _build_b_table()
    return _B_TABLE


def scalar_mul_base(nibbles: jax.Array) -> Point:
    """[s]B via the comb table: 64 adds, zero doublings.

    nibbles: int32[..., 64] little-endian. acc = sum_i TABLE[i][s_i].
    """
    xs, ys, ts = (jnp.asarray(t) for t in b_table())
    batch_shape = nibbles.shape[:-1]

    def body(i, acc):
        # per-window affine entry, gathered per batch element
        nib = jnp.take(nibbles, i, axis=-1).astype(jnp.int32)
        tab = tuple(
            jnp.take(coord[i], nib, axis=0)  # [16, 22] gathered -> [..., 22]
            for coord in (xs, ys, ts)
        )
        one = jnp.broadcast_to(jnp.asarray(F.ONE), (*batch_shape, F.LIMBS))
        entry = (tab[0], tab[1], one, tab[2])
        return padd(acc, entry)

    return jax.lax.fori_loop(0, WINDOWS, body, identity(batch_shape))


# ---------------------------------------------------------------------------
# The verify equation
# ---------------------------------------------------------------------------


def verify_core(
    s_nibbles: jax.Array,
    k_nibbles: jax.Array,
    a_point: Point,
    a_valid: jax.Array,
    r_y: jax.Array,
    r_sign: jax.Array,
    prevalid: jax.Array,
) -> jax.Array:
    """Batched non-cofactored Ed25519 check: [s]B == R + [k]A.

    Args are per-batch-element device arrays; hashing (k), scalar range
    checks (s < L) and byte parsing happen on the host (SURVEY.md §7:
    ordering decisions host-side, device returns only accept bits).

    Returns bool[...] accept mask — ANDed with `a_valid` (public key
    decompressed OK), R decompression validity, and `prevalid` (host-side
    structural checks).
    """
    r_point, r_valid = decompress(r_y, r_sign)
    lhs = scalar_mul_base(s_nibbles)
    ka = scalar_mul_var(k_nibbles, a_point)
    rhs = padd(r_point, ka)
    return points_equal(lhs, rhs) & a_valid & r_valid & prevalid
