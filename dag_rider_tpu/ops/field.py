"""GF(2^255 - 19) arithmetic in int32 limbs — the TPU field layer.

The north star (BASELINE.json) calls for "vmap'd Ed25519 ... batch-verify
... one DAG round per device dispatch". The reference has no crypto at all
(SURVEY.md D10); this module is the field underneath the device-side group
arithmetic in :mod:`dag_rider_tpu.ops.curve`.

Design (SURVEY.md §7 "hard parts (a)"): TPUs have no widening 64-bit
multiply, so field elements are **22 little-endian limbs of 12 bits held in
int32** (radix 2^12, 264 bits of headroom over the 255-bit field):

- limbs are *signed*: subtraction is plain limb-wise ``a - b`` with no
  added bias, and arithmetic shifts make carry steps sign-correct.
- "reduced" invariant (what every public op accepts and returns):
  ``|limb0| < 2^14`` and ``|limb_i| < 2^13`` for i >= 1. With 12-bit
  radix this keeps every schoolbook product column below
  2 * 2^27 + 20 * 2^26 < 2^31 — the whole multiply fits int32 with no
  widening multiply.
- carries propagate in *parallel* (all limbs shift simultaneously, a
  constant number of steps) — every step is a handful of elementwise ops
  on the whole [batch, limbs] array, instead of a 22-deep sequential
  chain. Exact sequential passes are used only inside
  :func:`canonical`, where strict uniqueness is required.
- multiplication is schoolbook via one outer product + a pad/reshape
  anti-diagonal sum (static shapes, no gathers), then the high columns
  fold through 2^255 == 19 (mod p).

Everything is shape-polymorphic over leading batch dims and jit/vmap safe;
no Python control flow depends on traced values.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

# --- representation parameters --------------------------------------------

LIMB_BITS = 12
LIMBS = 22  # 22 * 12 = 264 >= 255
LIMB_MASK = (1 << LIMB_BITS) - 1
P_INT = 2**255 - 19

# 2^255 == 19 (mod p). Limb 21 spans bits 252..263, so one unit of the
# virtual "limb 22" (weight 2^264 = 2^255 * 2^9) folds to 19 * 2^9 at limb 0.
TOP_FOLD = 19 << 9  # 9728


def to_limbs(x: int) -> np.ndarray:
    """Host helper: python int in [0, 2^264) -> limb vector (int32[22])."""
    if not 0 <= x < 2**264:
        raise ValueError("out of limb range")
    out = np.zeros(LIMBS, dtype=np.int32)
    for i in range(LIMBS):
        out[i] = x & LIMB_MASK
        x >>= LIMB_BITS
    return out


def from_limbs(limbs) -> int:
    """Host helper: limb vector -> python int (signed limbs allowed)."""
    arr = np.asarray(limbs, dtype=np.int64)
    val = 0
    for i in reversed(range(arr.shape[-1])):
        val = (val << LIMB_BITS) + int(arr[..., i])
    return val


def bytes_to_limbs(data: bytes) -> np.ndarray:
    """32 little-endian bytes -> limb vector. Values >= p are representable;
    callers needing canonicity check it explicitly (RFC 8032 decoding)."""
    return to_limbs(int.from_bytes(data, "little"))


# Module constants in limb form (captured as jnp constants under jit).
P_LIMBS = to_limbs(P_INT)
# 2^14 * p: a multiple of p, every limb scaled by 2^14 (values < 2^26).
# Added inside canonical() to force any reduced (possibly negative) value
# positive before exact normalization: |reduced value| < 2^13 * 2^253 <
# 2^266 < 2^14 * p.
BIG_P = (P_LIMBS.astype(np.int64) << 14).astype(np.int32)

D_INT = (-121665 * pow(121666, P_INT - 2, P_INT)) % P_INT
D2_INT = (2 * D_INT) % P_INT
SQRT_M1_INT = pow(2, (P_INT - 1) // 4, P_INT)

ZERO = np.zeros(LIMBS, dtype=np.int32)
ONE = to_limbs(1)
D = to_limbs(D_INT)
D2 = to_limbs(D2_INT)
SQRT_M1 = to_limbs(SQRT_M1_INT)


# --- carry propagation -----------------------------------------------------


def _carry_step(x: jax.Array) -> jax.Array:
    """One parallel carry step with the 2^255 == 19 fold at the top limb.

    Arithmetic shift + mask decompose v = (v >> 12) * 4096 + (v & 0xFFF)
    exactly for signed v, so negative limbs carry correctly.
    """
    c = x >> LIMB_BITS
    low = x & LIMB_MASK
    shifted = jnp.concatenate([c[..., -1:] * TOP_FOLD, c[..., :-1]], axis=-1)
    return low + shifted


def carry(x: jax.Array, steps: int = 2) -> jax.Array:
    """Propagate carries back to the reduced invariant.

    Two steps suffice for |limbs| < 2^15 (add/sub results); three for
    |limbs| < 2^26 (scaled values). The result satisfies |limb0| < 2^14
    (it absorbs the top fold, which is < 9728 + 4096) and
    |limb_i| < 2^13 elsewhere.
    """
    for _ in range(steps):
        x = _carry_step(x)
    return x


# --- ring ops --------------------------------------------------------------


def add(a: jax.Array, b: jax.Array) -> jax.Array:
    """a + b (mod p), reduced."""
    return carry(a + b, steps=2)


def sub(a: jax.Array, b: jax.Array) -> jax.Array:
    """a - b (mod p), reduced. Signed limbs: no bias needed."""
    return carry(a - b, steps=2)


def neg(a: jax.Array) -> jax.Array:
    return carry(-a, steps=2)


_NCOLS = 46  # 43 product columns + headroom so no carry is ever dropped


def _columns(a: jax.Array, b: jax.Array) -> jax.Array:
    """Schoolbook product columns c[k] = sum_{i+j=k} a_i b_j -> [..., 46].

    Shift-accumulate: 22 statically-sliced multiply-adds into one
    [..., 46] accumulator. Ties the outer-product + pad/reshape
    anti-diagonal formulation in on-chip speed but peaks at 2x the input
    footprint instead of 22x (the [..., 22, 46] intermediate made wide
    batched ops HBM-traffic-bound and OOM'd the 8k-sig merged dispatch —
    PROFILE.md round 3). Static shapes; no gathers.
    """
    batch = jnp.broadcast_shapes(a.shape[:-1], b.shape[:-1])
    a = jnp.broadcast_to(a, (*batch, LIMBS))
    b = jnp.broadcast_to(b, (*batch, LIMBS))
    c = jnp.zeros((*batch, _NCOLS), dtype=a.dtype)
    for i in range(LIMBS):
        c = c.at[..., i : i + LIMBS].add(a[..., i : i + 1] * b)
    return c


def mul(a: jax.Array, b: jax.Array) -> jax.Array:
    """a * b (mod p), reduced. Inputs must be reduced."""
    c = _columns(a, b)  # 46 columns, |col| < 2^31, cols 44+ start at 0
    # Normalize columns before folding (the fold multiplies by 19 * 2^9 so
    # columns must be small first). Two parallel steps bring |col| below
    # 2^12.1; carries spill into columns 44/45 and none fall off the end
    # (col 45 stays < 4, its own carry is 0).
    for _ in range(2):
        cc = c >> LIMB_BITS
        c = (c & LIMB_MASK) + jnp.concatenate(
            [jnp.zeros_like(cc[..., :1]), cc[..., :-1]], axis=-1
        )
    lo = c[..., :LIMBS]
    hi = c[..., LIMBS : LIMBS + LIMBS]  # cols 22..43: weight 19 * 2^(12j+9)
    t = hi * 19  # |t| < 2^17
    # t * 2^9 split across two limbs: low 3 bits of t stay at offset 9,
    # the rest moves one limb up.
    lo = lo + ((t & 0x7) << 9)
    up = t >> 3
    lo = lo + jnp.concatenate(
        [jnp.zeros_like(up[..., :1]), up[..., :-1]], axis=-1
    )
    # up[21] lands at limb 22 (weight 2^264 == 19 * 2^9): fold once more.
    t2 = up[..., -1] * 19  # |t2| < 2^18
    lo = lo.at[..., 0].add((t2 & 0x7) << 9)
    lo = lo.at[..., 1].add(t2 >> 3)
    # cols 44/45: weights 2^528 == 361 * 2^18 and 2^540 == 361 * 2^30
    # (mod p), both exactly 2^6 * 361 = 23104 times a limb weight.
    lo = lo.at[..., 1].add(c[..., 44] * 23104)
    lo = lo.at[..., 2].add(c[..., 45] * 23104)
    return carry(lo, steps=3)


def square(a: jax.Array) -> jax.Array:
    return mul(a, a)


def nsquare(a: jax.Array, n: int) -> jax.Array:
    """a^(2^n) via fori_loop (keeps the HLO small for long chains)."""
    if n <= 4:
        for _ in range(n):
            a = square(a)
        return a
    return jax.lax.fori_loop(0, n, lambda _, x: square(x), a)


def mul_small(a: jax.Array, k: int) -> jax.Array:
    """a * k for python int 0 <= k < 2^12."""
    return carry(a * jnp.int32(k), steps=3)


# --- exponentiation chains (ref10-structure, public algorithm) -------------


def pow22523(z: jax.Array) -> jax.Array:
    """z^(2^252 - 3) (mod p) — the exponent of RFC 8032 §5.1.3 square-root
    decompression: sqrt candidate x = u v^3 (u v^7)^(2^252 - 3)."""
    t0 = square(z)                     # 2
    t1 = mul(z, nsquare(t0, 2))        # 9
    t0 = mul(t0, t1)                   # 11
    t0 = mul(t1, square(t0))           # 31 = 2^5 - 1
    t0 = mul(nsquare(t0, 5), t0)       # 2^10 - 1
    t1 = mul(nsquare(t0, 10), t0)      # 2^20 - 1
    t2 = mul(nsquare(t1, 20), t1)      # 2^40 - 1
    t1 = mul(nsquare(t2, 10), t0)      # 2^50 - 1
    t2 = mul(nsquare(t1, 50), t1)      # 2^100 - 1
    t3 = mul(nsquare(t2, 100), t2)     # 2^200 - 1
    t1 = mul(nsquare(t3, 50), t1)      # 2^250 - 1
    return mul(nsquare(t1, 2), z)      # 2^252 - 3


def invert(z: jax.Array) -> jax.Array:
    """z^(p-2) = z^(2^255 - 21) (mod p); maps 0 -> 0."""
    t0 = square(z)                     # 2
    t1 = mul(z, nsquare(t0, 2))        # 9
    t0m = mul(t0, t1)                  # 11
    t1 = mul(t1, square(t0m))          # 31 = 2^5 - 1
    t1 = mul(nsquare(t1, 5), t1)       # 2^10 - 1
    t2 = mul(nsquare(t1, 10), t1)      # 2^20 - 1
    t3 = mul(nsquare(t2, 20), t2)      # 2^40 - 1
    t2 = mul(nsquare(t3, 10), t1)      # 2^50 - 1
    t3 = mul(nsquare(t2, 50), t2)      # 2^100 - 1
    t4 = mul(nsquare(t3, 100), t3)     # 2^200 - 1
    t2 = mul(nsquare(t4, 50), t2)      # 2^250 - 1
    return mul(nsquare(t2, 5), t0m)    # 2^255 - 32 + 11 = 2^255 - 21


# --- canonicalization / predicates ----------------------------------------


def _seq_carry_fold(x: jax.Array) -> jax.Array:
    """Exact sequential carry pass (22 steps) + fold of all bits >= 255.

    Unlike the parallel :func:`carry`, this cannot leave a ripple (a chain
    of 0xFFF limbs propagating one place per step), so a few passes give
    strictly normalized limbs — required before value comparison.
    """
    carry_in = jnp.zeros_like(x[..., 0])
    limbs = []
    for i in range(LIMBS):
        v = x[..., i] + carry_in
        limbs.append(v & LIMB_MASK)
        carry_in = v >> LIMB_BITS
    out = jnp.stack(limbs, axis=-1)
    out = out.at[..., 0].add(carry_in * TOP_FOLD)
    hi = out[..., LIMBS - 1] >> 3  # bits 255..263, weight 2^255 == 19
    out = out.at[..., LIMBS - 1].set(out[..., LIMBS - 1] & 0x7)
    out = out.at[..., 0].add(hi * 19)
    return out


def canonical(x: jax.Array) -> jax.Array:
    """Unique representative in [0, p), limbs strictly in [0, 2^12).

    BIG_P (= 2^14 * p > any reduced magnitude) forces the value positive;
    three exact passes normalize to value < 2^255 with strict limbs; then
    x >= p is decided by whether x + 19 reaches bit 255 (for x in
    [0, 2^255): x >= p  <=>  x + 19 >= 2^255, and
    x - p == (x + 19) - 2^255).
    """
    x = x + jnp.asarray(BIG_P)
    for _ in range(3):
        x = _seq_carry_fold(x)
    t = x.at[..., 0].add(19)
    carry_in = jnp.zeros_like(t[..., 0])
    limbs = []
    for i in range(LIMBS):
        v = t[..., i] + carry_in
        limbs.append(v & LIMB_MASK)
        carry_in = v >> LIMB_BITS
    t = jnp.stack(limbs, axis=-1)
    ge_p = (t[..., LIMBS - 1] >> 3) > 0  # bit 255 set => x >= p
    t = t.at[..., LIMBS - 1].set(t[..., LIMBS - 1] & 0x7)  # == x - p
    return jnp.where(ge_p[..., None], t, x)


def is_zero(x: jax.Array) -> jax.Array:
    """x == 0 (mod p) -> bool[...]. Input must be reduced."""
    return jnp.all(canonical(x) == 0, axis=-1)


def eq(a: jax.Array, b: jax.Array) -> jax.Array:
    return is_zero(sub(a, b))


def parity(x: jax.Array) -> jax.Array:
    """Low bit of the canonical representative (RFC 8032 sign bit)."""
    return canonical(x)[..., 0] & 1


def select(cond: jax.Array, a: jax.Array, b: jax.Array) -> jax.Array:
    """cond ? a : b, limb-wise; cond is bool[...] broadcast over limbs."""
    return jnp.where(cond[..., None], a, b)
