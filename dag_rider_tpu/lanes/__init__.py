"""Sharded dissemination lanes (ISSUE 17): digest-only ordering.

Every DAG vertex used to carry its client block inline, so consensus
bandwidth — and the host pump's per-round cost — scaled with payload
weight. Lanes split the two concerns Narwhal-style (PAPERS: "Fides"):

- The producer's worker lane encodes the payload block, disseminates it
  over the dedicated lane channel (:mod:`dag_rider_tpu.transport.lanebus`
  in-process; blobbus-shaped for the item-1 cluster crossing), and
  collects 2f+1 signed availability acks into a batch availability
  certificate — the same BLS share-aggregation machinery round
  certificates use (:meth:`CertVerifier.aggregate`).
- Consensus proposes a constant-size :class:`LaneRef` carrier block in
  the payload's place; the vector pump and cert path order it unchanged.
- Delivery resolves the ref back to payload bytes through the lane
  store, with pull-based fetch-on-miss (the round-11 unicast sync
  pattern): a process that missed the batch asks a certified holder —
  2f+1 availability acks guarantee an honest one exists — before
  surfacing transactions.

Commit order and delivered bytes are provably identical to the inline
oracle: the ref is proposed in exactly the round the payload block
would have been (materialization is synchronous at proposal time —
dissemination overlaps the submit→propose gap, never delays it), block
content doesn't influence ordering (edges, coins, and tiebreaks are
content-independent), and resolution substitutes the exact bytes whose
sha256 the 2f+1 certificate pinned. Any lane failure — not enough
acks, a payload aliasing the carrier magic, an undersized block —
degrades that one block to the inline path (``ladder.lanes`` pins the
edge), so lanes can never cost liveness, only bandwidth.
"""

from __future__ import annotations

import dataclasses
import threading
from collections import OrderedDict
from typing import Dict, Optional, Tuple, Union

from dag_rider_tpu.config import Config
from dag_rider_tpu.core import codec
from dag_rider_tpu.core.types import Block, LaneRef, Vertex
from dag_rider_tpu.transport.lanebus import LaneEndpoint
from dag_rider_tpu.utils.slog import NOOP, EventLog

#: lane store capacity in batches — FIFO eviction (refs are not
#: round-keyed, so the round-floor GC the DAG books use doesn't apply);
#: an evicted batch is still recoverable from any other certified holder
_STORE_CAP = 16384


class LanePending:
    """An in-flight lane publish: the original payload block plus the
    dissemination task's results. Sits in ``Process.blocks_to_propose``
    until proposal time, when :meth:`LaneCoordinator.materialize` turns
    it into the certified carrier block (or the payload itself, on
    degrade). Exposes ``transactions`` so queue readers — checkpointing,
    the zero-loss audit, depth-based backpressure — see the payload
    exactly as they would an inline block."""

    __slots__ = ("block", "payload", "digest", "self_sig", "future", "error")

    def __init__(self, block: Block) -> None:
        self.block = block
        self.payload: Optional[bytes] = None
        self.digest: Optional[bytes] = None
        self.self_sig: bytes = b""
        self.future = None
        self.error: Optional[BaseException] = None

    @property
    def transactions(self) -> Tuple[bytes, ...]:
        return self.block.transactions


class LaneCoordinator:
    """One process's lane state: publish, store, resolve.

    Driver-thread methods (:meth:`begin_publish`, :meth:`materialize`,
    :meth:`resolve_vertex`, checkpointing) interleave with handler tasks
    running on the lane pool; the coordinator's books are guarded by one
    lock, and every counter a test asserts on is incremented on the
    driver thread so the numbers are deterministic.
    """

    def __init__(
        self,
        cfg: Config,
        index: int,
        endpoint: LaneEndpoint,
        *,
        cert_signer=None,
        cert_verifier=None,
        metrics=None,
        log: EventLog = NOOP,
    ) -> None:
        self.cfg = cfg
        self.index = index
        self.endpoint = endpoint
        self.cert_signer = cert_signer
        self.cert_verifier = cert_verifier
        self.metrics = metrics
        self.log = log
        self.quorum = cfg.quorum
        self.min_bytes = cfg.lane_batch_bytes
        self._lock = threading.Lock()
        #: digest -> encoded payload block (insertion-ordered for FIFO
        #: eviction)
        self._store: "OrderedDict[bytes, bytes]" = OrderedDict()
        #: digest -> {signer: ack signature} (producer-side collection)
        self._acks: Dict[bytes, Dict[int, bytes]] = {}
        self._seq = 0
        self._fetch_rr = 0
        # handler-side tallies (mirrored to metrics as gauges from the
        # driver thread — pool threads never touch the Metrics object)
        self._stored = 0
        self._served = 0
        self._rejected = 0
        self._evicted = 0
        endpoint.subscribe(self._on_message)

    # -- publish (producer side) --------------------------------------

    def begin_publish(
        self, block: Block
    ) -> Optional[LanePending]:
        """Start disseminating ``block`` on the lane pool; None when the
        block should ship inline instead (too small for a lane
        round-trip, or its payload aliases the carrier magic — refusing
        those keeps :func:`codec.lane_ref_of` unambiguous at delivery).
        """
        txs = block.transactions
        if not txs:
            return None
        size = 4 + sum(4 + len(tx) for tx in txs)  # exact encoded size
        if size < self.min_bytes:
            return None
        if any(tx.startswith(codec.LANE_MAGIC) for tx in txs):
            return None
        pending = LanePending(block)
        pending.future = self.endpoint.bus.submit(
            self._do_publish, pending
        )
        return pending

    def _do_publish(self, pending: LanePending) -> None:
        """Pool task: encode, hash, store locally, self-ack, broadcast.
        The per-batch payload hash runs here — n in-flight publishes
        spread their hashes across the lane workers."""
        payload = pending.block.encode()
        digest = self.endpoint.bus.digest_of(payload)
        pending.payload = payload
        pending.digest = digest
        self.endpoint.bus.seed_block(digest, pending.block)
        if self.cert_signer is not None:
            pending.self_sig = self.cert_signer.sign_availability(digest)
        self._store_batch(digest, payload)
        self._broadcast_batch(digest, payload)

    def _broadcast_batch(self, digest: bytes, payload: bytes) -> int:
        """The dissemination seam — Byzantine lane behaviors wrap this
        to withhold the batch from a victim subset. Delivery is inline
        (lanebus module docstring): by the time this returns, every
        reachable peer has stored the batch and acked."""
        return self.endpoint.broadcast("batch", (digest, payload))

    def materialize(
        self, entry: Union[Block, LanePending]
    ) -> Block:
        """Proposal-time exchange: a plain block passes through; a
        pending publish waits for its acks and yields the certified
        carrier block, or degrades to the original payload (the inline
        oracle) when fewer than 2f+1 processes attested availability."""
        if not isinstance(entry, LanePending):
            return entry
        try:
            # Work-steal the publish if the pool hasn't started it: under
            # a submit burst the driver's own publish can sit behind n-1
            # queued siblings, and FIFO queue delay — not publish work —
            # would dominate proposal latency. cancel() succeeding means
            # the pool never ran (and never will run) this task, so the
            # driver runs it here and pays only its OWN encode+hash.
            if entry.future.cancel():
                self._do_publish(entry)
            else:
                # the publish task delivers inline, so its completion
                # means every reachable peer's ack is already booked — no
                # bus-wide flush (which would serialize on every OTHER
                # in-flight publish and put their wall time on the
                # consensus path)
                entry.future.result()
        except Exception as e:  # noqa: BLE001 — degrade, never wedge
            entry.error = e
            return self._degrade(entry, f"publish failed: {e!r}")
        digest = entry.digest
        with self._lock:
            acks = self._acks.pop(digest, {})
        acks[self.index] = entry.self_sig
        valid = self._filter_acks(acks)
        if len(valid) < self.quorum:
            return self._degrade(
                entry, f"{len(valid)}/{self.quorum} availability acks"
            )
        signers = tuple(sorted(valid))[: self.quorum]
        agg = b""
        if self.cert_verifier is not None and self.cert_signer is not None:
            agg = self.cert_verifier.aggregate(
                [valid[s] for s in signers]
            ) or b""
        ref = LaneRef(
            producer=self.index,
            seq=self._seq,
            digest=digest,
            count=len(entry.block.transactions),
            nbytes=len(entry.payload),
            signers=signers,
            agg_sig=agg,
        )
        self._seq += 1
        if self.metrics is not None:
            self.metrics.inc("lane_batches_certified")
            self._sync_gauges()
        if self.log.enabled:
            self.log.event(
                "lane_certified",
                view=self.index,
                seq=ref.seq,
                nbytes=ref.nbytes,
                signers=len(signers),
            )
        return Block((codec.encode_lane_ref(ref),))

    def _degrade(self, entry: LanePending, why: str) -> Block:
        if self.metrics is not None:
            self.metrics.inc("lane_publish_degraded")
        if self.log.enabled:
            self.log.event("lane_degrade", view=self.index, detail=why)
        return entry.block

    def _filter_acks(
        self, acks: Dict[int, bytes]
    ) -> Dict[int, bytes]:
        """Keep structurally valid acks. Unsigned deployments (the
        keyless simulator) treat presence under the right digest as the
        ack; signed ones drop any share that fails G1 decompression —
        the cheap structural gate that keeps a garbage share from
        poisoning the aggregate."""
        if self.cert_signer is None:
            return dict(acks)
        from dag_rider_tpu.crypto import bls12381 as bls

        out = {}
        for signer, sig in acks.items():
            try:
                ok = bls.g1_decompress(sig) is not None
            except Exception:  # noqa: BLE001 — malformed share
                ok = False
            if ok:
                out[signer] = sig
        return out

    # -- lane channel handlers (pool threads) -------------------------

    def _on_message(self, sender: int, kind: str, value) -> None:
        if kind == "batch":
            self._on_batch(sender, value)
        elif kind == "ack":
            self._on_ack(sender, value)
        elif kind == "fetch":
            self._on_fetch(sender, value)

    def _on_batch(self, sender: int, value) -> None:
        claimed, body = value
        # memo hit for every receiver after the first — the bus hands
        # all n endpoints the same payload object (lanebus docstring)
        digest = self.endpoint.bus.digest_of(body)
        if digest != claimed or len(claimed) != 32:
            with self._lock:
                self._rejected += 1
            return
        self._store_batch(digest, body)
        if self.log.enabled:
            self.log.event(
                "lane_batch",
                view=self.index,
                sender=sender,
                nbytes=len(body),
            )
        self.endpoint.send(sender, "ack", self._make_ack(digest))

    def _make_ack(self, digest: bytes) -> Tuple[bytes, bytes]:
        """(echoed digest, signature) for one availability ack — the
        seam a garbage-ack Byzantine lane behavior wraps."""
        if self.cert_signer is None:
            return digest, b""
        return digest, self.cert_signer.sign_availability(digest)

    def _on_ack(self, sender: int, value) -> None:
        digest, sig = value
        with self._lock:
            self._acks.setdefault(digest, {})[sender] = sig

    def _on_fetch(self, sender: int, digest: bytes) -> None:
        with self._lock:
            body = self._store.get(digest)
            if body is not None:
                self._served += 1
        if body is not None:
            self.endpoint.send(sender, "batch", (digest, body))

    def _store_batch(self, digest: bytes, body: bytes) -> None:
        with self._lock:
            if digest not in self._store:
                self._store[digest] = body
                self._stored += 1
                while len(self._store) > _STORE_CAP:
                    self._store.popitem(last=False)
                    self._evicted += 1

    # -- resolve (delivery side) --------------------------------------

    def resolve_vertex(self, v: Vertex) -> Vertex:
        """Substitute a carrier block's payload before delivery. A
        non-carrier vertex passes through untouched, so the inline
        oracle path never pays anything here."""
        ref = codec.lane_ref_of(v.block)
        if ref is None:
            return v
        body = self._get_or_fetch(ref)
        block = self.endpoint.bus.block_of(ref.digest, body)
        return dataclasses.replace(v, block=block)

    def peek_block(self, block: Block) -> Optional[Block]:
        """Store-only resolve (no fetch) for audits over undelivered DAG
        state; None when the block is not a carrier or the batch is not
        held locally."""
        ref = codec.lane_ref_of(block)
        if ref is None:
            return None
        with self._lock:
            body = self._store.get(ref.digest)
        if body is None:
            return None
        return self.endpoint.bus.block_of(ref.digest, body)

    def _get_or_fetch(self, ref: LaneRef) -> bytes:
        with self._lock:
            body = self._store.get(ref.digest)
        if body is not None:
            return body
        # Miss: pull from a certified holder (round-11 unicast sync
        # pattern) — rotate through the ref's signers so one slow peer
        # doesn't absorb every fetch — then degrade to a broadcast ask.
        if self.metrics is not None:
            self.metrics.inc("lane_fetch_misses")
        if self.log.enabled:
            self.log.event(
                "lane_fetch",
                view=self.index,
                producer=ref.producer,
                seq=ref.seq,
            )
        holders = [s for s in ref.signers if s != self.index]
        if holders:
            start = self._fetch_rr % len(holders)
            holders = holders[start:] + holders[:start]
            self._fetch_rr += 1
        # sends are synchronous request/responses: a holder's serve has
        # landed in our store by the time send() returns
        for peer in holders:
            self.endpoint.send(peer, "fetch", ref.digest)
            with self._lock:
                body = self._store.get(ref.digest)
            if body is not None:
                return body
        self.endpoint.broadcast("fetch", ref.digest)
        with self._lock:
            body = self._store.get(ref.digest)
        if body is not None:
            return body
        raise RuntimeError(
            f"lane batch unrecoverable: producer {ref.producer} seq "
            f"{ref.seq} — no certified holder answered, yet 2f+1 "
            "attested availability"
        )

    # -- checkpoint / stats -------------------------------------------

    def checkpoint_state(self) -> dict:
        """Everything a restart needs: the batch store (availability
        the cluster counted this process for) and the publish sequence.
        No pending-fetch book exists to persist — fetches are
        synchronous within a delivery, never carried across steps; a
        pending *publish* persists as its payload block in
        ``blocks_to_propose`` and re-ships inline after restore."""
        with self._lock:
            batches = [
                [d.hex(), b.hex()] for d, b in self._store.items()
            ]
            return {"version": 1, "seq": self._seq, "batches": batches}

    def restore_state(self, state: Optional[dict]) -> None:
        """Inverse of :meth:`checkpoint_state`; None/empty (a pre-lanes
        checkpoint) restores an empty lane store. Batches are
        re-hashed on the way in — a corrupt manifest entry is dropped,
        not trusted (the digest IS the content's identity)."""
        import hashlib

        with self._lock:
            self._store.clear()
            self._acks.clear()
            self._seq = 0
        if not state:
            return
        with self._lock:
            self._seq = int(state.get("seq", 0))
        for d_hex, b_hex in state.get("batches", []):
            digest, body = bytes.fromhex(d_hex), bytes.fromhex(b_hex)
            if hashlib.sha256(body).digest() == digest:
                self._store_batch(digest, body)
        if self.log.enabled:
            self.log.event(
                "lane_restore",
                view=self.index,
                batches=len(state.get("batches", [])),
            )

    def _sync_gauges(self) -> None:
        with self._lock:
            stored, served = self._stored, self._served
            rejected, evicted = self._rejected, self._evicted
        c = self.metrics.counters
        c["lane_batches_stored"] = stored
        c["lane_fetch_served"] = served
        c["lane_acks_rejected"] = rejected
        c["lane_store_evicted"] = evicted

    def stats(self) -> dict:
        with self._lock:
            return {
                "seq": self._seq,
                "store": len(self._store),
                "stored": self._stored,
                "served": self._served,
                "rejected": self._rejected,
                "evicted": self._evicted,
            }
