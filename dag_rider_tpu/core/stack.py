"""Generic LIFO stack.

Counterpart of the reference's ``stack/stack.go`` (29 LoC): slice-backed,
generic, used by the commit rule to unwind the retroactive leader chain
oldest-first (reference ``process/process.go:84,341,412``).

Unlike the reference, ``pop`` on an empty stack raises a proper error
instead of panicking on a slice underflow (SURVEY.md D11,
``stack/stack.go:23-29``).
"""

from __future__ import annotations

from typing import Generic, Iterator, List, TypeVar

T = TypeVar("T")


class Stack(Generic[T]):
    """A simple LIFO stack over a Python list."""

    __slots__ = ("_items",)

    def __init__(self) -> None:
        self._items: List[T] = []

    def push(self, item: T) -> None:
        self._items.append(item)

    def pop(self) -> T:
        if not self._items:
            raise IndexError("pop from empty Stack")
        return self._items.pop()

    def peek(self) -> T:
        if not self._items:
            raise IndexError("peek of empty Stack")
        return self._items[-1]

    def is_empty(self) -> bool:
        return not self._items

    def __len__(self) -> int:
        return len(self._items)

    def __iter__(self) -> Iterator[T]:
        """Iterate in pop order (top first)."""
        return reversed(self._items)

    def __repr__(self) -> str:
        return f"Stack({self._items!r})"
