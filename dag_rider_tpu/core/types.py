"""Vertex / block data model.

TPU-native counterpart of the reference's data model
(``process/process.go:14-31``): a vertex is identified by ``(round, source)``,
carries a client block payload, strong edges to round-1 vertices and weak
edges to vertices in rounds < round-1.

Differences from the reference, by design:

- Sources are 0-based ints in [0, n).
- Vertices are immutable (frozen dataclasses) and carry an optional Ed25519
  signature + threshold-coin share — the reference has no authentication at
  all (SURVEY.md D10) and a stubbed coin (D9).
- A canonical byte encoding (``signing_bytes``) exists so vertices can be
  signed/verified and checkpointed; the reference has no serialization
  (SURVEY.md §5 "checkpoint/resume: absent").
"""

from __future__ import annotations

import dataclasses
import hashlib
import struct
from typing import NamedTuple, Optional, Tuple


class VertexID(NamedTuple):
    """Unique vertex identity: (round, source).

    Mirrors ``vertexID`` (reference ``process/process.go:19-24``). A correct
    process creates at most one vertex per round, so this pair is unique.
    Ordered lexicographically (round first) — this ordering is the
    deterministic tiebreak used by total-order delivery.

    A NamedTuple, not a frozen dataclass: ids are constructed and hashed
    millions of times per consensus run (proposal frontiers alone build
    n ids per proposal × n processes), and tuple __new__/__hash__ run in
    C — the frozen-dataclass version's __init__ + precomputed-hash dance
    was ~3 us per id and the single hottest allocation site of the
    n=256 host profile.

    Being a NamedTuple, a VertexID hashes and compares equal to the bare
    tuple ``(round, source)`` — INTENTIONAL (ADVICE r5 #4): hot paths
    may probe dicts/sets keyed by VertexID with plain tuples (skipping
    even the NamedTuple constructor) and membership answers must agree.
    Do not "fix" this by overriding __eq__/__hash__; code must not rely
    on the two being distinguishable.
    """

    round: int
    source: int

    def encode(self) -> bytes:
        return struct.pack("<II", self.round, self.source)


@dataclasses.dataclass(frozen=True)
class Block:
    """A client payload block (reference ``process/process.go:14-17``).

    The reference's block is an empty struct; ours carries real transaction
    bytes so end-to-end delivery is observable.
    """

    transactions: Tuple[bytes, ...] = ()

    def encode(self) -> bytes:
        out = [struct.pack("<I", len(self.transactions))]
        for tx in self.transactions:
            out.append(struct.pack("<I", len(tx)))
            out.append(tx)
        return b"".join(out)

    @staticmethod
    def decode(data: bytes, offset: int = 0) -> Tuple["Block", int]:
        (count,) = struct.unpack_from("<I", data, offset)
        offset += 4
        txs = []
        for _ in range(count):
            (ln,) = struct.unpack_from("<I", data, offset)
            offset += 4
            txs.append(data[offset : offset + ln])
            offset += ln
        return Block(tuple(txs)), offset


@dataclasses.dataclass(frozen=True)
class LaneRef:
    """A certified lane-batch reference (ISSUE 17).

    Stands in for a payload :class:`Block` on the consensus path when
    dissemination lanes are on: ``digest`` is the sha256 of the encoded
    payload block, ``signers`` the 2f+1 sources whose availability acks
    back the batch (sorted), and ``agg_sig`` the compressed G1 sum of
    their domain-separated BLS ack shares (empty in unsigned
    deployments — the keyless simulator). ``count``/``nbytes`` restate
    the payload shape so admission and accounting never need the bytes.

    The ref rides the existing wire unchanged, as the single
    magic-prefixed pseudo-transaction of a Block (see
    :func:`dag_rider_tpu.core.codec.encode_lane_ref`) — vertex identity,
    signing, and the cert path all see an ordinary small block.
    """

    producer: int
    seq: int
    digest: bytes
    count: int
    nbytes: int
    signers: Tuple[int, ...] = ()
    agg_sig: bytes = b""


@dataclasses.dataclass(frozen=True)
class EpochOp:
    """One reconfiguration request (ISSUE 20), ordered through consensus
    as the magic-prefixed pseudo-transaction of an ordinary block (see
    :func:`dag_rider_tpu.core.codec.encode_epoch_op`).

    ``kind`` is "join" | "leave" | "rotate"; ``target`` the node index
    joining or leaving (0 for a pure key rotation); ``nonce`` a
    submitter-chosen tag so identical requests stay distinguishable in
    the ordered log; ``payload`` carries opaque operator material (e.g.
    a joiner's identity seed), folded into the epoch seed derivation so
    rotated keys commit to it.
    """

    kind: str
    target: int = 0
    nonce: int = 0
    payload: bytes = b""


@dataclasses.dataclass(frozen=True)
class Vertex:
    """A DAG vertex (reference ``process/process.go:26-31``).

    strong_edges point to round-1 vertices (>= 2f+1 of them for a valid
    vertex); weak_edges point to otherwise-unreachable vertices in rounds
    < round-1, providing the fairness/inclusion guarantee (Alg. 2 lines
    29-31, quoted at reference ``process.go:300-302``).
    """

    id: VertexID
    block: Block = Block()
    strong_edges: Tuple[VertexID, ...] = ()
    weak_edges: Tuple[VertexID, ...] = ()
    signature: Optional[bytes] = None
    coin_share: Optional[bytes] = None
    #: BLS signature over digest() for the aggregated round-certificate
    #: path (ISSUE 9). Like ``signature``, an attestation OF the content
    #: — excluded from signing_bytes/digest (both enumerate fields
    #: explicitly), so attaching it never perturbs the vertex identity
    #: the per-vertex oracle path verifies.
    cert_sig: Optional[bytes] = None

    @property
    def round(self) -> int:
        return self.id.round

    @property
    def source(self) -> int:
        return self.id.source

    def signing_bytes(self) -> bytes:
        """Canonical encoding of everything a source attests to.

        Excludes the signature itself. Edges are sorted so the encoding is
        independent of construction order. Memoized: the encoding of an
        immutable vertex is hit once per verify *and* once per digest, and
        re-serializing ~2f+1 edges dominated the verifier's host prep at
        n=256 (round-2 VERDICT weak #3).
        """
        cached = self.__dict__.get("_signing_bytes")
        if cached is not None:
            return cached
        out = [b"dagrider-vertex-v1", self.id.encode(), self.block.encode()]
        for label, edges in ((b"S", self.strong_edges), (b"W", self.weak_edges)):
            out.append(label)
            out.append(struct.pack("<I", len(edges)))
            # VertexID is a NamedTuple: plain tuple comparison IS the
            # canonical (round, source) order, and it sorts in C
            for e in sorted(edges):
                out.append(e.encode())
        out.append(b"C")
        share = self.coin_share or b""
        out.append(struct.pack("<I", len(share)))
        out.append(share)
        enc = b"".join(out)
        object.__setattr__(self, "_signing_bytes", enc)
        return enc

    def digest(self) -> bytes:
        """SHA-512 digest of the canonical encoding (what gets signed).
        Memoized alongside :meth:`signing_bytes`."""
        cached = self.__dict__.get("_digest")
        if cached is not None:
            return cached
        d = hashlib.sha512(self.signing_bytes()).digest()
        object.__setattr__(self, "_digest", d)
        return d

    def edge_arrays(self):
        """Edges as four int32 numpy arrays
        ``(strong_rounds, strong_sources, weak_rounds, weak_sources)``.

        Memoized: admission gates and dense-mirror inserts check every
        edge of every vertex; per-edge attribute access over ~2f+1
        VertexIDs was the hottest slice of the 64-node host profile, and
        one fancy-index over these arrays replaces it."""
        cached = self.__dict__.get("_edge_arrays")
        if cached is not None:
            return cached
        import numpy as np

        # int64: wire rounds/sources are u32, which OVERFLOWS int32 —
        # a crafted vertex with round >= 2^31 must reach the admission
        # gate's range checks as a value, not as an OverflowError on the
        # network path (found by the snapshot corruption fuzz). The gate
        # bounds everything to [0, n) x [0, vr) before any index use.
        se, we = self.strong_edges, self.weak_edges
        arrs = (
            np.fromiter((e.round for e in se), np.int64, len(se)),
            np.fromiter((e.source for e in se), np.int64, len(se)),
            np.fromiter((e.round for e in we), np.int64, len(we)),
            np.fromiter((e.source for e in we), np.int64, len(we)),
        )
        object.__setattr__(self, "_edge_arrays", arrs)
        return arrs


@dataclasses.dataclass(frozen=True)
class RoundCertificate:
    """One aggregated attestation for a whole DAG round (ISSUE 9).

    Assembled by the round's designated aggregator once it has directly
    verified a quorum of the round's vertices: ``signers`` lists the
    source indices covered (sorted, >= 2f+1 of them), ``digests`` the
    matching vertex digests (parallel to ``signers``), and ``agg_sig``
    the compressed G1 sum of the per-vertex BLS ``cert_sig`` values.
    A receiver checks the whole round with ONE aggregate pairing —
    e(agg, -G2) * prod e(H(digest_i), pk_i) == 1 — instead of one
    ed25519 verify per vertex.
    """

    round: int
    signers: Tuple[int, ...]
    digests: Tuple[bytes, ...]
    agg_sig: bytes

    def signing_key(self) -> tuple:
        """Hashable identity of what the certificate claims — the memo
        key for sharing one verification verdict across an in-process
        cluster (the registry identity is added by the verifier)."""
        return (self.round, self.signers, self.digests, self.agg_sig)


@dataclasses.dataclass(frozen=True)
class SpanCertificate:
    """A cert-of-certs covering ``k`` consecutive round certificates
    (ISSUE 12 tentpole 3).

    ``signers[i]`` / ``digests[i]`` restate what the round
    ``first_round + i`` certificate claimed, and ``agg_sig`` is the
    compressed G1 sum of those rounds' certificate aggregates — so ONE
    combined multi-pairing proves every (digest, pk) pair across the
    span was signed, and a catch-up consumer pays 1/k of the per-round
    pairing count. Deliberately slim: no embedded per-round signatures
    (they would be unverified claims a receiver could only trust by
    re-doing the per-round work the span exists to avoid).

    Spans are an overlay on the certificate path, never a liveness
    dependency: round certificates keep flowing per-round, and a
    receiver that already settled a covered round just ignores the span
    for that round.
    """

    first_round: int
    signers: Tuple[Tuple[int, ...], ...]
    digests: Tuple[Tuple[bytes, ...], ...]
    agg_sig: bytes

    @property
    def last_round(self) -> int:
        return self.first_round + len(self.signers) - 1

    def signing_key(self) -> tuple:
        """Hashable identity of the span's combined claim — the memo key
        for the COMBINED verdict only (a passing span check does not
        imply each component round certificate is individually valid,
        so per-round verdicts are never derived from it)."""
        return ("span", self.first_round, self.signers, self.digests,
                self.agg_sig)


@dataclasses.dataclass(frozen=True)
class BroadcastMessage:
    """The unit the Transport carries (reference ``bcastMsg``,
    ``process/transport.go:11-18``): a vertex plus the round/sender stamps.

    The reference *trusts* these stamps (D10, ``process.go:159-162``); here
    they are cross-checked against the signed vertex id on receipt.

    ``kind`` extends the wire beyond the reference's single message type:
    "val" is a vertex payload (the only kind a Process consumes); "echo" /
    "ready" / "fetch" are the Bracha reliable-broadcast control messages of
    :mod:`dag_rider_tpu.transport.rbc`, which carry ``origin`` (the source
    index of the vertex being amplified) and ``digest`` instead of a
    payload.
    """

    vertex: Optional[Vertex]
    round: int
    sender: int
    kind: str = "val"
    origin: Optional[int] = None
    digest: Optional[bytes] = None
    #: aggregated round certificate, only for kind == "cert" (ISSUE 9)
    cert: Optional[RoundCertificate] = None
    #: cert-of-certs, only for kind == "cert_span" (ISSUE 12)
    span: Optional[SpanCertificate] = None
    #: reconfiguration epoch the sender was in (ISSUE 20). 0 is the
    #: genesis epoch and the only value static-membership deployments
    #: ever see; the codec emits the epoch wire section only when > 0,
    #: so pre-epoch bytes decode unchanged.
    epoch: int = 0
