from dag_rider_tpu.core.stack import Stack
from dag_rider_tpu.core.types import Block, BroadcastMessage, Vertex, VertexID

__all__ = ["Stack", "Block", "BroadcastMessage", "Vertex", "VertexID"]
