"""Wire / storage codec for vertices and broadcast messages.

The reference has no serialization at all — its Transport moves Go structs
through channels (``process/transport.go:11-18``) and nothing can cross a
process or persistence boundary (SURVEY.md §5 "checkpoint/resume: absent").
This codec is the single canonical byte format used by

- the networked Transport (gRPC/TCP), and
- the checkpoint format (utils/checkpoint.py),

so a checkpointed DAG and an on-the-wire vertex are the same bytes.

Layout (little-endian, length-prefixed): the signed portion reuses
``Vertex.signing_bytes()`` field order exactly, followed by the signature.
"""

from __future__ import annotations

import struct
from typing import List, Optional, Sequence, Tuple

from dag_rider_tpu.core.types import (
    Block,
    BroadcastMessage,
    EpochOp,
    LaneRef,
    RoundCertificate,
    SpanCertificate,
    Vertex,
    VertexID,
)

_MAGIC = b"DRv1"
#: v2 vertex: v1 plus a third optional blob (cert_sig). Emitted ONLY when
#: cert_sig is present, so every cert-off vertex — and every byte already
#: on disk in a checkpoint — stays exactly the DRv1 encoding.
_MAGIC_V2 = b"DRv2"


def encode_vertex(v: Vertex) -> bytes:
    v2 = v.cert_sig is not None
    out = [_MAGIC_V2 if v2 else _MAGIC, v.id.encode(), v.block.encode()]
    for edges in (v.strong_edges, v.weak_edges):
        out.append(struct.pack("<I", len(edges)))
        for e in sorted(edges):
            out.append(e.encode())
    blobs = (v.coin_share, v.signature, v.cert_sig) if v2 else (
        v.coin_share,
        v.signature,
    )
    for blob in blobs:
        if blob is None:
            out.append(struct.pack("<i", -1))
        else:
            out.append(struct.pack("<i", len(blob)))
            out.append(blob)
    return b"".join(out)


def decode_vertex(data: bytes, offset: int = 0) -> Tuple[Vertex, int]:
    magic = data[offset : offset + 4]
    if magic == _MAGIC:
        nblobs = 2
    elif magic == _MAGIC_V2:
        nblobs = 3
    else:
        raise ValueError("bad vertex magic")
    offset += 4
    rnd, source = struct.unpack_from("<II", data, offset)
    offset += 8
    block, offset = Block.decode(data, offset)
    edge_sets = []
    for _ in range(2):
        (count,) = struct.unpack_from("<I", data, offset)
        offset += 4
        edges = []
        for _ in range(count):
            er, es = struct.unpack_from("<II", data, offset)
            offset += 8
            edges.append(VertexID(er, es))
        edge_sets.append(tuple(edges))
    blobs = []
    for _ in range(nblobs):
        (ln,) = struct.unpack_from("<i", data, offset)
        offset += 4
        if ln < 0:
            blobs.append(None)
        else:
            blobs.append(data[offset : offset + ln])
            offset += ln
    v = Vertex(
        id=VertexID(rnd, source),
        block=block,
        strong_edges=edge_sets[0],
        weak_edges=edge_sets[1],
        coin_share=blobs[0],
        signature=blobs[1],
        cert_sig=blobs[2] if nblobs == 3 else None,
    )
    return v, offset


def encode_certificate(cert: RoundCertificate) -> bytes:
    """Certificate layout: round, signer count, signer u32s, the parallel
    digest blobs (u32 length-prefixed), then the aggregate signature."""
    out = [
        struct.pack("<II", cert.round, len(cert.signers)),
        struct.pack(f"<{len(cert.signers)}I", *cert.signers)
        if cert.signers
        else b"",
    ]
    for d in cert.digests:
        out.append(struct.pack("<I", len(d)))
        out.append(d)
    out.append(struct.pack("<I", len(cert.agg_sig)))
    out.append(cert.agg_sig)
    return b"".join(out)


def decode_certificate(
    data: bytes, offset: int = 0
) -> Tuple[RoundCertificate, int]:
    rnd, count = struct.unpack_from("<II", data, offset)
    offset += 8
    signers = struct.unpack_from(f"<{count}I", data, offset)
    offset += 4 * count
    digests = []
    for _ in range(count):
        (ln,) = struct.unpack_from("<I", data, offset)
        offset += 4
        digests.append(data[offset : offset + ln])
        offset += ln
    (ln,) = struct.unpack_from("<I", data, offset)
    offset += 4
    agg = data[offset : offset + ln]
    offset += ln
    return (
        RoundCertificate(
            round=rnd,
            signers=tuple(signers),
            digests=tuple(digests),
            agg_sig=agg,
        ),
        offset,
    )


def encode_span_certificate(span: SpanCertificate) -> bytes:
    """Span layout: first round, round count, then each round's signer
    count + signer u32s + parallel digest blobs, then the combined
    aggregate signature (ISSUE 12 tentpole 3)."""
    out = [struct.pack("<II", span.first_round, len(span.signers))]
    for signers, digests in zip(span.signers, span.digests):
        out.append(struct.pack("<I", len(signers)))
        if signers:
            out.append(struct.pack(f"<{len(signers)}I", *signers))
        for d in digests:
            out.append(struct.pack("<I", len(d)))
            out.append(d)
    out.append(struct.pack("<I", len(span.agg_sig)))
    out.append(span.agg_sig)
    return b"".join(out)


def decode_span_certificate(
    data: bytes, offset: int = 0
) -> Tuple[SpanCertificate, int]:
    first, k = struct.unpack_from("<II", data, offset)
    offset += 8
    all_signers = []
    all_digests = []
    for _ in range(k):
        (count,) = struct.unpack_from("<I", data, offset)
        offset += 4
        signers = struct.unpack_from(f"<{count}I", data, offset)
        offset += 4 * count
        digests = []
        for _ in range(count):
            (ln,) = struct.unpack_from("<I", data, offset)
            offset += 4
            digests.append(data[offset : offset + ln])
            offset += ln
        all_signers.append(tuple(signers))
        all_digests.append(tuple(digests))
    (ln,) = struct.unpack_from("<I", data, offset)
    offset += 4
    agg = data[offset : offset + ln]
    offset += ln
    return (
        SpanCertificate(
            first_round=first,
            signers=tuple(all_signers),
            digests=tuple(all_digests),
            agg_sig=agg,
        ),
        offset,
    )


_KINDS = (
    "val", "echo", "ready", "fetch", "sync", "sync_nack", "cert", "cert_span",
)


#: high bit of the kind byte flags a trailing u32 epoch section (ISSUE
#: 20). Epoch-0 messages — everything a static-membership deployment
#: ever sends, and every byte already on the wire or in a WAL — keep
#: their exact pre-epoch layout, same discipline as DRv2's conditional
#: cert_sig blob.
_EPOCH_BIT = 0x80


def encode_message(msg: BroadcastMessage) -> bytes:
    """Message layout: round, sender, kind byte, origin (int32, -1 = none),
    digest (int32 length prefix, -1 = none), vertex-present flag + vertex.
    When ``msg.epoch > 0`` the kind byte carries ``_EPOCH_BIT`` and a u32
    epoch id trails the message."""
    kind_byte = _KINDS.index(msg.kind)
    if msg.epoch > 0:
        kind_byte |= _EPOCH_BIT
    out = [
        struct.pack("<IIB", msg.round, msg.sender, kind_byte),
        struct.pack("<i", -1 if msg.origin is None else msg.origin),
    ]
    if msg.digest is None:
        out.append(struct.pack("<i", -1))
    else:
        out.append(struct.pack("<i", len(msg.digest)))
        out.append(msg.digest)
    if msg.vertex is None:
        out.append(b"\x00")
    else:
        out.append(b"\x01")
        out.append(encode_vertex(msg.vertex))
    # certificate section only for the cert kind: every pre-existing
    # message kind keeps its exact byte layout
    if msg.kind == "cert":
        if msg.cert is None:
            out.append(b"\x00")
        else:
            out.append(b"\x01")
            out.append(encode_certificate(msg.cert))
    # likewise the span section exists only for the new cert_span kind
    if msg.kind == "cert_span":
        if msg.span is None:
            out.append(b"\x00")
        else:
            out.append(b"\x01")
            out.append(encode_span_certificate(msg.span))
    if msg.epoch > 0:
        out.append(struct.pack("<I", msg.epoch))
    return b"".join(out)


def decode_message(data: bytes, offset: int = 0) -> Tuple[BroadcastMessage, int]:
    rnd, sender, kind_code = struct.unpack_from("<IIB", data, offset)
    offset += 9
    has_epoch = bool(kind_code & _EPOCH_BIT)
    kind_code &= ~_EPOCH_BIT
    (origin,) = struct.unpack_from("<i", data, offset)
    offset += 4
    (dlen,) = struct.unpack_from("<i", data, offset)
    offset += 4
    digest = None
    if dlen >= 0:
        digest = data[offset : offset + dlen]
        offset += dlen
    has_vertex = data[offset]
    offset += 1
    v = None
    if has_vertex:
        v, offset = decode_vertex(data, offset)
    kind = _KINDS[kind_code]
    cert = None
    if kind == "cert":
        has_cert = data[offset]
        offset += 1
        if has_cert:
            cert, offset = decode_certificate(data, offset)
    span = None
    if kind == "cert_span":
        has_span = data[offset]
        offset += 1
        if has_span:
            span, offset = decode_span_certificate(data, offset)
    epoch = 0
    if has_epoch:
        (epoch,) = struct.unpack_from("<I", data, offset)
        offset += 4
    return (
        BroadcastMessage(
            vertex=v,
            round=rnd,
            sender=sender,
            kind=kind,
            origin=None if origin < 0 else origin,
            digest=digest,
            cert=cert,
            span=span,
            epoch=epoch,
        ),
        offset,
    )


_BATCH_MAGIC = b"DRb1"


def encode_many(msgs: Sequence[BroadcastMessage]) -> bytes:
    """One contiguous buffer for a whole batch of messages.

    Layout: batch magic, u32 count, then ``count`` concatenated
    :func:`encode_message` payloads. The point is one header parse and
    one allocation per *batch* on the hot pump path, not one per vertex
    (ISSUE 8); the per-message layout is unchanged, so a batch of one is
    the same bytes as ``encode_message`` plus an 8-byte prefix.
    """
    out = [_BATCH_MAGIC, struct.pack("<I", len(msgs))]
    out.extend(encode_message(m) for m in msgs)
    return b"".join(out)


def decode_many(data: bytes, offset: int = 0) -> List[BroadcastMessage]:
    if data[offset : offset + 4] != _BATCH_MAGIC:
        raise ValueError("bad batch magic")
    offset += 4
    (count,) = struct.unpack_from("<I", data, offset)
    offset += 4
    msgs = []
    for _ in range(count):
        m, offset = decode_message(data, offset)
        msgs.append(m)
    if offset != len(data):
        raise ValueError(
            f"trailing bytes after batch: {len(data) - offset}"
        )
    return msgs


# -- lane-batch references (ISSUE 17) ---------------------------------------

#: a lane ref is the single pseudo-transaction of its carrier Block;
#: 8 bytes so no honest client payload shorter than the prefix aliases
LANE_MAGIC = b"DRlane1\x00"


def encode_lane_ref(ref: LaneRef) -> bytes:
    """Encode a :class:`LaneRef` as a carrier pseudo-transaction.

    Layout after the magic: u32 producer, u32 seq, 32-byte sha256
    digest, u32 tx count, u32 payload bytes, u32 signer count + u32
    signers (sorted), u32 agg-sig length + bytes (0 for unsigned)."""
    out = [
        LANE_MAGIC,
        struct.pack("<II", ref.producer, ref.seq),
        ref.digest,
        struct.pack("<III", ref.count, ref.nbytes, len(ref.signers)),
    ]
    for s in ref.signers:
        out.append(struct.pack("<I", s))
    out.append(struct.pack("<I", len(ref.agg_sig)))
    out.append(ref.agg_sig)
    return b"".join(out)


def decode_lane_ref(tx: bytes) -> Optional[LaneRef]:
    """Parse a carrier pseudo-transaction; None when ``tx`` is an
    ordinary client transaction (no magic)."""
    if not tx.startswith(LANE_MAGIC):
        return None
    off = len(LANE_MAGIC)
    producer, seq = struct.unpack_from("<II", tx, off)
    off += 8
    digest = tx[off : off + 32]
    off += 32
    count, nbytes, nsig = struct.unpack_from("<III", tx, off)
    off += 12
    signers = struct.unpack_from(f"<{nsig}I", tx, off) if nsig else ()
    off += 4 * nsig
    (siglen,) = struct.unpack_from("<I", tx, off)
    off += 4
    agg = tx[off : off + siglen]
    if off + siglen != len(tx) or len(digest) != 32:
        raise ValueError("malformed lane ref")
    return LaneRef(producer, seq, digest, count, nbytes, tuple(signers), agg)


def lane_ref_of(block: Block) -> Optional[LaneRef]:
    """The ref a carrier block holds, or None for a payload block. A
    carrier is exactly one magic-prefixed pseudo-transaction — producers
    refuse to lane any payload whose own transactions alias the magic
    (see ``LaneCoordinator.begin_publish``), so the shape is unambiguous
    on the delivery path. A MALFORMED magic-prefixed transaction (only a
    Byzantine producer can craft one — honest publishes round-trip by
    construction) is treated as a payload: honest delivery surfaces the
    garbage bytes as-is, exactly as it would an inline garbage block,
    instead of crashing the resolve path."""
    if len(block.transactions) != 1:
        return None
    try:
        return decode_lane_ref(block.transactions[0])
    except (ValueError, struct.error):
        return None


# -- epoch reconfiguration control transactions (ISSUE 20) ------------------

#: an epoch op is the magic-prefixed pseudo-transaction of an ordinary
#: block; 8 bytes like LANE_MAGIC so no honest payload shorter than the
#: prefix aliases, and distinct from it so the two control lanes never
#: collide
EPOCH_MAGIC = b"DRepoch\x00"

_EPOCH_OPS = ("join", "leave", "rotate")


def encode_epoch_op(op: EpochOp) -> bytes:
    """Encode an :class:`EpochOp` as a control pseudo-transaction.

    Layout after the magic: u8 op kind, u32 target index, u32 nonce,
    u32 payload length + bytes."""
    return b"".join(
        (
            EPOCH_MAGIC,
            struct.pack("<BII", _EPOCH_OPS.index(op.kind), op.target,
                        op.nonce),
            struct.pack("<I", len(op.payload)),
            op.payload,
        )
    )


def decode_epoch_op(tx: bytes) -> Optional[EpochOp]:
    """Parse a control pseudo-transaction; None when ``tx`` is an
    ordinary client transaction (no magic); raises on a malformed
    magic-prefixed body."""
    if not tx.startswith(EPOCH_MAGIC):
        return None
    off = len(EPOCH_MAGIC)
    kind_code, target, nonce = struct.unpack_from("<BII", tx, off)
    off += 9
    (plen,) = struct.unpack_from("<I", tx, off)
    off += 4
    payload = tx[off : off + plen]
    if kind_code >= len(_EPOCH_OPS) or off + plen != len(tx):
        raise ValueError("malformed epoch op")
    return EpochOp(_EPOCH_OPS[kind_code], target, nonce, payload)


def epoch_op_of(tx: bytes) -> Optional[EpochOp]:
    """The op a control transaction carries, or None for a client
    transaction. Same degradation rule as :func:`lane_ref_of`: a
    MALFORMED magic-prefixed transaction (only a Byzantine or buggy
    submitter can craft one) is treated as an ordinary payload — the
    ordered log surfaces the garbage bytes as-is instead of crashing
    the delivery walk, and every correct process ignores it for epoch
    scheduling identically."""
    try:
        return decode_epoch_op(tx)
    except (ValueError, struct.error):
        return None


def frame(payload: bytes) -> bytes:
    """Length-prefixed frame for stream transports."""
    return struct.pack("<I", len(payload)) + payload


def read_frame(buf: bytes, offset: int = 0) -> Optional[Tuple[bytes, int]]:
    """Returns (payload, new_offset) or None if the buffer is incomplete."""
    if len(buf) - offset < 4:
        return None
    (ln,) = struct.unpack_from("<I", buf, offset)
    if len(buf) - offset - 4 < ln:
        return None
    return buf[offset + 4 : offset + 4 + ln], offset + 4 + ln
