"""Interprocedural dataflow core for driderlint v2 (round 17).

One shared pass over the discovered file list builds:

- a **function index** — every module-level function and every method,
  keyed by qualified name (``module.func`` / ``module.Class.method``);
- a **call graph** — per function, the resolved call sites (AST node,
  target qname, line), resolved through the module's import aliases,
  ``self``-method dispatch (including package base classes), and a
  light constructor-based type inference (``self.attr = ClassName(...)``
  in any method types ``self.attr``; ``x = ClassName(...)`` types the
  local ``x``) — the same def-use information the checkers reuse;
- **def-use chains** — per function, which local names are assigned
  which value expressions, and which names are parameters.

Resolution is deliberately *under*-approximate: a call the index cannot
type produces no edge rather than an edge to every same-named method.
The checkers built on top (``locks``/``ladder``) state invariants of
the form "no cycle over resolved edges" / "a resolved path exists", and
the dynamic race harness cross-validates coverage (the lock-site test
in tests/test_analysis_v2.py fails if a dynamically exercised lock is
invisible to this graph), so imprecision surfaces as a test failure,
not silently.

The pass is pure AST — nothing is imported or executed — so synthetic
planted-violation files flow through the identical code path.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from dag_rider_tpu.analysis.core import SourceFile

__all__ = [
    "FuncInfo",
    "ClassInfo",
    "CallSite",
    "FlowGraph",
    "build",
    "module_name",
    "dotted",
    "local_constructor_types",
    "param_names",
]


def module_name(rel: str) -> str:
    """`dag_rider_tpu/ops/field.py` -> `dag_rider_tpu.ops.field`;
    `bench.py` -> `bench` (matching ``__name__`` at runtime, which is
    how races.py keys dynamic lock sites)."""
    name = rel[:-3] if rel.endswith(".py") else rel
    name = name.replace("/", ".")
    if name.endswith(".__init__"):
        name = name[: -len(".__init__")]
    return name


def dotted(node: ast.AST) -> Optional[str]:
    """`a.b.c` attribute chains as a dotted string, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


@dataclasses.dataclass
class FuncInfo:
    """One function or method in the package."""

    qname: str  # module.func or module.Class.method
    rel: str
    module: str
    cls: Optional[str]  # enclosing class qname (module.Class) or None
    name: str
    node: ast.AST  # FunctionDef | AsyncFunctionDef
    lineno: int


@dataclasses.dataclass
class ClassInfo:
    """One class: methods, resolved package bases, inferred attr types."""

    qname: str  # module.Class
    rel: str
    module: str
    name: str
    node: ast.ClassDef
    bases: List[str] = dataclasses.field(default_factory=list)
    methods: Dict[str, FuncInfo] = dataclasses.field(default_factory=dict)
    #: self.<attr> -> class qname, inferred from `self.attr = Cls(...)`
    attr_types: Dict[str, str] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass(frozen=True)
class CallSite:
    """One resolved call edge, anchored to its AST node."""

    node: ast.Call
    target: str  # callee qname
    line: int


def param_names(fn: ast.AST) -> List[str]:
    """All parameter names of a FunctionDef, positional and keyword."""
    a = fn.args
    names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return names


class _ModuleIndex:
    """Per-module name environment: import aliases + top-level defs."""

    def __init__(self, rel: str, tree: ast.Module) -> None:
        self.rel = rel
        self.name = module_name(rel)
        self.is_pkg = rel.endswith("/__init__.py")
        #: local alias -> dotted target ("np" -> "numpy",
        #: "Cfg" -> "dag_rider_tpu.config.Config")
        self.aliases: Dict[str, str] = {}
        self.functions: Dict[str, ast.AST] = {}
        self.classes: Dict[str, ast.ClassDef] = {}
        for node in tree.body:
            if isinstance(node, (ast.Import, ast.ImportFrom)):
                self._bind_import(node, override=True)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions[node.name] = node
            elif isinstance(node, ast.ClassDef):
                self.classes[node.name] = node
        # function-local imports fill gaps (bench.py and the lazy seams
        # defer heavy deps into function bodies); top-level bindings win
        top = set(map(id, tree.body))
        for node in ast.walk(tree):
            if isinstance(node, (ast.Import, ast.ImportFrom)) and (
                id(node) not in top
            ):
                self._bind_import(node, override=False)

    def _bind_import(self, node: ast.AST, *, override: bool) -> None:
        def bind(name: str, target: str) -> None:
            if override or name not in self.aliases:
                self.aliases[name] = target

        if isinstance(node, ast.Import):
            for al in node.names:
                bound = al.asname or al.name.split(".")[0]
                target = al.name if al.asname else al.name.split(".")[0]
                bind(bound, target)
        elif isinstance(node, ast.ImportFrom):
            if node.level:
                # relative import: a package's own level-1 is itself
                drop = node.level - 1 if self.is_pkg else node.level
                parts = self.name.split(".")
                pkg = ".".join(parts[: len(parts) - drop])
                base = f"{pkg}.{node.module}" if node.module else pkg
            elif node.module is None:
                return
            else:
                base = node.module
            for al in node.names:
                if al.name == "*":
                    continue
                bind(al.asname or al.name, f"{base}.{al.name}")

    def expand(self, name: str) -> str:
        """First-segment alias expansion: `np.random.rand` with
        np->numpy becomes `numpy.random.rand`; local names expand to
        `module.name`."""
        head, _, rest = name.partition(".")
        if head in self.aliases:
            base = self.aliases[head]
        elif head in self.functions or head in self.classes:
            base = f"{self.name}.{head}"
        else:
            return name
        return f"{base}.{rest}" if rest else base


class FlowGraph:
    """The package-wide call graph + def-use index."""

    def __init__(self) -> None:
        self.functions: Dict[str, FuncInfo] = {}
        self.classes: Dict[str, ClassInfo] = {}
        self.modules: Dict[str, _ModuleIndex] = {}
        #: caller qname -> resolved call sites
        self.callsites: Dict[str, List[CallSite]] = {}
        self._reach_memo: Dict[str, Set[str]] = {}

    # -- queries ------------------------------------------------------------

    def callees(self, qname: str) -> Set[str]:
        return {cs.target for cs in self.callsites.get(qname, ())}

    def callers_of(self, qname: str) -> Set[str]:
        out = set()
        for caller, sites in self.callsites.items():
            if any(cs.target == qname for cs in sites):
                out.add(caller)
        return out

    def reachable(self, qname: str) -> Set[str]:
        """Every function transitively callable from ``qname``
        (inclusive). Memoized; safe on recursive graphs."""
        memo = self._reach_memo.get(qname)
        if memo is not None:
            return memo
        seen: Set[str] = set()
        stack = [qname]
        while stack:
            q = stack.pop()
            if q in seen:
                continue
            seen.add(q)
            stack.extend(self.callees(q))
        self._reach_memo[qname] = seen
        return seen

    def method_on(self, cls_qname: str, meth: str) -> Optional[str]:
        """Resolve a method through the (package-local) base chain."""
        seen: Set[str] = set()
        stack = [cls_qname]
        while stack:
            c = stack.pop()
            if c in seen:
                continue
            seen.add(c)
            info = self.classes.get(c)
            if info is None:
                continue
            if meth in info.methods:
                return info.methods[meth].qname
            stack.extend(info.bases)
        return None


def local_constructor_types(
    fn: ast.AST, graph: FlowGraph, mod: "_ModuleIndex"
) -> Dict[str, str]:
    """Def-use slice for receiver typing: local names assigned a direct
    package-class constructor call in this function body."""
    out: Dict[str, str] = {}
    for node in ast.walk(fn):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
            continue
        tgt = node.targets[0]
        if not (isinstance(tgt, ast.Name) and isinstance(node.value, ast.Call)):
            continue
        d = dotted(node.value.func)
        if d is None:
            continue
        expanded = mod.expand(d)
        if expanded in graph.classes:
            out[tgt.id] = expanded
    return out


def _class_attr_types(
    cls_node: ast.ClassDef, graph: FlowGraph, mod: "_ModuleIndex"
) -> Dict[str, str]:
    """`self.attr = ClassName(...)` anywhere in the class's methods."""
    out: Dict[str, str] = {}
    for node in ast.walk(cls_node):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
            continue
        tgt = node.targets[0]
        if not (
            isinstance(tgt, ast.Attribute)
            and isinstance(tgt.value, ast.Name)
            and tgt.value.id == "self"
            and isinstance(node.value, ast.Call)
        ):
            continue
        d = dotted(node.value.func)
        if d is None:
            continue
        expanded = mod.expand(d)
        if expanded in graph.classes:
            out[tgt.attr] = expanded
    return out


def build(files: Sequence[SourceFile]) -> FlowGraph:
    """Two passes: index every function/class, then resolve calls."""
    graph = FlowGraph()

    # pass 1: indexes
    for rel, tree, _src in files:
        mod = _ModuleIndex(rel, tree)
        graph.modules[mod.name] = mod
        for name, fnode in mod.functions.items():
            qn = f"{mod.name}.{name}"
            graph.functions[qn] = FuncInfo(
                qn, rel, mod.name, None, name, fnode, fnode.lineno
            )
        for cname, cnode in mod.classes.items():
            cqn = f"{mod.name}.{cname}"
            cinfo = ClassInfo(cqn, rel, mod.name, cname, cnode)
            for stmt in cnode.body:
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    mqn = f"{cqn}.{stmt.name}"
                    fi = FuncInfo(
                        mqn, rel, mod.name, cqn, stmt.name, stmt, stmt.lineno
                    )
                    graph.functions[mqn] = fi
                    cinfo.methods[stmt.name] = fi
            graph.classes[cqn] = cinfo

    # pass 1.5: bases + attribute types (need the full class index)
    for cqn, cinfo in graph.classes.items():
        mod = graph.modules[cinfo.module]
        for b in cinfo.node.bases:
            d = dotted(b)
            if d is None:
                continue
            expanded = mod.expand(d)
            if expanded in graph.classes:
                cinfo.bases.append(expanded)
        cinfo.attr_types = _class_attr_types(cinfo.node, graph, mod)

    # pass 2: resolve call sites
    for qn, fi in graph.functions.items():
        mod = graph.modules[fi.module]
        local_types = local_constructor_types(fi.node, graph, mod)
        sites: List[CallSite] = []
        for node in ast.walk(fi.node):
            if not isinstance(node, ast.Call):
                continue
            target = _resolve_call(node, fi, graph, mod, local_types)
            if target is not None:
                sites.append(CallSite(node, target, node.lineno))
        graph.callsites[qn] = sites
    return graph


def _constructor_target(graph: FlowGraph, cls_qname: str) -> str:
    """Calling a class resolves to its __init__ when defined (through
    bases), else to the class qname itself (still a graph node for
    existence checks)."""
    init = graph.method_on(cls_qname, "__init__")
    return init if init is not None else cls_qname


def _resolve_call(
    node: ast.Call,
    fi: FuncInfo,
    graph: FlowGraph,
    mod: "_ModuleIndex",
    local_types: Dict[str, str],
) -> Optional[str]:
    d = dotted(node.func)
    if d is None:
        return None
    head, _, rest = d.partition(".")

    # self.meth() / self.attr.meth()
    if head == "self" and fi.cls is not None:
        parts = rest.split(".") if rest else []
        if len(parts) == 1:
            return graph.method_on(fi.cls, parts[0])
        if len(parts) == 2:
            cinfo = graph.classes.get(fi.cls)
            if cinfo is not None:
                # walk the base chain for the attr's inferred type too
                stack, seen = [fi.cls], set()
                while stack:
                    c = stack.pop()
                    if c in seen:
                        continue
                    seen.add(c)
                    ci = graph.classes.get(c)
                    if ci is None:
                        continue
                    owner = ci.attr_types.get(parts[0])
                    if owner is not None:
                        return graph.method_on(owner, parts[1])
                    stack.extend(ci.bases)
        return None

    # localvar.meth() via constructor-typed locals
    if head in local_types:
        if rest and "." not in rest:
            return graph.method_on(local_types[head], rest)
        return None

    # alias/module/global resolution
    expanded = mod.expand(d)
    if expanded in graph.classes:
        return _constructor_target(graph, expanded)
    if expanded in graph.functions:
        return expanded
    # Class.method (static/unbound) or module.Class(...) chains
    owner, _, meth = expanded.rpartition(".")
    if owner in graph.classes and meth:
        return graph.method_on(owner, meth)
    return None


def iter_attr_assign_targets(
    fn: ast.AST,
) -> Iterable[Tuple[ast.Assign, ast.Attribute]]:
    """Every single-target attribute assignment in a function body —
    the def-use slice release.py walks for save/restore discipline."""
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            tgt = node.targets[0]
            if isinstance(tgt, ast.Attribute):
                yield node, tgt
