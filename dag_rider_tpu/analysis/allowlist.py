"""The driderlint allowlist: every entry is a triaged, justified
exception. An entry that stops matching anything FAILS the run (see
core.apply_allowlist) — excuses don't outlive their violations.
"""

from __future__ import annotations

from typing import List

from dag_rider_tpu.analysis.core import Allow

ALLOWS: List[Allow] = [
    Allow(
        checker="determinism",
        path="dag_rider_tpu/utils/slog.py",
        contains="time.time()",
        reason=(
            "structured-log event timestamps are observability metadata "
            "read by humans and log shippers; they never feed consensus "
            "state, ordering, or any A/B-compared output"
        ),
    ),
]
