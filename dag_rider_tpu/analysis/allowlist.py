"""The driderlint allowlist: every entry is a triaged, justified
exception. An entry that stops matching anything FAILS the run (see
core.apply_allowlist) — excuses don't outlive their violations.

Round 16 emptied it: the last entry (slog.py's bare ``time.time()``
event stamp) was fixed at the source by injecting the clock into
``EventLog``, the same convention the round-14 transport wall-clock
injection set.
"""

from __future__ import annotations

from typing import List

from dag_rider_tpu.analysis.core import Allow

ALLOWS: List[Allow] = []
