"""Exception-safe release of claimed resources (driderlint v2).

The five knob-gated fast paths share long-lived objects (the device
verifier, the fault injector, the transports) whose state individual
rungs and tests *borrow*: set ``fixed_bucket`` for one measurement,
arm a fault plan for one chaos window, flip ``pipeline_enabled`` for
one A/B side. A borrow that is not returned on the exception path
leaks — ADVICE r5 #3 (bench.py's sim256 rung leaking a sim-sized
bucket into the deferred merged headline phase) was a live instance,
fixed by hand in round 8; this checker makes the whole class
impossible to reintroduce.

Two rules, both path-sensitive over the AST's try/finally structure:

**R1 — paired calls.** For each registered (acquire, release) method
pair (``arm``/``disarm``, ``install``/``uninstall``,
``subscribe``/``unsubscribe``): when a function calls BOTH on the same
receiver, the release must run on all paths — the acquire must sit in
the body of a ``try`` whose ``finally`` performs the release. A
function that only acquires transfers ownership to its caller and is
not flagged (that is the transports' subscribe idiom: handlers live
for the transport's life).

**R2 — borrowed-attribute save/restore.** :data:`RESTORED_ATTRS` names
the shared-verifier state attributes that rungs borrow. Writing one on
a *shared* receiver (a parameter, an outer-scope name, anything not
constructed in the same function) must happen inside a ``try`` whose
``finally`` writes the same attribute back. Exempt: ``self`` receivers
and ``__init__`` bodies (configuration at construction is ownership,
not a borrow), locally-constructed receivers (the object dies with the
function), and the restore writes themselves. Additionally, the
generic save/restore shape ``prev = obj.attr … obj.attr = prev`` is
checked for ANY attribute: once a function visibly intends to restore,
the mutation must be under the restoring ``finally`` — a mutation
before the ``try`` opens is a leak window (an exception between them
skips the restore).

``with`` context managers are exempt by construction — that is the
fix this checker pushes toward.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Sequence, Set, Tuple

from dag_rider_tpu.analysis import flow
from dag_rider_tpu.analysis.core import Finding, SourceFile

CHECKER = "release"

#: (acquire, release) method-name pairs for R1
CALL_PAIRS: Tuple[Tuple[str, str], ...] = (
    ("arm", "disarm"),
    ("install", "uninstall"),
    ("subscribe", "unsubscribe"),
)

#: shared-verifier state attributes rungs borrow (R2)
RESTORED_ATTRS = frozenset(
    {"fixed_bucket", "prep_workers", "pipeline_enabled"}
)


@dataclasses.dataclass
class _Ctx:
    """Where a statement sits relative to enclosing Try nodes."""

    #: innermost-last chain of (Try node, section) — section is one of
    #: "body", "handler", "orelse", "finalbody"
    chain: Tuple[Tuple[ast.Try, str], ...]

    def in_finalbody(self) -> bool:
        return any(sec == "finalbody" for _t, sec in self.chain)

    def covering_tries(self) -> List[ast.Try]:
        """Try nodes whose *body* contains this statement (their
        ``finally`` runs if this statement raises afterwards)."""
        return [t for t, sec in self.chain if sec == "body"]


def _walk_with_ctx(fn: ast.AST):
    """Yield (node, _Ctx) for every node in the function body, tracking
    the try/finally chain. Nested function bodies are skipped (they run
    on their own schedule, not on this function's paths)."""

    def emit(node: ast.AST, chain: Tuple[Tuple[ast.Try, str], ...]):
        yield node, _Ctx(chain)
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            return  # do not descend into the nested body
        if isinstance(node, ast.Try):
            for part, sec in (
                (node.body, "body"),
                (node.handlers, "handler"),
                (node.orelse, "orelse"),
                (node.finalbody, "finalbody"),
            ):
                for sub in part:
                    yield from emit(sub, chain + ((node, sec),))
        else:
            for child in ast.iter_child_nodes(node):
                yield from emit(child, chain)

    for child in ast.iter_child_nodes(fn):
        yield from emit(child, ())


def _receiver_of_call(node: ast.Call) -> Optional[Tuple[str, str]]:
    """('obj.sub', 'meth') for obj.sub.meth(...), else None."""
    if isinstance(node.func, ast.Attribute):
        recv = flow.dotted(node.func.value)
        if recv is not None:
            return recv, node.func.attr
    return None


def _attr_write(node: ast.AST) -> Optional[Tuple[str, str, ast.AST]]:
    """(receiver, attr, value) for single-target attribute assigns."""
    if isinstance(node, ast.Assign) and len(node.targets) == 1:
        tgt = node.targets[0]
        if isinstance(tgt, ast.Attribute):
            recv = flow.dotted(tgt.value)
            if recv is not None:
                return recv, tgt.attr, node.value
    if isinstance(node, ast.AugAssign) and isinstance(
        node.target, ast.Attribute
    ):
        recv = flow.dotted(node.target.value)
        if recv is not None:
            return recv, node.target.attr, node.value
    return None


def _finalbody_restores(t: ast.Try, recv: str, attr: str) -> bool:
    for stmt in t.finalbody:
        for sub in ast.walk(stmt):
            w = _attr_write(sub)
            if w is not None and w[0] == recv and w[1] == attr:
                return True
    return False


def _finalbody_calls(t: ast.Try, recv: str, meth: str) -> bool:
    for stmt in t.finalbody:
        for sub in ast.walk(stmt):
            if isinstance(sub, ast.Call):
                rc = _receiver_of_call(sub)
                if rc is not None and rc == (recv, meth):
                    return True
    return False


def _check_function(
    fi: flow.FuncInfo,
    graph: flow.FlowGraph,
) -> List[Finding]:
    out: List[Finding] = []
    fn = fi.node
    mod = graph.modules[fi.module]
    local_ctors = flow.local_constructor_types(fn, graph, mod)
    param_set = set(flow.param_names(fn))
    nodes = list(_walk_with_ctx(fn))

    # index: every attribute write + call with its try context
    writes: List[Tuple[str, str, ast.AST, _Ctx, int]] = []
    calls: List[Tuple[str, str, _Ctx, int]] = []
    #: saved-name -> (receiver, attr): prev = obj.attr
    saves: Dict[str, Tuple[str, str]] = {}
    for node, ctx in nodes:
        w = _attr_write(node)
        if w is not None:
            writes.append((w[0], w[1], w[2], ctx, node.lineno))
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and isinstance(node.value, ast.Attribute)
        ):
            recv = flow.dotted(node.value.value)
            if recv is not None:
                saves[node.targets[0].id] = (recv, node.value.attr)
        if isinstance(node, ast.Call):
            rc = _receiver_of_call(node)
            if rc is not None:
                calls.append((rc[0], rc[1], ctx, node.lineno))

    def is_restore(recv: str, attr: str, value: ast.AST, ctx: _Ctx) -> bool:
        if ctx.in_finalbody():
            return True
        if isinstance(value, ast.Name):
            return saves.get(value.id) == (recv, attr)
        return False

    def covered(recv: str, attr: str, ctx: _Ctx) -> bool:
        return any(
            _finalbody_restores(t, recv, attr)
            for t in ctx.covering_tries()
        )

    # -- R2a: registered borrowed attributes on shared receivers ----------
    for recv, attr, value, ctx, line in writes:
        if attr not in RESTORED_ATTRS:
            continue
        head = recv.partition(".")[0]
        if head == "self" or fi.name == "__init__":
            continue
        if head in local_ctors and head not in param_set:
            continue  # object constructed (and dying) here
        if is_restore(recv, attr, value, ctx):
            continue
        if covered(recv, attr, ctx):
            continue
        out.append(
            Finding(
                CHECKER,
                fi.rel,
                line,
                f"{recv}.{attr} mutated on a shared object without a "
                "finally-restore on the exception path — borrow it "
                "under try/finally (ADVICE r5 #3 class)",
            )
        )

    # -- R2b: generic save/restore shapes for any attribute ---------------
    restored_pairs: Set[Tuple[str, str]] = set()
    for recv, attr, value, ctx, _line in writes:
        if (
            isinstance(value, ast.Name)
            and saves.get(value.id) == (recv, attr)
        ):
            restored_pairs.add((recv, attr))
    for recv, attr in sorted(restored_pairs):
        for w_recv, w_attr, value, ctx, line in writes:
            if (w_recv, w_attr) != (recv, attr):
                continue
            if is_restore(recv, attr, value, ctx):
                continue
            if not covered(recv, attr, ctx):
                out.append(
                    Finding(
                        CHECKER,
                        fi.rel,
                        line,
                        f"{recv}.{attr} is saved and restored in this "
                        "function, but this mutation is outside the "
                        "try whose finally restores it — an exception "
                        "here leaks the borrowed state",
                    )
                )

    # -- R1: paired calls --------------------------------------------------
    for acq_name, rel_name in CALL_PAIRS:
        acq_sites = [
            (recv, ctx, line)
            for recv, meth, ctx, line in calls
            if meth == acq_name
        ]
        rel_recvs = {
            recv for recv, meth, _ctx, _line in calls if meth == rel_name
        }
        for recv, ctx, line in acq_sites:
            if recv not in rel_recvs:
                continue  # ownership transfer: no release here at all
            ok = any(
                _finalbody_calls(t, recv, rel_name)
                for t in ctx.covering_tries()
            )
            if not ok:
                out.append(
                    Finding(
                        CHECKER,
                        fi.rel,
                        line,
                        f"{recv}.{acq_name}() is released by "
                        f"{recv}.{rel_name}() in this function, but not "
                        "in a finally covering the acquire — an "
                        "exception path skips the release",
                    )
                )
    return out


def run(
    files: Sequence[SourceFile],
    repo_root: str,
    graph: Optional[flow.FlowGraph] = None,
) -> List[Finding]:
    if graph is None:
        graph = flow.build(files)
    findings: List[Finding] = []
    seen: Set[Tuple[str, int, str]] = set()
    for qn, fi in graph.functions.items():
        if fi.rel.startswith("dag_rider_tpu/analysis/"):
            continue
        scopes = [fi]
        # nested defs (bench rung helpers) are their own borrow scopes
        for node in ast.walk(fi.node):
            if (
                isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                and node is not fi.node
            ):
                scopes.append(
                    flow.FuncInfo(
                        f"{qn}.{node.name}",
                        fi.rel,
                        fi.module,
                        None,
                        node.name,
                        node,
                        node.lineno,
                    )
                )
        for scope in scopes:
            for f in _check_function(scope, graph):
                key = (f.path, f.line, f.message)
                if key not in seen:
                    seen.add(key)
                    findings.append(f)
    return findings
