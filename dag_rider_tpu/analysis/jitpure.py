"""jit purity: traced functions must be side-effect-free Python.

A jitted function's Python body runs at TRACE time, once per cache key
— not per call. Any Python side effect inside it (env read, print,
file I/O, global mutation, wall clock, RNG) therefore fires on a
schedule the caller cannot reason about: once, never again, or again
on every retrace. The rule over ``ops/`` and ``parallel/``: nothing in
a jitted function may touch the world outside its arguments.

Detected jit spellings: ``@jax.jit`` / ``@jit`` decorators,
``@functools.partial(jax.jit, ...)`` / ``@partial(jit, ...)``, and
module-level ``name = jax.jit(fn)`` rebinding a function defined in
the same file. Host callbacks (``pure_callback`` / ``io_callback`` /
``jax.debug.callback``) are flagged wherever they appear in scope —
the repo's design keeps ALL host work outside the traced region.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Sequence, Set

from dag_rider_tpu.analysis.core import Finding, SourceFile

CHECKER = "jitpure"

_SCOPES = ("dag_rider_tpu/ops/", "dag_rider_tpu/parallel/")

_BANNED_CALLS = {
    "print": "print() at trace time",
    "open": "file I/O at trace time",
    "input": "console input at trace time",
    "time.time": "wall clock at trace time",
    "time.monotonic": "clock read at trace time",
    "time.perf_counter": "clock read at trace time",
    "time.sleep": "sleep at trace time",
    "os.getenv": "environment read at trace time",
    "os.environ.get": "environment read at trace time",
    "jax.pure_callback": "host callback inside a jitted fn",
    "jax.experimental.io_callback": "host callback inside a jitted fn",
    "jax.debug.callback": "host callback inside a jitted fn",
    "pure_callback": "host callback inside a jitted fn",
    "io_callback": "host callback inside a jitted fn",
}


def _dotted(node: ast.AST) -> Optional[str]:
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_jit_expr(node: ast.AST) -> bool:
    """True for ``jax.jit`` / ``jit`` and ``partial(jax.jit, ...)``."""
    d = _dotted(node)
    if d in ("jax.jit", "jit"):
        return True
    if isinstance(node, ast.Call):
        f = _dotted(node.func)
        if f in ("functools.partial", "partial") and node.args:
            return _is_jit_expr(node.args[0])
        # e.g. jax.jit(..., static_argnames=...) used as a decorator
        return _is_jit_expr(node.func)
    return False


def _jitted_functions(tree: ast.Module) -> Set[str]:
    """Names of module-level functions that are jitted, via decorator or
    a later ``x = jax.jit(name)`` rebinding."""
    defs = {
        n.name: n
        for n in ast.walk(tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    }
    jitted: Set[str] = set()
    for name, fn in defs.items():
        if any(_is_jit_expr(d) for d in fn.decorator_list):
            jitted.add(name)
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            if _is_jit_expr(node.value.func) and node.value.args:
                arg = node.value.args[0]
                if isinstance(arg, ast.Name) and arg.id in defs:
                    jitted.add(arg.id)
    return jitted


def _check_body(rel: str, fn: ast.AST) -> List[Finding]:
    out: List[Finding] = []
    for node in ast.walk(fn):
        if isinstance(node, ast.Global):
            out.append(
                Finding(
                    CHECKER,
                    rel,
                    node.lineno,
                    f"global statement inside jitted {fn.name}()",
                )
            )
        if isinstance(node, ast.Call):
            d = _dotted(node.func)
            if d is None:
                continue
            why = _BANNED_CALLS.get(d)
            if why is None and (
                d.startswith("random.")
                or d.startswith("np.random.")
                or d.startswith("numpy.random.")
            ):
                why = "host RNG at trace time"
            if why is None and d in ("os.environ.get",):
                why = "environment read at trace time"
            if why is not None:
                out.append(
                    Finding(
                        CHECKER,
                        rel,
                        node.lineno,
                        f"{d}() inside jitted {fn.name}() — {why}",
                    )
                )
        if isinstance(node, ast.Subscript):
            if _dotted(node.value) == "os.environ":
                out.append(
                    Finding(
                        CHECKER,
                        rel,
                        node.lineno,
                        f"os.environ[...] inside jitted {fn.name}() — "
                        "environment read at trace time",
                    )
                )
    return out


def run(files: Sequence[SourceFile], repo_root: str) -> List[Finding]:
    findings: List[Finding] = []
    for rel, tree, _src in files:
        if not rel.startswith(_SCOPES):
            continue
        jitted = _jitted_functions(tree)
        for fn in ast.walk(tree):
            if (
                isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef))
                and fn.name in jitted
            ):
                findings.extend(_check_body(rel, fn))
    return findings
