"""Dynamic lock-race harness (the ``-race`` half of driderlint).

Installed (``DAGRIDER_RACE=1`` under pytest, or ``install()`` directly)
it monkeypatches ``threading.Lock``/``RLock`` so every lock allocated
*by package code* is tracked by creation site, then enforces three
runtime invariants while the existing chaos/fuzz suites drive the
threaded modules:

1. **Lock-order cycles** — acquiring lock B while holding lock A adds
   the edge ``site(A) -> site(B)`` to a global acquisition-order graph;
   an edge that closes a cycle is a deadlock that merely hasn't fired
   yet and raises :class:`RaceViolation` at the acquire *attempt*
   (before blocking — the harness reports the deadlock instead of
   becoming it). Same-thread re-acquire of a non-reentrant lock is the
   degenerate one-node cycle and raises immediately.
2. **Guarded fields** — :data:`GUARDED_FIELDS` declares, per class,
   which shared attributes its lock owns (the discipline the modules'
   comments promise). :func:`guard` swaps the instance's class for a
   checking subclass (rebinding outside the lock raises) and wraps the
   attribute's container so mutator methods (``append``/``add``/
   ``pop``/ ``__setitem__``/…) check lock ownership too. Reads are
   deliberately not intercepted: the repo's idiom allows relaxed reads
   (e.g. ``delivered_count``), it is *writes* that corrupt.
3. **Serialized methods** — :data:`SERIAL_METHODS` declares methods
   that are lock-free by single-owner contract (PrepEngine's ring
   discipline, VerifierPipeline's window). Overlapping calls from two
   threads raise; same-thread reentrancy is allowed.

Violations RAISE in the offending thread *and* are recorded in
:data:`VIOLATIONS`, because the offending thread is often a pool
worker whose exception a Future would swallow — the pytest hook in
tests/conftest.py fails the session on any unconsumed record.
"""

from __future__ import annotations

import sys
import threading
from collections import deque
from typing import Callable, Dict, List, Optional, Set, Tuple

__all__ = [
    "RaceViolation",
    "GUARDED_FIELDS",
    "SERIAL_METHODS",
    "VIOLATIONS",
    "install",
    "uninstall",
    "active",
    "guard",
    "guard_serial",
    "drain_violations",
]


class RaceViolation(AssertionError):
    """A thread-discipline invariant was broken (or would deadlock)."""


#: violations recorded by any thread since install()/drain; the
#: conftest session hook fails the run if this is non-empty at exit
VIOLATIONS: List[str] = []

_real_lock = threading.Lock
_real_rlock = threading.RLock

_graph: Optional["LockGraph"] = None
_installed = False


def _record(msg: str) -> RaceViolation:
    VIOLATIONS.append(msg)
    return RaceViolation(msg)


def drain_violations() -> List[str]:
    """Return and clear the recorded violations (planted-violation
    tests consume what they deliberately caused)."""
    out = list(VIOLATIONS)
    VIOLATIONS.clear()
    return out


def active() -> bool:
    return _installed


# -- lock-order graph -------------------------------------------------------


class LockGraph:
    """Acquisition-order edges keyed by lock *creation site* — two
    instances of the same class rank as the same node, so an ordering
    inversion between peers of one class is visible even when no single
    run interleaves the same two instances. Self-edges (site to itself,
    distinct instances) are skipped: sibling instances of one class are
    routinely nested intentionally and carry no fixed order."""

    def __init__(self) -> None:
        self._mu = _real_lock()
        self._edges: Dict[str, set] = {}
        self._local = threading.local()

    def _held(self) -> list:
        h = getattr(self._local, "held", None)
        if h is None:
            h = []
            self._local.held = h
        return h

    def before_acquire(self, lock: "_TrackedBase") -> None:
        """Edge recording + deadlock checks, run BEFORE blocking."""
        held = self._held()
        already = any(l is lock for l in held)
        if already and not lock.reentrant:
            raise _record(
                f"same-thread re-acquire of non-reentrant lock "
                f"{lock.site} — guaranteed deadlock"
            )
        if already:
            return  # RLock re-entry establishes no new ordering
        for h in held:
            if h.site != lock.site:
                self._add_edge(h.site, lock.site)

    def after_acquire(self, lock: "_TrackedBase") -> None:
        self._held().append(lock)

    def on_release(self, lock: "_TrackedBase") -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] is lock:
                del held[i]
                return

    def _add_edge(self, a: str, b: str) -> None:
        with self._mu:
            succ = self._edges.setdefault(a, set())
            if b in succ:
                return
            path = self._path(b, a)
            succ.add(b)
            if path is not None:
                cycle = " -> ".join([a] + path)
                raise _record(f"lock-order cycle (deadlock): {cycle}")

    def _path(self, src: str, dst: str) -> Optional[List[str]]:
        """A path src -> ... -> dst through recorded edges, or None.
        Caller holds self._mu."""
        stack = [(src, [src])]
        seen = set()
        while stack:
            node, path = stack.pop()
            if node == dst:
                return path
            if node in seen:
                continue
            seen.add(node)
            for nxt in self._edges.get(node, ()):
                stack.append((nxt, path + [nxt]))
        return None


# -- tracked locks ----------------------------------------------------------


class _TrackedBase:
    reentrant = False

    def __init__(self, graph: LockGraph, site: str) -> None:
        self._graph = graph
        self.site = site
        self._owner: Optional[int] = None
        self._depth = 0

    def held_by_current(self) -> bool:
        return self._owner == threading.get_ident()


class TrackedLock(_TrackedBase):
    reentrant = False

    def __init__(self, graph: LockGraph, site: str) -> None:
        super().__init__(graph, site)
        self._inner = _real_lock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        self._graph.before_acquire(self)
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._graph.after_acquire(self)
            self._owner = threading.get_ident()
        return ok

    def release(self) -> None:
        self._owner = None
        self._graph.on_release(self)
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()


class TrackedRLock(_TrackedBase):
    reentrant = True

    def __init__(self, graph: LockGraph, site: str) -> None:
        super().__init__(graph, site)
        self._inner = _real_rlock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        self._graph.before_acquire(self)
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            if self._depth == 0 or self._owner != threading.get_ident():
                self._graph.after_acquire(self)
            self._owner = threading.get_ident()
            self._depth += 1
        return ok

    def release(self) -> None:
        self._depth -= 1
        if self._depth <= 0:
            self._owner = None
            self._graph.on_release(self)
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()


def _caller_module(depth: int = 2) -> Tuple[str, int]:
    f = sys._getframe(depth)
    return f.f_globals.get("__name__", ""), f.f_lineno


#: every site the factories handed a tracked lock for, across the whole
#: install() window — the dynamic half of the static/dynamic lock-site
#: cross-validation (tests/test_analysis_v2.py asserts these are a
#: subset of locks.lock_sites()). Never cleared by uninstall(): the
#: test wants the union over every suite that ran under DAGRIDER_RACE.
SITES: Set[str] = set()


def _tracked_lock_factory():
    mod, line = _caller_module()
    if not mod.startswith("dag_rider_tpu") or mod.startswith(
        "dag_rider_tpu.analysis"
    ):
        return _real_lock()
    site = f"{mod}:{line}"
    SITES.add(site)
    return TrackedLock(_graph, site)


def _tracked_rlock_factory():
    mod, line = _caller_module()
    if not mod.startswith("dag_rider_tpu") or mod.startswith(
        "dag_rider_tpu.analysis"
    ):
        return _real_rlock()
    site = f"{mod}:{line}"
    SITES.add(site)
    return TrackedRLock(_graph, site)


# -- guarded fields ---------------------------------------------------------

#: class -> (lock attribute, guarded shared attributes). Declared as
#: dotted names so importing this module stays cheap; resolved lazily
#: by install()/guard().
GUARDED_FIELDS: Dict[str, Tuple[str, Tuple[str, ...]]] = {
    "dag_rider_tpu.verifier.resilient.ResilientVerifier": (
        "_lock",
        ("_down", "_probing"),
    ),
    "dag_rider_tpu.transport.memory.InMemoryTransport": (
        "_lock",
        ("_handlers", "_batch_handlers", "_queue", "_fanout"),
    ),
    "dag_rider_tpu.mempool.Mempool": ("_lock", ("_inflight",)),
    # run_blocks legitimately overlaps itself (caller-thread prep of
    # chunk k+1 concurrent with the seam thread's prep of k+2 into a
    # different ring slot), so the GAUGES are the shared state, not the
    # method — first real finding of this harness (fixed round 14).
    "dag_rider_tpu.verifier.prep.PrepEngine": (
        "_gauge_lock",
        (
            "last_blocks",
            "dispatches",
            "dispatches_parallel",
            "rows_total",
            "rows_parallel",
            "serial_retries",
        ),
    ),
}

#: class -> methods serialized by single-owner contract (no lock at
#: all — the contract is "never two threads in here at once")
SERIAL_METHODS: Dict[str, Tuple[str, ...]] = {
    "dag_rider_tpu.verifier.pipeline.VerifierPipeline": (
        "run_coalesced",
        "drain",
    ),
}


def _resolve(dotted: str):
    mod, _, cls = dotted.rpartition(".")
    import importlib

    return getattr(importlib.import_module(mod), cls)


class _FieldGuard:
    """Shared check closure a guarded instance and its wrapped
    containers consult before any mutation."""

    __slots__ = ("obj", "lock_attr", "cls_name", "field")

    def __init__(self, obj, lock_attr: str, cls_name: str, field: str):
        self.obj = obj
        self.lock_attr = lock_attr
        self.cls_name = cls_name
        self.field = field

    def check(self) -> None:
        lock = self.obj.__dict__.get(self.lock_attr)
        if isinstance(lock, _TrackedBase) and lock.held_by_current():
            return
        raise _record(
            f"unguarded write to {self.cls_name}.{self.field} — "
            f"mutation without holding {self.cls_name}.{self.lock_attr}"
        )


def _make_guarded_container(value, fg: _FieldGuard):
    if isinstance(value, deque):
        g = _GuardedDeque(fg, value, maxlen=value.maxlen)
        return g
    if isinstance(value, dict):
        return _GuardedDict(fg, value)
    if isinstance(value, set):
        return _GuardedSet(fg, value)
    if isinstance(value, list):
        return _GuardedList(fg, value)
    return value


def _mutator(name):
    def m(self, *a, **k):
        self._fg.check()
        return getattr(self._base_type, name)(self, *a, **k)

    m.__name__ = name
    return m


def _build_guarded(base, mutators):
    ns = {"_base_type": base}

    def __init__(self, fg, *a, **k):
        object.__setattr__(self, "_fg", fg)
        base.__init__(self, *a, **k)

    ns["__init__"] = __init__
    for name in mutators:
        if hasattr(base, name):
            ns[name] = _mutator(name)
    return type(f"_Guarded{base.__name__.title()}", (base,), ns)


_GuardedList = _build_guarded(
    list,
    (
        "append",
        "extend",
        "insert",
        "pop",
        "remove",
        "clear",
        "sort",
        "reverse",
        "__setitem__",
        "__delitem__",
        "__iadd__",
        "__imul__",
    ),
)
_GuardedDict = _build_guarded(
    dict,
    (
        "__setitem__",
        "__delitem__",
        "pop",
        "popitem",
        "clear",
        "update",
        "setdefault",
        "__ior__",
    ),
)
_GuardedSet = _build_guarded(
    set,
    (
        "add",
        "discard",
        "remove",
        "pop",
        "clear",
        "update",
        "difference_update",
        "intersection_update",
        "symmetric_difference_update",
        "__ior__",
        "__iand__",
        "__isub__",
        "__ixor__",
    ),
)
_GuardedDeque = _build_guarded(
    deque,
    (
        "append",
        "appendleft",
        "extend",
        "extendleft",
        "pop",
        "popleft",
        "remove",
        "clear",
        "rotate",
        "insert",
        "__setitem__",
        "__delitem__",
        "__iadd__",
    ),
)

_guard_subclass_cache: Dict[type, type] = {}


def guard(obj) -> None:
    """Enforce the declared guarded-field discipline on one instance.

    Swaps ``obj.__class__`` for a checking subclass and wraps the
    guarded containers. The instance's lock must be a tracked lock
    (created after :func:`install`); a raw lock is replaced with a
    tracked one — safe while unheld, which construction time is.
    """
    if getattr(type(obj), "_driderlint_guarded", False):
        return  # already guarded (auto-guard + explicit guard compose)
    dotted = f"{type(obj).__module__}.{type(obj).__qualname__}"
    spec = GUARDED_FIELDS.get(dotted)
    if spec is None:
        raise KeyError(f"{dotted} has no GUARDED_FIELDS declaration")
    lock_attr, fields = spec
    lock = getattr(obj, lock_attr)
    if not isinstance(lock, _TrackedBase):
        graph = _graph if _graph is not None else LockGraph()
        cls = (
            TrackedRLock
            if type(lock).__name__ == "RLock"
            else TrackedLock
        )
        object.__setattr__(
            obj, lock_attr, cls(graph, f"{dotted}.{lock_attr}")
        )
    cls_name = type(obj).__name__
    for field in fields:
        fg = _FieldGuard(obj, lock_attr, cls_name, field)
        wrapped = _make_guarded_container(obj.__dict__[field], fg)
        object.__setattr__(obj, field, wrapped)
    base = type(obj)
    sub = _guard_subclass_cache.get(base)
    if sub is None:

        def __setattr__(self, name, value, _fields=fields,
                        _lock_attr=lock_attr, _cls_name=cls_name):
            if name in _fields:
                _FieldGuard(self, _lock_attr, _cls_name, name).check()
                value = _make_guarded_container(
                    value, _FieldGuard(self, _lock_attr, _cls_name, name)
                )
            object.__setattr__(self, name, value)

        sub = type(
            base.__name__,
            (base,),
            {"__setattr__": __setattr__, "_driderlint_guarded": True},
        )
        _guard_subclass_cache[base] = sub
    obj.__class__ = sub


def guard_serial(obj, methods: Optional[Tuple[str, ...]] = None) -> None:
    """Enforce the single-owner contract on one instance: any two
    overlapping calls (across ALL listed methods) from distinct threads
    raise. Same-thread nesting is allowed."""
    if methods is None:
        dotted = f"{type(obj).__module__}.{type(obj).__qualname__}"
        methods = SERIAL_METHODS.get(dotted)
        if methods is None:
            raise KeyError(f"{dotted} has no SERIAL_METHODS declaration")
    mu = _real_lock()
    state = {"owner": None, "depth": 0}
    cls_name = type(obj).__name__

    def _wrap(name: str, bound: Callable) -> Callable:
        def wrapper(*a, **k):
            me = threading.get_ident()
            with mu:
                if state["owner"] is not None and state["owner"] != me:
                    raise _record(
                        f"serialized-method overlap: {cls_name}.{name}()"
                        f" entered by thread {me} while thread "
                        f"{state['owner']} is still inside the "
                        f"single-owner group {methods}"
                    )
                state["owner"] = me
                state["depth"] += 1
            try:
                return bound(*a, **k)
            finally:
                with mu:
                    state["depth"] -= 1
                    if state["depth"] == 0:
                        state["owner"] = None

        wrapper.__name__ = name
        return wrapper

    for name in methods:
        object.__setattr__(obj, name, _wrap(name, getattr(obj, name)))


# -- install / uninstall ----------------------------------------------------

_patched_inits: List[Tuple[type, Callable]] = []


def _auto_guard_classes() -> None:
    """Wrap the declared classes' __init__ so every instance built
    while the harness is active is guarded automatically — this is how
    the chaos/fuzz suites drive the harness with zero per-test code."""
    for dotted in GUARDED_FIELDS:
        cls = _resolve(dotted)
        orig = cls.__init__

        def wrapped(self, *a, _orig=orig, **k):
            _orig(self, *a, **k)
            guard(self)

        cls.__init__ = wrapped
        _patched_inits.append((cls, orig))
    for dotted, methods in SERIAL_METHODS.items():
        cls = _resolve(dotted)
        orig = cls.__init__

        def wrapped_s(self, *a, _orig=orig, _methods=methods, **k):
            _orig(self, *a, **k)
            guard_serial(self, _methods)

        cls.__init__ = wrapped_s
        _patched_inits.append((cls, orig))


def install(auto_guard: bool = True) -> None:
    """Activate the harness: tracked lock factories for package code,
    plus (by default) auto-guarding of the declared classes."""
    global _graph, _installed
    if _installed:
        return
    _graph = LockGraph()
    threading.Lock = _tracked_lock_factory
    threading.RLock = _tracked_rlock_factory
    if auto_guard:
        _auto_guard_classes()
    _installed = True


def uninstall() -> None:
    global _graph, _installed
    if not _installed:
        return
    threading.Lock = _real_lock
    threading.RLock = _real_rlock
    for cls, orig in reversed(_patched_inits):
        cls.__init__ = orig
    _patched_inits.clear()
    _graph = None
    _installed = False
