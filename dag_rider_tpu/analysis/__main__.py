"""``python -m dag_rider_tpu.analysis`` — run driderlint over the repo.

Exit 0: clean (suppressed findings are reported for transparency).
Exit 1: violations, allowlist entries that suppress nothing, or the
``--budget-s`` wall-time budget blown (driderlint gates every PR, so
it must stay cheap; a checker that quietly grows quadratic gets caught
here, not in everyone's CI latency).

``--with-external`` additionally runs ruff and mypy (pinned configs in
pyproject.toml) when they are importable; absent tools are reported as
skipped, never as failures — the container this repo develops in does
not ship them, CI does. mypy GATES on the strict per-module list
(config.py, analysis/, core/, utils/metrics.py — the modules pyproject
marks strict) and stays advisory on the rest.
"""

from __future__ import annotations

import argparse
import importlib.util
import os
import subprocess
import sys
import time

from dag_rider_tpu.analysis.core import run_static

#: modules where mypy findings gate (pyproject [[tool.mypy.overrides]]
#: pins the strictness for exactly this list)
MYPY_GATED = (
    "dag_rider_tpu/config.py",
    "dag_rider_tpu/analysis",
    "dag_rider_tpu/core",
    "dag_rider_tpu/utils/metrics.py",
)

#: still checked, failures reported but not gating (yet)
MYPY_ADVISORY = ("dag_rider_tpu/consensus",)


def _repo_root() -> str:
    here = os.path.dirname(os.path.abspath(__file__))
    return os.path.dirname(os.path.dirname(here))


def _run_external(repo_root: str) -> int:
    """ruff + gated mypy when installed; 0 if gate-clean."""
    rc = 0
    if importlib.util.find_spec("ruff") is not None:
        print("== ruff ==")
        proc = subprocess.run(
            [sys.executable, "-m", "ruff", "check", "."],
            cwd=repo_root,
        )
        rc |= proc.returncode
    else:
        print("== ruff == not installed (skipped)")
    if importlib.util.find_spec("mypy") is not None:
        print("== mypy (gating) ==")
        proc = subprocess.run(
            [sys.executable, "-m", "mypy", *MYPY_GATED],
            cwd=repo_root,
        )
        rc |= proc.returncode
        print("== mypy (advisory) ==")
        subprocess.run(
            [sys.executable, "-m", "mypy", *MYPY_ADVISORY],
            cwd=repo_root,
        )
    else:
        print("== mypy == not installed (skipped)")
    return rc


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m dag_rider_tpu.analysis")
    ap.add_argument(
        "--with-external",
        action="store_true",
        help="also run ruff/mypy when installed",
    )
    ap.add_argument(
        "--root", default=None, help="repo root (default: auto-detected)"
    )
    ap.add_argument(
        "--budget-s",
        type=float,
        default=0.0,
        help="fail if the static checkers exceed this wall time (0: off)",
    )
    args = ap.parse_args(argv)
    root = args.root or _repo_root()

    t0 = time.monotonic()
    kept, suppressed, unused = run_static(root)
    elapsed = time.monotonic() - t0
    print(f"driderlint over {root} ({elapsed:.2f}s)")
    for f in suppressed:
        print(f"  allowed  {f}")
    for f in kept:
        print(f"  VIOLATION  {f}")
    for a in unused:
        print(
            f"  STALE ALLOW  [{a.checker}] {a.path} contains "
            f"{a.contains!r} — suppresses nothing; delete it"
        )
    rc = 1 if (kept or unused) else 0
    if args.budget_s and elapsed > args.budget_s:
        print(
            f"  BUDGET  static checkers took {elapsed:.2f}s "
            f"> {args.budget_s:.0f}s budget — driderlint must stay "
            "cheap enough to gate every PR"
        )
        rc = 1

    if args.with_external:
        rc |= _run_external(root)

    if rc == 0:
        print(
            f"clean: 0 violations, {len(suppressed)} allowlisted, "
            "0 stale allows"
        )
    else:
        print(
            f"FAILED: {len(kept)} violation(s), {len(unused)} stale "
            "allowlist entr(ies)"
        )
    return rc


if __name__ == "__main__":
    sys.exit(main())
