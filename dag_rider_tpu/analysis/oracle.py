"""Oracle purity: fast-path-only code must not mutate reference state.

Every A/B byte-identity gate in this repo (tests/test_pump_vector.py,
tests/test_cert.py) compares a fast path against the scalar per-vertex
reference oracle *in separate runs*. That comparison is only meaningful
if code reachable exclusively under ``pump=vector`` / ``cert=agg``
never mutates the state the scalar path owns — otherwise the oracle
being compared against is already contaminated and "byte-identical"
proves nothing.

Statically enforced shape (over ``consensus/``):

- inside ``if self._vector:`` bodies and the vector-only methods
  (``_drain_buffer_vector``, ``on_val_batch``, ``_process_inbox``),
  no mutation of the scalar pump's admission state
  (``_buffer``, ``_buffered_ids``, ``_blocked_on``);
- inside ``else:`` / ``if not self._vector:`` scalar branches, no
  mutation of the vector pump's state (``_inbox``, ``_buffer_rounds``);
- inside ``if self._cert:`` bodies and the cert-only methods, no
  mutation of the scalar admission state either. (Pushes into
  ``_pending_verify`` are legal there — per-vertex re-verification IS
  the cert path's degradation seam.)

Mutation = direct assignment / augmented assignment / subscript store
to ``self.<attr>``, or calling a mutator method on it. Local aliases
are deliberately out of scope — the repo idiom aliases *device arrays*
(rebuilt functionally), not the host admission dicts this rule guards.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Sequence

from dag_rider_tpu.analysis.core import Finding, SourceFile

CHECKER = "oracle"

#: scalar reference-path admission state (owned by the per-vertex pump)
SCALAR_STATE = frozenset({"_buffer", "_buffered_ids", "_blocked_on"})
#: vector-pump-only state
VECTOR_STATE = frozenset({"_inbox", "_buffer_rounds"})

VECTOR_ONLY_FUNCS = frozenset(
    {"_drain_buffer_vector", "on_val_batch", "_process_inbox"}
)
CERT_ONLY_FUNCS = frozenset(
    {
        "_on_certificate",
        "_cert_step",
        "_apply_certificate",
        "_degrade_cert_round",
        "_cert_tick",
        "_maybe_assemble_certs",
        # cert-of-certs overlay (ISSUE 12)
        "_on_span",
        "_apply_span",
        "_bank_span_cert",
        "_maybe_assemble_spans",
    }
)

_MUTATORS = frozenset(
    {
        "append",
        "appendleft",
        "add",
        "extend",
        "insert",
        "pop",
        "popleft",
        "remove",
        "discard",
        "clear",
        "update",
        "setdefault",
    }
)


def _self_attr(node: ast.AST) -> Optional[str]:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _mutated_attrs(node: ast.AST):
    """Yield (attr, lineno) for every self.<attr> mutation under node
    (node itself included)."""
    for n in ast.walk(node):
        if isinstance(n, (ast.Assign, ast.AugAssign)):
            targets = n.targets if isinstance(n, ast.Assign) else [n.target]
            for t in targets:
                base = t
                while isinstance(base, ast.Subscript):
                    base = base.value
                attr = _self_attr(base)
                if attr is not None:
                    yield attr, n.lineno
        elif isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute):
            if n.func.attr in _MUTATORS:
                attr = _self_attr(n.func.value)
                if attr is not None:
                    yield attr, n.lineno


def _guard_kind(test: ast.AST) -> Optional[str]:
    """'vector' for ``self._vector``, 'not_vector' for
    ``not self._vector``, 'cert' for ``self._cert``, else None."""
    if _self_attr(test) == "_vector":
        return "vector"
    if _self_attr(test) == "_cert":
        return "cert"
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        if _self_attr(test.operand) == "_vector":
            return "not_vector"
    return None


def _check_region(
    rel: str, body: Sequence[ast.stmt], forbidden: frozenset, label: str
) -> List[Finding]:
    out = []
    for stmt in body:
        for attr, line in _mutated_attrs(stmt):
            if attr in forbidden:
                out.append(
                    Finding(
                        CHECKER,
                        rel,
                        line,
                        f"{label} mutates self.{attr} — reference-path "
                        "state the A/B byte-identity gates assume "
                        "untouched",
                    )
                )
    return out


def run(files: Sequence[SourceFile], repo_root: str) -> List[Finding]:
    findings: List[Finding] = []
    for rel, tree, _src in files:
        if not rel.startswith("dag_rider_tpu/consensus/"):
            continue
        for fn in ast.walk(tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if fn.name in VECTOR_ONLY_FUNCS:
                findings.extend(
                    _check_region(
                        rel,
                        fn.body,
                        SCALAR_STATE,
                        f"vector-only method {fn.name}()",
                    )
                )
            if fn.name in CERT_ONLY_FUNCS:
                findings.extend(
                    _check_region(
                        rel,
                        fn.body,
                        SCALAR_STATE,
                        f"cert-only method {fn.name}()",
                    )
                )
            for node in ast.walk(fn):
                if not isinstance(node, ast.If):
                    continue
                kind = _guard_kind(node.test)
                if kind == "vector":
                    findings.extend(
                        _check_region(
                            rel,
                            node.body,
                            SCALAR_STATE,
                            "vector-only branch (if self._vector)",
                        )
                    )
                    findings.extend(
                        _check_region(
                            rel,
                            node.orelse,
                            VECTOR_STATE,
                            "scalar branch (else of if self._vector)",
                        )
                    )
                elif kind == "not_vector":
                    findings.extend(
                        _check_region(
                            rel,
                            node.body,
                            VECTOR_STATE,
                            "scalar branch (if not self._vector)",
                        )
                    )
                elif kind == "cert":
                    findings.extend(
                        _check_region(
                            rel,
                            node.body,
                            SCALAR_STATE,
                            "cert-only branch (if self._cert)",
                        )
                    )
    return findings
