"""Static lock-order proofs over the interprocedural flow graph.

The dynamic race harness (``races.py``) proves "no lock-order cycle was
*observed*" on the interleavings the chaos suites happen to drive. This
checker upgrades that to "no cycle is *possible* over resolved call
paths": it extracts every ``threading.Lock()``/``RLock()`` creation
site in the package (keyed ``module:line``, the exact key the dynamic
harness uses, so the two views cross-validate), every ``with <lock>:``
acquisition, and builds the static acquisition-order graph — lock A
precedes lock B when a ``with A:`` body acquires B directly (nested
``with``) or calls a function from whose resolved call closure some
function acquires B. A cycle in that graph is a deadlock that merely
needs the right interleaving; it fails the tree today, not the night
the scheduler finds it.

Like the dynamic graph, same-site edges are skipped (two instances of
one class nest intentionally and carry no fixed order) — except the
statically-certain degenerate case: a nested ``with`` on the *same*
non-reentrant lock expression, which is a guaranteed self-deadlock.

Exported for the cross-validation test: :func:`lock_sites` (static
creation-site registry) and :func:`build_lock_graph` (sites + edges).
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Sequence, Set, Tuple

from dag_rider_tpu.analysis import flow
from dag_rider_tpu.analysis.core import Finding, SourceFile

CHECKER = "locks"

#: analysis/ is excluded exactly as the dynamic factories exclude it
#: (the harness's own bookkeeping locks must not rank in the graph)
_EXCLUDED_PREFIX = "dag_rider_tpu/analysis/"

_LOCK_CTORS = {"threading.Lock": False, "threading.RLock": True}


@dataclasses.dataclass(frozen=True)
class LockDecl:
    """One lock creation site."""

    site: str  # module:line — the dynamic harness's key
    rel: str
    line: int
    reentrant: bool
    #: (owner, attr): owner is a class qname for `self.attr = Lock()`,
    #: the module name for module-level `NAME = Lock()`, else None
    owner: Optional[str]
    attr: Optional[str]


def _creation_sites(
    files: Sequence[SourceFile], graph: flow.FlowGraph
) -> List[LockDecl]:
    out: List[LockDecl] = []
    for rel, tree, _src in files:
        if rel.startswith(_EXCLUDED_PREFIX) or not rel.startswith(
            "dag_rider_tpu/"
        ):
            continue
        mod = graph.modules[flow.module_name(rel)]
        cls_stack: List[Tuple[ast.ClassDef, str]] = []

        def visit(node: ast.AST, cls_qn: Optional[str]) -> None:
            for child in ast.iter_child_nodes(node):
                child_cls = cls_qn
                if isinstance(child, ast.ClassDef):
                    child_cls = f"{mod.name}.{child.name}"
                if isinstance(child, ast.Assign) and isinstance(
                    child.value, ast.Call
                ):
                    d = flow.dotted(child.value.func)
                    expanded = mod.expand(d) if d else None
                    if expanded in _LOCK_CTORS:
                        owner = attr = None
                        if len(child.targets) == 1:
                            tgt = child.targets[0]
                            if (
                                isinstance(tgt, ast.Attribute)
                                and isinstance(tgt.value, ast.Name)
                                and tgt.value.id == "self"
                                and cls_qn is not None
                            ):
                                owner, attr = cls_qn, tgt.attr
                            elif isinstance(tgt, ast.Name):
                                owner, attr = mod.name, tgt.id
                        out.append(
                            LockDecl(
                                f"{mod.name}:{child.value.lineno}",
                                rel,
                                child.value.lineno,
                                _LOCK_CTORS[expanded],
                                owner,
                                attr,
                            )
                        )
                visit(child, child_cls)

        visit(tree, None)
        del cls_stack
    return out


class _LockIndex:
    """Resolve a `with <expr>:` context expression to a LockDecl."""

    def __init__(self, decls: Sequence[LockDecl], graph: flow.FlowGraph):
        self.graph = graph
        #: (owner, attr) -> decl
        self.by_owner: Dict[Tuple[str, str], LockDecl] = {
            (d.owner, d.attr): d
            for d in decls
            if d.owner is not None and d.attr is not None
        }

    def _class_lock(self, cls_qn: str, attr: str) -> Optional[LockDecl]:
        """Walk the package base chain for the lock's declaring class."""
        stack, seen = [cls_qn], set()
        while stack:
            c = stack.pop()
            if c in seen:
                continue
            seen.add(c)
            decl = self.by_owner.get((c, attr))
            if decl is not None:
                return decl
            info = self.graph.classes.get(c)
            if info is not None:
                stack.extend(info.bases)
        return None

    def resolve(
        self, expr: ast.AST, fi: flow.FuncInfo
    ) -> Optional[LockDecl]:
        d = flow.dotted(expr)
        if d is None:
            return None
        head, _, rest = d.partition(".")
        if head == "self" and fi.cls is not None and rest and "." not in rest:
            return self._class_lock(fi.cls, rest)
        if "." not in d:
            return self.by_owner.get((fi.module, d))
        # self.attr._lock — type the attr through the flow graph
        if head == "self" and fi.cls is not None:
            parts = rest.split(".")
            if len(parts) == 2:
                info = self.graph.classes.get(fi.cls)
                if info is not None:
                    owner = info.attr_types.get(parts[0])
                    if owner is not None:
                        return self._class_lock(owner, parts[1])
        return None


def build_lock_graph(
    files: Sequence[SourceFile], graph: Optional[flow.FlowGraph] = None
) -> Tuple[
    List[LockDecl],
    Dict[str, Set[str]],
    List[Finding],
]:
    """(creation sites, order edges site->sites, structural findings).

    Structural findings cover the statically-certain violations found
    while building: nested ``with`` on the same non-reentrant lock.
    """
    if graph is None:
        graph = flow.build(files)
    decls = _creation_sites(files, graph)
    index = _LockIndex(decls, graph)
    findings: List[Finding] = []

    # direct acquisitions per function
    direct: Dict[str, List[Tuple[ast.With, LockDecl]]] = {}
    for qn, fi in graph.functions.items():
        if fi.rel.startswith(_EXCLUDED_PREFIX) or not fi.rel.startswith(
            "dag_rider_tpu/"
        ):
            continue
        acqs: List[Tuple[ast.With, LockDecl]] = []
        for node in ast.walk(fi.node):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    decl = index.resolve(item.context_expr, fi)
                    if decl is not None:
                        acqs.append((node, decl))
        if acqs:
            direct[qn] = acqs

    # closure: every lock any function in reachable(g) directly takes
    def closure_locks(qn: str) -> Set[str]:
        out: Set[str] = set()
        for h in graph.reachable(qn):
            for _w, decl in direct.get(h, ()):
                out.add(decl.site)
        return out

    # call-site lookup by AST node identity, per function
    edges: Dict[str, Set[str]] = {}

    def add_edge(a: str, b: str) -> None:
        if a != b:
            edges.setdefault(a, set()).add(b)

    for qn, acqs in direct.items():
        fi = graph.functions[qn]
        sites_by_node = {
            id(cs.node): cs.target for cs in graph.callsites.get(qn, ())
        }
        for wnode, decl in acqs:
            for inner in ast.walk(wnode):
                if inner is wnode:
                    continue
                if isinstance(inner, (ast.With, ast.AsyncWith)):
                    for item in inner.items:
                        idecl = index.resolve(item.context_expr, fi)
                        if idecl is None:
                            continue
                        if idecl.site == decl.site and not decl.reentrant:
                            findings.append(
                                Finding(
                                    CHECKER,
                                    fi.rel,
                                    inner.lineno,
                                    f"nested with on non-reentrant lock "
                                    f"{decl.site} inside its own critical "
                                    "section — guaranteed self-deadlock",
                                )
                            )
                        add_edge(decl.site, idecl.site)
                elif isinstance(inner, ast.Call):
                    target = sites_by_node.get(id(inner))
                    if target is None:
                        continue
                    for b in closure_locks(target):
                        add_edge(decl.site, b)
    return decls, edges, findings


def _find_cycle(edges: Dict[str, Set[str]]) -> Optional[List[str]]:
    """First cycle in the order graph (as a closed site path), or None."""
    WHITE, GRAY, BLACK = 0, 1, 2
    color: Dict[str, int] = {}
    path: List[str] = []

    def dfs(u: str) -> Optional[List[str]]:
        color[u] = GRAY
        path.append(u)
        for v in sorted(edges.get(u, ())):
            c = color.get(v, WHITE)
            if c == GRAY:
                i = path.index(v)
                return path[i:] + [v]
            if c == WHITE:
                got = dfs(v)
                if got is not None:
                    return got
        path.pop()
        color[u] = BLACK
        return None

    for node in sorted(edges):
        if color.get(node, WHITE) == WHITE:
            got = dfs(node)
            if got is not None:
                return got
    return None


def lock_sites(files: Sequence[SourceFile]) -> Dict[str, LockDecl]:
    """site-key -> decl, for the dynamic/static cross-validation test."""
    graph = flow.build(files)
    return {d.site: d for d in _creation_sites(files, graph)}


def run(
    files: Sequence[SourceFile],
    repo_root: str,
    graph: Optional[flow.FlowGraph] = None,
) -> List[Finding]:
    decls, edges, findings = build_lock_graph(files, graph)
    cycle = _find_cycle(edges)
    while cycle is not None:
        rel = line = None
        by_site = {d.site: d for d in decls}
        head = by_site.get(cycle[0])
        rel = head.rel if head else "dag_rider_tpu"
        line = head.line if head else 0
        findings.append(
            Finding(
                CHECKER,
                rel,
                line,
                "static lock-order cycle (deadlock possible): "
                + " -> ".join(cycle),
            )
        )
        # break the reported cycle and look for independent ones
        edges[cycle[-2]].discard(cycle[-1])
        cycle = _find_cycle(edges)
    return findings
