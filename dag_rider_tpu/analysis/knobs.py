"""Knob discipline: every DAGRIDER_* env read routes through config.py.

Three rules:

1. No direct ``os.environ`` / ``os.getenv`` read of a ``DAGRIDER_*``
   name outside ``dag_rider_tpu/config.py``. bench.py may read the
   ``DAGRIDER_BENCH_*`` namespace directly (bench-local tuning the
   package never sees) but nothing else.
2. Every ``DAGRIDER_*`` literal passed to a config ``env_*`` accessor
   must be registered in ``config.KNOBS`` (the accessors also enforce
   this at runtime; the static rule catches dead/typo'd reads on paths
   tests never execute).
3. Every registered knob must appear in the README knob table — a knob
   an operator cannot discover is not a knob, it is a trap.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Sequence

from dag_rider_tpu.analysis.core import Finding, SourceFile
from dag_rider_tpu.config import KNOBS

CHECKER = "knobs"

_CONFIG_PATH = "dag_rider_tpu/config.py"
_ACCESSORS = {
    "env_flag",
    "env_str",
    "env_choice",
    "env_int",
    "env_opt_int",
    "env_float",
}


def _literal(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _is_os_environ(node: ast.AST) -> bool:
    """Matches ``os.environ`` (Attribute) or a bare ``environ`` name."""
    if isinstance(node, ast.Attribute) and node.attr == "environ":
        return True
    return isinstance(node, ast.Name) and node.id == "environ"


def _direct_env_read(node: ast.AST) -> Optional[ast.AST]:
    """The name-expression node of a direct env read, if ``node`` is one:
    ``os.environ.get(X, ...)``, ``os.environ[X]``, ``os.getenv(X, ...)``.
    """
    if isinstance(node, ast.Call):
        f = node.func
        if (
            isinstance(f, ast.Attribute)
            and f.attr == "get"
            and _is_os_environ(f.value)
            and node.args
        ):
            return node.args[0]
        if (
            isinstance(f, ast.Attribute)
            and f.attr == "getenv"
            and node.args
        ):
            return node.args[0]
        if isinstance(f, ast.Name) and f.id == "getenv" and node.args:
            return node.args[0]
    if isinstance(node, ast.Subscript) and _is_os_environ(node.value):
        return node.slice
    return None


def run(files: Sequence[SourceFile], repo_root: str) -> List[Finding]:
    findings: List[Finding] = []
    for rel, tree, _src in files:
        in_config = rel == _CONFIG_PATH
        in_bench = rel == "bench.py"
        for node in ast.walk(tree):
            name_node = _direct_env_read(node)
            if name_node is not None and not in_config:
                name = _literal(name_node)
                if name is None or not name.startswith("DAGRIDER_"):
                    continue
                if in_bench and name.startswith("DAGRIDER_BENCH_"):
                    continue
                findings.append(
                    Finding(
                        CHECKER,
                        rel,
                        node.lineno,
                        f"direct environment read of {name} — route it "
                        "through a dag_rider_tpu.config env_* accessor",
                    )
                )
                continue
            # accessor calls naming unregistered knobs
            if isinstance(node, ast.Call):
                f = node.func
                fname = (
                    f.attr
                    if isinstance(f, ast.Attribute)
                    else f.id if isinstance(f, ast.Name) else None
                )
                if fname in _ACCESSORS and node.args:
                    name = _literal(node.args[0])
                    if (
                        name is not None
                        and name.startswith("DAGRIDER_")
                        and name not in KNOBS
                    ):
                        findings.append(
                            Finding(
                                CHECKER,
                                rel,
                                node.lineno,
                                f"{fname}({name!r}) names a knob that is "
                                "not registered in config.KNOBS",
                            )
                        )
    findings.extend(_check_readme(repo_root))
    return findings


def _check_readme(repo_root: str) -> List[Finding]:
    import os

    readme = os.path.join(repo_root, "README.md")
    if not os.path.exists(readme):
        return [Finding(CHECKER, "README.md", 0, "README.md is missing")]
    with open(readme, "r", encoding="utf-8") as fh:
        text = fh.read()
    out = []
    for name in sorted(KNOBS):
        if name not in text:
            out.append(
                Finding(
                    CHECKER,
                    "README.md",
                    0,
                    f"registered knob {name} is not documented in the "
                    "README knob table",
                )
            )
    return out
