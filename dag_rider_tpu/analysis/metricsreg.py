"""Metrics discipline: every counter bumped must be registered.

``Metrics.counters`` is a defaultdict — a typo'd name silently mints a
new counter that no dashboard, test, or BASELINE row will ever look
at. The rule: any literal counter name passed to ``*.inc("...")`` /
``*._inc("...")`` or indexed as ``*.counters["..."]`` (read or write)
must appear in ``utils.metrics.KNOWN_COUNTERS``. Non-literal names
(merge loops forwarding existing counters) are out of scope.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Sequence

from dag_rider_tpu.analysis.core import Finding, SourceFile
from dag_rider_tpu.utils.metrics import KNOWN_COUNTERS

CHECKER = "metrics"


def _literal(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _counter_name(node: ast.AST) -> Optional[str]:
    """The literal counter name this node bumps/reads, if any."""
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
        if node.func.attr in ("inc", "_inc") and node.args:
            return _literal(node.args[0])
        # counters.get("name") / counters.get("name", 0)
        if (
            node.func.attr == "get"
            and isinstance(node.func.value, ast.Attribute)
            and node.func.value.attr == "counters"
            and node.args
        ):
            return _literal(node.args[0])
    if isinstance(node, ast.Subscript):
        v = node.value
        if isinstance(v, ast.Attribute) and v.attr == "counters":
            return _literal(node.slice)
    return None


def run(files: Sequence[SourceFile], repo_root: str) -> List[Finding]:
    findings: List[Finding] = []
    for rel, tree, _src in files:
        if rel == "dag_rider_tpu/utils/metrics.py":
            continue  # the registry itself
        for node in ast.walk(tree):
            name = _counter_name(node)
            if name is not None and name not in KNOWN_COUNTERS:
                findings.append(
                    Finding(
                        CHECKER,
                        rel,
                        node.lineno,
                        f"counter {name!r} is not registered in "
                        "utils.metrics.KNOWN_COUNTERS",
                    )
                )
    return findings
