"""Event discipline: every event emitted must be registered.

``EventLog.event`` accepts any name — a typo'd event silently creates a
record that no trace report, flight-recorder trigger, or chrome export
row will ever join on (the causal chains in ``obs.report`` join on
EXACT event names; a misspelt ``tx_delivr`` just drops the transaction
from every latency percentile). The rule, mirroring the metrics
checker: any literal event name passed to ``*.event("...")`` must
appear in ``utils.slog.KNOWN_EVENTS``. Non-literal names (forwarding
loops) are out of scope.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Sequence

from dag_rider_tpu.analysis.core import Finding, SourceFile
from dag_rider_tpu.utils.slog import KNOWN_EVENTS

CHECKER = "events"


def _literal(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _event_name(node: ast.AST) -> Optional[str]:
    """The literal event name this node emits, if any."""
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
        if node.func.attr == "event" and node.args:
            return _literal(node.args[0])
    return None


def run(files: Sequence[SourceFile], repo_root: str) -> List[Finding]:
    findings: List[Finding] = []
    for rel, tree, _src in files:
        if rel == "dag_rider_tpu/utils/slog.py":
            continue  # the registry itself
        for node in ast.walk(tree):
            name = _event_name(node)
            if name is not None and name not in KNOWN_EVENTS:
                findings.append(
                    Finding(
                        CHECKER,
                        rel,
                        node.lineno,
                        f"event {name!r} is not registered in "
                        "utils.slog.KNOWN_EVENTS",
                    )
                )
    return findings
