"""Determinism discipline: the consensus stack must be a pure function
of its inputs.

Three rules over the package (bench.py is exempt — measuring wall time
is its job):

1. No ``time.time()`` *calls* anywhere in the package. Monotonic /
   perf-counter clocks are fine (latency measurement), and passing
   ``time.time`` as an injectable default *reference* is the approved
   pattern (transport/net.py) — only an actual call hardwires the wall
   clock. Justified uses (observability timestamps) go on the
   allowlist with a reason.
2. No unseeded RNG: module-level ``random.<fn>()`` calls, zero-arg
   ``random.Random()``, and ``np.random.<fn>()`` (the legacy global
   generator) are all process-global, seed-uncontrolled state.
   ``random.Random(seed)`` / ``np.random.default_rng(seed)`` with an
   explicit seed are fine.
3. No iteration-order dependence on ``consensus/`` commit paths:
   iterating a set expression (or a ``self`` attribute initialized as
   a set) feeds hash-randomized order into code whose outputs must be
   byte-identical across processes. Wrap in ``sorted(...)`` or use a
   list/dict (insertion-ordered).
"""

from __future__ import annotations

import ast
from typing import List, Optional, Sequence, Set

from dag_rider_tpu.analysis.core import Finding, SourceFile

CHECKER = "determinism"

_UNSEEDED_RANDOM_FNS = {
    "random",
    "randint",
    "randrange",
    "uniform",
    "choice",
    "choices",
    "shuffle",
    "sample",
    "getrandbits",
    "gauss",
    "seed",
}


def _dotted(node: ast.AST) -> Optional[str]:
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _set_attrs_of_file(tree: ast.Module) -> Set[str]:
    """self attributes initialized as set()/frozenset()/set literals in
    any __init__ of the file."""
    attrs: Set[str] = set()
    for node in ast.walk(tree):
        if not (isinstance(node, ast.FunctionDef) and node.name == "__init__"):
            continue
        for stmt in ast.walk(node):
            if not isinstance(stmt, ast.Assign):
                continue
            val = stmt.value
            is_set = isinstance(val, (ast.Set, ast.SetComp)) or (
                isinstance(val, ast.Call)
                and isinstance(val.func, ast.Name)
                and val.func.id in ("set", "frozenset")
            )
            if not is_set:
                continue
            for t in stmt.targets:
                if (
                    isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                ):
                    attrs.add(t.attr)
    return attrs


def _is_set_expr(node: ast.AST, set_attrs: Set[str]) -> bool:
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    if (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Name)
        and node.func.id in ("set", "frozenset")
    ):
        return True
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
        and node.attr in set_attrs
    ):
        return True
    return False


def run(files: Sequence[SourceFile], repo_root: str) -> List[Finding]:
    findings: List[Finding] = []
    for rel, tree, _src in files:
        if rel == "bench.py":
            continue
        in_consensus = rel.startswith("dag_rider_tpu/consensus/")
        set_attrs = _set_attrs_of_file(tree) if in_consensus else set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                dotted = _dotted(node.func)
                if dotted == "time.time":
                    findings.append(
                        Finding(
                            CHECKER,
                            rel,
                            node.lineno,
                            "wall-clock time.time() call — use an "
                            "injectable clock / time.monotonic, or "
                            "allowlist with a reason",
                        )
                    )
                elif dotted is not None:
                    parts = dotted.split(".")
                    if (
                        len(parts) == 2
                        and parts[0] == "random"
                        and parts[1] in _UNSEEDED_RANDOM_FNS
                    ):
                        findings.append(
                            Finding(
                                CHECKER,
                                rel,
                                node.lineno,
                                f"unseeded module-level {dotted}() — use "
                                "a random.Random(seed) instance",
                            )
                        )
                    elif dotted == "random.Random" and not (
                        node.args or node.keywords
                    ):
                        findings.append(
                            Finding(
                                CHECKER,
                                rel,
                                node.lineno,
                                "random.Random() without a seed",
                            )
                        )
                    elif (
                        len(parts) == 3
                        and parts[0] in ("np", "numpy")
                        and parts[1] == "random"
                        and parts[2] != "default_rng"
                    ):
                        findings.append(
                            Finding(
                                CHECKER,
                                rel,
                                node.lineno,
                                f"legacy global-state {dotted}() — use "
                                "np.random.default_rng(seed)",
                            )
                        )
                    elif dotted in (
                        "np.random.default_rng",
                        "numpy.random.default_rng",
                    ) and not (node.args or node.keywords):
                        findings.append(
                            Finding(
                                CHECKER,
                                rel,
                                node.lineno,
                                "np.random.default_rng() without a seed",
                            )
                        )
            if in_consensus:
                iters = []
                if isinstance(node, ast.For):
                    iters.append(node.iter)
                elif isinstance(
                    node, (ast.ListComp, ast.SetComp, ast.GeneratorExp,
                           ast.DictComp)
                ):
                    iters.extend(g.iter for g in node.generators)
                for it in iters:
                    if _is_set_expr(it, set_attrs):
                        findings.append(
                            Finding(
                                CHECKER,
                                rel,
                                it.lineno,
                                "iteration over a set on a consensus "
                                "path — order is hash-randomized; wrap "
                                "in sorted(...) or use an "
                                "insertion-ordered container",
                            )
                        )
    return findings
