"""driderlint plumbing: findings, file discovery, allowlist semantics.

A checker is a module with a ``CHECKER`` name and a
``run(files, repo_root) -> List[Finding]`` function, where ``files`` is
the list of ``(relpath, ast_tree, source)`` triples :func:`discover`
produces. Checkers take the parsed file list rather than re-reading the
tree so the planted-violation tests can feed synthetic files through
the exact production code path.

Allowlist semantics (the "zero unexplained entries" rule): every
:class:`Allow` must carry a non-empty reason; an entry that suppresses
nothing is itself a failure (dead allowlist lines are how real
violations sneak back in under an old excuse).
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import List, Optional, Sequence, Set, Tuple

#: (relpath-with-forward-slashes, parsed tree, source text)
SourceFile = Tuple[str, ast.Module, str]


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at a source location."""

    checker: str
    path: str
    line: int
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.checker}] {self.message}"


@dataclasses.dataclass(frozen=True)
class Allow:
    """One allowlisted (suppressed) finding.

    Matches any finding with the same ``checker`` and ``path`` whose
    message contains ``contains``. ``reason`` is mandatory and shown in
    the report — an allowlist entry is a documented triage decision,
    not an off switch.
    """

    checker: str
    path: str
    contains: str
    reason: str


def discover(repo_root: str) -> List[SourceFile]:
    """Every .py file of the package plus the repo-root bench.py, in a
    deterministic order."""
    files: List[SourceFile] = []
    pkg = os.path.join(repo_root, "dag_rider_tpu")
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames.sort()
        if "__pycache__" in dirpath:
            continue
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            full = os.path.join(dirpath, fn)
            rel = os.path.relpath(full, repo_root).replace(os.sep, "/")
            with open(full, "r", encoding="utf-8") as fh:
                src = fh.read()
            files.append((rel, ast.parse(src, filename=rel), src))
    bench = os.path.join(repo_root, "bench.py")
    if os.path.exists(bench):
        with open(bench, "r", encoding="utf-8") as fh:
            src = fh.read()
        files.append(("bench.py", ast.parse(src, filename="bench.py"), src))
    return files


def apply_allowlist(
    findings: Sequence[Finding], allows: Sequence[Allow]
) -> Tuple[List[Finding], List[Finding], List[Allow]]:
    """Split findings into (kept, suppressed) and return the allowlist
    entries that matched nothing (each of which is a failure)."""
    kept: List[Finding] = []
    suppressed: List[Finding] = []
    used: Set[int] = set()
    for f in findings:
        hit = None
        for i, a in enumerate(allows):
            if (
                a.checker == f.checker
                and a.path == f.path
                and a.contains in f.message
            ):
                hit = i
                break
        if hit is None:
            kept.append(f)
        else:
            used.add(hit)
            suppressed.append(f)
    unused = [a for i, a in enumerate(allows) if i not in used]
    return kept, suppressed, unused


def run_static(
    repo_root: str, files: Optional[Sequence[SourceFile]] = None
) -> Tuple[List[Finding], List[Finding], List[Allow]]:
    """Run every static checker over the tree and apply the allowlist.

    Returns (kept, suppressed, unused_allows); a clean tree is
    ``([], suppressed, [])``.
    """
    from dag_rider_tpu.analysis import (
        allowlist,
        determinism,
        events,
        flow,
        jitpure,
        knobs,
        ladder,
        locks,
        metricsreg,
        oracle,
        release,
        shapes,
    )

    if files is None:
        files = discover(repo_root)
    findings: List[Finding] = []
    for checker in (knobs, determinism, oracle, jitpure, metricsreg, events):
        findings.extend(checker.run(files, repo_root))
    # v2 interprocedural checkers share ONE flow-graph build (the graph
    # is the expensive half of their runtime)
    graph = flow.build(files)
    for checker in (locks, release, shapes, ladder):
        findings.extend(checker.run(files, repo_root, graph=graph))
    bad_allows = [a for a in allowlist.ALLOWS if not a.reason.strip()]
    kept, suppressed, unused = apply_allowlist(findings, allowlist.ALLOWS)
    for a in bad_allows:
        kept.append(
            Finding(
                "allowlist",
                a.path,
                0,
                f"allowlist entry {a.checker}:{a.contains!r} has no reason",
            )
        )
    return kept, suppressed, unused
