"""Degradation-ladder totality over the interprocedural flow graph.

Every knob-gated fast path in the repo is paired with a byte-identical
oracle fallback — that pairing is the safety argument for shipping the
fast path at all (vector pump → scalar drain, aggregated certificate →
per-vertex verifies, span → per-round certificates, device MSM/pairing
→ host bigint). The pairing is also invisible to per-function lint: it
lives in the call graph, as an edge from the seam function to the
oracle. A future refactor can strand a fast path — delete the fallback
branch, rename the oracle, orphan the seam — and every test still
passes, because tests pin one knob value at a time.

This checker makes the ladder structure itself a gated invariant.
:data:`LADDERS` declares each rung as (knob, entry seam, fast path,
oracle); the checker proves, on the package flow graph:

* the knob is still a registered config knob (a deleted knob with a
  live ladder entry is a stale declaration — also flagged);
* entry, fast, and oracle functions all still exist;
* BOTH the fast path and the oracle are reachable from the entry seam
  (the degradation edge is intact, not just the fast edge);
* the fast path has at least one caller — a stranded fast path is dead
  weight that silently stops being exercised.

The declarations are deliberately explicit qnames, not discovered: the
point is that a PR deleting a rung must *edit this table* (or fail
tier1-analysis), turning a silent strand into a reviewed decision.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

from dag_rider_tpu.analysis import flow
from dag_rider_tpu.analysis.core import Finding, SourceFile

CHECKER = "ladder"

_P = "dag_rider_tpu.consensus.process.Process."
_C = "dag_rider_tpu.verifier.cert.CertVerifier."


@dataclasses.dataclass(frozen=True)
class Ladder:
    """One degradation rung: entry branches on knob between fast and
    oracle (the oracle may BE the entry's own body — pass entry)."""

    knob: str
    entry: str
    fast: str
    oracle: str


#: the shipped ladder table — edit alongside any seam refactor
LADDERS: Tuple[Ladder, ...] = (
    # vector pump: one jnp round-batch drain vs the scalar Python walk
    Ladder(
        "DAGRIDER_PUMP",
        _P + "_drain_buffer",
        _P + "_drain_buffer_vector",
        _P + "_drain_buffer",
    ),
    # aggregated round certificate vs per-vertex verifies (reject path
    # degrades the whole round back to the per-vertex oracle)
    Ladder(
        "DAGRIDER_CERT",
        _P + "_cert_step",
        _P + "_apply_certificate",
        _P + "_degrade_cert_round",
    ),
    # cert-of-certs span vs per-round certificates
    Ladder(
        "DAGRIDER_CERT_SPAN",
        _P + "_cert_step",
        _P + "_apply_span",
        _P + "_apply_certificate",
    ),
    # device MSM vs host bigint sum
    Ladder(
        "DAGRIDER_CERT_MSM",
        _C + "_sum_points",
        "dag_rider_tpu.ops.bls_msm.sum_points",
        "dag_rider_tpu.crypto.bls12381.g1_sum",
    ),
    # sharded (mesh) MSM vs host bigint sum
    Ladder(
        "DAGRIDER_CERT_MSM",
        _C + "_sum_points",
        "dag_rider_tpu.parallel.msm.ShardedMSM.sum_points",
        "dag_rider_tpu.crypto.bls12381.g1_sum",
    ),
    # device pairing product vs host pairing
    Ladder(
        "DAGRIDER_CERT_PAIR",
        _C + "_pairing_check",
        "dag_rider_tpu.ops.bls_pairing.multi_pairing_check",
        "dag_rider_tpu.crypto.bls12381.multi_pairing_check",
    ),
    # pipelined per-round wave attempts vs the 4-round boundary sweep
    Ladder(
        "DAGRIDER_WAVE_PIPELINE",
        _P + "step",
        _P + "_try_waves_pipelined",
        _P + "_try_advance",
    ),
    # eager speculative surface vs the coin-ordered canonical walk (the
    # walk is also the reconciliation oracle for what eager surfaced)
    Ladder(
        "DAGRIDER_EAGER_DELIVER",
        _P + "_try_wave",
        _P + "_eager_surface",
        _P + "_order_vertices",
    ),
    # sharded dissemination lanes vs inline payloads (sub-threshold,
    # magic-aliasing and under-quorum blocks all fall back to inline)
    Ladder(
        "DAGRIDER_LANES",
        _P + "submit",
        _P + "_submit_via_lanes",
        _P + "_submit_inline",
    ),
    # epoch reconfiguration: delivery-time boundary scan vs the static-
    # membership no-op seam (epoch off = fixed validator set forever)
    Ladder(
        "DAGRIDER_EPOCH",
        _P + "_epoch_note_delivery",
        _P + "_epoch_scan_chunk",
        _P + "_epoch_static",
    ),
)


def _short(qn: str) -> str:
    return qn.rsplit(".", 1)[-1]


def check_ladders(
    graph: flow.FlowGraph, ladders: Sequence[Ladder]
) -> List[Finding]:
    from dag_rider_tpu.config import KNOBS

    out: List[Finding] = []

    def fnd(rel: str, line: int, msg: str) -> None:
        out.append(Finding(CHECKER, rel, line, msg))

    for lad in ladders:
        entry = graph.functions.get(lad.entry)
        where = (entry.rel, entry.lineno) if entry else (
            "dag_rider_tpu/analysis/ladder.py",
            0,
        )
        if lad.knob not in KNOBS:
            fnd(
                *where,
                f"ladder {lad.knob}: knob is not registered in "
                "config.KNOBS (stale ladder declaration or deleted knob)",
            )
        missing = [
            q
            for q in (lad.entry, lad.fast, lad.oracle)
            if q not in graph.functions
        ]
        if missing:
            fnd(
                *where,
                f"ladder {lad.knob}: missing function(s) "
                + ", ".join(missing)
                + " — seam renamed or deleted without editing LADDERS",
            )
            continue
        reach = graph.reachable(lad.entry)
        if lad.fast not in reach:
            fnd(
                *where,
                f"ladder {lad.knob}: fast path {_short(lad.fast)} not "
                f"reachable from entry {_short(lad.entry)} — fast edge "
                "severed",
            )
        if lad.oracle not in reach:
            fnd(
                *where,
                f"ladder {lad.knob}: oracle {_short(lad.oracle)} not "
                f"reachable from entry {_short(lad.entry)} — degradation "
                "edge severed; the fast path has no fallback",
            )
        if lad.fast != lad.entry and not graph.callers_of(lad.fast):
            fnd(
                *where,
                f"ladder {lad.knob}: fast path {_short(lad.fast)} has "
                "no callers — stranded fast path",
            )
    return out


def run(
    files: Sequence[SourceFile],
    repo_root: str,
    graph: Optional[flow.FlowGraph] = None,
    ladders: Sequence[Ladder] = LADDERS,
) -> List[Finding]:
    if graph is None:
        graph = flow.build(files)
    return check_ladders(graph, ladders)
