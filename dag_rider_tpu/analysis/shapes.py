"""jit shape-stability: recompile hazards in traced kernels.

The device hot path stays fast only while every jitted program in
``ops/`` + ``parallel/`` compiles once per bucket shape and never
falls back to the interpreter. Three hazard classes defeat that
silently — the code still returns right answers, just recompiled or
synced per call:

* **Traced-value Python control flow.** ``if``/``while``/``assert``
  on a value derived from a traced argument either raises
  ``TracerBoolConversionError`` at trace time or, with
  ``static_argnums`` misuse, silently keys a retrace per value. The
  decision belongs in ``lax.cond``/``jnp.where``/``lax.while_loop``.
* **Host round-trips.** ``.item()``/``.tolist()``/``int()``/
  ``float()``/``np.asarray()`` on a tracer forces a device sync per
  call inside the traced region (or fails to trace at all).
* **Unhashable static args.** A ``static_argnames`` parameter keys
  the jit cache by value; passing a ``list``/``dict``/``set`` display
  at a call site is a ``TypeError`` the first time that path runs.

The checker is a one-pass abstract interpreter over each jitted
body with a three-point taint lattice ``TRACED > SHAPE > STATIC``:
parameters start TRACED (static ones STATIC), ``x.shape``/``len(x)``
of a TRACED value is SHAPE (trace-time constant — branching on it is
the *intended* bucketing idiom and is not flagged; a ``while`` on it
is flagged, because shape-driven iteration counts unroll a different
program per shape class). Everything else propagates the max of its
inputs. ``is``/``is not`` comparisons and ``isinstance`` stay STATIC
(trace-time identity on optionals is standard jit idiom).

jit spellings recognized are jitpure's: ``@jax.jit``, ``@jit``,
``@functools.partial(jax.jit, ...)``, and ``name = jax.jit(fn)``
rebinding. Static-arg call-site checks resolve through the
interprocedural flow graph, so a bad call in ``verifier/`` against a
kernel in ``ops/`` is still caught.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Sequence, Set, Tuple

from dag_rider_tpu.analysis import flow
from dag_rider_tpu.analysis.core import Finding, SourceFile
from dag_rider_tpu.analysis.jitpure import _is_jit_expr

CHECKER = "shapes"

_SCOPES = ("dag_rider_tpu/ops/", "dag_rider_tpu/parallel/")

STATIC, SHAPE, TRACED = 0, 1, 2

#: attribute reads that turn a tracer into a trace-time constant
_SHAPE_ATTRS = frozenset({"shape", "ndim", "size", "dtype"})

#: calls that force a host round-trip when fed a tracer
_SYNC_CALLS = frozenset({"int", "float", "bool", "complex"})
_SYNC_METHODS = frozenset({"item", "tolist", "block_until_ready"})
_SYNC_NP = frozenset({"np.asarray", "np.array", "numpy.asarray", "numpy.array"})

_UNHASHABLE_DISPLAYS = (ast.List, ast.Dict, ast.Set, ast.ListComp,
                        ast.DictComp, ast.SetComp)


@dataclasses.dataclass
class _JitFn:
    fi: flow.FuncInfo
    static_names: Set[str]


def _static_params(fn: ast.AST, jit_call: Optional[ast.Call]) -> Set[str]:
    """Parameter names keyed statically, from static_argnames/nums."""
    names: Set[str] = set()
    params = flow.param_names(fn)
    if jit_call is None:
        return names
    for kw in jit_call.keywords:
        if kw.arg == "static_argnames":
            for sub in ast.walk(kw.value):
                if isinstance(sub, ast.Constant) and isinstance(
                    sub.value, str
                ):
                    names.add(sub.value)
        elif kw.arg == "static_argnums":
            for sub in ast.walk(kw.value):
                if isinstance(sub, ast.Constant) and isinstance(
                    sub.value, int
                ):
                    if 0 <= sub.value < len(params):
                        names.add(params[sub.value])
    return names


def _jit_call_of(expr: ast.AST) -> Optional[ast.Call]:
    """The Call node carrying static_arg* keywords, if any."""
    if isinstance(expr, ast.Call):
        f = flow.dotted(expr.func)
        if f in ("functools.partial", "partial") and expr.args:
            return expr if _is_jit_expr(expr.args[0]) else None
        if _is_jit_expr(expr.func):
            return expr
    return None


def _jitted_in_module(
    rel: str, tree: ast.Module, graph: flow.FlowGraph
) -> List[_JitFn]:
    mod_name = flow.module_name(rel)
    out: List[_JitFn] = []
    defs: Dict[str, ast.AST] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs[node.name] = node
    claimed: Dict[str, Optional[ast.Call]] = {}
    for name, fn in defs.items():
        for dec in fn.decorator_list:
            if _is_jit_expr(dec):
                claimed[name] = _jit_call_of(dec)
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            if _is_jit_expr(node.value.func) and node.value.args:
                arg = node.value.args[0]
                if isinstance(arg, ast.Name) and arg.id in defs:
                    claimed.setdefault(arg.id, _jit_call_of(node.value))
    for name, jc in claimed.items():
        fn = defs[name]
        qn = f"{mod_name}.{name}"
        fi = graph.functions.get(qn) or flow.FuncInfo(
            qn, rel, mod_name, None, name, fn, fn.lineno
        )
        out.append(_JitFn(fi, _static_params(fn, jc)))
    return out


class _Interp:
    """One jitted body; findings accumulate in self.out."""

    def __init__(self, rel: str, fname: str, out: List[Finding]):
        self.rel = rel
        self.fname = fname
        self.out = out

    def flag(self, node: ast.AST, msg: str) -> None:
        self.out.append(
            Finding(
                CHECKER, self.rel, node.lineno, f"{msg} in jitted "
                f"{self.fname}()"
            )
        )

    # -- expression taint --------------------------------------------------
    def taint(self, node: ast.AST, env: Dict[str, int]) -> int:
        if node is None or isinstance(node, ast.Constant):
            return STATIC
        if isinstance(node, ast.Name):
            return env.get(node.id, STATIC)
        if isinstance(node, ast.Attribute):
            base = self.taint(node.value, env)
            if node.attr in _SHAPE_ATTRS and base == TRACED:
                return SHAPE
            return base
        if isinstance(node, ast.Call):
            return self._call_taint(node, env)
        if isinstance(node, ast.Compare):
            if all(
                isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops
            ):
                return STATIC
            ts = [self.taint(node.left, env)] + [
                self.taint(c, env) for c in node.comparators
            ]
            return max(ts)
        if isinstance(node, ast.IfExp):
            t = self.taint(node.test, env)
            if t == TRACED:
                self.flag(
                    node,
                    "Python conditional expression on a traced value "
                    "(use jnp.where)",
                )
            return max(
                self.taint(node.body, env), self.taint(node.orelse, env)
            )
        if isinstance(node, (ast.Lambda,)):
            return STATIC
        kids = [
            self.taint(c, env)
            for c in ast.iter_child_nodes(node)
            if not isinstance(c, (ast.operator, ast.cmpop, ast.boolop,
                                  ast.unaryop, ast.expr_context))
        ]
        return max(kids, default=STATIC)

    def _call_taint(self, node: ast.Call, env: Dict[str, int]) -> int:
        d = flow.dotted(node.func)
        args = max(
            [self.taint(a, env) for a in node.args]
            + [self.taint(kw.value, env) for kw in node.keywords],
            default=STATIC,
        )
        if d in ("isinstance", "getattr", "hasattr", "callable", "type"):
            return STATIC
        if d == "len":
            return SHAPE if args == TRACED else args
        if d in _SYNC_CALLS and args == TRACED:
            self.flag(
                node,
                f"{d}() on a traced value — host round-trip / "
                "TracerConversion",
            )
            return STATIC
        if d in _SYNC_NP and args == TRACED:
            self.flag(
                node, f"{d}() on a traced value — host materialization"
            )
            return STATIC
        if isinstance(node.func, ast.Attribute):
            if node.func.attr in _SYNC_METHODS:
                if self.taint(node.func.value, env) == TRACED:
                    self.flag(
                        node,
                        f".{node.func.attr}() on a traced value — device "
                        "sync per call",
                    )
                    return STATIC
        if d is not None:
            head = d.partition(".")[0]
            if head in ("jnp", "jax", "lax"):
                return TRACED
        return args

    # -- statement walk ----------------------------------------------------
    def run_body(self, body: Sequence[ast.stmt], env: Dict[str, int]) -> None:
        # two passes: loop-carried taint stabilizes on the second
        for _ in range(2):
            for stmt in body:
                self.stmt(stmt, env)

    def _bind(self, tgt: ast.AST, t: int, env: Dict[str, int]) -> None:
        if isinstance(tgt, ast.Name):
            env[tgt.id] = t
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            for el in tgt.elts:
                self._bind(el, t, env)
        elif isinstance(tgt, ast.Starred):
            self._bind(tgt.value, t, env)

    def stmt(self, node: ast.stmt, env: Dict[str, int]) -> None:
        if isinstance(node, ast.Assign):
            t = self.taint(node.value, env)
            for tgt in node.targets:
                self._bind(tgt, t, env)
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            self._bind(node.target, self.taint(node.value, env), env)
        elif isinstance(node, ast.AugAssign):
            t = self.taint(node.value, env)
            if isinstance(node.target, ast.Name):
                env[node.target.id] = max(
                    env.get(node.target.id, STATIC), t
                )
        elif isinstance(node, (ast.If, ast.While)):
            t = self.taint(node.test, env)
            if t == TRACED:
                kind = "if" if isinstance(node, ast.If) else "while"
                self.flag(
                    node,
                    f"Python {kind} on a traced value — trace-time "
                    "error or per-value retrace (use lax.cond/"
                    "lax.while_loop)",
                )
            elif t == SHAPE and isinstance(node, ast.While):
                self.flag(
                    node,
                    "Python while on a shape-derived bound — one "
                    "unrolled program per shape class (use "
                    "lax.fori_loop)",
                )
            self.run_body(node.body, env)
            self.run_body(node.orelse, env)
        elif isinstance(node, ast.For):
            t = self.taint(node.iter, env)
            if t == TRACED:
                self.flag(
                    node,
                    "Python for over a traced value — unrolls per "
                    "element (use lax.scan/lax.fori_loop)",
                )
            self._bind(node.target, t, env)
            self.run_body(node.body, env)
            self.run_body(node.orelse, env)
        elif isinstance(node, ast.Assert):
            if self.taint(node.test, env) == TRACED:
                self.flag(
                    node,
                    "assert on a traced value — trace-time error "
                    "(use checkify or a host-side check)",
                )
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            self.run_body(node.body, env)
        elif isinstance(node, ast.Try):
            self.run_body(node.body, env)
            for h in node.handlers:
                self.run_body(h.body, env)
            self.run_body(node.orelse, env)
            self.run_body(node.finalbody, env)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # scan/cond bodies close over tracers; their own params are
            # tracers too (carry/element slots)
            inner = dict(env)
            for p in flow.param_names(node):
                inner[p] = TRACED
            self.run_body(node.body, inner)
        elif isinstance(node, (ast.Return, ast.Expr)):
            if node.value is not None:
                self.taint(node.value, env)


def _check_jit_body(jf: _JitFn, out: List[Finding]) -> None:
    fn = jf.fi.node
    env: Dict[str, int] = {}
    for p in flow.param_names(fn):
        env[p] = STATIC if p in jf.static_names else TRACED
    interp = _Interp(jf.fi.rel, jf.fi.name, out)
    interp.run_body(fn.body, env)


def _check_static_callsites(
    jit_fns: Dict[str, _JitFn], graph: flow.FlowGraph, out: List[Finding]
) -> None:
    for qn, sites in graph.callsites.items():
        caller = graph.functions[qn]
        for cs in sites:
            jf = jit_fns.get(cs.target)
            if jf is None or not jf.static_names:
                continue
            params = flow.param_names(jf.fi.node)
            for i, a in enumerate(cs.node.args):
                name = params[i] if i < len(params) else None
                if name in jf.static_names and isinstance(
                    a, _UNHASHABLE_DISPLAYS
                ):
                    out.append(
                        Finding(
                            CHECKER,
                            caller.rel,
                            a.lineno,
                            f"unhashable static arg {name!r} passed to "
                            f"{jf.fi.name}() — jit cache key TypeError",
                        )
                    )
            for kw in cs.node.keywords:
                if kw.arg in jf.static_names and isinstance(
                    kw.value, _UNHASHABLE_DISPLAYS
                ):
                    out.append(
                        Finding(
                            CHECKER,
                            caller.rel,
                            kw.value.lineno,
                            f"unhashable static arg {kw.arg!r} passed to "
                            f"{jf.fi.name}() — jit cache key TypeError",
                        )
                    )


def run(
    files: Sequence[SourceFile],
    repo_root: str,
    graph: Optional[flow.FlowGraph] = None,
) -> List[Finding]:
    if graph is None:
        graph = flow.build(files)
    out: List[Finding] = []
    jit_fns: Dict[str, _JitFn] = {}
    for rel, tree, _src in files:
        if not rel.startswith(_SCOPES):
            continue
        for jf in _jitted_in_module(rel, tree, graph):
            jit_fns[jf.fi.qname] = jf
            _check_jit_body(jf, out)
    _check_static_callsites(jit_fns, graph, out)
    # stable order, dedup the two-pass loop artifacts
    seen: Set[Tuple[str, int, str]] = set()
    uniq: List[Finding] = []
    for f in out:
        key = (f.path, f.line, f.message)
        if key not in seen:
            seen.add(key)
            uniq.append(f)
    return uniq
