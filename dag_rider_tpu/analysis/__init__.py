"""driderlint — project-invariant static analysis + race detection (round 14).

The byte-identity A/B tests and the chaos suite check *behavior*; this
package checks the *invariants those tests silently assume*, the way
"Reusable Formal Verification of DAG-based Consensus Protocols"
(arXiv 2407.02167) argues DAG-BFT correctness should be carried by
reusable machine-checked properties rather than per-change testing. The
reference Go prototype got ``go vet`` and ``-race`` for free; this is
the Python/JAX port's equivalent, specialized to THIS repo's seams:

- ``knobs``       — every DAGRIDER_* env read routes through the
                    config.py registry and appears in the README table
- ``determinism`` — no wall clock, unseeded RNG, or set-iteration-order
                    dependence on consensus commit paths
- ``oracle``      — vector-pump / agg-cert-only code never mutates the
                    scalar reference path's state (what every A/B
                    byte-identity test assumes)
- ``jitpure``     — no Python side effects inside jitted fns in ops/
                    and parallel/
- ``metrics``     — every counter bumped is registered in
                    utils/metrics.KNOWN_COUNTERS
- ``races``       — a runtime harness: lock-order cycle detection +
                    guarded-field / serialized-method enforcement,
                    driven by the existing chaos/fuzz suites under
                    DAGRIDER_RACE=1

Run the static suite with ``python -m dag_rider_tpu.analysis``; every
checker is proven non-vacuous by a planted violation in
tests/test_analysis.py, mirroring the consensus/invariants.py pattern.
"""

from dag_rider_tpu.analysis.core import Allow, Finding, discover, run_static

__all__ = ["Allow", "Finding", "discover", "run_static"]
