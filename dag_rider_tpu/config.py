"""Framework configuration.

The reference has no config system (SURVEY.md §5): its only knobs are the
``New(index, faulty, tp)`` arguments (``process/process.go:34``) and hardcoded
constants (wave length 4 at ``process.go:238,332,400``, channel buffer 10 at
``process.go:174``). This dataclass makes every knob explicit, including the
TPU-specific ones (verifier backend, device mesh shape).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple


@dataclasses.dataclass(frozen=True)
class Config:
    """All tunables for one DAG-Rider deployment.

    Attributes:
        n: committee size (number of processes). Process indices are
           0-based ints in [0, n) — unlike the reference's 1-based indices
           (``process/process.go:38-40``), which only exist there to paper
           over the genesis-seeding bug (SURVEY.md D2).
        f: max Byzantine faults tolerated. Defaults to floor((n-1)/3),
           the optimal resilience the protocol is designed for. Quorum
           size is 2f+1 (``process.go:165,236,337``).
        wave_length: rounds per wave. The paper (and reference) fix this
           at 4 (``process.go:394-402``); kept configurable for experiments
           but all tests use 4.
        signature_scheme: "none" | "ed25519" | "bls12381". "none" matches
           the reference (no crypto at all — SURVEY.md D10); "ed25519" is
           the per-vertex signing scheme of the north-star Verifier.
        verifier_backend: "cpu" | "tpu". Both must produce byte-identical
           commit order (BASELINE.json north star).
        coin: "fixed" | "round_robin" | "threshold_bls". "fixed" reproduces
           the reference stub's *determinism* (``process.go:390-392``)
           without its bug (we return wave-independent leader 0 only when
           explicitly configured); "threshold_bls" is the real common coin
           the reference's TODO names (``process.go:388``).
        propose_empty: if True, a process with no queued client blocks
           proposes an empty block instead of stalling round advancement.
           The reference busy-waits forever instead (D7, ``process.go:277``).
        mesh_shape: device mesh for multi-chip sharding, e.g. (8,) for a
           1-D "batch" mesh over vertices, (4, 2) for (batch, shard).
        mesh_axis_names: names for the mesh axes.
        max_rounds: capacity hint for dense DAG tensors (grown on demand).
        sync_patience: quiescent step() passes with a stuck buffer before
           a process broadcasts a catch-up sync request (0 disables the
           anti-entropy protocol — elastic recovery, SURVEY §5).
        sync_window: max rounds served per sync request (bounds responder
           amplification together with the per-requester serve cap).
    """

    n: int = 4
    f: Optional[int] = None
    wave_length: int = 4
    signature_scheme: str = "none"
    verifier_backend: str = "cpu"
    coin: str = "round_robin"
    propose_empty: bool = True
    mesh_shape: Tuple[int, ...] = (1,)
    mesh_axis_names: Tuple[str, ...] = ("batch",)
    max_rounds: int = 64
    sync_patience: int = 8
    sync_window: int = 8
    # Wall-clock flood control (0 disables, e.g. in lockstep simulations):
    # a requester spaces its sync requests by at least
    # sync_request_cooldown_s, and a responder serves any one requester at
    # most once per sync_serve_cooldown_s. Rate limits rather than
    # lifetime caps: a lost response can always be re-requested later
    # (no permanent wedge), and a Byzantine requester rotating windows
    # still extracts at most one window per cooldown.
    sync_request_cooldown_s: float = 0.5
    sync_serve_cooldown_s: float = 0.2
    # Garbage-collection depth in rounds (None = unbounded, matching the
    # reference's grow-forever state, process.go:72-85). When set, the
    # ordering rule deterministically EXCLUDES vertices with
    # round <= leader_round - gc_depth from delivery (every process
    # excludes the same vertices for the same committed leader chain, so
    # the total order stays identical — the standard DAG-BFT GC trade:
    # fairness holds only for vertices admitted within the window), and
    # each process retires DAG state below its decided frontier minus
    # gc_depth (DagState.prune_below), bounding memory for long runs.
    gc_depth: Optional[int] = None

    def __post_init__(self) -> None:
        if self.n < 1:
            raise ValueError(f"n must be >= 1, got {self.n}")
        if self.f is None:
            object.__setattr__(self, "f", (self.n - 1) // 3)
        if self.n < 3 * self.f + 1:
            raise ValueError(
                f"need n >= 3f+1 for BFT resilience, got n={self.n}, f={self.f}"
            )
        if self.wave_length < 1:
            raise ValueError("wave_length must be >= 1")
        if self.signature_scheme not in ("none", "ed25519", "bls12381"):
            raise ValueError(f"unknown signature scheme {self.signature_scheme!r}")
        if self.verifier_backend not in ("cpu", "tpu"):
            raise ValueError(f"unknown verifier backend {self.verifier_backend!r}")
        if self.coin not in ("fixed", "round_robin", "threshold_bls"):
            raise ValueError(f"unknown coin {self.coin!r}")
        if self.gc_depth is not None:
            # The horizon must sit safely below everything the live
            # machinery touches: catch-up sync windows, the current
            # wave's 4 rounds, and one wave of retroactive leader walk.
            floor = self.sync_window + 2 * self.wave_length
            if self.gc_depth < floor:
                raise ValueError(
                    f"gc_depth must be >= sync_window + 2*wave_length "
                    f"({floor}), got {self.gc_depth}"
                )

    @property
    def quorum(self) -> int:
        """2f+1 — the quorum threshold used everywhere the reference uses it
        (round advance ``process.go:236``, admission ``process.go:165``,
        commit ``process.go:337``)."""
        return 2 * self.f + 1

    def wave_round(self, wave: int, k: int) -> int:
        """round(w, k) = wave_length*(w-1) + k, 1-indexed k in [1, wave_length].

        Mirrors ``waveRound`` (reference ``process/process.go:394-402``);
        waves are 1-indexed, round 0 is the genesis round.
        """
        if not 1 <= k <= self.wave_length:
            raise ValueError(f"k must be in [1, {self.wave_length}], got {k}")
        return self.wave_length * (wave - 1) + k

    def wave_of_round(self, rnd: int) -> int:
        """Inverse: which wave a round >= 1 belongs to."""
        if rnd < 1:
            raise ValueError("rounds >= 1 belong to waves; round 0 is genesis")
        return (rnd - 1) // self.wave_length + 1
