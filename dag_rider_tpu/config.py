"""Framework configuration.

The reference has no config system (SURVEY.md §5): its only knobs are the
``New(index, faulty, tp)`` arguments (``process/process.go:34``) and hardcoded
constants (wave length 4 at ``process.go:238,332,400``, channel buffer 10 at
``process.go:174``). This dataclass makes every knob explicit, including the
TPU-specific ones (verifier backend, device mesh shape).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, Optional, Tuple

# ---------------------------------------------------------------------------
# Central DAGRIDER_* knob registry (round 14).
#
# Every environment variable the package reads must be registered here and
# read through one of the env_* accessors below; the driderlint knob checker
# (dag_rider_tpu/analysis/knobs.py) rejects any direct ``os.environ`` read of
# a DAGRIDER_* name outside this module, and cross-checks that every
# registered knob appears in the README knob table. bench.py's
# DAGRIDER_BENCH_* namespace is the one carve-out (bench-local tuning, never
# read by the package).
# ---------------------------------------------------------------------------

#: shared env-flag convention: anything but these (case-insensitive) is on
_OFF_WORDS = ("0", "false", "no", "off")


@dataclasses.dataclass(frozen=True)
class Knob:
    """One registered environment knob.

    ``kind`` is "flag" | "int" | "float" | "str" | "choice"; ``default``
    is the value an empty/unset variable resolves to (already typed);
    ``choices``/``minimum`` carry the validation the accessor enforces.
    """

    name: str
    kind: str
    default: object
    doc: str
    choices: Optional[Tuple[str, ...]] = None
    minimum: Optional[float] = None


KNOBS: Dict[str, Knob] = {}


def _register(
    name: str,
    kind: str,
    default: object,
    doc: str,
    choices: Optional[Tuple[str, ...]] = None,
    minimum: Optional[float] = None,
) -> None:
    KNOBS[name] = Knob(name, kind, default, doc, choices, minimum)


_register("DAGRIDER_PUMP", "choice", "scalar",
          "host consensus pump path", choices=("scalar", "vector"))
_register("DAGRIDER_CERT", "choice", "off",
          "aggregated round certificates", choices=("off", "agg"))
_register("DAGRIDER_CERT_MSM", "choice", "host",
          "certificate-aggregation MSM backend",
          choices=("host", "device", "sharded"))
_register("DAGRIDER_MESH", "int", None,
          "batch-axis device count for the sharded verifier mesh",
          minimum=1)
_register("DAGRIDER_SHARDED_COMB_IMPL", "str", "",
          "per-shard comb impl override (e.g. pallas_interpret)")
_register("DAGRIDER_VERIFY_DEPTH", "int", 2,
          "pipeline in-flight window depth", minimum=1)
_register("DAGRIDER_VERIFY_RETRY", "int", 1,
          "bounded retry count per resilient-verifier tier", minimum=0)
_register("DAGRIDER_VERIFY_FALLBACK", "str", "",
          "fallback-tier selector (cpu, or 0/off/none/false for none)")
_register("DAGRIDER_PREP_WORKERS", "int", 1,
          "parallel host-prep worker count", minimum=1)
_register("DAGRIDER_NATIVE", "flag", True,
          "native challenge hashing (hashlib fallback when off)")
_register("DAGRIDER_COMB", "flag", True,
          "fixed-key comb tables for the TPU verifier")
_register("DAGRIDER_COMB_BITS", "choice", "",
          "comb table window width", choices=("", "4", "8"))
_register("DAGRIDER_PALLAS_GROUP", "flag", True,
          "Pallas group-op kernels on real TPU backends")
_register("DAGRIDER_MSM_PALLAS", "flag", True,
          "Mosaic MSM kernels on real TPU backends")
_register("DAGRIDER_MEMPOOL_CAP", "int", 65536,
          "mempool capacity in transactions", minimum=1)
_register("DAGRIDER_BATCH_BYTES", "int", 8192,
          "target payload bytes per built block", minimum=1)
_register("DAGRIDER_BATCH_DEADLINE_MS", "float", 50.0,
          "max hold latency before a partial batch ships", minimum=0)
_register("DAGRIDER_ADMIT_WATERMARKS", "str", "",
          'admission watermarks as "low,high" pool-fill fractions')
_register("DAGRIDER_MEMPOOL_TTL_S", "float", 60.0,
          "pending-transaction eviction age in seconds")
_register("DAGRIDER_ADAPTIVE_DEADLINE", "flag", False,
          "drive the batcher's effective deadline from the live "
          "submit->deliver latency histogram (ISSUE 16 tentpole 3)")
_register("DAGRIDER_PROFILE_DIR", "str", "",
          "jax.profiler trace output directory for bench runs")
_register("DAGRIDER_AGG_OUT", "str", "BENCH_r06.json",
          "aggregate-cert bench output path")
_register("DAGRIDER_MULTICHIP_OUT", "str", "MULTICHIP_r06.json",
          "multichip bench output path")
_register("DAGRIDER_RACE", "flag", False,
          "install the dynamic lock-race harness under pytest")
_register("DAGRIDER_CERT_SIGN", "choice", "host",
          "batched BLS share-signing backend",
          choices=("host", "native", "device"))
_register("DAGRIDER_CERT_PAIR", "choice", "host",
          "certificate aggregate-pairing backend",
          choices=("host", "device"))
_register("DAGRIDER_CERT_SPAN", "int", 0,
          "rounds per cert-of-certs span (0 disables span certificates)",
          minimum=0)
_register("DAGRIDER_CERT_SELFCHECK", "flag", True,
          "aggregator self-verifies certificates before gossip")
_register("DAGRIDER_CERT2_OUT", "str", "BENCH_r07.json",
          "certificate-phase-2 bench output path")
_register("DAGRIDER_TRACE", "flag", False,
          "causal tracing layer (ring recorder + lifecycle/phase spans)")
_register("DAGRIDER_TRACE_SAMPLE", "float", 1.0,
          "fraction of transactions stamped with lifecycle spans",
          minimum=0)
_register("DAGRIDER_TRACE_RING", "int", 65536,
          "trace ring-buffer capacity in events", minimum=1)
_register("DAGRIDER_FLIGHT_DIR", "str", "",
          "flight-recorder dump directory (empty disables dumps)")
_register("DAGRIDER_FLIGHT_EVENTS", "int", 4096,
          "events retained in the flight-recorder last-N ring", minimum=1)
_register("DAGRIDER_WAVE_PIPELINE", "flag", False,
          "pipelined wave evaluation (decide each wave the step its "
          "commit-round quorum lands instead of at the 4-round boundary)")
_register("DAGRIDER_EAGER_DELIVER", "flag", False,
          "optimistic early delivery: surface each decided chunk via "
          "on_deliver_early ahead of the deferred canonical flush")
_register("DAGRIDER_FINALITY_OUT", "str", "BENCH_r08.json",
          "finality-ladder bench output path")
_register("DAGRIDER_LANES", "flag", False,
          "sharded dissemination lanes: vertices carry certified batch "
          "digests while worker lanes move the payload bytes (ISSUE 17)")
_register("DAGRIDER_LANE_WORKERS", "int", 4,
          "payload-dissemination worker threads per lane bus", minimum=1)
_register("DAGRIDER_LANE_BATCH_BYTES", "int", 1024,
          "minimum encoded block size worth a lane round-trip; smaller "
          "blocks ship inline (the oracle path)", minimum=1)
_register("DAGRIDER_LANES_OUT", "str", "BENCH_r09.json",
          "lanes-ladder bench output path")
_register("DAGRIDER_CLUSTER_TRANSPORT", "choice", "uds",
          "address family for multi-process cluster harness sockets",
          choices=("uds", "tcp"))
_register("DAGRIDER_CLUSTER_BOOT_S", "float", 15.0,
          "per-node readiness timeout when booting cluster processes",
          minimum=0)
_register("DAGRIDER_CLUSTER_KEEP", "flag", False,
          "keep the cluster harness workspace (logs, checkpoints, flight "
          "dumps) after a run instead of deleting it")
_register("DAGRIDER_CLUSTER_OUT", "str", "BENCH_r20.json",
          "cluster-e2e ladder bench output path")
_register("DAGRIDER_EPOCH", "flag", False,
          "epoch reconfiguration: validator-set changes ordered through "
          "consensus as control txs, taking effect at deterministic "
          "wave boundaries (ISSUE 20)")
_register("DAGRIDER_EPOCH_WAVES", "int", 8,
          "epoch boundary interval in waves: a committed reconfiguration "
          "control tx takes effect at the next multiple of this many "
          "waves", minimum=1)
_register("DAGRIDER_EPOCH_GC", "int", 0,
          "extra epoch GC depth in rounds kept past the committed "
          "frontier when an epoch settles (0 = reuse gc_depth)",
          minimum=0)
_register("DAGRIDER_EPOCH_ROTATE", "choice", "seed",
          "threshold-key rotation mode at epoch boundaries: seed = "
          "deterministic seeded dealer (every node derives identical "
          "keys from the committed transcript), dkg = full joint-Feldman "
          "resharing over crypto/dkg.py, none = epoch bump only",
          choices=("seed", "dkg", "none"))
_register("DAGRIDER_EPOCH_OUT", "str", "BENCH_r21.json",
          "epoch ladder bench output path")


def _raw(name: str) -> str:
    if name not in KNOBS:
        raise KeyError(
            f"unregistered DAGRIDER knob {name!r} — add it to "
            "dag_rider_tpu.config.KNOBS"
        )
    return os.environ.get(name, "").strip()


def env_flag(name: str, default: Optional[bool] = None) -> bool:
    """Registered boolean knob; empty/unset resolves to the registry
    default. Anything but 0/false/no/off (case-insensitive) is on."""
    raw = _raw(name)
    if not raw:
        d = KNOBS[name].default if default is None else default
        return bool(d)
    return raw.lower() not in _OFF_WORDS


def env_str(name: str, default: Optional[str] = None) -> str:
    raw = _raw(name)
    if raw:
        return raw
    return str(KNOBS[name].default if default is None else default)


def env_choice(name: str, default: Optional[str] = None) -> str:
    """Registered enumerated knob; raises ValueError outside choices."""
    knob = KNOBS[name]
    raw = _raw(name)
    val = raw if raw else str(knob.default if default is None else default)
    if knob.choices is not None and val not in knob.choices:
        raise ValueError(
            f"{name} must be one of {knob.choices}, got {val!r}"
        )
    return val


def env_int(name: str, default: Optional[int] = None) -> int:
    knob = KNOBS[name]
    raw = _raw(name)
    if not raw:
        return int(knob.default if default is None else default)  # type: ignore[arg-type]
    try:
        val = int(raw)
    except ValueError as e:
        raise ValueError(f"{name} must be an int, got {raw!r}") from e
    if knob.minimum is not None and val < knob.minimum:
        raise ValueError(
            f"{name} must be >= {int(knob.minimum)}, got {raw!r}"
        )
    return val


def env_opt_int(name: str) -> Optional[int]:
    """Registered optional int knob: unset/empty yields None."""
    knob = KNOBS[name]
    raw = _raw(name)
    if not raw:
        return None
    try:
        val = int(raw)
    except ValueError as e:
        raise ValueError(f"{name} must be an int, got {raw!r}") from e
    if knob.minimum is not None and val < knob.minimum:
        raise ValueError(
            f"{name} must be >= {int(knob.minimum)}, got {raw!r}"
        )
    return val


def env_float(name: str, default: Optional[float] = None) -> float:
    knob = KNOBS[name]
    raw = _raw(name)
    if not raw:
        return float(knob.default if default is None else default)  # type: ignore[arg-type]
    try:
        val = float(raw)
    except ValueError as e:
        raise ValueError(f"{name} must be a float, got {raw!r}") from e
    if knob.minimum is not None and val < knob.minimum:
        raise ValueError(
            f"{name} must be >= {knob.minimum}, got {raw!r}"
        )
    return val


@dataclasses.dataclass(frozen=True)
class Config:
    """All tunables for one DAG-Rider deployment.

    Attributes:
        n: committee size (number of processes). Process indices are
           0-based ints in [0, n) — unlike the reference's 1-based indices
           (``process/process.go:38-40``), which only exist there to paper
           over the genesis-seeding bug (SURVEY.md D2).
        f: max Byzantine faults tolerated. Defaults to floor((n-1)/3),
           the optimal resilience the protocol is designed for. Quorum
           size is 2f+1 (``process.go:165,236,337``).
        wave_length: rounds per wave. The paper (and reference) fix this
           at 4 (``process.go:394-402``); kept configurable for experiments
           but all tests use 4.
        signature_scheme: "none" | "ed25519" | "bls12381". "none" matches
           the reference (no crypto at all — SURVEY.md D10); "ed25519" is
           the per-vertex signing scheme of the north-star Verifier.
        verifier_backend: "cpu" | "tpu". Both must produce byte-identical
           commit order (BASELINE.json north star).
        coin: "fixed" | "round_robin" | "threshold_bls". "fixed" reproduces
           the reference stub's *determinism* (``process.go:390-392``)
           without its bug (we return wave-independent leader 0 only when
           explicitly configured); "threshold_bls" is the real common coin
           the reference's TODO names (``process.go:388``).
        propose_empty: if True, a process with no queued client blocks
           proposes an empty block instead of stalling round advancement.
           The reference busy-waits forever instead (D7, ``process.go:277``).
        mesh_shape: device mesh for multi-chip sharding, e.g. (8,) for a
           1-D "batch" mesh over vertices, (4, 2) for (batch, shard).
        mesh_axis_names: names for the mesh axes.
        max_rounds: capacity hint for dense DAG tensors (grown on demand).
        sync_patience: quiescent step() passes with a stuck buffer before
           a process broadcasts a catch-up sync request (0 disables the
           anti-entropy protocol — elastic recovery, SURVEY §5).
        sync_window: max rounds served per sync request (bounds responder
           amplification together with the per-requester serve cap).
    """

    n: int = 4
    f: Optional[int] = None
    wave_length: int = 4
    signature_scheme: str = "none"
    verifier_backend: str = "cpu"
    coin: str = "round_robin"
    propose_empty: bool = True
    mesh_shape: Tuple[int, ...] = (1,)
    mesh_axis_names: Tuple[str, ...] = ("batch",)
    max_rounds: int = 64
    sync_patience: int = 8
    sync_window: int = 8
    # Wall-clock flood control (0 disables, e.g. in lockstep simulations):
    # a requester spaces its sync requests by at least
    # sync_request_cooldown_s, and a responder serves any one requester at
    # most once per sync_serve_cooldown_s. Rate limits rather than
    # lifetime caps: a lost response can always be re-requested later
    # (no permanent wedge), and a Byzantine requester rotating windows
    # still extracts at most one window per cooldown.
    sync_request_cooldown_s: float = 0.5
    sync_serve_cooldown_s: float = 0.2
    # Garbage-collection depth in rounds (None = unbounded, matching the
    # reference's grow-forever state, process.go:72-85). When set, the
    # ordering rule deterministically EXCLUDES vertices with
    # round <= leader_round - gc_depth from delivery (every process
    # excludes the same vertices for the same committed leader chain, so
    # the total order stays identical — the standard DAG-BFT GC trade:
    # fairness holds only for vertices admitted within the window), and
    # each process retires DAG state below its decided frontier minus
    # gc_depth (DagState.prune_below), bounding memory for long runs.
    gc_depth: Optional[int] = None
    # Host consensus pump path: "scalar" is the reference per-message /
    # per-vertex semantics; "vector" is the round-batched refinement
    # (byte-identical commit order — tests/test_pump_vector.py is the
    # gate). None resolves from DAGRIDER_PUMP, defaulting to "scalar";
    # an explicit value beats the environment.
    pump: Optional[str] = None
    # Aggregated round certificates (ISSUE 9): "off" keeps the per-vertex
    # verify path as the reference oracle; "agg" BLS-signs vertex digests
    # and lets the round's designated aggregator gossip one
    # RoundCertificate that peers check with a single aggregate pairing
    # instead of n per-vertex verifies. Same resolution rule as pump:
    # None reads DAGRIDER_CERT, explicit beats env.
    cert: Optional[str] = None
    # Quiescent step() passes a non-aggregator waits on a round's
    # certificate before giving up and re-verifying that round per-vertex
    # (the Byzantine-aggregator liveness valve). Must exceed the clean
    # cert latency of 1-2 steps and stay below sync_patience so a silent
    # aggregator degrades locally before the sync machinery fires.
    cert_patience: int = 6
    # Cert-of-certs span width k (ISSUE 12 tentpole 3): every k
    # consecutive verified round certificates fold into one
    # SpanCertificate whose single combined pairing replaces k per-round
    # checks on catch-up consumers. 0 disables spans. Round certs keep
    # flowing regardless — spans are an overlay, never a liveness
    # dependency (receivers must not WAIT on a span). None resolves from
    # DAGRIDER_CERT_SPAN; explicit beats env, like pump/cert.
    cert_span: Optional[int] = None
    # Aggregator self-check before gossiping a certificate (and span):
    # catches local corruption at the cost of one extra aggregate
    # verify per assembly. None resolves from DAGRIDER_CERT_SELFCHECK
    # (default on); peers verify independently either way, so turning
    # it off trades early local detection for assembly latency.
    cert_selfcheck: Optional[bool] = None
    # Pipelined wave evaluation (ISSUE 16 tentpole 1): instead of the
    # one-shot attempt at each 4-round boundary, every undecided wave
    # whose commit round has a quorum is (re)evaluated each step, so a
    # wave decides the moment its votes land rather than when the local
    # round counter happens to cross the boundary. The decided leader
    # chain — and therefore the total order — is unchanged (covering
    # lemma: a quorum of round-4w votes for L_w guarantees every later
    # leader strong-reaches L_w, so the retroactive walk is invariant
    # to attempt timing); tests pin byte-identity against the scalar
    # oracle. None resolves from DAGRIDER_WAVE_PIPELINE; explicit beats
    # env, like pump/cert.
    wave_pipeline: Optional[bool] = None
    # Eager optimistic delivery (ISSUE 16 tentpole 2): surface each
    # decided wave's exact canonical chunk through on_deliver_early at
    # DECISION time, ahead of the (possibly deferred) canonical
    # _order_vertices flush, and reconcile the speculative log against
    # the canonical order when the flush runs. The speculative stream
    # is a prefix of the final order by construction; a reconciliation
    # mismatch is an invariant violation routed through the flight
    # recorder. None resolves from DAGRIDER_EAGER_DELIVER.
    eager_deliver: Optional[bool] = None
    # Sharded dissemination lanes (ISSUE 17): when on, each submitted
    # block whose encoding reaches lane_batch_bytes is disseminated over
    # the dedicated lane channel by worker threads, certified by 2f+1
    # signed availability acks, and proposed as a constant-size digest
    # ref; the consensus pump orders refs, delivery resolves them back
    # to payload bytes through the lane store (fetch-on-miss). Off keeps
    # inline payloads — the byte-identity oracle. None resolves from
    # DAGRIDER_LANES; explicit beats env, like pump/cert.
    lanes: Optional[bool] = None
    #: lane worker-thread count (None -> DAGRIDER_LANE_WORKERS)
    lane_workers: Optional[int] = None
    #: minimum encoded-block bytes before a block rides a lane
    #: (None -> DAGRIDER_LANE_BATCH_BYTES); smaller blocks stay inline
    lane_batch_bytes: Optional[int] = None
    # Epoch reconfiguration (ISSUE 20): when on, magic-prefixed control
    # transactions committed through the ordinary total order schedule
    # validator-set changes (join/leave/key-rotation) that take effect
    # at the next epoch boundary — a wave number every process derives
    # identically from the ordered log — rotating the threshold coin
    # keys and advancing an epoch id carried in the wire form (stale
    # pre-rotation messages are rejected at the receive seam). Off keeps
    # the static-membership oracle. None resolves from DAGRIDER_EPOCH;
    # explicit beats env, like pump/cert/lanes.
    epoch: Optional[bool] = None
    #: boundary interval in waves (None -> DAGRIDER_EPOCH_WAVES): a
    #: control tx committed in wave w activates at the next multiple
    #: of epoch_waves strictly after w
    epoch_waves: Optional[int] = None
    #: extra GC depth in rounds kept past a settled epoch's frontier
    #: (None -> DAGRIDER_EPOCH_GC; 0 = reuse gc_depth)
    epoch_gc: Optional[int] = None
    #: key-rotation mode at boundaries (None -> DAGRIDER_EPOCH_ROTATE):
    #: "seed" derives the next ThresholdKeys from a deterministic
    #: dealer seeded by the committed transcript, "dkg" runs the full
    #: joint-Feldman resharing, "none" bumps the epoch id only
    epoch_rotate: Optional[str] = None

    def __post_init__(self) -> None:
        if self.n < 1:
            raise ValueError(f"n must be >= 1, got {self.n}")
        if self.pump is None:
            object.__setattr__(self, "pump", env_choice("DAGRIDER_PUMP"))
        if self.pump not in ("scalar", "vector"):
            raise ValueError(
                f'pump must be "scalar" or "vector", got {self.pump!r}'
            )
        if self.cert is None:
            object.__setattr__(self, "cert", env_choice("DAGRIDER_CERT"))
        if self.cert not in ("off", "agg"):
            raise ValueError(
                f'cert must be "off" or "agg", got {self.cert!r}'
            )
        if self.cert_patience < 1:
            raise ValueError(
                f"cert_patience must be >= 1, got {self.cert_patience}"
            )
        if self.cert_span is None:
            object.__setattr__(self, "cert_span", env_int("DAGRIDER_CERT_SPAN"))
        if self.cert_span < 0:
            raise ValueError(
                f"cert_span must be >= 0, got {self.cert_span}"
            )
        if self.cert_selfcheck is None:
            object.__setattr__(
                self, "cert_selfcheck", env_flag("DAGRIDER_CERT_SELFCHECK")
            )
        if self.wave_pipeline is None:
            object.__setattr__(
                self, "wave_pipeline", env_flag("DAGRIDER_WAVE_PIPELINE")
            )
        if self.eager_deliver is None:
            object.__setattr__(
                self, "eager_deliver", env_flag("DAGRIDER_EAGER_DELIVER")
            )
        if self.lanes is None:
            object.__setattr__(self, "lanes", env_flag("DAGRIDER_LANES"))
        if self.lane_workers is None:
            object.__setattr__(
                self, "lane_workers", env_int("DAGRIDER_LANE_WORKERS")
            )
        if self.lane_workers < 1:
            raise ValueError(
                f"lane_workers must be >= 1, got {self.lane_workers}"
            )
        if self.lane_batch_bytes is None:
            object.__setattr__(
                self,
                "lane_batch_bytes",
                env_int("DAGRIDER_LANE_BATCH_BYTES"),
            )
        if self.lane_batch_bytes < 1:
            raise ValueError(
                f"lane_batch_bytes must be >= 1, got {self.lane_batch_bytes}"
            )
        if self.epoch is None:
            object.__setattr__(self, "epoch", env_flag("DAGRIDER_EPOCH"))
        if self.epoch_waves is None:
            object.__setattr__(
                self, "epoch_waves", env_int("DAGRIDER_EPOCH_WAVES")
            )
        if self.epoch_waves < 1:
            raise ValueError(
                f"epoch_waves must be >= 1, got {self.epoch_waves}"
            )
        if self.epoch_gc is None:
            object.__setattr__(self, "epoch_gc", env_int("DAGRIDER_EPOCH_GC"))
        if self.epoch_gc < 0:
            raise ValueError(f"epoch_gc must be >= 0, got {self.epoch_gc}")
        if self.epoch_rotate is None:
            object.__setattr__(
                self, "epoch_rotate", env_choice("DAGRIDER_EPOCH_ROTATE")
            )
        if self.epoch_rotate not in ("seed", "dkg", "none"):
            raise ValueError(
                f'epoch_rotate must be "seed", "dkg" or "none", '
                f"got {self.epoch_rotate!r}"
            )
        if self.f is None:
            object.__setattr__(self, "f", (self.n - 1) // 3)
        if self.n < 3 * self.f + 1:
            raise ValueError(
                f"need n >= 3f+1 for BFT resilience, got n={self.n}, f={self.f}"
            )
        if self.wave_length < 1:
            raise ValueError("wave_length must be >= 1")
        if self.signature_scheme not in ("none", "ed25519", "bls12381"):
            raise ValueError(f"unknown signature scheme {self.signature_scheme!r}")
        if self.verifier_backend not in ("cpu", "tpu"):
            raise ValueError(f"unknown verifier backend {self.verifier_backend!r}")
        if self.coin not in ("fixed", "round_robin", "threshold_bls"):
            raise ValueError(f"unknown coin {self.coin!r}")
        if self.gc_depth is not None:
            # The horizon must sit safely below everything the live
            # machinery touches: catch-up sync windows, the current
            # wave's 4 rounds, and one wave of retroactive leader walk.
            floor = self.sync_window + 2 * self.wave_length
            if self.gc_depth < floor:
                raise ValueError(
                    f"gc_depth must be >= sync_window + 2*wave_length "
                    f"({floor}), got {self.gc_depth}"
                )

    @property
    def quorum(self) -> int:
        """2f+1 — the quorum threshold used everywhere the reference uses it
        (round advance ``process.go:236``, admission ``process.go:165``,
        commit ``process.go:337``)."""
        return 2 * self.f + 1

    def wave_round(self, wave: int, k: int) -> int:
        """round(w, k) = wave_length*(w-1) + k, 1-indexed k in [1, wave_length].

        Mirrors ``waveRound`` (reference ``process/process.go:394-402``);
        waves are 1-indexed, round 0 is the genesis round.
        """
        if not 1 <= k <= self.wave_length:
            raise ValueError(f"k must be in [1, {self.wave_length}], got {k}")
        return self.wave_length * (wave - 1) + k

    def wave_of_round(self, rnd: int) -> int:
        """Inverse: which wave a round >= 1 belongs to."""
        if rnd < 1:
            raise ValueError("rounds >= 1 belong to waves; round 0 is genesis")
        return (rnd - 1) // self.wave_length + 1


@dataclasses.dataclass(frozen=True)
class MempoolConfig:
    """Knobs for the ingestion edge (``dag_rider_tpu/mempool/``).

    Dataclass defaults < env < explicit :meth:`from_dict` values — so a
    deployed fleet is retunable via environment without editing every
    node's JSON config, and a config file still wins when it speaks up.

    Env knobs: ``DAGRIDER_MEMPOOL_CAP`` (pool capacity, transactions),
    ``DAGRIDER_BATCH_BYTES`` (target payload bytes per built block),
    ``DAGRIDER_BATCH_DEADLINE_MS`` (max hold latency before a partial
    batch ships), ``DAGRIDER_ADMIT_WATERMARKS`` ("low,high" pool-fill
    fractions driving accept → throttle → shed), and
    ``DAGRIDER_MEMPOOL_TTL_S`` (pending-transaction eviction age).

    Attributes:
        cap: max pending transactions the pool holds; adds beyond it shed.
        batch_bytes: the batcher packs blocks up to this many payload
            bytes (a single oversized transaction still ships alone).
        batch_deadline_ms: a non-empty pool older than this flushes a
            partial block — bounds client latency at low load.
        admit_low / admit_high: pool-fill watermarks. Below low every
            source is accepted (subject to ``source_rate``); between them
            each source is throttled to ``throttle_rate`` tx/s; at or
            above high everything sheds.
        ttl_s: pending transactions older than this are evicted (they
            were accepted but never packed — a stalled cluster must not
            pin client payloads forever).
        source_rate: per-source hard rate cap in tx/s applied even in
            the accept band (0 = uncapped).
        throttle_rate: per-source tx/s allowed inside the throttle band.
        source_burst: token-bucket burst depth for both rate caps.
        max_batch_txs: hard cap on transactions per built block (guards
            the wire codec against pathological many-tiny-tx blocks).
        max_staged_blocks: stop pulling built blocks into
            ``Process.blocks_to_propose`` while it already holds this
            many — DAG-Rider proposes ONE block per round, so under
            sustained overload the proposal queue is the next unbounded
            buffer after the pool; capping it keeps excess transactions
            *in* the pool where the watermarks can see them and shed.
    """

    cap: int = 65536
    batch_bytes: int = 8192
    batch_deadline_ms: float = 50.0
    admit_low: float = 0.5
    admit_high: float = 0.9
    ttl_s: float = 60.0
    source_rate: float = 0.0
    throttle_rate: float = 64.0
    source_burst: float = 32.0
    max_batch_txs: int = 1024
    max_staged_blocks: int = 16
    #: ISSUE 16 tentpole 3 (DAGRIDER_ADAPTIVE_DEADLINE): when True the
    #: Mempool drives the batcher's EFFECTIVE deadline from the live
    #: submit→deliver histogram — a 50 ms hold is noise against a 10 s
    #: end-to-end path but a third of a sub-second one, so the deadline
    #: tracks a small fraction of the measured p50 (floored at 1 ms,
    #: capped at the configured batch_deadline_ms). Off by default:
    #: adaptive packing changes block contents, so byte-identity A/B
    #: suites must keep it off.
    adaptive_deadline: bool = False

    def __post_init__(self) -> None:
        if self.cap < 1:
            raise ValueError(f"mempool cap must be >= 1, got {self.cap}")
        if self.batch_bytes < 1:
            raise ValueError(
                f"batch_bytes must be >= 1, got {self.batch_bytes}"
            )
        if self.batch_deadline_ms < 0:
            raise ValueError(
                f"batch_deadline_ms must be >= 0, got {self.batch_deadline_ms}"
            )
        if not 0.0 <= self.admit_low <= self.admit_high <= 1.0:
            raise ValueError(
                "admission watermarks need 0 <= low <= high <= 1, got "
                f"low={self.admit_low}, high={self.admit_high}"
            )
        if self.ttl_s <= 0:
            raise ValueError(f"ttl_s must be > 0, got {self.ttl_s}")
        if self.source_rate < 0:
            raise ValueError(
                f"source_rate must be >= 0, got {self.source_rate}"
            )
        if self.throttle_rate <= 0:
            raise ValueError(
                f"throttle_rate must be > 0, got {self.throttle_rate}"
            )
        if self.source_burst < 1:
            raise ValueError(
                f"source_burst must be >= 1, got {self.source_burst}"
            )
        if self.max_batch_txs < 1:
            raise ValueError(
                f"max_batch_txs must be >= 1, got {self.max_batch_txs}"
            )
        if self.max_staged_blocks < 1:
            raise ValueError(
                f"max_staged_blocks must be >= 1, got {self.max_staged_blocks}"
            )

    @classmethod
    def from_env(cls) -> "MempoolConfig":
        low, high = cls._env_watermarks()
        return cls(
            cap=env_int("DAGRIDER_MEMPOOL_CAP", cls.cap),
            batch_bytes=env_int("DAGRIDER_BATCH_BYTES", cls.batch_bytes),
            batch_deadline_ms=env_float(
                "DAGRIDER_BATCH_DEADLINE_MS", cls.batch_deadline_ms
            ),
            admit_low=low,
            admit_high=high,
            ttl_s=env_float("DAGRIDER_MEMPOOL_TTL_S", cls.ttl_s),
            adaptive_deadline=env_flag("DAGRIDER_ADAPTIVE_DEADLINE"),
        )

    @staticmethod
    def _env_watermarks() -> Tuple[float, float]:
        raw = env_str("DAGRIDER_ADMIT_WATERMARKS")
        if not raw:
            return MempoolConfig.admit_low, MempoolConfig.admit_high
        parts = raw.split(",")
        if len(parts) != 2:
            raise ValueError(
                f'DAGRIDER_ADMIT_WATERMARKS must be "low,high", got {raw!r}'
            )
        return float(parts[0]), float(parts[1])

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "MempoolConfig":
        """Env-seeded config with explicit overrides; unknown keys raise
        (a typo'd knob silently falling back to defaults is exactly the
        class of config bug this repo's explicit-knob rule exists to
        kill)."""
        base = dataclasses.asdict(cls.from_env())
        if d:
            fields = {f.name for f in dataclasses.fields(cls)}
            unknown = set(d) - fields
            if unknown:
                raise ValueError(
                    f"unknown mempool config keys: {sorted(unknown)}"
                )
            base.update(d)
        return cls(**base)
