"""Benchmark: vertex-signatures verified/sec on one chip (north star).

Prints ONE JSON line:
  {"metric": "vertex_sigs_per_sec", "value": N, "unit": "sigs/s",
   "vs_baseline": N / 50000}

BASELINE.json north star: >= 50,000 vertex-signatures verified/sec on a
single TPU v5e chip at committee size n=256. The measured quantity is the
steady-state end-to-end Verifier throughput: host prep (SHA-512 challenge
scalars, byte parsing) + one device dispatch per whole-round batch —
exactly what the consensus hot path pays per DAG round.
"""

from __future__ import annotations

import json
import time

import numpy as np


def build_batch(n: int, rounds: int):
    from dag_rider_tpu.core.types import Block, Vertex, VertexID
    from dag_rider_tpu.verifier.base import KeyRegistry, VertexSigner
    from dag_rider_tpu.verifier.tpu import TPUVerifier

    reg, seeds = KeyRegistry.generate(n)
    signers = [VertexSigner(s) for s in seeds]
    batches = []
    for r in range(rounds):
        vs = []
        for i in range(n):
            v = Vertex(
                id=VertexID(r + 1, i),
                block=Block((f"r{r}-tx-{i}".encode() * 2,)),
                strong_edges=tuple(
                    VertexID(r, s) for s in range(min(n, 2 * ((n - 1) // 3) + 1))
                ),
            )
            vs.append(signers[i].sign_vertex(v))
        batches.append(vs)
    return TPUVerifier(reg), batches


def main() -> None:
    n = 256
    warm_rounds = 2
    timed_rounds = 8
    verifier, batches = build_batch(n, warm_rounds + timed_rounds)

    for b in batches[:warm_rounds]:  # compile + warm
        mask = verifier.verify_batch(b)
        assert all(mask), "warmup batch failed to verify"

    t0 = time.perf_counter()
    total = 0
    for b in batches[warm_rounds:]:
        mask = verifier.verify_batch(b)
        total += len(mask)
        assert all(mask)
    dt = time.perf_counter() - t0

    sigs_per_sec = total / dt
    baseline = 50_000.0
    print(
        json.dumps(
            {
                "metric": "vertex_sigs_per_sec",
                "value": round(sigs_per_sec, 1),
                "unit": "sigs/s",
                "vs_baseline": round(sigs_per_sec / baseline, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
