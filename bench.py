"""Benchmark: vertex-signatures verified/sec on one chip (north star).

Prints ONE JSON line:
  {"metric": "vertex_sigs_per_sec", "value": N, "unit": "sigs/s",
   "vs_baseline": N / 50000, "backend": ..., "wave_commit_p50_ms": ...}

BASELINE.json north star: >= 50,000 vertex-signatures verified/sec on a
single TPU v5e chip at committee size n=256. The measured quantity is the
steady-state end-to-end Verifier throughput: host prep (SHA-512 challenge
scalars, byte parsing) + one device dispatch per whole-round batch —
exactly what the consensus hot path pays per DAG round.
``wave_commit_p50_ms`` is the per-wave device pipeline latency: 4 round
verify dispatches + the wave-commit quorum kernel + host total ordering.

Robustness (round-1 postmortem: the TPU backend raised UNAVAILABLE during
init and the whole bench died rc=1 with no data): the measurement runs in a
time-boxed subprocess; if the primary backend fails to initialize or hangs,
the bench re-runs on the CPU backend and reports that number with the
backend recorded — one JSON line and rc=0, always.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

BASELINE = 50_000.0
_REPO = os.path.dirname(os.path.abspath(__file__))


# ----------------------------------------------------------------------
# Inner: the actual measurement (runs in a subprocess, one backend)
# ----------------------------------------------------------------------

def _build_batches(n: int, rounds: int):
    from dag_rider_tpu.core.types import Block, Vertex, VertexID
    from dag_rider_tpu.verifier.base import KeyRegistry, VertexSigner
    from dag_rider_tpu.verifier.tpu import TPUVerifier

    reg, seeds = KeyRegistry.generate(n)
    signers = [VertexSigner(s) for s in seeds]
    quorum = 2 * ((n - 1) // 3) + 1
    batches = []
    for r in range(rounds):
        vs = []
        for i in range(n):
            v = Vertex(
                id=VertexID(r + 1, i),
                block=Block((f"r{r}-tx-{i}".encode() * 2,)),
                strong_edges=tuple(
                    VertexID(r, s) for s in range(min(n, quorum))
                ),
            )
            vs.append(signers[i].sign_vertex(v))
        batches.append(vs)
    return TPUVerifier(reg), batches


def _inner() -> None:
    import jax

    # The axon sitecustomize force-sets jax_platforms at interpreter start,
    # overriding the JAX_PLATFORMS env var (same issue tests/conftest.py
    # works around). Re-assert the platform this attempt was asked to use.
    want = os.environ.get("DAGRIDER_BENCH_PLATFORM")
    if want:
        jax.config.update("jax_platforms", want)

    from dag_rider_tpu.utils.jaxcache import enable_persistent_cache

    enable_persistent_cache(os.path.join(_REPO, ".jax_cache"))

    import numpy as np
    import jax.numpy as jnp

    t0 = time.perf_counter()
    backend = jax.default_backend()
    init_s = time.perf_counter() - t0

    n = int(os.environ.get("DAGRIDER_BENCH_N", "256"))
    warm_rounds = 2
    timed_rounds = int(os.environ.get("DAGRIDER_BENCH_ROUNDS", "8"))
    verifier, batches = _build_batches(n, warm_rounds + timed_rounds)

    t0 = time.perf_counter()
    for b in batches[:warm_rounds]:  # compile + warm
        mask = verifier.verify_batch(b)
        assert all(mask), "warmup batch failed to verify"
    compile_s = time.perf_counter() - t0

    # Optional profiler capture (SURVEY §5): set DAGRIDER_PROFILE_DIR to
    # write a jax.profiler trace of the timed loop (TraceAnnotations inside
    # TPUVerifier.verify_batch label host-prep vs device-dispatch).
    profile_dir = os.environ.get("DAGRIDER_PROFILE_DIR")
    if profile_dir:
        jax.profiler.start_trace(profile_dir)
    t0 = time.perf_counter()
    total = 0
    for b in batches[warm_rounds:]:
        mask = verifier.verify_batch(b)
        total += len(mask)
        assert all(mask)
    dt = time.perf_counter() - t0
    if profile_dir:
        jax.profiler.stop_trace()
    sigs_per_sec = total / dt

    # -- wave-commit pipeline latency: one wave = 4 round verify
    # dispatches + the quorum kernel + host total ordering over the wave's
    # dense DAG (the host twin the Process runs at commit time).
    from dag_rider_tpu.ops import dag_kernels

    rng = np.random.default_rng(7)
    strong_wave = jnp.asarray(
        rng.random((3, n, n)) < min(1.0, (2 * ((n - 1) // 3) + 1.5) / n)
    )
    exists_r4 = jnp.ones(n, dtype=bool)
    leader = jnp.int32(1)
    commit_fn = jax.jit(
        lambda s, e, l: dag_kernels.wave_commit_votes(
            s, e, l, quorum=2 * ((n - 1) // 3) + 1
        )
    )
    jax.block_until_ready(commit_fn(strong_wave, exists_r4, leader))  # warm

    strong_np = np.asarray(strong_wave)
    wave_ms = []
    n_waves = max(4, timed_rounds // 2)
    for w in range(n_waves):
        t0 = time.perf_counter()
        for k in range(4):
            verifier.verify_batch(batches[(w * 4 + k) % len(batches)])
        commit, votes = commit_fn(strong_wave, exists_r4, leader)
        jax.block_until_ready((commit, votes))
        # host ordering twin: causal closure over the wave's rounds
        reach = np.eye(n, dtype=bool)
        for r in range(3):
            reach = (reach.astype(np.int32) @ strong_np[r].astype(np.int32)) > 0
        wave_ms.append(1e3 * (time.perf_counter() - t0))
    wave_ms.sort()
    p50 = wave_ms[len(wave_ms) // 2]

    print(
        json.dumps(
            {
                "metric": "vertex_sigs_per_sec",
                "value": round(sigs_per_sec, 1),
                "unit": "sigs/s",
                "vs_baseline": round(sigs_per_sec / BASELINE, 3),
                "backend": backend,
                "n": n,
                "wave_commit_p50_ms": round(p50, 2),
                "compile_s": round(compile_s, 1),
                "backend_init_s": round(init_s, 1),
            }
        )
    )


# ----------------------------------------------------------------------
# Outer: backend attempts with timeouts; always emits JSON, rc=0
# ----------------------------------------------------------------------

def _attempt(env: dict, timeout_s: float):
    """Run the inner bench in a subprocess; return (json_line | None, tail)."""
    env = dict(env)
    env["DAGRIDER_BENCH_INNER"] = "1"
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env,
            cwd=_REPO,
            capture_output=True,
            text=True,
            timeout=timeout_s,
        )
    except subprocess.TimeoutExpired as e:
        out = (e.output or "") if isinstance(e.output, str) else ""
        return None, f"timeout after {timeout_s}s; partial output: {out[-500:]}"
    for line in reversed(proc.stdout.splitlines()):
        line = line.strip()
        if line.startswith("{") and line.endswith("}"):
            try:
                return json.loads(line), ""
            except json.JSONDecodeError:
                continue
    tail = (proc.stderr or proc.stdout or "")[-800:]
    return None, f"rc={proc.returncode}; {tail}"


def main() -> None:
    if os.environ.get("DAGRIDER_BENCH_INNER"):
        _inner()
        return

    errors = []
    # Budgets: worst case (primary hang + CPU fallback) must stay under the
    # ~9.5-minute driver window with headroom; the CPU fallback hits the
    # persistent compile cache, so 150s is generous.
    primary_timeout = float(os.environ.get("DAGRIDER_BENCH_TPU_TIMEOUT", "270"))
    cpu_timeout = float(os.environ.get("DAGRIDER_BENCH_CPU_TIMEOUT", "150"))

    # Attempt 1: whatever backend the environment selects (TPU under the
    # driver). Time-boxed because axon backend init can hang for minutes.
    result, err = _attempt(os.environ, primary_timeout)
    if result is None:
        errors.append(f"primary backend: {err}")
        # Attempt 2: forced-CPU fallback so a perf number always exists.
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["DAGRIDER_BENCH_PLATFORM"] = "cpu"
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env.pop("PALLAS_AXON_REMOTE_COMPILE", None)
        env.setdefault("DAGRIDER_BENCH_N", "64")  # CPU: smaller committee
        env.setdefault("DAGRIDER_BENCH_ROUNDS", "4")
        result, err = _attempt(env, cpu_timeout)
        if result is None:
            errors.append(f"cpu fallback: {err}")

    if result is None:
        result = {
            "metric": "vertex_sigs_per_sec",
            "value": 0.0,
            "unit": "sigs/s",
            "vs_baseline": 0.0,
            "backend": "none",
            "error": " || ".join(errors)[-900:],
        }
    elif errors:
        result["fallback_reason"] = " || ".join(errors)[-400:]
    print(json.dumps(result))


if __name__ == "__main__":
    main()
