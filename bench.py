"""Benchmark: vertex-signatures verified/sec on one chip (north star).

Prints ONE JSON line (the last JSON line on stdout is authoritative):
  {"metric": "vertex_sigs_per_sec", "value": N, "unit": "sigs/s",
   "vs_baseline": N / 50000, "backend": ..., "n": ...,
   "wave_commit_p50_ms": ..., "phases": {...}, "ladder": {...}}

BASELINE.json north star: >= 50,000 vertex-signatures verified/sec on a
single TPU v5e chip at committee size n=256. The measured quantity is the
steady-state end-to-end Verifier throughput: host prep (SHA-512 challenge
scalars, byte parsing) + one device dispatch per whole-round batch —
exactly what the consensus hot path pays per DAG round. ``ladder`` holds
BASELINE.md rungs #3/#4: a time-boxed 64-node consensus-in-the-loop
simulation with the device verifier (Metrics sigs_per_sec +
wave_commit_p50_ms), and the 256-node threshold-coin timing including one
Byzantine share (batched RLC recovery).

Round-3 architecture (round-2 postmortem: the TPU attempt timed out at
270 s with *empty* partial output — non-diagnostic, and the whole window
was wasted compiling/attempting n=256 first):

- Every stage runs in a subprocess with ``python -u`` and emits flushed
  ``[bench +T.Ts] stage`` markers to stderr, so any timeout's tail shows
  exactly where time went (backend init vs compile vs execution).
- A cheap *probe* subprocess initializes the backend and runs one tiny
  dispatch first. If the probe can't reach the device inside its budget,
  the remaining budget goes straight to the CPU fallback instead of
  hanging in backend init.
- The *measure* subprocess works phase by phase (n=64 verify -> n=256
  verify -> wave pipeline -> ladder rungs), re-printing a cumulative JSON
  line after every phase — a timeout loses at most the current phase,
  never the whole run.
- All budgets come from DAGRIDER_BENCH_BUDGET (default 540 s total) and
  are enforced both by the parent (subprocess timeouts) and inside the
  measure child (phases are skipped when the deadline nears).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

BASELINE = 50_000.0
_REPO = os.path.dirname(os.path.abspath(__file__))
_T0 = time.monotonic()


def _append_log(path: str, line: str) -> None:
    """Wall-clock-stamped append; never lets log IO break a bench stage."""
    try:
        import datetime

        stamp = datetime.datetime.now().isoformat(timespec="seconds")
        with open(path, "a") as fh:
            fh.write(f"{stamp} {line}\n")
    except OSError:
        pass


def _mark(msg: str) -> None:
    line = f"[bench +{time.monotonic() - _T0:7.1f}s] {msg}"
    print(line, file=sys.stderr, flush=True)
    # Tee into a per-run file (parent truncates it at start, children
    # append): a stage killed by the parent's timeout loses its piped
    # stderr, and the postmortem needs the LAST mark — e.g. "importing
    # jax" vs "backend up" decides wedged-relay vs slow-compile.
    path = os.environ.get("DAGRIDER_BENCH_MARK_FILE")
    if path:
        _append_log(path, f"[pid {os.getpid()}] {line}")


def _relay_log(msg: str) -> None:
    """Persist a wall-clock-timestamped relay-health line (round-4 VERDICT
    #1: make a wedged relay distinguishable from a compile timeout after
    the fact — stderr is lost once the driver truncates it)."""
    _append_log(os.path.join(_REPO, "relay_health.log"), msg)


# ----------------------------------------------------------------------
# Stage: probe (backend init + one tiny dispatch)
# ----------------------------------------------------------------------

def _probe() -> None:
    _mark("probe: python up, importing jax")
    import jax

    want = os.environ.get("DAGRIDER_BENCH_PLATFORM")
    if want:
        jax.config.update("jax_platforms", want)
    _mark(f"probe: jax {jax.__version__} imported; initializing backend")
    t0 = time.monotonic()
    devs = jax.devices()
    init_s = time.monotonic() - t0
    _mark(f"probe: backend up in {init_s:.1f}s: {devs}")
    import jax.numpy as jnp

    t0 = time.monotonic()
    x = jnp.ones((256, 256), dtype=jnp.int32)
    y = (x * 2 + x).sum()
    y.block_until_ready()
    _mark(f"probe: tiny dispatch done in {time.monotonic() - t0:.1f}s")
    print(
        json.dumps(
            {
                "probe_ok": True,
                "backend": jax.default_backend(),
                "device_kind": getattr(devs[0], "device_kind", "?"),
                "init_s": round(init_s, 1),
            }
        ),
        flush=True,
    )


# ----------------------------------------------------------------------
# Stage: measure (phased, deadline-aware, cumulative JSON after each phase)
# ----------------------------------------------------------------------

def _quorum(n: int) -> int:
    return 2 * ((n - 1) // 3) + 1


def _signed_round(signers, n: int, rnd: int, quorum: int):
    """One round's signed vertex batch (the unit every bench phase uses).

    The consensus pipeline computes the digest at r_deliver admission
    (process.on_message), which also fills the signing-bytes memo;
    pre-touching here keeps the verify phases measuring the Verifier
    seam, same as in production.
    """
    from dag_rider_tpu.core.types import Block, Vertex, VertexID

    vs = []
    for i in range(n):
        v = Vertex(
            id=VertexID(rnd, i),
            block=Block((f"r{rnd}-tx-{i}".encode() * 2,)),
            strong_edges=tuple(
                VertexID(rnd - 1, s) for s in range(min(n, quorum))
            ),
        )
        v = signers[i].sign_vertex(v)
        v.digest()
        vs.append(v)
    return vs


def _sign_rounds_worker(args):
    """Sign a slice of rounds in a spawn worker (pure-Python Ed25519 —
    no jax import, so workers start fast and are fork-safety-clean).
    Deterministic: output depends only on (seeds, n, round numbers)."""
    seeds, n, rnds = args
    from dag_rider_tpu.verifier.base import VertexSigner

    signers = [VertexSigner(s) for s in seeds]
    quorum = _quorum(n)
    return [(r, _signed_round(signers, n, r, quorum)) for r in rnds]


def _build_batches(n: int, rounds: int):
    from dag_rider_tpu.verifier.base import KeyRegistry, VertexSigner
    from dag_rider_tpu.verifier.tpu import TPUVerifier

    reg, seeds = KeyRegistry.generate(n)
    signers = [VertexSigner(s) for s in seeds]
    quorum = _quorum(n)
    workers = min(8, os.cpu_count() or 1)
    if n * rounds >= 2048 and workers >= 4:
        # The n=256 headline phase signs ~16k vertices at ~2.6 ms each —
        # 42 s of the cold-start budget single-threaded (round-3 weak
        # #8). Host signing is embarrassingly parallel and deterministic;
        # spawn (not fork: the parent may hold live TPU-backend state)
        # + jax-free workers cut it to ~1/workers. Signature memos ride
        # the pickles, so digest() stays pre-warmed like the serial path.
        import concurrent.futures as cf
        import multiprocessing as mp

        chunks = [
            list(range(w + 1, rounds + 1, workers)) for w in range(workers)
        ]
        by_round = {}
        try:
            with cf.ProcessPoolExecutor(
                max_workers=workers, mp_context=mp.get_context("spawn")
            ) as ex:
                for part in ex.map(
                    _sign_rounds_worker,
                    [(seeds, n, c) for c in chunks if c],
                ):
                    for r, vs in part:
                        by_round[r] = vs
            batches = [by_round[r + 1] for r in range(rounds)]
        except Exception as e:  # noqa: BLE001 — a broken pool must not
            # cost the headline phase; serial signing is the pre-change
            # behavior and always works
            _mark(f"parallel signing failed ({e!r}); falling back to serial")
            batches = [
                _signed_round(signers, n, r + 1, quorum)
                for r in range(rounds)
            ]
    else:
        batches = [
            _signed_round(signers, n, r + 1, quorum) for r in range(rounds)
        ]
    return TPUVerifier(reg), batches, signers


def _sim_rung(
    n: int,
    box_s: float,
    verifier,
    signers,
    *,
    bucket: int,
    chunk: int,
    coin: str = "round_robin",
    gc_depth: int = 24,
    pipelined: bool = True,
    target_per_view: int = 0,
    max_s: float = 0.0,
):
    """Time-boxed consensus-in-the-loop simulation (BASELINE configs #3/#4
    live halves): n processes, shared device verifier (coalesced + async
    pipelined dispatch — Simulation.run), signed vertices, optional
    threshold-BLS coin. Returns the ladder entry dict."""
    import time as _t

    from dag_rider_tpu.config import Config
    from dag_rider_tpu.consensus.simulator import Simulation

    # the verifier is SHARED across rungs (and with the deferred
    # merged headline phase): borrow its state under try/finally so
    # an exception inside the box cannot leak a sim-sized bucket or
    # a disabled pipeline into whoever runs next (driderlint:release)
    prev_bucket = getattr(verifier, "fixed_bucket", None)
    prev_enabled = getattr(verifier, "pipeline_enabled", True)
    try:
        verifier.fixed_bucket = bucket
        cfg = Config(
            n=n, coin="round_robin", propose_empty=True, gc_depth=gc_depth
        )
        coin_factory = None
        entry_coin = coin
        if coin == "threshold_bls":
            # Shared aggregation oracle: the (f+1)-of-n combine + pairing
            # check is a pure function of the observed shares (identical at
            # every process), so the sim evaluates it once per wave — the
            # same amortization as the shared Verifier. Per-process share
            # SIGNING stays real; the standalone coin cost is measured
            # honestly by the coin256 rung.
            from dag_rider_tpu.consensus.coin import ThresholdCoin
            from dag_rider_tpu.crypto import threshold as th

            f = (n - 1) // 3
            keys = th.ThresholdKeys.generate(n, f + 1)
            oracle = ThresholdCoin(keys, 0, n)

            def coin_factory(i: int):
                c = ThresholdCoin(keys, i, n)
                c._shares = oracle._shares
                c._sigma = oracle._sigma
                c._tried_at = oracle._tried_at
                # shared books must not be pruned by whichever process's GC
                # floor runs first — a (slightly) lagging sibling still reads
                # them; a production per-process coin prunes by its OWN
                # floor, which cannot outrun its own queries
                c.prune_below = lambda wave: None
                return c

            cfg = Config(
                n=n, coin="threshold_bls", propose_empty=True, gc_depth=gc_depth
            )
        sim = Simulation(
            cfg,
            coin_factory=coin_factory,
            verifier_factory=lambda i: verifier,
            signer_factory=lambda i: signers[i],
        )
        sim.submit_blocks(per_process=2)
        # AOT-compile the rung's program shape OUTSIDE the timed box (no-op
        # when already warmed this run or served from the persistent cache)
        warm0 = getattr(verifier, "warmup_compile_s", 0.0)
        if hasattr(verifier, "warmup"):
            verifier.warmup()
        if not pipelined:
            # Explicit A/B switch: Simulation.run (and the verifier's own
            # chunk streaming) sees pipeline_enabled False and takes the
            # synchronous depth-1 path — the before/after evidence for how
            # much the dispatch/delivery overlap cuts wave-commit p50
            # (round-4 VERDICT #4; replaces the round-5 None shadow).
            verifier.pipeline_enabled = False
        tot0 = (
            getattr(verifier, "total_prepare_s", 0.0),
            getattr(verifier, "total_dispatch_s", 0.0),
            getattr(verifier, "total_dispatches", 0),
            getattr(verifier, "total_sigs_dispatched", 0),
        )
        # host-prep engine row counters BEFORE the box, for a rung-local
        # parallel fraction (prep_stats' own fraction is engine-lifetime)
        ps0 = (
            verifier.prep_stats()
            if callable(getattr(verifier, "prep_stats", None))
            else None
        )
        t0 = _t.monotonic()
        pumped = 0
        while True:
            el = _t.monotonic() - t0
            if el >= box_s:
                # optional extension past the box until the rung's own
                # spec is met (BASELINE config #3: >= 10k vertices per
                # view) — bounded by max_s so it cannot eat the ladder
                if (
                    not target_per_view
                    or el >= max_s
                    or max((len(d) for d in sim.deliveries), default=0)
                    >= target_per_view
                ):
                    break
            pumped += sim.run(max_messages=chunk)
        dt = _t.monotonic() - t0
    finally:
        verifier.pipeline_enabled = prev_enabled
        verifier.fixed_bucket = prev_bucket
    sigs = sum(p.metrics.verify_sigs_total for p in sim.processes)
    waves = [
        s for p in sim.processes for s in p.metrics.wave_commit_seconds
    ]
    waves.sort()
    # the end-to-end cadence (wall time between consecutive decided
    # waves, ~4 rounds of verify+consensus each) — the quantity the
    # round-3 staged proxy modeled; wave_commit_p50_ms below is only
    # the decide+ordering walk
    intervals = [
        s for p in sim.processes for s in p.metrics.wave_interval_seconds
    ]
    intervals.sort()
    delivered = sum(len(d) for d in sim.deliveries)
    # one delta per counter — sigs_device and the breakdown's
    # sigs_dispatched MUST stay the same number
    # the depth-K window Simulation.run streamed dispatches through
    # (None on the pipeline-off side — its gauges then read empty)
    pipe = getattr(sim, "_verify_pipe", None)
    d_prep = getattr(verifier, "total_prepare_s", 0.0) - tot0[0]
    d_disp = getattr(verifier, "total_dispatch_s", 0.0) - tot0[1]
    d_count = getattr(verifier, "total_dispatches", 0) - tot0[2]
    d_sigs = getattr(verifier, "total_sigs_dispatched", 0) - tot0[3]
    if ps0 is not None:
        ps1 = verifier.prep_stats()
        d_rows = ps1["rows_total"] - ps0["rows_total"]
        d_rows_par = ps1["rows_parallel"] - ps0["rows_parallel"]
        prep_gauges = {
            "prep_workers": ps1["workers"],
            "prep_parallel_fraction": (
                round(d_rows_par / d_rows, 3) if d_rows > 0 else 0.0
            ),
        }
    else:
        prep_gauges = {"prep_workers": 1, "prep_parallel_fraction": 0.0}
    # round-9 resilience gauges: containment/ladder counters of this
    # rung's verify stack (all zero on a clean run — the chaos rung and
    # ladder deployments are where they move)
    rs_fn = getattr(
        pipe if pipe is not None else verifier, "resilience_stats", None
    )
    rs = rs_fn() if callable(rs_fn) else {}
    res_gauges = {
        "verify_retries": rs.get("retries", 0),
        "verify_fallback_tier": rs.get("fallback_tier", 0),
        "verify_quarantined": rs.get("quarantined", 0),
        "poisoned_windows": rs.get("poisoned_windows", 0),
        "sidecar_rpc_failures": rs.get("sidecar_rpc_failures", 0),
    }
    # round-12 host-pump gauges: which pump flavor drove the run and
    # what the host paid per round at the consensus seam (the quantity
    # the vectorized pump exists to move)
    snap0 = sim.processes[0].metrics.snapshot()
    pump_gauges = {
        k: snap0[k]
        for k in ("pump_path", "pump_msgs_per_s", "host_pump_ms_per_round")
        if k in snap0
    }
    return {
        "nodes": n,
        "coin": entry_coin,
        "pipelined": pipelined,
        # Explicit, non-interchangeable counters (pre-round-5 entries
        # used one ambiguous sigs_verified/sigs_per_sec pair):
        # *_applied = per-process verdicts applied, the aggregate a real
        # n-node cluster performs (under dedup, fanned out from unique
        # device checks); *_device = what THIS chip actually verified.
        # Without dedup the two coincide.
        "dedup": sim.dedup,
        "seconds": round(dt, 1),
        "messages": pumped,
        "sigs_applied": sigs,
        "sigs_applied_per_sec": round(sigs / dt, 1),
        "sigs_device": d_sigs,
        "sigs_device_per_sec": round(d_sigs / dt, 1),
        "vertices_delivered_total": delivered,
        # per-view DAG size (BASELINE config #3's "10k-vertex DAG" is
        # per view, not summed across the n copies)
        "vertices_delivered_per_view": max(
            (len(d) for d in sim.deliveries), default=0
        ),
        "max_round": max(p.round for p in sim.processes),
        # bounded-memory evidence: cumulative DAG size vs live window
        "vertices_live_max": max(
            len(p.dag.vertices) for p in sim.processes
        ),
        "vertices_pruned_total": sum(
            p.dag.pruned_count for p in sim.processes
        ),
        "wave_commit_p50_ms": (
            round(1e3 * waves[len(waves) // 2], 2) if waves else None
        ),
        "wave_interval_p50_ms": (
            round(1e3 * intervals[len(intervals) // 2], 2)
            if intervals
            else None
        ),
        # where the wall time went at the verifier seam (VERDICT r04 #2:
        # a shortfall must be attributable): host prep vs device
        # dispatch+sync vs everything else (admission, ordering, coin,
        # message pump)
        "verifier_breakdown": {
            "prepare_s": round(d_prep, 2),
            # LOWER BOUND on pipelined runs (ADVICE r5 #1): device time
            # hidden under the delivery-flush window or later chunks'
            # host prep never blocks resolve and books ~0 here — only
            # UNHIDDEN device time is measured
            "device_s": round(d_disp, 2),
            "host_other_s": round(max(0.0, dt - d_prep - d_disp), 2),
            "dispatches": d_count,
            "sigs_dispatched": d_sigs,
            "ms_per_dispatch": (
                round(1e3 * d_disp / d_count, 1) if d_count else None
            ),
            # depth-K window gauges (verifier/pipeline.py): configured
            # depth, in-flight high-water, and the share of seam wall
            # time the host spent working instead of blocked in resolve
            # — the amortization evidence future BENCH rounds track
            "queue_depth": getattr(pipe, "depth", 1) if pipe else 1,
            "queue_depth_max": (
                getattr(pipe, "depth_hwm", 0) if pipe else 0
            ),
            "overlap_fraction": (
                round(pipe.overlap_fraction(), 3)
                if pipe is not None and pipe.overlap_fraction() is not None
                else 0.0
            ),
            # AOT lower+compile seconds this rung paid OUTSIDE the box
            # (0.0 on a warm program / persistent-cache process)
            "warmup_compile_s": round(
                getattr(verifier, "warmup_compile_s", 0.0) - warm0, 2
            ),
            # mesh placement gauges (ShardedTPUVerifier; 1/0/0.0 on the
            # single-chip path): devices the dispatch laid out over,
            # per-shard rows of the last dispatch, and its shard fill
            # imbalance (0.0 = every shard carried equal real rows)
            "mesh_devices": getattr(verifier, "mesh_devices", 1),
            "shard_batch": getattr(verifier, "last_shard_batch", 0),
            "shard_imbalance": round(
                getattr(verifier, "last_shard_imbalance", 0.0), 3
            ),
            # parallel host-prep engine gauges (verifier/prep.py):
            # configured worker count + share of this rung's prepped
            # rows that took the row-block parallel path
            **prep_gauges,
            # fault-containment / degradation-ladder gauges (round 9)
            **res_gauges,
            # host consensus-pump gauges (round 12)
            **pump_gauges,
        },
    }


def _vec_ab_rung(n: int, budget_s: float, target_round: int) -> dict:
    """Scalar-vs-vector host pump A/B (round 12). Two null-verifier sims
    run the SAME protocol to the same target round, one per pump flavor;
    the vector path must produce byte-identical per-view delivery
    sequences (id + digest) — it is an execution strategy, not a
    protocol change — and the msgs/s ratio is the rung's headline.
    Raises AssertionError on commit-order divergence. Also the tier1-vec
    CI smoke (tests/test_bench_rungs.py)."""
    import time as _t

    from dag_rider_tpu.config import Config
    from dag_rider_tpu.consensus.simulator import Simulation

    sides: dict = {}
    orders: dict = {}
    for path in ("scalar", "vector"):
        cfg = Config(
            n=n,
            coin="round_robin",
            propose_empty=True,
            gc_depth=24,
            pump=path,
        )
        sim = Simulation(cfg)
        sim.submit_blocks(per_process=2)
        t0 = _t.monotonic()
        pumped = 0
        while (
            max(p.round for p in sim.processes) < target_round
            and _t.monotonic() - t0 < budget_s
        ):
            pumped += sim.run(max_messages=n * (n - 1))
        dt = _t.monotonic() - t0
        sim.check_agreement()
        snap0 = sim.processes[0].metrics.snapshot()
        orders[path] = [
            [(v.id, v.digest()) for v in d] for d in sim.deliveries
        ]
        sides[path] = {
            "seconds": round(dt, 2),
            "messages": pumped,
            "msgs_per_sec": round(pumped / dt, 1),
            "max_round": max(p.round for p in sim.processes),
            "vertices_delivered_total": sum(
                len(d) for d in sim.deliveries
            ),
            **{
                k: snap0[k]
                for k in ("pump_msgs_per_s", "host_pump_ms_per_round")
                if k in snap0
            },
        }
    identical = orders["scalar"] == orders["vector"]
    entry = {
        "nodes": n,
        "target_round": target_round,
        "scalar": sides["scalar"],
        "vector": sides["vector"],
        # the equivalence gate: same deliveries, same order, same
        # bytes, at every view
        "commit_order_identical": identical,
        "speedup": round(
            sides["vector"]["msgs_per_sec"]
            / max(sides["scalar"]["msgs_per_sec"], 1e-9),
            2,
        ),
    }
    if not identical:
        raise AssertionError(
            f"sim{n}_vec: vector pump diverged from scalar commit order"
        )
    return entry


def _trace_ab_rung(
    n: int, budget_s: float, target_round: int, reps: int = 9
) -> dict:
    """Trace-off vs trace-on A/B (round 16). Null-verifier sims run the
    SAME protocol to the same target round, one side with no log and one
    with the full obs bundle (ring recorder + flight watch + lifecycle/
    phase spans at sample rate 1.0); tracing must produce byte-identical
    per-view delivery sequences — events observe, they never feed
    consensus state — and the msgs/s delta is the rung's headline,
    gated at < 5% overhead. A single pump to round ~40 is sub-second,
    where one scheduler blip reads as ±30% — the headline is the median
    of per-rep PAIRED CPU-time ratios: each rep runs both sides
    back-to-back (alternating which goes first, so a co-tenant burst
    arriving mid-pair biases reps in both directions instead of always
    penalizing the second side), `time.process_time` excludes
    preemption, and the median rejects the burst-poisoned tail. Commit
    order is checked on EVERY repetition and raises AssertionError on
    divergence. Also the tier1-obs CI smoke (tests/test_bench_rungs.py)."""
    import time as _t

    from dag_rider_tpu import obs
    from dag_rider_tpu.config import Config
    from dag_rider_tpu.consensus.simulator import Simulation

    sides: dict = {}
    orders: dict = {}
    ring_stats: dict = {}
    deadline = _t.monotonic() + 2.0 * budget_s

    def one_run(path: str) -> dict:
        cfg = Config(
            n=n,
            coin="round_robin",
            propose_empty=True,
            gc_depth=24,
        )
        tracing = obs.build_tracing(sample_rate=1.0) if path == "on" else None
        sim = Simulation(
            cfg, log=tracing.log if tracing is not None else None
        )
        sim.submit_blocks(per_process=2)
        t0 = _t.monotonic()
        c0 = _t.process_time()
        pumped = 0
        while (
            max(p.round for p in sim.processes) < target_round
            and _t.monotonic() - t0 < budget_s
        ):
            pumped += sim.run(max_messages=n * (n - 1))
        dt = _t.monotonic() - t0
        cpu = _t.process_time() - c0
        sim.check_agreement()
        order = [[(v.id, v.digest()) for v in d] for d in sim.deliveries]
        if path in orders:
            if orders[path] != order:
                raise AssertionError(
                    f"trace_overhead: {path} side not reproducible at n={n}"
                )
        else:
            orders[path] = order
        if tracing is not None:
            ring_stats.update(
                trace_events=len(tracing.recorder),
                trace_dropped=tracing.recorder.dropped,
            )
        return {
            "seconds": round(dt, 2),
            "cpu_seconds": round(cpu, 3),
            "messages": pumped,
            "msgs_per_sec": round(pumped / dt, 1),
            "max_round": max(p.round for p in sim.processes),
            "vertices_delivered_total": sum(
                len(d) for d in sim.deliveries
            ),
        }

    ratios = []
    for rep in range(max(1, reps)):
        pair = {}
        first = ("off", "on") if rep % 2 == 0 else ("on", "off")
        for path in first:
            run = one_run(path)
            pair[path] = run["cpu_seconds"]
            best = sides.get(path)
            if best is None or run["msgs_per_sec"] > best["msgs_per_sec"]:
                sides[path] = run
        # paired CPU ratio: both runs of a rep share the box's load
        # state, so the ratio is far less noisy than either side's
        # absolute msgs/s on a busy host
        ratios.append(pair["on"] / max(pair["off"], 1e-9))
        if rep > 0 and _t.monotonic() > deadline:
            break  # both sides have >= 2 samples; stay inside the box
    ratios.sort()
    median_ratio = ratios[len(ratios) // 2]
    identical = orders["off"] == orders["on"]
    overhead_pct = round(100.0 * (median_ratio - 1.0), 2)
    entry = {
        "nodes": n,
        "target_round": target_round,
        "off": sides["off"],
        "on": sides["on"],
        **ring_stats,
        # the equivalence gate: same deliveries, same order, same
        # bytes, at every view — tracing is observation, not protocol
        "commit_order_identical": identical,
        "overhead_pct": overhead_pct,
        "overhead_ok": overhead_pct < 5.0,
    }
    if not identical:
        raise AssertionError(
            f"trace_overhead: tracing diverged commit order at n={n}"
        )
    return entry


def _finality_rung(
    n: int = 64,
    wall_s: float = 10.0,
    rate: float = 2000.0,
    drain_s: float = 30.0,
) -> dict:
    """ladder.finality rung (ISSUE 16): submit→deliver finality with
    pipelined waves + eager optimistic delivery, in two halves.

    Half 1 — the byte-identity gate: knobs-off vs knobs-on lockstep
    sims over a seeded n × adversary matrix must produce byte-identical
    per-view delivery sequences (id + digest), the eager reconciliation
    books must balance (delivered == reconciled) and the expected-zero
    rollback counter must read zero on every honest process. RAISES
    AssertionError on any divergence — a recorded entry IS a passed
    gate.

    Half 2 — the wall-clock headline: a mempool-fronted load run at
    ``n`` with everything on (wave pipeline, eager delivery, adaptive
    batch deadline) against a knobs-off twin. Each transaction's
    end-to-end latency is decomposed at observation time into
    queueing (submit → block built, the batcher's hold) and wave lag
    (block built → a_deliver, DAG admission + commit + flush), so the
    attribution components sum to the measured total per sample — the
    means are checked to sum exactly (float slack only). The eager
    stream's submit→early-surface p50 rides alongside as the optimistic
    finality number, and ``p50_under_1s`` records the sub-second
    acceptance gate at the knobs-on side."""
    import time as _t

    from dag_rider_tpu.config import Config, MempoolConfig
    from dag_rider_tpu.consensus.adversary import (
        ByzantineProcess,
        make_behavior,
    )
    from dag_rider_tpu.consensus.process import Process
    from dag_rider_tpu.consensus.simulator import Simulation
    from dag_rider_tpu.mempool.loadgen import (
        ClusterLoadDriver,
        LoadGenerator,
    )
    from dag_rider_tpu.utils.metrics import Histogram

    # -- half 1: identity gate over the seeded matrix ----------------------

    def one_side(sz, seed, adversary, knobs_on, cycles):
        cfg = Config(
            n=sz,
            coin="round_robin",
            propose_empty=True,
            wave_pipeline=knobs_on,
            eager_deliver=knobs_on,
            # lockstep pump: wall-clock sync throttles would starve the
            # anti-entropy recovery the withhold adversary forces
            sync_request_cooldown_s=0.0,
            sync_serve_cooldown_s=0.0,
            sync_patience=1,
        )
        nbyz = cfg.f if adversary else 0
        behaviors = {
            i: make_behavior(adversary, seed=seed + 1000 + i)
            for i in range(nbyz)
        }

        def factory(pcfg, i, ptp, **kwargs):
            if i in behaviors:
                return ByzantineProcess(
                    pcfg, i, ptp, behavior=behaviors[i], **kwargs
                )
            return Process(pcfg, i, ptp, **kwargs)

        sim = Simulation(
            cfg, process_factory=factory if behaviors else None
        )
        sim.submit_blocks(per_process=2)
        for _ in range(cycles):
            sim.run(max_messages=sz * (sz - 1))
        logs = [
            [(v.id.round, v.id.source, v.digest()) for v in d]
            for d in sim.deliveries
        ]
        return logs, sim, nbyz

    matrix = (
        (4, 1, None, 12),
        (16, 5, "equivocate", 12),
        (16, 6, "withhold", 40),
        (32, 7, None, 8),
        (64, 8, None, 8),
    )
    identity = []
    for sz, seed, adversary, cycles in matrix:
        off_logs, _, nbyz = one_side(sz, seed, adversary, False, cycles)
        on_logs, sim, _ = one_side(sz, seed, adversary, True, cycles)
        if not any(off_logs[nbyz:]):
            raise AssertionError(
                f"finality identity n={sz} {adversary}: oracle "
                "delivered nothing — vacuous gate"
            )
        if off_logs != on_logs:
            raise AssertionError(
                f"finality identity n={sz} {adversary}: knobs-on "
                "commit order diverged from the oracle"
            )
        eager_del = eager_rec = 0
        for i, p in enumerate(sim.processes):
            if i < nbyz:
                continue
            snap = p.metrics.snapshot()
            if snap.get("eager_rollbacks_expected_zero", 0):
                raise AssertionError(
                    f"finality identity n={sz} {adversary}: eager "
                    "rollback counter nonzero on an honest process"
                )
            eager_del += snap.get("eager_delivered", 0)
            eager_rec += snap.get("eager_reconciled", 0)
        if eager_del != eager_rec:
            raise AssertionError(
                f"finality identity n={sz} {adversary}: eager books "
                f"unbalanced ({eager_del} surfaced, {eager_rec} "
                "reconciled)"
            )
        identity.append(
            {
                "n": sz,
                "seed": seed,
                "adversary": adversary or "clean",
                "delivered_view0": len(off_logs[nbyz]),
                "eager_delivered": eager_del,
            }
        )

    # -- half 2: wall-clock latency + attribution at n ---------------------

    class _AttribDriver(ClusterLoadDriver):
        """ClusterLoadDriver that splits every closed latency book into
        its two exhaustive components at the same timestamps the total
        uses, so component means sum to the total mean exactly."""

        def __init__(self, *a, **kw):
            super().__init__(*a, **kw)
            self.batch_wait = Histogram()
            self.wave_lag = Histogram()
            self.sum_batch = 0.0
            self.sum_wave = 0.0
            self.sum_total = 0.0
            self.n_attr = 0
            self._built_at = {}
            for mp in self.mempools:
                orig = mp.observe_delivered

                def wrapped(block, now=None, mp=mp, orig=orig):
                    t = mp.clock() if now is None else now
                    for tx in block.transactions:
                        t0 = mp._inflight.get(tx)
                        if t0 is None:
                            # not this view's transaction — leave the
                            # build stamp for the origin mempool's pass
                            continue
                        tb = self._built_at.pop(tx, None)
                        if tb is not None:
                            bw = max(0.0, tb - t0)
                            wl = max(0.0, t - tb)
                            self.batch_wait.observe(bw)
                            self.wave_lag.observe(wl)
                            self.sum_batch += bw
                            self.sum_wave += wl
                            self.sum_total += max(0.0, t - t0)
                            self.n_attr += 1
                    orig(block, now=now)

                mp.observe_delivered = wrapped

        def _flush_batches(self, t, force=False):
            now = None if self.wall else t
            for i, mp in enumerate(self.mempools):
                staged = len(self.sim.processes[i].blocks_to_propose)
                blocks = mp.build_blocks(
                    now=now, force=force, staged=staged
                )
                tb = mp.clock() if now is None else now
                for b in blocks:
                    for tx in b.transactions:
                        if tx in mp._inflight:
                            self._built_at[tx] = tb
                    self.sim.processes[i].submit(b)
                    self.submission_log.append((self.cycles, i, b))

    sides: dict = {}
    attribution: dict = {}
    eager_lat = Histogram()
    for path in ("off", "on"):
        on = path == "on"
        cfg = Config(
            n=n,
            coin="round_robin",
            propose_empty=True,
            gc_depth=24,
            wave_pipeline=on,
            eager_deliver=on,
        )
        sim = Simulation(cfg)
        gen = LoadGenerator(
            clients=32, rate=rate, tx_bytes=32, seed=16, profile="poisson"
        )
        drv = _AttribDriver(
            sim,
            gen,
            mcfg=MempoolConfig(
                cap=65536, batch_bytes=4096, adaptive_deadline=on
            ),
            wall=True,
        )
        if on:
            # submit→early-surface latency: the optimistic finality a
            # client acting on the speculative stream would see (books
            # stay open — the canonical a_deliver still closes them)
            for p, mp, esink in zip(
                sim.processes, drv.mempools, sim.eager_deliveries
            ):

                def early(v, mp=mp, esink=esink):
                    t = mp.clock()
                    for tx in v.block.transactions:
                        t0 = mp._inflight.get(tx)
                        if t0 is not None:
                            eager_lat.observe(max(0.0, t - t0))
                    esink.append(v)

                p.on_deliver_early = early
        entry = drv.run(wall_s, drain_s=drain_s)
        sim.check_agreement()
        if entry["audit"]["lost"] or entry["audit"]["duplicates"]:
            raise AssertionError(
                f"finality {path}: audit failed: {entry['audit']}"
            )
        entry["verifier"] = "none"
        if drv.n_attr:
            mean_batch = 1e3 * drv.sum_batch / drv.n_attr
            mean_wave = 1e3 * drv.sum_wave / drv.n_attr
            mean_total = 1e3 * drv.sum_total / drv.n_attr
            snap = sim.processes[0].metrics.snapshot()
            attribution[path] = {
                "samples": drv.n_attr,
                # queueing: submit → block built (the batcher's hold)
                "batch_wait_ms_mean": round(mean_batch, 3),
                "batch_wait_ms_p50": round(
                    1e3 * drv.batch_wait.percentile(50), 3
                ),
                # wave lag: block built → a_deliver (admission + DAG
                # rounds + wave commit + flush); the host pump floor
                # rides inside it and is reported for context
                "wave_lag_ms_mean": round(mean_wave, 3),
                "wave_lag_ms_p50": round(
                    1e3 * drv.wave_lag.percentile(50), 3
                ),
                "total_ms_mean": round(mean_total, 3),
                "host_pump_ms_per_round": snap.get(
                    "host_pump_ms_per_round"
                ),
                "deadline_ms_effective": snap.get("deadline_ms_effective"),
            }
            if abs(mean_total - (mean_batch + mean_wave)) > 0.05:
                raise AssertionError(
                    f"finality {path}: attribution components do not "
                    f"sum to the measured total ({mean_batch:.3f} + "
                    f"{mean_wave:.3f} != {mean_total:.3f} ms)"
                )
        sides[path] = entry

    p50_on = sides["on"].get("submit_deliver_p50_ms")
    entry = {
        "nodes": n,
        "wall_s": wall_s,
        "offered_rate": rate,
        "identity": identity,
        # half 1 raises on divergence, so reaching here means the gate
        # held across the whole matrix
        "commit_order_identical": True,
        "off": sides["off"],
        "on": sides["on"],
        "attribution": attribution,
        "p50_under_1s": bool(p50_on is not None and p50_on < 1000.0),
    }
    if len(eager_lat):
        entry["submit_eager_p50_ms"] = round(
            1e3 * eager_lat.percentile(50), 3
        )
    return entry


def _lanes_ab_rung(
    n: int = 64,
    sizes: tuple = (131072, 524288, 2097152),
    cycles: int = 6,
    sweep: tuple = (1, 2, 4),
) -> dict:
    """ladder.lanes rung (ISSUE 17): sharded dissemination lanes —
    digest-only ordering with parallel payload workers — in two halves.

    Half 1 — the byte-identity gate: lanes-on vs inline lockstep sims
    over a seeded n × adversary × pump matrix must produce the same
    per-view commit order (round, source) AND the same delivered
    payload bytes (sha256 over the length-prefixed transaction stream,
    post lane-store resolution). RAISES AssertionError on any
    divergence — a recorded entry IS a passed gate.

    Half 2 — the throughput headline at ``n`` with Ed25519-signed
    vertices (verifier="cpu" — the keyless sim passes vertex objects by
    reference, so inline dissemination there is literally free and an
    A/B against it would be meaningless): committed payload bytes per
    second of ordering-path (pump) time, lanes vs inline, as block
    weight grows 16x. The pump is the metric because it is the claim —
    lanes exist to keep payload weight OFF the consensus critical path;
    signature verification is already coalesced/offloaded outside the
    pump window on both sides. Each burst is submitted and (lanes side)
    flushed before pumping — steady-state pipelining, where worker
    lanes disseminate a burst while ordering runs. ``throughput_2x``
    records the >=2x acceptance gate at 4 workers and the top block
    size; ``pump_flat_1p3x`` records lanes' host_pump_ms_per_round
    staying within 1.3x across the 16x size growth (inline's grows with
    block weight — that gap IS the win). A worker sweep at the top size
    rides alongside."""
    import hashlib
    import time as _t

    from dag_rider_tpu.config import Config
    from dag_rider_tpu.consensus.adversary import (
        ByzantineProcess,
        make_behavior,
    )
    from dag_rider_tpu.consensus.process import Process
    from dag_rider_tpu.consensus.simulator import Simulation
    from dag_rider_tpu.core.types import Block

    # -- half 1: identity gate over the seeded matrix ----------------------

    def identity_side(sz, seed, adversary, pump, lanes, id_cycles):
        cfg = Config(
            n=sz,
            coin="round_robin",
            propose_empty=True,
            pump=pump,
            lanes=lanes,
            lane_batch_bytes=256,
            sync_request_cooldown_s=0.0,
            sync_serve_cooldown_s=0.0,
            sync_patience=1,
        )
        nbyz = cfg.f if adversary else 0
        behaviors = {
            i: make_behavior(adversary, seed=seed + 1000 + i)
            for i in range(nbyz)
        }

        def factory(pcfg, i, ptp, **kwargs):
            if i in behaviors:
                return ByzantineProcess(
                    pcfg, i, ptp, behavior=behaviors[i], **kwargs
                )
            return Process(pcfg, i, ptp, **kwargs)

        sim = Simulation(
            cfg, process_factory=factory if behaviors else None
        )
        sim.submit_blocks(2, tx_bytes=600)  # above the 256-byte floor
        for _ in range(id_cycles):
            sim.run(max_messages=sz * (sz - 1))
        orders, digests = [], []
        for view in sim.deliveries[nbyz:]:
            orders.append([(v.id.round, v.id.source) for v in view])
            h = hashlib.sha256()
            for v in view:
                for tx in v.block.transactions:
                    h.update(len(tx).to_bytes(4, "little"))
                    h.update(tx)
            digests.append(h.hexdigest())
        return orders, digests, sim, nbyz

    id_matrix = (
        (4, 21, None, 12),
        (16, 22, "equivocate", 12),
        (16, 23, "lane_withhold", 12),
        (32, 24, None, 10),
    )
    identity = []
    for sz, seed, adversary, id_cycles in id_matrix:
        for pump in ("scalar", "vector"):
            ref_o, ref_d, _, nbyz = identity_side(
                sz, seed, adversary, pump, False, id_cycles
            )
            lane_o, lane_d, sim, _ = identity_side(
                sz, seed, adversary, pump, True, id_cycles
            )
            if not any(ref_o):
                raise AssertionError(
                    f"lanes identity n={sz} {adversary} {pump}: oracle "
                    "delivered nothing — vacuous gate"
                )
            if ref_o != lane_o:
                raise AssertionError(
                    f"lanes identity n={sz} {adversary} {pump}: commit "
                    "order diverged from the inline oracle"
                )
            if ref_d != lane_d:
                raise AssertionError(
                    f"lanes identity n={sz} {adversary} {pump}: "
                    "delivered payload bytes diverged from the oracle"
                )
            certified = sum(
                p.metrics.counters.get("lane_batches_certified", 0)
                for p in sim.processes
            )
            if adversary != "lane_withhold" and not certified:
                raise AssertionError(
                    f"lanes identity n={sz} {adversary} {pump}: no "
                    "batch ever certified — blocks shipped inline, "
                    "vacuous gate"
                )
            identity.append(
                {
                    "n": sz,
                    "seed": seed,
                    "adversary": adversary or "clean",
                    "pump": pump,
                    "delivered_view0": len(ref_o[0]),
                    "lane_batches_certified": certified,
                }
            )

    # -- half 2: committed-bytes/s per pump-second at n --------------------

    def tput_side(size, lanes, workers):
        import gc

        # drain the previous side's multi-hundred-MB object graph before
        # timing this one — a generational collection landing mid-pump
        # charges the victim side a triple-digit-ms pause it didn't earn
        gc.collect()
        cfg = Config(
            n=n, lanes=lanes, lane_workers=workers, lane_batch_bytes=4096
        )
        sim = Simulation(cfg, verifier="cpu")
        p0 = sim.processes[0]
        acc = {"bytes": 0, "txs": 0}

        def on_dlv(v, acc=acc):
            for tx in v.block.transactions:
                acc["bytes"] += len(tx)
                acc["txs"] += 1

        p0.on_deliver = on_dlv
        # borrow the collector off for the timed box (restored in
        # finally): both sides get the same allocator behavior and no
        # side eats a mid-pump generational pause
        gc_was = gc.isenabled()
        gc.disable()
        try:
            t0 = _t.perf_counter()
            for c in range(cycles):
                for p in sim.processes:
                    p.submit(
                        Block(
                            (
                                f"c{c}-p{p.index}".encode().ljust(
                                    size, b"."
                                ),
                            )
                        )
                    )
                if lanes and sim.lane_bus is not None:
                    # steady-state pipelining: the worker lanes finish
                    # disseminating the burst before ordering pumps it
                    # (in sustained operation this overlaps the previous
                    # burst's ordering)
                    sim.lane_bus.flush()
                sim.run(max_messages=2 * n * n)
            sim.run(max_messages=4 * n * n)
            wall = _t.perf_counter() - t0
        finally:
            if gc_was:
                gc.enable()
        m = p0.metrics
        # land delivered bytes in the metrics seam so the snapshot
        # derives the committed_bytes_per_s gauge (the same path a
        # mempool-fronted node exercises)
        m.observe_mempool({"delivered_bytes": acc["bytes"]})
        snap = m.snapshot()
        return {
            "delivered_txs": acc["txs"],
            "delivered_bytes": acc["bytes"],
            "wall_s": round(wall, 2),
            "host_pump_ms_per_round": snap.get(
                "host_pump_ms_per_round"
            ),
            "committed_bytes_per_s": snap.get("committed_bytes_per_s"),
        }

    def best_of(runs, size, lanes, workers):
        # best-of-k by pump floor: the box this runs on shares its core,
        # and a neighbor's burst landing mid-pump inflates one run's
        # floor by triple-digit ms; the minimum is the reproducible cost
        best = None
        for _ in range(runs):
            side = tput_side(size, lanes, workers)
            if (
                best is None
                or side["host_pump_ms_per_round"]
                < best["host_pump_ms_per_round"]
            ):
                best = side
        return best

    ab = []
    for size in sizes:
        inline = best_of(2, size, False, 4)
        laned = best_of(2, size, True, 4)
        if inline["delivered_txs"] != laned["delivered_txs"]:
            raise AssertionError(
                f"lanes A/B size={size}: delivered tx counts diverged "
                f"({inline['delivered_txs']} vs {laned['delivered_txs']})"
            )
        ratio = (
            laned["committed_bytes_per_s"]
            / inline["committed_bytes_per_s"]
            if inline["committed_bytes_per_s"]
            else 0.0
        )
        ab.append(
            {
                "block_bytes": size,
                "inline": inline,
                "lanes": laned,
                "committed_bytes_ratio": round(ratio, 2),
            }
        )

    workers_sweep = []
    for w in sweep:
        side = tput_side(sizes[-1], True, w)
        workers_sweep.append(
            {
                "workers": w,
                "wall_s": side["wall_s"],
                "host_pump_ms_per_round": side["host_pump_ms_per_round"],
                "committed_bytes_per_s": side["committed_bytes_per_s"],
            }
        )

    lane_pumps = [e["lanes"]["host_pump_ms_per_round"] for e in ab]
    flatness = (
        max(lane_pumps) / min(lane_pumps) if min(lane_pumps) else 0.0
    )
    top = ab[-1]
    return {
        "nodes": n,
        "block_bytes": list(sizes),
        "cycles": cycles,
        "verifier": "cpu",
        "identity": identity,
        # half 1 raises on divergence, so reaching here means both
        # gates held across the whole matrix
        "commit_order_identical": True,
        "delivered_bytes_identical": True,
        "ab": ab,
        "workers_sweep": workers_sweep,
        "committed_bytes_ratio_top": top["committed_bytes_ratio"],
        "throughput_2x": top["committed_bytes_ratio"] >= 2.0,
        "lane_pump_flatness": round(flatness, 2),
        "pump_flat_1p3x": bool(flatness and flatness <= 1.3),
    }


def _agg_ladder_rung(sizes=(64, 256)) -> dict:
    """verify_n256_agg ladder rung (round 13): component costs of the
    aggregated round-certificate check at committee quorums vs the
    per-vertex ed25519 reference.

    Honesty notes on "flat in n": what is flat is the signature-OP count
    (one aggregate check per round regardless of n, vs n per-vertex
    verifies) and the per-vertex-AMORTIZED check cost (``agg_check_warm_s
    / n`` — the shared Miller squarings and the single final
    exponentiation amortize over a bigger round). The raw host check
    still grows with the pair count — sublinearly (4x pairs should cost
    well under 4x wall; that ratio is ``agg_check_growth``) but it
    grows; the device-work claim is carried by the op counts and the MSM
    seam, not by host pairing wall time."""
    import hashlib
    import time as _t

    from dag_rider_tpu.crypto import bls12381 as _bls
    from dag_rider_tpu.crypto import ed25519 as _ed
    from dag_rider_tpu.ops import bls_msm as _msm
    from dag_rider_tpu.verifier.base import CertSigner, KeyRegistry
    from dag_rider_tpu.verifier.cert import CertVerifier

    entry: dict = {"sizes": {}}
    for n in sizes:
        q = _quorum(n)
        reg, _seeds, sks = KeyRegistry.generate_with_cert(n)
        cv = CertVerifier(reg, q, msm="host")
        digests = [
            hashlib.sha256(b"agg-rung|%d|%d" % (n, i)).digest()
            for i in range(q)
        ]
        signers = [CertSigner(sk) for sk in sks[:q]]
        t0 = _t.monotonic()
        shares = [
            s.sign_digest(d) for s, d in zip(signers, digests)
        ]
        sign_s = _t.monotonic() - t0
        t0 = _t.monotonic()
        cert = cv.make_certificate(
            1, list(zip(range(q), digests, shares))
        )
        assemble_s = _t.monotonic() - t0
        # the device MSM seam must land on the host group-law point;
        # compile outside the timed box (each padded batch size is its
        # own program) and report the warm dispatch. The half is
        # skippable: on the 1-core fallback the compile alone can eat
        # minutes at the n=256 padding.
        size_entry_extra: dict = {}
        if os.environ.get("DAGRIDER_BENCH_AGG_DEVMSM", "1") == "1":
            pts = [_bls.g1_decompress(s) for s in shares]
            t0 = _t.monotonic()
            dev_pt = _msm.sum_points(pts)  # compile + run
            compile_s = _t.monotonic() - t0
            t0 = _t.monotonic()
            dev_pt = _msm.sum_points(pts)
            msm_device_s = _t.monotonic() - t0
            msm_match = _bls.g1_compress(dev_pt) == cert.agg_sig
            size_entry_extra = {
                "msm_device_ms": round(msm_device_s * 1000, 1),
                "msm_device_compile_s": round(compile_s, 1),
                "msm_match": msm_match,
            }
        else:
            msm_match = True
        # _check (not verify_certificate): the memo would turn the warm
        # timings into dict hits
        t0 = _t.monotonic()
        ok_cold = cv._check(cert)
        cold_s = _t.monotonic() - t0
        warms = []
        for _ in range(2):
            t0 = _t.monotonic()
            ok_warm = cv._check(cert)
            warms.append(_t.monotonic() - t0)
        warm_s = min(warms)
        if not (ok_cold and ok_warm and msm_match):
            raise AssertionError(
                f"agg rung n={n}: check/MSM disagreement "
                f"(cold={ok_cold} warm={ok_warm} msm={msm_match})"
            )
        # per-vertex reference: the n ed25519 verifies the round costs
        # every receiver without the certificate
        esk, epk = _ed.generate_keypair(
            hashlib.sha256(b"agg-rung-ed|%d" % n).digest()
        )
        msgs = [
            hashlib.sha256(b"agg-rung-msg|%d|%d" % (n, i)).digest()
            for i in range(n)
        ]
        esigs = [_ed.sign(esk, m) for m in msgs]
        _ed.verify(epk, msgs[0], esigs[0])  # warm the comb tables
        t0 = _t.monotonic()
        for m, s in zip(msgs, esigs):
            if not _ed.verify(epk, m, s):
                raise AssertionError("ed25519 reference verify failed")
        ref_s = _t.monotonic() - t0
        entry["sizes"][str(n)] = {
            "quorum": q,
            "pairs": q + 1,
            "share_sign_ms_per_vertex": round(sign_s / q * 1000, 2),
            "assemble_ms": round(assemble_s * 1000, 1),
            **size_entry_extra,
            "agg_check_cold_s": round(cold_s, 3),
            "agg_check_warm_s": round(warm_s, 3),
            "agg_check_ms_per_vertex": round(warm_s / n * 1000, 2),
            "per_vertex_ed25519_s": round(ref_s, 3),
            "per_vertex_ms_per_sig": round(ref_s / n * 1000, 2),
            "verify_ops_agg": 1,
            "verify_ops_per_vertex": n,
        }
    lo, hi = str(sizes[0]), str(sizes[-1])
    a, b = entry["sizes"][lo], entry["sizes"][hi]
    entry["pairs_growth"] = round(b["pairs"] / a["pairs"], 2)
    entry["agg_check_growth"] = round(
        b["agg_check_warm_s"] / a["agg_check_warm_s"], 2
    )
    entry["per_vertex_growth"] = round(
        b["per_vertex_ed25519_s"] / a["per_vertex_ed25519_s"], 2
    )
    # the acceptance headline: per-round verify cost amortized per
    # vertex stays ~flat (within 2x) on the agg path while the
    # per-vertex path pays linearly more ops
    entry["agg_ms_per_vertex_growth"] = round(
        b["agg_check_ms_per_vertex"] / a["agg_check_ms_per_vertex"], 2
    )
    entry["agg_per_vertex_flat_within_2x"] = (
        entry["agg_ms_per_vertex_growth"] <= 2.0
    )
    entry["verify_ops_growth_agg"] = 1.0
    return entry


def _cert_ab_rung(n: int, blocks: int = 6) -> dict:
    """Aggregated-certificate sim A/B (round 13): paired cert-on /
    cert-off runs — same committee, same blocks, same vector pump, same
    shared CPU-oracle verifier — compared delivery-log to delivery-log.
    ``sigs_device`` sums each process's requested verify dispatches
    (``verify_sigs_total``, counted BEFORE the in-process cluster's
    cross-process dedup — i.e. what every node's own device pays in a
    real deployment, n-1 per round per receiver); the certificate path
    must cut the cluster-wide count by ~n while the commit order stays
    byte-identical. Raises on divergence."""
    import time as _t

    from dag_rider_tpu.config import Config
    from dag_rider_tpu.consensus.simulator import Simulation
    from dag_rider_tpu.core.types import Block

    sides: dict = {}
    orders: dict = {}
    for mode in ("per_vertex", "agg"):
        cfg = Config(
            n=n, coin="round_robin", propose_empty=False, pump="vector"
        )
        sim = Simulation(cfg, verifier="cpu", cert=(mode == "agg"))
        for i in range(n):
            for k in range(blocks):
                sim.processes[i].submit(
                    Block((f"p{i}-blk{k}".encode().ljust(32, b"."),))
                )
        t0 = _t.monotonic()
        sim.run(max_messages=100 * n * n)
        dt = _t.monotonic() - t0
        sim.check_agreement()
        snaps = [p.metrics.snapshot() for p in sim.processes]
        orders[mode] = [
            [(v.id, v.digest()) for v in d] for d in sim.deliveries
        ]
        sides[mode] = {
            "seconds": round(dt, 2),
            "sigs_device": sum(
                s.get("verify_sigs_total", 0) for s in snaps
            ),
            "certs_assembled": sum(
                s.get("certs_assembled", 0) for s in snaps
            ),
            "certs_rejected": sum(
                s.get("certs_rejected", 0) for s in snaps
            ),
            "cert_timeouts": sum(
                s.get("cert_timeouts", 0) for s in snaps
            ),
            "sigs_saved": sum(s.get("sigs_saved", 0) for s in snaps),
            "cert_fastpath_fraction": round(
                sum(s.get("cert_fastpath_fraction", 0.0) for s in snaps)
                / len(snaps),
                4,
            ),
            "max_round": max(p.round for p in sim.processes),
            "vertices_delivered_total": sum(
                len(d) for d in sim.deliveries
            ),
        }
    identical = orders["per_vertex"] == orders["agg"]
    ref_sigs = max(sides["per_vertex"]["sigs_device"], 1)
    entry = {
        "nodes": n,
        "blocks_per_process": blocks,
        "per_vertex": sides["per_vertex"],
        "agg": sides["agg"],
        "commit_order_identical": identical,
        "sigs_device_drop": round(
            ref_sigs / max(sides["agg"]["sigs_device"], 1), 1
        ),
    }
    if not identical:
        raise AssertionError(
            f"sim{n}_agg: certificate path diverged from per-vertex "
            "commit order"
        )
    return entry


def _cert_phase2_rung(n: int = 256, span: int = 4) -> dict:
    """cert_phase2 ladder rung (ISSUE 12): the three stacked certificate
    optimizations priced against their own oracles.

    - sign: the round's quorum of share signatures, sequential host loop
      vs sign_many through the native cffi Montgomery kernels (the
      toolchain is warmed OUTSIDE the timed region — round-14 lesson:
      an unwarmed first call times the ~0.7s cffi compile, not the
      math). Acceptance: >=3x at the n=256 quorum. The device lane is
      the same seam on the field381 limb kernels; its local numbers are
      compile-dominated, so it rides behind DAGRIDER_BENCH_CERT2_DEV=1
      with byte-identity asserted whenever it runs.
    - assemble: aggregator-side cost with and without the pre-gossip
      self-check (DAGRIDER_CERT_SELFCHECK both ways).
    - span_replay: the cert-of-certs catch-up story — a fresh consumer
      settling R rounds through R/span combined checks; acceptance is
      pairing_checks/round < 1 with the spans restating exactly the
      per-round claims.
    - sim: live span-on / span-off / cert-off triple A/B at a small
      committee, byte-identical commit order required.
    """
    import hashlib
    import time as _t

    from dag_rider_tpu.crypto import bls12381 as _bls
    from dag_rider_tpu.verifier.base import CertSigner, KeyRegistry
    from dag_rider_tpu.verifier.cert import CertVerifier

    entry: dict = {"nodes": n, "span": span}

    # -- share signing: sequential vs batched native ---------------------
    q = _quorum(n)
    reg, _seeds, sks = KeyRegistry.generate_with_cert(n)
    digests = [
        hashlib.sha256(b"cert2-rung|%d|%d" % (n, i)).digest()
        for i in range(q)
    ]
    qsks = sks[:q]
    signers = [CertSigner(sk) for sk in qsks]
    t0 = _t.monotonic()
    seq = [s.sign_digest(d) for s, d in zip(signers, digests)]
    host_s = _t.monotonic() - t0
    from dag_rider_tpu.ops import native381 as _nat

    native_ready = _nat.available()  # compile OUTSIDE the timed region
    if native_ready:
        _bls.sign_many(qsks[:2], digests[:2], backend="native")  # warm
    t0 = _t.monotonic()
    batched = _bls.sign_many(qsks, digests, backend="native")
    native_s = _t.monotonic() - t0
    if batched != seq:
        raise AssertionError("cert2 rung: sign_many diverged from sign")
    entry["sign"] = {
        "quorum": q,
        "native_toolchain": native_ready,
        "host_ms_per_vertex": round(host_s / q * 1000, 2),
        "native_ms_per_vertex": round(native_s / q * 1000, 2),
        "native_speedup_x": round(host_s / max(native_s, 1e-9), 2),
    }
    if os.environ.get("DAGRIDER_BENCH_CERT2_DEV", "") == "1":
        dev_sks, dev_digests = qsks[:8], digests[:8]
        dev = _bls.sign_many(dev_sks, dev_digests, backend="device")
        t0 = _t.monotonic()
        dev = _bls.sign_many(dev_sks, dev_digests, backend="device")
        dev_s = _t.monotonic() - t0
        if dev != seq[:8]:
            raise AssertionError("cert2 rung: device sign diverged")
        entry["sign"]["device_ms_per_vertex_warm"] = round(
            dev_s / 8 * 1000, 2
        )
    else:
        entry["sign"]["device_note"] = (
            "device lane byte-identity is pinned by tests/"
            "test_cert_phase2.py; local wall time is compile-dominated "
            "(DAGRIDER_BENCH_CERT2_DEV=1 to time the warm dispatch)"
        )

    # -- assembly: self-check on vs off ----------------------------------
    cv = CertVerifier(reg, q, msm="host")
    entries_q = list(zip(range(q), digests, seq))
    t0 = _t.monotonic()
    cert = cv.make_certificate(1, entries_q)
    assemble_s = _t.monotonic() - t0
    t0 = _t.monotonic()
    if not cv._check(cert):
        raise AssertionError("cert2 rung: assembled certificate invalid")
    selfcheck_s = _t.monotonic() - t0
    entry["assemble"] = {
        "assemble_ms": round(assemble_s * 1000, 1),
        "selfcheck_ms": round(selfcheck_s * 1000, 1),
        "assemble_with_selfcheck_ms": round(
            (assemble_s + selfcheck_s) * 1000, 1
        ),
    }

    # -- span replay: R rounds settled in R/span combined checks ---------
    sn = 16
    sq = _quorum(sn)
    sreg, _sseeds, ssks = KeyRegistry.generate_with_cert(sn)
    maker = CertVerifier(sreg, sq, msm="host")
    epochs = 2
    rounds = span * epochs
    certs = []
    for r in range(1, rounds + 1):
        ds = [
            hashlib.sha256(b"cert2-span|%d|%d" % (r, i)).digest()
            for i in range(sq)
        ]
        shares = _bls.sign_many(ssks[:sq], ds, backend="native")
        certs.append(
            maker.make_certificate(r, list(zip(range(sq), ds, shares)))
        )
    spans = [
        maker.make_span(e * span + 1, certs[e * span : (e + 1) * span])
        for e in range(epochs)
    ]
    consumer = CertVerifier(sreg, sq, msm="host")
    t0 = _t.monotonic()
    if not all(consumer.verify_span(s) for s in spans):
        raise AssertionError("cert2 rung: span replay verify failed")
    span_s = _t.monotonic() - t0
    per_round = CertVerifier(sreg, sq, msm="host")
    t0 = _t.monotonic()
    if not all(per_round.verify_certificate(c) for c in certs):
        raise AssertionError("cert2 rung: per-round replay verify failed")
    round_s = _t.monotonic() - t0
    entry["span_replay"] = {
        "nodes": sn,
        "rounds": rounds,
        "pairing_checks_span": consumer.stats["pairing_checks"],
        "pairing_checks_per_round": round(
            consumer.stats["pairing_checks"] / rounds, 3
        ),
        "pairing_checks_per_round_cert_path": round(
            per_round.stats["pairing_checks"] / rounds, 3
        ),
        "span_replay_s": round(span_s, 3),
        "per_round_replay_s": round(round_s, 3),
        "replay_speedup_x": round(round_s / max(span_s, 1e-9), 2),
    }

    # -- live sim: span-on / span-off / cert-off triple A/B --------------
    from dag_rider_tpu.config import Config
    from dag_rider_tpu.consensus.simulator import Simulation
    from dag_rider_tpu.core.types import Block

    sides: dict = {}
    orders: dict = {}
    for mode in ("per_vertex", "cert", "span"):
        cfg = Config(
            n=sn,
            coin="round_robin",
            propose_empty=False,
            pump="vector",
            cert_span=span if mode == "span" else 0,
        )
        sim = Simulation(cfg, verifier="cpu", cert=(mode != "per_vertex"))
        for i in range(sn):
            for k in range(6):
                sim.processes[i].submit(
                    Block((f"c2-p{i}-b{k}".encode().ljust(32, b"."),))
                )
        t0 = _t.monotonic()
        sim.run(max_messages=100 * sn * sn)
        dt = _t.monotonic() - t0
        sim.check_agreement()
        snaps = [p.metrics.snapshot() for p in sim.processes]
        orders[mode] = [
            [(v.id, v.digest()) for v in d] for d in sim.deliveries
        ]
        side = {
            "seconds": round(dt, 2),
            "sigs_device": sum(
                s.get("verify_sigs_total", 0) for s in snaps
            ),
            "max_round": max(p.round for p in sim.processes),
        }
        if mode != "per_vertex":
            side["certs_assembled"] = sum(
                s.get("certs_assembled", 0) for s in snaps
            )
            side["pairing_checks"] = sim.cert_verifier.stats[
                "pairing_checks"
            ]
        if mode == "span":
            side["spans_assembled"] = sum(
                s.get("spans_assembled", 0) for s in snaps
            )
            side["span_rounds_settled"] = sum(
                s.get("span_rounds_settled", 0) for s in snaps
            )
        sides[mode] = side
    identical = orders["per_vertex"] == orders["cert"] == orders["span"]
    entry["sim"] = {
        "nodes": sn,
        "per_vertex": sides["per_vertex"],
        "cert": sides["cert"],
        "span": sides["span"],
        "commit_order_identical": identical,
    }
    if not identical:
        raise AssertionError(
            "cert_phase2: span path diverged from per-round/per-vertex "
            "commit order"
        )
    return entry


def _cluster_e2e_rung(
    n: int = 4,
    load_s: float = 6.0,
    rate: float = 300.0,
    transport: str = "uds",
    seed: int = 7,
    boot_s: float = 15.0,
) -> dict:
    """Ladder rung (ISSUE 19): the full stack as n separate OS processes
    over real sockets. Two cells:

    - **clean**: boot n nodes, drive seeded open-loop load through the
      wire-level Submit door, stop, audit. Reports committed-tx/s and
      wire submit→deliver p50/p99.
    - **kill_rejoin**: same load, but one node (seeded pick, never the
      client's primary) gets a genuine SIGKILL mid-load, then restarts
      from its checkpoint + WAL and rejoins via snapshot sync.

    Gates (the rung RAISES on any): both audits clean — commit-order
    agreement (rejoiner checked as an order-preserving embedding), zero
    lost accepted transactions, no duplicate delivery, liveness, empty
    flight recorders; byte-identical committed prefix across the steady
    nodes of each cell; the kill cell genuinely killed and restarted;
    and the clean cell committed something.
    """
    import shutil
    import tempfile
    import threading as _th

    from dag_rider_tpu.cluster import audit as _caudit
    from dag_rider_tpu.cluster import client as _cclient
    from dag_rider_tpu.cluster.directory import build_cluster
    from dag_rider_tpu.cluster.supervisor import (
        ClusterSupervisor,
        seeded_kill_plan,
    )

    def _cell(name: str, plan: list) -> dict:
        root = tempfile.mkdtemp(prefix=f"dagrider-bench-{name}-")
        spec = build_cluster(root, n, transport=transport, seed=seed)
        sup = ClusterSupervisor(spec)
        t0 = time.monotonic()
        sup.start_all()
        not_ready = sup.wait_ready(boot_s)
        if not_ready:
            sup.stop_all()
            raise AssertionError(
                f"cluster_e2e {name}: nodes {not_ready} not ready in "
                f"{boot_s}s (workspace kept at {root})"
            )
        boot_wall = time.monotonic() - t0
        load: dict = {}
        loader = _th.Thread(
            target=lambda: load.update(
                _cclient.drive_load(
                    spec, duration_s=load_s, rate=rate, seed=seed
                )
            ),
            daemon=True,
        )
        loader.start()
        executed = sup.run_plan(plan)
        loader.join(timeout=load_s + 60)
        if executed:
            sup.wait_ready(boot_s)
        _th.Event().wait(1.5)  # settle: let in-flight waves commit
        forced = sup.stop_all()
        report = _caudit.audit_cluster(
            spec, restarted=sup.restart_counts.keys()
        )
        # byte-identical committed prefix across the steady nodes (a
        # rejoiner's log — supervised restart or an audit-detected
        # mid-run state transfer — has a legitimate recovery gap and is
        # covered by the embedding check inside the audit)
        steady = [
            i for i in range(n) if i not in report["rejoined"]
        ] or list(range(n))
        recs = {
            i: _caudit._records(
                _caudit.read_delivery_log(spec.nodes[i].delivery_log)
            )
            for i in steady
        }
        k = min(len(r) for r in recs.values())
        prefix_identical = (
            len({tuple(r[:k]) for r in recs.values()}) == 1
        )
        entry = {
            "nodes": n,
            "transport": transport,
            "boot_s": round(boot_wall, 2),
            "load": load,
            "fault_plan": executed,
            "kills": dict(sup.kill_counts),
            "restarts": dict(sup.restart_counts),
            "forced_stops": forced,
            "ok": report["ok"],
            "violations": report["violations"],
            "accepted_tx": report["accepted_tx"],
            "delivered_tx": report["delivered_tx"],
            "in_flight_tx": report["in_flight_tx"],
            "lost_tx": report["lost_tx"],
            "duplicate_tx": report["duplicate_tx"],
            "decided_waves": report["decided_waves"],
            "flight_dump_files": report["flight_dump_files"],
            "committed_tx_per_sec": round(
                report["delivered_tx"] / load_s, 1
            ),
            "prefix_identical": prefix_identical,
            "common_prefix_len": k,
        }
        for key in (
            "submit_deliver_p50_ms",
            "submit_deliver_p99_ms",
            "latency_samples",
        ):
            if key in report:
                entry[key] = report[key]
        if report["ok"] and prefix_identical:
            shutil.rmtree(root, ignore_errors=True)
        else:
            entry["workspace"] = root  # kept for post-mortem
        return entry

    clean = _cell("clean", [])
    kill_at = max(1.0, min(2.0, load_s / 3))
    kill = _cell(
        "kill",
        seeded_kill_plan(
            seed, n, kill_at_s=kill_at, restart_after_s=1.5
        ),
    )
    entry = {"clean": clean, "kill_rejoin": kill}
    for name, cell in entry.items():
        if not cell["ok"]:
            raise AssertionError(
                f"cluster_e2e {name} audit failed: {cell['violations']}"
            )
        if not cell["prefix_identical"]:
            raise AssertionError(
                f"cluster_e2e {name}: steady commit prefixes diverge "
                f"(common len {cell['common_prefix_len']})"
            )
    if clean["delivered_tx"] <= 0:
        raise AssertionError(f"cluster_e2e clean committed nothing: {clean}")
    if not kill["kills"] or not kill["restarts"]:
        raise AssertionError(
            f"cluster_e2e kill cell never killed/restarted: {kill}"
        )
    if kill["lost_tx"]:
        raise AssertionError(
            f"cluster_e2e: {kill['lost_tx']} accepted transactions lost "
            f"across kill -9 + rejoin"
        )
    return entry


def _epoch_join_cell(
    n: int,
    load_s: float,
    rate: float,
    seed: int,
    boot_s: float,
    catchup_s: float = 120.0,
) -> dict:
    """Mid-run join from a span-attested snapshot, as real OS
    processes: n-1 nodes boot with epochs + span certs on, a rotate op
    is committed through the wire Submit door, and the last node starts
    only after the survivors have GC'd past its genesis — forcing a
    state transfer it can ONLY satisfy from the attested snapshot."""
    import math
    import shutil
    import tempfile
    import threading as _th

    from dag_rider_tpu.cluster import audit as _caudit
    from dag_rider_tpu.cluster import client as _cclient
    from dag_rider_tpu.cluster.directory import build_cluster
    from dag_rider_tpu.cluster.supervisor import ClusterSupervisor
    from dag_rider_tpu.core.codec import encode_epoch_op
    from dag_rider_tpu.core.types import EpochOp

    # k_span=2, NOT 4: round r's cert aggregator is r % n, so while the
    # joiner is absent every n-th round degrades to per-vertex verifies.
    # A span window aligned with that stride (k=n=4) always contains a
    # degraded round and never settles; k=2 keeps every other window
    # settling, so the donor has a live span chain to attest with
    k_span = 2
    gc_depth = 16
    root = tempfile.mkdtemp(prefix="dagrider-bench-epochjoin-")
    spec = build_cluster(
        root,
        n,
        transport="uds",
        seed=seed,
        gc_depth=gc_depth,
        # patience is quiescent pump ticks (~ms): socket-distributed
        # share aggregation needs seconds, not the in-process default
        node_overrides={"cert": "agg", "cert_patience": 2000},
    )
    sup = ClusterSupervisor(
        spec,
        env={
            "DAGRIDER_EPOCH": "1",
            "DAGRIDER_EPOCH_WAVES": "4",
            "DAGRIDER_CERT_SPAN": str(k_span),
            # share signing dominates the cert path at wall-clock round
            # rates; the compiled lane keeps certs (and therefore
            # spans) assembling at socket speed
            "DAGRIDER_CERT_SIGN": "native",
        },
    )
    joiner = n - 1
    for i in range(n - 1):
        sup.start(i)
    not_ready = sup.wait_ready(boot_s)
    if not_ready:
        sup.stop_all()
        raise AssertionError(
            f"epoch join: nodes {not_ready} not ready in {boot_s}s "
            f"(workspace kept at {root})"
        )
    # commit one rotate op through the wire front door, and ledger it so
    # the audit's zero-loss accounting covers control traffic too
    op = encode_epoch_op(EpochOp("rotate", joiner, seed, b""))
    cli = _cclient.SubmitClient(spec)
    verdict = None
    for _ in range(50):
        verdict = cli.submit(0, "epochctl", op)
        if verdict and (verdict["accepted"] or verdict["deduped"]):
            break
        _th.Event().wait(0.1)
    cli.close()
    if not verdict or not (verdict["accepted"] or verdict["deduped"]):
        sup.stop_all()
        raise AssertionError(f"epoch join: rotate op never acked: {verdict}")
    with open(spec.accepted_log, "a", buffering=1) as fh:
        fh.write(
            json.dumps(
                {
                    "tx": op.hex(),
                    "ts": time.time(),
                    "node": verdict["node"],
                    "client": "epochctl",
                }
            )
            + "\n"
        )
    load: dict = {}
    loader = _th.Thread(
        target=lambda: load.update(
            _cclient.drive_load(spec, duration_s=load_s, rate=rate, seed=seed)
        ),
        daemon=True,
    )
    loader.start()
    # start the joiner only once the survivors' committed frontier is
    # past gc_depth: its genesis rounds are pruned everywhere, so plain
    # window sync CANNOT answer — only the attested snapshot can. The
    # cert path runs at pairing speed, so rounds take ~1s of wall clock
    # here; the survivors keep advancing (empty-proposing) after the
    # load drains, hence the window is much wider than load_s.
    deadline = time.monotonic() + load_s + 90.0
    survivor_round = 0
    while time.monotonic() < deadline:
        log = _caudit.read_delivery_log(spec.nodes[0].delivery_log)
        survivor_round = max((rec["r"] for rec in log), default=0)
        if survivor_round > gc_depth + 8:
            break
        _th.Event().wait(0.25)
    if survivor_round <= gc_depth + 8:
        sup.stop_all()
        raise AssertionError(
            f"epoch join: survivors never committed past the joiner "
            f"horizon (round {survivor_round} <= {gc_depth + 8}; "
            f"workspace kept at {root})"
        )
    sup.start(joiner)
    loader.join(timeout=load_s + 60)
    not_ready = sup.wait_ready(boot_s)
    if not_ready:
        sup.stop_all()
        raise AssertionError(
            f"epoch join: joiner never ready (workspace kept at {root})"
        )
    # the survivors stay live (empty-proposing) after the load drains:
    # hold the cluster up until the joiner has COMMITTED past the
    # frontier it joined behind — boot (~10s of interpreter + jax),
    # nack accrual, the snapshot fetch/restore, and then a full wave
    # past the restored round all happen inside this window, at ~1s
    # per round of cert-path wall clock
    catch_deadline = time.monotonic() + catchup_s
    while time.monotonic() < catch_deadline:
        jlog = _caudit.read_delivery_log(spec.nodes[joiner].delivery_log)
        if jlog and max(rec["r"] for rec in jlog) >= survivor_round:
            break
        _th.Event().wait(0.5)
    _th.Event().wait(1.5)  # settle: let in-flight waves commit
    sup.stop_all()
    report = _caudit.audit_cluster(spec, restarted=[joiner])
    finals = {
        i: _caudit.read_final(spec.nodes[i].final_report) or {}
        for i in range(n)
    }
    epochs = {
        i: int(finals[i].get("metrics", {}).get("epoch_current", 0))
        for i in range(n)
    }
    jm = finals[joiner].get("metrics", {})
    spans_verified = int(jm.get("snapshot_spans_verified", 0))
    pairing = int(jm.get("snapshot_pairing_checks", 0))
    join_round = int(finals[joiner].get("round", 0))
    budget = math.ceil(max(1, join_round) / k_span)
    entry = {
        "nodes": n,
        "survivor_round_at_join": survivor_round,
        "load": load,
        "ok": report["ok"],
        "violations": report["violations"],
        "accepted_tx": report["accepted_tx"],
        "delivered_tx": report["delivered_tx"],
        "lost_tx": report["lost_tx"],
        "duplicate_tx": report["duplicate_tx"],
        "joiner_delivered": report["log_lengths"].get(joiner, 0),
        "epochs": epochs,
        "snapshot_spans_verified": spans_verified,
        "snapshot_pairing_checks": pairing,
        "pairing_budget": budget,
    }
    ok = (
        report["ok"]
        and entry["joiner_delivered"] > 0
        and spans_verified > 0
        and pairing <= budget
        and min(epochs.values()) >= 1
        and len(set(epochs.values())) == 1
    )
    if ok:
        shutil.rmtree(root, ignore_errors=True)
    else:
        entry["workspace"] = root  # kept for post-mortem
    if not report["ok"]:
        raise AssertionError(f"epoch join audit failed: {report['violations']}")
    if entry["joiner_delivered"] <= 0:
        raise AssertionError(f"epoch join: joiner committed nothing: {entry}")
    if spans_verified <= 0:
        raise AssertionError(
            f"epoch join: joiner never verified a span — state transfer "
            f"took the unattested path: {entry}"
        )
    if pairing > budget:
        raise AssertionError(
            f"epoch join: {pairing} pairing checks over the "
            f"ceil(round/k_span)={budget} budget: {entry}"
        )
    if min(epochs.values()) < 1 or len(set(epochs.values())) != 1:
        raise AssertionError(f"epoch join: epochs disagree: {epochs}")
    return entry


def _epoch_rotate_ab_cell(seed: int) -> dict:
    """Key-rotation acceptance, in-process with REAL per-process
    threshold coins (independent share books, shared initial dealer
    keys): an epoch boundary rotates every share key in lockstep, the
    cluster keeps deciding waves on the rotated keys, and the committed
    prefix up to the boundary is byte-identical to a static-membership
    run fed the same transactions — including the control op itself
    (zero lost acked txs)."""
    from dag_rider_tpu import Config
    from dag_rider_tpu.consensus import Simulation
    from dag_rider_tpu.consensus.coin import ThresholdCoin
    from dag_rider_tpu.core import codec
    from dag_rider_tpu.core.types import Block, EpochOp
    from dag_rider_tpu.crypto import threshold as th

    n, wl = 4, 4
    keys = th.ThresholdKeys.generate(n, (n - 1) // 3 + 1, seed=b"bench-ab")
    op = codec.encode_epoch_op(EpochOp("rotate", 0, seed, b""))

    def run(epoch_on: bool) -> Simulation:
        cfg = Config(
            n=n,
            coin="threshold_bls",
            propose_empty=True,
            epoch=epoch_on,
            epoch_waves=4,
            epoch_rotate="seed",
        )
        sim = Simulation(
            cfg, coin_factory=lambda i: ThresholdCoin(keys, i, n)
        )
        sim.submit_blocks(per_process=2)
        sim.processes[0].submit(Block((op,)))
        for _ in range(900):
            done = min(p.decided_wave for p in sim.processes) >= 5 and (
                not epoch_on
                or min(p.epoch_mgr.epoch for p in sim.processes) >= 1
            )
            if done:
                break
            sim.run(max_messages=300)
        else:
            raise AssertionError(
                f"epoch rotate_ab: run(epoch={epoch_on}) never settled"
            )
        sim.check_agreement()
        return sim

    rot = run(True)
    static = run(False)
    rotations = min(
        p.metrics.counters["epoch_rotations"] for p in rot.processes
    )
    if rotations < 1:
        raise AssertionError("epoch rotate_ab: a process never rotated keys")
    cut = rot.processes[0].epoch_mgr.history[-1].boundary_wave * wl

    def prefix(sim):
        return [
            (v.id.round, v.id.source, v.digest())
            for v in sim.deliveries[0]
            if v.id.round <= cut
        ]

    if prefix(rot) != prefix(static):
        raise AssertionError(
            "epoch rotate_ab: pre-boundary prefix diverges from the "
            "static-membership run"
        )
    delivered = {
        tx
        for v in rot.deliveries[0]
        if v.block is not None
        for tx in v.block.transactions
    }
    if op not in delivered:
        raise AssertionError("epoch rotate_ab: control op lost")
    return {
        "boundary_wave": cut // wl,
        "decided_waves": min(p.decided_wave for p in rot.processes),
        "rotations_min": rotations,
        "prefix_identical": True,
        "prefix_len": len(prefix(rot)),
        "control_op_committed": True,
    }


def _epoch_flatness_cell(seed: int) -> dict:
    """Three sequenced epochs under GC: vertices_live_max must settle —
    the retained window is bounded by waves+depth, not by history."""
    from dag_rider_tpu import Config
    from dag_rider_tpu.consensus import Simulation
    from dag_rider_tpu.core import codec
    from dag_rider_tpu.core.types import Block, EpochOp

    cfg = Config(
        n=4,
        coin="round_robin",
        propose_empty=True,
        epoch=True,
        epoch_waves=2,
        gc_depth=16,
        epoch_gc=0,
    )
    sim = Simulation(cfg)
    sim.submit_blocks(per_process=2)
    marks = []
    for k in range(3):
        sim.processes[0].submit(
            Block((codec.encode_epoch_op(EpochOp("rotate", 0, seed + k, b"")),))
        )
        for _ in range(900):
            if min(p.epoch_mgr.epoch for p in sim.processes) >= k + 1:
                break
            sim.run(max_messages=300)
        else:
            raise AssertionError(f"epoch flatness: epoch {k + 1} never settled")
        marks.append(
            max(
                p.metrics.counters["vertices_live_max"]
                for p in sim.processes
            )
        )
    if marks[-1] > marks[0] + cfg.n * cfg.wave_length:
        raise AssertionError(
            f"epoch flatness: vertices_live_max grew across epochs: {marks}"
        )
    bound = cfg.n * (
        cfg.epoch_waves * cfg.wave_length
        + cfg.gc_depth
        + 4 * cfg.wave_length
    )
    if marks[-1] > bound:
        raise AssertionError(
            f"epoch flatness: high-water {marks[-1]} over bound {bound}"
        )
    return {
        "epochs": 3,
        "vertices_live_max_per_epoch": marks,
        "bound": bound,
        "flat": True,
    }


def _epoch_rung(
    n: int = 4,
    load_s: float = 8.0,
    rate: float = 250.0,
    seed: int = 7,
    boot_s: float = 20.0,
    catchup_s: float = 120.0,
    cells: tuple = ("join", "rotate_ab", "flatness"),
) -> dict:
    """Ladder rung (ISSUE 20): epoch reconfiguration + span-attested
    snapshot sync. Three cells, each RAISING on a missed gate:

    - **join**: a late node catches up mid-load from a span-attested
      snapshot within <= ceil(round / k_span) pairing checks, its
      commit log embeds byte-identically into the survivor order, and
      every node lands in the same epoch >= 1.
    - **rotate_ab**: an epoch boundary rotates real threshold-coin
      share keys in lockstep with zero lost acked txs and a pre-
      boundary prefix byte-identical to a static-membership run.
    - **flatness**: vertices_live_max stays flat across >= 3 settled
      epochs — the GC floor advances with the boundary.
    """
    entry: dict = {}
    if "join" in cells:
        entry["join"] = _epoch_join_cell(
            n, load_s, rate, seed, boot_s, catchup_s=catchup_s
        )
    if "rotate_ab" in cells:
        entry["rotate_ab"] = _epoch_rotate_ab_cell(seed)
    if "flatness" in cells:
        entry["flatness"] = _epoch_flatness_cell(seed)
    return entry


def _measure() -> None:
    budget = float(os.environ.get("DAGRIDER_BENCH_SECONDS", "300"))
    t_start = time.monotonic()

    def left() -> float:
        return budget - (time.monotonic() - t_start)

    _mark(f"measure: python up (budget {budget:.0f}s), importing jax")
    import jax

    want = os.environ.get("DAGRIDER_BENCH_PLATFORM")
    if want:
        jax.config.update("jax_platforms", want)

    from dag_rider_tpu.utils.jaxcache import enable_persistent_cache

    enable_persistent_cache(os.path.join(_REPO, ".jax_cache"))

    import numpy as np
    import jax.numpy as jnp

    # Init watchdog: a relay that wedges BETWEEN the probe and this stage
    # (observed round 5: probe OK at T, measure init hung 3 s later for
    # the whole 37 min window) must fail fast so the outer loop can
    # re-probe or fall back — a successful probe minutes ago proves
    # nothing about this process's connection. A daemon THREAD (not
    # SIGALRM: the hang sits inside the blocking PJRT C++ handshake,
    # where a Python signal handler would not run until the call
    # returns) hard-exits rc=3 so the parent sees a deliberate abort,
    # not a mid-ladder death.
    import threading

    watchdog_s = float(os.environ.get("DAGRIDER_BENCH_INIT_WATCHDOG", "150"))
    init_done = threading.Event()

    def _init_watchdog():
        if not init_done.wait(watchdog_s):
            _mark(
                f"measure: backend init/first-dispatch exceeded "
                f"{watchdog_s:.0f}s watchdog — relay wedged; aborting stage"
            )
            _relay_log(f"measure stage init watchdog ({watchdog_s:.0f}s) fired")
            sys.stderr.flush()
            os._exit(3)

    if watchdog_s > 0:
        threading.Thread(target=_init_watchdog, daemon=True).start()
    t0 = time.monotonic()
    backend = jax.default_backend()
    device_kind = getattr(jax.devices()[0], "device_kind", "?")
    # one tiny dispatch: init can "succeed" while the first real
    # transfer wedges — cover both under the same watchdog
    jnp.zeros((8,), dtype=jnp.int32).sum().block_until_ready()
    init_s = time.monotonic() - t0
    init_done.set()
    _mark(f"measure: backend '{backend}' ({device_kind}) up in {init_s:.1f}s")

    result = {
        "metric": "vertex_sigs_per_sec",
        "value": 0.0,
        "unit": "sigs/s",
        "vs_baseline": 0.0,
        # the axon PJRT plugin registers the chip under platform "axon";
        # device_kind carries the actual hardware (e.g. TPU v5e)
        "backend": backend,
        "device_kind": device_kind,
        "n": 0,
        "phases": {"backend_init_s": round(init_s, 1)},
        "ladder": {},
    }

    def emit() -> None:
        print(json.dumps(result), flush=True)

    built = {}  # n -> (verifier, batches); reused by the wave phase

    def merged_phase(n: int) -> None:
        """Merged multi-round throughput at committee n — all built rounds
        in ONE padded device dispatch via verify_rounds (the per-dispatch
        fixed cost is ~50-200 ms of relay/transfer latency on the axon
        backend — PROFILE.md round 3 — so the steady-state consensus shape
        amortizes it across consecutive rounds)."""
        if n not in built:
            return
        if left() < 45:
            # the merged bucket is a SECOND program compile — on a CPU
            # fallback it can eat minutes and starve later rungs
            _mark(f"skipping merged_n{n} (left {left():.0f}s)")
            return
        verifier, batches, _ = built[n]
        rounds = batches[1:]
        _mark(f"merged_n{n}: compiling merged bucket ({sum(len(b) for b in rounds)} sigs)")
        masks = verifier.verify_rounds(rounds)  # compile + warm this bucket
        if not all(all(m) for m in masks):
            _mark(f"merged_n{n}: verification failed, discarding phase")
            return
        # Best of 3: the relay's fixed per-dispatch cost fluctuates
        # run to run (~±20% on the headline — PROFILE.md); repeated
        # timed dispatches cost ~0.3 s each and isolate the steady
        # state from a single unlucky round-trip.
        times = []
        for _ in range(3):
            t0 = time.monotonic()
            masks = verifier.verify_rounds(rounds)
            times.append(time.monotonic() - t0)
        dt = min(times)
        total = sum(len(m) for m in masks)
        sigs = total / dt
        result["phases"][f"verify_n{n}_merged"] = {
            "rounds": len(rounds),
            "sigs": total,
            "sigs_per_sec": round(sigs, 1),
            "dispatch_ms": round(1e3 * dt, 2),
            "dispatch_ms_median": round(
                1e3 * sorted(times)[len(times) // 2], 2
            ),
        }
        _mark(f"merged_n{n}: {sigs:,.0f} sigs/s ({len(rounds)} rounds/dispatch)")
        if sigs > result["value"] and n >= result["n"]:
            result["value"] = round(sigs, 1)
            result["vs_baseline"] = round(sigs / BASELINE, 3)
            result["n"] = n
        emit()

    def verify_phase(n: int, timed_rounds: int, built_rounds: int = 0) -> bool:
        """One committee size: build, compile/warm, measure. Returns ok.

        built_rounds (>= timed_rounds) controls how many signed rounds are
        constructed — the merged phase wants a big burst to dispatch, but
        per-round timing needs only a few synchronizing samples (each is a
        full device round-trip; 63 of them would burn ~4 s of budget for
        no extra information).
        """
        built_rounds = max(built_rounds, timed_rounds)
        tag = f"verify_n{n}"
        _mark(f"{tag}: building {1 + built_rounds} signed rounds")
        t0 = time.monotonic()
        verifier, batches, signers = _build_batches(n, 1 + built_rounds)
        built[n] = (verifier, batches, signers)
        build_s = time.monotonic() - t0
        _mark(f"{tag}: build done in {build_s:.1f}s; compiling (warm batch)")
        t0 = time.monotonic()
        mask = verifier.verify_batch(batches[0])
        if not all(mask):
            _mark(f"{tag}: WARM BATCH FAILED TO VERIFY — aborting phase")
            return False
        compile_s = time.monotonic() - t0
        _mark(f"{tag}: compile+warm done in {compile_s:.1f}s; timing")
        from dag_rider_tpu import config as _cfg

        profile_dir = _cfg.env_str("DAGRIDER_PROFILE_DIR")
        if profile_dir:
            jax.profiler.start_trace(profile_dir)
        try:
            total = 0
            t0 = time.monotonic()
            prep_s = 0.0
            for k, b in enumerate(batches[1 : 1 + timed_rounds]):
                mask = verifier.verify_batch(b)
                prep_s += verifier.last_prepare_s
                total += len(b)
                if not all(mask):
                    _mark(f"{tag}: timed batch {k} failed")
                    return False
                _mark(f"{tag}: timed batch {k} done")
            dt = time.monotonic() - t0
        finally:
            if profile_dir:
                jax.profiler.stop_trace()
        sigs = total / dt
        _mark(
            f"{tag}: {sigs:,.0f} sigs/s  (host prep {1e3 * prep_s / timed_rounds:.1f}"
            f" ms/round, device+prep {1e3 * dt / timed_rounds:.1f} ms/round)"
        )
        result["phases"][tag] = {
            "build_s": round(build_s, 1),
            "compile_s": round(compile_s, 1),
            "sigs_per_sec": round(sigs, 1),
            "host_prep_ms_per_round": round(1e3 * prep_s / timed_rounds, 2),
            "round_ms": round(1e3 * dt / timed_rounds, 2),
        }
        # The headline is pinned to the LARGEST measured committee (the
        # north star is defined at n=256) — never a smaller-n number that
        # happens to be faster.
        if n >= result["n"]:
            result["value"] = round(sigs, 1)
            result["vs_baseline"] = round(sigs / BASELINE, 3)
            result["n"] = n
        emit()
        return True

    # Phase order depends on the backend (round-3 postmortem: the official
    # record must carry the *headline* even when the run truncates):
    #  - device backends: n=256 build+compile+merged FIRST — the north
    #    star is defined at n=256, so it lands before any rung can eat
    #    the budget.
    #  - CPU fallback: n=64 first (n=256 would burn the whole fallback
    #    window compiling; DAGRIDER_BENCH_N256_MIN gates it off).
    n256_min = float(os.environ.get("DAGRIDER_BENCH_N256_MIN", "150"))
    # On-device: 63 built rounds so the merged phase dispatches a ~16k-
    # signature program (measured 50.6k sigs/s at 16384, 57.7k at 32768 —
    # PROFILE.md round 3). The CPU fallback shrinks this (round-4 VERDICT
    # #6: the fallback must still *measure the north-star committee size*,
    # which it can afford only with a small merged burst).
    n256_rounds = int(os.environ.get("DAGRIDER_BENCH_N256_ROUNDS", "63"))
    headline_first = backend != "cpu" and left() > n256_min

    if headline_first:
        # n=256 (the north-star committee size) first, with only 4
        # synchronizing per-round timing samples.
        if verify_phase(256, timed_rounds=4, built_rounds=n256_rounds):
            merged_phase(256)
        if left() > 30:
            verify_phase(64, timed_rounds=4)
    else:
        # n=64 first: small program compiles fast; guarantees a number.
        # The merged phase is DEFERRED to the end of the stage on this
        # path (cpu_merged_n below): its second program compile must not
        # starve the host-consensus/coin rungs of the fallback window.
        verify_phase(64, timed_rounds=4)
        cpu_merged_n = 64
        if left() > n256_min:
            if verify_phase(256, timed_rounds=4, built_rounds=n256_rounds):
                cpu_merged_n = 256
        else:
            _mark(f"skipping n=256 (only {left():.0f}s left)")

    # -- phase C: wave-commit pipeline latency at the measured n
    if left() > 30 and result["n"]:
        n = result["n"]
        _mark("wave pipeline: warm + timing")
        from dag_rider_tpu.ops import dag_kernels

        quorum = _quorum(n)
        rng = np.random.default_rng(7)
        strong_wave = jnp.asarray(
            rng.random((3, n, n)) < min(1.0, (quorum + 0.5) / n)
        )
        exists_r4 = jnp.ones(n, dtype=bool)
        leader = jnp.int32(1)
        commit_fn = jax.jit(
            lambda s, e, l: dag_kernels.wave_commit_votes(s, e, l, quorum=quorum)
        )
        jax.block_until_ready(commit_fn(strong_wave, exists_r4, leader))
        # reuse the already-built, already-warm batches from verify_phase;
        # the 4 rounds of a wave arrive as one merged dispatch (the
        # steady-state consensus shape — Simulation.run coalescing)
        verifier, batches, _ = built[n]
        verifier.verify_rounds(batches[:4])  # warm the wave-burst bucket
        strong_np = np.asarray(strong_wave)
        wave_ms = []
        for w in range(6):
            t0 = time.monotonic()
            verifier.verify_rounds(batches[:4])
            jax.block_until_ready(commit_fn(strong_wave, exists_r4, leader))
            reach = np.eye(n, dtype=bool)
            for r in range(3):
                reach = (
                    reach.astype(np.int32) @ strong_np[r].astype(np.int32)
                ) > 0
            wave_ms.append(1e3 * (time.monotonic() - t0))
        wave_ms.sort()
        # staged proxy (verify-4-rounds + commit kernels); the sim256
        # rung overwrites the top-level field with the end-to-end number
        p50 = round(wave_ms[len(wave_ms) // 2], 2)
        result["phases"]["wave_pipeline_p50_ms"] = p50
        result["wave_commit_p50_ms"] = p50
        _mark(f"wave pipeline p50 (staged proxy): {p50} ms")
        emit()

    # -- ladder rung #3 live half: n=256 consensus-in-the-loop with the
    # threshold coin (the north-star committee size — round-3 VERDICT #3
    # wants the END-TO-END wave_commit_p50 and sigs/s at n=256, not the
    # staged proxy). Reuses the headline phase's verifier+signers (their
    # comb tables and the 16k-bucket program are already built/compiled).
    sim256_budget = float(os.environ.get("DAGRIDER_BENCH_SIM256_S", "60"))
    if sim256_budget > 0 and 256 in built and left() > sim256_budget + 35:
        _mark(f"ladder sim256: time-boxed {sim256_budget:.0f}s consensus run")
        verifier, _, signers = built[256]
        # One round's coalesced burst is 256*255 = 65,280 sigs. The
        # default 16384 bucket chunks it into 4 dispatches through the
        # SAME program the merged headline phase compiled (no extra
        # compile in the driver's budget); a long local capture can set
        # DAGRIDER_BENCH_SIM256_BUCKET=65280 to pay one bigger compile
        # and run ONE dispatch per round — with the pipeline overlapping
        # host prep, in-loop throughput approaches the merged phase's.
        sim256_bucket = int(
            os.environ.get("DAGRIDER_BENCH_SIM256_BUCKET", "16384")
        )
        # the verifier is SHARED with the (possibly deferred) merged
        # phase — restore its bucket after the rungs, or a 512-bucket
        # sim leaves verify_rounds chunking the "merged" dispatch
        prev_bucket = verifier.fixed_bucket
        # try/finally (ADVICE r5 #3): an exception anywhere in the two
        # rungs must not leak a sim-sized bucket into the deferred
        # merged headline phase sharing this verifier
        try:
            if sim256_bucket != 16384:
                # a non-default bucket is a NEW program shape — compile
                # it OUTSIDE the timed box (the 16384 default reuses the
                # merged headline phase's program; sim64 pre-warms the
                # same way)
                _mark(
                    f"ladder sim256: pre-warming bucket-{sim256_bucket} program"
                )
                verifier.fixed_bucket = sim256_bucket
                verifier.warmup()  # AOT: jit().lower().compile() at the shape
                verifier.verify_batch(built[256][1][0][:9])  # host-prep warm
            entry = _sim_rung(
                256,
                sim256_budget,
                verifier,
                signers,
                bucket=sim256_bucket,
                chunk=256 * 255,
                coin="threshold_bls",
            )
            entry["bucket"] = sim256_bucket
            result["ladder"]["sim256"] = entry
            # the official end-to-end p50 at the north-star committee size
            if entry["wave_commit_p50_ms"] is not None:
                result["wave_commit_p50_ms"] = entry["wave_commit_p50_ms"]
            _mark(
                f"ladder sim256: {entry['sigs_applied']} applied sigs "
                f"({entry['sigs_applied_per_sec']:,.0f}/s; device "
                f"{entry['sigs_device_per_sec']:,.0f}/s), "
                f"{entry['vertices_delivered_total']} delivered, "
                f"round {entry['max_round']}, "
                f"wave p50 {entry['wave_commit_p50_ms']} ms"
            )
            emit()
            # before/after overlap evidence (round-4 VERDICT #4): the
            # same rung with the dispatch/delivery pipeline forced OFF —
            # the p50 delta is what the overlap buys at the north-star
            # committee
            sync_budget = float(
                os.environ.get("DAGRIDER_BENCH_SIM256_SYNC_S", "25")
            )
            if sync_budget > 0 and left() > sync_budget + 30:
                _mark(f"ladder sim256_sync: {sync_budget:.0f}s, pipeline OFF")
                entry = _sim_rung(
                    256,
                    sync_budget,
                    verifier,
                    signers,
                    bucket=sim256_bucket,  # same program as the A side
                    chunk=256 * 255,
                    coin="threshold_bls",
                    pipelined=False,
                )
                entry["bucket"] = sim256_bucket
                result["ladder"]["sim256_sync"] = entry
                _mark(
                    f"ladder sim256_sync: wave p50 "
                    f"{entry['wave_commit_p50_ms']} ms "
                    f"({entry['sigs_applied_per_sec']:,.0f} applied sigs/s)"
                )
                emit()
        finally:
            verifier.fixed_bucket = prev_bucket
    else:
        _mark(f"skipping ladder sim256 (left {left():.0f}s)")

    # -- ladder rung #3: 64-node consensus-in-the-loop, device verifier
    # (35 s box: enough for ~50 rounds at the round-4 host path; the
    # budget must also fit sim256 + verify1024 + msm)
    sim_budget = float(os.environ.get("DAGRIDER_BENCH_SIM_S", "35"))
    if sim_budget > 0 and left() > sim_budget + 25:
        _mark(f"ladder sim64: time-boxed {sim_budget:.0f}s consensus run")
        from dag_rider_tpu.verifier.base import KeyRegistry, VertexSigner
        from dag_rider_tpu.verifier.tpu import TPUVerifier

        n = 64
        reg, seeds = KeyRegistry.generate(n)
        shared = TPUVerifier(reg)
        # All 64 processes share this verifier, so the simulator
        # coalesces every pump cycle's batches into ONE device dispatch
        # (Simulation.run); the fixed bucket keeps that single program
        # shape compiled once, however burst sizes wander. Round-sized
        # chunks (64*63 = 4032 <= the 4096 bucket) keep it one dispatch
        # per DAG round — round-3 ran 500-message chunks, paying the
        # fixed dispatch cost 8x per round.
        signers = [VertexSigner(s) for s in seeds]
        # With dispatch dedup a round's unique burst is only n sigs, so
        # the CPU fallback runs this rung at bucket 128 (dispatch cost
        # ~180 ms vs the 4096 program's bucket-padded cost) — an in-loop
        # consensus number with real crypto even on a dead-relay round.
        sim_bucket = int(os.environ.get("DAGRIDER_BENCH_SIM_BUCKET", "4096"))
        shared.fixed_bucket = sim_bucket
        warm_all = _signed_round(signers, n, 1, _quorum(n))
        shared.warmup()  # AOT-compile the fixed-bucket program
        shared.verify_batch(warm_all[:9])  # warm host prep + native lib
        _mark(f"ladder sim64: fixed-bucket({sim_bucket}) program pre-warmed")
        entry = _sim_rung(
            n,
            sim_budget,
            shared,
            signers,
            bucket=sim_bucket,
            chunk=4032,
            # BASELINE config #3 says a 10k-vertex DAG; keep pumping past
            # the box until a view holds 10k vertices (bounded so the
            # remaining ladder rungs still fit)
            target_per_view=10_000,
            max_s=max(sim_budget, min(240.0, left() - 150.0)),
        )
        result["ladder"]["sim64"] = entry
        if result.get("wave_commit_p50_ms") is None and entry[
            "wave_commit_p50_ms"
        ]:
            result["wave_commit_p50_ms"] = entry["wave_commit_p50_ms"]
        _mark(
            f"ladder sim64: {entry['sigs_applied']} applied sigs in "
            f"{entry['seconds']:.0f}s ({entry['sigs_applied_per_sec']:,.0f}/s), "
            f"{entry['vertices_delivered_total']} delivered, "
            f"round {entry['max_round']}"
        )
        emit()
    else:
        _mark(f"skipping ladder sim64 (only {left():.0f}s left)")

    # -- host-path consensus rung (CPU fallback evidence): the full
    # 64-node protocol loop with a null verifier — admission, waves,
    # ordering, GC — pure host throughput. On the device path this is
    # covered by sim64/sim256; the CPU fallback sets
    # DAGRIDER_BENCH_HOSTSIM_S so the official record still carries a
    # consensus number when the chip is unreachable.
    def host_rung(n: int, secs: float, pump: str | None = None) -> None:
        tag = f"sim{n}_host" + (f"_{pump}" if pump else "")
        _mark(f"ladder {tag}: {secs:.0f}s null-verifier consensus")
        from dag_rider_tpu.config import Config
        from dag_rider_tpu.consensus.simulator import Simulation

        cfg = Config(
            n=n,
            coin="round_robin",
            propose_empty=True,
            gc_depth=24,
            # None defers to DAGRIDER_PUMP / scalar (Config default)
            pump=pump,
        )
        sim = Simulation(cfg)
        sim.submit_blocks(per_process=2)
        t0 = time.monotonic()
        pumped = 0
        while time.monotonic() - t0 < secs:
            pumped += sim.run(max_messages=n * (n - 1))
        dt = time.monotonic() - t0
        sim.check_agreement()
        snap0 = sim.processes[0].metrics.snapshot()
        result["ladder"][tag] = {
            "nodes": n,
            "verifier": "none",
            "pump": sim.processes[0].cfg.pump,
            "seconds": round(dt, 1),
            "messages": pumped,
            "msgs_per_sec": round(pumped / dt, 1),
            "max_round": max(p.round for p in sim.processes),
            "vertices_delivered_total": sum(
                len(d) for d in sim.deliveries
            ),
            "vertices_live_max": max(
                len(p.dag.vertices) for p in sim.processes
            ),
            "agreement": True,
            # host-pump accounting (round 12): ms of pump+step per
            # round advanced, and delivered msgs per pump-wall second
            **{
                k: snap0[k]
                for k in (
                    "pump_path",
                    "pump_msgs_per_s",
                    "host_pump_ms_per_round",
                )
                if k in snap0
            },
        }
        host_ivals = sorted(
            s
            for p in sim.processes
            for s in p.metrics.wave_interval_seconds
        )
        # always present (null when no 2nd wave decided) — same schema
        # as the _sim_rung entries
        result["ladder"][tag]["wave_interval_p50_ms"] = (
            round(1e3 * host_ivals[len(host_ivals) // 2], 2)
            if host_ivals
            else None
        )
        _mark(
            f"ladder {tag}: {pumped / dt:,.0f} msg/s, round "
            f"{result['ladder'][tag]['max_round']}, agreement ok"
        )
        emit()

    hostsim_s = float(os.environ.get("DAGRIDER_BENCH_HOSTSIM_S", "0"))
    if hostsim_s > 0 and left() > hostsim_s + 10:
        host_rung(64, hostsim_s)
    # n=256 host consensus (round-4 VERDICT #6: even a wedged-relay round
    # must record consensus behavior at the committee size the baseline
    # is defined at)
    hostsim256_s = float(os.environ.get("DAGRIDER_BENCH_HOSTSIM256_S", "0"))
    if hostsim256_s > 0 and left() > hostsim256_s + 10:
        host_rung(256, hostsim256_s)

    # -- ladder rung (round 12): scalar-vs-vector host pump A/B
    # (bench._vec_ab_rung, the tier1-vec CI smoke). Off by default; a
    # local capture sets DAGRIDER_BENCH_SIM256VEC_S high and _N=256 for
    # the committee size.
    vecab_s = float(os.environ.get("DAGRIDER_BENCH_SIM256VEC_S", "0"))
    vecab_n = int(os.environ.get("DAGRIDER_BENCH_SIM256VEC_N", "256"))
    vecab_round = int(os.environ.get("DAGRIDER_BENCH_SIM256VEC_ROUND", "12"))
    if vecab_s > 0 and left() > 2 * vecab_s + 10:
        tag = f"sim{vecab_n}_vec"
        _mark(f"ladder {tag}: scalar-vs-vector A/B to round {vecab_round}")
        entry = _vec_ab_rung(vecab_n, vecab_s, vecab_round)
        result["ladder"][tag] = entry
        _mark(
            f"ladder {tag}: scalar "
            f"{entry['scalar']['msgs_per_sec']:,.0f} msg/s vs vector "
            f"{entry['vector']['msgs_per_sec']:,.0f} msg/s "
            f"({entry['speedup']}x), commit order identical"
        )
        emit()

    # -- ladder rung (round 16): trace-off vs trace-on A/B
    # (bench._trace_ab_rung, the tier1-obs CI smoke). Off by default; a
    # local capture sets DAGRIDER_BENCH_TRACE_S for the per-side budget.
    trab_s = float(os.environ.get("DAGRIDER_BENCH_TRACE_S", "0"))
    trab_n = int(os.environ.get("DAGRIDER_BENCH_TRACE_N", "16"))
    trab_round = int(os.environ.get("DAGRIDER_BENCH_TRACE_ROUND", "60"))
    if trab_s > 0 and left() > 2 * trab_s + 10:
        _mark(f"ladder trace_overhead: off-vs-on A/B to round {trab_round}")
        entry = _trace_ab_rung(trab_n, trab_s, trab_round)
        result["ladder"]["trace_overhead"] = entry
        _mark(
            f"ladder trace_overhead: off "
            f"{entry['off']['msgs_per_sec']:,.0f} msg/s vs on "
            f"{entry['on']['msgs_per_sec']:,.0f} msg/s "
            f"({entry['overhead_pct']}% overhead, "
            f"gate {'ok' if entry['overhead_ok'] else 'FAIL'}), "
            "commit order identical"
        )
        emit()

    # -- ladder rungs (round 13): aggregated round certificates. Two
    # halves — verify_n256_agg prices the aggregate-check components at
    # the n=64/n=256 quorums against the per-vertex ed25519 reference,
    # and sim{n}_agg runs the cert-on/cert-off sim A/B (byte-identical
    # commit order, cluster-wide sigs_device drop). Off by default (the
    # host pairing halves eat ~1 min); a local capture sets
    # DAGRIDER_BENCH_AGG=1 (+ _AGG_N for the sim committee size) and
    # gets BENCH_r06.json when both halves pass.
    agg_on = os.environ.get("DAGRIDER_BENCH_AGG", "") == "1"
    agg_n = int(os.environ.get("DAGRIDER_BENCH_AGG_N", "64"))
    if agg_on and left() > 30:
        agg_ok = sim_ok = False
        try:
            _mark("ladder verify_n256_agg: aggregate-check components")
            entry = _agg_ladder_rung()
            result["ladder"]["verify_n256_agg"] = entry
            agg_ok = entry["agg_per_vertex_flat_within_2x"]
            _mark(
                "ladder verify_n256_agg: check "
                f"{entry['sizes']['64']['agg_check_warm_s']}s@64 -> "
                f"{entry['sizes']['256']['agg_check_warm_s']}s@256 "
                f"({entry['agg_check_growth']}x wall for "
                f"{entry['pairs_growth']}x pairs; per-vertex amortized "
                f"{entry['agg_ms_per_vertex_growth']}x)"
            )
            emit()
        except Exception as e:  # noqa: BLE001 — rung is best-effort
            _mark(f"ladder verify_n256_agg FAILED: {e!r}")
        try:
            tag = f"sim{agg_n}_agg"
            _mark(f"ladder {tag}: cert-on/cert-off sim A/B")
            entry = _cert_ab_rung(agg_n)
            result["ladder"][tag] = entry
            sim_ok = (
                entry["commit_order_identical"]
                and entry["sigs_device_drop"] >= 10.0
            )
            _mark(
                f"ladder {tag}: sigs_device "
                f"{entry['per_vertex']['sigs_device']} -> "
                f"{entry['agg']['sigs_device']} "
                f"({entry['sigs_device_drop']}x drop), commit order "
                "identical"
            )
            emit()
        except Exception as e:  # noqa: BLE001 — rung is best-effort
            _mark(f"ladder {tag} FAILED: {e!r}")
        if agg_ok and sim_ok:
            rec = {
                "verify_n256_agg": result["ladder"]["verify_n256_agg"],
                f"sim{agg_n}_agg": result["ladder"][f"sim{agg_n}_agg"],
                "backend": result.get("backend", "cpu"),
                "device_kind": result.get("device_kind", "cpu"),
                "ok": True,
                "skipped": False,
            }
            from dag_rider_tpu import config as _cfg

            out_path = os.path.join(
                _REPO, _cfg.env_str("DAGRIDER_AGG_OUT")
            )
            with open(out_path, "w") as fh:
                json.dump(rec, fh, indent=1)
                fh.write("\n")
            _mark(f"ladder agg: wrote {out_path}")

    # -- ladder rung (ISSUE 12): certificate path phase 2 — batched
    # share signing, the pairing seam, and cert-of-certs replay, each
    # against its oracle. Off by default (the n=256 host signing oracle
    # alone is ~a minute); a local capture sets DAGRIDER_BENCH_CERT2=1
    # and gets BENCH_r07.json (DAGRIDER_CERT2_OUT) when the acceptance
    # gates pass: native signing >=3x, span replay < 1 product check
    # per round, triple-A/B commit order byte-identical.
    c2_on = os.environ.get("DAGRIDER_BENCH_CERT2", "") == "1"
    if c2_on and left() > 30:
        try:
            _mark(
                "ladder cert_phase2: batched signing / span replay / "
                "triple sim A/B"
            )
            entry = _cert_phase2_rung()
            result["ladder"]["cert_phase2"] = entry
            c2_ok = (
                entry["sign"]["native_speedup_x"] >= 3.0
                and entry["span_replay"]["pairing_checks_per_round"] < 1.0
                and entry["sim"]["commit_order_identical"]
            )
            _mark(
                "ladder cert_phase2: native sign "
                f"{entry['sign']['native_speedup_x']}x, span replay "
                f"{entry['span_replay']['pairing_checks_per_round']} "
                "checks/round, commit order identical"
            )
            emit()
            if c2_ok:
                rec = {
                    "cert_phase2": entry,
                    "backend": result.get("backend", "cpu"),
                    "device_kind": result.get("device_kind", "cpu"),
                    "ok": True,
                    "skipped": False,
                }
                from dag_rider_tpu import config as _cfg

                out_path = os.path.join(
                    _REPO, _cfg.env_str("DAGRIDER_CERT2_OUT")
                )
                with open(out_path, "w") as fh:
                    json.dump(rec, fh, indent=1)
                    fh.write("\n")
                _mark(f"ladder cert_phase2: wrote {out_path}")
        except Exception as e:  # noqa: BLE001 — rung is best-effort
            _mark(f"ladder cert_phase2 FAILED: {e!r}")

    # -- ladder rung #9 (round 10): mempool-fronted end-to-end commit
    # pipeline — client transactions through admission/batching/consensus
    # to a_deliver on the WALL clock, so committed-tx/s and the
    # submit→a_deliver percentiles are what a cluster client would see.
    # Null verifier on purpose: the crypto seam has its own rungs; this
    # one prices the ingestion + ordering pipeline. The chaos variant
    # reruns a tight pool under phase-aligned bursts THROUGH an
    # unreliable transport (delay + duplicate faults) on the virtual
    # clock — the acceptance gate is shed-not-crash: audit lost == 0
    # and duplicates == 0 WITH shed > 0, agreement intact.
    mp_secs = float(os.environ.get("DAGRIDER_BENCH_MEMPOOL_S", "20"))
    mp_n = int(os.environ.get("DAGRIDER_BENCH_MEMPOOL_N", "256"))
    mp_rate = float(os.environ.get("DAGRIDER_BENCH_MEMPOOL_RATE", "4000"))
    # the drain (commit the tail of in-flight blocks) is wall-bounded
    # separately: ~16 DAG rounds at n=256 is minutes of host pumping on
    # a slow core, and the rung must never eat the remaining ladder
    mp_drain = float(os.environ.get("DAGRIDER_BENCH_MEMPOOL_DRAIN_S", "30"))
    if mp_secs > 0 and left() > mp_secs + mp_drain + 20:
        from dag_rider_tpu.config import Config as _MpCfg
        from dag_rider_tpu.config import MempoolConfig as _MpMCfg
        from dag_rider_tpu.consensus.simulator import Simulation as _MpSim
        from dag_rider_tpu.mempool.loadgen import (
            ClusterLoadDriver,
            LoadGenerator,
        )

        _mark(
            f"ladder mempool_e2e: n={mp_n}, {mp_rate:,.0f} tx/s offered, "
            f"{mp_secs:.0f}s wall"
        )
        try:
            sim = _MpSim(
                _MpCfg(
                    n=mp_n,
                    coin="round_robin",
                    propose_empty=True,
                    gc_depth=24,
                )
            )
            gen = LoadGenerator(
                clients=32,
                rate=mp_rate,
                tx_bytes=32,
                seed=10,
                profile="poisson",
            )
            drv = ClusterLoadDriver(
                sim,
                gen,
                mcfg=_MpMCfg(cap=65536, batch_bytes=4096),
                wall=True,
            )
            entry = drv.run(mp_secs, drain_s=mp_drain)
            sim.check_agreement()
            entry["verifier"] = "none"
            entry["agreement"] = True
            result["ladder"]["mempool_e2e"] = entry
            if entry["audit"]["lost"] or entry["audit"]["duplicates"]:
                raise AssertionError(f"mempool audit failed: {entry['audit']}")
            _mark(
                f"ladder mempool_e2e: {entry['committed_tx_per_sec']:,.0f} "
                f"committed tx/s ({entry['committed_tx']} committed / "
                f"{entry['offered_tx']} offered), fill "
                f"{entry['batch_fill']}, p50 "
                f"{entry.get('submit_deliver_p50_ms')} ms / p99 "
                f"{entry.get('submit_deliver_p99_ms')} ms"
            )
            emit()
        except Exception as e:  # noqa: BLE001 — rung is best-effort
            _mark(f"ladder mempool_e2e FAILED: {e!r}")
    else:
        _mark(f"skipping ladder mempool_e2e (left {left():.0f}s)")

    mpc_secs = float(os.environ.get("DAGRIDER_BENCH_MEMPOOL_CHAOS_S", "1"))
    mpc_n = int(os.environ.get("DAGRIDER_BENCH_MEMPOOL_CHAOS_N", "64"))
    if mpc_secs > 0 and left() > 50:
        from dag_rider_tpu.config import Config as _MpCfg
        from dag_rider_tpu.config import MempoolConfig as _MpMCfg
        from dag_rider_tpu.consensus.simulator import Simulation as _MpSim
        from dag_rider_tpu.mempool.loadgen import (
            ClusterLoadDriver,
            LoadGenerator,
        )
        from dag_rider_tpu.transport.faults import FaultPlan, FaultyTransport

        _mark(
            f"ladder mempool_chaos: n={mpc_n}, 8x bursts over tight pool, "
            f"delay/duplicate faults, {mpc_secs:.0f}s virtual"
        )
        try:
            sim = _MpSim(
                _MpCfg(
                    n=mpc_n,
                    coin="round_robin",
                    propose_empty=True,
                    gc_depth=24,
                ),
                transport=FaultyTransport(
                    FaultPlan(delay=0.05, duplicate=0.02, seed=10)
                ),
            )
            gen = LoadGenerator(
                clients=2 * mpc_n,
                rate=40_000.0,
                tx_bytes=32,
                seed=10,
                profile="burst",
            )
            # pool sized to saturate: the burst peaks MUST overflow the
            # watermarks or the rung proves nothing about shedding
            drv = ClusterLoadDriver(
                sim,
                gen,
                mcfg=_MpMCfg(
                    cap=512, batch_bytes=512, max_batch_txs=64
                ),
                dt=0.02,
            )
            entry = drv.run(mpc_secs, drain_s=20.0)
            sim.check_agreement()
            audit = entry["audit"]
            entry["verifier"] = "none"
            entry["agreement"] = True
            entry["transport_faults"] = dict(sim.transport.stats)
            result["ladder"]["mempool_chaos"] = entry
            if audit["lost"] or audit["duplicates"]:
                raise AssertionError(f"chaos audit failed: {audit}")
            if not entry["shed_tx"]:
                raise AssertionError(
                    f"chaos rung never shed — not an overload run: {entry}"
                )
            _mark(
                f"ladder mempool_chaos: {entry['offered_tx']} offered, "
                f"{entry['accepted_tx']} accepted, {entry['shed_tx']} shed, "
                f"lost {audit['lost']}, dups {audit['duplicates']}, "
                f"agreement ok"
            )
            emit()
        except Exception as e:  # noqa: BLE001 — rung is best-effort
            _mark(f"ladder mempool_chaos FAILED: {e!r}")
    else:
        _mark(f"skipping ladder mempool_chaos (left {left():.0f}s)")

    # -- ladder rung (ISSUE 16): submit→deliver finality — pipelined
    # waves + eager optimistic delivery. Half 1 is the byte-identity
    # gate over the seeded n × adversary matrix (the rung RAISES on any
    # divergence, unbalanced eager books, or a nonzero expected-zero
    # rollback counter); half 2 is the wall-clock knobs-on/off latency
    # A/B at n=64 with the per-transaction attribution split (batcher
    # queueing vs wave lag, components summing to the measured total).
    fin_s = float(os.environ.get("DAGRIDER_BENCH_FINALITY_S", "15"))
    fin_n = int(os.environ.get("DAGRIDER_BENCH_FINALITY_N", "64"))
    fin_rate = float(os.environ.get("DAGRIDER_BENCH_FINALITY_RATE", "2000"))
    if fin_s > 0 and left() > 2 * fin_s + 80:
        _mark(f"ladder finality: n={fin_n}, {fin_s:.0f}s wall per side")
        try:
            t_rung = time.monotonic()
            entry = _finality_rung(
                n=fin_n, wall_s=fin_s, rate=fin_rate, drain_s=30.0
            )
            entry["rung_seconds"] = round(time.monotonic() - t_rung, 1)
            result["ladder"]["finality"] = entry
            _mark(
                f"ladder finality: identity gate held over "
                f"{len(entry['identity'])} matrix cases, p50 "
                f"{entry['on'].get('submit_deliver_p50_ms')} ms on / "
                f"{entry['off'].get('submit_deliver_p50_ms')} ms off, "
                f"eager p50 {entry.get('submit_eager_p50_ms')} ms, "
                f"sub-second gate "
                f"{'OK' if entry['p50_under_1s'] else 'MISSED'}"
            )
            emit()
            import datetime as _dt

            from dag_rider_tpu import config as _cfg

            out_path = os.path.join(
                _REPO, _cfg.env_str("DAGRIDER_FINALITY_OUT")
            )
            with open(out_path, "w") as fh:
                json.dump(
                    {
                        "schema": "dag-rider-tpu/bench-finality/v1",
                        "captured": _dt.datetime.now().isoformat(
                            timespec="seconds"
                        ),
                        "backend": result.get("backend", "cpu"),
                        "finality": entry,
                    },
                    fh,
                    indent=1,
                )
                fh.write("\n")
            _mark(f"ladder finality: wrote {out_path}")
        except Exception as e:  # noqa: BLE001 — rung is best-effort
            _mark(f"ladder finality FAILED: {e!r}")
    else:
        _mark(f"skipping ladder finality (left {left():.0f}s)")

    # -- ladder rung (ISSUE 17): sharded dissemination lanes. Half 1 is
    # the byte-identity gate (commit order AND delivered payload bytes,
    # lanes vs inline, over a seeded n × adversary × pump matrix — the
    # rung RAISES on divergence); half 2 is the committed-bytes-per-
    # pump-second A/B at n=64 with Ed25519-signed vertices as block
    # weight grows 16x, plus a lane-worker sweep at the top size.
    lanes_s = float(os.environ.get("DAGRIDER_BENCH_LANES_S", "15"))
    lanes_n = int(os.environ.get("DAGRIDER_BENCH_LANES_N", "64"))
    if lanes_s > 0 and left() > 150:
        _mark(f"ladder lanes: n={lanes_n}, identity matrix + A/B sweep")
        try:
            t_rung = time.monotonic()
            entry = _lanes_ab_rung(n=lanes_n)
            entry["rung_seconds"] = round(time.monotonic() - t_rung, 1)
            result["ladder"]["lanes"] = entry
            _mark(
                f"ladder lanes: identity gate held over "
                f"{len(entry['identity'])} matrix cases, "
                f"committed-bytes ratio "
                f"{entry['committed_bytes_ratio_top']}x at top size "
                f"({'OK' if entry['throughput_2x'] else 'MISSED'}), "
                f"lane pump flatness {entry['lane_pump_flatness']}x "
                f"({'OK' if entry['pump_flat_1p3x'] else 'MISSED'})"
            )
            emit()
            import datetime as _dt

            from dag_rider_tpu import config as _cfg

            out_path = os.path.join(
                _REPO, _cfg.env_str("DAGRIDER_LANES_OUT")
            )
            with open(out_path, "w") as fh:
                json.dump(
                    {
                        "schema": "dag-rider-tpu/bench-lanes/v1",
                        "captured": _dt.datetime.now().isoformat(
                            timespec="seconds"
                        ),
                        "backend": result.get("backend", "cpu"),
                        "lanes": entry,
                    },
                    fh,
                    indent=1,
                )
                fh.write("\n")
            _mark(f"ladder lanes: wrote {out_path}")
        except Exception as e:  # noqa: BLE001 — rung is best-effort
            _mark(f"ladder lanes FAILED: {e!r}")
    else:
        _mark(f"skipping ladder lanes (left {left():.0f}s)")

    # -- ladder rung (ISSUE 19): real multi-process cluster over sockets
    # with a kill -9 + rejoin-from-checkpoint cell. Gates: clean audits
    # (agreement incl. rejoin embedding, zero loss, uniqueness,
    # liveness, empty flight recorders) and byte-identical steady commit
    # prefixes — the rung RAISES otherwise.
    clu_s = float(os.environ.get("DAGRIDER_BENCH_CLUSTER_S", "6"))
    clu_n = int(os.environ.get("DAGRIDER_BENCH_CLUSTER_N", "4"))
    clu_rate = float(os.environ.get("DAGRIDER_BENCH_CLUSTER_RATE", "300"))
    if clu_s > 0 and left() > 2 * clu_s + 60:
        _mark(
            f"ladder cluster_e2e: n={clu_n} OS processes over uds, "
            f"{clu_s:.0f}s load per cell + one SIGKILL/rejoin"
        )
        try:
            t_rung = time.monotonic()
            entry = _cluster_e2e_rung(n=clu_n, load_s=clu_s, rate=clu_rate)
            entry["rung_seconds"] = round(time.monotonic() - t_rung, 1)
            result["ladder"]["cluster_e2e"] = entry
            ck = entry["kill_rejoin"]
            _mark(
                f"ladder cluster_e2e: clean "
                f"{entry['clean']['committed_tx_per_sec']} committed tx/s "
                f"(p50 {entry['clean'].get('submit_deliver_p50_ms')} ms / "
                f"p99 {entry['clean'].get('submit_deliver_p99_ms')} ms); "
                f"kill-and-rejoin kills={ck['kills']} lost={ck['lost_tx']} "
                f"prefix_identical={ck['prefix_identical']} "
                f"flight_dumps={ck['flight_dump_files']}"
            )
            emit()
            import datetime as _dt

            from dag_rider_tpu import config as _cfg

            out_path = os.path.join(
                _REPO, _cfg.env_str("DAGRIDER_CLUSTER_OUT")
            )
            with open(out_path, "w") as fh:
                json.dump(
                    {
                        "schema": "dag-rider-tpu/bench-cluster/v1",
                        "captured": _dt.datetime.now().isoformat(
                            timespec="seconds"
                        ),
                        "backend": result.get("backend", "cpu"),
                        "cluster_e2e": entry,
                    },
                    fh,
                    indent=1,
                )
                fh.write("\n")
            _mark(f"ladder cluster_e2e: wrote {out_path}")
        except Exception as e:  # noqa: BLE001 — rung is best-effort
            _mark(f"ladder cluster_e2e FAILED: {e!r}")
    else:
        _mark(f"skipping ladder cluster_e2e (left {left():.0f}s)")

    # -- ladder rung (ISSUE 20): epoch reconfiguration + span-attested
    # snapshot sync. Three gated cells — a real OS-process cluster where
    # a late node joins mid-load from a span-attested snapshot (pairing
    # budget + embedding + unanimous epoch), a threshold-coin rotation
    # A/B (byte-identical pre-boundary prefix, zero lost acked txs) and
    # a 3-epoch GC flatness check — the rung RAISES on any missed gate.
    ep_s = float(os.environ.get("DAGRIDER_BENCH_EPOCH_S", "180"))
    ep_rate = float(os.environ.get("DAGRIDER_BENCH_EPOCH_RATE", "250"))
    if ep_s > 0 and left() > ep_s + 30:
        _mark(
            "ladder epoch: mid-load join from span-attested snapshot "
            "+ key-rotation A/B + GC flatness across 3 epochs"
        )
        try:
            t_rung = time.monotonic()
            entry = _epoch_rung(
                rate=ep_rate, catchup_s=max(60.0, ep_s - 60)
            )
            entry["rung_seconds"] = round(time.monotonic() - t_rung, 1)
            result["ladder"]["epoch"] = entry
            j = entry["join"]
            _mark(
                f"ladder epoch: joiner verified "
                f"{j['snapshot_spans_verified']} spans in "
                f"{j['snapshot_pairing_checks']} pairings "
                f"(budget {j['pairing_budget']}), epochs "
                f"{sorted(set(j['epochs'].values()))}, "
                f"lost={j['lost_tx']}; rotate_ab boundary wave "
                f"{entry['rotate_ab']['boundary_wave']} prefix_identical="
                f"{entry['rotate_ab']['prefix_identical']}; flatness "
                f"{entry['flatness']['vertices_live_max_per_epoch']}"
            )
            emit()
            import datetime as _dt

            from dag_rider_tpu import config as _cfg

            out_path = os.path.join(
                _REPO, _cfg.env_str("DAGRIDER_EPOCH_OUT")
            )
            with open(out_path, "w") as fh:
                json.dump(
                    {
                        "schema": "dag-rider-tpu/bench-epoch/v1",
                        "captured": _dt.datetime.now().isoformat(
                            timespec="seconds"
                        ),
                        "backend": result.get("backend", "cpu"),
                        "epoch": entry,
                    },
                    fh,
                    indent=1,
                )
                fh.write("\n")
            _mark(f"ladder epoch: wrote {out_path}")
        except Exception as e:  # noqa: BLE001 — rung is best-effort
            _mark(f"ladder epoch FAILED: {e!r}")
    else:
        _mark(f"skipping ladder epoch (left {left():.0f}s)")

    # -- ladder rung: Byzantine adversary x WAN suite at committee scale.
    # Every adversary class from consensus/adversary.py drives f=10 of
    # n=32 nodes (f < n/3) through consensus/scenarios.py, plus a
    # partition-then-heal WAN run — run_scenario RAISES unless agreement,
    # commit-uniqueness, zero-loss, and the liveness floor all hold, so a
    # recorded entry IS a passed invariant audit. The detection counters
    # (equivocations_detected, edge_rejects, coin_filtered, sync_served)
    # land in the entry so the record also proves each attack genuinely
    # ran. garbage_coin is the expensive one (pure-Python pairings per
    # filtered wave) and gets its own cycle cap.
    byz_s = float(os.environ.get("DAGRIDER_BENCH_BYZ_S", "150"))
    byz_n = int(os.environ.get("DAGRIDER_BENCH_BYZ_N", "32"))
    byz_seed = int(os.environ.get("DAGRIDER_BENCH_BYZ_SEED", "0"))
    if byz_s > 0 and left() > byz_s + 20:
        from dag_rider_tpu.consensus.scenarios import Scenario, run_scenario

        t_rung = time.monotonic()
        byz_plan = [
            # (scenario kwargs, per-scenario wall cap fraction)
            dict(),
            dict(wan="partition", min_waves=1, min_each=1),
            dict(adversary="equivocate", min_waves=1, min_each=0),
            dict(
                adversary="equivocate_split",
                cycles=12,
                min_waves=1,
                min_each=0,
            ),
            dict(adversary="withhold", min_waves=1, min_each=0),
            dict(adversary="invalid_edges", min_waves=1, min_each=0),
            dict(
                adversary="garbage_coin",
                cycles=4,
                min_waves=1,
                min_each=0,
            ),
        ]
        rung: dict = {"n": byz_n, "seed": byz_seed, "scenarios": {}}
        result["ladder"]["byzantine"] = rung
        for kw in byz_plan:
            if time.monotonic() - t_rung > byz_s or left() < 20:
                _mark(
                    f"ladder byzantine: budget spent, skipping "
                    f"{kw.get('adversary') or 'clean'}/{kw.get('wan', 'lan')}"
                )
                continue
            sc = Scenario(n=byz_n, seed=byz_seed, **kw)
            _mark(f"ladder byzantine: {sc.name} (n={byz_n})")
            t0 = time.monotonic()
            try:
                r = run_scenario(sc)
                rung["scenarios"][sc.name] = {
                    "adversary": r["adversary"],
                    "wan": r["wan"],
                    "rbc": r["rbc"],
                    "coin": r["coin"],
                    "byzantine": len(r["byzantine"]),
                    "f": r["f"],
                    "rounds": r["rounds"],
                    "decided_waves": r["decided_waves"],
                    "audit": r["audit"],
                    "equivocations_detected": r["equivocations_detected"],
                    "edge_rejects": r["edge_rejects"],
                    "coin_filtered": r["coin_filtered"],
                    "sync_requested": r["sync_requested"],
                    "sync_served": r["sync_served"],
                    "behavior": r["behavior"],
                    "invariants": r["invariants"],
                    "wall_s": round(time.monotonic() - t0, 2),
                }
                _mark(
                    f"ladder byzantine: {sc.name} OK in "
                    f"{time.monotonic() - t0:.1f}s — waves "
                    f"{r['decided_waves']['min']}..{r['decided_waves']['max']}, "
                    f"eq {r['equivocations_detected']}, edges "
                    f"{r['edge_rejects']}, coin {r['coin_filtered']}"
                )
            except Exception as e:  # noqa: BLE001 — rung is best-effort
                rung["scenarios"][sc.name] = {
                    "failed": repr(e)[:300],
                    "wall_s": round(time.monotonic() - t0, 2),
                }
                _mark(f"ladder byzantine: {sc.name} FAILED: {e!r}")
        rung["wall_s"] = round(time.monotonic() - t_rung, 1)
        rung["passed"] = sum(
            1 for v in rung["scenarios"].values() if "failed" not in v
        )
        emit()
    else:
        _mark(f"skipping ladder byzantine (left {left():.0f}s)")

    # -- ladder rung #4: 256-node threshold coin with one Byzantine share
    if left() > 30:
        _mark("ladder coin256: keygen")
        from dag_rider_tpu.crypto import threshold as th

        n, f = 256, 85
        keys = th.ThresholdKeys.generate(n, f + 1)
        wave = 1
        shares = {
            i: th.sign_share(keys.share_sks[i], wave) for i in range(f + 2)
        }
        shares[0] = th.sign_share(keys.share_sks[0], wave + 13)  # Byzantine
        _mark("ladder coin256: poisoned aggregate + batched recovery")
        t0 = time.monotonic()
        sigma = th.aggregate(shares, keys.threshold)
        first_ok = sigma is not None and th.verify_group(
            keys.group_pk, wave, sigma
        )
        good = th.batch_verify_shares(keys.share_pks, wave, shares)
        sigma = th.aggregate(good, keys.threshold)
        ok = sigma is not None and th.verify_group(keys.group_pk, wave, sigma)
        dt = time.monotonic() - t0
        result["ladder"]["coin256"] = {
            "nodes": n,
            "threshold": f + 1,
            "byzantine_shares": 1,
            "first_aggregate_rejected": not first_ok,
            "recovered": ok,
            "good_shares": len(good),
            "recovery_s": round(dt, 2),
        }
        _mark(f"ladder coin256: recovered={ok} in {dt:.1f}s")
        emit()
        # coin aggregation on-device (VERDICT r3 #6): the lambda-weighted
        # share combination is a G1 MSM — time host vs device at the
        # n=256 share count (87 points pads to one 128-lane dispatch).
        if backend != "cpu" and left() > 45:
            try:
                from dag_rider_tpu.parallel.msm import ShardedMSM

                t0 = time.monotonic()
                host_sigma = th.aggregate(good, keys.threshold)
                host_s = time.monotonic() - t0
                sm = ShardedMSM()
                dev_sigma = th.aggregate(good, keys.threshold, msm=sm)
                t0 = time.monotonic()
                dev_sigma = th.aggregate(good, keys.threshold, msm=sm)
                dev_s = time.monotonic() - t0
                result["ladder"]["coin256"]["aggregate_host_s"] = round(
                    host_s, 3
                )
                result["ladder"]["coin256"]["aggregate_device_s"] = round(
                    dev_s, 3
                )
                result["ladder"]["coin256"]["aggregate_match"] = (
                    host_sigma == dev_sigma
                )
                _mark(
                    f"ladder coin256: aggregate host {host_s:.3f}s vs "
                    f"device {dev_s:.3f}s (match={host_sigma == dev_sigma})"
                )
                emit()
            except Exception as e:  # noqa: BLE001 — evidence, not headline
                _mark(f"ladder coin256: device aggregate FAILED: {e!r}")
    else:
        _mark(f"skipping ladder coin256 (only {left():.0f}s left)")

    # -- ladder rung #5 (Ed25519 half): committee n=1024 — comb tables at
    # 4x the north-star registry (536 MB device HBM) and a merged 4-round
    # verify. The MSM half of the rung is the msm phase below.
    if os.environ.get("DAGRIDER_BENCH_N1024", "1") == "1" and left() > 110:
        _mark("ladder verify1024: keygen + signing 4 rounds")
        n = 1024
        t0 = time.monotonic()
        verifier, batches, _ = _build_batches(n, 4)
        build_s = time.monotonic() - t0
        _mark(f"ladder verify1024: built in {build_s:.0f}s; compiling")
        # One compile only (the merged-bucket program): its warm masks are
        # the validity check — a separate single-round warm would compile
        # a second ~23 s program just to verify what the merged path
        # re-checks anyway.
        t0 = time.monotonic()
        masks = verifier.verify_rounds(batches)
        compile_s = time.monotonic() - t0
        if all(all(m) for m in masks):
            t0 = time.monotonic()
            masks = verifier.verify_rounds(batches)
            dt = time.monotonic() - t0
            total = sum(len(m) for m in masks)
            if all(all(m) for m in masks):
                result["ladder"]["verify1024"] = {
                    "nodes": n,
                    "sigs": total,
                    "build_s": round(build_s, 1),
                    "compile_s": round(compile_s, 1),
                    "sigs_per_sec": round(total / dt, 1),
                    "dispatch_ms": round(1e3 * dt, 2),
                }
                _mark(
                    f"ladder verify1024: {total / dt:,.0f} sigs/s "
                    f"({total} sigs/dispatch)"
                )
                emit()
            else:
                _mark("ladder verify1024: merged masks failed, discarding")
        else:
            _mark("ladder verify1024: warm batch failed, discarding")
    else:
        _mark(f"skipping ladder verify1024 (left {left():.0f}s)")

    # -- ladder rung #6 (round 7): mesh-sharded comb verify at the
    # flagship n=256, driven through the FULL async seam (warmup +
    # dispatch/resolve via VerifierPipeline) — sigs/s at 1 device vs the
    # mesh, same signatures, masks checked identical. When a real
    # multi-device mesh exists the record also refreshes
    # MULTICHIP_r06.json so the smoke file becomes a scaling curve.
    if os.environ.get("DAGRIDER_BENCH_SHARDED", "1") == "1" and left() > 120:
        try:
            from dag_rider_tpu.parallel.mesh import mesh_from_env
            from dag_rider_tpu.parallel.sharded_verifier import (
                ShardedTPUVerifier,
            )
            from dag_rider_tpu.verifier.pipeline import VerifierPipeline

            mesh = mesh_from_env()
            n_dev = int(np.prod(mesh.devices.shape))
            n = 256
            if n in built:
                single, sbatches, _ = built[n]
                sbatches = sbatches[:4]
            else:
                _mark("ladder verify_n256_sharded: signing 4 rounds")
                single, sbatches, _ = _build_batches(n, 4)
            s_total = sum(len(b) for b in sbatches)
            s_bucket = 256
            _mark(
                f"ladder verify_n256_sharded: {n_dev}-device mesh, "
                f"{s_total} sigs, bucket {s_bucket}"
            )

            def _timed_pipe(v):
                # `single` is built[256]'s verifier, reused by the prep
                # and chaos rungs after this one: borrow the bucket
                # under try/finally (driderlint:release)
                prev = getattr(v, "fixed_bucket", None)
                try:
                    v.fixed_bucket = s_bucket
                    pipe = VerifierPipeline(v, depth=2, warmup=True)
                    masks = pipe.verify_rounds(sbatches)  # compile + warm
                    times = []
                    for _ in range(3):
                        t0 = time.monotonic()
                        masks = pipe.verify_rounds(sbatches)
                        times.append(time.monotonic() - t0)
                    return masks, min(times)
                finally:
                    v.fixed_bucket = prev

            one_masks, one_dt = _timed_pipe(single)
            sharded = ShardedTPUVerifier(single.registry, mesh)
            mesh_masks, mesh_dt = _timed_pipe(sharded)
            match = mesh_masks == one_masks and all(
                all(m) for m in mesh_masks
            )
            entry = {
                "nodes": n,
                "sigs": s_total,
                "devices": n_dev,
                "bucket": s_bucket,
                "pipeline_depth": 2,
                "single_device_sigs_per_sec": round(s_total / one_dt, 1),
                "sharded_sigs_per_sec": round(s_total / mesh_dt, 1),
                "speedup": round(one_dt / mesh_dt, 2),
                "shard_batch": sharded.last_shard_batch,
                "shard_imbalance": round(sharded.last_shard_imbalance, 3),
                "masks_match": match,
            }
            result["ladder"]["verify_n256_sharded"] = entry
            _mark(
                f"ladder verify_n256_sharded: 1-dev "
                f"{s_total / one_dt:,.0f} sigs/s vs {n_dev}-dev "
                f"{s_total / mesh_dt:,.0f} sigs/s "
                f"(x{one_dt / mesh_dt:.2f}, match={match})"
            )
            emit()
            if match and n_dev > 1:
                rec = dict(entry)
                rec.update(
                    backend=backend,
                    device_kind=device_kind,
                    ok=True,
                    skipped=False,
                )
                from dag_rider_tpu import config as _cfg

                out_path = os.path.join(
                    _REPO, _cfg.env_str("DAGRIDER_MULTICHIP_OUT")
                )
                with open(out_path, "w") as fh:
                    json.dump(rec, fh, indent=1)
                    fh.write("\n")
                _mark(f"ladder verify_n256_sharded: wrote {out_path}")
        except Exception as e:  # noqa: BLE001 — rung is best-effort
            _mark(f"ladder verify_n256_sharded FAILED: {e!r}")
    else:
        _mark(f"skipping ladder verify_n256_sharded (left {left():.0f}s)")

    # -- ladder rung #7 (round 8): parallel host-prep 1-vs-N A/B at the
    # flagship n=256, through the FULL async seam (VerifierPipeline,
    # depth 2 — prep runs on the engine's seam thread, row-blocked
    # across the pool). On the CPU backend the device program dominates
    # wall clock, so the rung's headline is host_prep_ms_per_round on
    # both sides — a wall-clock tie with a prep-ms drop is the expected
    # CPU shape; on a real chip the prep drop surfaces in sigs/s.
    if (
        os.environ.get("DAGRIDER_BENCH_PREP", "1") == "1"
        and left() > 90
        and 256 in built
    ):
        try:
            from dag_rider_tpu.verifier.pipeline import VerifierPipeline
            from dag_rider_tpu.verifier.prep import default_prep_workers

            verifier, pbatches, _ = built[256]
            pbatches = pbatches[:4]
            p_total = sum(len(b) for b in pbatches)
            p_workers = int(
                os.environ.get("DAGRIDER_BENCH_PREP_WORKERS", "0")
            ) or min(4, os.cpu_count() or 1)
            _mark(
                f"ladder verify_n256_prep: {p_total} sigs, bucket 256, "
                f"workers 1 vs {p_workers}"
            )
            prev_bucket = verifier.fixed_bucket
            prev_workers = verifier.prep_workers
            try:
                verifier.fixed_bucket = 256  # same program shape as the
                # sharded rung's single-device side (already compiled
                # when that rung ran; persistent cache otherwise)
                pipe = VerifierPipeline(verifier, depth=2, warmup=True)
                sides = {}
                masks_by_side = {}
                for w in dict.fromkeys((1, p_workers)):
                    verifier.prep_workers = w
                    pipe.verify_rounds(pbatches)  # warm: pool + program
                    ps0 = verifier.prep_stats()
                    prep0 = verifier.total_prepare_s
                    times = []
                    for _ in range(3):
                        t0 = time.monotonic()
                        masks_by_side[w] = pipe.verify_rounds(pbatches)
                        times.append(time.monotonic() - t0)
                    ps1 = verifier.prep_stats()
                    d_prep = verifier.total_prepare_s - prep0
                    d_rows = ps1["rows_total"] - ps0["rows_total"]
                    d_par = ps1["rows_parallel"] - ps0["rows_parallel"]
                    sides[w] = {
                        "prep_workers": w,
                        "host_prep_ms_per_round": round(
                            1e3 * d_prep / (3 * len(pbatches)), 3
                        ),
                        "sigs_per_sec": round(3 * p_total / sum(times), 1),
                        "wall_s": round(min(times), 3),
                        "parallel_fraction": (
                            round(d_par / d_rows, 3) if d_rows else 0.0
                        ),
                    }
                serial, par = sides[1], sides[p_workers]
                match = all(
                    m == masks_by_side[1] for m in masks_by_side.values()
                ) and all(all(r) for r in masks_by_side[1])
                entry = {
                    "nodes": 256,
                    "sigs": p_total,
                    "bucket": 256,
                    "pipeline_depth": 2,
                    "serial": serial,
                    "parallel": par,
                    "prep_speedup": (
                        round(
                            serial["host_prep_ms_per_round"]
                            / par["host_prep_ms_per_round"],
                            2,
                        )
                        if par["host_prep_ms_per_round"]
                        else None
                    ),
                    "masks_match": match,
                }
                result["ladder"]["verify_n256_prep"] = entry
                _mark(
                    f"ladder verify_n256_prep: prep "
                    f"{serial['host_prep_ms_per_round']} ms/round @1w vs "
                    f"{par['host_prep_ms_per_round']} ms/round "
                    f"@{p_workers}w (x{entry['prep_speedup']}, "
                    f"match={match})"
                )
                emit()
            finally:
                # restore the shared verifier for the deferred merged
                # headline phase: bucket back, engine back to the env
                # default (leaving prep_workers None would pin the LAST
                # A/B side's pool)
                verifier.prep_workers = (
                    prev_workers
                    if prev_workers is not None
                    else default_prep_workers()
                )
                verifier.fixed_bucket = prev_bucket
        except Exception as e:  # noqa: BLE001 — rung is best-effort
            _mark(f"ladder verify_n256_prep FAILED: {e!r}")
    else:
        _mark(f"skipping ladder verify_n256_prep (left {left():.0f}s)")

    # -- ladder rung #8 (round 9): verify under injected chaos at the
    # flagship n=256, through the FULL async seam. Budgeted faults at
    # the dispatch/resolve seams poison depth-2 windows mid-stream; the
    # containment machinery must salvage, re-arm the ring and quarantine
    # with masks IDENTICAL to the clean run — the rung's headline is the
    # latency cost of containment (slowdown vs clean), never
    # correctness. Quarantined chunks re-verify on a clean DEVICE
    # verifier tier (the CPU reference would dominate the rung's wall
    # clock on a 1-core host and measure the oracle, not containment).
    if (
        os.environ.get("DAGRIDER_BENCH_CHAOS", "1") == "1"
        and left() > 60
        and 256 in built
    ):
        try:
            from dag_rider_tpu.verifier.faults import (
                VerifierFaultInjector,
                VerifierFaultPlan,
            )
            from dag_rider_tpu.verifier.pipeline import VerifierPipeline
            from dag_rider_tpu.verifier.tpu import TPUVerifier as _ChaosTPUV

            verifier, cbatches, _ = built[256]
            cbatches = cbatches[:4]
            c_total = sum(len(b) for b in cbatches)
            _mark(
                f"ladder verify_n256_chaos: {c_total} sigs, bucket 256, "
                f"budgeted dispatch/resolve faults"
            )
            prev_bucket = verifier.fixed_bucket
            inj = None
            try:
                verifier.fixed_bucket = 256
                pipe = VerifierPipeline(verifier, depth=2, warmup=True)
                pipe.verify_rounds(cbatches)  # warm program + ring
                t0 = time.monotonic()
                clean_masks = pipe.verify_rounds(cbatches)
                clean_dt = time.monotonic() - t0

                quarantine = _ChaosTPUV(verifier.registry)
                quarantine.fixed_bucket = 256
                quarantine.warmup()  # persistent-cache hit: same shape
                pipe.quarantine_verifier = quarantine
                inj = VerifierFaultInjector(
                    VerifierFaultPlan(
                        dispatch_raise=0.5,
                        resolve_raise=0.5,
                        max_faults=4,
                        seed=9,
                    )
                )
                inj.arm(verifier)
                t0 = time.monotonic()
                chaos_masks = pipe.verify_rounds(cbatches)
                chaos_dt = time.monotonic() - t0
            finally:
                if inj is not None:
                    inj.disarm()
                verifier.fixed_bucket = prev_bucket
            match = chaos_masks == clean_masks and all(
                all(m) for m in clean_masks
            )
            rs = pipe.resilience_stats()
            entry = {
                "nodes": 256,
                "sigs": c_total,
                "bucket": 256,
                "pipeline_depth": 2,
                "clean_sigs_per_sec": round(c_total / clean_dt, 1),
                "chaos_sigs_per_sec": round(c_total / chaos_dt, 1),
                "containment_slowdown": round(chaos_dt / clean_dt, 2),
                "faults_injected": inj.faults_injected,
                "fault_stats": dict(inj.stats),
                "poisoned_windows": rs["poisoned_windows"],
                "verify_quarantined": rs["quarantined"],
                "quarantine_rejected": rs["quarantine_rejected"],
                "masks_match": match,
            }
            result["ladder"]["verify_n256_chaos"] = entry
            _mark(
                f"ladder verify_n256_chaos: clean "
                f"{c_total / clean_dt:,.0f} sigs/s vs chaos "
                f"{c_total / chaos_dt:,.0f} sigs/s "
                f"(x{chaos_dt / clean_dt:.2f} slowdown, "
                f"{inj.faults_injected} faults, match={match})"
            )
            emit()
        except Exception as e:  # noqa: BLE001 — rung is best-effort
            _mark(f"ladder verify_n256_chaos FAILED: {e!r}")
    else:
        _mark(f"skipping ladder verify_n256_chaos (left {left():.0f}s)")

    # -- ladder rung #5 (single-host half): T-point G1 MSM on the device
    msm_t = int(os.environ.get("DAGRIDER_BENCH_MSM_T", "1024"))
    if msm_t > 0 and left() > 90:
        _mark(f"ladder msm{msm_t}: building points")
        import random

        from dag_rider_tpu.crypto import bls12381 as bls
        from dag_rider_tpu.parallel.msm import ShardedMSM

        rng = random.Random(3)
        base = bls.g1_mul(rng.randrange(1, bls.R))
        pts, acc = [], base
        for _ in range(msm_t):  # cheap distinct points: repeated doubling
            pts.append(acc)
            acc = bls.g1_double(acc)
        ks = [rng.randrange(0, bls.R) for _ in range(msm_t)]
        # auto impl picks the pallas tree engine on a real chip; a Mosaic
        # failure on the unproven-on-hardware kernel must not cost the
        # rung — fall back to the bit-identical jnp tree once (skipped
        # when auto already resolves to jnp: identical config).
        from dag_rider_tpu.ops.bls_msm import msm_impl as _msm_impl

        _shards = ShardedMSM().n_shards
        auto_impl = _msm_impl(max(4, msm_t) // _shards)
        impls = (auto_impl,) if auto_impl == "jnp" else (auto_impl, "jnp")
        for impl in impls:
            sm = ShardedMSM(impl=impl)
            try:
                _mark(
                    f"ladder msm{msm_t}: compiling + first run (impl={impl})"
                )
                t0 = time.monotonic()
                first = sm(ks, pts)
                compile_s = time.monotonic() - t0
                _mark(
                    f"ladder msm{msm_t}: first run {compile_s:.1f}s; timing warm run"
                )
                t0 = time.monotonic()
                warm = sm(ks, pts)
                dt = time.monotonic() - t0
            except Exception as e:  # noqa: BLE001 — rung is best-effort
                _mark(f"ladder msm{msm_t}: impl={impl} FAILED: {e!r}")
                continue
            ok = first == warm and first is not None
            result["ladder"][f"msm{msm_t}"] = {
                "points": msm_t,
                "devices": sm.n_shards,
                "impl": impl,
                "compile_plus_first_s": round(compile_s, 1),
                "warm_s": round(dt, 2),
                "points_per_sec": round(msm_t / dt, 1),
                "deterministic": ok,
            }
            _mark(
                f"ladder msm{msm_t}: warm {dt:.2f}s ({msm_t / dt:,.0f} points/s)"
            )
            emit()
            break
    elif msm_t > 0:
        _mark(f"skipping ladder msm{msm_t} (only {left():.0f}s left)")

    # -- Pallas-vs-XLA field-mul microbench (SURVEY §2a evidence; guarded:
    # a Mosaic lowering failure must never cost the headline number)
    if os.environ.get("DAGRIDER_BENCH_PALLAS", "1") == "1" and left() > 60:
        try:
            _mark("pallas probe: compiling field-mul chains (xla + pallas)")
            from dag_rider_tpu.ops import pallas_field

            xla_ms, pallas_ms, same = pallas_field.benchmark_vs_xla()
            result["phases"]["pallas_field_mul"] = {
                "batch": 8192,
                "chain": 64,
                "xla_ms": round(xla_ms, 2),
                "pallas_ms": round(pallas_ms, 2),
                "bit_identical": same,
                "speedup": round(xla_ms / pallas_ms, 2) if pallas_ms else None,
            }
            _mark(
                f"pallas probe: xla {xla_ms:.1f}ms vs pallas {pallas_ms:.1f}ms"
                f" (identical={same})"
            )
            emit()
        except Exception as e:  # noqa: BLE001 — evidence phase is best-effort
            result["phases"]["pallas_field_mul"] = {"error": repr(e)[:200]}
            _mark(f"pallas probe FAILED (non-fatal): {e!r}")
            emit()
    if not headline_first:
        # deferred CPU merged phase: only with whatever window remains
        # after every rung has had its chance (guarded inside)
        merged_phase(cpu_merged_n)
    _mark("measure: done")
    emit()


# ----------------------------------------------------------------------
# Outer: budget manager; always emits one JSON line, rc=0
# ----------------------------------------------------------------------

def _run_stage(stage: str, env: dict, timeout_s: float):
    """Run a stage subprocess; return (last_json | None, stderr_tail)."""
    env = dict(env)
    env["DAGRIDER_BENCH_STAGE"] = stage
    # Own process group + group kill on timeout: the measure child may
    # hold a spawn pool of signing workers, and SIGKILLing only the
    # child would orphan them to contend with the CPU fallback.
    proc = subprocess.Popen(
        [sys.executable, "-u", os.path.abspath(__file__)],
        env=env,
        cwd=_REPO,
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
        start_new_session=True,
    )
    try:
        out, err = proc.communicate(timeout=timeout_s)
        rc = proc.returncode
    except subprocess.TimeoutExpired as e:
        import signal

        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            proc.kill()
        out, err = proc.communicate()
        rc = "timeout"
        out = (e.output or "") + (out or "") if isinstance(
            e.output, str
        ) else out or ""
        err = (e.stderr or "") + (err or "") if isinstance(
            e.stderr, str
        ) else err or ""
    out, err = out or "", err or ""
    parsed = None
    for line in reversed((out or "").splitlines()):
        line = line.strip()
        if line.startswith("{") and line.endswith("}"):
            try:
                parsed = json.loads(line)
                break
            except json.JSONDecodeError:
                continue
    tail = "; ".join((err or "").strip().splitlines()[-6:])[-700:]
    if parsed is None or rc != 0:
        # A stage that crashed AFTER emitting a progressive JSON line
        # still parses — surface the rc so main() can mark the record
        # truncated instead of silently passing it off as complete.
        tail = f"rc={rc}; {tail}"
    return parsed, tail, rc


def main() -> None:
    stage = os.environ.get("DAGRIDER_BENCH_STAGE")
    if stage == "probe":
        _probe()
        return
    if stage == "measure":
        _measure()
        return

    budget = float(os.environ.get("DAGRIDER_BENCH_BUDGET", "540"))
    # enough for the n=256 phases (VERDICT r4 #6) + the dedup'd in-loop
    # sim64 AND sim256 rungs + the round-10 mempool e2e/chaos rungs the
    # fallback now carries
    cpu_reserve = float(os.environ.get("DAGRIDER_BENCH_CPU_RESERVE", "270"))
    notes = []
    # Critical diagnostics (mid-run truncation, probe-vs-record
    # mismatch) are kept separate and joined FIRST: the chronological
    # probe-failure notes alone can exceed fallback_reason's 800-char
    # cap in a multi-attempt run, and the structural facts must not
    # be the part that falls off.
    key_notes = []

    # fresh per-run stage-mark tee (see _mark): the postmortem artifact
    # for any stage the parent has to kill
    mark_file = os.environ.setdefault(
        "DAGRIDER_BENCH_MARK_FILE", os.path.join(_REPO, "bench_marks.log")
    )
    try:
        open(mark_file, "w").close()
    except OSError:
        pass

    def elapsed() -> float:
        return time.monotonic() - _T0

    def run_cpu_fallback(timeout_s: float):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["DAGRIDER_BENCH_PLATFORM"] = "cpu"
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env.pop("PALLAS_AXON_REMOTE_COMPILE", None)
        env["DAGRIDER_BENCH_SECONDS"] = str(timeout_s - 15.0)
        # North-star-shaped evidence even when the chip is unreachable
        # (round-4 VERDICT #6): measure verify at n=256 with a SMALL
        # merged burst (6 rounds ~ 1.5k sigs — the full 63-round burst
        # is a device shape that would eat the whole CPU window), plus
        # an n=256 host consensus rung.
        env["DAGRIDER_BENCH_N256_MIN"] = "90"
        env["DAGRIDER_BENCH_N256_ROUNDS"] = "6"
        # With dispatch dedup (round 5) a 64-node in-loop rung is CPU-
        # feasible: 63 unique sigs/round through a 128-bucket program
        # (~180 ms/dispatch warm, compile persisted in .jax_cache) —
        # ~14k applied sigs/s of real-crypto consensus evidence on a
        # dead-relay round. The n=256 sim and T=1024 MSM stay TPU-only.
        env["DAGRIDER_BENCH_SIM_S"] = "20"
        env["DAGRIDER_BENCH_SIM_BUCKET"] = "128"
        # ... and so is an in-loop rung at the NORTH-STAR committee
        # size: 256 unique sigs/round through a 512 bucket — measured
        # 24.6k applied sigs/s, wave p50 2.5 ms on this host's CPU.
        # (Deadline-aware phases: on a cold .jax_cache the compile eats
        # the rung and the progressive emit keeps the earlier phases.)
        env["DAGRIDER_BENCH_SIM256_S"] = "25"
        env["DAGRIDER_BENCH_SIM256_BUCKET"] = "512"
        env["DAGRIDER_BENCH_SIM256_SYNC_S"] = "0"
        env["DAGRIDER_BENCH_HOSTSIM_S"] = "12"  # host consensus evidence
        env["DAGRIDER_BENCH_HOSTSIM256_S"] = "15"
        # Mempool end-to-end pipeline (round 10): client-visible
        # committed-tx/s + submit→a_deliver percentiles at the flagship
        # committee, null verifier. A 10 s load window + 30 s bounded
        # drain fits the CPU box; the chaos variant (1 virtual second of
        # 8x bursts through delay/duplicate faults) proves
        # shed-not-crash on every record, chip or not.
        env["DAGRIDER_BENCH_MEMPOOL_S"] = "10"
        env["DAGRIDER_BENCH_MEMPOOL_DRAIN_S"] = "30"
        env["DAGRIDER_BENCH_MEMPOOL_CHAOS_S"] = "1"
        env["DAGRIDER_BENCH_MSM_T"] = "0"
        env["DAGRIDER_BENCH_N1024"] = "0"
        env["DAGRIDER_BENCH_PALLAS"] = "0"  # Mosaic needs the real chip
        return _run_stage("measure", env, timeout_s)

    # Probe retry ladder (round-3 postmortem: BENCH_r03 lost the on-chip
    # headline because the single probe hit a transiently wedged relay and
    # the whole remaining budget went to the CPU fallback; round-4 VERDICT
    # #1: attempts must CONTINUE after the CPU fallback banks, not stop).
    # Loop: probe -> on success measure on the chip; on failure bank a CPU
    # number once, then keep re-probing on a 30 s cadence until the budget
    # can no longer fit a probe + minimal measurement — a relay that
    # recovers at any point in the run still gets measured.
    result = None
    cpu_result = None
    probe = None
    attempt = 0
    while budget - elapsed() >= 110.0:
        attempt += 1
        pt = min(
            120.0 if attempt == 1 else 60.0,
            max(25.0, budget - elapsed() - 90.0),
        )
        _mark(f"outer: probing primary backend, attempt {attempt} (timeout {pt:.0f}s)")
        _relay_log(f"probe attempt {attempt} start (timeout {pt:.0f}s)")
        probe_i, tail, _ = _run_stage("probe", dict(os.environ), pt)
        if probe_i and probe_i.get("probe_ok"):
            probe = probe_i
            _mark(f"outer: probe ok ({probe})")
            _relay_log(
                f"probe attempt {attempt} OK: backend="
                f"{probe.get('backend')} init_s={probe.get('init_s')}"
            )
            # full measurement on the primary backend; reserve CPU time
            # only if no CPU number is banked yet
            reserve = cpu_reserve if cpu_result is None else 0.0
            meas_timeout = max(60.0, budget - elapsed() - reserve)
            env = dict(os.environ)
            env["DAGRIDER_BENCH_SECONDS"] = str(meas_timeout - 20.0)
            _mark(f"outer: measuring on primary (timeout {meas_timeout:.0f}s)")
            result, mtail, mrc = _run_stage("measure", env, meas_timeout)
            _relay_log(
                "primary measure "
                + (
                    f"ok (rc={mrc})"
                    if result and result.get("value")
                    else f"failed: {mtail[:200]}"
                )
            )
            if result is None or not result.get("value"):
                notes.append(f"primary measure: {mtail}")
                if result is not None:
                    notes.append("primary measure returned zero value")
                    result = None
                # The relay can wedge BETWEEN a good probe and the measure
                # stage's own init (round-5 postmortem) — with the init
                # watchdog the failure costs ~150s, not the window, so
                # keep cycling probe->measure while the budget allows.
                # Fall through to the shared banking/pacing block below:
                # probe-ok/measure-fail cycles must bank a CPU number
                # too, or they starve the terminal fallback to its 60s
                # floor.
                _mark("outer: primary measure failed; will re-probe")
            else:
                if mrc != 0:
                    # crashed mid-measure after a progressive emit: keep
                    # the partial record (it carries real on-chip phases)
                    # but say so — a truncated ladder must not read as a
                    # short one
                    result["truncated"] = True
                    key_notes.append(
                        f"measure stage exited rc={mrc} mid-run: {mtail}"
                    )
                break
        else:
            notes.append(f"probe attempt {attempt} failed: {tail}")
            _mark(f"outer: probe attempt {attempt} FAILED ({tail})")
            _relay_log(f"probe attempt {attempt} FAILED: {tail[:300]}")
        banked = False
        if cpu_result is None and budget - elapsed() > cpu_reserve + 130.0:
            # bank a CPU number while waiting for the relay to recover
            cpu_timeout = max(60.0, min(cpu_reserve, budget - elapsed() - 100.0))
            _mark(f"outer: CPU fallback between probes (timeout {cpu_timeout:.0f}s)")
            cpu_result, ctail, crc = run_cpu_fallback(cpu_timeout)
            banked = cpu_result is not None
            if not banked:
                notes.append(f"cpu fallback: {ctail}")
            elif crc != 0:
                cpu_result["truncated"] = True
                key_notes.append(f"cpu fallback exited rc={crc} mid-run: {ctail}")
        if not banked:
            # Always pace failed probes — a probe (or fallback) that
            # fails in <1s (e.g. ImportError of a base dep) must not
            # spin the loop spawning subprocesses until the budget
            # floor is hit. A successful fallback already consumed
            # minutes, which is pacing enough.
            wait = min(30.0, max(5.0, budget - elapsed() - 110.0))
            _mark(f"outer: waiting {wait:.0f}s before next probe attempt")
            time.sleep(wait)

    if result is None and cpu_result is None:
        # terminal CPU fallback — a number must always exist
        cpu_timeout = max(60.0, min(cpu_reserve, budget - elapsed()))
        _mark(f"outer: terminal CPU fallback (timeout {cpu_timeout:.0f}s)")
        cpu_result, ctail, crc = run_cpu_fallback(cpu_timeout)
        if cpu_result is None:
            notes.append(f"cpu fallback: {ctail}")
        elif crc != 0:
            cpu_result["truncated"] = True
            key_notes.append(f"cpu fallback exited rc={crc} mid-run: {ctail}")

    if result is None:
        result = cpu_result

    if result is None:
        result = {
            "metric": "vertex_sigs_per_sec",
            "value": 0.0,
            "unit": "sigs/s",
            "vs_baseline": 0.0,
            "backend": "none",
        }
    if probe:
        if result is not cpu_result and result.get("value"):
            # only a record actually measured on the probed backend gets
            # the probe attached — not the CPU fallback, and not the
            # synthesized zero-value record below
            result.setdefault("phases", {})["probe"] = probe
        else:
            # a TPU probe succeeded at some point but every measure on it
            # failed — a postmortem reading phases.probe on a CPU (or
            # empty) record would conclude the chip was reachable for
            # THIS measurement
            key_notes.append(
                f"a primary probe succeeded ({probe.get('backend')}) but "
                "no primary measurement completed; record is a fallback"
            )
    if notes or key_notes:
        # Head-preserving truncation: each note keeps its lead (the
        # attempt tag + rc), the join keeps the FIRST 800 chars — the
        # round-4 record's tail-clip produced garbled reasons like
        # "e attempt 2 failed: rc=timeout; ...". Critical diagnostics
        # join first so the chronological probe spam is what falls off.
        result["fallback_reason"] = " || ".join(
            [n[:240] for n in key_notes] + [n[:240] for n in notes]
        )[:800]
    print(json.dumps(result))


if __name__ == "__main__":
    main()
